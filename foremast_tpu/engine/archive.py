"""Pluggable job archive: the reference's Elasticsearch role, optional.

The reference parks every job document and HPA log in ES indices
`documents`/`hpalogs` (foremast-service/pkg/search/elasticsearchstore.go:
17-21) — its durability AND its audit surface (Kibana over ES,
design.md:49-51). The TPU runtime's live store is in-process (jobs resolve
in milliseconds; a queue database adds nothing), so the archive is a
write-behind sink for *terminal* jobs and hpalogs:

  * `FileArchive` — CRC-framed segment records (dataplane/segfile, the
    same format the window tier and job tier persist on) with size-based
    compacting rotation; zero dependencies, queryable via
    /v1/healthcheck/search. Pre-existing newline-JSON archives are read
    transparently and converted at the next compaction.
  * `EsArchive` — same record stream PUT into real ES-compatible indices
    (same names as the reference), for fleets that already run
    ES/OpenSearch + Kibana. Best-effort: archive failures must never fail
    a verdict.

Both implement index_job/index_hpalog/search; JobStore calls them on
terminal transitions, which also makes terminal-job pruning safe
(JobStore.gc) — the reference never prunes ES, we must not grow RAM
forever.
"""
from __future__ import annotations

import json
import os
import urllib.error
import urllib.request

try:
    import fcntl
except ImportError:  # Windows: no flock; single-process archives only
    fcntl = None

from ..dataplane import segfile
from ..resilience.faults import seam_point
from ..utils.locks import make_lock

__all__ = ["FileArchive", "EsArchive", "MEMBER_STATE_PREFIX"]

# Shard-membership heartbeat state keys (engine/sharding.py writes them,
# re-exporting this prefix as MEMBER_KEY_PREFIX). The canonical constant
# lives HERE because compaction must age the blobs out: the default
# replica id is hostname-pid — a fresh key every pod restart — and
# keeping the latest record per state key forever would grow the
# compacted state section (and every membership read that scans it)
# without bound across deployment history.
MEMBER_STATE_PREFIX = "shard-member:"
# a member silent this long is ages past any plausible MEMBER_TTL_S
# (default 15 s; docs/configuration.md): safe to drop. FileArchive drops
# at compaction; EsArchive via delete_state, driven by the membership
# reader (engine/sharding.py prunes what its read filters out anyway)
KEEP_MEMBER_SECONDS = 3600.0

# jobs.py's TERMINAL_STATUSES, duplicated here because jobs.py imports
# from this module (tests pin the two sets against drift)
_TERMINAL = frozenset((
    "completed_health", "completed_unhealth", "completed_unknown",
    "preprocess_failed", "abort",
))


def _statuses(status) -> list | None:
    """Normalize a status filter to a list (or None = any)."""
    if not status:
        return None
    return [status] if isinstance(status, str) else list(status)


def _match(rec: dict, app, namespace, status, strategy) -> bool:
    """Shared live/archive record predicate; status may be str or list."""
    statuses = _statuses(status)
    return (
        (app is None or rec.get("app_name") == app)
        and (namespace is None or rec.get("namespace") == namespace)
        and (statuses is None or rec.get("status") in statuses)
        and (strategy is None or rec.get("strategy") == strategy)
    )


def _parse_framed(buf: bytes, start: int) -> tuple[list[dict], int]:
    """Parse CRC-framed records from ``buf[start:]`` ->
    ``(records, consumed)``. ``consumed`` is the offset incremental
    readers may resume from: end-of-buffer on a clean parse, else the
    FIRST damaged offset — archive records are independent newest-wins
    states, so the walk salvages past damage (``next_valid_frame``) but
    the damaged region stays "unconsumed" and is re-walked (idempotently)
    until compaction rewrites it away."""
    recs: list[dict] = []
    i, n = start, len(buf)
    first_bad = None
    while i < n:
        frames, status, bad = segfile.scan(buf, i)
        for off, plen in frames:
            try:
                recs.append(json.loads(buf[off:off + plen]))
            except json.JSONDecodeError:
                continue  # CRC-valid but unparseable: skip, never fatal
        if status == segfile.SCAN_OK:
            break
        if first_bad is None:
            first_bad = bad
        i = segfile.next_valid_frame(buf, bad + 1)
        if i == -1:
            break
    return recs, (first_bad if first_bad is not None else n)


def _parse_legacy(buf: bytes, start: int) -> tuple[list[dict], int]:
    """Parse newline-JSON records (pre-segment archives) ->
    ``(records, consumed)``; a torn tail line (no trailing newline yet)
    stays unconsumed for the next incremental pass."""
    recs: list[dict] = []
    end = buf.rfind(b"\n", start) + 1
    if end <= start:
        return recs, start
    for line in buf[start:end].split(b"\n"):
        if not line:
            continue
        try:
            recs.append(json.loads(line))
        except json.JSONDecodeError:
            continue  # torn interleave from a pre-flock writer: skip
    return recs, end


def _parse_file(buf: bytes) -> list[dict]:
    """Whole-file parse with per-file format detection (compaction and
    generation rebuilds; the incremental path in _advance_view_locked
    remembers the format instead of sniffing)."""
    if not buf:
        return []
    framed = buf[:len(segfile.MAGIC)] == segfile.MAGIC
    return (_parse_framed if framed else _parse_legacy)(buf, 0)[0]


def _merge_view(docs: dict, states: dict, recs: list[dict]) -> None:
    """Fold records into the {id: doc} / {key: (value, ts)} maps,
    newest-wins by each record's OWN stamp (append order lies: a wedged
    peer can append a stale open record after a terminal one)."""
    for rec in recs:
        t = rec.get("_type")
        if t == "document":
            rid = rec.get("id", "")
            cur = docs.get(rid)
            if cur is None or (rec.get("modified_at", 0.0)
                               >= cur.get("modified_at", 0.0)):
                docs[rid] = rec
        elif t == "state":
            key = rec.get("key", "")
            cur = states.get(key)
            if cur is None or rec.get("updated_at", 0.0) >= cur[1]:
                states[key] = (rec.get("value"), rec.get("updated_at", 0.0))


class FileArchive:
    """Append-only segment-record archive with compacting rotation.

    Records land as CRC frames (dataplane/segfile — the same format the
    window tier and tiered job store persist on), so a crash can only
    tear the last frame and readers can always tell damage from a torn
    tail. Files written by pre-segment builds (newline-JSON) are read
    transparently, keep receiving newline appends so the two formats
    never mix within one file, and convert at their next compaction.

    MULTI-PROCESS SAFE on POSIX: the cross-replica failover deployment
    shares one archive path between runtimes (docs/operations.md), so
    every file MUTATION holds an fcntl flock on a sidecar `.lock` file
    (readers stay lock-free — see _refresh_view), and each record lands
    as ONE O_APPEND os.write, so concurrent appends can never interleave
    into torn frames. Without fcntl (Windows) a per-process lock is all
    there is: share an archive only via ES there.

    READS are served from an incrementally-maintained view (latest doc
    per id + latest state blob per key): between mutations a read costs
    a couple of stat(2)s, and after appends only the NEW bytes of the
    active file are parsed — the per-heartbeat membership read
    (list_state) and the adoption scan (search/claim_job) no longer pay
    a full two-generation JSON walk per call. Rotation (new `.1` inode)
    triggers the only full rebuild, counted on ``view_rebuilds``.

    Rotation COMPACTS instead of discarding: when the active file
    exceeds max_bytes, both generations merge into `.1` keeping the
    latest record per job id, the latest state blob per key, and the
    newest `keep_hpalogs` hpalogs. Terminal verdicts therefore survive
    any amount of open-job mirror churn (gc() trusts the archive to hold
    them), and steady-state size tracks the job count, not the write
    rate.
    """

    def __init__(self, path: str, max_bytes: int = 64 * 1024 * 1024,
                 keep_hpalogs: int = 1000,
                 keep_terminal_seconds: float = 30 * 86400.0,
                 injector=None):
        self.path = path
        self.max_bytes = max_bytes
        self.keep_hpalogs = keep_hpalogs
        # resilience/faults.py FaultInjector carrying a crash plan: the
        # append/compact seam_point crossings below are what let the
        # crashcheck sweep cut between any two archive mutations
        self.injector = injector
        # compaction retention for TERMINAL documents: without an age
        # bound, unique per-rollout job ids accumulate forever and every
        # compaction rewrites the whole history under the flock. Open
        # records are never aged (they are adoptable state, bounded by
        # fleet size); state blobs are last-per-key.
        self.keep_terminal_seconds = keep_terminal_seconds
        self._lock = make_lock("engine.archive.file")
        # times a lock-free view refresh exhausted its rescans and fell
        # back to a rebuild under the mutation lock (sustained-rotation
        # churn); exposed for observability
        self.locked_scan_fallbacks = 0
        self.compactions = 0
        # full two-generation view rebuilds (first read + every rotation);
        # steady-state reads between rotations advance incrementally and
        # never bump this — the counter IS the O(archive)-walk budget
        self.view_rebuilds = 0
        # (ino of .1, active-file format, active bytes consumed,
        #  {id: doc}, {key: (value, updated_at)}) — replaced wholesale
        # (copy-on-write) so readers iterate a stable snapshot while a
        # concurrent refresh installs the next one
        self._view: tuple | None = None
        self._view_lock = make_lock("engine.archive.view")
        # times the sidecar .lock could not be opened/flocked while fcntl
        # IS available: mutations proceeded under the in-process lock only,
        # and compaction was suppressed (truncating without the
        # cross-process lock can destroy another replica's append)
        self.lock_degradations = 0
        self.compactions_skipped_unlocked = 0
        # detected short writes (disk full mid-record): rolled back to
        # the pre-append size under the cross-process lock, else left as
        # a torn tail the framed scan truncates; either way the append
        # reports failure so the caller keeps its RAM copy
        self.append_short_writes = 0
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    # -- cross-process mutation lock --
    def _flock(self):
        """Context manager holding the cross-process mutation lock (plus
        the in-process lock: flock is per-fd, threads share the process)."""
        outer = self

        class _Lock:
            def __enter__(self):
                outer._lock.acquire()
                self._fd = None
                # cross-process exclusion held? True when fcntl is absent
                # (per-process lock is all there is by design) or the flock
                # succeeded; False = DEGRADED (lock file unopenable), which
                # callers must treat as "no right to compact"
                self.cross_locked = fcntl is None
                if fcntl is not None:
                    try:
                        self._fd = os.open(outer.path + ".lock",
                                           os.O_CREAT | os.O_RDWR, 0o644)
                        fcntl.flock(self._fd, fcntl.LOCK_EX)
                        self.cross_locked = True
                    except OSError:
                        outer.lock_degradations += 1
                        if self._fd is not None:
                            os.close(self._fd)
                            self._fd = None
                return self

            def __exit__(self, *exc):
                if self._fd is not None:
                    try:
                        fcntl.flock(self._fd, fcntl.LOCK_UN)
                    finally:
                        os.close(self._fd)
                outer._lock.release()

        return _Lock()

    # -- writing --
    def _maybe_compact_locked(self, rec_len: int,
                              cross_locked: bool) -> None:
        """Size-triggered compaction check (caller holds the flock)."""
        try:
            if (os.path.exists(self.path)
                    and os.path.getsize(self.path) + rec_len > self.max_bytes):
                if cross_locked:
                    self._compact_locked()
                else:
                    # degraded: an unlocked compaction could truncate
                    # away a concurrent peer append in a shared-archive
                    # (RWX PVC) deployment — the append itself is safe
                    # (O_APPEND, interleave-atomic), compaction is not.
                    # The file grows past max_bytes until the lock
                    # heals; counted so operators see it.
                    self.compactions_skipped_unlocked += 1
        except OSError:
            pass

    def _active_framed_locked(self) -> bool:
        """Format of the ACTIVE file (caller holds the flock). Sniffed
        per append — not cached — because a shared-path peer's compaction
        can convert a legacy file under us; four bytes per append keeps
        the no-mixed-files invariant safe against that."""
        try:
            with open(self.path, "rb") as f:
                head = f.read(len(segfile.MAGIC))
        except OSError:
            return True  # absent: next append starts a framed file
        return len(head) < len(segfile.MAGIC) or head == segfile.MAGIC

    def _raw_append_locked(self, payload: bytes,
                           cross_locked: bool = True) -> bool:
        """One interleave-atomic write(2) (caller holds the flock).
        Shared by _append and claim_job so the write path cannot drift.
        Deliberately NOT a write loop — the record must land as a single
        write(2) or concurrent peer appends could interleave into it —
        so a detected short write takes the rollback arm instead:
        ftruncate back to the pre-append size while the cross-process
        lock guarantees no peer appended after us. Without that lock the
        torn tail stays (the framed scan truncates it; truncating
        ourselves could destroy a peer's record)."""
        if self._active_framed_locked():
            blob = segfile.frame(payload)
        else:
            blob = payload + b"\n"  # legacy file: stay line-framed
        seam_point(self, "archive.append")
        try:
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                base = os.fstat(fd).st_size
                n = os.write(fd, blob)
                if n != len(blob):
                    self.append_short_writes += 1
                    if cross_locked:
                        try:
                            os.ftruncate(fd, base)
                        except OSError:
                            pass
                    return False  # caller keeps RAM copy
            finally:
                os.close(fd)
        except OSError:
            return False  # disk full/unwritable: caller keeps RAM copy
        return True

    def _append(self, rec: dict) -> bool:
        payload = json.dumps(rec, separators=(",", ":")).encode()
        with self._flock() as lk:
            self._maybe_compact_locked(
                len(payload) + segfile.FRAME_OVERHEAD, lk.cross_locked)
            return self._raw_append_locked(payload, lk.cross_locked)

    def _compact_locked(self):
        """Merge both generations into `.1`, last-write-wins (caller holds
        the mutation lock, so no concurrent append can slip between the
        copy and the truncation). Terminal documents age out past
        keep_terminal_seconds so the compacted size tracks the LIVE job
        count, not deployment history. Output is always framed — this is
        where a legacy newline archive converts."""
        import time as _time

        now = _time.time()
        horizon = now - self.keep_terminal_seconds
        docs: dict[str, dict] = {}
        states: dict[str, dict] = {}
        hpalogs: list[dict] = []
        for rec in self._scan_once():
            t = rec.get("_type")
            if t == "document":
                cur = docs.get(rec.get("id", ""))
                if cur is None or (rec.get("modified_at", 0.0)
                                   >= cur.get("modified_at", 0.0)):
                    docs[rec.get("id", "")] = rec
            elif t == "state":
                cur = states.get(rec.get("key", ""))
                if cur is None or (rec.get("updated_at", 0.0)
                                   >= cur.get("updated_at", 0.0)):
                    states[rec.get("key", "")] = rec
            elif t == "hpalog":
                hpalogs.append(rec)
        hpalogs.sort(key=lambda r: r.get("timestamp", 0.0))
        hpalogs = hpalogs[-self.keep_hpalogs:]
        keep_docs = [
            rec for rec in docs.values()
            if rec.get("status") not in _TERMINAL
            or rec.get("modified_at", 0.0) >= horizon
        ]
        # dead shard-member heartbeat blobs age out like terminal docs do
        # (hostname-pid replica ids mint a new key per restart; without a
        # horizon the state section accumulates every incarnation forever)
        keep_states = [
            rec for rec in states.values()
            if not rec.get("key", "").startswith(MEMBER_STATE_PREFIX)
            or now - rec.get("updated_at", 0.0) <= KEEP_MEMBER_SECONDS
        ]
        tmp = self.path + ".1.tmp"
        with open(tmp, "wb") as f:
            for rec in (*keep_docs, *keep_states, *hpalogs):
                f.write(segfile.frame(
                    json.dumps(rec, separators=(",", ":")).encode()))
            f.flush()
            os.fsync(f.fileno())
        seam_point(self, "archive.compact.replace")
        os.replace(tmp, self.path + ".1")
        # truncate the active file (its records now live compacted in .1)
        # — a crash between the replace above and this truncate leaves
        # every record present in BOTH generations, which the newest-wins
        # view merge reads through unchanged (crashcheck enumerates it)
        seam_point(self, "archive.compact.truncate")
        fd = os.open(self.path, os.O_WRONLY | os.O_TRUNC | os.O_CREAT, 0o644)
        os.close(fd)
        self.compactions += 1

    def index_job(self, doc: dict) -> bool:
        return self._append({"_type": "document", **doc})

    def claim_job(self, job_id: str, expected_modified_at: float,
                  rec: dict) -> bool:
        """Single-adopter compare-and-swap: append `rec` only while the
        archive's LATEST record for `job_id` still carries
        `expected_modified_at` — under the cross-process mutation lock, so
        two replicas racing to adopt the same stale/released record cannot
        both win (the loser sees the winner's claim record and backs off).
        Returns False when the record moved (a peer's claim or any newer
        state) or is absent. A DEGRADED flock (sidecar .lock unopenable)
        keeps the in-process check but loses the cross-process guarantee —
        adoption degrades to the optimistic semantics, which stay safe
        (last-write-wins verdicts); counted on lock_degradations.

        Cost note: the check reads the incrementally-maintained doc view
        (refreshed under the flock, so it is exact), so a mass-adoption
        burst costs one suffix parse of the appends since the last read —
        not the O(archive) two-generation scan per claim it used to."""
        payload = json.dumps({"_type": "document", **rec},
                             separators=(",", ":")).encode()
        with self._flock() as lk:
            # same size-triggered compaction as _append: a mass-adoption
            # burst (rebalance after a replica death) appends one claim
            # record per job and must not grow the file unboundedly
            self._maybe_compact_locked(
                len(payload) + segfile.FRAME_OVERHEAD, lk.cross_locked)
            view = self._refresh_view(locked=True)
            latest = view[3].get(job_id)
            if latest is None:
                return False
            if latest.get("modified_at", 0.0) != expected_modified_at:
                return False
            return self._raw_append_locked(payload, lk.cross_locked)

    def index_hpalog(self, log: dict) -> bool:
        return self._append({"_type": "hpalog", **log})

    def get(self, job_id: str) -> dict | None:
        """Latest (by modified_at) archived record for one job id."""
        return self._refresh_view()[3].get(job_id)

    # -- reading --
    def _scan_once(self):
        """Whole-archive record walk (compaction's input): both
        generations, per-file format detection, damage skipped."""
        for p in (self.path + ".1", self.path):
            yield from _parse_file(segfile.read_file(p))

    def _mutation_sig(self):
        """(inode of .1, size of active file): compaction replaces .1
        (new inode) and truncates the active file (size shrink) — either
        tells a lock-free reader its refresh may have missed moving
        records. Plain appends only GROW the active file, which the
        incremental view absorbs without a rescan."""
        try:
            ino1 = os.stat(self.path + ".1").st_ino
        except OSError:
            ino1 = None
        try:
            size = os.stat(self.path).st_size
        except OSError:
            size = 0
        return (ino1, size)

    def _advance_view_locked(self, force_full: bool = False):
        """Bring the view up to date (caller holds _view_lock) and return
        it. Same `.1` generation + grown active file -> parse only the new
        suffix into a copy-on-write successor; anything else (first read,
        rotation, shrink race) -> full rebuild. The returned tuple is
        immutable once installed: readers keep iterating their snapshot
        while the next one lands."""
        try:
            ino1 = os.stat(self.path + ".1").st_ino
        except OSError:
            ino1 = None
        v = self._view
        if not force_full and v is not None and v[0] == ino1:
            _, framed, scanned, docs, states = v
            try:
                size = os.stat(self.path).st_size
            except OSError:
                size = 0
            if size == scanned:
                return v
            if size > scanned:
                try:
                    with open(self.path, "rb") as f:
                        f.seek(scanned)
                        tail = f.read()
                except OSError:
                    tail = b""
                if framed is None:  # file was empty at the last rebuild
                    framed = tail[:len(segfile.MAGIC)] == segfile.MAGIC
                recs, consumed = (_parse_framed if framed
                                  else _parse_legacy)(tail, 0)
                if not recs and consumed == 0:
                    return v  # only a torn/in-flight tail: nothing new
                new_docs = dict(docs)
                new_states = dict(states)
                _merge_view(new_docs, new_states, recs)
                nv = (ino1, framed, scanned + consumed, new_docs, new_states)
                self._view = nv
                return nv
            # size < scanned with an unchanged .1 inode: mid-compaction
            # race or external truncation — rebuild from scratch
        docs, states = {}, {}
        _merge_view(docs, states, _parse_file(
            segfile.read_file(self.path + ".1")))
        bufa = segfile.read_file(self.path)
        framed = (bufa[:len(segfile.MAGIC)] == segfile.MAGIC) if bufa \
            else None
        consumed = 0
        if bufa:
            recs, consumed = (_parse_framed if framed
                              else _parse_legacy)(bufa, 0)
            _merge_view(docs, states, recs)
        self.view_rebuilds += 1
        nv = (ino1, framed, consumed, docs, states)
        self._view = nv
        return nv

    def _refresh_view(self, locked: bool = False):
        """Lock-free view refresh with rotation-race protection: a
        compaction DURING the refresh could hide records mid-move (new
        `.1` written after we read the old one, active file truncated
        after we read it), so detect it — `.1` inode change or active
        file shrink — and retry; the view merge is last-write-wins per
        id/key, so re-delivered records are harmless. If churn outlasts
        the retries, one rebuild runs UNDER the mutation lock (compaction
        cannot race it), so a read never silently serves a partial view;
        the fallback is counted for observability. ``locked=True`` means
        the caller already holds the flock (claim_job): one advance is
        exact by construction."""
        for _attempt in range(1 if locked else 3):
            sig_before = self._mutation_sig()
            with self._view_lock:
                v = self._advance_view_locked()
            sig_after = self._mutation_sig()
            if locked or (sig_after[0] == sig_before[0]
                          and sig_after[1] >= sig_before[1]):
                return v
        self.locked_scan_fallbacks += 1
        with self._flock():
            with self._view_lock:
                return self._advance_view_locked(force_full=True)

    def search(self, app=None, namespace=None, status=None, strategy=None,
               limit: int = 50, oldest_first: bool = False) -> list[dict]:
        """Latest record per job id (by its own modified_at), capped.

        Sorted newest-first for humans; `oldest_first=True` for the
        adoption scan — a crashed peer's stuck jobs have the OLDEST
        stamps, so a newest-first cap at fleet scale would cut exactly
        the records failover exists to find.

        Dedupe happens BEFORE filtering (the view already holds only each
        job's LATEST archived state) — the same semantics as ES, where a
        PUT per id overwrites and a search can never surface a superseded
        state. (Filtering first would resurrect a completed job's earlier
        open-status record — fatal for cross-replica adoption, which asks
        the archive for open jobs.)"""
        out = [
            rec for rec in self._refresh_view()[3].values()
            if _match(rec, app, namespace, status, strategy)
        ]
        out.sort(key=lambda r: r.get("modified_at", 0.0),
                 reverse=not oldest_first)
        return out[:limit]

    # -- engine state blobs (breath cooldowns): last-writer-wins by stamp --
    def index_state(self, key: str, value, updated_at: float) -> bool:
        return self._append({"_type": "state", "key": key, "value": value,
                             "updated_at": updated_at})

    def get_state(self, key: str):
        """Latest (value, updated_at) for an engine state blob, or None."""
        return self._refresh_view()[4].get(key)

    def list_state(self, prefix: str = "") -> dict | None:
        """{key: (value, updated_at)} — latest per key under `prefix`
        (the shard-membership enumeration; engine/sharding.py). Returns a
        dict on success; implementations that can FAIL the read (EsArchive,
        the breaker wrapper) return None instead of {} so callers can keep
        their previous view through an outage. Served from the
        incremental view: between mutations the per-heartbeat membership
        read costs a couple of stat(2)s, and each heartbeat's own append
        costs one suffix parse — never a two-generation walk."""
        states = self._refresh_view()[4]
        if not prefix:
            return dict(states)
        return {k: v for k, v in states.items() if k.startswith(prefix)}


class EsArchive:
    """Write-behind into ES-compatible REST indices (documents/hpalogs).

    Engine state blobs go to a third index (`enginestate`) so they can
    never pollute a documents search."""

    def __init__(self, endpoint: str, documents_index: str = "documents",
                 hpalogs_index: str = "hpalogs",
                 state_index: str = "enginestate", timeout: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.documents_index = documents_index
        self.hpalogs_index = hpalogs_index
        self.state_index = state_index
        self.timeout = timeout
        self.errors = 0  # observability: archive is best-effort

    def _req(self, method: str, path: str, body: dict | None = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self.endpoint}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    def index_job(self, doc: dict) -> bool:
        # external versioning by the doc's own modified_at: a recovered
        # wedged peer's STALE open mirror must not overwrite a newer
        # terminal record another replica already wrote (ES rejects
        # version <= existing with 409 — which means the archive already
        # holds something at least as new: success for our contract)
        version = int(doc.get("modified_at", 0.0) * 1_000_000)
        try:
            self._req(
                "PUT",
                f"/{self.documents_index}/_doc/{doc['id']}"
                f"?version_type=external_gte&version={version}",
                doc,
            )
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                return True  # archive already newer: record is safe
            self.errors += 1
            return False
        except Exception:  # noqa: BLE001 - never fail a verdict on archive IO
            self.errors += 1
            return False

    def index_hpalog(self, log: dict) -> bool:
        try:
            self._req("POST", f"/{self.hpalogs_index}/_doc", log)
            return True
        except Exception:  # noqa: BLE001
            self.errors += 1
            return False

    def get(self, job_id: str) -> dict | None:
        try:
            res = self._req("GET", f"/{self.documents_index}/_doc/{job_id}")
        except Exception:  # noqa: BLE001
            self.errors += 1
            return None
        return res.get("_source")

    def claim_job(self, job_id: str, expected_modified_at: float,
                  rec: dict) -> bool:
        """Single-adopter compare-and-swap via ES optimistic concurrency:
        re-read the doc, verify it is still the version the adoption scan
        decided on, then PUT conditioned on if_seq_no/if_primary_term — a
        racing peer's claim bumps the seq_no and this PUT 409s. Servers
        without the concurrency fields degrade to the plain external-
        version PUT (optimistic adoption, the pre-CAS semantics)."""
        try:
            res = self._req("GET", f"/{self.documents_index}/_doc/{job_id}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False  # nothing to claim
            self.errors += 1  # 5xx outage: visible on foremast_archive_errors
            return False
        except Exception:  # noqa: BLE001 - transport: treat as lost race
            self.errors += 1
            return False
        src = res.get("_source") or {}
        if src.get("modified_at", 0.0) != expected_modified_at:
            return False  # the record moved since the scan read it
        seq_no, p_term = res.get("_seq_no"), res.get("_primary_term")
        if seq_no is None or p_term is None:
            return self.index_job(rec)
        try:
            self._req(
                "PUT",
                f"/{self.documents_index}/_doc/{job_id}"
                f"?if_seq_no={seq_no}&if_primary_term={p_term}",
                rec,
            )
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                return False  # a peer claimed it first
            self.errors += 1
            return False
        except Exception:  # noqa: BLE001 - never fail a verdict on archive IO
            self.errors += 1
            return False

    def index_state(self, key: str, value, updated_at: float) -> bool:
        version = int(updated_at * 1_000_000)
        try:
            self._req(
                "PUT",
                f"/{self.state_index}/_doc/{key}"
                f"?version_type=external_gte&version={version}",
                {"key": key, "value": value, "updated_at": updated_at},
            )
            return True
        except urllib.error.HTTPError as e:
            if e.code == 409:
                return True  # a newer state blob is already archived
            self.errors += 1
            return False
        except Exception:  # noqa: BLE001
            self.errors += 1
            return False

    def get_state(self, key: str):
        try:
            res = self._req("GET", f"/{self.state_index}/_doc/{key}")
        except Exception:  # noqa: BLE001
            self.errors += 1
            return None
        src = res.get("_source")
        if not src:
            return None
        return (src.get("value"), src.get("updated_at", 0.0))

    def list_state(self, prefix: str = "") -> dict | None:
        """{key: (value, updated_at)} under `prefix`, or None on a FAILED
        read (outage) so membership callers keep their previous view
        instead of collapsing the ring (engine/sharding.py)."""
        query = ({"prefix": {"key.keyword": prefix}} if prefix
                 else {"match_all": {}})
        try:
            res = self._req(
                "POST", f"/{self.state_index}/_search",
                # newest-first: if the result ever exceeds the cap, the
                # truncated page drops the OLDEST docs (dead replica
                # incarnations), never a live member's current heartbeat
                {"query": query, "size": 1000,
                 "sort": [{"updated_at": {"order": "desc",
                                          "unmapped_type": "double"}}]},
            )
        except Exception:  # noqa: BLE001
            self.errors += 1
            return None
        out: dict[str, tuple] = {}
        for h in res.get("hits", {}).get("hits", []):
            src = h.get("_source") or {}
            key = src.get("key", "")
            if key:
                out[key] = (src.get("value"), src.get("updated_at", 0.0))
        return out

    def delete_state(self, key: str) -> bool:
        """Best-effort DELETE of one state doc. ES has no compaction pass
        to age dead shard-member blobs out (FileArchive drops them when
        it compacts), so the membership reader prunes long-dead
        incarnations through this instead (engine/sharding.py)."""
        try:
            self._req("DELETE", f"/{self.state_index}/_doc/{key}")
            return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return True  # already gone
            self.errors += 1
            return False
        except Exception:  # noqa: BLE001 - best-effort hygiene
            self.errors += 1
            return False

    def search(self, app=None, namespace=None, status=None, strategy=None,
               limit: int = 50, oldest_first: bool = False) -> list[dict]:
        terms = []
        for field_name, v in (("app_name", app), ("namespace", namespace),
                              ("strategy", strategy)):
            if v is not None:
                terms.append({"term": {f"{field_name}.keyword": v}})
        statuses = _statuses(status)
        if statuses is not None:
            terms.append({"terms": {"status.keyword": statuses}})
        query = {"bool": {"must": terms}} if terms else {"match_all": {}}
        # oldest_first: the adoption scan wants the STALEST records — a
        # newest-first cap would cut a crashed peer's stuck jobs first
        order = "asc" if oldest_first else "desc"
        try:
            res = self._req(
                "POST",
                f"/{self.documents_index}/_search",
                {"query": query, "size": limit,
                 "sort": [{"modified_at": order}]},
            )
        except Exception:  # noqa: BLE001
            self.errors += 1
            return []
        return [h.get("_source", {}) for h in
                res.get("hits", {}).get("hits", [])]
