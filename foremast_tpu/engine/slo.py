"""Detection-latency SLOs: ingest->verdict latency per job class.

Foremast's value proposition is FAST, explainable health verdicts, yet
until this module nothing measured how fast: the cycle stage gauges time
engine internals, not the thing an operator is promised — how long after
a job's window advanced (its newest judged sample arrived) did the
verdict land? The analyzer stamps each job's window-advance moment
through the cycle (the newest valid sample timestamp across its judged
current windows, plus an ingest marker as its preprocess completes) and
observes the latency when the verdict folds: the poll/scrape wait
(cycle ``now`` minus the newest sample's own timestamp — the component
the streaming dataplane exists to remove, floored by the metric step /
CYCLE_SECONDS under poll-driven operation) plus the measured in-cycle
tail (``Analyzer._observe_latency``), bucketed per job CLASS:

  * ``canary``     — new-deployment analyses (rollingUpdate/canary/
                     rollover): the verdict gates a live rollout, so the
                     tightest target;
  * ``continuous`` — steady-state monitors, re-judged every cycle;
  * ``hpa``        — autoscaling scores, consumed by the HPA adapter.

Each class carries an SLO target (SLO_CANARY_S / SLO_CONTINUOUS_S /
SLO_HPA_S) and the fleet-wide objective (SLO_OBJECTIVE, default 0.99:
99% of verdicts inside the target). The tracker keeps its own bucket
counts (quantile estimates for /status, the fleet digest, and
`foremast-tpu top`) and mirrors everything onto the exporter:

  foremastbrain:detection_latency_seconds{class=}   histogram
  foremastbrain:slo_attainment{class=}              gauge (0..1)
  foremastbrain:slo_error_budget_burn{class=}       gauge (burn rate)
  foremastbrain:slo_violations_total{class=}        counter

Burn rate is the standard SRE ratio: observed violation rate over the
budgeted violation rate (1 - objective). 1.0 = burning exactly the
budget; >1 = the error budget shrinks; a sustained burn >> 1 is the
page. Pure observation: nothing here feeds back into scoring, so the
verdict A/B identity contract (tests/test_provenance.py) covers it.

This was the latency baseline the streaming dataplane had to beat —
measured before improved, per SWIFT's trace-first methodology. With
push ingestion (foremast_tpu/ingest) the poll/scrape wait collapses to
push latency: the event scheduler scores a pushed job the moment its
window advances, and the analyzer observes each window advance ONCE
(Analyzer._observe_latency), so re-confirming sweeps cannot drown the
advance's own latency. The polled-vs-streamed A/B lives in
bench_cycle.run_stream_ab (`make perf`).
"""
from __future__ import annotations

import bisect

from ..dataplane.exporter import DEFAULT_TIME_BUCKETS
from ..utils.locks import make_lock

__all__ = ["DetectionSLO", "classify", "SLO_CLASSES"]

SLO_CLASSES = ("canary", "continuous", "hpa")


def classify(strategy: str) -> str:
    """Job class for SLO accounting from the wire strategy."""
    if strategy == "hpa":
        return "hpa"
    if strategy == "continuous":
        return "continuous"
    return "canary"  # rollingUpdate / canary / rollover


class DetectionSLO:
    """Per-class ingest->verdict latency distributions + SLO math.

    The engine worker writes (observe); HTTP/CLI threads read (snapshot,
    quantile). All reads copy under the lock. Allocation-bounded by
    construction: three classes x one fixed bucket grid."""

    def __init__(self, exporter=None, targets: dict | None = None,
                 objective: float = 0.99,
                 buckets: tuple = DEFAULT_TIME_BUCKETS):
        self.exporter = exporter
        self.targets = dict(targets or {})
        # objective clamped to (0, 1): 1.0 would make the budget zero and
        # every burn infinite; 0 would make attainment meaningless
        self.objective = min(max(float(objective), 0.0), 0.999999)
        self._edges = tuple(buckets)
        self._lock = make_lock("engine.slo")
        # class -> [bucket counts (+Inf implicit last)], sum, count,
        # violations (latency > target)
        self._counts: dict[str, list] = {}
        self._sums: dict[str, float] = {}
        self._totals: dict[str, int] = {}
        self._violations: dict[str, int] = {}

    # -------------------------------------------------------------- writing
    def observe(self, cls: str, latency_s: float):
        """One ingest->verdict observation for a job of class `cls`."""
        v = max(float(latency_s), 0.0)
        target = float(self.targets.get(cls, 0.0))
        violated = target > 0 and v > target
        with self._lock:
            counts = self._counts.get(cls)
            if counts is None:
                counts = self._counts[cls] = [0] * (len(self._edges) + 1)
                self._sums[cls] = 0.0
                self._totals[cls] = 0
                self._violations[cls] = 0
            counts[bisect.bisect_left(self._edges, v)] += 1
            self._sums[cls] += v
            self._totals[cls] += 1
            if violated:
                self._violations[cls] += 1
            attainment = 1.0 - self._violations[cls] / self._totals[cls]
        if self.exporter is not None:
            self.exporter.record_histogram(
                "foremastbrain:detection_latency_seconds", {"class": cls}, v,
                help="Window-advance (newest judged sample) to verdict "
                     "latency per job class (seconds).",
                buckets=self._edges)
            if violated:
                self.exporter.record_counter(
                    "foremastbrain:slo_violations_total", {"class": cls},
                    help="verdicts that landed outside the class's "
                         "detection-latency SLO target")
            self._export_gauges(cls, attainment)

    def _export_gauges(self, cls: str, attainment: float):
        self.exporter.record_gauge(
            "foremastbrain:slo_attainment", {"class": cls},
            round(attainment, 6),
            help="Fraction of verdicts inside the class's detection-"
                 "latency SLO target (cumulative).")
        self.exporter.record_gauge(
            "foremastbrain:slo_error_budget_burn", {"class": cls},
            round(self._burn_from(attainment), 4),
            help="Error-budget burn rate: observed violation rate over "
                 "the budgeted rate (1 - SLO_OBJECTIVE); >1 = budget "
                 "shrinking.")

    def _burn_from(self, attainment: float) -> float:
        budget = 1.0 - self.objective
        return (1.0 - attainment) / budget if budget > 0 else 0.0

    # -------------------------------------------------------------- reading
    def quantile(self, q: float, cls: str | None = None) -> float:
        """Bucket-resolution quantile estimate (seconds): the upper edge
        of the bucket the q-th observation lands in. `cls=None` pools
        every class. 0.0 when nothing was observed."""
        with self._lock:
            if cls is None:
                rows = list(self._counts.values())
            else:
                rows = [self._counts[cls]] if cls in self._counts else []
            if not rows:
                return 0.0
            counts = [sum(r[i] for r in rows)
                      for i in range(len(self._edges) + 1)]
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                # +Inf bucket: report the last finite edge (the estimate
                # is a floor, which is the honest direction for an SLO)
                return float(self._edges[min(i, len(self._edges) - 1)])
        return float(self._edges[-1])

    def attainment(self, cls: str) -> float:
        with self._lock:
            n = self._totals.get(cls, 0)
            if n == 0:
                return 1.0
            return 1.0 - self._violations.get(cls, 0) / n

    def burn(self, cls: str) -> float:
        return self._burn_from(self.attainment(cls))

    def burn_summary(self) -> dict:
        """{class: burn} for classes with observations — the HealthMonitor
        detail tap (informational, never a state driver; empty before the
        first verdict so existing health-detail consumers see no change)."""
        with self._lock:
            have = [c for c, n in self._totals.items() if n]
        return {c: round(self.burn(c), 4) for c in sorted(have)}

    def snapshot(self) -> dict:
        """Full /status section: per-class distribution + SLO math, plus
        the configured targets even before the first observation (the
        operator should see the knobs, not an empty object)."""
        with self._lock:
            classes = sorted(set(self._totals) | set(self.targets))
            totals = dict(self._totals)
            sums = dict(self._sums)
            violations = dict(self._violations)
        out = {"objective": self.objective, "classes": {}}
        for cls in classes:
            n = totals.get(cls, 0)
            att = (1.0 - violations.get(cls, 0) / n) if n else 1.0
            out["classes"][cls] = {
                "target_s": self.targets.get(cls, 0.0),
                "count": n,
                "violations": violations.get(cls, 0),
                "p50_s": round(self.quantile(0.5, cls), 4),
                "p99_s": round(self.quantile(0.99, cls), 4),
                "mean_s": round(sums.get(cls, 0.0) / n, 4) if n else 0.0,
                "attainment": round(att, 6),
                "burn": round(self._burn_from(att), 4),
            }
        return out

    def digest(self) -> dict:
        """Compact per-class block for the fleet status digest (rides the
        membership heartbeat blob — must stay small)."""
        with self._lock:
            have = sorted(c for c, n in self._totals.items() if n)
        out = {}
        for cls in have:
            att = self.attainment(cls)
            out[cls] = {
                "p50_s": round(self.quantile(0.5, cls), 4),
                "p99_s": round(self.quantile(0.99, cls), 4),
                "attainment": round(att, 6),
                "burn": round(self._burn_from(att), 4),
                "n": self._totals.get(cls, 0),
            }
        return out

    def refresh_metrics(self):
        """Re-stamp the SLO gauges at scrape time (gauges are time-staled
        by the exporter; a quiet fleet must not scrape away its
        attainment history)."""
        if self.exporter is None:
            return
        with self._lock:
            have = [c for c, n in self._totals.items() if n]
        for cls in have:
            self._export_gauges(cls, self.attainment(cls))

    def reset(self):
        """Clear observations (bench legs isolate their measured cycles
        from warm-up; the exporter's cumulative series are untouched)."""
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()
            self._violations.clear()
