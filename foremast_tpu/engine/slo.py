"""Detection-latency SLOs: ingest->verdict latency per job class.

Foremast's value proposition is FAST, explainable health verdicts, yet
until this module nothing measured how fast: the cycle stage gauges time
engine internals, not the thing an operator is promised — how long after
a job's window advanced (its newest judged sample arrived) did the
verdict land? The analyzer stamps each job's window-advance moment
through the cycle (the newest valid sample timestamp across its judged
current windows, plus an ingest marker as its preprocess completes) and
observes the latency when the verdict folds: the poll/scrape wait
(cycle ``now`` minus the newest sample's own timestamp — the component
the streaming dataplane exists to remove, floored by the metric step /
CYCLE_SECONDS under poll-driven operation) plus the measured in-cycle
tail (``Analyzer._observe_latency``), bucketed per job CLASS:

  * ``canary``     — new-deployment analyses (rollingUpdate/canary/
                     rollover): the verdict gates a live rollout, so the
                     tightest target;
  * ``continuous`` — steady-state monitors, re-judged every cycle;
  * ``hpa``        — autoscaling scores, consumed by the HPA adapter.

Each class carries an SLO target (SLO_CANARY_S / SLO_CONTINUOUS_S /
SLO_HPA_S) and the fleet-wide objective (SLO_OBJECTIVE, default 0.99:
99% of verdicts inside the target). The tracker keeps its own bucket
counts (quantile estimates for /status, the fleet digest, and
`foremast-tpu top`) and mirrors everything onto the exporter:

  foremastbrain:detection_latency_seconds{class=}   histogram
  foremastbrain:slo_attainment{class=}              gauge (0..1)
  foremastbrain:slo_error_budget_burn{class=}       gauge (burn rate)
  foremastbrain:slo_violations_total{class=}        counter

Burn rate is the standard SRE ratio: observed violation rate over the
budgeted violation rate (1 - objective). 1.0 = burning exactly the
budget; >1 = the error budget shrinks; a sustained burn >> 1 is the
page. Pure observation: nothing here feeds back into scoring, so the
verdict A/B identity contract (tests/test_provenance.py) covers it.

This was the latency baseline the streaming dataplane had to beat —
measured before improved, per SWIFT's trace-first methodology. With
push ingestion (foremast_tpu/ingest) the poll/scrape wait collapses to
push latency: the event scheduler scores a pushed job the moment its
window advances, and the analyzer observes each window advance ONCE
(Analyzer._observe_latency), so re-confirming sweeps cannot drown the
advance's own latency. The polled-vs-streamed A/B lives in
bench_cycle.run_stream_ab (`make perf`).
"""
from __future__ import annotations

import bisect
import time
from collections import OrderedDict

from ..dataplane.exporter import DEFAULT_TIME_BUCKETS
from ..utils.locks import make_lock

__all__ = [
    "DetectionSLO", "DetectionWaterfall", "classify", "SLO_CLASSES",
    "STAGES", "STAGE_ORDER",
]

SLO_CLASSES = ("canary", "continuous", "hpa")

# ---------------------------------------------------------------------------
# Detection-latency waterfall stages (PR 14): the decomposition of ONE
# detection_latency_seconds observation into where the time actually
# went, exported as foremastbrain:detection_stage_seconds{stage=}.
# Stage names are REGISTERED constants — the devtools trace-registry
# rule rejects unregistered literals in add_stage() calls, exactly like
# span names — so dashboards and the runbook can enumerate them.
#
#   ingest_receive  sample existed -> receiver accepted it (push
#                   transport lag + decode/route/buffer time)
#   forward_hop     origin replica's first contact -> the owning
#                   replica's receipt (one ring hop; absent unforwarded)
#   wal_append      the durability write before the /ingest ack
#   splice          the delta-cache splice of the pushed batch
#   debounce_wait   scheduler notify -> debounce window elapsed
#                   (bounded by INGEST_DEBOUNCE_MS)
#   schedule_wait   debounce end -> the partial cycle actually started
#                   (waiting behind a running sweep); for POLLED jobs
#                   this is the whole poll/scrape wait (cycle `now`
#                   minus the newest judged sample — push stages absent)
#   score           cycle start -> verdict fold began (fetch + dispatch
#                   + collect for this job's cycle)
#   fold            fold began -> this job's verdict was written
# ---------------------------------------------------------------------------
STAGE_INGEST_RECEIVE = "ingest_receive"
STAGE_FORWARD_HOP = "forward_hop"
STAGE_WAL_APPEND = "wal_append"
STAGE_SPLICE = "splice"
STAGE_DEBOUNCE_WAIT = "debounce_wait"
STAGE_SCHEDULE_WAIT = "schedule_wait"
STAGE_SCORE = "score"
STAGE_FOLD = "fold"

STAGE_ORDER = (
    STAGE_INGEST_RECEIVE, STAGE_FORWARD_HOP, STAGE_WAL_APPEND,
    STAGE_SPLICE, STAGE_DEBOUNCE_WAIT, STAGE_SCHEDULE_WAIT,
    STAGE_SCORE, STAGE_FOLD,
)
STAGES = frozenset(STAGE_ORDER)


def classify(strategy: str) -> str:
    """Job class for SLO accounting from the wire strategy."""
    if strategy == "hpa":
        return "hpa"
    if strategy == "continuous":
        return "continuous"
    return "canary"  # rollingUpdate / canary / rollover


class DetectionSLO:
    """Per-class ingest->verdict latency distributions + SLO math.

    The engine worker writes (observe); HTTP/CLI threads read (snapshot,
    quantile). All reads copy under the lock. Allocation-bounded by
    construction: three classes x one fixed bucket grid."""

    def __init__(self, exporter=None, targets: dict | None = None,
                 objective: float = 0.99,
                 buckets: tuple = DEFAULT_TIME_BUCKETS):
        self.exporter = exporter
        self.targets = dict(targets or {})
        # objective clamped to (0, 1): 1.0 would make the budget zero and
        # every burn infinite; 0 would make attainment meaningless
        self.objective = min(max(float(objective), 0.0), 0.999999)
        self._edges = tuple(buckets)
        self._lock = make_lock("engine.slo")
        # class -> [bucket counts (+Inf implicit last)], sum, count,
        # violations (latency > target)
        self._counts: dict[str, list] = {}
        self._sums: dict[str, float] = {}
        self._totals: dict[str, int] = {}
        self._violations: dict[str, int] = {}

    # -------------------------------------------------------------- writing
    def observe(self, cls: str, latency_s: float):
        """One ingest->verdict observation for a job of class `cls`."""
        v = max(float(latency_s), 0.0)
        target = float(self.targets.get(cls, 0.0))
        violated = target > 0 and v > target
        with self._lock:
            counts = self._counts.get(cls)
            if counts is None:
                counts = self._counts[cls] = [0] * (len(self._edges) + 1)
                self._sums[cls] = 0.0
                self._totals[cls] = 0
                self._violations[cls] = 0
            counts[bisect.bisect_left(self._edges, v)] += 1
            self._sums[cls] += v
            self._totals[cls] += 1
            if violated:
                self._violations[cls] += 1
            attainment = 1.0 - self._violations[cls] / self._totals[cls]
        if self.exporter is not None:
            self.exporter.record_histogram(
                "foremastbrain:detection_latency_seconds", {"class": cls}, v,
                help="Window-advance (newest judged sample) to verdict "
                     "latency per job class (seconds).",
                buckets=self._edges)
            if violated:
                self.exporter.record_counter(
                    "foremastbrain:slo_violations_total", {"class": cls},
                    help="verdicts that landed outside the class's "
                         "detection-latency SLO target")
            self._export_gauges(cls, attainment)

    def _export_gauges(self, cls: str, attainment: float):
        self.exporter.record_gauge(
            "foremastbrain:slo_attainment", {"class": cls},
            round(attainment, 6),
            help="Fraction of verdicts inside the class's detection-"
                 "latency SLO target (cumulative).")
        self.exporter.record_gauge(
            "foremastbrain:slo_error_budget_burn", {"class": cls},
            round(self._burn_from(attainment), 4),
            help="Error-budget burn rate: observed violation rate over "
                 "the budgeted rate (1 - SLO_OBJECTIVE); >1 = budget "
                 "shrinking.")

    def _burn_from(self, attainment: float) -> float:
        budget = 1.0 - self.objective
        return (1.0 - attainment) / budget if budget > 0 else 0.0

    # -------------------------------------------------------------- reading
    def quantile(self, q: float, cls: str | None = None) -> float:
        """Bucket-resolution quantile estimate (seconds): the upper edge
        of the bucket the q-th observation lands in. `cls=None` pools
        every class. 0.0 when nothing was observed."""
        with self._lock:
            if cls is None:
                rows = list(self._counts.values())
            else:
                rows = [self._counts[cls]] if cls in self._counts else []
            if not rows:
                return 0.0
            counts = [sum(r[i] for r in rows)
                      for i in range(len(self._edges) + 1)]
        total = sum(counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                # +Inf bucket: report the last finite edge (the estimate
                # is a floor, which is the honest direction for an SLO)
                return float(self._edges[min(i, len(self._edges) - 1)])
        return float(self._edges[-1])

    def attainment(self, cls: str) -> float:
        with self._lock:
            n = self._totals.get(cls, 0)
            if n == 0:
                return 1.0
            return 1.0 - self._violations.get(cls, 0) / n

    def burn(self, cls: str) -> float:
        return self._burn_from(self.attainment(cls))

    def burn_summary(self) -> dict:
        """{class: burn} for classes with observations — the HealthMonitor
        detail tap (informational, never a state driver; empty before the
        first verdict so existing health-detail consumers see no change)."""
        with self._lock:
            have = [c for c, n in self._totals.items() if n]
        return {c: round(self.burn(c), 4) for c in sorted(have)}

    def snapshot(self) -> dict:
        """Full /status section: per-class distribution + SLO math, plus
        the configured targets even before the first observation (the
        operator should see the knobs, not an empty object)."""
        with self._lock:
            classes = sorted(set(self._totals) | set(self.targets))
            totals = dict(self._totals)
            sums = dict(self._sums)
            violations = dict(self._violations)
        out = {"objective": self.objective, "classes": {}}
        for cls in classes:
            n = totals.get(cls, 0)
            att = (1.0 - violations.get(cls, 0) / n) if n else 1.0
            out["classes"][cls] = {
                "target_s": self.targets.get(cls, 0.0),
                "count": n,
                "violations": violations.get(cls, 0),
                "p50_s": round(self.quantile(0.5, cls), 4),
                "p99_s": round(self.quantile(0.99, cls), 4),
                "mean_s": round(sums.get(cls, 0.0) / n, 4) if n else 0.0,
                "attainment": round(att, 6),
                "burn": round(self._burn_from(att), 4),
            }
        return out

    def digest(self) -> dict:
        """Compact per-class block for the fleet status digest (rides the
        membership heartbeat blob — must stay small)."""
        with self._lock:
            have = sorted(c for c, n in self._totals.items() if n)
        out = {}
        for cls in have:
            att = self.attainment(cls)
            out[cls] = {
                "p50_s": round(self.quantile(0.5, cls), 4),
                "p99_s": round(self.quantile(0.99, cls), 4),
                "attainment": round(att, 6),
                "burn": round(self._burn_from(att), 4),
                "n": self._totals.get(cls, 0),
            }
        return out

    def refresh_metrics(self):
        """Re-stamp the SLO gauges at scrape time (gauges are time-staled
        by the exporter; a quiet fleet must not scrape away its
        attainment history)."""
        if self.exporter is None:
            return
        with self._lock:
            have = [c for c, n in self._totals.items() if n]
        for cls in have:
            self._export_gauges(cls, self.attainment(cls))

    def reset(self):
        """Clear observations (bench legs isolate their measured cycles
        from warm-up; the exporter's cumulative series are untouched)."""
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()
            self._violations.clear()


class DetectionWaterfall:
    """Per-job detection-latency stage attribution (STAGE_ORDER above).

    The push half of the pipeline (ingest receiver, event scheduler)
    accumulates stage seconds into a bounded in-flight book keyed by
    job id; the analyzer closes each record at verdict fold (`observe`),
    exporting one histogram sample per stage
    (``foremastbrain:detection_stage_seconds{stage=}``) so PR 10's SLO
    burn decomposes into actionable stages. Polled jobs get the same
    waterfall minus the push stages: their whole wait is
    ``schedule_wait`` (cycle ``now`` − newest judged sample). The book
    also carries each push's adopted W3C trace context + first-contact
    timestamp (stamped ONCE at the origin replica, propagated through
    ring forwards), which is how the verdict span and the provenance
    ``trace_id`` link back to the push's distributed trace.

    Pure observation, allocation-bounded (LRU book + fixed bucket
    grids); HTTP threads write, the engine thread closes — everything
    under one short lock, nothing blocking held."""

    def __init__(self, exporter=None, max_jobs: int = 4096,
                 buckets: tuple = DEFAULT_TIME_BUCKETS):
        self.exporter = exporter
        self.max_jobs = int(max_jobs)
        self._edges = tuple(buckets)
        self._lock = make_lock("engine.slo.waterfall")
        # job_id -> {"origin": wall ts of first contact, "accepted": wall
        # ts the owning replica accepted, "notify_mono": scheduler stamp,
        # "stages": {stage: seconds}, "ctx": W3CContext | None}
        self._inflight: OrderedDict[str, dict] = OrderedDict()
        # stage -> [bucket counts (+Inf implicit), sum, count]; "total"
        # pseudo-row tracks the per-observation stage sum so the bench
        # can compare it against detection_latency_seconds directly
        self._hist: dict[str, list] = {}
        self.observed_total = 0
        self.streamed_total = 0
        self.last: dict = {}

    # ------------------------------------------------------------- writing
    def begin_push(self, job_id: str, origin_wall: float,
                   accepted_wall: float, ctx=None):
        """Open (or refresh) a job's in-flight record at push accept.
        The ORIGIN timestamp is kept from the earliest unobserved push
        (detection latency is measured from first contact, never reset
        by forwarding or a second push); the accepted stamp and trace
        context follow the newest push."""
        with self._lock:
            rec = self._inflight.get(job_id)
            if rec is None:
                rec = self._inflight[job_id] = {
                    "origin": float(origin_wall), "stages": {},
                    "notify_mono": 0.0, "ctx": None,
                }
                while len(self._inflight) > self.max_jobs:
                    self._inflight.popitem(last=False)
            else:
                rec["origin"] = min(rec["origin"], float(origin_wall))
                self._inflight.move_to_end(job_id)
            rec["accepted"] = float(accepted_wall)
            if ctx is not None:
                rec["ctx"] = ctx

    def add_stage(self, job_id: str, stage: str, seconds: float):
        """Accumulate stage seconds onto a job's in-flight record (no-op
        when the job has none — stage timings without a push accept have
        nothing to attribute to)."""
        with self._lock:
            rec = self._inflight.get(job_id)
            if rec is not None:
                rec["stages"][stage] = \
                    rec["stages"].get(stage, 0.0) + max(float(seconds), 0.0)

    def notify(self, job_ids):
        """Scheduler tap: stamp when each pushed job entered the pending
        set (the debounce/schedule wait clock starts here)."""
        now = time.monotonic()
        with self._lock:
            for jid in job_ids:
                rec = self._inflight.get(jid)
                if rec is not None and not rec["notify_mono"]:
                    rec["notify_mono"] = now

    def claim(self, job_ids, debounce_seconds: float):
        """Scheduler tap: the partial cycle is starting NOW for these
        jobs — split the measured notify->start wait into the debounce
        window (bounded by the knob) and the scheduling excess (waiting
        behind a running sweep)."""
        now = time.monotonic()
        db = max(float(debounce_seconds), 0.0)
        with self._lock:
            for jid in job_ids:
                rec = self._inflight.get(jid)
                if rec is None or not rec["notify_mono"]:
                    continue
                wait = max(now - rec["notify_mono"], 0.0)
                rec["notify_mono"] = 0.0
                d = min(wait, db)
                st = rec["stages"]
                st[STAGE_DEBOUNCE_WAIT] = st.get(STAGE_DEBOUNCE_WAIT,
                                                 0.0) + d
                st[STAGE_SCHEDULE_WAIT] = st.get(STAGE_SCHEDULE_WAIT,
                                                 0.0) + (wait - d)
                rec["scheduled"] = True

    def discard(self, job_id: str):
        """Drop a job's in-flight record WITHOUT observing it — the
        SLO-dedupe path: a cycle that re-confirms an already-observed
        advance consumes nothing, and the stale record's stages must not
        leak into (and inflate) the job's NEXT genuine observation."""
        with self._lock:
            self._inflight.pop(job_id, None)

    def single_context(self, job_ids):
        """The one W3C context shared by every in-flight record among
        `job_ids` (None when there are zero, several, or mixed traces) —
        lets a partial cycle triggered by a single push adopt that
        push's trace for its whole engine.cycle span."""
        ctx = None
        with self._lock:
            for jid in job_ids:
                rec = self._inflight.get(jid)
                c = rec.get("ctx") if rec is not None else None
                if c is None:
                    continue
                if ctx is None:
                    ctx = c
                elif ctx.trace_id != c.trace_id:
                    return None
        return ctx

    # ------------------------------------------------------------- closing
    def observe(self, job_id: str, now: float, newest_ts: float,
                score_s: float, fold_s: float) -> dict:
        """Close a job's waterfall at verdict fold. Pushed jobs consume
        their in-flight record (push stages + measured waits, with a
        wall-clock fallback for the accept->cycle wait when no scheduler
        ran, e.g. bench partial cycles); polled jobs synthesize the
        poll-wait-only shape. Returns {"stages", "ctx", "trace_id",
        "streamed", "total_s"}."""
        with self._lock:
            rec = self._inflight.pop(job_id, None)
        stages: dict[str, float] = {}
        ctx = None
        streamed = rec is not None
        if rec is not None:
            ctx = rec.get("ctx")
            for stage in STAGE_ORDER:
                v = rec["stages"].get(stage)
                if v is not None:
                    stages[stage] = v
            if not rec.get("scheduled") and STAGE_SCHEDULE_WAIT not in \
                    stages and rec.get("accepted"):
                # no scheduler stamped the wait (direct run_cycle): the
                # accept->cycle gap in the same clock domain as `now`
                stages[STAGE_SCHEDULE_WAIT] = \
                    max(float(now) - rec["accepted"], 0.0)
        elif newest_ts > 0:
            stages[STAGE_SCHEDULE_WAIT] = max(float(now) - newest_ts, 0.0)
        stages[STAGE_SCORE] = max(float(score_s), 0.0)
        stages[STAGE_FOLD] = max(float(fold_s), 0.0)
        total = sum(stages.values())
        with self._lock:
            for stage, v in stages.items():
                self._observe_hist(stage, v)
            self._observe_hist("total", total)
            self.observed_total += 1
            if streamed:
                self.streamed_total += 1
            self.last = {
                "job_id": job_id,
                "streamed": streamed,
                "stages": {k: round(v, 6) for k, v in stages.items()},
                "total_s": round(total, 6),
                "trace_id": ctx.trace_id if ctx is not None else "",
            }
        if self.exporter is not None:
            for stage, v in stages.items():
                self.exporter.record_histogram(
                    "foremastbrain:detection_stage_seconds",
                    {"stage": stage}, v,
                    help="Detection-latency waterfall: seconds spent per "
                         "stage between a sample existing and its "
                         "verdict (docs/operations.md \"Following one "
                         "push to its verdict\").",
                    buckets=self._edges)
        return {
            "stages": stages,
            "ctx": ctx,
            "trace_id": ctx.trace_id if ctx is not None else "",
            "streamed": streamed,
            "total_s": total,
        }

    def _observe_hist(self, stage: str, v: float):
        h = self._hist.get(stage)
        if h is None:
            h = self._hist[stage] = [[0] * (len(self._edges) + 1), 0.0, 0]
        h[0][bisect.bisect_left(self._edges, v)] += 1
        h[1] += v
        h[2] += 1

    # ------------------------------------------------------------- reading
    def quantile(self, stage: str, q: float) -> float:
        """Bucket-resolution quantile of one stage's distribution (the
        same floor-honest estimate DetectionSLO.quantile makes)."""
        with self._lock:
            h = self._hist.get(stage)
            counts = list(h[0]) if h is not None else None
        if not counts or sum(counts) == 0:
            return 0.0
        rank = q * sum(counts)
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= rank:
                return float(self._edges[min(i, len(self._edges) - 1)])
        return float(self._edges[-1])

    def snapshot(self) -> dict:
        """/status section: per-stage distribution summary + the last
        closed waterfall (ordered; absent stages omitted)."""
        with self._lock:
            rows = {s: (list(h[0]), h[1], h[2])
                    for s, h in self._hist.items()}
            out = {
                "observed": self.observed_total,
                "streamed": self.streamed_total,
                "inflight": len(self._inflight),
                "last": dict(self.last),
            }
        stages = {}
        for stage in (*STAGE_ORDER, "total"):
            row = rows.get(stage)
            if row is None:
                continue
            _counts, total, n = row
            stages[stage] = {
                "count": n,
                "mean_s": round(total / n, 6) if n else 0.0,
                "p50_s": round(self.quantile(stage, 0.5), 4),
                "p99_s": round(self.quantile(stage, 0.99), 4),
            }
        out["stages"] = stages
        return out

    def reset(self):
        """Clear distributions AND the in-flight book (bench warm-up
        isolation, mirroring DetectionSLO.reset)."""
        with self._lock:
            self._hist.clear()
            self._inflight.clear()
            self.observed_total = 0
            self.streamed_total = 0
            self.last = {}
