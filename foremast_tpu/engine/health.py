"""Health state machine: the brain's own degraded-mode self-assessment.

The reference brain had no notion of its own health — a slow Prometheus
or a hung worker looked identical to "no anomalies" from the outside, and
the operator happily rolled deployments back on verdicts computed from
stale or shed data. This module condenses the degraded-mode layer's
signals (load shedding, stale-verdict serving, quarantine, the collect
watchdog, breaker states, cycle liveness) into ONE ordered state:

  OK          every verdict this cycle came from fresh data, on time.
  DEGRADED    verdicts are flowing but some are second-class: a breaker
              is open/half-open, stale verdicts were served, the collect
              watchdog fired, or jobs sit in poison quarantine. Consumers
              that ACT on verdicts (operator remediation) must hold off —
              rolling back a deployment on stale data is worse than
              waiting a cycle.
  OVERLOADED  the cycle deadline budget forced load shedding: the brain
              cannot score the whole fleet inside its cadence. Verdicts
              that were produced are trustworthy; coverage is not.
  STALLED     no cycle has completed inside the liveness window — the
              worker is wedged (hung device, livelocked fetch). /readyz
              fails so traffic (and peers' adoption scans) route around.

Severity is ordered OK < DEGRADED < OVERLOADED < STALLED; the machine
reports the worst condition currently true, so DEGRADED→OK recovery is
automatic one clean cycle after the underlying fault clears — there is no
latched state to reset.

Exposed as `/readyz` (readiness — distinct from `/healthz` liveness,
which only answers "is the process up"), in the `/status` health section,
and as the `foremastbrain:health_state` gauge (0 ok / 1 degraded /
2 overloaded / 3 stalled) on `/metrics`.
"""
from __future__ import annotations

import time

from ..utils.locks import make_lock

__all__ = ["HealthMonitor", "STATE_OK", "STATE_DEGRADED", "STATE_OVERLOADED",
           "STATE_STALLED", "HEALTH_STATE_VALUES"]

STATE_OK = "ok"
STATE_DEGRADED = "degraded"
STATE_OVERLOADED = "overloaded"
STATE_STALLED = "stalled"

# numeric encoding for the foremastbrain:health_state gauge
HEALTH_STATE_VALUES = {
    STATE_OK: 0, STATE_DEGRADED: 1, STATE_OVERLOADED: 2, STATE_STALLED: 3,
}


class HealthMonitor:
    """Per-cycle degraded-mode signal accumulator + state computation.

    The engine stamps `begin_cycle()`/`end_cycle(...)` around every cycle;
    readers (`/readyz`, `/status`, the operator's suppression probe) call
    `state()` at any time. Thread-safe: the engine worker writes, HTTP
    threads read.

    `breakers_fn` is wired by the runtime to the live breaker boards
    (data source + archive); standalone analyzers (tests, prewarm) leave
    it None and the breaker signal simply reads empty.
    """

    def __init__(self, exporter=None, cycle_seconds: float = 10.0,
                 stall_grace_seconds: float = 30.0,
                 clock=time.monotonic, recorder=None):
        self._lock = make_lock("engine.health")
        self.exporter = exporter
        self.cycle_seconds = float(cycle_seconds)
        # liveness window floor: tiny test cadences must not flag a
        # perfectly healthy engine STALLED between two instant cycles
        self.stall_grace_seconds = float(stall_grace_seconds)
        self._clock = clock
        self.breakers_fn = None  # () -> {key: "closed"|"half-open"|"open"}
        # sharded-brain tap (engine/sharding.py ShardManager.health_summary):
        # () -> {replica, replicas, owned, adopting, draining}. Folded into
        # the state() detail so /readyz and /status answer "which slice of
        # the fleet is this replica responsible for, and is it mid-
        # rebalance" — informational, never a state driver (a rebalance is
        # normal operation, not degradation).
        self.shards_fn = None
        # detection-latency SLO tap (engine/slo.py DetectionSLO
        # burn_summary): () -> {class: error-budget burn}. Folded into
        # the state() detail like shards_fn — informational, never a
        # state driver (latency is an SLO conversation, not readiness;
        # readiness failing on a burnt budget would route traffic away
        # from a brain that is merely slow, making it slower).
        self.slo_fn = None
        # flight recorder (engine/flightrec.py): hears state transitions
        # and breaker flips; transitions into OVERLOADED/STALLED auto-dump
        self.recorder = recorder
        self._last_seen_state: str | None = None
        self._last_open_breakers: tuple = ()
        self._started_at: float | None = None
        self._last_cycle_end: float | None = None
        # last COMPLETED cycle's degraded-mode signals
        self.last_cycle: dict = {
            "shed": 0, "stale_served": 0, "watchdog_fires": 0,
            "quarantined": 0, "deadline_overrun": False,
        }

    # ------------------------------------------------------------ wiring
    def configure(self, cycle_seconds: float | None = None,
                  breakers_fn=None, shards_fn=None, slo_fn=None):
        with self._lock:
            if cycle_seconds is not None:
                self.cycle_seconds = float(cycle_seconds)
            if breakers_fn is not None:
                self.breakers_fn = breakers_fn
            if shards_fn is not None:
                self.shards_fn = shards_fn
            if slo_fn is not None:
                self.slo_fn = slo_fn

    # --------------------------------------------------------- engine side
    def begin_cycle(self):
        with self._lock:
            if self._started_at is None:
                self._started_at = self._clock()

    def end_cycle(self, *, shed: int = 0, stale_served: int = 0,
                  watchdog_fires: int = 0, quarantined: int = 0,
                  deadline_overrun: bool = False):
        """Stamp one COMPLETED cycle. The engine calls this only when the
        cycle returned — a raising cycle leaves the liveness reference
        untouched, so both a hung cycle and a crash-looping worker age
        into STALLED (the worker loop swallows exceptions and retries,
        which would otherwise look exactly like health)."""
        with self._lock:
            self._last_cycle_end = self._clock()
            self.last_cycle = {
                "shed": int(shed),
                "stale_served": int(stale_served),
                "watchdog_fires": int(watchdog_fires),
                "quarantined": int(quarantined),
                "deadline_overrun": bool(deadline_overrun),
            }
        self._export()

    # --------------------------------------------------------- reader side
    # first-cycle warmup allowance: before ANY cycle has completed, the
    # stall window stretches (10x, min 10 minutes) — a cold pod's first
    # cycle legitimately pays the full compile storm + LSTM warm training
    # (minutes on CPU without a compile cache), and flagging that STALLED
    # would make the /readyz readinessProbe pull a healthy warming pod.
    # A genuinely wedged-from-birth worker still trips it, just later.
    FIRST_CYCLE_GRACE_FACTOR = 10.0
    FIRST_CYCLE_GRACE_MIN_S = 600.0

    def _stall_after(self, warming: bool) -> float:
        """Liveness window: a cycle (plus its deadline slack) must complete
        inside 3 cadences, floored by the grace so sub-second test cadences
        don't flap; stretched while the first cycle is still warming up."""
        base = max(3.0 * self.cycle_seconds, self.stall_grace_seconds)
        if warming:
            return max(self.FIRST_CYCLE_GRACE_FACTOR * base,
                       self.FIRST_CYCLE_GRACE_MIN_S)
        return base

    def state(self, now: float | None = None) -> tuple[str, dict]:
        """(state, detail). Worst-condition-wins; detail names every
        contributing signal so the runbook's "which knob moves it"
        question is answerable from the payload alone."""
        now = self._clock() if now is None else now
        with self._lock:
            last = dict(self.last_cycle)
            started = self._started_at
            last_end = self._last_cycle_end
            breakers_fn = self.breakers_fn
            shards_fn = self.shards_fn
            slo_fn = self.slo_fn
        open_breakers = []
        if breakers_fn is not None:
            try:
                open_breakers = sorted(
                    k for k, s in breakers_fn().items() if s != "closed")
            except Exception:  # noqa: BLE001 - a probe must never raise
                open_breakers = []
        detail = dict(last)
        detail["open_breakers"] = open_breakers
        if shards_fn is not None:
            try:
                detail["shards"] = shards_fn()
            except Exception:  # noqa: BLE001 - a probe must never raise
                pass
        if slo_fn is not None:
            try:
                burns = slo_fn()
                if burns:  # empty before the first verdict: no key churn
                    detail["slo_burn"] = burns
            except Exception:  # noqa: BLE001 - a probe must never raise
                pass
        # STALLED: the engine has started cycling but nothing COMPLETED
        # inside the liveness window. The reference is the last completed
        # cycle (first begin before any completes), so it covers every
        # wedge shape the same way: hung mid-cycle, crash-looping (raises
        # each cadence — those never stamp end_cycle), or a dead worker.
        stall_after = self._stall_after(warming=last_end is None)
        reference = last_end if last_end is not None else started
        if reference is not None and now - reference > stall_after:
            detail["seconds_since_cycle"] = round(now - reference, 3)
            return self._observe(STATE_STALLED, detail)
        # OVERLOADED means coverage was actually cut (jobs shed). A cycle
        # that merely OVERRAN the budget without shedding (scoring ran
        # long after every fetch landed) produced full, fresh coverage —
        # that is a capacity warning (`deadline_overrun` in the detail),
        # not a reason to fail readiness or hold remediation.
        if last["shed"] > 0:
            return self._observe(STATE_OVERLOADED, detail)
        if (open_breakers or last["stale_served"] > 0
                or last["watchdog_fires"] > 0 or last["quarantined"] > 0):
            return self._observe(STATE_DEGRADED, detail)
        return self._observe(STATE_OK, detail)

    def _observe(self, state: str, detail: dict) -> tuple[str, dict]:
        """Edge-detect state transitions and breaker flips for the flight
        recorder. Detection happens wherever the state is COMPUTED — the
        STALLED transition has no end_cycle() to hook, it is only ever
        seen by a reader (/readyz probe, /metrics scrape, the operator's
        suppression poll). Events are recorded UNDER the lock so the ring
        order always matches the edge order (two readers winning
        successive edges — incident then recovery — must not land
        inverted in the ring); only the auto-DUMP (file I/O, re-reads
        tracer/provenance state) runs outside."""
        if self.recorder is None:
            return state, detail
        fire_transition = None
        with self._lock:
            if self._last_seen_state != state:
                prev = self._last_seen_state
                self._last_seen_state = state
                # the engine is born OK: a first observation that is
                # already degraded/overloaded/stalled IS a transition
                # (the incident predates the first probe)
                if prev is not None or state != STATE_OK:
                    fire_transition = (prev or STATE_OK, state)
            breakers = tuple(detail.get("open_breakers") or ())
            flips = None
            if breakers != self._last_open_breakers:
                flips = (self._last_open_breakers, breakers)
                self._last_open_breakers = breakers
            try:
                if flips is not None:
                    from .flightrec import EVENT_BREAKER

                    self.recorder.record_event(
                        EVENT_BREAKER, was=list(flips[0]),
                        now=list(flips[1]))
                if fire_transition is not None:
                    self.recorder.record_transition(
                        fire_transition[0], fire_transition[1], detail)
            except Exception:  # noqa: BLE001 - diagnostics never break a probe
                pass
        if fire_transition is not None:
            try:
                self.recorder.maybe_auto_dump(state, detail)
            except Exception:  # noqa: BLE001 - diagnostics never break a probe
                pass
        return state, detail

    # ------------------------------------------------------------- export
    def _export(self):
        if self.exporter is None:
            return
        state, _ = self.state()
        self.exporter.record_gauge(
            "foremastbrain:health_state", {},
            HEALTH_STATE_VALUES[state],
            help="degraded-mode health state: 0 ok, 1 degraded, "
                 "2 overloaded, 3 stalled")

    def refresh_metrics(self):
        """Re-stamp the health gauge at scrape time (the STALLED
        transition has no end_cycle() to fire it — a wedged worker is
        exactly the case where nothing else would export)."""
        self._export()
