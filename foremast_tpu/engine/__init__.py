"""Job state machine, config, batched analyzer, workers."""
from . import jobs  # noqa: F401
from .analyzer import Analyzer  # noqa: F401
from .config import EngineConfig, MetricPolicy, from_env  # noqa: F401
from .jobs import Document, HpaLog, JobStore, MetricQueries, to_external  # noqa: F401
from .scheduler import EngineWorker, StreamScheduler  # noqa: F401
