"""Tier-0 triage gate: clear the boring rows before family scoring.

PR 3's fingerprint memo skips rows whose bytes didn't move; in a live
steady fleet most rows DO move every cycle (one new sample) yet remain
unremarkable, and each still paid a full per-family device launch. The
gate composes directly after `CyclePipeline._memo_check`: memo skips
unchanged rows, this tier skips changed-but-unremarkable ones. Rows are
batched into the fused `ops.triage.screen_rows` program (one launch
shared by every screened family per T bucket, an order of magnitude
coarser than the family fire rungs because the screen is one cheap
pass), classified host-side as CLEAR or SUSPECT, and:

  * CLEAR rows short-circuit to a healthy result through the existing
    verdict machinery — the synthesized result dict is exactly what the
    family's collect would produce for a zero-violation row (count 0,
    first_ts -1, the screen's band means for the exported bounds), so
    folding, stale-state refresh, memoization and `/metrics` all behave
    identically; provenance tags the job `triaged` with the screen
    statistics vs thresholds.
  * SUSPECT rows flow unchanged into the family rung accumulators and
    are scored by the full path — escalation can never change a verdict,
    only cost a launch.

Verdict safety is by construction, not just by test:

  * the CLEAR rule for the band family requires the violation count of
    the policy band SHRUNK by `TRIAGE_MARGIN` sigmas to stay under the
    family's verdict gate, computed with the band scorer's own
    smoother/sigma math (see ops/triage.py for the one-sided dominance
    argument: shrunk count >= real count, so a sub-gate shrunk count
    implies the full scorer's count is sub-gate — healthy) — and the
    band family is screened ONLY under `moving_average*` algorithms,
    where that replica argument holds. Seasonal/HW/SES bands always
    escalate.
  * canary-class jobs (anything not continuous/hpa) always escalate:
    their verdict gates a live rollout.
  * the hpa family always escalates — its per-cycle score and hpalog
    emission ARE the verdict; there is nothing sound to short-circuit.
  * pair and bivariate rows always escalate by default: rank-test
    p-values (pair) and ellipse correlation breaks (bivariate) are not
    bounded by any cheap marginal statistic, so the screen is not
    provably one-sided there. Opting them in via `TRIAGE_FAMILIES`
    TRADES VERDICT FIDELITY FOR LAUNCHES: a sustained sub-band
    distribution shift (e.g. a uniform ~1.5-sigma level drift stays
    inside the band and under TRIAGE_Z, yet a rank test over a full
    window condemns it) will be cleared that the full pair scorer would
    convict. Only for fleets where band-style violations are the signal
    of record — documented in docs/performance.md; hpa opt-in is
    ignored.

The CLEAR/SUSPECT thresholds (`TRIAGE_Z`, `TRIAGE_MARGIN`,
`TRIAGE_MIN_POINTS`) are applied host-side from the kernel's outputs, so
threshold sweeps — including the verdict-safety sweep test — compile
nothing new.
"""
from __future__ import annotations

import time

import numpy as np

from ..dataplane.promql import CONTINUOUS_STRATEGIES
from ..ops import triage as triage_ops
from ..ops.windowing import bucket_length
from .analyzer import _concat_trimmed

__all__ = ["TriageGate", "screen_cap", "SCREENABLE_FAMILIES"]

# families the generic screen can represent as packed rows at all; hpa is
# deliberately absent (see module docstring), lstm never enters the
# accumulators in the first place
SCREENABLE_FAMILIES = ("pair", "band", "bivariate")

# memory budget for one screen launch, in row-steps: the row cap scales
# down for long T buckets so a 16k-row screen of 1k-step windows and a
# 1k-row screen of 16k-step windows cost the same peak bytes
_SCREEN_BUDGET_STEPS = 1024


def screen_cap(fire_rows: int, T: int) -> int:
    """Max rows per screen launch for a T bucket (memory-aware)."""
    fire_rows = max(int(fire_rows), 16)
    budget = fire_rows * _SCREEN_BUDGET_STEPS
    return int(min(fire_rows, max(budget // max(int(T), 1), 1024)))


class TriageGate:
    """One cycle's screen state. Single-threaded like CyclePipeline: fed
    from the ordered preprocess stream, so routing stays deterministic."""

    def __init__(self, analyzer):
        cfg = analyzer.config
        self.an = analyzer
        fams = set(cfg.triage_families) & set(SCREENABLE_FAMILIES)
        if not cfg.algorithm.startswith("moving_average"):
            # the one-sided replica argument only covers the MA band;
            # other forecasters' bands always take the full path
            fams.discard("band")
        self.families = frozenset(fams)
        self.z = float(cfg.triage_z)
        self.margin = float(cfg.triage_margin)
        self.min_points = int(cfg.triage_min_points)
        self.fire_rows = max(int(cfg.triage_fire_rows), 16)
        self.acc: dict[int, list] = {}        # screen T bucket -> [unit]
        self._rows_in: dict[int, int] = {}    # screen T bucket -> row count
        self.results: dict[str, dict] = {f: {} for f in SCREENABLE_FAMILIES}
        self.stats: dict = {}                 # result key -> screen stats
        self.job_hits: dict[str, int] = {}    # job -> cleared results
        self.screened: dict[str, int] = {}    # per-family row counts
        self.cleared: dict[str, int] = {}
        self.escalated: dict[str, int] = {}
        self.launches = 0
        self.seconds = 0.0

    @property
    def active(self) -> bool:
        return bool(self.families)

    def accepts(self, family: str, strategy: str) -> bool:
        """Does this (family, job-class) row enter the screen at all?"""
        return family in self.families and strategy in CONTINUOUS_STRATEGIES

    # --------------------------------------------------------------- feeding
    def add(self, family: str, fam_T: int, entry, pipe) -> None:
        """Route one accumulator entry into the screen; fire full buckets.

        Called inside `CyclePipeline.feed`'s per-item guard: a malformed
        entry raises out to the pipeline's per-job retry list, same blast
        radius as every scoring step."""
        unit = self._unit(family, fam_T, entry)
        T = unit["T"]
        self.acc.setdefault(T, []).append(unit)
        self._rows_in[T] = self._rows_in.get(T, 0) + len(unit["rows"])
        # counters are in ROWS (a bivariate unit is 2 channel rows) so the
        # exported "rows screened/cleared/escalated" totals stay honest
        self.screened[family] = (self.screened.get(family, 0)
                                 + len(unit["rows"]))
        if self._rows_in[T] >= screen_cap(self.fire_rows, T):
            units = self.acc[T]
            self.acc[T] = []
            self._rows_in[T] = 0
            self._fire(T, units, pipe)

    def flush(self, pipe) -> None:
        """Screen every remaining partial bucket (pipeline stream end)."""
        buckets, self.acc = self.acc, {}
        self._rows_in = {}
        for T, units in buckets.items():
            if units:
                self._fire(T, units, pipe)

    def _unit(self, family: str, fam_T: int, entry) -> dict:
        """One logical screen unit: 1 row (pair/band) or 2 channel rows
        (bivariate), in the exact packed layout the family scorer uses.
        `rows` entries are (values, mask, n_h, policy)."""
        if family == "band":
            it = entry
            vals, mask, n_h = _concat_trimmed(it.historical, it.current)
            rows = [(vals, mask, n_h, it.policy)]
            key = (it.job_id, it.metric, "band")
            T = fam_T  # _band_T buckets the same concat length
        elif family == "pair":
            it = entry
            vals, mask, n_h = _concat_trimmed(it.baseline, it.current)
            rows = [(vals, mask, n_h, it.policy)]
            key = (it.job_id, it.metric, "pair")
            T = bucket_length(vals.shape[0])
        else:  # bivariate: entry is (item, joint-grid prep)
            it, (x, m, n_h, _n_c) = entry
            rows = [(x[0], m[0], n_h, it.policies[0]),
                    (x[1], m[1], n_h, it.policies[1])]
            key = (it.job_id, "&".join(it.metrics), "bivariate")
            T = bucket_length(x.shape[1])
        return {"family": family, "fam_T": fam_T, "entry": entry,
                "key": key, "T": T, "rows": rows}

    # --------------------------------------------------------------- firing
    def _fire(self, T: int, units: list, pipe) -> None:
        t0 = time.perf_counter()
        rows = [(u, r) for u in units for r in u["rows"]]
        try:
            outs = self._screen(T, rows)
        except Exception:  # noqa: BLE001 - screen failure must never fail a
            # cycle: a wedged/hung screen (WatchdogTimeout included) or a
            # packing surprise escalates the whole bucket to the full
            # path, which carries its own watchdog + per-job isolation
            outs = None
        suspects: list = []
        if outs is None:
            suspects = units
        else:
            i = 0
            for u in units:
                u_outs = outs[i:i + len(u["rows"])]
                i += len(u["rows"])
                if all(self._row_clear(u["family"], o) for o in u_outs):
                    self._clear(u, u_outs)
                else:
                    suspects.append(u)
        # the triage clock stops BEFORE suspects route into the family
        # accumulators: pipe._add can fire full family rungs, and that
        # dispatch time belongs to the pipeline's dispatch stage — booking
        # it here would double-count it into foremastbrain:triage_seconds
        self.seconds += time.perf_counter() - t0
        for u in suspects:
            self._escalate(u, pipe)

    def _screen(self, T: int, rows: list) -> list[dict]:
        """Pack + launch the fused kernel (rung-chunked), materialize
        under the analyzer's watchdog, return per-row output dicts."""
        cap = screen_cap(self.fire_rows, T)
        window = self.an.config.ma_window
        out_rows: list[dict] = []
        for i in range(0, len(rows), cap):
            chunk = rows[i:i + cap]
            n = len(chunk)
            R = self._rung(n, cap)
            xv = np.zeros((R, T), np.float32)
            xm = np.zeros((R, T), bool)
            reg = np.zeros((R, T), bool)
            thr = np.zeros(R, np.float32)
            bnd = np.ones(R, np.int32)
            mlb = np.zeros(R, np.float32)
            for j, (_, (vals, mask, n_h, pol)) in enumerate(chunk):
                L = vals.shape[0]
                xv[j, :L] = vals
                xm[j, :L] = mask
                reg[j, n_h:L] = True
                thr[j] = pol.threshold
                bnd[j] = pol.bound
                mlb[j] = pol.min_lower_bound
            mg = np.full(R, self.margin, np.float32)
            self.an.device_launches += 1
            self.launches += 1
            st = triage_ops.screen_rows(xv, xm, reg, thr, bnd, mlb, mg,
                                        window)
            # materialize straight to Python lists, real rows only: the
            # per-row classification below touches every field of every
            # row, and 10k+ boxed numpy scalar reads per cycle cost more
            # host time than the screen saves in launches
            out = self.an._watchdog_call(
                lambda s=st, m=n: {k: np.asarray(v)[:m].tolist()
                                   for k, v in s.items()})
            out_rows += [{k: out[k][j] for k in out} for j in range(n)]
        return out_rows

    def _rung(self, n: int, cap: int) -> int:
        """Smallest screen batch rung >= n (the family chunker's ladder
        walk, capped at the screen's own memory-aware cap)."""
        return type(self.an)._rung_for(n, cap)

    # ------------------------------------------------------- classification
    def _row_clear(self, family: str, o: dict) -> bool:
        """CLEAR iff the full path provably returns healthy for this row.

        The load-bearing check is `shrunk_count` vs the family's verdict
        gate: shrunk_count counts violations of the band NARROWED by
        `margin` sigmas, a superset of the real band's violations AND of
        any float-drift flips (a point the scorer's program could count
        differently sits within ulps of the real boundary, i.e. well
        outside the shrunk band), so shrunk_count below the gate implies
        the scorer's count is below the gate — healthy. Comparing against
        the gate rather than zero is what lets tight-threshold policies
        (a 2-sigma error band over ordinary noise always has a few
        outliers, which the scorer's gate exists to tolerate) still
        clear. The robust-z guard is escalation-only on top."""
        if int(o["n_hist"]) < self.min_points:
            return False  # too thin a floor: let the full path decide
        shrunk = int(o["shrunk_count"])
        checked = int(o["checked"])
        if family == "pair":
            # the pair kernel's internal band condemns at a fixed 0.3
            # violation fraction (parallel/fleet.py _pair_verdict)
            if shrunk > 0.3 * max(checked, 1):
                return False
        else:
            # band/bivariate gate: count >= max(band_min_points,
            # band_violation_fraction * checked) is unhealthy. A
            # non-positive gate (operator forced band_min_points to 0 on
            # an empty region) can never clear: 0 < 0 is false.
            if not shrunk < self.an._gate(checked):
                return False
        if float(o["robust_z"]) >= self.z:
            # defense-in-depth guard: suspicious, escalate. >= (not >) so
            # TRIAGE_Z=0 really does screen nothing — a constant series'
            # robust_z is exactly 0.0 and must escalate at z=0 too
            return False
        return True

    def _escalate(self, u: dict, pipe) -> None:
        self.escalated[u["family"]] = (self.escalated.get(u["family"], 0)
                                       + len(u["rows"]))
        pipe._add(u["family"], u["fam_T"], u["entry"])

    def _clear(self, u: dict, outs: list[dict]) -> None:
        family, key = u["family"], u["key"]
        o = outs[0]
        # synthesized healthy results: verdict-bearing fields (unhealthy,
        # count vs gate, exported bounds) match the full path; sub-gate
        # cosmetics the healthy fold never reads (first_ts/anomaly_pairs
        # of tolerated outliers, pair p-values) are zeroed
        if family == "pair":
            res = {"unhealthy": False, "min_p": 1.0,
                   "pairwise_unhealthy": False, "band_unhealthy": False,
                   "band_count": int(o["count"])}
        elif family == "band":
            res = {"count": int(o["count"]), "unhealthy": False,
                   "first_ts": -1.0,
                   "upper": float(o["upper_mean"]),
                   "lower": float(o["lower_mean"]),
                   "anomaly_pairs": []}
        else:
            it = u["entry"][0]
            res = {"count": 0, "unhealthy": False, "first_ts": -1.0,
                   "anomaly_pairs": [],
                   "bounds": {
                       it.metrics[0]: (float(outs[0]["upper_mean"]),
                                       float(outs[0]["lower_mean"])),
                       it.metrics[1]: (float(outs[1]["upper_mean"]),
                                       float(outs[1]["lower_mean"])),
                   }}
        self.results[family][key] = res
        self.stats[key] = {
            "triaged": True,
            "robust_z": round(max(float(x["robust_z"]) for x in outs), 4),
            "resid_z": round(max(float(x["resid_z"]) for x in outs), 4),
            "z_threshold": self.z,
            "margin": self.margin,
            "checked": sum(int(x["checked"]) for x in outs),
        }
        job_id = key[0]
        self.job_hits[job_id] = self.job_hits.get(job_id, 0) + 1
        self.cleared[family] = self.cleared.get(family, 0) + len(u["rows"])
