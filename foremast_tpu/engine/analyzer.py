"""The analysis engine: jobs -> device batches -> verdicts.

This collapses the reference's L3 brain worker loop (poll ES -> fetch
Prometheus -> scipy per job -> write verdict, SURVEY.md §2.4/§3.1) into a
batched cycle: every runnable job's windows are fetched, packed into dense
(B, T) buckets, and scored by ONE jitted program per bucket — pairwise tests
and forecast-band checks fused (parallel.fleet), HPA scores batched
(ops.hpa). Verdict semantics preserved:

  * two judgment modes (foremast-brain/README.md:7-10): pairwise
    baseline-vs-current, and historical-model band anomaly detection.
  * fail-fast: completed_unhealth the moment an anomaly is seen; otherwise
    keep re-checking until endTime (docs/guides/design.md:43) — implemented
    by re-queuing unfinished healthy jobs each cycle.
  * insufficient data by endTime -> completed_unknown.
  * continuous jobs re-materialize START_TIME/END_TIME windows per cycle
    (foremast-service/cmd/manager/main.go:59-63); hpa jobs additionally emit
    hpalogs + the foremastbrain:..hpa_score series every cycle.
"""
from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field

import numpy as np

from ..utils.locks import make_lock
from ..dataplane.exporter import VerdictExporter
from ..dataplane.fetch import FetchError, grid_from_series
from ..dataplane.promql import (
    CONTINUOUS_STRATEGIES,
    STRATEGY_HPA,
    materialize_placeholders,
)
from ..models import lstm_ae
from ..ops import bivariate as bv
from ..ops import forecast as fc
from ..ops import seqscan as sq
from ..ops import hpa as hpa_ops
from ..ops.windowing import (
    MAX_WINDOW_STEPS,
    Window,
    bucket_length,
    pack_windows,
)
from ..parallel import fleet as fl
from ..resilience.policy import Deadline
from ..utils import tracing
from ..utils.timeutils import from_rfc3339
from . import jobs as J
from . import flightrec
from . import provenance as prov
from . import slo as slo_mod
from .config import EngineConfig, MetricPolicy
from .health import HealthMonitor


class WatchdogTimeout(Exception):
    """A device materialization (or its per-job retry) overran WATCHDOG_S.

    Raised by Analyzer._watchdog_call; the pipeline's collect phase treats
    it like any collect failure — the bucket fails over to the sync
    per-job path — so one hung launch costs one bucket's timeout, not the
    whole cycle."""


# shed marker carried through the preprocess stream in the `failed` slot:
# distinguishable from every real FetchError string (which the analyzer
# stamps into job reasons) by identity, never shown to users directly
_SHED = "__cycle_deadline_shed__"

# poison-job quarantine re-admission backoff: first parking sits out
# QUARANTINE_BASE_S, doubling per subsequent parking up to the cap. Not
# env knobs — QUARANTINE_AFTER is the operator-facing control; the
# backoff shape only needs to be sane (docs/resilience.md).
QUARANTINE_BASE_S = 30.0
QUARANTINE_MAX_S = 3600.0


@dataclass
class _PairItem:
    job_id: str
    metric: str
    baseline: Window
    current: Window
    policy: MetricPolicy


@dataclass
class _BandItem:
    job_id: str
    metric: str
    historical: Window
    current: Window
    policy: MetricPolicy


@dataclass
class _BiItem:
    """Two-metric joint job (ML_ALGORITHM=bivariate_normal; design.md:53-88)."""

    job_id: str
    metrics: tuple  # (name1, name2)
    hist: tuple  # (Window, Window)
    cur: tuple  # (Window, Window)
    policies: tuple  # (MetricPolicy, MetricPolicy)


@dataclass
class _MultiItem:
    """3+-metric LSTM-autoencoder job (faq.md:8-10)."""

    job_id: str
    cache_key: str  # app/namespace identity for the model cache
    metrics: list
    hist: list  # [Window]
    cur: list  # [Window]


@dataclass
class _HpaItem:
    job_id: str
    metric: str
    historical: Window
    current: Window
    is_increase: bool = True
    priority: int = 0
    # wire isAbsolute (models.go:179-183): static SLA limit is a value on
    # the metric's own scale vs a multiple of the healthy historical mean
    is_absolute: bool = False
    # ready-pod-count Window from the job's podCountURL, stamped on every
    # item of the job by _preprocess; None = no pod data (neutral 1/1).
    # Split into (pods_now, pods_hist) at score time against the job's own
    # current-window boundary (_pod_count_stats).
    pod_window: object = None


def _fp(*parts) -> bytes:
    """Order-sensitive fingerprint of scorer inputs (SCORE_MEMO).

    Windows hash their full identity (start, step, length, values, mask);
    ndarrays their bytes; everything else its repr. blake2b-128 — the memo
    only ever compares fingerprints of the SAME key, so 128 bits is far
    past accidental-collision territory, and hashing is ~100x cheaper than
    the device launch it elides."""
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        if p is None:
            h.update(b"\xffN")
        elif isinstance(p, Window):
            h.update(np.float64(
                (p.start, p.step, p.values.shape[0])).tobytes())
            h.update(p.values.tobytes())
            h.update(p.mask.tobytes())
        elif isinstance(p, np.ndarray):
            h.update(np.int64(p.shape).tobytes())
            h.update(p.tobytes())
        else:
            h.update(repr(p).encode())
        h.update(b"|")
    return h.digest()


def _concat_trimmed(hist: Window, cur: Window):
    """(values, mask, n_h) of hist+current, hist left-trimmed so the concat
    fits the largest compiled bucket (static-shape ceiling)."""
    n_c = cur.values.shape[0]
    max_h = max(MAX_WINDOW_STEPS - n_c, 0)
    h_vals = hist.values[-max_h:] if max_h else hist.values[:0]
    h_mask = hist.mask[-max_h:] if max_h else hist.mask[:0]
    vals = np.concatenate([h_vals, cur.values[: MAX_WINDOW_STEPS]])
    mask = np.concatenate([h_mask, cur.mask[: MAX_WINDOW_STEPS]])
    return vals, mask, h_vals.shape[0]


def _joint_grid(hists: list, curs: list):
    """Stack a job's metrics onto one shared concat grid.

    Metrics of one job are fetched with identical start/end/step parameters,
    so their grids line up; residual off-by-a-few length skew (scrape lag)
    is resolved by trimming every series to the common length. Current
    windows are HEAD-trimmed so concat index n_h + j maps to each current
    window's own index j — the invariant the anomaly-timestamp math
    (cur.start + (idx - n_h) * step) depends on. History keeps its tail
    (most recent points). Returns (values (F, T), masks (F, T), n_h, n_c).
    """
    n_c = min(c.values.shape[0] for c in curs)
    n_c = min(n_c, MAX_WINDOW_STEPS)
    n_h = min(h.values.shape[0] for h in hists)
    n_h = min(n_h, MAX_WINDOW_STEPS - n_c)
    vals, masks = [], []
    for h, c in zip(hists, curs):
        hv = h.values[-n_h:] if n_h else h.values[:0]
        hm = h.mask[-n_h:] if n_h else h.mask[:0]
        vals.append(np.concatenate([hv, c.values[:n_c]]))
        masks.append(np.concatenate([hm, c.mask[:n_c]]))
    return np.stack(vals), np.stack(masks), n_h, n_c


def _concat_ts(cur: Window, n_h: int, j: int) -> float:
    """Translate a concat-grid index onto the CURRENT window's own time grid.

    Anomalies lie in the current region; the historical grid ends days
    earlier, so extrapolating it would stamp anomalies in the future. Valid
    because concat index n_h + k maps to current index k (history is
    tail-kept, current head-kept — _concat_trimmed/_joint_grid invariant).
    """
    return float(cur.start + (j - n_h) * cur.step)


def _pod_count_stats(win, split_ts: float):
    """(pods_now, pods_hist) from a ready-pod-count Window, or None.

    `split_ts` is the start of the job's CURRENT (scoring) window, so the
    recent/older split aligns exactly with the region the demand estimate
    covers and the history the capacity proxy averages — no second copy
    of the materialization-window constant. Single-sided data falls back
    to the other side so a short fetch still normalizes consistently
    rather than mixing a real pods_now with a fabricated pods_hist.
    """
    if win is None or win.n_valid == 0:
        return None
    t = win.start + np.arange(win.values.shape[0]) * win.step
    recent = win.mask & (t >= split_ts)
    older = win.mask & ~recent
    n_now = float(win.values[recent].mean()) if recent.any() else None
    n_hist = float(win.values[older].mean()) if older.any() else None
    if n_now is None and n_hist is None:
        return None
    n_now = n_hist if n_now is None else n_now
    n_hist = n_now if n_hist is None else n_hist
    return (max(n_now, 1e-6), max(n_hist, 1e-6))


@dataclass
class _JobState:
    doc: J.Document
    unhealthy: list = field(default_factory=list)  # (metric, detail, anomaly pairs)
    judged_any: bool = False
    failed: str = ""
    # per-job fetch accounting from the preprocess thread's trace notes
    # (delta vs full, points, seconds) — provenance's "fetch mode" block
    fetch: dict = field(default_factory=dict)
    # ingest marker (monotonic): set as the job's preprocess result
    # streams in — the job was freshly ingested this cycle (0 = shed
    # before fetch / quarantined: no latency observation).
    ingest_at: float = 0.0
    # window-advance stamp: the newest VALID sample timestamp across the
    # job's judged current windows (the data's own clock). Detection
    # latency = (cycle `now` − this, the poll/scrape wait) + the
    # measured in-cycle tail — see Analyzer._observe_latency.
    newest_ts: float = 0.0


class Analyzer:
    def __init__(self, config: EngineConfig, data_source, store: J.JobStore,
                 exporter: VerdictExporter | None = None,
                 breath: hpa_ops.BreathState | None = None):
        self.config = config
        self.source = data_source
        self.store = store
        self.exporter = exporter or VerdictExporter()
        if breath is None:
            # restart-safe cooldowns: hydrate armed breath timers from the
            # store snapshot (persisted at every cycle boundary below), so
            # a runtime bounce mid-cooldown still suppresses the flip
            # (dynamic_autoscaling.md:117-126)
            breath = hpa_ops.BreathState()
            breath.load(store.get_state("breath") or {})
        self.breath = breath
        # LSTM-AE model cache (MAX_CACHE_SIZE semantics,
        # foremast-brain/README.md:30): key -> (params, err_mu, err_sigma);
        # insertion-ordered dict doubles as the LRU eviction queue.
        self._lstm_cache: dict = {}
        self._lstm_models: dict = {}  # (F, hidden, latent) -> module instance
        # fleet-scoring support: every trained entry gets a version, and
        # stacked parameter pytrees are cached per (shape, members) — the
        # 256-way eager jnp.stack costs ~20x the fleet launch itself, so
        # it must happen only when membership/params change, not per cycle
        self._lstm_param_version = 0
        self._lstm_stack_cache: dict = {}
        self.lstm_stack_rebuilds = 0  # observability: stack-cache churn
        # per-CYCLE train-on-miss counter (reset in _run_cycle); lives on
        # the instance so the _isolate per-job retry path cannot reset it
        self._lstm_trained_this_cycle = 0
        # jobs left unjudged because the cycle's train budget was spent —
        # distinguishes "fleet warming up" (rising counter: budget too
        # small for the churn) from "jobs simply in progress" (zero);
        # cumulative like lstm_stack_rebuilds, also stamped per cycle on
        # the engine.score.lstm span. Tracked as a per-cycle ID SET, not a
        # counter: the _isolate per-job retry path re-invokes the scorer
        # within one cycle and a counter would double-count every skipped
        # job after a batch failure.
        self.lstm_budget_skips = 0
        self._lstm_budget_skipped_ids: set = set()
        # last cycle's stage/family timing decomposition (served on
        # /status; gauges on /metrics) — empty until the first cycle
        self.last_cycle_stages: dict = {}
        # -- fingerprint score memoization (SCORE_MEMO) --
        # (family, result_key) -> (fingerprint, result dict). Survives
        # across cycles on the analyzer; the per-cycle CyclePipeline
        # consults it so unchanged rows skip their device launch entirely.
        # LRU-bounded at 4x WINDOW_CACHE_MAX (~one entry per job window).
        self._score_memo: OrderedDict = OrderedDict()
        self.score_memo_hits: dict[str, int] = {}    # family -> cumulative
        self.score_memo_misses: dict[str, int] = {}
        # lstm memo tables: deterministic-training reuse (train-window
        # fingerprint -> trained entry; PRNGKey(0) + identical data =>
        # identical params, so reuse == retrain) and verdict reuse
        # ((job, metrics) -> (score-input fingerprint, z))
        self._lstm_train_memo: OrderedDict = OrderedDict()
        self._lstm_z_memo: OrderedDict = OrderedDict()
        self.lstm_train_memo_hits = 0
        self.lstm_rescore_skips = 0
        # total device-program launches (chunk launches across every
        # family, lstm scoring, training, and the tier-0 triage screen) —
        # the steady-state no-change gate asserts this stays flat over a
        # memo-hit cycle
        self.device_launches = 0
        # -- single-dispatch mega-batching (MEGABATCH) cumulative
        # counters: launches through the mega path, real rows carried and
        # padding rows added (the packing-efficiency signal satellite
        # benches track as padded/real waste ratio). Per-cycle deltas
        # land in last_cycle_stages["megabatch"].
        self.megabatch_launches_total = 0
        self.megabatch_real_rows_total = 0
        self.megabatch_pad_rows_total = 0
        # donated-kernel twins for the mega path: fn id -> jax.jit twin
        # with the big (B, T) input buffers donated, so a 100k-row mega
        # launch does not hold input AND output copies live at once.
        # Only populated on non-CPU backends (CPU XLA does not alias
        # donated buffers; donating there just warns per program).
        self._donated_twins: dict = {}
        # -- tier-0 triage (TRIAGE; engine/triage.py) cumulative counters:
        # rows screened / cleared / escalated per family, and fused
        # screen launches. Per-cycle deltas land in last_cycle_stages.
        self.triage_screened_total: dict[str, int] = {}
        self.triage_cleared_total: dict[str, int] = {}
        self.triage_escalated_total: dict[str, int] = {}
        self.triage_launches_total = 0
        # -- observability: provenance + flight recorder + trace ids --
        # per-(job, cycle) verdict attribution (engine/provenance.py):
        # which verdict path fired, per-family scores vs thresholds,
        # fetch mode — served at /jobs/<id>/explain. enabled=False (the
        # PROVENANCE=0 A/B leg) turns every call into a no-op.
        self.provenance = prov.ProvenanceRecorder(enabled=config.provenance)
        # incident flight recorder (engine/flightrec.py): bounded ring of
        # structured engine events, auto-dumped on the transition into
        # OVERLOADED/STALLED and on graceful shutdown
        self.flight = flightrec.FlightRecorder(
            dump_dir=config.flight_dump_dir,
            tracer=tracing.tracer, provenance=self.provenance,
            knobs_fn=self._dump_knobs)
        # cycle correlation id: worker-scoped monotonic sequence, bound
        # into the tracer (spans + log records) and stamped on provenance
        self._cycle_seq = 0
        self.current_cycle_id = ""
        # monotonic stamp of the current cycle's start: the in-cycle half
        # of each detection-latency observation (_observe_latency)
        self._cycle_mono0 = 0.0
        # jobs whose lstm verdict was served from the z-memo this cycle
        # (provenance memo-hit classification); reset per cycle
        self._lstm_memo_jobs: set = set()
        # -- degraded-mode operation state (docs/resilience.md) --
        # health state machine: the runtime wires cycle cadence + breaker
        # boards in; standalone analyzers still compute shed/stale/
        # watchdog-driven states. The flight recorder hears its
        # transitions (and dumps on OVERLOADED/STALLED).
        self.health = HealthMonitor(exporter=self.exporter,
                                    recorder=self.flight)
        self.flight.health_fn = self.health.state
        # detection-latency SLOs (engine/slo.py): ingest->verdict latency
        # per job class, with per-class targets and error-budget burn —
        # the latency baseline the streaming-dataplane roadmap item must
        # beat. Pure observation; burn rides the health detail
        # (informational) and /status, histograms ride /metrics.
        self.slo = slo_mod.DetectionSLO(
            exporter=self.exporter,
            targets={
                "canary": config.slo_canary_seconds,
                "continuous": config.slo_continuous_seconds,
                "hpa": config.slo_hpa_seconds,
            },
            objective=config.slo_objective)
        self.health.configure(slo_fn=self.slo.burn_summary)
        # detection-latency waterfall (engine/slo.py DetectionWaterfall):
        # the per-stage decomposition of each SLO observation. The ingest
        # receiver opens records at push accept (with the push's W3C
        # trace context + origin timestamp), the stream scheduler stamps
        # the debounce/schedule waits, and _observe_latency closes each
        # record at verdict fold — exporting
        # foremastbrain:detection_stage_seconds{stage=} histograms and
        # the verdict span that ends the push's distributed trace.
        self.waterfall = slo_mod.DetectionWaterfall(exporter=self.exporter)
        # monotonic stamp of the current cycle's fold start: splits the
        # in-cycle tail into the waterfall's score and fold stages
        self._cycle_fold_mono = 0.0
        # once-per-window-advance SLO dedupe: job_id -> newest judged
        # sample ts already observed (_observe_latency). Entries die with
        # the job (_prune_degraded_state).
        self._slo_seen: dict[str, float] = {}
        # load shedding (CYCLE_DEADLINE_S): cumulative shed count + the
        # consecutive-shed streak per open job (a shed job sorts ahead of
        # its priority class next cycle, so a permanently-blown budget
        # still round-robins the fleet instead of starving the tail)
        self.jobs_shed_total = 0
        self._shed_streak: dict[str, int] = {}
        # stale-verdict serving (MAX_STALE_S): job_id -> last cycle
        # timestamp at which the job was judged healthy on FRESH data.
        # Entries die with the job (terminal transitions pop them).
        self.stale_verdicts_served_total = 0
        self._stale_state: dict[str, float] = {}
        # poison-job quarantine (QUARANTINE_AFTER): job_id ->
        # [consecutive_failures, quarantined_until, times_quarantined]
        self.jobs_quarantined_total = 0
        self._quarantine: dict[str, list] = {}
        # hung-launch watchdog (WATCHDOG_S): fires counter + the live
        # count of abandoned sacrificial threads (each still parked on a
        # hung device call); bounded by _WATCHDOG_MAX_ABANDONED
        self.watchdog_fires_total = 0
        self._wd_lock = make_lock("engine.analyzer.watchdog")
        self._watchdog_abandoned = 0
        # sharded multi-replica brain (engine/sharding.py): the runtime
        # wires a ShardManager in; its ownership predicate then gates the
        # per-cycle claim so N replicas partition the fleet instead of
        # racing for it. None = single-replica (own everything), unchanged.
        self.shard = None

    def _memo_put(self, table: OrderedDict, key, val):
        """Insert-and-bound for the memo tables (LRU, shared ceiling)."""
        table[key] = val
        table.move_to_end(key)
        bound = max(4 * self.config.window_cache_max, 64)
        while len(table) > bound:
            table.popitem(last=False)

    def _memo_key_fp(self, family: str, entry, T: int):
        """(result_key, fingerprint) for one routed accumulator entry.

        The fingerprint covers everything the family's launch+collect
        reads from the entry: every window's full identity, the policy,
        and the T bucket (the band kernel gate is a function of T).
        Config is deliberately absent — it is frozen for the analyzer's
        lifetime, and the memo dies with the analyzer."""
        if family == "pair":
            it = entry
            return ((it.job_id, it.metric, "pair"),
                    _fp(b"pair", T, it.metric, it.baseline, it.current,
                        it.policy))
        if family == "band":
            it = entry
            return ((it.job_id, it.metric, "band"),
                    _fp(b"band", T, it.metric, it.historical, it.current,
                        it.policy))
        if family == "bivariate":
            it = entry[0]  # (item, joint-grid prep)
            return ((it.job_id, "&".join(it.metrics), "bivariate"),
                    _fp(b"bi", T, it.metrics, *it.hist, *it.cur,
                        *it.policies))
        job_id, t, s = entry  # hpa row
        return (job_id,
                _fp(b"hpa", T, t.metric, t.historical, t.current,
                    t.is_increase, t.priority, t.is_absolute, t.pod_window,
                    s.metric, s.historical, s.current, s.is_increase,
                    s.priority, s.is_absolute))

    def _dump_knobs(self) -> dict:
        """Knob values folded into flight-recorder dumps: the degraded-mode
        and observability controls an incident post-mortem needs."""
        cfg = self.config
        from ..utils import knobs as _knobs

        return {
            "engine": {
                "cycle_deadline_seconds": cfg.cycle_deadline_seconds,
                "max_stale_seconds": cfg.max_stale_seconds,
                "quarantine_after": cfg.quarantine_after,
                "watchdog_seconds": cfg.watchdog_seconds,
                "fetch_cycle_deadline_seconds":
                    cfg.fetch_cycle_deadline_seconds,
                "score_pipeline": cfg.score_pipeline,
                "score_memo": cfg.score_memo,
                "delta_fetch": cfg.delta_fetch,
                "provenance": cfg.provenance,
                "max_claim_per_cycle": cfg.max_claim_per_cycle,
                "fetch_concurrency": cfg.fetch_concurrency,
            },
            "env": {name: k.read()
                    for name, k in sorted(_knobs.all_knobs().items())
                    if k.scope in ("runtime", "devtools")},
        }

    def status_digest(self) -> dict:
        """Compact JSON-safe status digest this replica publishes in its
        membership heartbeat blob (engine/sharding.py digest_fn) — the
        cross-replica federation medium GET /fleet aggregates: health
        state, job counts, last-cycle golden signals, lease/triage
        counters, and per-class detection-latency SLO attainment. Must
        stay small (re-written every HEARTBEAT_S into the shared archive)
        and cheap (runs on the heartbeat thread). Dicts mutated by the
        cycle thread are snapshotted before summing."""
        state, _detail = self.health.state()
        stats = self.last_cycle_stages or {}
        store = self.store
        digest = {
            "v": 1,
            "health": state,
            "cycle_id": self.current_cycle_id,
            "jobs": store.status_counts(),
            "cycle": {
                "jobs": stats.get("jobs", 0),
                "device_launches": stats.get("device_launches", 0),
                "shed": stats.get("jobs_shed", 0),
                "stale_served": stats.get("stale_verdicts_served", 0),
                "watchdog_fires": stats.get("watchdog_fires", 0),
                "quarantined": stats.get("quarantined_jobs", 0),
            },
            "lease": {
                "claims": store.lease_claims_total,
                "steals": store.lease_steals_total,
                "releases": store.lease_releases_total,
                "adoptions": store.adopted_total,
            },
            "triage": {
                "screened": sum(dict(self.triage_screened_total).values()),
                "cleared": sum(dict(self.triage_cleared_total).values()),
                "escalated": sum(dict(self.triage_escalated_total).values()),
            },
            "slo": self.slo.digest(),
        }
        if self.shard is not None:
            digest["shards"] = self.shard.health_summary()
        return digest

    # ------------------------------------------------------------------ fetch
    def _fetch_window(self, url: str, now: float) -> Window | None:
        if not url:
            return None
        url = materialize_placeholders(url, now)
        t0 = time.perf_counter()
        try:
            # byte-level sources expose fetch_window: body -> grid Window
            # in one fused native call, skipping the intermediate
            # (ts, vals) arrays (fetch.window_from_prometheus_body).
            # Series-level sources (fixture dicts, wavefront) go through
            # fetch() + grid_from_series — the two paths are asserted
            # equivalent in tests/test_native.py.
            fw = getattr(self.source, "fetch_window", None)
            if fw is not None:
                win = fw(url)
            else:
                win = None
            if win is None:
                ts, vals = self.source.fetch(url)
                win = grid_from_series(ts, vals)
            if win is not None:
                tracing.tracer.add_note("points", int(win.values.shape[0]))
            return win
        finally:
            dt = time.perf_counter() - t0
            tracing.tracer.add_note("fetches", 1)
            tracing.tracer.add_note("fetch_seconds", dt)
            self.exporter.record_histogram(
                "foremastbrain:fetch_seconds", {}, dt,
                help="Per-window metric fetch latency (seconds).")

    def _preprocess(self, doc: J.Document, now: float):
        """Fetch all windows for a job; returns (pair, band, bi, multi, hpa)
        item lists. Band candidates route by the configured model family and
        metric count (design.md:53-88): bivariate_normal pairs 2-metric jobs,
        lstm_autoencoder pools 3+-metric jobs; everything else (and any job
        not matching its family's metric count) scores univariate bands."""
        pairs, bands, bis, multis, hpas = [], [], [], [], []
        candidates = []  # (name, hist, cur, policy) judgeable by history
        pod_window = None
        if doc.strategy == STRATEGY_HPA and doc.pod_count_url:
            # podCountURL (metricsquery.go:149-169): ready-pod counts over
            # the job window, fetched once per job and folded into a true
            # per-pod score (see ops.hpa.hpa_scores pods_now/pods_hist).
            # Best-effort: a missing count series degrades to the
            # aggregate score, never fails the job. Catches ANY failure,
            # not just FetchError — a proxy can flatten errors to a 200
            # with an unparseable body, and a garbage pod endpoint must
            # not abort the cycle (prep_many only converts FetchError).
            try:
                pod_window = self._fetch_window(doc.pod_count_url, now)
            except Exception:  # noqa: BLE001 - optional signal, never fatal
                pod_window = None
        for name, mq in doc.metrics.items():
            policy = self.config.policy_for(name)
            cur = self._fetch_window(mq.current, now)
            base = self._fetch_window(mq.baseline, now)
            hist = self._fetch_window(mq.historical, now)
            if cur is None or cur.n_valid == 0:
                # no current data -> nothing judgeable for this metric; the
                # job ends COMPLETED_UNKNOWN at endTime, never "healthy"
                continue
            if doc.strategy == STRATEGY_HPA:
                if hist is not None:
                    hpas.append(
                        _HpaItem(doc.id, name, hist, cur, mq.is_increase,
                                 mq.priority, mq.is_absolute, pod_window)
                    )
                continue
            if base is not None and base.n_valid > 0:
                pairs.append(_PairItem(doc.id, name, base, cur, policy))
            if hist is not None and hist.n_valid >= self.config.min_historical_points:
                candidates.append((name, hist, cur, policy))
        algo = self.config.algorithm
        # the reference dispatches the historical model by METRIC COUNT
        # (docs/guides/design.md:53-88: one metric -> MA/ES/DES/HW/Prophet,
        # two -> bivariate normal, 3+ -> LSTM); ML_ALGORITHM names the
        # univariate forecaster. multimetric_auto=False restores the
        # explicit-algorithm-only routing.
        auto = self.config.multimetric_auto
        if (auto or algo.startswith("bivariate")) and len(candidates) == 2:
            (n1, h1, c1, p1), (n2, h2, c2, p2) = candidates
            bis.append(_BiItem(doc.id, (n1, n2), (h1, h2), (c1, c2), (p1, p2)))
        elif (auto or algo.startswith("lstm")) and len(candidates) >= 3:
            multis.append(
                _MultiItem(
                    doc.id,
                    f"{doc.app_name}/{doc.namespace}",
                    [c[0] for c in candidates],
                    [c[1] for c in candidates],
                    [c[2] for c in candidates],
                )
            )
        else:
            for name, hist, cur, policy in candidates:
                bands.append(_BandItem(doc.id, name, hist, cur, policy))
        return pairs, bands, bis, multis, hpas

    # ------------------------------------------------------------- scoring
    def _isolate(self, score_fn, items):
        """Run a batch scorer with per-job blast-radius containment.

        Scorers batch many jobs into one device program, so one poisoned
        item would otherwise fail the whole cycle for everyone — and the
        stuck-job takeover would re-claim and re-crash it forever. On batch
        failure, retry per JOB (not per item: _score_hpa scores a job's
        metrics jointly — splitting them would misassign tps/sla roles) and
        report {job_id: error} for the offenders only.
        """
        try:
            return score_fn(items), {}
        except Exception:  # noqa: BLE001 - fall back to per-job isolation
            results, bad = {}, {}
            by_job: dict[str, list] = {}
            for it in items:
                by_job.setdefault(it.job_id, []).append(it)
            for job_id, group in by_job.items():
                try:
                    results.update(score_fn(group))
                except Exception as e:  # noqa: BLE001
                    bad[job_id] = f"{type(e).__name__}: {e}"
            return results, bad

    def _watchdog_call(self, fn, *args):
        """Run a collect-phase materialization bounded by WATCHDOG_S.

        JAX device waits have no timeout parameter, so the bound comes
        from outside: the call runs on a sacrificial daemon thread and
        the caller waits at most the budget. On expiry the thread is
        ABANDONED (a truly hung runtime call cannot be interrupted from
        Python) and WatchdogTimeout raised — the pipeline fails the
        bucket over to the sync per-job path, which is wrapped too, so a
        poisoned device stalls one bucket per cycle, never the cycle.
        Disabled (WATCHDOG_S=0) this is a plain call with zero overhead.
        """
        timeout = self.config.watchdog_seconds
        if timeout <= 0:
            return fn(*args)
        with self._wd_lock:
            if self._watchdog_abandoned >= self._WATCHDOG_MAX_ABANDONED:
                # a persistently wedged device would otherwise accumulate
                # abandoned threads (and their pinned launch state)
                # without bound across cycles; at the cap, new guarded
                # calls fast-fail as watchdog fires — same failover and
                # the same DEGRADED health signal, zero new threads
                self._record_watchdog_fire()
                raise WatchdogTimeout(
                    f"{self._watchdog_abandoned} abandoned watchdog "
                    "threads (device wedged); call skipped")
        out: list = []
        err: list = []
        done = threading.Event()
        abandoned = {"flag": False}
        # cross-thread trace correlation: the sacrificial thread adopts
        # this thread's trace context, so spans it opens parent under the
        # cycle trace (and its log lines carry cycle_id) instead of
        # orphaning; an ABANDONED thread can at worst append late,
        # silently-dropped children — never corrupt another stack
        ctx = tracing.tracer.context()

        def run():
            try:
                with tracing.tracer.attach(ctx):
                    out.append(fn(*args))
            except BaseException as e:  # noqa: BLE001 - relayed to caller
                err.append(e)
            finally:
                done.set()
                # flag read UNDER the lock, pairing with the timed-out
                # main thread's locked {is_set check -> flag set}: without
                # it, a call completing exactly at the timeout boundary
                # could read the flag before main sets it and leak the
                # abandoned slot forever (8 leaks = watchdog wedged shut)
                with self._wd_lock:
                    if abandoned["flag"]:
                        # the hung call eventually returned: free its slot
                        self._watchdog_abandoned -= 1

        t = threading.Thread(target=run, name="collect-watchdog", daemon=True)
        t.start()
        if not done.wait(timeout):
            with self._wd_lock:
                if not done.is_set():
                    abandoned["flag"] = True
                    self._watchdog_abandoned += 1
            if abandoned["flag"]:
                self._record_watchdog_fire()
                raise WatchdogTimeout(
                    f"device materialization exceeded {timeout:g}s "
                    "(watchdog)")
        if err:
            raise err[0]
        return out[0]

    # abandoned-thread ceiling: past this many never-returned device
    # calls the watchdog stops spawning and fast-fails instead
    _WATCHDOG_MAX_ABANDONED = 8

    def _record_watchdog_fire(self):
        self.watchdog_fires_total += 1
        self.flight.record_event(flightrec.EVENT_WATCHDOG,
                                 abandoned=self._watchdog_abandoned)
        self.exporter.record_counter(
            "foremastbrain:watchdog_fires_total", {},
            help="device materializations timed out by the collect "
                 "watchdog (WATCHDOG_S)")

    @staticmethod
    def _newest_sample_ts(items) -> float:
        """Newest VALID sample timestamp across a job's judged current
        windows — the moment the job's window last ADVANCED, on the
        data's own clock. 0.0 when nothing is judgeable."""
        pairs, bands, bis, multis, hpas = items
        curs = ([it.current for it in pairs]
                + [it.current for it in bands]
                + [w for it in bis for w in it.cur]
                + [w for it in multis for w in it.cur]
                + [it.current for it in hpas])
        newest = 0.0
        for w in curs:
            if w is None or w.n_valid == 0:
                continue
            idx = int(np.flatnonzero(w.mask)[-1])
            newest = max(newest, float(w.start + idx * w.step))
        return newest

    def _observe_latency(self, st: _JobState, now: float):
        """One window-advance -> verdict detection-latency observation
        for a judged job (engine/slo.py), annotated onto its provenance
        record BEFORE the terminal transition attaches the summary to
        the Document.

        Two addends, each in a self-consistent clock domain:
          * poll/scrape wait — cycle `now` minus the newest judged
            sample's own timestamp (how long fresh evidence sat waiting
            to be LOOKED at; under poll-driven operation this is the
            TTL-cache + cycle-tick wait the streaming dataplane removes);
          * in-cycle tail — monotonic fold time minus the cycle start
            (fetch + dispatch + collect + fold for this job's cycle).

        Each WINDOW ADVANCE is observed once: a cycle that re-judges a
        job on the same newest sample is a re-confirmation of an
        already-detected state, not a new detection, and counting its
        ever-growing staleness would drown the latency of the advance
        itself (with streaming, a verdict landing 0.5 s after the push
        must not be followed by sweeps re-reporting the same sample at
        10/20/30 s). Jobs with NO judgeable samples (newest_ts == 0)
        keep the per-cycle observation — there is no advance to key on.

        No-op for jobs that ingested nothing this cycle (shed,
        quarantined, stale-served)."""
        if not st.ingest_at:
            return
        tail0 = self._cycle_mono0 or st.ingest_at
        mono_now = time.monotonic()
        lat = max(mono_now - tail0, 0.0)
        if st.newest_ts > 0:
            if self._slo_seen.get(st.doc.id, 0.0) >= st.newest_ts:
                st.ingest_at = 0.0
                # a re-confirmation consumes nothing: drop any waterfall
                # record a redundant push opened (its watermark is
                # independent of the SLO dedupe), or its stages would
                # leak into the job's NEXT genuine observation
                self.waterfall.discard(st.doc.id)
                return  # this advance was already observed
            self._slo_seen[st.doc.id] = st.newest_ts
            lat += max(now - st.newest_ts, 0.0)
        st.ingest_at = 0.0  # at most one observation per cycle
        self.slo.observe(slo_mod.classify(st.doc.strategy), lat)
        # waterfall: split the in-cycle tail at the fold boundary and
        # close this job's stage record (push stages came from the
        # receiver/scheduler; polled jobs synthesize the poll wait)
        fold0 = self._cycle_fold_mono or mono_now
        wf = self.waterfall.observe(
            st.doc.id, now=now, newest_ts=st.newest_ts,
            score_s=max(fold0 - tail0, 0.0),
            fold_s=max(mono_now - fold0, 0.0))
        ann = {"detection_latency_s": round(lat, 6)}
        if wf["stages"]:
            ann["detection_stages"] = {
                k: round(v, 6) for k, v in wf["stages"].items()}
        if wf["trace_id"]:
            # the push's trace beats the cycle's own: `explain` must
            # link the verdict to the distributed trace that carried it
            ann["trace_id"] = wf["trace_id"]
        self.provenance.annotate(st.doc.id, **ann)
        ctx = wf["ctx"]
        if ctx is not None and ctx.sampled:
            # close the push's distributed trace AT the verdict: a
            # remote-parented span under the receive/forward chain
            # carrying the waterfall, so one trace runs push -> verdict
            # across every replica it touched
            with tracing.tracer.span(
                    tracing.SPAN_ENGINE_VERDICT, _remote=ctx,
                    job_id=st.doc.id, status=st.doc.status,
                    detection_latency_s=round(lat, 4),
                    waterfall={k: round(v, 6)
                               for k, v in wf["stages"].items()}):
                pass

    def reset_slo(self):
        """Clear SLO observations AND the once-per-advance dedupe map
        (bench legs isolate measured cycles from warm-up; resetting the
        histograms without the map would mute the first post-reset
        observation per job). The waterfall follows — stage
        distributions must cover exactly the observations the SLO does."""
        self._slo_seen.clear()
        self.slo.reset()
        self.waterfall.reset()

    def _prov_content(self, job_id: str) -> str | None:
        """Compact provenance JSON for a terminal Document's
        processing_content (None keeps the field untouched when
        provenance is off — the A/B identity contract covers
        status/reason/anomaly; the attachment itself is the feature)."""
        if not self.provenance.enabled:
            return None
        return self.provenance.summary_json(job_id) or None

    def quarantined_count(self, now: float | None = None) -> int:
        """Jobs currently parked in poison quarantine. Snapshot first
        (list() is atomic under the GIL): /metrics scrapes call this from
        HTTP threads while the cycle thread inserts/pops entries, and
        iterating the live dict would raise mid-scrape."""
        now = time.time() if now is None else now
        return sum(1 for q in list(self._quarantine.values()) if q[1] > now)

    # ladder continues past the default chunk so a LARGE configured
    # score_batch still pads small fleets to the nearest rung, never to
    # the full chunk (10k rows must not pad to a 1M-row launch). The
    # 512 rung exists for the expensive per-row families (LSTM fleet
    # scoring: a 500-job fleet padding to 1024 doubles the scan work;
    # measured 6.8 s -> ~3.5 s per mixed cycle on CPU).
    _BATCH_BUCKETS = (16, 64, 256, 512, 1024, 4096, 16384, 65536)

    @classmethod
    def _rung_for(cls, n: int, cap: int) -> int:
        """Smallest batch rung >= n from the ladder, capped at `cap`.
        The ONE ladder walk — the family chunker and the triage screen
        (engine/triage.py, whose prewarm rung set in pipeline.prewarm is
        derived from the same ladder) both route through it."""
        for b in cls._BATCH_BUCKETS:
            if b >= cap:
                break
            if n <= b:
                return b
        return cap

    def _bucket_rows(self, n: int) -> int:
        """Smallest batch rung >= n, capped at the configured chunk."""
        return self._rung_for(n, max(16, self.config.score_batch))

    # mega padding classes (MEGABATCH): below this the classic rung
    # ladder bounds tiny-program churn; above it classes are mantissa-
    # quantized so a big fleet pads by at most 1/16 — the rung ladder's
    # power-of-4 gaps would waste up to 4x compute at mega batch sizes
    # (a 1500-row fleet padding to 4096), which on a compute-bound
    # backend costs more than the launches the mega path saves.
    _MEGA_MANTISSA_FLOOR = 512

    @classmethod
    def _mega_rows(cls, n: int) -> int:
        """Smallest mega padding class >= n: rung-ladder snapped up to
        _MEGA_MANTISSA_FLOOR, then ceil to 5-bit-mantissa granularity
        (m * 2^e with m in [16, 32)) — waste <= 6.25%, program count
        bounded at 16 classes per octave (and a steady fleet only ever
        compiles the one class its size lands in)."""
        n = max(int(n), 1)
        if n <= cls._MEGA_MANTISSA_FLOOR:
            for b in cls._BATCH_BUCKETS:
                if n <= b:
                    return b
        e = max(n.bit_length() - 5, 0)  # keeps the mantissa in [16, 32)
        return -(-n // (1 << e)) << e

    def _mega_cap(self, T: int) -> int:
        """Mega-launch row ceiling for a T bucket: MEGABATCH_MAX_ROWS at
        T <= 1024, scaled ~1/T beyond (floor 1024) so a long-history
        bucket's mega launch costs the same peak bytes as a short one."""
        max_rows = max(int(self.config.megabatch_max_rows), 1024)
        budget = max_rows * 1024  # row-steps at the base T
        return int(min(max_rows, max(budget // max(int(T), 1024), 1024)))

    def _launch_chunks(self, fn, arrays: list, donate: int = 0) -> list:
        """Row-chunk packed (B, ...) arrays into FIXED batch buckets and
        call fn per chunk WITHOUT materializing the outputs.

        XLA specializes every jitted program on the batch dimension, so
        launching the raw fleet size compiles a fresh program whenever the
        claim count changes — and CPU compile time itself grows with B
        (measured ~33 s at B=10k vs ~133 s at B=50k). Fixed batch rungs
        amortize to ONE compiled program per (rung, T bucket) for the life
        of the process and bound peak memory at any fleet size. Partial
        chunks (small fleets AND the tail of a big one) pad up to the
        smallest rung that fits — never to the full chunk — with edge
        padding (repeat of the last row — always semantically valid
        inputs); padded rows are trimmed on merge.

        Returns [(out_dict, n_valid_rows)] in row order. The out dicts
        hold whatever fn returned — for jitted scorers these are
        async-dispatch device values; nothing blocks until
        `_collect_chunks` materializes them, so the caller can keep
        packing the next bucket while the device drains this one.
        """
        B = arrays[0].shape[0]
        mega = self.config.megabatch
        if mega:
            # single-dispatch mega-batching: ONE launch for the whole
            # accumulated batch (chunked only at the memory-aware cap),
            # padded to the fine mega class instead of rung-chunked.
            # Row-wise scorers make the launch boundary verdict-neutral
            # (the same argument the streamed-vs-barriered determinism
            # test pins), so this changes launch count, never results.
            T = max((a.shape[1] for a in arrays if a.ndim > 1),
                    default=1024)
            C = self._mega_cap(T)
        else:
            C = self._bucket_rows(B)
        launches = []
        for i in range(0, B, C):
            sl = [a[i:i + C] for a in arrays]
            n = sl[0].shape[0]
            target = (min(self._mega_rows(n), C) if mega
                      else self._bucket_rows(n))
            if n < target:
                sl = [np.pad(a, ((0, target - n),) + ((0, 0),) * (a.ndim - 1),
                             mode="edge") for a in sl]
            self.device_launches += 1
            if mega:
                self.megabatch_launches_total += 1
                self.megabatch_real_rows_total += n
                self.megabatch_pad_rows_total += target - n
                launches.append((self._mega_call(fn, sl, donate), n))
            else:
                launches.append((fn(*sl), n))
        return launches

    def _mega_call(self, fn, sl: list, donate: int):
        """Invoke one mega launch, through a donated-buffer jit twin
        when the kernel is a pure jitted program (`donate` leading array
        args) and the backend aliases donated inputs (TPU/GPU). The big
        packed (B, T) arrays are dead after the launch, so donation
        halves the mega launch's peak footprint. CPU XLA does not alias
        (donating there only warns per program), and the host-composite
        band/hpa closures cannot be re-jitted — both take the plain
        call, same results."""
        if donate:
            import jax

            if jax.default_backend() != "cpu":
                tw = self._donated_twins.get(id(fn))
                if tw is None:
                    tw = jax.jit(fn, donate_argnums=tuple(range(donate)))  # lint: disable=jit-hygiene -- donate_argnums is the leading-array count a launch half passes as a literal (4/5), never a traced value
                    self._donated_twins[id(fn)] = tw
                args = [jax.device_put(a) if i < donate else a
                        for i, a in enumerate(sl)]
                return tw(*args)
        return fn(*sl)

    @staticmethod
    def _collect_chunks(launches: list) -> dict:
        """Materialize `_launch_chunks` output: block on the device values,
        trim padded rows, concatenate chunks back into one (B, ...) dict."""
        outs = [
            {k: np.asarray(v)[:n] for k, v in out.items()}
            for out, n in launches
        ]
        if len(outs) == 1:
            return outs[0]
        return {k: np.concatenate([o[k] for o in outs]) for k in outs[0]}

    def _score_chunks(self, fn, arrays: list) -> dict:
        """Synchronous launch+collect (the pre-pipeline contract)."""
        return self._collect_chunks(self._launch_chunks(fn, arrays))

    def _launch_period_partitions(self, band_fn, args, xv, xm, regions) -> list:
        """Launch a band scorer, partitioned by detected seasonal period.

        The HW/seasonal-trend scan needs a STATIC period (the season buffer
        length is a compiled shape), so per-series detected periods cannot
        ride one launch. Candidate sets are tiny (operational cycles), so
        the fleet splits into at most a handful of sub-batches — each still
        chunked into the fixed rungs — and outputs merge back in original
        order at collect time. No-period algorithms and auto-off fall
        through to one partition. Detection itself materializes (the chosen
        periods steer host-side batching), but the scoring launches stay
        async. Returns [(row_idx | None, chunk launches)].
        """
        chosen = self._detect_periods(xv, xm, regions)
        if chosen is None:
            return [(None, self._launch_chunks(band_fn, args))]
        parts = []
        for p in np.unique(chosen):
            idx = np.nonzero(chosen == p)[0]
            parts.append((idx, self._launch_chunks(
                lambda *a, _p=int(p): band_fn(*a, _period=_p),
                [a[idx] for a in args],
            )))
        return parts

    def _collect_period_partitions(self, parts: list, B: int) -> dict:
        if len(parts) == 1 and parts[0][0] is None:
            return self._collect_chunks(parts[0][1])
        out: dict | None = None
        for idx, launches in parts:
            sub = self._collect_chunks(launches)
            if out is None:
                out = {
                    k: np.empty((B,) + v.shape[1:], v.dtype)
                    for k, v in sub.items()
                }
            for k, v in sub.items():
                out[k][idx] = v
        return out

    # ------------------------------------------------ family launch/collect
    # Each batch family (pair, band, bivariate, hpa) is split into a
    # `_launch_*` half (pack + async device dispatch; returns an opaque
    # state tuple whose [0] is the claim-ordered entry list) and a
    # `_collect_*` half (materialize + per-item postprocess). The
    # synchronous `_score_*` entry points — the pre-pipeline contract, and
    # the per-job retry path of the `_isolate` blast-radius fallback — are
    # launch + immediate collect over the same code, so the two paths
    # cannot drift.

    @staticmethod
    def _pair_T(it: _PairItem) -> int:
        return bucket_length(
            max(it.baseline.values.shape[0], it.current.values.shape[0])
        )

    @staticmethod
    def _by_bucket(items, key) -> dict:
        by: dict[int, list] = {}
        for it in items:
            by.setdefault(key(it), []).append(it)
        return by

    def _launch_pairs(self, group: list, T: int):
        cfg = self.config
        bvals, bm = pack_windows([it.baseline for it in group], pad_to=T)
        cv, cm = pack_windows([it.current for it in group], pad_to=T)
        B = len(group)
        launches = self._launch_chunks(fl.score_pairs, [
            bvals, bm, cv, cm,
            np.full(B, cfg.pairwise_threshold, np.float32),
            np.full(B, cfg.enabled_tests(), np.int32),
            np.full(
                B,
                fl.COMBINE_ALL if cfg.pairwise_combine_all else fl.COMBINE_ANY,
                np.int32,
            ),
            np.full(B, cfg.ma_window, np.int32),
            np.asarray([it.policy.threshold for it in group], np.float32),
            np.asarray([it.policy.bound for it in group], np.int32),
            np.asarray([it.policy.min_lower_bound for it in group], np.float32),
            np.tile(
                np.asarray(
                    [
                        cfg.min_mann_whitney_points,
                        cfg.min_wilcoxon_points,
                        cfg.min_kruskal_points,
                        cfg.min_friedman_points,
                    ],
                    np.int32,
                ),
                (B, 1),
            ),
        ], donate=4)
        return (group, launches)

    def _collect_pairs(self, state) -> dict:
        group, launches = state
        out = self._collect_chunks(launches)
        results = {}
        # one bulk .tolist() per field instead of 5 boxed numpy scalar
        # reads per row: at 100k rows the boxed reads alone cost more
        # host time than the merge (tolist yields the same Python
        # bool/float/int values bool()/float()/int() did — byte-identical
        # verdicts, pinned by the mega A/B)
        unhealthy = out["unhealthy"].tolist()
        min_p = out["min_p"].tolist()
        pw = out["pairwise_unhealthy"].tolist()
        band = out["band_unhealthy"].tolist()
        band_count = out["band_count"].tolist()
        for i, it in enumerate(group):
            results[(it.job_id, it.metric, "pair")] = {
                "unhealthy": unhealthy[i],
                "min_p": min_p[i],
                "pairwise_unhealthy": pw[i],
                "band_unhealthy": band[i],
                "band_count": band_count[i],
            }
        return results

    def _score_pairs(self, items: list[_PairItem]):
        """Batch all pairwise items (bucketed by window length)."""
        results = {}
        for T, group in self._by_bucket(items, self._pair_T).items():
            results.update(self._collect_pairs(self._launch_pairs(group, T)))
        return results

    def _needs_period(self) -> bool:
        return self.config.algorithm.startswith(
            ("holt_winters", "seasonal_trend", "prophet")
        )

    def _detect_periods(self, xv, xm, region) -> "np.ndarray | None":
        """Per-series seasonal period for the band batch (auto-detection).

        Returns an int array of chosen periods, or None when the configured
        algorithm has no period or auto-detection is off. The fallback for
        unsupported/aperiodic series is the static HW_PERIOD, clamped the
        same way the static path clamps it."""
        cfg = self.config
        cands = tuple(p for p in cfg.hw_period_candidates if p >= 2)
        # an empty candidate set (operator set HW_PERIOD_CANDIDATES="") is
        # an explicit "static period only" — same as auto off
        if not (self._needs_period() and cfg.hw_period_auto and cands):
            return None
        T = xv.shape[1]
        fallback = min(cfg.hw_period, max(T // 2, 2))

        def detect_fn(xv_c, xm_c, reg_c):
            period, _ = fc.detect_period(
                xv_c, xm_c & ~reg_c, cands,
                np.int32(fallback), np.float32(cfg.hw_min_seasonal_acf),
                alias_margin=np.float32(cfg.hw_alias_margin),
                contrast_margin=np.float32(cfg.hw_contrast_margin),
            )
            return {"period": period}

        # through the fixed batch rungs like every scorer: one compiled
        # detection program per (rung, T bucket), bounded launch memory
        return self._score_chunks(detect_fn, [xv, xm, region])["period"]

    def _predict(self, xv, xm, region, data_steps: int | None = None,
                 period_override: int | None = None):
        """Forecaster dispatch on config.algorithm (history-only fit).

        `data_steps` steers the long-window kernel gate; the band path
        passes its bucket T so the choice is a pure function of the
        compiled bucket — identical for every chunking of the same
        bucket (streamed vs. barriered launches must agree bit-for-bit).
        `period_override` carries a detected seasonal period (already
        support-gated against the series length by detect_period);
        without it the static HW_PERIOD config is clamped to the window.
        """
        algo = self.config.algorithm
        hist_mask = xm & ~region
        B = xv.shape[0]
        # long windows: same smoother, time-parallel (associative scan).
        # SES only — the DES associative form compounds f32 rounding on
        # trending series (~4e-3 relative at T>=4096, enough to flip a
        # borderline band verdict), so DES always runs sequentially here.
        long = (data_steps if data_steps is not None
                else xv.shape[1]) >= self.config.long_window_steps
        if algo.startswith("exponential_smoothing"):
            ses = sq.ses_predictions_assoc if long else fc.ses_predictions
            preds = ses(xv, hist_mask, np.full(B, 0.3, np.float32))
        elif algo.startswith("double_exponential"):
            preds = fc.des_predictions(
                xv, hist_mask, np.full(B, 0.5, np.float32), np.full(B, 0.1, np.float32)
            )
        elif algo.startswith("holt_winters"):
            period = (period_override if period_override is not None
                      else min(self.config.hw_period, max(xv.shape[1] // 2, 2)))
            fitm = hist_mask.copy()
            fitm[:, : 2 * period] = False
            _, preds = fc.fit_holt_winters(xv, hist_mask, fitm, period)
        elif algo.startswith("seasonal_trend") or algo.startswith("prophet"):
            period = (period_override if period_override is not None
                      else min(self.config.hw_period, max(xv.shape[1] // 2, 2)))
            _, preds = fc.fit_seasonal_trend(
                xv, hist_mask, hist_mask, period, self.config.st_order,
                n_changepoints=self.config.st_changepoints,
            )
        else:  # moving_average_all default
            preds = fc.moving_average_predictions(xv, hist_mask, self.config.ma_window)
        return np.asarray(preds), hist_mask

    @staticmethod
    def _band_T(it: _BandItem) -> int:
        return bucket_length(
            min(
                it.historical.values.shape[0] + it.current.values.shape[0],
                MAX_WINDOW_STEPS,
            )
        )

    def _launch_bands(self, group: list, T: int):
        concats = []
        regions = np.zeros((len(group), T), bool)
        n_hs = []
        for i, it in enumerate(group):
            h, c = it.historical, it.current
            vals, mask, n_h = _concat_trimmed(h, c)
            n_hs.append(n_h)
            concats.append(Window(vals, mask, h.start, h.step))
            regions[i, n_h : vals.shape[0]] = True
        xv, xm = pack_windows(concats, pad_to=T)

        def band_fn(xv_c, xm_c, reg_c, thr_c, bnd_c, mlb_c, _period=None):
            # the long-window kernel gate is a function of the BUCKET (T),
            # not of the rows sharing a chunk: a data-dependent gate (max
            # real length in the chunk) would make a row's smoother choice
            # depend on its chunk-mates, so streamed launches (different
            # chunk boundaries) could flip a borderline band verdict vs.
            # the barriered path. T is already what the program compiles
            # on; buckets only reach 4096 when their members are >2048
            # points, where the assoc scan is the right kernel anyway.
            preds, hist_mask = self._predict(
                xv_c, xm_c, reg_c, T, period_override=_period)
            sigma = np.asarray(
                fc.residual_sigma(xv_c, preds, hist_mask, ~reg_c))
            return fc.band_anomalies(
                xv_c, xm_c, reg_c, preds, sigma, thr_c, bnd_c, mlb_c)

        args = [
            xv, xm, regions,
            np.asarray([it.policy.threshold for it in group], np.float32),
            np.asarray([it.policy.bound for it in group], np.int32),
            np.asarray([it.policy.min_lower_bound for it in group], np.float32),
        ]
        parts = self._launch_period_partitions(band_fn, args, xv, xm, regions)
        return (group, parts, xv, regions, n_hs)

    def _collect_bands(self, state) -> dict:
        group, parts, xv, regions, n_hs = state
        out = self._collect_period_partitions(parts, len(group))
        results = {}
        # bulk tolist for the per-row scalar fields (see _collect_pairs);
        # the (B, T) arrays stay numpy — they are row-sliced, not boxed
        counts = out["count"].tolist()
        firsts = out["first_index"].tolist()
        uppers = out["upper"]
        lowers = out["lower"]
        flags = out["flags"]
        checked = out["checked"].tolist()
        for i, it in enumerate(group):
            n_h = n_hs[i]
            anomalous_idx = np.nonzero(flags[i])[0]
            anomaly_pairs = []
            for j in anomalous_idx[:50]:
                anomaly_pairs += [_concat_ts(it.current, n_h, int(j)),
                                  float(xv[i, j])]
            region_sel = regions[i]
            first = firsts[i]
            results[(it.job_id, it.metric, "band")] = {
                "count": counts[i],
                "unhealthy": counts[i] >= self._gate(checked[i]),
                "first_ts": (
                    _concat_ts(it.current, n_h, first) if first >= 0 else -1.0
                ),
                "upper": float(np.mean(uppers[i][region_sel])),
                "lower": float(np.mean(lowers[i][region_sel])),
                "anomaly_pairs": anomaly_pairs,
            }
        return results

    def _score_bands(self, items: list[_BandItem]):
        results = {}
        for T, group in self._by_bucket(items, self._band_T).items():
            results.update(self._collect_bands(self._launch_bands(group, T)))
        return results

    def _gate(self, checked) -> float:
        """Unhealthy-verdict gate: min anomalous points for a band-style
        scorer to condemn a window (see EngineConfig.band_min_points)."""
        return max(
            self.config.band_min_points,
            self.config.band_violation_fraction * float(checked),
        )

    @staticmethod
    def _bi_prep(it: _BiItem):
        """((x, m, n_h, n_c) joint grid, T bucket) for one bivariate item."""
        pre = _joint_grid(list(it.hist), list(it.cur))
        return pre, bucket_length(pre[0].shape[1])

    def _launch_bivariate(self, entries: list, T: int):
        """entries: [(item, joint-grid prep)] — one launch state per bucket."""
        B = len(entries)
        x1 = np.zeros((B, T), np.float32)
        x2 = np.zeros((B, T), np.float32)
        m1 = np.zeros((B, T), bool)
        m2 = np.zeros((B, T), bool)
        region = np.zeros((B, T), bool)
        thr = np.empty(B, np.float32)
        mlb1 = np.empty(B, np.float32)
        mlb2 = np.empty(B, np.float32)
        bm1 = np.empty(B, np.int32)
        bm2 = np.empty(B, np.int32)
        for i, (it, (x, m, n_h, n_c)) in enumerate(entries):
            n = x.shape[1]
            x1[i, :n], x2[i, :n] = x[0], x[1]
            m1[i, :n], m2[i, :n] = m[0], m[1]
            region[i, n_h:n] = True
            # the pair shares one ellipse: use the stricter (smaller)
            # radius of the two metric policies
            thr[i] = min(it.policies[0].threshold, it.policies[1].threshold)
            mlb1[i] = it.policies[0].min_lower_bound
            mlb2[i] = it.policies[1].min_lower_bound
            bm1[i] = it.policies[0].bound
            bm2[i] = it.policies[1].bound
        launches = self._launch_chunks(bv.bivariate_normal_anomalies, [
            x1, m1, x2, m2, region, thr, mlb1, mlb2, bm1, bm2,
        ], donate=5)
        return (entries, launches, region)

    def _collect_bivariate(self, state) -> dict:
        entries, launches, region = state
        out = self._collect_chunks(launches)
        results = {}
        # bulk tolist for the per-row scalars (see _collect_pairs)
        counts = np.asarray(out["count"]).tolist()
        firsts = np.asarray(out["first_index"]).tolist()
        checked = np.asarray(out["checked"]).tolist()
        flags = np.asarray(out["flags"])
        upper1 = np.asarray(out["upper1"])
        lower1 = np.asarray(out["lower1"])
        upper2 = np.asarray(out["upper2"])
        lower2 = np.asarray(out["lower2"])
        for i, (it, (x, m, n_h, n_c)) in enumerate(entries):
            cur0 = it.cur[0]
            first = firsts[i]
            anomalous_idx = np.nonzero(flags[i])[0]
            anomaly_pairs = []
            for j in anomalous_idx[:50]:
                anomaly_pairs += [_concat_ts(cur0, n_h, int(j)),
                                  float(x[0, int(j)])]
            sel = region[i]
            results[(it.job_id, "&".join(it.metrics), "bivariate")] = {
                "count": counts[i],
                "unhealthy": counts[i] >= self._gate(checked[i]),
                "first_ts": (
                    _concat_ts(cur0, n_h, first) if first >= 0 else -1.0
                ),
                "anomaly_pairs": anomaly_pairs,
                "bounds": {
                    it.metrics[0]: (
                        float(np.mean(upper1[i][sel])),
                        float(np.mean(lower1[i][sel])),
                    ),
                    it.metrics[1]: (
                        float(np.mean(upper2[i][sel])),
                        float(np.mean(lower2[i][sel])),
                    ),
                },
            }
        return results

    def _score_bivariate(self, items: list[_BiItem]):
        """Joint 2-metric scoring: one bivariate-normal program per bucket."""
        results = {}
        by_bucket: dict[int, list] = {}
        for it in items:
            pre, T = self._bi_prep(it)
            by_bucket.setdefault(T, []).append((it, pre))
        for T, entries in by_bucket.items():
            results.update(
                self._collect_bivariate(self._launch_bivariate(entries, T)))
        return results

    def _lstm_model(self, F: int, unroll: int = 8):
        """Module instance per (F, dims, unroll). Scoring uses unroll=8
        (fleet-launch dispatch bound); training passes unroll=1 (the
        unrolled fwd+bwd compiles slower and runs ~2x slower). Both share
        one param tree shape — see LstmAutoencoder.unroll."""
        key = (F, self.config.lstm_hidden, self.config.lstm_latent, unroll)
        if key not in self._lstm_models:
            self._lstm_models[key] = lstm_ae.LstmAutoencoder(
                hidden=self.config.lstm_hidden,
                latent=self.config.lstm_latent,
                features=F,
                unroll=unroll,
            )
        return self._lstm_models[key]

    def _score_multi(self, items: list[_MultiItem]):
        """LSTM-autoencoder scoring for 3+-metric jobs (faq.md:8-10).

        Per job: standardize each metric on its history, train the AE on
        non-overlapping historical subwindows (cached per app, LRU-bounded by
        MAX_CACHE_SIZE), then z-score the current window's reconstruction
        error against the healthy-error distribution."""
        cfg = self.config
        results = {}
        memo_on = cfg.score_memo
        memo_zs: list = []   # (item, z) reused without a device launch
        zfp_by_job: dict = {}  # (job_id, metrics) -> score-input fp
        # (item, params, err_mu, err_sd, version, cwin, cmask)
        scoreable: list = []
        # (item, cache_key, hwin, hmask, cwin, cmask, train_fp) — budgeted
        # misses
        pending: list = []
        pending_keys: set = set()
        # same-cycle duplicates of a pending cache_key (N jobs of one app
        # share the app/metrics/W key): they ride the leader's training —
        # one budget slot, one model — and resolve from the cache after
        followers: list = []
        budget = cfg.lstm_max_train_per_cycle
        for it in items:
            x, m, n_h, n_c = _joint_grid(it.hist, it.cur)
            F, T = x.shape
            W = min(cfg.lstm_window, max(n_h // 2, 1))
            if W < 4 or n_h < 2 * W:
                # not enough history to learn from: leave the job unjudged
                # (COMPLETED_UNKNOWN at endTime), same as sparse band jobs
                continue
            hist_m = m[:, :n_h]
            hw = hist_m.astype(np.float64)
            n = np.maximum(hw.sum(axis=1), 1.0)
            # float64 reductions: any f32-finite history (<= 3.4e38)
            # squares and sums without overflow in f64, so mu/sd stay
            # finite and the standardized series is well-defined — no
            # NaN edge, no warning suppression needed (review r05)
            xh = x[:, :n_h].astype(np.float64)
            mu = (xh * hw).sum(axis=1) / n
            sd = np.sqrt((((xh - mu[:, None]) * hw) ** 2).sum(axis=1) / n)
            sd = np.maximum(sd, 1e-6)
            xs = ((x - mu[:, None]) / sd[:, None]).T.astype(np.float32)  # (T, F)
            ms = m.T  # (T, F)

            k = n_h // W
            h0 = n_h - k * W
            hwin = xs[h0:n_h].reshape(k, W, F)
            hmask = ms[h0:n_h].reshape(k, W, F)
            # score windows tiling the WHOLE current region (not just the
            # last W steps); a final tail window may dip into history — its
            # history steps are mask-zeroed so they add no reconstruction
            # error and cannot dilute the z-score
            starts = list(range(n_h, T - W + 1, W))
            if not starts or starts[-1] + W < T:
                starts.append(max(T - W, 0))
            cwin = np.stack([xs[s : s + W] for s in starts])
            cmask = np.stack([ms[s : s + W] for s in starts])
            for k_i, s in enumerate(starts):
                if s < n_h:
                    cmask[k_i, : n_h - s] = False

            cache_key = (it.cache_key, tuple(it.metrics), W)
            entry = self._lstm_cache.pop(cache_key, None)
            train_fp = _fp(b"lstm-train", hwin, hmask, cfg.lstm_epochs,
                           cfg.lstm_hidden, cfg.lstm_latent) if memo_on \
                else None
            if entry is None and memo_on:
                # train-window fingerprint memo: training is deterministic
                # (PRNGKey(0), fixed epochs), so identical train windows
                # reproduce identical params — reuse the previous entry
                # instead of re-paying the train (the restart/eviction/
                # key-churn case BENCH_r05 measured at 25.8 s of warmup)
                entry = self._lstm_train_memo.get(train_fp)
                if entry is not None:
                    self._lstm_train_memo.move_to_end(train_fp)
                    self.lstm_train_memo_hits += 1
            if entry is None:
                if cache_key in pending_keys:
                    # a leader is already training this key this cycle:
                    # no extra budget slot, no redundant training
                    followers.append((it, cache_key, cwin, cmask))
                    continue
                # the counter lives on the analyzer and resets per CYCLE,
                # not per call: the _isolate per-job retry path re-invokes
                # this scorer many times within one cycle, and a per-call
                # counter would let one poisoned job convert the budgeted
                # warm-up into the full unbounded training burst
                if budget > 0 and self._lstm_trained_this_cycle >= budget:
                    # train-on-miss budget spent (VERDICT r3: a cold
                    # multi-metric fleet must not blow the cycle budget on
                    # unbounded AE training): leave the job unjudged; it
                    # stays in progress and warms up on a later cycle.
                    self._lstm_budget_skipped_ids.add(it.job_id)
                    continue
                self._lstm_trained_this_cycle += 1
                # defer: same-shape misses train together in one vmapped
                # loop (lstm_ae.train_fleet) after the collection pass
                pending.append((it, cache_key, hwin, hmask, cwin, cmask,
                                train_fp))
                pending_keys.add(cache_key)
                continue
            self._lstm_cache[cache_key] = entry  # re-insert = mark recent
            while len(self._lstm_cache) > cfg.max_cache_size:
                self._lstm_cache.pop(next(iter(self._lstm_cache)))
            params, err_mu, err_sd, version = entry
            if memo_on:
                # verdict memo: unchanged score windows against unchanged
                # params (version pins them) reuse the previous z without
                # a device launch — the steady-state common case for jobs
                # whose train window hasn't moved
                jkey = (it.job_id, tuple(it.metrics))
                zfp = _fp(b"lstm-z", cwin, cmask, err_mu, err_sd, version)
                prev = self._lstm_z_memo.get(jkey)
                if prev is not None and prev[0] == zfp:
                    self._lstm_z_memo.move_to_end(jkey)
                    self.lstm_rescore_skips += 1
                    self._lstm_memo_jobs.add(it.job_id)
                    memo_zs.append((it, prev[1]))
                    continue
                zfp_by_job[jkey] = zfp
            scoreable.append((it, params, err_mu, err_sd, version,
                              cwin, cmask))

        scoreable.extend(self._train_pending(pending))
        for it, cache_key, cwin, cmask in followers:
            entry = self._lstm_cache.get(cache_key)
            if entry is None:
                continue  # the leader's training failed: follower waits too
            params, err_mu, err_sd, version = entry
            scoreable.append((it, params, err_mu, err_sd, version,
                              cwin, cmask))
        if memo_on:
            # freshly trained / follower rows get their score fingerprint
            # recorded too, so the NEXT cycle's unchanged windows memo-hit
            for it, _p, mu_, sd_, version, cwin, cmask in scoreable:
                jkey = (it.job_id, tuple(it.metrics))
                zfp_by_job.setdefault(
                    jkey, _fp(b"lstm-z", cwin, cmask, mu_, sd_, version))
        import itertools

        for (it, z) in itertools.chain(
                memo_zs, self._score_multi_fleet(scoreable)):
            results[(it.job_id, "+".join(it.metrics), "lstm")] = {
                "unhealthy": z > cfg.lstm_threshold,
                "z": z,
            }
            if memo_on:
                jkey = (it.job_id, tuple(it.metrics))
                zfp = zfp_by_job.get(jkey)
                if zfp is not None:
                    self._memo_put(self._lstm_z_memo, jkey, (zfp, z))
        return results

    def _train_pending(self, pending):
        """Train this cycle's budgeted cache-misses, same-shape groups in
        one vmapped loop (lstm_ae.train_fleet: E dispatches for the whole
        group instead of J*E — measured 6.7x for 8 jobs on CPU). Each
        job's sliced params land in the LRU cache exactly like the
        single-job path. Yields scoreable tuples."""
        import jax as _jax

        cfg = self.config
        groups: dict[tuple, list] = {}
        for rec in pending:
            hwin = rec[2]
            groups.setdefault(hwin.shape, []).append(rec)
        def train_one(rec):
            it, cache_key, hwin, hmask, cwin, cmask = rec[:6]
            self.device_launches += 1
            state, tx = lstm_ae.init_state(
                model, _jax.random.PRNGKey(0), T=hwin.shape[1])
            state, _ = lstm_ae.train(
                model, state, tx, hwin, hmask, epochs=cfg.lstm_epochs)
            mu_, sd_ = lstm_ae.fit_score_normalizer(
                state.params, hwin, hmask, model.apply)
            return (state.params, float(mu_), float(sd_))

        for (k, W, F), recs in groups.items():
            model = self._lstm_model(F, unroll=1)  # training: no unroll
            with tracing.span("engine.lstm_train", jobs=len(recs),
                              features=F, window=W):
                trained: list
                if len(recs) == 1:
                    try:
                        trained = [train_one(recs[0])]
                    except Exception:  # noqa: BLE001 - poisoned job skips;
                        trained = [None]  # it retries on a later budget
                else:
                    try:
                        Xh = np.stack([r[2] for r in recs])
                        Mh = np.stack([r[3] for r in recs])
                        self.device_launches += 1
                        pstack, mus, sds = lstm_ae.train_fleet(
                            model, _jax.random.PRNGKey(0), Xh, Mh,
                            epochs=cfg.lstm_epochs)
                        trained = [
                            (_jax.tree.map(lambda a, j=j: a[j], pstack),
                             float(mus[j]), float(sds[j]))
                            for j in range(len(recs))
                        ]
                    except Exception:  # noqa: BLE001 - blast-radius per job
                        # batched training poisoned by one member: retry
                        # per JOB so the healthy majority still trains and
                        # caches this cycle (the _isolate contract); the
                        # offender alone is skipped (its budget slot is
                        # spent — it retries on a later cycle's budget)
                        trained = []
                        for rec in recs:
                            try:
                                trained.append(train_one(rec))
                            except Exception:  # noqa: BLE001
                                trained.append(None)
            for rec, result in zip(recs, trained):
                if result is None:
                    continue
                it, cache_key, _hw, _hm, cwin, cmask = rec[:6]
                params, mu_, sd_ = result
                self._lstm_param_version += 1
                entry = (params, mu_, sd_, self._lstm_param_version)
                self._lstm_cache[cache_key] = entry
                while len(self._lstm_cache) > cfg.max_cache_size:
                    self._lstm_cache.pop(next(iter(self._lstm_cache)))
                train_fp = rec[6] if len(rec) > 6 else None
                if train_fp is not None:
                    # params are shared refs with the LRU cache, so this
                    # index adds no param memory; bound it like the cache
                    self._lstm_train_memo[train_fp] = entry
                    self._lstm_train_memo.move_to_end(train_fp)
                    while len(self._lstm_train_memo) > cfg.max_cache_size:
                        self._lstm_train_memo.popitem(last=False)
                yield (it, params, mu_, sd_, entry[3], cwin, cmask)

    # fleet scoring engages above this group size; smaller groups take the
    # per-job path (rung padding would waste more than it saves)
    _LSTM_FLEET_MIN = 4

    def _score_multi_fleet(self, scoreable):
        """Score collected multi-metric jobs, batching same-shape groups.

        Each job owns its own trained AE params, so a warm fleet's scoring
        was J per-job device dispatches per cycle — the dominant cost of
        the multi family once training is cached. Jobs whose score windows
        share a (F, W, K) shape stack into ONE vmapped launch over a
        stacked parameter pytree (lstm_ae.anomaly_scores_fleet), with the
        job axis padded to the fixed batch rungs so XLA compiles one
        program per (rung, shape) for the life of the process.

        Yields (item, z) pairs.
        """
        import jax as _jax
        import jax.numpy as jnp

        groups: dict[tuple, list] = {}
        for rec in scoreable:
            cwin = rec[5]
            key = (cwin.shape[2], cwin.shape[1], cwin.shape[0])  # (F, W, K)
            groups.setdefault(key, []).append(rec)
        chunk_cap = self._bucket_rows(self.config.score_batch)
        for (F, W, K), recs in groups.items():
            model = self._lstm_model(F)
            if len(recs) < self._LSTM_FLEET_MIN:
                for it, params, mu, sd, _ver, cwin, cmask in recs:
                    self.device_launches += 1
                    z = float(np.max(np.asarray(lstm_ae.anomaly_scores(
                        params, cwin, cmask, mu, sd, model.apply))))
                    yield it, z
                continue
            # chunk like _score_chunks: groups beyond the configured batch
            # cap split into full chunks (pad can never go negative)
            for lo in range(0, len(recs), chunk_cap):
                chunk = recs[lo:lo + chunk_cap]
                J = len(chunk)
                rung = self._bucket_rows(J)
                pad = rung - J
                # stacked-params cache: the stack itself costs ~20x the
                # fleet launch, so reuse it while the member set +
                # versions hold (stable for a warm continuous fleet;
                # rebuilt on retrain, membership change, or rung move).
                # LRU with re-insert on hit, so concurrently-live shape
                # groups cannot evict each other cycle over cycle.
                stack_key = (F, W, K, rung, tuple(r[4] for r in chunk))
                pstack = self._lstm_stack_cache.pop(stack_key, None)
                if pstack is None:
                    self.lstm_stack_rebuilds += 1

                    def stack(leaves):
                        arr = jnp.stack(leaves)
                        if pad:
                            reps = jnp.repeat(arr[-1:], pad, axis=0)
                            arr = jnp.concatenate([arr, reps])
                        return arr

                    pstack = _jax.tree.map(
                        lambda *xs: stack(list(xs)), *[r[1] for r in chunk])
                self._lstm_stack_cache[stack_key] = pstack  # mark recent
                while len(self._lstm_stack_cache) > 32:
                    self._lstm_stack_cache.pop(
                        next(iter(self._lstm_stack_cache)))
                X = np.stack([r[5] for r in chunk])
                M = np.stack([r[6] for r in chunk])
                mus = np.asarray([r[2] for r in chunk], np.float32)
                sds = np.asarray([r[3] for r in chunk], np.float32)
                if pad:
                    X = np.concatenate([X, np.repeat(X[-1:], pad, axis=0)])
                    M = np.concatenate([M, np.repeat(M[-1:], pad, axis=0)])
                    mus = np.concatenate([mus, np.repeat(mus[-1:], pad)])
                    sds = np.concatenate([sds, np.repeat(sds[-1:], pad)])
                self.device_launches += 1
                zs = np.asarray(lstm_ae.anomaly_scores_fleet(
                    pstack, X, M, mus, sds, model.apply))[:J]
                for (it, *_), z in zip(chunk, zs.max(axis=1)):
                    yield it, float(z)

    # ------------------------------------------- LSTM model-cache persistence
    def save_lstm_cache(self, path: str, max_entries: int | None = None) -> int:
        """Persist trained LSTM-AE models (params + score normalizers) so
        a restarted runtime warm-starts instead of re-paying the budgeted
        train-on-miss warm-up for every known app. The reference brain
        kept its model cache in RAM only (MAX_CACHE_SIZE,
        foremast-brain/README.md:30) — every restart retrained the fleet.

        One flax msgpack blob, written atomically (tmp + rename, same
        crash rule as the job snapshot). ``max_entries`` caps the write
        to the most-recent entries in LRU order; the default (None)
        persists the whole cache — it is already bounded by
        MAX_CACHE_SIZE, and a silent lower cap would quietly re-pay the
        warm-up for every app past it after a restart. Returns the
        number of entries written."""
        import json

        import flax.serialization as fser
        import jax

        items = list(self._lstm_cache.items())
        if max_entries is not None and len(items) > max_entries:
            items = items[-max_entries:]
        cfg = self.config
        payload = {
            "format": 1,
            # architecture fingerprint: params from a different geometry
            # must never be offered to this engine's modules
            "arch": {"hidden": cfg.lstm_hidden, "latent": cfg.lstm_latent,
                     "lstm_window": cfg.lstm_window},
            "keys": json.dumps(
                [[k[0], list(k[1]), int(k[2])] for k, _ in items]),
            "mu": np.asarray([e[1] for _, e in items], np.float64),
            "sd": np.asarray([e[2] for _, e in items], np.float64),
        }
        for idx, (_, e) in enumerate(items):
            payload[f"p{idx}"] = jax.device_get(e[0])
        blob = fser.msgpack_serialize(payload)
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        import os

        os.replace(tmp, path)
        return len(items)

    def load_lstm_cache(self, path: str) -> int:
        """Load a save_lstm_cache blob into the warm cache. Absent,
        corrupt, or architecture-mismatched files load 0 entries and
        never raise — a bad cache file must degrade to the ordinary
        cold-start warm-up, not crash startup. Returns entries loaded."""
        import json

        import flax.serialization as fser

        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            return 0
        cfg = self.config
        try:
            payload = fser.msgpack_restore(blob)
            if payload.get("format") != 1:
                return 0
            arch = payload.get("arch") or {}
            if (int(arch.get("hidden", -1)) != cfg.lstm_hidden
                    or int(arch.get("latent", -1)) != cfg.lstm_latent
                    or int(arch.get("lstm_window", -1)) != cfg.lstm_window):
                return 0
            keys = json.loads(payload["keys"])
            mu, sd = payload["mu"], payload["sd"]
            loaded = 0
            for idx, k in enumerate(keys):
                params = payload.get(f"p{idx}")
                if params is None:
                    continue
                key = (str(k[0]), tuple(str(m) for m in k[1]), int(k[2]))
                self._lstm_param_version += 1
                self._lstm_cache[key] = (
                    params, float(mu[idx]), float(sd[idx]),
                    self._lstm_param_version,
                )
                loaded += 1
            while len(self._lstm_cache) > cfg.max_cache_size:
                self._lstm_cache.pop(next(iter(self._lstm_cache)))
            return loaded
        except Exception:  # noqa: BLE001 — corrupt cache file: cold-start
            return 0

    @staticmethod
    def _hpa_rows(items: list[_HpaItem]) -> list:
        """[(job_id, tps_item, sla_item)] — primary (priority 0 / tps-like)
        metric drives the traffic model; an SLA metric (is_increase &
        priority>0) the reward."""
        by_job: dict[str, list[_HpaItem]] = {}
        for it in items:
            by_job.setdefault(it.job_id, []).append(it)
        rows = []
        for job_id, group in by_job.items():
            group.sort(key=lambda it: it.priority)
            tps_it = group[0]
            # SLA metric contract: is_increase (a "more is worse" signal)
            # with priority > 0; fall back to any secondary, then primary
            sla_candidates = [it for it in group[1:] if it.is_increase]
            if sla_candidates:
                sla_it = sla_candidates[0]
            else:
                sla_it = group[1] if len(group) > 1 else group[0]
            rows.append((job_id, tps_it, sla_it))
        return rows

    @staticmethod
    def _hpa_row_T(row) -> int:
        """Pack-length bucket for one HPA row: the max of the job's OWN tps
        and sla series (lengths are data-driven and independent) like every
        other fleet scorer — one global max-T would pad a whole
        heterogeneous fleet to its single longest member (a lone
        7-day-history job would inflate every 2-hour job's scan 128x)."""
        return max(
            bucket_length(
                min(
                    it.historical.values.shape[0]
                    + it.current.values.shape[0],
                    MAX_WINDOW_STEPS,
                )
            )
            for it in (row[1], row[2])
        )

    def _score_hpa(self, items: list[_HpaItem]):
        out = {}
        by_bucket: dict[int, list] = {}
        for row in self._hpa_rows(items):
            by_bucket.setdefault(self._hpa_row_T(row), []).append(row)
        for T, bucket_rows in by_bucket.items():
            out.update(self._collect_hpa(self._launch_hpa(bucket_rows, T)))
        return out

    def _launch_hpa(self, rows, T: int):
        """Pack + launch one pack-length bucket of HPA jobs."""

        def build(it):
            vals, mask, n_h = _concat_trimmed(it.historical, it.current)
            region = np.zeros(T, bool)
            region[n_h : vals.shape[0]] = True
            # carry the series' own step: a non-default-step job must not
            # silently snap back to the 60 s DEFAULT_STEP
            return Window(vals, mask, it.historical.start,
                          it.historical.step), region

        tps_w, regions = zip(*[build(t) for _, t, _ in rows])
        sla_w = [build(s)[0] for _, _, s in rows]
        tv, tm = pack_windows(list(tps_w), pad_to=T)
        sv, sm = pack_windows(list(sla_w), pad_to=T)
        reg = np.stack(list(regions))

        # per-job SLA criteria (dynamic_autoscaling.md:45-56): mode from
        # ML_SLA_MODE, limit from the SLA metric's policy (sla_limit{N})
        # falling back to ML_SLA_LIMIT; a static/min mode with no limit
        # configured degrades to dynamic (there is nothing static to hold
        # the metric against), never to a fake 1e9 "static" limit that
        # would make SLA_MIN collapse to dynamic silently.
        mode_cfg = {"static": hpa_ops.SLA_STATIC, "min": hpa_ops.SLA_MIN}.get(
            self.config.sla_mode, hpa_ops.SLA_DYNAMIC)
        limits = np.empty(len(rows), np.float32)
        modes = np.empty(len(rows), np.int32)
        absolutes = np.empty(len(rows), bool)
        pods_now = np.ones(len(rows), np.float32)
        pods_hist = np.ones(len(rows), np.float32)
        had_pods = [False] * len(rows)
        for i, (_job_id, tps_it, sla_it) in enumerate(rows):
            lim = self.config.policy_for(sla_it.metric).sla_limit
            if lim <= 0.0:
                lim = self.config.sla_limit
            if lim <= 0.0:
                limits[i], modes[i] = 1e9, hpa_ops.SLA_DYNAMIC
            else:
                limits[i], modes[i] = lim, mode_cfg
            # limit interpretation: ABSOLUTE (the deploy convention quotes
            # latency SLAs in ms) unless the operator opts the fleet into
            # relative limits (ML_SLA_LIMIT_RELATIVE); a wire
            # isAbsolute=true still pins that metric absolute under the
            # relative default. The bare wire default (false) must NOT
            # silently turn ML_SLA_LIMIT=250ms into 250*mean.
            absolutes[i] = (sla_it.is_absolute
                            or not self.config.sla_limit_relative)
            # pod counts split at the job's own current-window boundary —
            # the exact region/history split the demand and capacity use
            pc = _pod_count_stats(tps_it.pod_window, tps_it.current.start)
            if pc is not None:
                pods_now[i], pods_hist[i] = pc
                had_pods[i] = True

        def hpa_fn(tv_c, tm_c, reg_c, sv_c, sm_c, lim_c, mode_c, abs_c,
                   pn_c, ph_c):
            n = tv_c.shape[0]
            hist_mask = tm_c & ~reg_c
            preds = np.asarray(
                fc.ses_predictions(tv_c, hist_mask, np.full(n, 0.3, np.float32))
            )
            sigma = np.asarray(fc.residual_sigma(tv_c, preds, hist_mask, ~reg_c))
            return hpa_ops.hpa_scores(
                tv_c, tm_c, reg_c, preds, sigma, sv_c, sm_c,
                lim_c, mode_c,
                np.full(n, self.config.threshold, np.float32),
                np.full(n, self.config.sla_headroom_safe, np.float32),
                pods_now=pn_c, pods_hist=ph_c, sla_absolute=abs_c,
            )

        launches = self._launch_chunks(
            hpa_fn,
            [tv, tm, reg, sv, sm, limits, modes, absolutes,
             pods_now, pods_hist],
        )
        return (rows, launches, had_pods)

    def _collect_hpa(self, state) -> dict:
        rows, launches, had_pods = state
        res = self._collect_chunks(launches)
        # bulk tolist (see _collect_pairs); int()/float() coercions kept
        # where the kernel dtype is not already the Python target type
        lists = {k: res[k].tolist() for k in (
            "score", "reason", "current_tps", "tps_upper", "tps_lower",
            "sla_current", "sla_limit", "pods_now", "demand_per_pod")}
        out: dict = {}
        for i, (job_id, tps_it, sla_it) in enumerate(rows):
            out[job_id] = {
                "raw_score": float(lists["score"][i]),
                "reason_code": int(lists["reason"][i]),
                "tps_metric": tps_it.metric,
                "sla_metric": sla_it.metric,
                "current_tps": float(lists["current_tps"][i]),
                "upper": float(lists["tps_upper"][i]),
                "lower": float(lists["tps_lower"][i]),
                "sla_current": float(lists["sla_current"][i]),
                "sla_limit": float(lists["sla_limit"][i]),
                "pods_now": float(lists["pods_now"][i]),
                "demand_per_pod": float(lists["demand_per_pod"][i]),
                "has_pod_data": had_pods[i],
            }
        return out

    # ------------------------------------------------------------- verdict
    def _serve_stale(self, doc: J.Document, failure: str, worker: str,
                     now: float, in_postprocess: bool = False) -> str | None:
        """Re-serve a warm job's last fresh verdict during a source outage.

        A job is warm when it was judged healthy on FRESH data at most
        MAX_STALE_S ago. Serving means: mid-window, requeue with the
        staleness age stamped in the reason (no PREPROCESS_FAILED flap);
        past endTime, complete COMPLETED_HEALTH on the last fresh verdict
        instead of flipping COMPLETED_UNKNOWN. Unhealthy verdicts are
        never stale-served — they complete terminally the cycle they are
        seen, so a live job's last verdict is always "healthy so far".
        Returns the applied status, or None when the job is not warm
        (callers fall through to the pre-degraded-mode behavior).
        """
        max_stale = self.config.max_stale_seconds
        at = self._stale_state.get(doc.id)
        if max_stale <= 0 or at is None or now - at > max_stale:
            return None
        age = now - at
        self.stale_verdicts_served_total += 1
        self.exporter.record_counter(
            "foremastbrain:stale_verdicts_served_total", {},
            help="verdicts re-served from warm state during source "
                 "outages (bounded by MAX_STALE_S)")
        reason = (f"stale verdict served (age {age:.0f}s, last judged "
                  f"healthy): {failure}")
        self.flight.record_event(flightrec.EVENT_STALE_SERVE,
                                 job_id=doc.id, age=round(age, 1))
        try:
            end_time = from_rfc3339(doc.end_time)
        except (ValueError, TypeError):
            end_time = (float("inf")
                        if doc.strategy in CONTINUOUS_STRATEGIES else now)
        if doc.strategy not in CONTINUOUS_STRATEGIES and now >= end_time:
            # the watch window closed during the outage: the job watched
            # healthy right up to the blackout, and the last fresh verdict
            # is younger than MAX_STALE_S — complete on it
            if not in_postprocess:
                self.store.advance(doc.id, J.PREPROCESS_COMPLETED,
                                   J.POSTPROCESS_INPROGRESS, worker=worker)
            self._stale_state.pop(doc.id, None)
            self.provenance.record(
                doc.id, prov.PATH_STALE_SERVED, status=J.COMPLETED_HEALTH,
                detail=f"age {age:.0f}s", reason=reason)
            self.store.transition(doc.id, J.COMPLETED_HEALTH, reason=reason,
                                  worker=worker,
                                  processing_content=self._prov_content(doc.id))
            return J.COMPLETED_HEALTH
        self.provenance.record(
            doc.id, prov.PATH_STALE_SERVED, status=J.INITIAL,
            detail=f"age {age:.0f}s", reason=reason)
        self.store.transition(doc.id, J.INITIAL, reason=reason, worker=worker)
        return J.INITIAL

    def _record_scoring_failure(self, job_id: str, now: float):
        """Quarantine bookkeeping for one _isolate per-job retry failure.

        QUARANTINE_AFTER consecutive failures park the job; each parking
        doubles the re-admission backoff (QUARANTINE_BASE_S..MAX). A job
        that was quarantined before re-parks on its FIRST post-probe
        failure — the probe answered the only open question."""
        qa = self.config.quarantine_after
        if qa <= 0:
            return
        q = self._quarantine.setdefault(job_id, [0, 0.0, 0])
        q[0] += 1
        if q[2] > 0 or q[0] >= qa:
            q[2] += 1
            q[0] = 0
            delay = min(QUARANTINE_BASE_S * (2.0 ** (q[2] - 1)),
                        QUARANTINE_MAX_S)
            q[1] = now + delay
            self.jobs_quarantined_total += 1
            self.flight.record_event(flightrec.EVENT_QUARANTINE,
                                     job_id=job_id, delay_s=delay,
                                     times=q[2])
            self.exporter.record_counter(
                "foremastbrain:jobs_quarantined_total", {},
                help="poison-job quarantine parkings (QUARANTINE_AFTER "
                     "consecutive scoring failures)")

    def run_cycle(self, worker: str = "worker-0", now: float | None = None,
                  job_ids=None, partial: bool = False) -> dict:
        """One engine cycle. Returns {job_id: new_status} for observability.

        ``job_ids``/``partial`` are the event-driven scheduler's seam
        (engine/scheduler.py StreamScheduler): a PARTIAL cycle claims
        only the named jobs — the ones whose windows just advanced via
        push ingest — and runs them through the identical pipeline
        rungs, so a partial cycle's verdicts are exactly the ones the
        next full sweep would have produced, just earlier. Partial and
        full cycles share this entry point and must never run
        concurrently (the scheduler serializes them on one thread)."""
        # cycle correlation id: bound into the tracer BEFORE the cycle
        # span opens, so the span's attrs, every cross-thread child span,
        # every log record (TraceContextFilter), and every provenance
        # record of this cycle carry the same grep-able id. Partial
        # cycles mint `-p` ids so a grep separates the two cycle kinds.
        self._cycle_seq += 1
        cycle_id = f"{worker}-{'p' if partial else 'c'}{self._cycle_seq}"
        self.current_cycle_id = cycle_id
        t_cycle0 = time.perf_counter()
        self._cycle_mono0 = time.monotonic()
        self._cycle_fold_mono = 0.0
        # a partial cycle triggered by ONE push adopts that push's W3C
        # context: its engine.cycle span (and every child) continues the
        # push's distributed trace instead of minting its own. Bursts
        # spanning several traces keep their own root — each job's
        # verdict span still closes its own push trace.
        remote_ctx = (self.waterfall.single_context(job_ids)
                      if partial and job_ids else None)
        with tracing.tracer.bind(cycle_id=cycle_id), \
                tracing.tracer.adopt_remote(remote_ctx), \
                tracing.span(tracing.SPAN_ENGINE_CYCLE, worker=worker):
            now = time.time() if now is None else now
            self.provenance.begin_cycle(cycle_id, worker=worker)
            # degraded mode: the whole-cycle deadline budget
            # (CYCLE_DEADLINE_S). Burns down through fetch -> preprocess ->
            # dispatch; once expired, un-preprocessed jobs are shed in
            # reverse priority order and carried to the next cycle.
            cd = self.config.cycle_deadline_seconds
            cycle_dl = Deadline.after(cd) if cd > 0 else None
            # resilience: arm a per-cycle fetch deadline so retry/backoff
            # trains inside a ResilientDataSource can never overrun the
            # cycle budget (every fetch thread shares the one Deadline;
            # plain sources have no set_cycle_deadline and skip this)
            sd = getattr(self.source, "set_cycle_deadline", None)
            budget = self.config.fetch_cycle_deadline_seconds
            fetch_dl = Deadline.after(budget) if budget > 0 else None
            if cycle_dl is not None:
                # the fetch retry train must never outlive the CYCLE budget
                fetch_dl = (cycle_dl if fetch_dl is None
                            else Deadline(min(fetch_dl.at, cycle_dl.at)))
            if sd is not None:
                sd(fetch_dl)
            self.health.begin_cycle()
            try:
                outcomes = self._run_cycle(worker, now, cycle_dl,
                                           job_ids=job_ids, partial=partial)
            finally:
                if sd is not None:
                    sd(None)
            # end_cycle only on SUCCESS: a raising cycle must not refresh
            # the liveness reference, so a crash-looping engine (worker
            # loop swallows and retries) ages into STALLED instead of
            # reporting OK on zero completed verdicts. The per-cycle
            # deltas come straight from the cycle stats _run_cycle just
            # published — ONE computation feeds /status and the health
            # machine, so the two surfaces can never drift.
            stats = self.last_cycle_stages
            self.health.end_cycle(
                shed=stats.get("jobs_shed", 0),
                stale_served=stats.get("stale_verdicts_served", 0),
                watchdog_fires=stats.get("watchdog_fires", 0),
                quarantined=self.quarantined_count(now),
                deadline_overrun=(cycle_dl is not None
                                  and cycle_dl.expired()),
            )
            # cycle-duration distribution (p50/p99 on /metrics — the
            # last-cycle stage gauges alone can't answer tail questions)
            self.exporter.record_histogram(
                "foremastbrain:cycle_seconds", {},
                time.perf_counter() - t_cycle0,
                help="End-to-end engine cycle duration (seconds).")
            return outcomes

    def _job_priority(self, doc: J.Document) -> tuple:
        """Load-shedding sort key: lower scores FIRST.

        New-deployment analyses (rollingUpdate/canary/rollover) lead —
        their verdict gates a live rollout, and they are exempt from
        shedding entirely (_stream_prep's class gate); steady-state
        monitors (continuous/hpa) watch forever and can carry a cycle.
        Within the monitor class, a job shed on recent cycles sorts
        ahead, so a permanently blown budget round-robins the fleet
        instead of starving the tail.
        """
        return (1 if doc.strategy in CONTINUOUS_STRATEGIES else 0,
                -self._shed_streak.get(doc.id, 0))

    def _stream_prep(self, claimed: list, now: float,
                     deadline: Deadline | None = None):
        """Yield (doc_id, items, failed, fetch_notes) per job, in claim
        order, as the fetch pool completes chunks. `fetch_notes` is the
        tracer's per-job fetch accounting (delta/full/cached counts,
        points, seconds) for the provenance record; shed jobs yield
        `(doc.id, None, _SHED, {})`.

        Per-job fetches overlap on a bounded pool: fetch is network-bound
        in production (and the native parser releases the GIL during its C
        scan), so cycle time tracks store latency, not fleet size. Jobs are
        mapped in CHUNKS (several per worker for tail-balance) — at 10k+
        fleet sizes, per-job task dispatch costs more GIL time than the
        preprocess itself. ex.map preserves submission order, and chunks
        are cut in claim order, so the yielded stream — and with it bucket
        packing and verdict folding — stays deterministic; consuming it
        incrementally is what lets the pipeline dispatch bucket N while
        bucket N+1 is still fetching.

        `deadline` is the cycle budget (CYCLE_DEADLINE_S): once expired,
        STEADY-STATE jobs (continuous/hpa) not yet fetched yield the
        _SHED marker WITHOUT touching the network and carry over to the
        next cycle. New-deployment analyses are never shed — their
        verdict gates a live rollout, and because chunks run concurrently
        on the fetch pool, a class-based gate is the only one that holds
        under interleaving (a position-based cutoff could shed a canary
        while monitors on other workers complete). A canary-heavy
        overrun therefore shows as `deadline_overrun`, not shedding. The
        first MONITOR-class job of the cycle is additionally exempt — the
        guaranteed-progress floor. It must be the first SHEDDABLE job,
        not claimed[0]: in a mixed fleet the sort puts a (class-exempt)
        canary first, and guaranteeing that one would leave monitors with
        no floor at all — permanently starved whenever deployment churn
        alone burns the budget. The sort puts the longest-shed monitor at
        the head of its class, so the floor round-robins the fleet.
        """
        guaranteed = next(
            (d.id for d in claimed if d.strategy in CONTINUOUS_STRATEGIES),
            None)
        # trace-context handle captured on the cycle thread: every fetch
        # pool worker attaches it, so spans opened during preprocess parent
        # under the cycle trace and dataplane log lines carry cycle_id —
        # the PR 2 thread pool no longer orphans its spans
        ctx = tracing.tracer.context()

        def prep_many(chunk):
            out = []
            with tracing.tracer.attach(ctx):
                for doc in chunk:
                    if (deadline is not None and doc.id != guaranteed
                            and doc.strategy in CONTINUOUS_STRATEGIES
                            and deadline.expired()):
                        out.append((doc.id, None, _SHED, {}))
                        continue
                    with tracing.tracer.bind(job_id=doc.id):
                        tracing.tracer.begin_notes()
                        try:
                            items = self._preprocess(doc, now)
                            out.append((doc.id, items, "",
                                        tracing.tracer.take_notes()))
                        except FetchError as e:
                            out.append((doc.id, None, str(e),
                                        tracing.tracer.take_notes()))
            return out

        workers = min(max(self.config.fetch_concurrency, 1), len(claimed) or 1)
        if workers <= 1:
            yield from prep_many(claimed)
            return
        step = max(1, -(-len(claimed) // (workers * 8)))
        chunks = [claimed[i:i + step]
                  for i in range(0, len(claimed), step)]
        with ThreadPoolExecutor(max_workers=workers) as ex:
            for rs in ex.map(prep_many, chunks):
                yield from rs

    def _run_cycle(self, worker: str, now: float,
                   cycle_dl: Deadline | None = None, job_ids=None,
                   partial: bool = False) -> dict:
        from .pipeline import CyclePipeline

        with tracing.span("engine.claim"):
            claimed = self.store.claim_open_jobs(
                worker,
                limit=self.config.max_claim_per_cycle,
                max_stuck_seconds=self.config.max_stuck_seconds,
                owns_fn=self.shard.owns if self.shard is not None else None,
                only_ids=set(job_ids) if job_ids is not None else None,
            )
        outcomes: dict[str, str] = {}
        if self._quarantine:
            # poison-job quarantine gate: parked jobs requeue untouched —
            # not one fetch, not one _isolate retry — until their
            # re-admission time; everyone else proceeds normally
            admitted = []
            for doc in claimed:
                q = self._quarantine.get(doc.id)
                if q is not None and now < q[1]:
                    self.provenance.record(
                        doc.id, prov.PATH_QUARANTINED, status=J.INITIAL,
                        detail=(f"re-admission in {q[1] - now:.0f}s, "
                                f"parked {q[2]}x"))
                    self.store.transition(
                        doc.id, J.INITIAL, worker=worker,
                        reason=(f"quarantined: scoring poisoned; "
                                f"re-admission in {q[1] - now:.0f}s"))
                    outcomes[doc.id] = J.INITIAL
                else:
                    admitted.append(doc)
            claimed = admitted
        # priority order (stable, so claim order breaks ties): deployment
        # canaries score first; steady-state monitors shed first when the
        # cycle deadline burns down
        if cycle_dl is not None:
            claimed.sort(key=self._job_priority)
        states: dict[str, _JobState] = {}
        all_pairs: list[_PairItem] = []
        all_bands: list[_BandItem] = []
        all_bis: list[_BiItem] = []
        all_multis: list[_MultiItem] = []
        all_hpas: list[_HpaItem] = []
        self._lstm_trained_this_cycle = 0
        self._lstm_budget_skipped_ids = set()
        self._lstm_memo_jobs = set()
        launches0 = self.device_launches
        mega_l0 = self.megabatch_launches_total
        mega_r0 = self.megabatch_real_rows_total
        mega_p0 = self.megabatch_pad_rows_total
        rescore_skips0 = self.lstm_rescore_skips
        shed_cycle0 = self.jobs_shed_total
        stale_cycle0 = self.stale_verdicts_served_total
        wd_cycle0 = self.watchdog_fires_total
        pipe = CyclePipeline(self) if self.config.score_pipeline else None
        stages = {"preprocess": 0.0, "dispatch": 0.0, "collect": 0.0,
                  "fold": 0.0}
        with tracing.span(tracing.SPAN_ENGINE_PREPROCESS, jobs=len(claimed)):
            for doc in claimed:
                states[doc.id] = _JobState(doc)
            t_wait = time.perf_counter()
            for doc_id, items, failed, fetch_notes in self._stream_prep(
                    claimed, now, cycle_dl):
                stages["preprocess"] += time.perf_counter() - t_wait
                if fetch_notes:
                    states[doc_id].fetch = fetch_notes
                if failed:
                    states[doc_id].failed = failed
                else:
                    # detection-latency stamps: the job was freshly
                    # ingested this cycle, and its window last advanced
                    # at the newest judged sample's own timestamp
                    # (engine/slo.py; _observe_latency)
                    states[doc_id].ingest_at = time.monotonic()
                    states[doc_id].newest_ts = self._newest_sample_ts(items)
                    pairs, bands, bis, multis, hpas = items
                    all_pairs += pairs
                    all_bands += bands
                    all_bis += bis
                    all_multis += multis
                    all_hpas += hpas
                    if pipe is not None:
                        # streamed dispatch: full bucket rungs launch here,
                        # overlapping the remaining fetches (the pipeline
                        # accounts its own dispatch time)
                        pipe.feed(pairs, bands, bis, multis, hpas,
                                  strategy=states[doc_id].doc.strategy)
                t_wait = time.perf_counter()
        shed_ids: list = []
        for doc_id, st in states.items():
            if not st.failed:
                self._shed_streak.pop(doc_id, None)
                self.store.advance(doc_id, J.PREPROCESS_COMPLETED,
                                   J.POSTPROCESS_INPROGRESS, worker=worker)
                continue
            doc = st.doc
            if st.failed == _SHED:
                # load shedding (CYCLE_DEADLINE_S): the budget burned down
                # before this job's fetch started. Carry it to the next
                # cycle — the shed streak promotes it within its class, so
                # it completes with a verdict byte-identical to the one it
                # would have produced unshed (tests/test_degraded.py).
                self.jobs_shed_total += 1
                self._shed_streak[doc_id] = self._shed_streak.get(doc_id, 0) + 1
                shed_ids.append(doc_id)
                self.provenance.record(
                    doc_id, prov.PATH_SHED_CARRYOVER, status=J.INITIAL,
                    detail=f"streak {self._shed_streak[doc_id]}")
                self.exporter.record_counter(
                    "foremastbrain:jobs_shed_total", {},
                    help="jobs shed by the cycle deadline budget and "
                         "carried to the next cycle")
                self.store.transition(
                    doc_id, J.INITIAL, worker=worker,
                    reason="shed: cycle deadline budget exhausted; "
                           "carried over")
                outcomes[doc_id] = J.INITIAL
                continue
            # real fetch failure (retries exhausted / breaker open /
            # garbage body): a warm job re-serves its last fresh verdict
            # instead of flapping (stale-verdict serving, MAX_STALE_S)
            served = self._serve_stale(doc, st.failed, worker, now)
            if served is not None:
                outcomes[doc_id] = served
            elif doc.strategy in CONTINUOUS_STRATEGIES:
                # perpetual jobs survive transient fetch errors: requeue
                # instead of dying terminally on one network blip
                self.provenance.record(
                    doc_id, prov.PATH_FETCH_RETRY, status=J.INITIAL,
                    reason=st.failed, fetch=st.fetch)
                self.store.transition(
                    doc_id, J.INITIAL, reason=f"fetch retry: {st.failed}",
                    worker=worker,
                )
                outcomes[doc_id] = J.INITIAL
            else:
                self.provenance.record(
                    doc_id, prov.PATH_NO_DATA, status=J.PREPROCESS_FAILED,
                    reason=st.failed, fetch=st.fetch)
                self.store.transition(
                    doc_id, J.PREPROCESS_FAILED, reason=st.failed,
                    worker=worker,
                    processing_content=self._prov_content(doc_id))
                outcomes[doc_id] = J.PREPROCESS_FAILED
        if shed_ids:
            self.flight.record_event(flightrec.EVENT_SHED,
                                     count=len(shed_ids),
                                     jobs=shed_ids[:16])

        live = {k: v for k, v in states.items() if not v.failed}
        fam_seconds: dict[str, float] = {}
        with tracing.span(tracing.SPAN_ENGINE_SCORE, pairs=len(all_pairs),
                          bands=len(all_bands), bis=len(all_bis),
                          multis=len(all_multis), hpas=len(all_hpas)):
            if pipe is not None:
                (pair_res, band_res, bi_res, multi_res, hpa_res,
                 scoring_failed) = pipe.finish()
                for k, v in pipe.stage_seconds.items():
                    stages[k] += v
                fam_seconds = pipe.family_seconds
                # the bench's per-family decomposition reads these stats
                # (engine.score.<fam>), span or not
                for fam in ("pair", "band", "bivariate", "hpa"):
                    tracing.tracer.add_timing(
                        tracing.SCORE_SPANS[fam], fam_seconds.get(fam, 0.0))
            else:
                # barriered fallback (SCORE_PIPELINE=0): one child span per
                # model family, families strictly sequential
                def timed(fam, score_fn, items, attrs_fn=None):
                    with tracing.span(tracing.SCORE_SPANS[fam],
                                      n=len(items)) as sp:
                        t0 = time.perf_counter()
                        res = self._isolate(score_fn, items)
                        fam_seconds[fam] = time.perf_counter() - t0
                        if attrs_fn is not None:
                            attrs_fn(sp)
                        return res

                pair_res, pair_bad = timed("pair", self._score_pairs, all_pairs)
                band_res, band_bad = timed("band", self._score_bands, all_bands)
                bi_res, bi_bad = timed("bivariate", self._score_bivariate, all_bis)
                multi_res, multi_bad = timed(
                    "lstm", self._score_multi, all_multis,
                    attrs_fn=lambda sp: sp.attrs.__setitem__(
                        "budget_skips", len(self._lstm_budget_skipped_ids)))
                hpa_res, hpa_bad = timed("hpa", self._score_hpa, all_hpas)
                scoring_failed = {**pair_bad, **band_bad, **bi_bad,
                                  **multi_bad, **hpa_bad}
                stages["collect"] += sum(fam_seconds.values())
            self.lstm_budget_skips += len(self._lstm_budget_skipped_ids)

        t_fold = time.perf_counter()
        # waterfall boundary: everything before this instant is the
        # `score` stage, everything after is `fold` (_observe_latency)
        self._cycle_fold_mono = time.monotonic()
        # -- provenance collection (zero work when recording is off) --
        # per-family score-vs-threshold entries and judged-result counts
        # per job; counts vs the pipeline's memo-hit map classify each
        # verdict as fresh-scored or memo-served.
        prov_on = self.provenance.enabled
        fam_entries: dict[str, list] = {}
        judged_items: dict[str, int] = {}
        memo_job_hits = pipe.memo_job_hits if pipe is not None else {}
        triage_gate = pipe.triage if pipe is not None else None
        triage_job_hits = triage_gate.job_hits if triage_gate is not None \
            else {}
        # per-result screen statistics for cleared rows, keyed by the
        # family result key — folded into the provenance family entries so
        # `explain` shows the screen's numbers vs its thresholds
        triage_stats = triage_gate.stats if triage_gate is not None else {}

        # a partial (event-driven) cycle's fresh scores carry their own
        # path tag: `explain` answers "did this verdict wait for the
        # tick, or did the push wake it?" without cycle-id archaeology
        scored_path = prov.PATH_STREAM_SCORED if partial \
            else prov.PATH_SCORED

        def _vpath(job_id: str) -> tuple:
            """(path, detail) for a judged job: memo-hit when EVERY result
            came from the fingerprint memo, triaged when the tier-0
            screen cleared the rest, scored otherwise."""
            n = judged_items.get(job_id, 0)
            m = memo_job_hits.get(job_id, 0) + (
                1 if job_id in self._lstm_memo_jobs else 0)
            t = triage_job_hits.get(job_id, 0)
            if n and m >= n:
                return prov.PATH_MEMO_HIT, f"{m}/{n} results from memo"
            if n and t and m + t >= n:
                detail = f"{t}/{n} screened clear"
                if m:
                    detail += f", {m} memo"
                return prov.PATH_TRIAGED, detail
            if t:
                return (scored_path,
                        f"{n - m - t}/{n} fresh, {m} memo, {t} triaged")
            if m:
                return scored_path, f"{n - m}/{n} fresh, {m} memo"
            return scored_path, ""

        # fold per-metric results into per-job verdicts
        for it in all_pairs:
            r = pair_res.get((it.job_id, it.metric, "pair"))
            if r is None:
                continue
            st = live[it.job_id]
            st.judged_any = True
            if prov_on:
                judged_items[it.job_id] = judged_items.get(it.job_id, 0) + 1
                entry = {
                    "family": "pair", "metric": it.metric,
                    "min_p": round(r["min_p"], 8),
                    "alpha": self.config.pairwise_threshold,
                    "unhealthy": bool(r["unhealthy"])}
                entry.update(triage_stats.get(
                    (it.job_id, it.metric, "pair"), {}))
                fam_entries.setdefault(it.job_id, []).append(entry)
            if r["unhealthy"]:
                causes = []
                if r["pairwise_unhealthy"]:
                    causes.append(f"pairwise rejection p={r['min_p']:.2e}")
                if r["band_unhealthy"]:
                    causes.append(
                        f"{r['band_count']} points outside the baseline band"
                    )
                st.unhealthy.append((it.metric, "; ".join(causes), []))
        for it in all_bands:
            r = band_res.get((it.job_id, it.metric, "band"))
            if r is None:
                continue
            st = live[it.job_id]
            st.judged_any = True
            if prov_on:
                judged_items[it.job_id] = judged_items.get(it.job_id, 0) + 1
                entry = {
                    "family": "band", "metric": it.metric,
                    "anomalous_points": int(r["count"]),
                    "band": [round(r["lower"], 4), round(r["upper"], 4)],
                    "unhealthy": bool(r["unhealthy"])}
                entry.update(triage_stats.get(
                    (it.job_id, it.metric, "band"), {}))
                fam_entries.setdefault(it.job_id, []).append(entry)
            self.exporter.record_bounds(
                st.doc.app_name, st.doc.namespace, it.metric,
                r["upper"], r["lower"], float(r["unhealthy"]),
            )
            if r["unhealthy"]:
                st.unhealthy.append(
                    (
                        it.metric,
                        f"{r['count']} points outside "
                        f"[{r['lower']:.4g},{r['upper']:.4g}] from ts {r['first_ts']:.0f}",
                        r["anomaly_pairs"],
                    )
                )
        for it in all_bis:
            r = bi_res.get((it.job_id, "&".join(it.metrics), "bivariate"))
            if r is None:
                continue
            st = live[it.job_id]
            st.judged_any = True
            if prov_on:
                judged_items[it.job_id] = judged_items.get(it.job_id, 0) + 1
                entry = {
                    "family": "bivariate", "metric": "&".join(it.metrics),
                    "anomalous_points": int(r["count"]),
                    "unhealthy": bool(r["unhealthy"])}
                entry.update(triage_stats.get(
                    (it.job_id, "&".join(it.metrics), "bivariate"), {}))
                fam_entries.setdefault(it.job_id, []).append(entry)
            for metric, (upper, lower) in r["bounds"].items():
                self.exporter.record_bounds(
                    st.doc.app_name, st.doc.namespace, metric,
                    upper, lower, float(r["unhealthy"]),
                )
            if r["unhealthy"]:
                st.unhealthy.append(
                    (
                        "&".join(it.metrics),
                        f"{r['count']} points outside the joint "
                        f"bivariate-normal ellipse from ts {r['first_ts']:.0f}",
                        r["anomaly_pairs"],
                    )
                )
        for it in all_multis:
            r = multi_res.get((it.job_id, "+".join(it.metrics), "lstm"))
            if r is None:
                continue
            st = live[it.job_id]
            st.judged_any = True
            if prov_on:
                judged_items[it.job_id] = judged_items.get(it.job_id, 0) + 1
                fam_entries.setdefault(it.job_id, []).append({
                    "family": "lstm", "metric": "+".join(it.metrics),
                    "z": round(float(r["z"]), 4),
                    "threshold": self.config.lstm_threshold,
                    "unhealthy": bool(r["unhealthy"])})
            if r["unhealthy"]:
                st.unhealthy.append(
                    (
                        "+".join(it.metrics),
                        f"LSTM-AE reconstruction z={r['z']:.2f} exceeds "
                        f"{self.config.lstm_threshold:.1f}",
                        [],
                    )
                )
        if prov_on:
            # hpa results fold inside _finish_hpa; count them here so the
            # memo-vs-fresh classification sees them like every family
            for job_id in hpa_res:
                if job_id in live:
                    judged_items[job_id] = judged_items.get(job_id, 0) + 1

        for job_id, st in live.items():
            doc = st.doc
            if job_id in scoring_failed:
                reason = f"scoring failed: {scoring_failed[job_id]}"
                if scoring_failed[job_id].startswith("WatchdogTimeout"):
                    # watchdog fires are INFRASTRUCTURE evidence (a hung
                    # or wedged device), not job poison: every strategy
                    # requeues for the next cycle — quarantining the job
                    # (or aborting a canary) would misattribute the
                    # device's fault to the workload and blank coverage
                    # long after the device recovers
                    self.provenance.record(
                        job_id, prov.PATH_WATCHDOG_FAILOVER,
                        status=J.INITIAL, reason=reason, fetch=st.fetch)
                    self.store.transition(
                        job_id, J.INITIAL, reason=reason, worker=worker)
                    outcomes[job_id] = J.INITIAL
                    continue
                if doc.strategy in CONTINUOUS_STRATEGIES:
                    # perpetual jobs retry next cycle (data may heal) —
                    # but a job that keeps poisoning its per-job retry is
                    # parked (quarantine) instead of re-burning the
                    # _isolate fallback every cycle forever
                    self._record_scoring_failure(job_id, now)
                    self.provenance.record(
                        job_id, prov.PATH_BLAST_RADIUS, status=J.INITIAL,
                        reason=reason, fetch=st.fetch)
                    self.store.transition(job_id, J.INITIAL, reason=reason, worker=worker)
                    outcomes[job_id] = J.INITIAL
                else:
                    self._quarantine.pop(job_id, None)  # terminal: moot
                    self.provenance.record(
                        job_id, prov.PATH_BLAST_RADIUS, status=J.ABORT,
                        reason=reason, fetch=st.fetch)
                    self.store.transition(
                        job_id, J.ABORT, reason=reason, worker=worker,
                        processing_content=self._prov_content(job_id))
                    outcomes[job_id] = J.ABORT
                continue
            # scored cleanly: full quarantine reset (consecutive = 0)
            self._quarantine.pop(job_id, None)
            if doc.strategy == STRATEGY_HPA:
                res = hpa_res.get(job_id)
                outcomes[job_id] = self._finish_hpa(
                    st, res, worker, now,
                    path_info=_vpath(job_id) if prov_on else None)
                if res is not None:
                    # a scored hpa cycle IS the detection; annotates the
                    # record _finish_hpa just wrote
                    self._observe_latency(st, now)
                continue
            try:
                end_time = from_rfc3339(doc.end_time)
            except (ValueError, TypeError):
                # continuous jobs carry END_TIME placeholders: never expire
                end_time = float("inf") if doc.strategy in CONTINUOUS_STRATEGIES else now
            if st.unhealthy:
                metrics = ", ".join(dict.fromkeys(m for m, _, _ in st.unhealthy))
                reason = "; ".join(f"{m}: {d}" for m, d, _ in st.unhealthy)
                anomaly = {m: pairs for m, _, pairs in st.unhealthy if pairs}
                self._stale_state.pop(job_id, None)
                reason = f"anomaly detected on {metrics} :: {reason}"
                if prov_on:
                    path, detail = _vpath(job_id)
                    self.provenance.record(  # lint: disable=trace-registry -- path from _vpath (registered constants only)
                        job_id, path, status=J.COMPLETED_UNHEALTH,
                        detail=detail, reason=reason,
                        families=fam_entries.get(job_id),
                        fetch=st.fetch)
                # observed between record and transition: the latency
                # annotation must land before the summary is attached
                self._observe_latency(st, now)
                self.store.transition(
                    job_id, J.COMPLETED_UNHEALTH,
                    reason=reason,
                    anomaly=anomaly, worker=worker,
                    processing_content=self._prov_content(job_id),
                )
                outcomes[job_id] = J.COMPLETED_UNHEALTH
            elif now < end_time:
                # healthy so far; keep watching until endTime (fail-fast
                # rule); continuous jobs loop here forever. A judged cycle
                # refreshes the job's warm stale-serving state.
                if st.judged_any:
                    self._stale_state[job_id] = now
                if prov_on and st.judged_any:
                    path, detail = _vpath(job_id)
                    self.provenance.record(  # lint: disable=trace-registry -- path from _vpath (registered constants only)
                        job_id, path, status=J.INITIAL, detail=detail,
                        families=fam_entries.get(job_id), fetch=st.fetch)
                if st.judged_any:
                    # "healthy so far" is a verdict too: the monitor fleet's
                    # steady-state latency is exactly this path
                    self._observe_latency(st, now)
                self.store.requeue(job_id, worker=worker)
                outcomes[job_id] = J.INITIAL
            elif st.judged_any:
                self._stale_state.pop(job_id, None)
                if prov_on:
                    path, detail = _vpath(job_id)
                    self.provenance.record(  # lint: disable=trace-registry -- path from _vpath (registered constants only)
                        job_id, path, status=J.COMPLETED_HEALTH,
                        detail=detail, families=fam_entries.get(job_id),
                        fetch=st.fetch)
                self._observe_latency(st, now)
                self.store.transition(
                    job_id, J.COMPLETED_HEALTH, worker=worker,
                    processing_content=self._prov_content(job_id))
                outcomes[job_id] = J.COMPLETED_HEALTH
            else:
                # no judgeable data at endTime: a warm job re-serves its
                # last fresh verdict (zero UNKNOWN flips during a bounded
                # source blackout); cold jobs keep the reference semantics
                served = self._serve_stale(
                    doc, "insufficient data points to judge", worker, now,
                    in_postprocess=True)
                if served is not None:
                    outcomes[job_id] = served
                    continue
                self.provenance.record(
                    job_id, prov.PATH_NO_DATA, status=J.COMPLETED_UNKNOWN,
                    reason="insufficient data points to judge",
                    fetch=st.fetch)
                self.store.transition(
                    job_id, J.COMPLETED_UNKNOWN,
                    reason="insufficient data points to judge", worker=worker,
                    processing_content=self._prov_content(job_id),
                )
                outcomes[job_id] = J.COMPLETED_UNKNOWN
        stages["fold"] = time.perf_counter() - t_fold
        # per-stage observability: tracer stats (foremast_trace_* on
        # /metrics, bench decomposition) + foremastbrain gauges + /status
        for name, secs in stages.items():
            tracing.tracer.add_timing(tracing.STAGE_SPANS[name], secs)
        self.exporter.record_cycle_stages(stages, fam_seconds)
        triage_cycle = None
        if triage_gate is not None and triage_gate.active:
            tg = triage_gate
            tracing.tracer.add_timing(tracing.SPAN_ENGINE_TRIAGE, tg.seconds)
            screened = sum(tg.screened.values())
            cleared = sum(tg.cleared.values())
            escalated = sum(tg.escalated.values())
            for fam in sorted(set(tg.screened) | set(tg.cleared)
                              | set(tg.escalated)):
                self.triage_screened_total[fam] = (
                    self.triage_screened_total.get(fam, 0)
                    + tg.screened.get(fam, 0))
                self.triage_cleared_total[fam] = (
                    self.triage_cleared_total.get(fam, 0)
                    + tg.cleared.get(fam, 0))
                self.triage_escalated_total[fam] = (
                    self.triage_escalated_total.get(fam, 0)
                    + tg.escalated.get(fam, 0))
                self.exporter.record_triage(
                    fam, tg.screened.get(fam, 0), tg.cleared.get(fam, 0),
                    tg.escalated.get(fam, 0))
            self.triage_launches_total += tg.launches
            # recorded even when this cycle screened 0 rows (everything
            # memo-hit): the "(last cycle)" gauge must not go stale at the
            # previous cycle's ratio while triage_seconds keeps updating
            self.exporter.record_gauge(
                "foremastbrain:triage_escalation_ratio", {},
                round(escalated / screened, 6) if screened else 0.0,
                help="Fraction of screened rows escalated to the "
                     "full scorers (last cycle).")
            self.exporter.record_gauge(
                "foremastbrain:triage_seconds", {},
                round(tg.seconds, 6),
                help="Tier-0 triage screen stage seconds (last cycle).")
            triage_cycle = {
                "screened": screened,
                "cleared": cleared,
                "escalated": escalated,
                "escalation_ratio": (round(escalated / screened, 6)
                                     if screened else 0.0),
                "launches": tg.launches,
                "seconds": round(tg.seconds, 6),
            }
        mega_cycle = None
        if self.config.megabatch:
            real = self.megabatch_real_rows_total - mega_r0
            padded = self.megabatch_pad_rows_total - mega_p0
            mega_launches = self.megabatch_launches_total - mega_l0
            waste = round(padded / real, 6) if real else 0.0
            mega_cycle = {
                "launches": mega_launches,
                "real_rows": real,
                "padded_rows": padded,
                # the packing-efficiency signal: padding rows added per
                # real row this cycle (0 = every launch landed exactly
                # on its padding class)
                "padding_waste_ratio": waste,
            }
            self.exporter.record_gauge(
                "foremastbrain:megabatch_padding_waste_ratio", {}, waste,
                help="Mega-batch padding rows per real row (last cycle).")
            if mega_launches:
                self.exporter.record_counter(
                    "foremastbrain:megabatch_launches_total", {},
                    inc=mega_launches,
                    help="device launches through the single-dispatch "
                         "mega-batch path (MEGABATCH)")
                self.exporter.record_counter(
                    "foremastbrain:megabatch_real_rows_total", {},
                    inc=real,
                    help="real rows carried by mega-batch launches")
                self.exporter.record_counter(
                    "foremastbrain:megabatch_padded_rows_total", {},
                    inc=padded,
                    help="padding rows added to reach mega padding "
                         "classes (waste = padded/real)")
        self.provenance.finish_cycle(
            stage_seconds=stages,
            device_launches=self.device_launches - launches0,
            jobs=len(claimed))
        self.last_cycle_stages = {
            "cycle_id": self.current_cycle_id,
            "jobs": len(claimed),
            "partial": partial,
            "pipelined": pipe is not None,
            "stage_seconds": {k: round(v, 6) for k, v in stages.items()},
            "family_score_seconds": {
                k: round(v, 6) for k, v in fam_seconds.items()},
            # steady-state memo observability: launches actually fired
            # this cycle and verdicts served straight from fingerprints
            "device_launches": self.device_launches - launches0,
            # per-family launch counts (pipelined cycles): the dispatch-
            # collapse observability the mega-batch A/B reads — but
            # recorded for the rung path too, so the two are comparable
            "family_launches": dict(pipe.family_launches)
            if pipe is not None else {},
            "score_memo_hits": dict(pipe.memo_hits) if pipe is not None
            else {},
            # tier-0 triage: this cycle's screened/cleared/escalated rows,
            # escalation ratio, fused screen launches, and stage seconds
            # (None when the gate is off or inactive)
            "triage": triage_cycle,
            # single-dispatch mega-batching: launches / real vs padded
            # rows / per-family launch counts (None when MEGABATCH=0)
            "megabatch": mega_cycle,
            "lstm_rescore_skips": self.lstm_rescore_skips - rescore_skips0,
            # degraded-mode signals (cumulative totals live on /metrics;
            # these are this cycle's contribution + the live park count)
            "jobs_shed": self.jobs_shed_total - shed_cycle0,
            "stale_verdicts_served":
            self.stale_verdicts_served_total - stale_cycle0,
            "watchdog_fires": self.watchdog_fires_total - wd_cycle0,
            "quarantined_jobs": self.quarantined_count(now),
        }
        self._prune_degraded_state(outcomes, orphan_sweep=not partial)
        self.store.put_state("breath", self.breath.export())
        self.store.flush()
        return outcomes

    def _prune_degraded_state(self, outcomes: dict,
                              orphan_sweep: bool = True):
        """Drop per-job degraded-mode state for jobs that can never come
        back: terminal outcomes this cycle, plus jobs deleted out from
        under the analyzer (store gc, unwatch) — without the sweep the
        maps grow one orphan per churned canary id for the life of the
        process. O(map sizes) per cycle; the maps hold open jobs only
        once this runs. Partial cycles skip the orphan sweep (they would
        re-scan fleet-sized maps per push burst); the next full sweep
        covers it."""
        for jid, status in outcomes.items():
            if status in J.TERMINAL_STATUSES:
                self._stale_state.pop(jid, None)
                self._quarantine.pop(jid, None)
                self._shed_streak.pop(jid, None)
                self._slo_seen.pop(jid, None)
        if not orphan_sweep:
            return
        for table in (self._stale_state, self._quarantine,
                      self._shed_streak, self._slo_seen):
            for jid in [j for j in table
                        if j not in outcomes and self.store.get(j) is None]:
                table.pop(jid, None)

    def _finish_hpa(self, st: _JobState, res, worker: str, now: float,
                    path_info: tuple | None = None) -> str:
        doc = st.doc
        if res is None:
            self.provenance.record(
                doc.id, prov.PATH_NO_DATA, status=J.INITIAL,
                detail="no scoreable hpa window", fetch=st.fetch)
            self.store.requeue(doc.id, worker=worker)
            return J.INITIAL
        self._stale_state[doc.id] = now  # scored on fresh data this cycle
        gated = self.breath.apply(doc.id, res["raw_score"], now=now)
        reason_names = {0: "predicted trend", 1: "anomaly trend",
                        2: "SLA violation", 3: "SLA headroom"}
        reason = (
            f"hpa score {gated:.1f} (raw {res['raw_score']:.1f}) via "
            f"{reason_names.get(res['reason_code'], '?')} on {res['tps_metric']}"
        )
        if self.provenance.enabled:
            path, detail = path_info if path_info is not None \
                else (prov.PATH_SCORED, "")
            self.provenance.record(  # lint: disable=trace-registry -- path from _vpath (registered constants only)
                doc.id, path, status=J.INITIAL, detail=detail,
                reason=reason, fetch=st.fetch,
                families=[{
                    "family": "hpa", "metric": res["tps_metric"],
                    "raw_score": round(float(res["raw_score"]), 2),
                    "gated_score": round(float(gated), 2),
                    "sla_metric": res["sla_metric"],
                    "sla_current": round(float(res["sla_current"]), 4),
                    "sla_limit": round(float(res["sla_limit"]), 4),
                }])
        if res.get("has_pod_data"):
            # per-pod normalization context rides the FREE-FORM reason;
            # details stay strictly {current, upper, lower} band entries —
            # letter templating and wire consumers (models.go:194-209)
            # format every detail as a metric-vs-band sentence, which a
            # replicas-vs-demand tuple would turn into nonsense
            reason += (
                f" [per-pod: {res['pods_now']:.1f} pods, "
                f"demand/pod {res['demand_per_pod']:.1f}]"
            )
        self.store.add_hpalog(
            J.HpaLog(
                job_id=doc.id,
                hpascore=gated,
                reason=reason,
                details=[
                    {
                        "metricType": res["tps_metric"],
                        "current": res["current_tps"],
                        "upper": res["upper"],
                        "lower": res["lower"],
                    },
                    {
                        "metricType": res["sla_metric"],
                        "current": res["sla_current"],
                        "upper": res["sla_limit"],
                        "lower": 0.0,
                    },
                ],
                timestamp=now,
            )
        )
        self.exporter.record_hpa_score(doc.app_name, doc.namespace, gated)
        self.store.requeue(doc.id, worker=worker)
        return J.INITIAL
