"""Job documents, the brain status machine, and the durable job store.

Wire/behavior contracts re-implemented (not ported) from the reference:
  * internal statuses and their lifecycle — initial -> preprocess_inprogress
    -> preprocess_completed -> postprocess_inprogress -> completed_health |
    completed_unhealth | completed_unknown | preprocess_failed | abort
    (foremast-service/pkg/converter/converter.go:10-29).
  * external mapping — new / inprogress / success / anomaly / abort
    (converter.go:10-29).
  * document shape — appName, strategy, per-category query-config strings,
    hpa metric flags, podCountURL, status, reason, processingContent
    (foremast-service/pkg/models/models.go:102-124).
  * stuck-job takeover — any job inprogress longer than MAX_STUCK_IN_SECONDS
    may be re-leased by another worker (design.md:37-43; 90 s at
    foremast-brain.yaml:80-81). The store is the lease medium, like ES was.

The store here is in-memory + thread-safe with an optional JSON snapshot
(checkpoint/resume); it is deliberately pluggable — an ES-backed archive can
implement the same four methods.
"""
from __future__ import annotations

import heapq
import json
import logging
import os
import threading
import time
from dataclasses import asdict, dataclass, field

from .archive import _match
from .jobtier import KIND_DOC, KIND_STATE
from ..utils.locks import make_lock, make_rlock

log = logging.getLogger("foremast_tpu.engine.jobs")


# --- internal status machine -------------------------------------------------
INITIAL = "initial"
PREPROCESS_INPROGRESS = "preprocess_inprogress"
PREPROCESS_COMPLETED = "preprocess_completed"
POSTPROCESS_INPROGRESS = "postprocess_inprogress"
COMPLETED_HEALTH = "completed_health"
COMPLETED_UNHEALTH = "completed_unhealth"
COMPLETED_UNKNOWN = "completed_unknown"
PREPROCESS_FAILED = "preprocess_failed"
ABORT = "abort"

OPEN_STATUSES = (INITIAL, PREPROCESS_INPROGRESS, PREPROCESS_COMPLETED, POSTPROCESS_INPROGRESS)
TERMINAL_STATUSES = (COMPLETED_HEALTH, COMPLETED_UNHEALTH, COMPLETED_UNKNOWN, PREPROCESS_FAILED, ABORT)
INPROGRESS_STATUSES = (PREPROCESS_INPROGRESS, PREPROCESS_COMPLETED, POSTPROCESS_INPROGRESS)

_TRANSITIONS = {
    INITIAL: {PREPROCESS_INPROGRESS, ABORT},
    # INITIAL also reachable: transient fetch failures on perpetual
    # (continuous/hpa) jobs requeue instead of dying
    PREPROCESS_INPROGRESS: {PREPROCESS_COMPLETED, PREPROCESS_FAILED, INITIAL, ABORT},
    PREPROCESS_COMPLETED: {POSTPROCESS_INPROGRESS, ABORT},
    POSTPROCESS_INPROGRESS: {
        COMPLETED_HEALTH,
        COMPLETED_UNHEALTH,
        COMPLETED_UNKNOWN,
        # healthy-so-far jobs requeue until endTime (fail-fast rule:
        # design.md:43); continuous/hpa jobs requeue every cycle
        INITIAL,
        ABORT,
    },
}

EXTERNAL_STATUS = {
    INITIAL: "new",
    PREPROCESS_INPROGRESS: "inprogress",
    PREPROCESS_COMPLETED: "inprogress",
    POSTPROCESS_INPROGRESS: "inprogress",
    COMPLETED_HEALTH: "success",
    COMPLETED_UNHEALTH: "anomaly",
    COMPLETED_UNKNOWN: "abort",
    PREPROCESS_FAILED: "abort",
    ABORT: "abort",
}


def to_external(status: str) -> str:
    return EXTERNAL_STATUS.get(status, "unknown")


def verdict_digest(store) -> str:
    """Fleet-wide verdict identity: blake2b over every open+terminal
    job's (id, status, reason, sorted anomaly). This IS the A/B identity
    contract — every bench/simulator gate compares this digest, so any
    change to what counts as verdict identity happens here, once.
    Deliberately excludes processing_content (the provenance attachment
    the provenance A/B toggles)."""
    import hashlib

    dig = hashlib.blake2b(digest_size=16)
    every = store.by_status(*OPEN_STATUSES, *TERMINAL_STATUSES)
    for d in sorted(every, key=lambda d: d.id):
        dig.update(repr((d.id, d.status, d.reason,
                         sorted(d.anomaly.items()))).encode())
    return dig.hexdigest()


class InvalidTransition(Exception):
    pass


@dataclass
class MetricQueries:
    """Per-metric query URLs by category."""

    current: str = ""
    baseline: str = ""
    historical: str = ""
    # hpa flags (models.go:179-183 HPAMetric)
    priority: int = 0
    is_increase: bool = True
    is_absolute: bool = False


@dataclass
class Document:
    """One analysis job."""

    id: str
    app_name: str
    strategy: str  # rollingUpdate | canary | continuous | hpa | rollover
    start_time: str
    end_time: str
    namespace: str = ""
    metrics: dict = field(default_factory=dict)  # name -> MetricQueries
    pod_count_url: str = ""
    status: str = INITIAL
    reason: str = ""
    anomaly: dict = field(default_factory=dict)  # metric -> flat [ts,v,...]
    processing_content: str = ""
    created_at: float = field(default_factory=time.time)
    modified_at: float = field(default_factory=time.time)
    lease_holder: str = ""
    lease_at: float = 0.0
    # archive freshness mark: the modified_at value of the last doc version
    # the archive CONFIRMED holding. archived_at >= modified_at means the
    # archive is up to date with this doc (used by gc() and the open-job
    # mirror; the mark is the cut version's own stamp, never time.time(),
    # so a concurrent modification can't make a stale record look fresh).
    archived_at: float = 0.0
    # graceful-shutdown handoff mark: a draining runtime stamps this on
    # every open job it releases (release_leases) before its final mirror
    # flush. A peer's adopt_stale_from_archive treats a released record as
    # immediately adoptable — no MAX_STUCK_IN_SECONDS wait — because the
    # owner EXPLICITLY surrendered the lease rather than going silent.
    # Cleared the moment any worker (re)claims the job.
    released_at: float = 0.0

    def to_json(self) -> dict:
        # hand-rolled (not dataclasses.asdict, which recurses + deepcopies):
        # the snapshot flusher serializes every doc under the store lock, and
        # asdict made that cut ~8x slower, blocking transitions fleet-wide.
        # test_engine.py pins this against the dataclass fields for drift.
        return {
            "id": self.id,
            "app_name": self.app_name,
            "strategy": self.strategy,
            "start_time": self.start_time,
            "end_time": self.end_time,
            "namespace": self.namespace,
            "metrics": {
                k: {"current": v.current, "baseline": v.baseline,
                    "historical": v.historical, "priority": v.priority,
                    "is_increase": v.is_increase, "is_absolute": v.is_absolute}
                if isinstance(v, MetricQueries) else v
                for k, v in self.metrics.items()
            },
            "pod_count_url": self.pod_count_url,
            "status": self.status,
            "reason": self.reason,
            "anomaly": {k: list(v) for k, v in self.anomaly.items()},
            "processing_content": self.processing_content,
            "created_at": self.created_at,
            "modified_at": self.modified_at,
            "lease_holder": self.lease_holder,
            "lease_at": self.lease_at,
            "archived_at": self.archived_at,
            "released_at": self.released_at,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Document":
        d = dict(d)
        d["metrics"] = {k: MetricQueries(**v) for k, v in d.get("metrics", {}).items()}
        # forward-compat: pre-released_at snapshots/archives load with the
        # default (0.0 = never released)
        return cls(**d)


@dataclass
class HpaLog:
    """hpalogs record (models.go:194-209): score + reasoning details."""

    job_id: str
    hpascore: float
    reason: str
    details: list  # [{metricType, current, upper, lower}]
    timestamp: float = field(default_factory=time.time)


class JobStore:
    """Thread-safe job + hpalog store with lease-based work stealing.

    `archive` (engine/archive.py) is an optional write-behind sink: every
    terminal transition and hpalog is mirrored there, which is what makes
    `gc()` safe — terminal jobs older than the retention window are pruned
    from memory because their record of truth lives in the archive (ES's
    role in the reference; it never pruned, but it also wasn't RAM).
    """

    def __init__(self, snapshot_path: str | None = None, archive=None,
                 mirror_open: bool = True, tier=None,
                 tier_hot_seconds: float = 300.0,
                 tier_checkpoint_min_seconds: float = 5.0):
        self._lock = make_rlock("engine.jobs.store")
        self._jobs: dict[str, Document] = {}
        self._hpalogs: list[HpaLog] = []
        self._state: dict = {}  # engine-owned durable blobs (breath timers)
        self._state_updated: dict = {}  # key -> local update stamp
        self._state_archived: dict = {}  # key -> last stamp archived
        self._snapshot_path = snapshot_path
        self.archive = archive
        # cross-replica failover (reference: ES as the shared lease medium,
        # docs/guides/design.md:37-43): mirror OPEN jobs + engine state to
        # the archive on the flush cadence so a replacement runtime can
        # adopt a crashed peer's in-flight work (adopt_stale_from_archive)
        self.mirror_open = mirror_open and archive is not None
        self.adopted_total = 0  # observability: jobs adopted from peers
        # lease lifecycle counters (foremastbrain:lease_*_total on
        # /metrics): fresh INITIAL claims, stuck-lease takeover steals,
        # and released handoffs (shutdown + shard rebalance). Adoptions
        # are adopted_total above.
        self.lease_claims_total = 0
        self.lease_steals_total = 0
        self.lease_releases_total = 0
        self.mirror_failures_total = 0  # failed mirror writes (any cause)
        # per-doc retry backoff after a failed mirror write: id ->
        # (retry_at, current_delay). Keeps a permanently-rejected doc (ES
        # 400 mapping conflict, oversized doc) from head-of-line-blocking
        # the cut every flush — after a failure the doc sits out a doubling
        # 5 s -> 300 s window while everything behind it mirrors normally.
        # Transient outages clear on the first successful retry (backoff
        # entry dropped), so a blip costs one doc ~5 s of mirror staleness.
        self._mirror_backoff: dict[str, tuple[float, float]] = {}
        # RAM-only exposure instrumentation (VERDICT r3 #8): how long do
        # accepted mutations live only in RAM before reaching a durable
        # medium? _dirty_since marks the OLDEST unflushed mutation; each
        # completed flush records (flush time - that mark) as the realized
        # loss window. loss_window_max_seconds is the worst case observed
        # — the number to alert on (it should sit near the adaptive flush
        # cadence; see docs/operations.md).
        self._dirty_since: float | None = None
        self.loss_window_last_seconds = 0.0
        self.loss_window_max_seconds = 0.0
        self._dirty = False
        self._last_write = 0.0
        # background flusher: serialization/IO happen off the callers'
        # threads (see _persist); writes are ordered by a sequence number so
        # a slow older flush can never clobber a newer snapshot
        self._write_lock = make_lock("engine.jobs.snapshot_write")
        self._flush_seq = 0  # bumped under _lock when a payload is cut
        self._written_seq = 0  # last seq that reached disk (under _write_lock)
        self._flush_cost = 0.0  # last serialize+write seconds (adaptive cadence)
        self._flush_wake = threading.Event()
        self._flusher: threading.Thread | None = None
        self._closed = False
        # crash-durable tier (engine/jobtier.py): WAL ahead of every
        # mutation ack; terminal/cold docs + engine state spill to the
        # CRC-framed segment at checkpoint and EVICT from RAM once the
        # segment (and the archive, when one exists) confirmed holding
        # them — the 1M-jobs-per-replica path. None = RAM-only store
        # with snapshot durability, exactly the pre-tier behavior.
        self.tier = tier
        self.tier_hot_seconds = float(tier_hot_seconds)
        self.tier_checkpoint_min_seconds = float(tier_checkpoint_min_seconds)
        # id -> modified_at of the doc version the SEGMENT confirmed
        # holding (the spill analogue of archived_at; same cut-version
        # rule so a concurrent mutation keeps the doc spill-dirty)
        self._tier_spilled: dict[str, float] = {}
        self._tier_state_spilled: dict[str, float] = {}
        self._tier_last_checkpoint = 0.0
        self.tier_evictions_total = 0
        self.tier_recovery: dict = {}
        if snapshot_path:
            self._load()

    # -- tier WAL (record-or-effect: the record lands BEFORE the caller
    # sees the mutation acknowledged; the effect reaches the segment at
    # checkpoint, and the rotated WAL generation is only retired once
    # the spill debt is zero) --
    def _wal_docs(self, recs) -> None:
        """WAL post-mutation Document records ahead of the ack. Always
        called OUTSIDE self._lock (the tier does file I/O); failures
        degrade inside the tier (counted) — the mutation stays dirty in
        RAM and the snapshot/checkpoint paths still cover it."""
        if self.tier is not None and recs:
            if len(recs) == 1:
                self.tier.wal_append(KIND_DOC, recs[0])
            else:
                self.tier.wal_append_many(KIND_DOC, recs)

    def _wal_state(self, key: str, value, stamp: float) -> None:
        if self.tier is not None:
            self.tier.wal_append(KIND_STATE,
                                 {"k": key, "v": value, "ts": stamp})

    # -- documents --
    def create(self, doc: Document) -> tuple[Document, bool]:
        """Create or return the existing open duplicate (dedupe-by-id,
        matching the reference service's create semantics)."""
        with self._lock:
            cur = self._jobs.get(doc.id)
            if cur is not None and cur.status in OPEN_STATUSES:
                return cur, False
            self._jobs[doc.id] = doc
            self._persist()
            rec = doc.to_json() if self.tier is not None else None
        self._wal_docs([rec] if rec is not None else [])
        return doc, True

    def get(self, job_id: str) -> Document | None:
        with self._lock:
            doc = self._jobs.get(job_id)
        if doc is None and self.tier is not None:
            # evicted cold doc: materialize from the segment tier (the
            # returned copy is READ-ONLY by construction — only terminal
            # docs evict, and terminal docs never transition again)
            rec = self.tier.get_doc(job_id)
            if rec is not None:
                try:
                    return Document.from_json(rec)
                except (TypeError, ValueError):
                    return None
        return doc

    def transition(self, job_id: str, new_status: str, *, reason: str = "",
                   anomaly: dict | None = None, worker: str = "",
                   processing_content: str | None = None) -> Document:
        with self._lock:
            doc = self._jobs[job_id]
            allowed = _TRANSITIONS.get(doc.status, set())
            if new_status not in allowed:
                raise InvalidTransition(f"{doc.status} -> {new_status}")
            doc.status = new_status
            doc.modified_at = time.time()
            if reason:
                doc.reason = reason
            if anomaly is not None:
                doc.anomaly = anomaly
            if processing_content is not None:
                # verdict provenance rides the reference's free-form
                # processing_content field into the archive record
                doc.processing_content = processing_content
            if worker:
                doc.lease_holder = worker
                doc.lease_at = doc.modified_at
            self._persist()
            cut_modified = doc.modified_at
            terminal = new_status in TERMINAL_STATUSES
            rec = (
                doc.to_json()
                if self.tier is not None or (self.archive is not None
                                             and terminal)
                else None
            )
            archive_rec = rec if self.archive is not None and terminal \
                else None
        # WAL ahead of the ack (the caller treats this return as the
        # acknowledgement), then archive I/O — both OUTSIDE the lock: a
        # slow disk or unreachable archive must not stall claim/create/
        # status for every other worker and API thread. Terminal docs
        # never transition again, so the record is stable.
        if rec is not None:
            self._wal_docs([rec])
        if archive_rec is not None and self.archive.index_job(archive_rec):
            doc.archived_at = cut_modified
            if self.tier is not None:
                # the archive-confirm mark is itself a WAL'd mutation:
                # the mirror-drain backlog (archive_dirty_count) must
                # survive kill -9, or recovery would re-mirror — and a
                # stale open mirror could shadow this terminal record
                rec2 = dict(rec)
                rec2["archived_at"] = cut_modified
                self._wal_docs([rec2])
        return doc

    def claim_open_jobs(self, worker: str, limit: int = 1024,
                        max_stuck_seconds: float = 90.0,
                        owns_fn=None, only_ids=None) -> list[Document]:
        """Lease up to `limit` runnable jobs for `worker`.

        A job is runnable if INITIAL, or stuck in an inprogress status longer
        than max_stuck_seconds (takeover — the reference's shared-nothing
        recovery mechanism).

        `owns_fn` is the sharded-brain ownership gate (engine/sharding.py
        ShardManager.owns): jobs in shards this replica does not own are
        skipped — they belong to a peer, and the rebalance reconciler
        (release_unowned) hands any local copies off. Must be a cheap
        pure-host predicate: it runs per doc under the store lock.

        `only_ids` scopes the claim to the named jobs — the event-driven
        scheduler's partial cycles lease exactly the pushed jobs instead
        of walking (and claiming) the whole fleet. When the set is small
        relative to the store, the walk iterates the ids directly.
        """
        now = time.time()
        out = []
        claims = steals = 0
        with self._lock:
            if only_ids is not None and len(only_ids) * 4 < len(self._jobs):
                # sorted: set iteration order is salted per process, and
                # the claim order feeds deterministic bucket packing
                candidates = [d for jid in sorted(only_ids)
                              if (d := self._jobs.get(jid)) is not None]
            else:
                candidates = self._jobs.values()
            for doc in candidates:
                if len(out) >= limit:
                    break
                if only_ids is not None and doc.id not in only_ids:
                    continue
                if owns_fn is not None and not owns_fn(doc.id):
                    continue
                if doc.status == INITIAL:
                    doc.status = PREPROCESS_INPROGRESS
                    claims += 1
                elif doc.status in INPROGRESS_STATUSES and (
                    now - (doc.lease_at or doc.modified_at) > max_stuck_seconds
                ):
                    doc.status = PREPROCESS_INPROGRESS  # reprocess from scratch
                    steals += 1
                else:
                    continue
                doc.lease_holder = worker
                doc.lease_at = now
                doc.modified_at = now
                doc.released_at = 0.0  # claimed again: handoff mark expires
                out.append(doc)
            if out:
                self.lease_claims_total += claims
                self.lease_steals_total += steals
                self._persist()
            recs = [d.to_json() for d in out] \
                if self.tier is not None and out else []
        self._wal_docs(recs)  # lease claims/steals ack through the WAL
        return out

    def release_leases(self, worker: str = "", content_fn=None) -> int:
        """Graceful-shutdown handoff: surrender every open lease.

        In-progress jobs drop back to INITIAL (reprocess-from-scratch, the
        same semantics a lease steal applies) and every open job is
        stamped released_at=now, so a peer's adopt_stale_from_archive
        takes them over IMMEDIATELY instead of waiting out the
        MAX_STUCK_IN_SECONDS window. Status rewinds bypass the transition
        table deliberately — this is the store's own shutdown protocol,
        equivalent to the takeover path's reset, not an engine-visible
        verdict transition.

        `content_fn(job_id) -> str|None` attaches a handoff provenance
        summary (engine/provenance.py handoff_json) to each released
        Document's processing_content, so the job's "why" — and the
        explicit handoff hop — travel with it into the archive for the
        adopter's `explain`. Must be a cheap pure-host callable (runs per
        doc under the store lock). Returns the number of jobs released."""
        now = time.time()
        released = 0
        recs: list[dict] = []
        with self._lock:
            for doc in self._jobs.values():
                if doc.status not in OPEN_STATUSES:
                    continue
                if content_fn is not None:
                    blob = content_fn(doc.id)
                    if blob:
                        doc.processing_content = blob
                if doc.status in INPROGRESS_STATUSES:
                    doc.status = INITIAL
                    # only the docs actually rewound get the handoff
                    # reason; INITIAL docs keep whatever diagnostic the
                    # engine last stamped (stale-verdict age, quarantine
                    # countdown, shed note) — a rolling restart must not
                    # wipe the fleet's degraded-mode reasons
                    if worker:
                        doc.reason = f"released by {worker} shutdown"
                doc.lease_holder = ""
                doc.released_at = now
                doc.modified_at = now
                released += 1
                if self.tier is not None:
                    recs.append(doc.to_json())
            if released:
                # shutdown is the mirror's last chance: docs parked in
                # failure backoff re-enter the next cut so the drain can
                # push the handoff stamps (one attempt each — the drain's
                # progress check still bounds a dead archive)
                self._mirror_backoff.clear()
                self.lease_releases_total += released
                self._persist()
        self._wal_docs(recs)  # handoff stamps survive a kill -9 mid-drain
        return released

    def release_unowned(self, owns_fn, worker: str = "",
                        content_fn=None) -> list[str]:
        """Shard-rebalance handoff: release every open job this replica no
        longer owns (engine/sharding.py calls this from ShardManager.tick
        after a membership change).

        Same semantics as release_leases, per doc: in-progress jobs rewind
        to INITIAL, the lease drops, and released_at stamps the record so
        the NEW owner's adoption scan takes it over immediately — no
        MAX_STUCK_IN_SECONDS wait. Docs already handed off (released,
        unleased, INITIAL) are left alone so a still-unadopted record is
        not re-stamped every tick. `content_fn` attaches the handoff
        provenance summary exactly as in release_leases. Returns the
        released ids."""
        now = time.time()
        released: list[str] = []
        recs: list[dict] = []
        with self._lock:
            for doc in self._jobs.values():
                if doc.status not in OPEN_STATUSES:
                    continue
                if owns_fn(doc.id):
                    continue
                if (doc.released_at > 0 and not doc.lease_holder
                        and doc.status == INITIAL):
                    continue  # already handed off, awaiting adoption/prune
                if content_fn is not None:
                    blob = content_fn(doc.id)
                    if blob:
                        doc.processing_content = blob
                if doc.status in INPROGRESS_STATUSES:
                    doc.status = INITIAL
                    if worker:
                        doc.reason = f"released by {worker} rebalance"
                doc.lease_holder = ""
                doc.released_at = now
                doc.modified_at = now
                # handed-off docs must reach the archive promptly: clear
                # any mirror-failure backoff so the next flush retries
                self._mirror_backoff.pop(doc.id, None)
                released.append(doc.id)
                if self.tier is not None:
                    recs.append(doc.to_json())
            if released:
                self.lease_releases_total += len(released)
                self._persist()
        self._wal_docs(recs)
        return released

    def prune_handed_off(self, owns_fn) -> int:
        """Drop local copies of handed-off jobs once the archive CONFIRMED
        holding the released record (archived_at caught up): the record of
        truth now lives in the archive for the new owner to adopt, and a
        lingering local open copy would shadow the peer's eventual
        terminal verdict in /search forever. Returns the number dropped."""
        if self.archive is None:
            return 0
        dropped = 0
        with self._lock:
            dead = [
                doc.id for doc in self._jobs.values()
                if doc.status in OPEN_STATUSES
                and doc.released_at > 0
                and not doc.lease_holder
                and doc.archived_at >= doc.modified_at
                and not owns_fn(doc.id)
            ]
            for jid in dead:
                del self._jobs[jid]
                self._tier_spilled.pop(jid, None)
                dropped += 1
            if dropped:
                self._persist()
        if dropped and self.tier is not None:
            # tombstone the tier copies: a spilled OPEN record of a job
            # we handed off would be resurrected at the next recovery
            # and shadow the adopter's eventual terminal verdict —
            # exactly the stale-copy problem this prune exists to fix
            self.tier.tombstone_docs(dead)
        return dropped

    def archive_dirty_count(self) -> int:
        """Docs whose newest version the archive has not confirmed yet —
        the write-behind backlog a graceful shutdown drains to zero.
        Always 0 without an archive (there is nothing to drain into)."""
        if self.archive is None:
            return 0
        with self._lock:
            return sum(1 for doc in self._jobs.values()
                       if doc.archived_at < doc.modified_at)

    def advance(self, job_id: str, *statuses: str, worker: str = "") -> Document:
        """Apply a chain of transitions under ONE lock acquisition.

        Semantically identical to calling transition() per status (each hop
        is validated against the state machine) — but the engine advances
        every preprocessed job through two hops per cycle, and at 10k+
        fleet sizes the extra lock round-trips are measurable. Only valid
        for non-terminal hops (no archive mirroring here; terminal verdicts
        go through transition())."""
        with self._lock:
            doc = self._jobs[job_id]
            # validate the WHOLE chain before touching the doc: a mid-chain
            # failure must not leave it half-advanced with a stale snapshot
            cur = doc.status
            for new_status in statuses:
                if new_status not in _TRANSITIONS.get(cur, set()):
                    raise InvalidTransition(f"{cur} -> {new_status}")
                if new_status in TERMINAL_STATUSES:
                    raise InvalidTransition(
                        f"terminal {new_status} must go through transition()"
                    )
                cur = new_status
            doc.status = cur
            doc.modified_at = time.time()
            if worker:
                doc.lease_holder = worker
                doc.lease_at = doc.modified_at
            self._persist()
            rec = doc.to_json() if self.tier is not None else None
        self._wal_docs([rec] if rec is not None else [])
        return doc

    def requeue(self, job_id: str, worker: str = "") -> Document:
        """Back to INITIAL for the next cycle (keeps reason/anomaly/config)."""
        return self.transition(job_id, INITIAL, worker=worker)

    def by_status(self, *statuses: str) -> list[Document]:
        """Live docs plus spilled tier docs (RAM wins per id) — the
        verdict_digest contract rides on this including EVERY job the
        store answers for, evicted or not."""
        with self._lock:
            out = [d for d in self._jobs.values() if d.status in statuses]
            live_ids = set(self._jobs) if self.tier is not None else None
        if self.tier is not None:
            # tier iteration outside the store lock: a million spilled
            # docs must not stall transitions for the duration
            for rec in self.tier.iter_docs(statuses):
                if rec.get("id") in live_ids:
                    continue
                try:
                    out.append(Document.from_json(rec))
                except (TypeError, ValueError):
                    continue
        return out

    def status_counts(self) -> dict:
        """{status: count} over live + spilled jobs (self-metrics gauge).
        Tier counts come from its index (no parse); the small hot set
        corrects the overlap for docs living in both places."""
        counts: dict[str, int] = {}
        if self.tier is not None:
            counts.update(self.tier.doc_status_counts())
        with self._lock:
            for d in self._jobs.values():
                if self.tier is not None:
                    spilled = self.tier.status_of(d.id)
                    if spilled is not None:
                        counts[spilled] = counts.get(spilled, 0) - 1
                counts[d.status] = counts.get(d.status, 0) + 1
        return {k: v for k, v in counts.items() if v > 0}

    @property
    def snapshot_flush_seconds(self) -> float:
        """Last measured serialize+write cost (0 until the first flush)."""
        return self._flush_cost

    @property
    def loss_window_open_seconds(self) -> float:
        """Age of the oldest mutation currently living ONLY in RAM (0 when
        everything has reached the snapshot) — the live crash exposure."""
        with self._lock:
            if self._dirty_since is None:
                return 0.0
            return max(time.time() - self._dirty_since, 0.0)

    # -- hpa logs --
    def add_hpalog(self, log: HpaLog, keep_last: int = 1000):
        with self._lock:
            self._hpalogs.append(log)
            if len(self._hpalogs) > keep_last:
                self._hpalogs = self._hpalogs[-keep_last:]
            self._persist()
        if self.archive is not None:
            self.archive.index_hpalog(asdict(log))

    # -- durable engine state (checkpoint/resume for non-job state) --
    def put_state(self, key: str, value) -> None:
        """Persist a JSON-safe engine blob through the snapshot. The engine
        writes these at cycle boundaries (run_cycle ends with flush()), so
        restart-sensitive scoring state — HPA breath cooldowns — rides the
        same durability path as the jobs themselves (and, with an archive,
        the cross-replica mirror: a replacement runtime inherits armed
        breath timers through get_state's archive fallback)."""
        with self._lock:
            self._state[key] = value
            self._state_updated[key] = stamp = time.time()
            self._persist()
        self._wal_state(key, value, stamp)

    def get_state(self, key: str, default=None):
        with self._lock:
            if key in self._state:
                return self._state[key]
        # restart with a tier: the blob spilled at the last checkpoint
        if self.tier is not None:
            rec = self.tier.get_state(key)
            if rec is not None:
                value, stamp = rec
                with self._lock:
                    if key not in self._state:  # don't clobber a local write
                        self._state[key] = value
                        self._state_updated[key] = stamp
                        self._tier_state_spilled[key] = stamp
                    return self._state[key]
        # fresh replacement runtime: fall back to the peer-mirrored blob
        if self.archive is not None and hasattr(self.archive, "get_state"):
            rec = self.archive.get_state(key)
            if rec is not None:
                value, stamp = rec
                with self._lock:
                    if key not in self._state:  # don't clobber a local write
                        self._state[key] = value
                        self._state_updated[key] = stamp
                        self._state_archived[key] = stamp
                    return self._state[key]
        return default

    def gc(self, max_age_seconds: float = 24 * 3600.0,
           now: float | None = None) -> int:
        """Prune terminal jobs older than the retention window.

        A job is only dropped once the archive has CONFIRMED holding its
        terminal record (archived_at > 0) — jobs resumed from an
        older snapshot, or whose archive write failed, are (re)archived
        here first and survive in RAM until that succeeds. Without an
        archive nothing is ever pruned. Returns the number dropped.
        """
        if self.archive is None:
            return 0
        now = time.time() if now is None else now
        with self._lock:
            candidates = [
                doc for doc in self._jobs.values()
                if doc.status in TERMINAL_STATUSES
                and now - doc.modified_at > max_age_seconds
            ]
        dropped = 0
        marked: list[dict] = []
        for doc in candidates:  # archive I/O outside the lock
            if doc.archived_at < doc.modified_at:
                # the archive's record (if any) predates this version —
                # e.g. an open-state mirror written before the terminal
                # transition whose own archive write failed
                cut_modified = doc.modified_at
                if not self.archive.index_job(doc.to_json()):
                    continue  # archive unavailable: keep the job in RAM
                doc.archived_at = cut_modified
                if self.tier is not None:
                    marked.append(doc.to_json())
            with self._lock:
                if self._jobs.get(doc.id) is doc:  # not re-created meanwhile
                    del self._jobs[doc.id]
                    self._tier_spilled.pop(doc.id, None)
                    dropped += 1
        self._wal_docs(marked)
        if dropped:
            with self._lock:
                self._persist()
        return dropped

    def search(self, app=None, namespace=None, status=None, strategy=None,
               limit: int = 50) -> list[dict]:
        """Live store + archive, newest first, deduped by job id.

        `status` may be a single internal status or a list of them (one
        pass either way — the archive is read once).
        """
        statuses = ([status] if isinstance(status, str) else
                    list(status) if status else None)
        with self._lock:
            live = [
                d.to_json() for d in self._jobs.values()
                if _match({"app_name": d.app_name, "namespace": d.namespace,
                           "status": d.status, "strategy": d.strategy},
                          app, namespace, statuses, strategy)
            ]
        seen = {r["id"] for r in live}
        if self.tier is not None:
            # stream the spilled tier through a bounded top-N heap: the
            # tier can hold a million docs and /jobs only wants `limit`
            matches = (
                rec for rec in self.tier.iter_docs(statuses)
                if rec.get("id") not in seen
                and _match(rec, app, namespace, statuses, strategy)
            )
            for rec in heapq.nlargest(
                    limit, matches,
                    key=lambda r: r.get("modified_at", 0.0)):
                live.append(rec)
                seen.add(rec.get("id"))
        if self.archive is not None:
            for rec in self.archive.search(app=app, namespace=namespace,
                                           status=statuses, strategy=strategy,
                                           limit=limit):
                rec = {k: v for k, v in rec.items() if k != "_type"}
                if rec.get("id") not in seen:
                    live.append(rec)
                    seen.add(rec.get("id"))
        live.sort(key=lambda r: r.get("modified_at", 0.0), reverse=True)
        return live[:limit]

    def hpalogs_for(self, job_id: str, limit: int = 20) -> list[HpaLog]:
        with self._lock:
            logs = [l for l in self._hpalogs if l.job_id == job_id]
        return sorted(logs, key=lambda l: -l.timestamp)[:limit]

    # -- checkpoint/resume --
    def _persist(self):
        """Write-behind: mark dirty and wake the background flusher.

        Serializing the whole store on every transition would be O(jobs^2)
        per cycle under the lock — and even debounced to 1 Hz, a synchronous
        flush makes some unlucky transition pay the whole serialize+write
        while every other worker blocks on the lock. Instead callers only
        flip a bit; the flusher thread owns the cadence (~1 s for typical
        stores, stretching with snapshot cost up to 30 s for 100k-job
        fleets — _flush_interval; either way far inside the 90 s lease
        takeover), and run_cycle/stop() still call flush() synchronously
        at cycle/shutdown boundaries. Always called under self._lock,
        which is what makes the lazy thread start race-free."""
        if not self._snapshot_path:
            return
        self._dirty = True
        if self._dirty_since is None:
            self._dirty_since = time.time()
        if self._flusher is None and not self._closed:
            self._flusher = threading.Thread(
                target=self._flush_loop, name="jobstore-flush", daemon=True
            )
            self._flusher.start()
        self._flush_wake.set()

    def _flush_interval(self) -> float:
        """Adaptive flusher cadence: 1 Hz while snapshots are cheap,
        stretching to 5x the measured serialize+write cost (cap 30 s) for
        huge fleets — a 100k-job store (~1.5 s per snapshot) must not pin
        a core re-serializing at 1 Hz. Worst-case snapshot staleness is
        therefore ~5x cost (<= 30 s), far inside the 90 s lease-takeover
        tolerance; tiny stores keep the ~1 s bound."""
        return min(30.0, max(1.0, 5.0 * self._flush_cost))

    def _flush_loop(self):
        while not self._closed:
            self._flush_wake.wait()
            if self._closed:
                return
            self._flush_wake.clear()
            # wait out the cadence in small closable slices: a plain
            # sleep(30) would make close() miss its join timeout
            deadline = self._last_write + self._flush_interval()
            while not self._closed and time.time() < deadline:
                time.sleep(min(0.2, max(0.0, deadline - time.time())))
            if self._closed:
                return
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 - flusher must survive
                # snapshot dir gone (teardown), disk trouble, or a
                # non-JSON-safe state blob: stay alive — a dead flusher
                # silently downgrades bounded staleness to cycle-length gaps.
                # The next synchronous flush() surfaces the error to a caller.
                log.warning("snapshot flush failed: %s", e)
                time.sleep(1.0)
                # flush() re-marked dirty; re-arm the (cleared) wake so the
                # retry happens even if the store goes quiescent
                self._flush_wake.set()

    def flush(self):
        """Force-write the snapshot (called at cycle boundaries/shutdown).

        The payload is cut under the store lock (to_json/asdict deep-copy,
        so the cut is a consistent point-in-time view); dumps+write happen
        outside it so transitions never wait on disk. _write_lock keeps the
        shared .tmp path single-writer, and the sequence check drops a flush
        that lost the race to a newer one — os.replace()ing an older
        snapshot over a newer one would be a durability regression.

        The archive mirror runs on every flush call regardless of snapshot
        state: archive-dirtiness is tracked per doc (archived_at <
        modified_at), not by the snapshot dirty bit, so capped or failed
        mirror writes retry at the next cycle boundary even on snapshotless
        stores."""
        if self._snapshot_path:
            self._try_snapshot()
        self._mirror_to_archive()

    def _try_snapshot(self) -> None:
        """Write the snapshot if dirty."""
        with self._lock:
            if not self._dirty:
                return
            dirty_since = self._dirty_since
            self._dirty_since = None
            t0 = time.perf_counter()  # after acquire: cost excludes lock waits
            data = {
                "jobs": [d.to_json() for d in self._jobs.values()],
                "hpalogs": [asdict(l) for l in self._hpalogs],
                # copy under the lock like the other members: dumps() runs
                # outside it, and put_state() mutates this dict in place
                "state": dict(self._state),
            }
            cut_s = time.perf_counter() - t0
            self._dirty = False
            self._last_write = time.time()
            self._flush_seq += 1
            seq = self._flush_seq
        try:
            t1 = time.perf_counter()
            payload = json.dumps(data)
            dumps_s = time.perf_counter() - t1
            with self._write_lock:
                if seq <= self._written_seq:
                    # a newer snapshot already reached disk; it contained a
                    # superset of this payload, so our oldest mutation IS
                    # durable — record its exposure conservatively (the
                    # newer write landed no later than now)
                    if dirty_since is not None:
                        w = max(time.time() - dirty_since, 0.0)
                        self.loss_window_last_seconds = w
                        self.loss_window_max_seconds = max(
                            self.loss_window_max_seconds, w)
                    return
                t2 = time.perf_counter()
                tmp = self._snapshot_path + ".tmp"
                with open(tmp, "w") as f:
                    f.write(payload)
                os.replace(tmp, self._snapshot_path)
                self._written_seq = seq
                # serialize+write work only — lock-wait time must not
                # inflate the adaptive cadence under contention
                self._flush_cost = cut_s + dumps_s + (time.perf_counter() - t2)
            if dirty_since is not None:
                # realized RAM-only exposure for the oldest mutation in
                # this payload (VERDICT r3 #8)
                w = max(time.time() - dirty_since, 0.0)
                self.loss_window_last_seconds = w
                self.loss_window_max_seconds = max(
                    self.loss_window_max_seconds, w)
        except BaseException:
            with self._lock:
                self._dirty = True  # this payload never landed; don't lose it
                # resume the exposure clock at the OLDEST unflushed
                # mutation: ours, or one that arrived during the failed
                # write — whichever is older
                if dirty_since is not None:
                    self._dirty_since = (
                        dirty_since if self._dirty_since is None
                        else min(self._dirty_since, dirty_since))
            raise

    _MIRROR_BATCH = 512  # open-doc archive writes per flush (bounds latency)
    _MIRROR_FAIL_CAP = 8  # consecutive failures treated as archive outage

    def _mirror_to_archive(self):
        """Mirror changed OPEN jobs + engine state to the archive.

        Runs on the flush cadence (bounded staleness like the snapshot,
        both far inside the lease-takeover window), best-effort (a dead
        archive must never fail a flush), and capped per flush; unwritten
        docs stay archive-dirty (archived_at < modified_at) and go next
        flush. This is the write half of cross-replica failover — the read
        half is adopt_stale_from_archive()."""
        if not self.mirror_open:
            return
        now = time.time()
        with self._lock:
            # ANY archive-dirty doc, not just open ones: a terminal whose
            # transition-time archive write failed must retry HERE (next
            # flush), not wait for gc's retention window — until the
            # terminal record lands, the archive's newest state for the
            # job is a stale open mirror that peers would adopt.
            # Docs in failure backoff sit out their window so a run of
            # permanently-rejected docs can never occupy the whole cut.
            cut = [
                (doc, doc.to_json(), doc.modified_at)
                for doc in self._jobs.values()
                if doc.archived_at < doc.modified_at
                and self._mirror_backoff.get(doc.id, (0.0, 0.0))[0] <= now
            ][: self._MIRROR_BATCH]
            state_cut = [
                (k, self._state[k], self._state_updated.get(k, 0.0))
                for k in self._state
                if self._state_updated.get(k, 0.0)
                > self._state_archived.get(k, 0.0)
            ]
        consecutive_failures = 0
        marked: list[dict] = []
        for doc, rec, cut_modified in cut:  # archive I/O outside the lock
            ok = self.archive.index_job(rec)
            with self._lock:  # backoff map is read by /metrics threads
                if ok:
                    consecutive_failures = 0
                    self._mirror_backoff.pop(doc.id, None)
                    # the cut version's own stamp: a doc modified mid-write
                    # keeps archived_at < modified_at and re-mirrors next
                    # flush
                    doc.archived_at = max(doc.archived_at, cut_modified)
                    if self.tier is not None:
                        marked.append(doc.to_json())
                else:
                    # a failed write parks THIS doc in a doubling backoff
                    # and moves on, so a permanently-rejected doc cannot
                    # head-of-line-block the fleet's failover mirror; a
                    # genuinely dead archive still short-circuits via the
                    # consecutive-failure cap instead of burning the batch.
                    # Caps stay far below the adoption threshold
                    # (max_stuck 90 s + skew margin): a doc parked past it
                    # after an outage heals would leave its last-mirrored
                    # lease stamp stale enough for a healthy peer to adopt
                    # the LIVE owner's job (open docs), or leave a stale
                    # open mirror shadowing an unlanded terminal record
                    # (terminal docs). 30 s/10 s still rotate poisoned
                    # docs out of the head of the cut (flush cadence ~1 s).
                    self.mirror_failures_total += 1
                    cap = 30.0 if doc.status in OPEN_STATUSES else 10.0
                    delay = min(
                        self._mirror_backoff.get(doc.id, (0.0, 2.5))[1] * 2,
                        cap)
                    self._mirror_backoff[doc.id] = (now + delay, delay)
                    consecutive_failures += 1
            if consecutive_failures >= self._MIRROR_FAIL_CAP:
                break  # archive-wide outage: retry the rest next flush
        with self._lock:
            if len(self._mirror_backoff) > 4 * self._MIRROR_BATCH:
                # bound the map: drop expired entries (their docs simply
                # become eligible again; terminal+archived docs leave stale
                # keys here)
                self._mirror_backoff = {
                    k: v for k, v in self._mirror_backoff.items()
                    if v[0] > now}
        # archive-confirm marks are WAL'd so the drain backlog
        # (archive_dirty_count) survives kill -9 instead of re-mirroring
        # the whole cut on every restart
        self._wal_docs(marked)
        if hasattr(self.archive, "index_state"):
            for key, value, stamp in state_cut:
                if self.archive.index_state(key, value, stamp):
                    with self._lock:
                        self._state_archived[key] = max(
                            self._state_archived.get(key, 0.0), stamp)

    def mirror_backed_off_docs(self, now: float | None = None) -> int:
        """Docs currently parked in mirror-failure backoff (a persistently
        nonzero value while the archive is otherwise healthy means the
        archive is REJECTING those docs, not suffering an outage)."""
        now = time.time() if now is None else now
        with self._lock:
            return sum(1 for v in self._mirror_backoff.values() if v[0] > now)

    def adopt_stale_from_archive(self, worker: str = "",
                                 max_stuck_seconds: float = 90.0,
                                 limit: int = 1024,
                                 now: float | None = None,
                                 skew_margin_seconds: float = 15.0,
                                 owns_fn=None, dead_holder_fn=None,
                                 on_adopt=None) -> int:
        """Adopt open jobs a crashed/partitioned peer left in the archive.

        The reference's failover medium is ES: any brain replica re-claims
        jobs stuck past MAX_STUCK_IN_SECONDS (docs/guides/design.md:37-43,
        elasticsearchstore.go:155 ByStatus "used by backend python model").
        Here the shared archive plays that role: open-job records mirrored
        by peers (see _mirror_to_archive) whose lease stamp has gone stale
        are pulled into the local store; the normal claim_open_jobs lease
        steal then reprocesses them.

        Three adoptability gates, any one suffices:
          * released — the owner stamped released_at (graceful shutdown or
            a shard-rebalance handoff): adoptable NOW, no stuck wait;
          * dead holder — `dead_holder_fn(lease_holder)` says the owning
            replica is POSITIVELY dead per the membership layer
            (engine/sharding.py): a kill -9'd peer's fleet is adoptable at
            membership-TTL latency instead of the stuck window;
          * stale — the lease stamp aged past max_stuck + skew margin (the
            original optimistic path, always available).

        `owns_fn` restricts adoption to this replica's own shards, so N
        replicas recovering a dead peer split its fleet instead of all
        pulling all of it.

        `on_adopt(doc)` is called (outside the store lock, best-effort)
        for each adopted Document — the runtime feeds the attached
        handoff provenance back into its recorder and names the adopted
        jobs in the flight-recorder adoption event.

        When the archive supports `claim_job` (compare-and-swap append;
        FileArchive/EsArchive do), the adoption is RACE-FREE: the claim
        record lands only if the archived record is still the version this
        scan read, so two replicas racing for the same record cannot both
        pull it — the loser's CAS fails and it moves on. Archives without
        claim_job keep the reference's optimistic semantics (double-score
        possible, harmless: verdict writes are last-write-wins per id).

        The staleness test compares PEER-written wall-clock stamps against
        the LOCAL clock, so cross-replica clock skew eats directly into the
        takeover threshold: skew approaching max_stuck_seconds could adopt
        a live peer's job. `skew_margin_seconds` widens the threshold to
        absorb ordinary NTP-grade drift; deployments without NTP should
        raise it (see docs/operations.md "Clock skew" and the
        examples/k8s/runtime-ha.yaml notes).

        Returns the number of jobs adopted."""
        if self.archive is None:
            return 0
        now = time.time() if now is None else now
        adopted = 0
        adopted_recs: list[dict] = []
        claim_cas = getattr(self.archive, "claim_job", None)
        # oldest_first: stale jobs have the OLDEST stamps; a newest-first
        # cap at fleet scale would return only the healthy churn
        for rec in self.archive.search(status=list(OPEN_STATUSES),
                                       limit=limit, oldest_first=True):
            rec = {k: v for k, v in rec.items() if k != "_type"}
            try:
                doc = Document.from_json(rec)
            except (TypeError, ValueError):
                continue  # malformed/foreign record: not adoptable
            if owns_fn is not None and not owns_fn(doc.id):
                continue  # a peer's shard: its owner recovers it
            # a gracefully-released record (release_leases stamped it on
            # shutdown, and nothing claimed it since) is adoptable NOW —
            # the owner surrendered the lease explicitly, so waiting out
            # the stuck window would only delay the takeover it asked for
            released = (doc.released_at > 0
                        and doc.released_at >= doc.lease_at)
            dead = (dead_holder_fn is not None and doc.lease_holder
                    and bool(dead_holder_fn(doc.lease_holder)))
            if not released and not dead and (
                    now - max(doc.lease_at, doc.modified_at)
                    <= max_stuck_seconds + skew_margin_seconds):
                continue  # the owner is (or was recently) alive
            with self._lock:
                cur = self._jobs.get(doc.id)
                if cur is not None and (
                    cur.status in OPEN_STATUSES
                    or cur.modified_at >= doc.modified_at
                ):
                    continue  # we hold it, or our copy is newer
            if doc.status in INPROGRESS_STATUSES:
                # reprocess from scratch — the same rewind the lease steal
                # applies. Without it a DEAD-HOLDER adoption (lease still
                # fresh, only membership says the owner died) would sit
                # unclaimable until the stuck window elapsed, defeating
                # the membership layer's faster recovery.
                doc.status = INITIAL
            if claim_cas is not None:
                # single-adopter guard: append our claim record only while
                # the archive still holds the exact version we read. The
                # claim bumps modified_at (so a racer's staleness test
                # fails too) and clears released_at (a handoff mark must
                # not leave the CLAIMED record insta-adoptable by the next
                # scan); lease_at stays stale so our own claim_open_jobs
                # steal proceeds normally. WALL clock, not the caller's
                # `now` (tests pass synthetic futures for staleness math —
                # a future-stamped claim would shadow every later write),
                # floored just past the expected version so the claim is
                # strictly newest even under writer clock skew.
                expected = doc.modified_at
                doc.modified_at = max(time.time(), expected + 1e-6)
                doc.released_at = 0.0
                if worker:
                    doc.lease_holder = worker
                if not claim_cas(doc.id, expected, doc.to_json()):
                    continue  # a peer won the race (or the record moved)
                doc.archived_at = doc.modified_at  # our claim IS archived
            else:
                doc.archived_at = doc.modified_at  # archive holds this version
                if worker:
                    # record who adopted it; lease_at stays STALE so the
                    # next claim_open_jobs steal proceeds normally
                    doc.lease_holder = worker
            with self._lock:
                cur = self._jobs.get(doc.id)
                if cur is not None and (
                    cur.status in OPEN_STATUSES
                    or cur.modified_at >= doc.modified_at
                ):
                    continue  # a local racer landed while the CAS ran
                self._jobs[doc.id] = doc
                self.adopted_total += 1
                adopted += 1
                self._persist()
                if self.tier is not None:
                    adopted_recs.append(doc.to_json())
            if on_adopt is not None:
                try:
                    on_adopt(doc)
                except Exception:  # noqa: BLE001 - observer, never fatal
                    log.warning("on_adopt hook failed for %s", doc.id,
                                exc_info=True)
        self._wal_docs(adopted_recs)  # adoptions survive a kill -9 too
        return adopted

    # -- tier checkpoint / recovery --
    def tier_checkpoint(self, force: bool = False) -> dict:
        """Rotate the tier WAL -> spill every dirty record into the
        segment -> retire the rotated generation once the spill debt is
        zero -> evict cold terminal docs from RAM.

        Record-or-effect: a mutation is either in a WAL generation
        (rotated or current) or in the segment at every instant, so a
        crash anywhere inside this sequence loses nothing — at worst
        the next recovery replays records whose effects already landed,
        which the newest-wins apply counts as stale no-ops. Rate
        limited (tier_checkpoint_min_seconds) so the runtime can call
        it every sweep."""
        if self.tier is None:
            return {}
        now_mono = time.monotonic()
        if not force and (now_mono - self._tier_last_checkpoint
                          < self.tier_checkpoint_min_seconds):
            return {}
        self._tier_last_checkpoint = now_mono
        t0 = time.monotonic()
        self.tier.rotate_wal()  # no-op if a prior generation's debt holds
        with self._lock:
            cut = [
                (doc.id, doc.modified_at, doc.to_json())
                for doc in self._jobs.values()
                if self._tier_spilled.get(doc.id, -1.0) < doc.modified_at
            ]
            state_cut = [
                (k, self._state[k], self._state_updated.get(k, 0.0))
                for k in self._state
                if self._tier_state_spilled.get(k, -1.0)
                < self._state_updated.get(k, 0.0)
            ]
        # spill OUTSIDE the lock (disk I/O); the cut-version stamps keep
        # docs mutated mid-spill dirty for the next round
        wrote = self.tier.spill_docs([rec for _, _, rec in cut])
        debt = len(cut) - wrote
        with self._lock:
            for jid, cut_modified, _rec in cut[:wrote]:
                self._tier_spilled[jid] = max(
                    self._tier_spilled.get(jid, -1.0), cut_modified)
        for key, value, stamp in state_cut:
            if self.tier.spill_state(key, value, stamp):
                with self._lock:
                    self._tier_state_spilled[key] = max(
                        self._tier_state_spilled.get(key, -1.0), stamp)
            else:
                debt += 1
        if debt == 0:
            self.tier.retire_wal()
        evicted = self._evict_cold()
        stats = {
            "spilled": wrote,
            "spill_debt": debt,
            "evicted": evicted,
            "seconds": round(time.monotonic() - t0, 4),
        }
        self.tier._observe_duration("checkpoint", time.monotonic() - t0)
        return stats

    def tier_snapshot(self) -> dict:
        """Tier section for /status and /metrics: the tier's own disk
        footprint + traffic, this store's eviction count, and what the
        last boot replayed."""
        if self.tier is None:
            return {}
        out = self.tier.snapshot()
        out["evictions"] = self.tier_evictions_total
        out["recovery"] = dict(self.tier_recovery)
        return out

    def _evict_cold(self) -> int:
        """Drop terminal docs from RAM once every durable medium that
        answers for them confirmed holding the current version: the
        segment always, the archive too when one exists. The hot window
        keeps recent verdicts as objects for the API's read-mostly
        traffic; everything colder is served from the segment mmap."""
        now = time.time()
        with self._lock:
            dead = [
                doc.id for doc in self._jobs.values()
                if doc.status in TERMINAL_STATUSES
                and self._tier_spilled.get(doc.id, -1.0) >= doc.modified_at
                and (self.archive is None
                     or doc.archived_at >= doc.modified_at)
                and now - doc.modified_at > self.tier_hot_seconds
            ]
            for jid in dead:
                del self._jobs[jid]
                self._tier_spilled.pop(jid, None)
                self._mirror_backoff.pop(jid, None)
            if dead:
                self.tier_evictions_total += len(dead)
                self._persist()  # the snapshot must not resurrect them
        return len(dead)

    def _apply_replay(self, kind: str, obj) -> str:
        """Apply one WAL record with the SAME newest-wins rule live
        mutation follows — the replay path is the transition path's
        idempotent twin. A record the store (RAM or segment) already
        reflects is a counted ``stale`` no-op; equal-stamp records
        tie-break on archived_at so a crash between the archive-confirm
        mark and its spill still recovers the mark."""
        if kind == KIND_DOC:
            try:
                doc = Document.from_json(obj)
            except (TypeError, ValueError):
                return "dropped"
            with self._lock:
                cur = self._jobs.get(doc.id)
                cur_mod = cur.modified_at if cur is not None else None
                cur_arch = cur.archived_at if cur is not None else 0.0
            if cur_mod is None:
                seg = self.tier.get_doc(doc.id)  # outside the store lock
                if seg is not None:
                    cur_mod = float(seg.get("modified_at", 0.0))
                    cur_arch = float(seg.get("archived_at", 0.0))
            if cur_mod is not None and (
                    doc.modified_at < cur_mod
                    or (doc.modified_at == cur_mod
                        and doc.archived_at <= cur_arch)):
                return "stale"
            with self._lock:
                cur = self._jobs.get(doc.id)
                if cur is not None and (
                        doc.modified_at < cur.modified_at
                        or (doc.modified_at == cur.modified_at
                            and doc.archived_at <= cur.archived_at)):
                    return "stale"
                self._jobs[doc.id] = doc  # tier-dirty: absent from
                #                           _tier_spilled, spills next
                #                           checkpoint
            return "applied"
        if kind == KIND_STATE:
            key = obj.get("k") if isinstance(obj, dict) else None
            if key is None:
                return "dropped"
            stamp = float(obj.get("ts", 0.0))
            seg = self.tier.get_state(key)
            seg_stamp = seg[1] if seg is not None else -1.0
            with self._lock:
                if (self._state_updated.get(key, -1.0) >= stamp
                        or seg_stamp >= stamp):
                    return "stale"
                self._state[key] = obj.get("v")
                self._state_updated[key] = stamp
            return "applied"
        return "dropped"

    def recover_from_tier(self) -> dict:
        """Boot-time recovery: rebuild the segment index, materialize
        every OPEN doc into RAM (this replica must re-claim its
        in-flight fleet; terminal docs stay in the segment), replay the
        WAL generations through _apply_replay, then checkpoint so the
        WAL restarts empty. Runs after _load() so WAL/segment records
        newer than the snapshot win."""
        if self.tier is None:
            return {}
        t0 = time.monotonic()
        stats = self.tier.recover(self._apply_replay)
        restored = 0
        for rec in self.tier.iter_docs(OPEN_STATUSES):
            try:
                doc = Document.from_json(rec)
            except (TypeError, ValueError):
                continue
            with self._lock:
                cur = self._jobs.get(doc.id)
                if cur is not None and cur.modified_at >= doc.modified_at:
                    continue
                self._jobs[doc.id] = doc
                # the segment IS the spilled version
                self._tier_spilled[doc.id] = doc.modified_at
                restored += 1
        stats["open_docs_restored"] = restored
        stats["seconds"] = round(time.monotonic() - t0, 4)
        self.tier_recovery = stats
        self.tier_checkpoint(force=True)
        return dict(stats)

    def close(self):
        """Final flush + stop the background flusher (idempotent)."""
        already = self._closed
        self._closed = True
        self._flush_wake.set()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
        self.flush()
        if self.tier is not None and not already:
            self.tier_checkpoint(force=True)

    def _load(self):
        if not os.path.exists(self._snapshot_path):
            return
        try:
            with open(self._snapshot_path) as f:
                data = json.load(f)
            jobs = {d["id"]: Document.from_json(d) for d in data.get("jobs", [])}
            logs = [HpaLog(**l) for l in data.get("hpalogs", [])]
            state = data.get("state", {}) or {}
        except (json.JSONDecodeError, OSError, KeyError, TypeError):
            # a torn/corrupt snapshot must not brick the service: quarantine
            # it and start empty (jobs are re-submitted by the operator tick)
            os.replace(self._snapshot_path, self._snapshot_path + ".corrupt")
            return
        self._jobs = jobs
        self._hpalogs = logs
        self._state = state if isinstance(state, dict) else {}
