"""Bucket-granular scoring pipeline: stream, dispatch, collect.

The pre-pipeline cycle was a chain of full barriers — every job fetched
and packed before ANY scoring started, the five model families scored
strictly sequentially, and each chunk launch blocked on materialization
before the next chunk was even packed. This module turns that chain into
a pipeline at three levels:

  1. **streaming preprocess -> dispatch** — `Analyzer._run_cycle` feeds
     each job's preprocessed items into `CyclePipeline` the moment its
     fetch-pool chunk completes. Items route into per-family /
     per-T-bucket accumulators, and a device program launches as soon as
     an accumulator fills a full batch rung (partials flush at stream
     end), so device execution of bucket N overlaps the fetch+pack of
     bucket N+1.
  2. **async dispatch** — launches go through the analyzer's
     `_launch_*` halves, which return JAX async-dispatch device values;
     nothing blocks until the final collect phase materializes them, so
     the four batch families interleave freely on the device queue.
  3. **persistent compile cache + prewarm** — `enable_compile_cache`
     points XLA's persistent compilation cache at COMPILE_CACHE_PATH so
     a restarted process skips the first-cycle compile storm, and
     `prewarm` compiles the standard (family x rung x T-bucket) grid up
     front (CLI: `foremast-tpu prewarm`; runtime: PREWARM_ON_START).

Two contracts are preserved exactly:

  * **deterministic folding** — accumulators fill in claim order, fire at
    the same chunk boundaries the barriered `_score_chunks` would cut
    (full rungs mid-stream, rung-padded partials at flush), and results
    are keyed dicts folded in claim order, so verdicts are byte-identical
    to the sequential path regardless of device completion order.
  * **`_isolate` blast radius** — a launch- or collect-time failure
    retries that group per JOB through the family's synchronous scorer;
    only the offending jobs report errors, everyone else's results stand.
"""
from __future__ import annotations

import time

from ..utils import tracing

__all__ = ["CyclePipeline", "CompileCounter", "enable_compile_cache",
           "prewarm", "STANDARD_RUNGS", "STANDARD_T_BUCKETS"]


class CyclePipeline:
    """One engine cycle's streaming dispatch state. Not thread-safe by
    design: `feed` is called from the single consumer of the (ordered)
    preprocess stream, which is what keeps launches deterministic."""

    FAMILIES = ("pair", "band", "bivariate", "hpa")

    def __init__(self, analyzer):
        self.an = analyzer
        # fire threshold: an accumulator launches the moment it holds a
        # full batch rung, so device execution overlaps the remaining
        # fetches. Snapped to the rung ladder (and capped at the chunk
        # size) so streamed launches hit the same compiled programs as the
        # flush; scorers are row-wise, so launch boundaries cannot change
        # verdicts (the determinism test pins pipeline == barriered).
        cap = max(16, analyzer.config.score_batch)
        fire = min(max(analyzer.config.pipeline_fire_rows, 16), cap)
        self.cap = analyzer._bucket_rows(fire)
        # single-dispatch mega-batching: accumulators hold the WHOLE
        # cycle's rows and flush as one padded launch per (family, T) at
        # finish — trading the mid-stream fetch/score overlap for launch
        # count, which is the winning trade once dispatch overhead
        # dominates (docs/performance.md §6). The fire threshold is the
        # PER-T memory-aware _mega_cap, not the global row ceiling:
        # _fire packs its whole bucket into (n, T) host arrays before
        # _launch_chunks re-chunks, so a T-blind cap would let a
        # long-window bucket materialize multi-GB packed arrays that the
        # launch-time cap then bounds too late. Firing at _mega_cap(T)
        # partitions rows exactly as the launch-time re-chunk would
        # (chunks of C + padded remainder), so launch counts and
        # verdicts are unchanged — only pack-time peak memory moves.
        self._mega = bool(analyzer.config.megabatch)
        self._mega_caps: dict = {}  # T -> analyzer._mega_cap(T)
        self.acc: dict = {f: {} for f in self.FAMILIES}  # family -> T -> []
        self.pending: list = []  # (family, entries, launch_state)
        self.failed: list = []   # (family, entries) awaiting per-job retry
        self.multis: list = []   # lstm items score at collect (train+cache)
        self.stage_seconds = {"dispatch": 0.0, "collect": 0.0}
        self.family_seconds: dict = {}
        self.launches = 0
        # device launches per family this cycle (from the analyzer's
        # device_launches delta around each _fire, so chunk-level splits
        # and the band family's period-detection launches count) — the
        # mega-batch "one launch per family per cycle" claim reads this
        self.family_launches: dict = {}
        # fingerprint score memo (SCORE_MEMO): unchanged rows resolve
        # straight from the analyzer's cross-cycle memo and never enter an
        # accumulator — buckets hold only changed rows, so steady-state
        # cycles fire fewer, smaller programs (and a no-change cycle fires
        # none at all). Routing/bucketing is unchanged for the rows that
        # do score, so launch boundaries — and verdicts — stay identical
        # to the memo-off path.
        self.memo = analyzer._score_memo if analyzer.config.score_memo \
            else None
        self.memo_results: dict = {f: {} for f in self.FAMILIES}
        # tier-0 triage gate (TRIAGE; engine/triage.py): composes after
        # the memo check — memo skips unchanged rows, triage screens the
        # changed-but-unremarkable ones in one fused kernel and
        # short-circuits CLEAR rows to synthesized healthy results;
        # SUSPECT rows fall through to the family accumulators unchanged.
        self.triage = None
        if analyzer.config.triage:
            from .triage import TriageGate

            gate = TriageGate(analyzer)
            if gate.active:
                self.triage = gate
        self.memo_hits: dict = {}  # family -> hits this cycle
        # provenance: which JOBS had items served from the memo this cycle
        # (job_id -> hit count) — lets /jobs/<id>/explain attribute a
        # verdict to the memo-hit path instead of a fresh device score
        self.memo_job_hits: dict = {}
        self._fps: dict = {}       # (family, result_key) -> fingerprint

    def _memo_check(self, family: str, entry, T: int) -> bool:
        """True when this entry's verdict was served from the memo."""
        if self.memo is None:
            return False
        key, fp = self.an._memo_key_fp(family, entry, T)
        hit = self.memo.get((family, key))
        if hit is not None and hit[0] == fp:
            self.memo.move_to_end((family, key))
            self.memo_results[family][key] = hit[1]
            self.memo_hits[family] = self.memo_hits.get(family, 0) + 1
            self.an.score_memo_hits[family] = (
                self.an.score_memo_hits.get(family, 0) + 1)
            job_id = key[0] if isinstance(key, tuple) else key
            self.memo_job_hits[job_id] = self.memo_job_hits.get(job_id, 0) + 1
            return True
        self._fps[(family, key)] = fp
        self.an.score_memo_misses[family] = (
            self.an.score_memo_misses.get(family, 0) + 1)
        return False

    # ------------------------------------------------------------- feeding
    def feed(self, pairs, bands, bis, multis, hpas, strategy: str = ""):
        """Route one job's preprocessed items (claim order) into the
        accumulators; launch any bucket that filled its rung.

        `strategy` is the owning job's strategy: the triage gate screens
        only steady-state (continuous/hpa-class) jobs — canary-class
        verdicts gate live rollouts and always take the full path.

        Routing (bucket keys, joint-grid prep, hpa row building, triage
        screening) is guarded per item like every scoring step: a
        malformed item lands in the per-job retry list instead of
        aborting the whole cycle — the `_isolate` blast-radius contract
        starts here, not at launch.
        """
        an = self.an
        tg = self.triage
        self.multis += multis
        for it in pairs:
            try:
                T = an._pair_T(it)
                if not self._memo_check("pair", it, T):
                    if tg is not None and tg.accepts("pair", strategy):
                        tg.add("pair", T, it, self)
                    else:
                        self._add("pair", T, it)
            except Exception:  # noqa: BLE001 - retried per job at collect
                self.failed.append(("pair", [it]))
        for it in bands:
            try:
                T = an._band_T(it)
                if not self._memo_check("band", it, T):
                    if tg is not None and tg.accepts("band", strategy):
                        tg.add("band", T, it, self)
                    else:
                        self._add("band", T, it)
            except Exception:  # noqa: BLE001
                self.failed.append(("band", [it]))
        for it in bis:
            try:
                pre, T = an._bi_prep(it)
                if not self._memo_check("bivariate", (it, pre), T):
                    if tg is not None and tg.accepts("bivariate", strategy):
                        tg.add("bivariate", T, (it, pre), self)
                    else:
                        self._add("bivariate", T, (it, pre))
            except Exception:  # noqa: BLE001
                self.failed.append(("bivariate", [it]))
        if hpas:
            try:
                rows = an._hpa_rows(hpas)
            except Exception:  # noqa: BLE001
                self.failed.append(("hpa", list(hpas)))
                rows = []
            for row in rows:
                try:
                    T = an._hpa_row_T(row)
                    if not self._memo_check("hpa", row, T):
                        self._add("hpa", T, row)
                except Exception:  # noqa: BLE001
                    self.failed.append(("hpa", [row]))

    def _add(self, family: str, T: int, entry):
        bucket = self.acc[family].setdefault(T, [])
        bucket.append(entry)
        if self._mega:
            cap = self._mega_caps.get(T)
            if cap is None:
                cap = self._mega_caps[T] = self.an._mega_cap(T)
        else:
            cap = self.cap
        if len(bucket) >= cap:
            self.acc[family][T] = []
            self._fire(family, T, bucket)

    def _fire(self, family: str, T: int, entries: list):
        t0 = time.perf_counter()
        d0 = self.an.device_launches
        try:
            if family == "pair":
                st = self.an._launch_pairs(entries, T)
            elif family == "band":
                st = self.an._launch_bands(entries, T)
            elif family == "bivariate":
                st = self.an._launch_bivariate(entries, T)
            else:
                st = self.an._launch_hpa(entries, T)
            self.pending.append((family, entries, st))
        except Exception:  # noqa: BLE001 - blast radius: retry per job later
            self.failed.append((family, entries))
        dt = time.perf_counter() - t0
        self.stage_seconds["dispatch"] += dt
        self.family_seconds[family] = self.family_seconds.get(family, 0.0) + dt
        self.launches += 1
        self.family_launches[family] = (
            self.family_launches.get(family, 0)
            + (self.an.device_launches - d0))

    @staticmethod
    def _entry_items(entries: list) -> list:
        """Flatten accumulator entries back to scorer items (for the
        per-job retry path): pair/band entries ARE items, bivariate
        entries are (item, prep), hpa entries are (job_id, tps, sla)."""
        items = []
        for e in entries:
            if hasattr(e, "job_id"):
                items.append(e)
            elif len(e) == 2:
                items.append(e[0])
            else:
                items.append(e[1])
                if e[2] is not e[1]:
                    items.append(e[2])
        return items

    # ----------------------------------------------------------- collecting
    def finish(self):
        """Flush partial buckets, materialize every launch, retry failures
        per job, and score the lstm family. Returns
        (pair_res, band_res, bi_res, multi_res, hpa_res, scoring_failed)."""
        an = self.an
        if self.triage is not None:
            # screen the remaining partial triage buckets FIRST: suspects
            # route into the family accumulators below and flush with
            # everyone else; cleared rows land in triage.results
            self.triage.flush(self)
        for family in self.FAMILIES:
            buckets, self.acc[family] = self.acc[family], {}
            for T, bucket in buckets.items():
                if bucket:
                    self._fire(family, T, bucket)
        results: dict = {f: {} for f in self.FAMILIES}
        bad: dict = {}
        collect = {"pair": an._collect_pairs, "band": an._collect_bands,
                   "bivariate": an._collect_bivariate, "hpa": an._collect_hpa}
        sync = {"pair": an._score_pairs, "band": an._score_bands,
                "bivariate": an._score_bivariate, "hpa": an._score_hpa}
        from .analyzer import WatchdogTimeout

        t0 = time.perf_counter()
        # Hung-launch watchdog budget: each materialization (and each
        # per-job retry below) runs under WATCHDOG_S (no-op when 0), and
        # the cycle pays for at most TWO timeouts total. One timeout can
        # be a single poisoned program; a second — from another bucket or
        # from a fresh sync retry — is device-level evidence, after which
        # every remaining watchdog-guarded wait is skipped instantly
        # (buckets fall through to the requeue path). Without the cap, a
        # wedged device would serialize one full WATCHDOG_S per pending
        # bucket plus one per retried job into a single cycle.
        wd0 = an.watchdog_fires_total

        def wedged() -> bool:
            return an.watchdog_fires_total - wd0 >= 2

        # materialize in launch order: completion order is the device's
        # business; claim-order folding happens downstream off keyed dicts
        for family, entries, st in self.pending:
            t1 = time.perf_counter()
            try:
                if wedged():
                    raise WatchdogTimeout(
                        "device wedged (2+ watchdog timeouts this cycle); "
                        "bucket skipped")
                results[family].update(an._watchdog_call(collect[family], st))
            except Exception:  # noqa: BLE001 - deferred device error
                self.failed.append((family, entries))
            dt = time.perf_counter() - t1
            self.family_seconds[family] = (
                self.family_seconds.get(family, 0.0) + dt)
        # blast-radius fallback: a failed group retries per JOB through the
        # family's synchronous scorer (same launch/collect code, barriered;
        # watchdog-bounded under the same two-timeout cycle budget)
        for family, entries in self.failed:
            by_job: dict[str, list] = {}
            for it in self._entry_items(entries):
                by_job.setdefault(it.job_id, []).append(it)
            for job_id, group in by_job.items():
                if wedged():
                    bad[job_id] = ("WatchdogTimeout: device wedged "
                                   "(2+ watchdog timeouts this cycle); "
                                   "retry skipped")
                    continue
                try:
                    results[family].update(
                        an._watchdog_call(sync[family], group))
                except Exception as e:  # noqa: BLE001
                    bad[job_id] = f"{type(e).__name__}: {e}"
        if self.triage is not None:
            # fold triage-cleared rows in BEFORE memoization: a cleared
            # row's synthesized result is the healthy result the scorer
            # would have produced, so memoizing it keeps the steady chain
            # (unchanged next cycle -> memo hit, no re-screen)
            for family, cleared in self.triage.results.items():
                results[family].update(cleared)
        if self.memo is not None:
            # memoize every freshly scored verdict (collect + retries) for
            # the next cycle, then fold the memo-served ones back in
            for family in self.FAMILIES:
                for key, res in results[family].items():
                    fp = self._fps.get((family, key))
                    if fp is not None:
                        an._memo_put(self.memo, (family, key), (fp, res))
                results[family].update(self.memo_results[family])
        # lstm scores here, not in the stream: training mutates the model
        # cache under a per-cycle budget whose order must match claim order
        with tracing.span(tracing.SCORE_SPANS["lstm"],
                          n=len(self.multis)) as lsp:
            t1 = time.perf_counter()
            multi_res, multi_bad = an._isolate(an._score_multi, self.multis)
            lsp.attrs["budget_skips"] = len(an._lstm_budget_skipped_ids)
            self.family_seconds["lstm"] = time.perf_counter() - t1
        # collect = everything after the stream: device wait + merge +
        # retries + the lstm family — the same work the barriered mode
        # books under collect, so SCORE_PIPELINE A/Bs compare like stages
        self.stage_seconds["collect"] += time.perf_counter() - t0
        bad.update(multi_bad)
        return (results["pair"], results["band"], results["bivariate"],
                multi_res, results["hpa"], bad)


# ---------------------------------------------------------------- compiles
class CompileCounter:
    """Counts XLA compilation work via jax.monitoring events.

    `compiles` counts backend_compile invocations — in a process WITHOUT
    the persistent cache this is exactly the number of fresh XLA
    compilations (in-memory jit cache hits never re-enter the backend),
    which is what the steady-state zero-recompile gate asserts. With the
    persistent cache enabled, backend_compile wraps retrieval too, so the
    compile-storm question becomes `cache_misses` (fresh work) vs
    `cache_hits` (replayed from COMPILE_CACHE_PATH).
    """

    COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
    CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
    CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

    def __init__(self):
        self.compiles = 0
        self.compile_seconds = 0.0
        self.cache_hits = 0
        self.cache_misses = 0

    def _on_duration(self, event, duration, **kw):
        if event == self.COMPILE_EVENT:
            self.compiles += 1
            self.compile_seconds += duration

    def _on_event(self, event, **kw):
        if event == self.CACHE_HIT_EVENT:
            self.cache_hits += 1
        elif event == self.CACHE_MISS_EVENT:
            self.cache_misses += 1

    def __enter__(self):
        import jax.monitoring as jm

        jm.register_event_duration_secs_listener(self._on_duration)
        jm.register_event_listener(self._on_event)
        return self

    def __exit__(self, *exc):
        try:
            from jax._src import monitoring as _m

            _m._unregister_event_duration_listener_by_callback(
                self._on_duration)
            _m._unregister_event_listener_by_callback(self._on_event)
        except Exception:  # noqa: BLE001 - best-effort on private API drift
            pass
        return False


def enable_compile_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at `path` (COMPILE_CACHE_PATH).

    Zeroes the min-compile-time/entry-size gates so even the small
    per-(rung, T) programs persist — they are exactly what the first-cycle
    compile storm is made of. Returns False (without raising) on jax
    builds that lack the knobs: the engine must run identically, just
    without restart amortization.
    """
    if not path:
        return False
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 - knob missing on this jax build
        return False
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 - defaults still cache big entries
            pass
    return True


# ----------------------------------------------------------------- prewarm
# the default prewarm grid: small rungs cover flush partials, 1024 covers
# the default PIPELINE_FIRE_ROWS streamed launches, and the T buckets the
# common 2h-current / short-history windows. Big fleets should prewarm
# their real rungs (e.g. --rungs 16,64,256,1024,8192) and their historical
# T buckets — see docs/performance.md for sizing.
STANDARD_RUNGS = (16, 64, 256, 1024)
STANDARD_T_BUCKETS = (128, 256)


def prewarm(config=None,
            families=("pair", "band", "bivariate", "hpa", "triage"),
            rungs=STANDARD_RUNGS, t_buckets=STANDARD_T_BUCKETS) -> dict:
    """Compile the (family x rung x T-bucket) scoring grid up front.

    Drives the REAL production entry points — the analyzer's family
    scorers on synthetic items — so the compiled signatures are exactly
    the ones steady-state cycles launch (dtype or packing drift would show
    up as a failed zero-recompile regression test, not a silent miss).
    With the persistent compile cache enabled the work is also banked for
    every future process. Blocks until the grid is compiled; run it in a
    background thread to prewarm behind live traffic (PREWARM_ON_START).
    """
    import numpy as np

    from ..ops import hpa as hpa_ops
    from ..ops import triage as triage_ops
    from ..ops.windowing import Window, bucket_length
    from ..parallel import fleet as fl
    from .analyzer import Analyzer, _BandItem, _BiItem, _HpaItem
    from .config import EngineConfig, from_env
    from .triage import screen_cap

    cfg = config if config is not None else from_env()
    if not isinstance(cfg, EngineConfig):
        raise TypeError(f"prewarm wants an EngineConfig, got {type(cfg)!r}")
    an = Analyzer(cfg, data_source=None, store=None,
                  breath=hpa_ops.BreathState())
    rng = np.random.default_rng(0)
    # clamp BOTH axes to their ladders: off-ladder values would compile
    # programs no cycle ever launches (the chunker pads rows to batch
    # rungs, pack_windows pads lengths to the window buckets) while the
    # real bucket stayed cold
    rungs = sorted({an._bucket_rows(int(r)) for r in rungs})
    t_buckets = sorted({bucket_length(int(t)) for t in t_buckets})
    policy = cfg.policy_for("latency")

    def win(T):
        return Window(rng.normal(10.0, 1.0, T).astype(np.float32),
                      np.ones(T, bool), 0)

    t0 = time.perf_counter()
    programs = 0
    with CompileCounter() as cc:
        for T in t_buckets:
            n_c = max(T // 4, 8)
            n_h = T - n_c
            if "triage" in families:
                # the fused tier-0 screen launches at exactly the rungs
                # TriageGate._rung can return: every _BATCH_BUCKETS entry
                # below the memory-aware cap, plus the cap itself (the
                # steady-state rung a big fleet's screen actually fires) —
                # deriving from the family rung list missed 512/4096 and
                # left mid-size buckets compiling at cycle time
                cap = screen_cap(cfg.triage_fire_rows, T)
                t_rungs = sorted(
                    {b for b in Analyzer._BATCH_BUCKETS if b < cap}
                    | {cap})
                for r in t_rungs:
                    np.asarray(triage_ops.screen_rows(
                        *triage_ops.triage_arg_spec(r, T),
                        cfg.ma_window)["count"])
                    programs += 1
            for r in rungs:
                if "pair" in families:
                    # the fused pairwise program straight at the kernel:
                    # fleet.pair_arg_spec mirrors _launch_pairs' packing
                    np.asarray(fl.score_pairs(*fl.pair_arg_spec(r, T))
                               ["unhealthy"])
                    programs += 1
                if "band" in families:
                    an._score_bands([
                        _BandItem(f"w{i}", "latency", win(n_h), win(n_c),
                                  policy)
                        for i in range(r)
                    ])
                    programs += 1
                if "bivariate" in families:
                    an._score_bivariate([
                        _BiItem(f"w{i}", ("latency", "cpu"),
                                (win(n_h), win(n_h)), (win(n_c), win(n_c)),
                                (policy, policy))
                        for i in range(r)
                    ])
                    programs += 1
                if "hpa" in families:
                    items = []
                    for i in range(r):
                        items.append(_HpaItem(f"w{i}", "tps", win(n_h),
                                              win(n_c), True, 0))
                        items.append(_HpaItem(f"w{i}", "latency", win(n_h),
                                              win(n_c), True, 1))
                    an._score_hpa(items)
                    programs += 1
    return {
        "families": list(families),
        "rungs": list(rungs),
        "t_buckets": list(t_buckets),
        "programs": programs,
        "backend_compiles": cc.compiles,
        "compile_cache_hits": cc.cache_hits,
        "seconds": round(time.perf_counter() - t0, 3),
    }
