"""Incident flight recorder: a bounded ring of structured engine events.

When the brain transitions into OVERLOADED/STALLED — or is SIGTERMed mid
incident — the evidence an operator needs (what shed, what quarantined,
which breaker flipped, which watchdog fired, in what order) has usually
already scrolled out of the log. The flight recorder keeps the last N
structured events in RAM, serves them at ``/debug/flight``, and
auto-dumps a JSON snapshot to disk — recent events + recent traces +
provenance for the jobs the events name + the live knob values — on the
transition into OVERLOADED/STALLED and on graceful shutdown, so every
incident leaves a self-contained artifact even when nobody was watching
the pod.

Always-on and allocation-bounded: the ring is a fixed-size deque, event
details are small dicts, dumps are rate-limited (``min_dump_interval_s``)
and pruned to the newest ``MAX_DUMPS`` files.

Event types are REGISTERED constants (the devtools trace-registry rule
rejects inline literals), so dumps stay machine-diffable across builds.
"""
from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import time
from collections import deque

from ..utils.locks import make_lock

log = logging.getLogger("foremast_tpu.engine.flightrec")

__all__ = [
    "FlightRecorder", "EVENT_TYPES",
    "EVENT_HEALTH_TRANSITION", "EVENT_SHED", "EVENT_QUARANTINE",
    "EVENT_STALE_SERVE", "EVENT_WATCHDOG", "EVENT_BREAKER",
    "EVENT_LEASE_HANDOFF", "EVENT_DUMP",
    "EVENT_REPLICA_JOIN", "EVENT_REPLICA_LEAVE", "EVENT_REBALANCE",
    "EVENT_SHARD_ADOPTION", "EVENT_STORE_RECOVERY",
]

# -- event-type registry -----------------------------------------------------
EVENT_HEALTH_TRANSITION = "health-transition"
EVENT_SHED = "load-shed"
EVENT_QUARANTINE = "quarantine"
EVENT_STALE_SERVE = "stale-serve"
EVENT_WATCHDOG = "watchdog-fire"
EVENT_BREAKER = "breaker-flip"
EVENT_LEASE_HANDOFF = "lease-handoff"
EVENT_DUMP = "flight-dump"
# sharded multi-replica membership (engine/sharding.py): another replica
# joined/left the ring, this replica's shard assignment changed, and a
# post-rebalance adoption scan pulled a peer's jobs
EVENT_REPLICA_JOIN = "replica-join"
EVENT_REPLICA_LEAVE = "replica-leave"
EVENT_REBALANCE = "shard-rebalance"
EVENT_SHARD_ADOPTION = "shard-adoption"
# crash-durable window store (dataplane/winstore.py): boot-time
# segment+WAL replay finished — detail carries the recovery stats
# (replayed records, scan statuses, seconds), so an incident dump after
# a restart self-documents what the replica recovered from disk
EVENT_STORE_RECOVERY = "window-store-recovery"

EVENT_TYPES = frozenset({
    EVENT_HEALTH_TRANSITION, EVENT_SHED, EVENT_QUARANTINE,
    EVENT_STALE_SERVE, EVENT_WATCHDOG, EVENT_BREAKER, EVENT_LEASE_HANDOFF,
    EVENT_DUMP, EVENT_REPLICA_JOIN, EVENT_REPLICA_LEAVE, EVENT_REBALANCE,
    EVENT_SHARD_ADOPTION, EVENT_STORE_RECOVERY,
})

MAX_DUMPS = 8  # newest dump files kept on disk per dump dir

# dump filenames are exactly what dump() writes (stamp + sanitized
# reason); the index/fetch endpoints validate against this so a request
# can never escape the dump dir or read arbitrary files
_DUMP_NAME_RE = re.compile(r"^foremast-flight-[A-Za-z0-9_-]+\.json$")


class FlightRecorder:
    """Bounded event ring + incident snapshot dumper.

    ``tracer``/``provenance``/``knobs_fn``/``health_fn`` are optional
    read-only taps the dump folds in; each degrades to an empty section
    when absent (tests construct bare recorders)."""

    def __init__(self, max_events: int = 512, dump_dir: str = "",
                 tracer=None, provenance=None, knobs_fn=None,
                 health_fn=None, min_dump_interval_s: float = 60.0):
        self._lock = make_lock("engine.flightrec")
        self._events: deque = deque(maxlen=max(int(max_events), 16))
        self.dump_dir = dump_dir or tempfile.gettempdir()
        self.tracer = tracer
        self.provenance = provenance
        self.knobs_fn = knobs_fn      # () -> {name: current value}
        self.health_fn = health_fn    # () -> (state, detail)
        self.min_dump_interval_s = float(min_dump_interval_s)
        # None = never auto-dumped: time.monotonic() is time-since-boot on
        # Linux, so a 0.0 sentinel would rate-limit away the first incident
        # of a pod born broken shortly after VM boot
        self._last_auto_dump: float | None = None
        self.events_total = 0
        self.dumps_total = 0
        self.last_dump_path = ""

    # ------------------------------------------------------------- events
    def record_event(self, etype: str, **detail):
        """Append one structured event (detail values must be JSON-safe)."""
        ev = {"ts": time.time(), "type": etype, "detail": detail}
        with self._lock:
            self._events.append(ev)
            self.events_total += 1

    def snapshot(self, limit: int = 100) -> list[dict]:
        with self._lock:
            return [dict(e) for e in list(self._events)[-limit:]]

    # ------------------------------------------------------------- health
    def record_transition(self, old: str, new: str, detail: dict):
        """Append one health-transition event (cheap: ring append only,
        safe to call while the health monitor still holds its state lock
        so the ring order always matches the edge order)."""
        self.record_event(EVENT_HEALTH_TRANSITION, old=old, new=new,
                          **{k: v for k, v in detail.items()
                             if k != "open_breakers"})

    def maybe_auto_dump(self, new: str, detail: dict):
        """Transitions into OVERLOADED/STALLED auto-dump (rate-limited:
        a state flapping at cycle cadence must not write a dump per
        cycle). Dumping does file I/O and re-reads tracer/provenance
        state — call it OUTSIDE any engine lock."""
        if new not in ("overloaded", "stalled"):
            return
        now = time.monotonic()
        with self._lock:
            if (self._last_auto_dump is not None
                    and now - self._last_auto_dump < self.min_dump_interval_s):
                return
            self._last_auto_dump = now
        self.dump(reason=f"health:{new}", health=(new, detail))

    def on_health_transition(self, old: str, new: str, detail: dict):
        """Record + maybe-dump in one call, for callers with no lock held."""
        self.record_transition(old, new, detail)
        self.maybe_auto_dump(new, detail)

    # -------------------------------------------------------------- dumps
    def _affected_jobs(self, events: list[dict]) -> list[str]:
        ids: list[str] = []
        seen = set()
        for ev in events:
            jid = ev.get("detail", {}).get("job_id")
            jids = ev.get("detail", {}).get("jobs") or ()
            for j in ([jid] if jid else []) + list(jids):
                if j not in seen:
                    seen.add(j)
                    ids.append(j)
        return ids[:64]

    def dump(self, reason: str, health=None) -> str | None:
        """Write one self-contained incident snapshot; returns its path.
        Best-effort: a full disk or read-only volume must never take the
        engine down with it (failures log and return None)."""
        self.record_event(EVENT_DUMP, reason=reason)
        events = self.snapshot(limit=self._events.maxlen)
        payload: dict = {
            "reason": reason,
            "ts": time.time(),
            "events": events,
        }
        try:
            if health is None and self.health_fn is not None:
                health = self.health_fn()
            if health is not None:
                payload["health"] = {"state": health[0], "detail": health[1]}
            if self.tracer is not None:
                payload["traces"] = self.tracer.snapshot(limit=20)
            if self.provenance is not None:
                payload["provenance"] = {
                    "affected_jobs": self.provenance.for_jobs(
                        self._affected_jobs(events)),
                    "recent": self.provenance.recent(limit=20),
                }
            if self.knobs_fn is not None:
                payload["knobs"] = self.knobs_fn()
            os.makedirs(self.dump_dir, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            safe_reason = "".join(
                c if c.isalnum() or c in "-_" else "-" for c in reason)
            path = os.path.join(
                self.dump_dir,
                f"foremast-flight-{stamp}-{safe_reason}.json")
            tmp = f"{path}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
            self._prune_dumps()
            with self._lock:
                self.dumps_total += 1
                self.last_dump_path = path
            log.warning("flight recorder dumped %s (%s)", path, reason)
            return path
        except Exception as e:  # noqa: BLE001 - diagnostics must not crash
            log.warning("flight dump failed (%s): %s", reason, e)
            return None

    def list_dumps(self) -> list[dict]:
        """Index of on-disk dumps (newest first): name, age, size, and
        the trigger parsed back out of the filename — so an operator can
        find the right historical incident from /debug/flight/dumps
        instead of shelling into the pod."""
        try:
            names = os.listdir(self.dump_dir)
        except OSError:
            return []
        now = time.time()
        out = []
        for fn in names:
            if not _DUMP_NAME_RE.match(fn):
                continue
            try:
                st = os.stat(os.path.join(self.dump_dir, fn))
            except OSError:
                continue
            # foremast-flight-<stamp>-<reason>.json; the stamp never
            # contains '-', so the first split yields the trigger intact
            stem = fn[len("foremast-flight-"):-len(".json")]
            trigger = stem.split("-", 1)[1] if "-" in stem else ""
            out.append({
                "name": fn,
                "age_s": round(max(now - st.st_mtime, 0.0), 1),
                "size_bytes": st.st_size,
                "trigger": trigger,
            })
        out.sort(key=lambda d: d["age_s"])
        return out

    def read_dump(self, name: str) -> dict | None:
        """One dump's parsed payload by exact filename, or None (unknown
        name, invalid name, unreadable file). Names are validated against
        the dump filename grammar — no path components ever reach the
        filesystem join."""
        if not _DUMP_NAME_RE.match(name) or os.path.basename(name) != name:
            return None
        try:
            with open(os.path.join(self.dump_dir, name),
                      encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return None
        return payload if isinstance(payload, dict) else None

    def _prune_dumps(self):
        try:
            dumps = sorted(
                fn for fn in os.listdir(self.dump_dir)
                if fn.startswith("foremast-flight-") and fn.endswith(".json"))
            for fn in dumps[:-MAX_DUMPS]:
                os.unlink(os.path.join(self.dump_dir, fn))
        except OSError:
            pass
