"""Engine configuration: the reference brain's ML_* env surface.

Re-implements the config contract documented in foremast-brain/README.md
(:22-38, :49-55) and deployed at deploy/foremast/3_brain/foremast-brain.yaml
(:24-81): global algorithm/threshold/bound plus indexed per-metric-type
overrides (metric_type{N} / threshold{N} / bound{N} / min_lower_bound{N}),
min-data-point gates per pairwise test, and the stuck-job takeover limit.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass(frozen=True)
class MetricPolicy:
    """Per-metric-type judgment knobs."""

    threshold: float = 2.0  # band half-width in sigmas
    bound: int = 1  # bitmask: 1 upper, 2 lower, 3 both
    min_lower_bound: float = 0.0
    # static SLA limit when this metric plays the HPA reward role
    # (docs/dynamic_autoscaling.md:45-56); 0 = unset, inherit ML_SLA_LIMIT.
    # Interpreted per the metric's wire isAbsolute flag: absolute value on
    # the metric's scale, or a multiple of the healthy historical mean.
    sla_limit: float = 0.0


# deployed defaults (foremast-brain.yaml:34-73)
DEFAULT_POLICIES = {
    "error5xx": MetricPolicy(2.0, 1, 0.0),
    "error4xx": MetricPolicy(3.0, 1, 0.0),
    "latency": MetricPolicy(10.0, 3, 0.0),
    "cpu": MetricPolicy(5.0, 1, 0.0),
    "memory": MetricPolicy(5.0, 1, 0.0),
}

PAIRWISE_TESTS = ("mann_whitney", "wilcoxon", "kruskal", "ks")


@dataclass(frozen=True)
class EngineConfig:
    algorithm: str = "moving_average_all"  # ML_ALGORITHM
    pairwise_algorithm: str = "mann_whitney_all"  # ML_PAIRWISE_ALGORITHM
    pairwise_threshold: float = 0.01  # ML_PAIRWISE_THRESHOLD (p-value alpha)
    threshold: float = 2.0  # ML_THRESHOLD (band sigmas)
    bound: int = 1  # ML_BOUND bitmask
    min_lower_bound: float = 0.0
    min_historical_points: int = 10  # MIN_HISTORICAL_DATA_POINT_TO_MEASURE
    min_mann_whitney_points: int = 20  # MIN_MANN_WHITE_DATA_POINTS
    min_wilcoxon_points: int = 20  # MIN_WILCOXON_DATA_POINTS
    min_kruskal_points: int = 5  # MIN_KRUSKAL_DATA_POINTS
    min_friedman_points: int = 5  # MIN_FRIEDMAN_DATA_POINTS (paired blocks)
    max_stuck_seconds: float = 90.0  # MAX_STUCK_IN_SECONDS
    max_cache_size: int = 1024  # MAX_CACHE_SIZE (model/window cache entries)
    # jobs leased per cycle (MAX_CLAIM_PER_CYCLE). The batched cycle scores
    # every claimed job in one device program per bucket, so this is the
    # fleet batch size, not a per-worker work-queue depth; at 100k-fleet
    # scale the default must not silently cap the cycle.
    max_claim_per_cycle: int = 100_000
    # device-launch row chunk: the fleet-batched scorers (pairs, bands,
    # bivariate, hpa) split their packed batches into fixed rungs so XLA
    # compiles ONE program per (rung, T) bucket instead of re-specializing
    # on every fleet size (analyzer._score_chunks; the LSTM path scores
    # per job and has no fleet batch dimension to chunk)
    score_batch: int = 8192
    # per-job window fetches run on a bounded thread pool
    # (FETCH_CONCURRENCY; 1 = serial). In production the fetch stage is
    # network-bound against the metric store, so overlap is the difference
    # between cycle time scaling with fleet size and with store latency.
    fetch_concurrency: int = 16
    # streaming scoring pipeline (SCORE_PIPELINE; engine/pipeline.py):
    # preprocess->dispatch overlap + async device launches collected in a
    # final phase. Verdicts are byte-identical to the barriered path
    # (enforced by tests/test_pipeline.py); 0 restores the full-barrier
    # cycle for A/B or debugging.
    score_pipeline: bool = True
    # streamed-launch fire threshold (PIPELINE_FIRE_ROWS): a family/T
    # accumulator launches as soon as it holds this many rows, overlapping
    # device execution with the remaining fetches. Clamped to
    # [16, score_batch]; values are snapped to the batch-rung ladder so
    # mid-stream launches reuse the same compiled programs as the flush.
    # Scorers are row-wise, so earlier launch boundaries cannot change
    # verdicts. score_batch-sized = fire only on full chunks.
    pipeline_fire_rows: int = 1024
    # steady-state delta fetch (DELTA_FETCH; dataplane/delta.py): keep the
    # last grid Window per query identity and re-fetch only the tail each
    # cycle, splicing it in (byte-identical to a full refetch, enforced by
    # tests/test_delta.py). 0 restores the full-refetch path exactly —
    # the runtime simply doesn't insert the DeltaWindowSource layer.
    delta_fetch: bool = True
    # delta window-cache entries (WINDOW_CACHE_MAX): one per distinct
    # (query, window-role) URL identity — ~3 per job; also bounds the
    # score-memo table at 4x this value. This is the HOT-tier (RAM)
    # ceiling: with WINDOW_STORE_DIR set (dataplane/winstore.py),
    # eviction spills dirty entries to the columnar warm segment and a
    # miss promotes them back, so at million-job scale this knob bounds
    # resident window memory without forfeiting the cached state.
    window_cache_max: int = 8192
    # fingerprint score memoization (SCORE_MEMO; engine/pipeline.py):
    # hash each job's packed scorer inputs per (job, family, T-bucket) and
    # reuse the previous verdict when unchanged — the common steady-state
    # case for baseline/historical-driven families. Pipeline buckets then
    # hold only changed rows and fire fewer, smaller programs. Effective
    # with SCORE_PIPELINE=1 (the default); verdicts stay byte-identical
    # (scorers are deterministic row-wise functions of the fingerprinted
    # inputs — pinned by tests/test_delta.py's identity test).
    score_memo: bool = True
    # tier-0 triage screen (TRIAGE; engine/triage.py + ops/triage.py):
    # before the family scorers launch, changed rows of steady-state
    # (continuous/hpa-class) jobs ride one fused robust-z + smoother-
    # residual screen; rows the screen clears short-circuit to the
    # healthy verdict the full path would produce, suspects escalate to
    # the full scorers unchanged. Verdict-safe by construction (see
    # engine/triage.py: shrunk-band dominance for the moving-average
    # band family; canary-class jobs, the hpa family, and
    # non-moving-average band algorithms always escalate) and by test
    # (the escalation-threshold sweep in tests/test_triage.py). Effective
    # with SCORE_PIPELINE=1 (the gate lives in the pipeline); 0 restores
    # the screen-free path exactly.
    triage: bool = True
    # robust z-band escalation guard (TRIAGE_Z): rows whose max
    # |x - median(hist)| / robust-scale over the current region exceeds
    # this always escalate, whatever the residual band says. Escalation-
    # only defense in depth — lowering it cannot change verdicts, only
    # shrink the launch savings (0 = screen nothing).
    triage_z: float = 8.0
    # one-sided CLEAR margin in sigmas (TRIAGE_MARGIN): a row clears only
    # while its violation count of the policy band SHRUNK by this much
    # stays under the family's verdict gate. The shrunk band is strictly
    # narrower, so its count dominates the real one (sub-gate shrunk
    # count => sub-gate real count => healthy), and any point the full
    # scorer could count differently sits within float ulps of the real
    # boundary — i.e. a macroscopic margin*sigma outside the shrunk band,
    # so drift flips cannot change the CLEAR decision. 0 removes the
    # drift guard (NOT recommended); >= the policy threshold disables
    # clearing.
    triage_margin: float = 0.25
    # minimum valid history points for a row to be screenable
    # (TRIAGE_MIN_POINTS); thinner rows always take the full path
    triage_min_points: int = 24
    # screen batch coarseness (TRIAGE_FIRE_ROWS): rows per fused screen
    # launch at T<=1024 (scaled down ~1/T past that for bounded launch
    # memory). An order of magnitude coarser than PIPELINE_FIRE_ROWS on
    # purpose: the screen is one cheap pass, so fewer, bigger launches
    # are the point.
    triage_fire_rows: int = 16384
    # families the screen may clear (TRIAGE_FAMILIES, comma list). The
    # default is the provably one-sided set: band (under moving_average*
    # algorithms only). pair/bivariate opt-in is NOT verdict-safe: the
    # screen cannot bound rank-test p-values or ellipse correlation, so
    # a sustained sub-band distribution shift the full scorer would
    # convict can clear (docs/performance.md §5); hpa is never screened.
    triage_families: tuple = ("band",)
    # single-dispatch mega-batching (MEGABATCH; engine/pipeline.py):
    # instead of firing per-(family, T-bucket) rung launches mid-stream,
    # each family's accumulator holds the WHOLE cycle's rows and flushes
    # as one padded launch per (family, T) — the rung ladder becomes
    # padding classes (mantissa-quantized above 512 rows, <= 1/16 waste;
    # analyzer._mega_rows), so a family costs ONE program launch per
    # cycle up to the MEGABATCH_MAX_ROWS ceiling (a 100k-row family
    # chunks at the ceiling into ~4 launches — vs ~13 rung chunks).
    # Trades the pipeline's
    # fetch/score overlap for launch count — the right trade once
    # dispatch overhead dominates (100k+ fleets; docs/performance.md §6).
    # Verdicts are byte-identical either way (scorers are row-wise;
    # pinned by tests/test_megabatch.py). Off by default: small fleets
    # keep the overlap, and the prewarm grid covers the rung programs.
    megabatch: bool = False
    # mega-launch row ceiling at T<=1024 (MEGABATCH_MAX_ROWS; scaled
    # down ~1/T beyond, floor 1024, for bounded launch memory). Fleets
    # past the cap chunk at it — still ~8x fewer launches than the rung
    # path's score_batch chunks.
    megabatch_max_rows: int = 32768
    # persistent XLA compilation cache directory (COMPILE_CACHE_PATH;
    # empty = disabled). A restarted process reuses compiled programs
    # instead of re-paying the first-cycle compile storm (~26 s per mixed
    # fleet on CPU, BENCH_r05).
    compile_cache_path: str = ""
    # compile the standard (family x rung x T-bucket) grid in a background
    # thread at startup (PREWARM_ON_START; engine/pipeline.py:prewarm), so
    # the first live cycle doesn't eat the compile storm either. Also
    # available ahead of deploy as `foremast-tpu prewarm`.
    prewarm_on_start: bool = False
    ma_window: int = 30  # moving-average lookback (steps)
    # windows at/above this length use the time-parallel associative-scan
    # SES smoother (ops/seqscan.py) instead of sequential lax.scan; DES
    # always stays sequential (f32 drift — see seqscan.py docstring)
    long_window_steps: int = 4096  # LONG_WINDOW_STEPS
    hw_period: int = 1440  # Holt-Winters / seasonal-trend period (steps; 1 day at 60s)
    # seasonality auto-detection (ops/forecast.py:detect_period): when on,
    # each band job's history votes among the candidate periods by masked
    # detrended autocorrelation; hw_period is only the fallback for series
    # with no supported/confident candidate. Candidates are operational
    # cycles in steps at 60 s: hour / shift / day.
    hw_period_auto: bool = True  # HW_PERIOD_AUTO
    hw_period_candidates: tuple = (60, 480, 720, 1440)  # HW_PERIOD_CANDIDATES
    hw_min_seasonal_acf: float = 0.2  # HW_MIN_SEASONAL_ACF
    # harmonic-alias margin: a shorter (fundamental-first) candidate wins
    # when its ACF score sits within this of the best candidate's. Larger
    # = stronger preference for the fundamental over its multiples, at
    # the cost of letting a noisier short candidate beat a genuinely
    # better long one (ops/forecast.py:detect_period).
    hw_alias_margin: float = 0.05  # HW_ALIAS_MARGIN
    # half-lag contrast slack: a candidate fails only when its half-lag
    # ACF beats its lag-p ACF by MORE than this (ties within noise are
    # harmonically valid picks — see ops/forecast.py:detect_period)
    hw_contrast_margin: float = 0.01  # HW_CONTRAST_MARGIN
    st_order: int = 3  # seasonal-trend (prophet) Fourier order
    # Prophet piecewise-linear trend: hinge changepoints on a uniform grid
    # over the first 80% of the window, L1-ish shrunk (iterated ridge) so
    # the trend stays piecewise-sparse (ops/forecast.py:fit_seasonal_trend).
    # 0 restores the single linear trend.
    st_changepoints: int = 12  # ST_CHANGEPOINTS
    # LSTM-autoencoder multivariate mode (3+ metrics; faq.md:8-10)
    lstm_window: int = 32  # subwindow length (steps) per training sample
    lstm_epochs: int = 30
    lstm_hidden: int = 32
    lstm_latent: int = 16
    lstm_threshold: float = 3.0  # recon-error z-score gate
    # train-on-miss budget per cycle: a cold multi-metric fleet must warm
    # up across cycles instead of blowing one cycle's budget on unbounded
    # AE training (jobs beyond the budget stay in progress and train on a
    # later cycle). <= 0 removes the cap.
    lstm_max_train_per_cycle: int = 8  # LSTM_MAX_TRAIN_PER_CYCLE
    # reference model dispatch by metric count (design.md:53-88): 2-metric
    # jobs -> bivariate normal, 3+ -> LSTM-AE, regardless of ML_ALGORITHM
    # (which names the univariate forecaster). False = route multivariate
    # families only when ML_ALGORITHM names them explicitly.
    multimetric_auto: bool = True  # ML_MULTIMETRIC_AUTO
    # band verdict gate: a window is unhealthy when
    # count >= max(band_min_points, band_violation_fraction * checked).
    # A single k-sigma excursion in a 30-point window is expected Gaussian
    # noise (~4.5% of points at 2 sigma); the per-metric thresholds assume
    # near-zero-variance error metrics, so noisy metrics need the gate.
    band_min_points: int = 2
    band_violation_fraction: float = 0.1
    # HPA reward shaping (SLA_HEADROOM_SAFE): below this SLA-budget
    # utilization scale-down is fully model-driven; between it and 1.0 the
    # reward ramps scale-down off (ops/hpa.py reward-shaping block)
    sla_headroom_safe: float = 0.7
    # SLA criteria mode for the HPA reward (ML_SLA_MODE; reference
    # dynamic_autoscaling.md:45-56): "static" fixed limit, "dynamic"
    # mean+3sigma of healthy history, "min" = min of both. Static modes
    # need a limit (ML_SLA_LIMIT or per-metric sla_limit{N}); a static
    # mode with no limit configured degrades to dynamic for that job.
    sla_mode: str = "dynamic"  # ML_SLA_MODE
    sla_limit: float = 0.0  # ML_SLA_LIMIT (0 = unset)
    # limit interpretation default: False = limits are ABSOLUTE values on
    # the metric's scale (latency ms — the deploy convention); True =
    # un-flagged metrics read the limit as a multiple of the healthy
    # historical mean. A wire isAbsolute=true always pins that metric
    # absolute. Guards ML_SLA_LIMIT=250(ms) from silently becoming
    # 250*mean under the wire flag's bare default.
    sla_limit_relative: bool = False  # ML_SLA_LIMIT_RELATIVE
    # -- resilience layer (resilience/; docs/resilience.md) --
    # retry train per fetch: attempts, exponential-backoff base/cap
    retry_max_attempts: int = 3  # RETRY_MAX_ATTEMPTS
    retry_base_delay: float = 0.2  # RETRY_BASE_DELAY (seconds)
    retry_max_delay: float = 5.0  # RETRY_MAX_DELAY (seconds)
    # per-window retry budget shared across every fetch: a dead backend
    # sees bounded TOTAL load (first attempts + budget), never
    # first-attempts x max_attempts. <= 0 removes the cap.
    retry_budget: int = 64  # RETRY_BUDGET
    retry_budget_window_seconds: float = 60.0  # RETRY_BUDGET_WINDOW
    # circuit breaker per endpoint host: consecutive failures to trip,
    # seconds open before a half-open probe
    breaker_failure_threshold: int = 5  # BREAKER_FAILURE_THRESHOLD
    breaker_recovery_seconds: float = 30.0  # BREAKER_RECOVERY_SECONDS
    # per-cycle fetch deadline: retries (and their backoff sleeps) must
    # finish inside this budget so a flapping backend cannot stretch the
    # cycle past its cadence. 0 disables.
    fetch_cycle_deadline_seconds: float = 8.0  # FETCH_CYCLE_DEADLINE
    # -- degraded-mode operation (docs/resilience.md runbook) --
    # whole-cycle deadline budget (CYCLE_DEADLINE_S): once it burns down,
    # STEADY-STATE monitor jobs (continuous/hpa) not yet preprocessed are
    # SHED and carry over to the next cycle instead of going
    # COMPLETED_UNKNOWN; new-deployment analyses are exempt (their
    # verdict gates a live rollout — a canary-heavy overrun shows as the
    # deadline_overrun health detail, not shedding). The first
    # monitor-class job is always guaranteed through per cycle (the
    # floor), and a shed job sorts to the head of the monitor class next
    # cycle, so every monitor makes progress even under a
    # permanently-blown budget.
    # 0 disables (unbounded cycles — the pre-degraded-mode behavior).
    cycle_deadline_seconds: float = 0.0  # CYCLE_DEADLINE_S
    # stale-verdict serving bound (MAX_STALE_S): when a warm job's fetch
    # exhausts retries / hits an open breaker / returns no data, its last
    # healthy verdict (at most this old) is re-served — stamped with its
    # staleness age — instead of flapping the job to PREPROCESS_FAILED or
    # COMPLETED_UNKNOWN. 0 disables stale serving.
    max_stale_seconds: float = 300.0  # MAX_STALE_S
    # poison-job quarantine (QUARANTINE_AFTER): a job whose per-job
    # _isolate retry fails this many CONSECUTIVE cycles is parked with
    # exponential re-admission backoff (30 s doubling, capped 3600 s)
    # instead of re-burning the blast-radius fallback every cycle
    # forever. 0 disables quarantine.
    quarantine_after: int = 3  # QUARANTINE_AFTER
    # hung-launch watchdog (WATCHDOG_S): bound on one bucket's device
    # materialization in the pipeline collect phase; a stuck launch times
    # out, fails over to the sync per-job path, and is counted on
    # foremastbrain:watchdog_fires_total. 0 disables (the safe default:
    # big first-cycle CPU executions can legitimately run long — enable
    # it once the fleet's shapes are prewarmed/compile-cached).
    watchdog_seconds: float = 0.0  # WATCHDOG_S
    # -- observability (docs/operations.md "Debugging a verdict") --
    # verdict provenance recording (PROVENANCE): per-(job, cycle)
    # attribution records — which verdict path fired (scored / memo-hit /
    # stale-served / shed-carryover / quarantined / watchdog-failover /
    # blast-radius-isolated), per-family scores vs thresholds, fetch mode
    # — served at /jobs/<id>/explain and attached to archived terminal
    # Documents. Recording only observes the cycle (verdicts are
    # byte-identical either way — pinned by tests/test_provenance.py);
    # 0 disables for the A/B leg.
    provenance: bool = True  # PROVENANCE
    # flight-recorder dump directory (FLIGHT_DUMP_DIR): incident JSON
    # snapshots (events + traces + provenance + knobs) written on the
    # transition into OVERLOADED/STALLED and on SIGTERM. Empty = the
    # system temp dir.
    flight_dump_dir: str = ""  # FLIGHT_DUMP_DIR
    # detection-latency SLO targets per job class (engine/slo.py):
    # ingest (window advance) -> verdict latency budget in seconds.
    # Canary verdicts gate live rollouts so their target is tightest;
    # monitors/hpa re-judge every cycle and budget a cadence or two.
    # 0 disables the target for that class (latency is still measured —
    # the histograms/quantiles always record; only attainment/burn need
    # a target). SLO_OBJECTIVE is the attainment goal the error budget
    # derives from (0.99 = 1% of verdicts may miss the target).
    slo_canary_seconds: float = 30.0  # SLO_CANARY_S
    slo_continuous_seconds: float = 60.0  # SLO_CONTINUOUS_S
    slo_hpa_seconds: float = 60.0  # SLO_HPA_S
    slo_objective: float = 0.99  # SLO_OBJECTIVE
    policies: dict = field(default_factory=lambda: dict(DEFAULT_POLICIES))

    def policy_for(self, metric_name: str) -> MetricPolicy:
        """Longest-substring match of configured metric types in the name
        (metric names arrive as e.g. namespace_app_pod_http_errors_5xx)."""
        best = None
        for key, pol in self.policies.items():
            norm = key.replace("error", "").lower()
            if key.lower() in metric_name.lower() or (
                norm and norm in metric_name.lower()
            ):
                if best is None or len(key) > len(best[0]):
                    best = (key, pol)
        if best:
            return best[1]
        return MetricPolicy(self.threshold, self.bound, self.min_lower_bound)

    @property
    def pairwise_combine_all(self) -> bool:
        return self.pairwise_algorithm.endswith("_all") or self.pairwise_algorithm == "all"

    def enabled_tests(self) -> int:
        """Bitmask of enabled pairwise tests (parallel.fleet TEST_* bits)."""
        from ..parallel import fleet as fl

        name = self.pairwise_algorithm
        table = {
            "mann_whitney": fl.TEST_MANN_WHITNEY,
            "wilcoxon": fl.TEST_WILCOXON,
            "kruskal": fl.TEST_KRUSKAL,
            "ks": fl.TEST_KS,
            "friedman": fl.TEST_FRIEDMAN,
        }
        for key, bit in table.items():
            if name.startswith(key):
                return bit
        # "all"/"any" composite modes enable the full family
        return (
            fl.TEST_MANN_WHITNEY | fl.TEST_WILCOXON | fl.TEST_KRUSKAL
            | fl.TEST_KS | fl.TEST_FRIEDMAN
        )


def _env_float(env, key, default):
    try:
        return float(env[key])
    except (KeyError, ValueError):
        return default


def _env_int(env, key, default):
    try:
        return int(env[key])
    except (KeyError, ValueError):
        return default


def _env_bool(env, key, default):
    """One definition of env truthiness for every boolean knob (operators
    write 0/1, true/false, yes/no, on/off in any case)."""
    raw = env.get(key)
    if raw is None:
        return default
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


def from_env(env=None) -> EngineConfig:
    """Build an EngineConfig from the ML_* env-var family."""
    env = dict(os.environ) if env is None else env
    policies = dict(DEFAULT_POLICIES)
    base = MetricPolicy(
        threshold=_env_float(env, "threshold", 2.0),
        bound=_env_int(env, "bound", 1),
        min_lower_bound=_env_float(env, "min_lower_bound", 0.0),
    )
    n = _env_int(env, "metric_type_threshold_count", 0)
    for i in range(n):
        name = env.get(f"metric_type{i}")
        if not name:
            continue
        policies[name] = MetricPolicy(
            threshold=_env_float(env, f"threshold{i}", base.threshold),
            bound=_env_int(env, f"bound{i}", base.bound),
            min_lower_bound=_env_float(env, f"min_lower_bound{i}", base.min_lower_bound),
            sla_limit=_env_float(env, f"sla_limit{i}", 0.0),
        )
    return EngineConfig(
        algorithm=env.get("ML_ALGORITHM", "moving_average_all"),
        pairwise_algorithm=env.get("ML_PAIRWISE_ALGORITHM", "mann_whitney_all"),
        pairwise_threshold=_env_float(env, "ML_PAIRWISE_THRESHOLD", 0.01),
        threshold=base.threshold,
        bound=base.bound,
        min_lower_bound=base.min_lower_bound,
        min_historical_points=_env_int(env, "MIN_HISTORICAL_DATA_POINT_TO_MEASURE", 10),
        min_mann_whitney_points=_env_int(env, "MIN_MANN_WHITE_DATA_POINTS", 20),
        min_wilcoxon_points=_env_int(env, "MIN_WILCOXON_DATA_POINTS", 20),
        min_kruskal_points=_env_int(env, "MIN_KRUSKAL_DATA_POINTS", 5),
        min_friedman_points=_env_int(env, "MIN_FRIEDMAN_DATA_POINTS", 5),
        max_stuck_seconds=_env_float(env, "MAX_STUCK_IN_SECONDS", 90.0),
        max_cache_size=_env_int(env, "MAX_CACHE_SIZE", 1024),
        max_claim_per_cycle=_env_int(env, "MAX_CLAIM_PER_CYCLE", 100_000),
        score_batch=_env_int(env, "SCORE_BATCH", 8192),
        fetch_concurrency=_env_int(env, "FETCH_CONCURRENCY", 16),
        score_pipeline=_env_bool(env, "SCORE_PIPELINE", True),
        pipeline_fire_rows=_env_int(env, "PIPELINE_FIRE_ROWS", 1024),
        delta_fetch=_env_bool(env, "DELTA_FETCH", True),
        window_cache_max=_env_int(env, "WINDOW_CACHE_MAX", 8192),
        score_memo=_env_bool(env, "SCORE_MEMO", True),
        triage=_env_bool(env, "TRIAGE", True),
        triage_z=_env_float(env, "TRIAGE_Z", 8.0),
        triage_margin=_env_float(env, "TRIAGE_MARGIN", 0.25),
        triage_min_points=_env_int(env, "TRIAGE_MIN_POINTS", 24),
        triage_fire_rows=_env_int(env, "TRIAGE_FIRE_ROWS", 16384),
        triage_families=tuple(
            f.strip() for f in env.get("TRIAGE_FAMILIES", "band").split(",")
            if f.strip()
        ),
        megabatch=_env_bool(env, "MEGABATCH", False),
        megabatch_max_rows=_env_int(env, "MEGABATCH_MAX_ROWS", 32768),
        compile_cache_path=env.get("COMPILE_CACHE_PATH", ""),
        prewarm_on_start=_env_bool(env, "PREWARM_ON_START", False),
        ma_window=_env_int(env, "MA_WINDOW", 30),
        long_window_steps=_env_int(env, "LONG_WINDOW_STEPS", 4096),
        hw_period=_env_int(env, "HW_PERIOD", 1440),
        hw_period_auto=_env_bool(env, "HW_PERIOD_AUTO", True),
        hw_period_candidates=tuple(
            int(p) for p in env.get("HW_PERIOD_CANDIDATES", "60,480,720,1440").split(",")
            if p.strip()
        ),
        hw_min_seasonal_acf=_env_float(env, "HW_MIN_SEASONAL_ACF", 0.2),
        hw_alias_margin=_env_float(env, "HW_ALIAS_MARGIN", 0.05),
        hw_contrast_margin=_env_float(env, "HW_CONTRAST_MARGIN", 0.01),
        st_order=_env_int(env, "ST_ORDER", 3),
        st_changepoints=_env_int(env, "ST_CHANGEPOINTS", 12),
        lstm_window=_env_int(env, "LSTM_WINDOW", 32),
        lstm_epochs=_env_int(env, "LSTM_EPOCHS", 30),
        lstm_hidden=_env_int(env, "LSTM_HIDDEN", 32),
        lstm_latent=_env_int(env, "LSTM_LATENT", 16),
        lstm_threshold=_env_float(env, "LSTM_THRESHOLD", 3.0),
        lstm_max_train_per_cycle=_env_int(env, "LSTM_MAX_TRAIN_PER_CYCLE", 8),
        multimetric_auto=_env_bool(env, "ML_MULTIMETRIC_AUTO", True),
        sla_headroom_safe=_env_float(env, "SLA_HEADROOM_SAFE", 0.7),
        sla_mode=env.get("ML_SLA_MODE", "dynamic").strip().lower(),
        sla_limit=_env_float(env, "ML_SLA_LIMIT", 0.0),
        sla_limit_relative=_env_bool(env, "ML_SLA_LIMIT_RELATIVE", False),
        retry_max_attempts=_env_int(env, "RETRY_MAX_ATTEMPTS", 3),
        retry_base_delay=_env_float(env, "RETRY_BASE_DELAY", 0.2),
        retry_max_delay=_env_float(env, "RETRY_MAX_DELAY", 5.0),
        retry_budget=_env_int(env, "RETRY_BUDGET", 64),
        retry_budget_window_seconds=_env_float(env, "RETRY_BUDGET_WINDOW", 60.0),
        breaker_failure_threshold=_env_int(env, "BREAKER_FAILURE_THRESHOLD", 5),
        breaker_recovery_seconds=_env_float(env, "BREAKER_RECOVERY_SECONDS", 30.0),
        fetch_cycle_deadline_seconds=_env_float(env, "FETCH_CYCLE_DEADLINE", 8.0),
        cycle_deadline_seconds=_env_float(env, "CYCLE_DEADLINE_S", 0.0),
        max_stale_seconds=_env_float(env, "MAX_STALE_S", 300.0),
        quarantine_after=_env_int(env, "QUARANTINE_AFTER", 3),
        watchdog_seconds=_env_float(env, "WATCHDOG_S", 0.0),
        provenance=_env_bool(env, "PROVENANCE", True),
        flight_dump_dir=env.get("FLIGHT_DUMP_DIR", ""),
        slo_canary_seconds=_env_float(env, "SLO_CANARY_S", 30.0),
        slo_continuous_seconds=_env_float(env, "SLO_CONTINUOUS_S", 60.0),
        slo_hpa_seconds=_env_float(env, "SLO_HPA_S", 60.0),
        slo_objective=_env_float(env, "SLO_OBJECTIVE", 0.99),
        policies=policies,
    )
