"""Engine scheduling: event-driven partial cycles + reconciliation sweeps.

The brain ran one shape of loop since PR 1: sleep ``CYCLE_SECONDS``, then
score the whole claimed fleet (the reference's ES poll loop,
docs/guides/design.md:37-43). PR 10's detection-latency SLOs made that
loop's cost legible — steady-state p99 sits at the metric step, because a
fresh sample waits out the TTL cache plus the tick before anything looks
at it. ``StreamScheduler`` removes the wait for PUSHED jobs:

  * **Partial cycles.** The ingest receiver (``foremast_tpu/ingest``)
    calls ``notify(job_ids)`` when a pushed sample advances a job's
    window past its step boundary. The scheduler batches notifications
    for a short debounce window, then runs ``analyzer.run_cycle`` over
    exactly those jobs — the same pipeline rungs (fingerprint memo →
    tier-0 triage → family accumulators), just scoped to the jobs with
    fresh evidence. Verdict latency becomes push latency, not cadence.
  * **Reconciliation sweeps.** The full-fleet cycle keeps running at
    ``cycle_seconds`` cadence as the fallback for jobs nobody pushes
    for, and as the self-healing pass that re-verifies push-fed windows
    against the backend (the delta splice canary). The sweep callback is
    the runtime's whole per-lap chore list (shard tick, adoption scan,
    model-cache save, gc), unchanged.

One thread runs both, so partial cycles and sweeps are naturally
serialized against each other — the analyzer's per-cycle state needs no
new locking. ``notify`` itself only takes the scheduler's condition
lock, so ingest HTTP threads never block on (or behind) scoring.

``EngineWorker`` below is the pre-streaming loop, kept for embedders and
tests that want the bare cadence worker without a runtime.
"""
from __future__ import annotations

import logging
import threading
import time

from .analyzer import Analyzer
from ..utils.locks import make_lock

log = logging.getLogger("foremast_tpu.engine")


class EngineWorker:
    def __init__(self, analyzer: Analyzer, name: str = "worker-0",
                 poll_interval: float = 10.0):
        self.analyzer = analyzer
        self.name = name
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.cycles = 0
        self.last_error: str = ""

    def start(self):
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.analyzer.run_cycle(worker=self.name)
                self.cycles += 1
            except Exception as e:  # noqa: BLE001 - worker must survive
                self.last_error = f"{type(e).__name__}: {e}"
                log.exception("engine cycle failed")
            self._stop.wait(self.poll_interval)

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout)


class StreamScheduler:
    """Event-driven engine scheduler (module docstring).

    ``run(stop_event)`` is the worker loop body — the runtime points its
    worker thread here. ``notify(job_ids)`` is the ingest tap: safe from
    any thread, never blocks on scoring.
    """

    def __init__(self, analyzer: Analyzer, full_cycle_fn,
                 cycle_seconds: float = 10.0, worker: str = "worker-0",
                 debounce_seconds: float = 0.15,
                 max_partial_jobs: int = 4096, exporter=None,
                 checkpoint_fn=None):
        self.analyzer = analyzer
        self.full_cycle_fn = full_cycle_fn
        # durability chore after each partial cycle (the runtime's
        # window-store checkpoint): pushed-dirtied window state folds
        # into the warm segments between sweeps, so a long CYCLE_SECONDS
        # under sustained push traffic bounds WAL growth at the
        # checkpoint rate limit, not the sweep cadence. Best-effort —
        # the callee rate-limits and swallows its own I/O failures.
        self.checkpoint_fn = checkpoint_fn
        self.cycle_seconds = max(float(cycle_seconds), 0.05)
        self.worker = worker
        # pushes arrive per scrape target; the debounce window folds one
        # scrape interval's burst into ONE partial cycle instead of a
        # cycle per HTTP request
        self.debounce_seconds = max(float(debounce_seconds), 0.0)
        # a notify burst larger than this rides the next full sweep
        # instead of a mega partial cycle (the sweep is the batched path)
        self.max_partial_jobs = max(int(max_partial_jobs), 1)
        self.exporter = exporter
        self._cond = threading.Condition(make_lock("engine.scheduler"))
        self._pending: set[str] = set()
        # observability
        self.partial_cycles_total = 0
        self.partial_jobs_total = 0
        self.notifications_total = 0
        self.sweeps_total = 0
        self.last_partial_at = 0.0

    # ------------------------------------------------------------- ingest
    def notify(self, job_ids) -> int:
        """Mark jobs dirty for an immediate partial cycle. Returns how
        many were newly marked (already-pending ids fold in free)."""
        ids = set(job_ids)
        if not ids:
            return 0
        with self._cond:
            before = len(self._pending)
            self._pending |= ids
            added = len(self._pending) - before
            self.notifications_total += 1
            self._cond.notify()
        # waterfall: the debounce/schedule wait clock starts at notify
        # (engine/slo.py DetectionWaterfall; no-op for unpushed jobs)
        wf = getattr(self.analyzer, "waterfall", None)
        if wf is not None:
            wf.notify(ids)
        return added

    # --------------------------------------------------------------- loop
    def run(self, stop_event: threading.Event):
        """The worker loop: full sweep immediately, then event-driven.

        Sweep cadence matches the old poll loop exactly — the next sweep
        lands ``cycle_seconds`` after the previous one STARTED, floored
        at zero (a slow sweep runs back-to-back, never piles up)."""
        while not stop_event.is_set():
            t0 = time.monotonic()
            self._sweep()
            next_sweep = t0 + self.cycle_seconds
            while not stop_event.is_set():
                with self._cond:
                    timeout = next_sweep - time.monotonic()
                    if not self._pending and timeout > 0:
                        # bounded wait so stop_event stays responsive
                        # even with no pushes and a long cadence
                        self._cond.wait(min(timeout, 0.25))
                    pending = bool(self._pending)
                if time.monotonic() >= next_sweep:
                    break
                if pending and not stop_event.is_set():
                    self._debounce(stop_event, next_sweep)
                    if not self._partial_cycle():
                        # burst bigger than the partial budget: the full
                        # sweep IS the batched path for it — run it now
                        # instead of spinning on the unconsumed pending
                        # set until the cadence tick
                        break

    def _debounce(self, stop_event, next_sweep: float):
        """Let one scrape burst coalesce before the partial cycle."""
        if self.debounce_seconds <= 0:
            return
        deadline = min(time.monotonic() + self.debounce_seconds,
                       next_sweep)
        while not stop_event.is_set():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            stop_event.wait(min(remaining, 0.05))

    def _sweep(self):
        """One full reconciliation sweep; pending jobs fold into it (the
        sweep claims the whole fleet, so a separate partial would only
        double-score)."""
        with self._cond:
            self._pending.clear()
        try:
            self.full_cycle_fn()
            self.sweeps_total += 1
        except Exception:  # noqa: BLE001 - the loop must survive
            log.exception("reconciliation sweep failed")

    def _partial_cycle(self) -> bool:
        """Run one partial cycle over the pending set. Returns False
        when the set exceeds the partial budget (the caller escalates
        to an immediate full sweep — which clears it)."""
        with self._cond:
            if not self._pending:
                return True
            if len(self._pending) > self.max_partial_jobs:
                return False
            ids = frozenset(self._pending)
            self._pending.clear()
        # waterfall: the partial cycle starts NOW — split each job's
        # measured notify->start wait into debounce vs schedule stages
        wf = getattr(self.analyzer, "waterfall", None)
        if wf is not None:
            wf.claim(ids, self.debounce_seconds)
        try:
            self.analyzer.run_cycle(worker=self.worker, job_ids=ids,
                                    partial=True)
            self.partial_cycles_total += 1
            self.partial_jobs_total += len(ids)
            self.last_partial_at = time.time()
            if self.exporter is not None:
                self.exporter.record_counter(
                    "foremastbrain:partial_cycles_total", {},
                    help="event-driven partial engine cycles (pushed "
                         "jobs scored without waiting for the tick)")
                self.exporter.record_counter(
                    "foremastbrain:partial_cycle_jobs_total", {},
                    len(ids),
                    help="jobs scored through event-driven partial "
                         "cycles")
        except Exception:  # noqa: BLE001 - the loop must survive
            log.exception("partial cycle failed")
        if self.checkpoint_fn is not None:
            try:
                self.checkpoint_fn()
            except Exception:  # noqa: BLE001 - durability is best-effort
                log.exception("post-partial checkpoint failed")
        return True

    # ------------------------------------------------------ observability
    def snapshot(self) -> dict:
        with self._cond:
            pending = len(self._pending)
        return {
            "cycle_seconds": self.cycle_seconds,
            "debounce_seconds": self.debounce_seconds,
            "pending_jobs": pending,
            "partial_cycles": self.partial_cycles_total,
            "partial_jobs": self.partial_jobs_total,
            "notifications": self.notifications_total,
            "sweeps": self.sweeps_total,
        }
