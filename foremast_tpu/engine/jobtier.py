"""Tiered, crash-durable job store backing: segments + WAL for jobs.

PR 13 made the *window* store crash-durable; this module extends the
same machinery (``dataplane/segfile.py`` CRC framing) to the JOB store
and provenance records — the last state surface where a kill -9 could
forfeit acked work, and the RAM ceiling between the measured 100k
simfleet run and 1M jobs per replica:

  * **segment tier** (``jobs.seg``) — terminal/cold job ``Document``s,
    closed provenance records, and engine state blobs live as framed
    ``key\\x00status\\x00body`` payloads with newest-wins compaction.
    The index keeps only ``(offset, length, status)`` per key (~100
    bytes), so a million spilled jobs cost index entries, not Python
    object graphs; reads mmap the body on demand.
  * **WAL** (``wal.log``/``wal.old``) — every acknowledged job-store
    mutation (create, transition, lease claim/steal/release, adoption,
    state write) appends the full post-mutation record BEFORE the call
    returns. Replay is newest-wins by ``modified_at``/stamp, so it is
    idempotent: a record the store already reflects is a counted
    ``stale`` no-op, and replay-twice == replay-once.
  * **record-or-effect** — the checkpoint rotates the WAL, spills every
    dirty record into the segment, and only unlinks the rotated
    generation once the spill debt is zero. A crash anywhere leaves
    each mutation either in a WAL generation or in the segment.

Failure policy mirrors the window store: append failures (disk full,
EIO, the ``disk=`` chaos shape) DEGRADE — counted, logged once per
breath, never raised to the mutating caller — because durability must
not turn disk pressure into a scoring outage. The record stays dirty
and retries at the next checkpoint.

Threading: the engine's cycle thread and API threads mutate through
``JobStore`` (which serializes on its own lock); the tier serializes
file access on two leaf locks (WAL, segment) that are never held
together with the store lock held by the same caller path twice —
``JobStore`` always calls the tier OUTSIDE its own lock.
"""
from __future__ import annotations

import json
import logging
import mmap
import os
import time

from ..dataplane import segfile
from ..dataplane.segfile import SCAN_OK
from ..resilience.faults import seam_point
from ..utils.locks import make_lock

log = logging.getLogger("foremast_tpu.engine.jobtier")

__all__ = ["JobTier", "KIND_DOC", "KIND_STATE"]

# WAL record kinds (payload prefix byte before the first NUL)
KIND_DOC = "d"      # full post-mutation Document JSON
KIND_STATE = "s"    # {"k": key, "v": value, "ts": stamp}

# segment key prefixes
_K_DOC = "j:"       # job documents (status column = doc.status)
_K_PROV = "p:"      # closed provenance records (status column empty)
_K_STATE = "s:"     # engine state blobs (body {"v":..., "ts":...})


def _split_payload(payload: bytes) -> tuple[str, str, int] | None:
    """``key\\x00status\\x00body`` -> (key, status, body_offset) or None.
    Only the two NUL-terminated prefixes are decoded — index builds over
    a million frames must not pay a JSON parse per record."""
    n1 = payload.find(b"\x00")
    if n1 <= 0:
        return None
    n2 = payload.find(b"\x00", n1 + 1)
    if n2 < 0:
        return None
    try:
        return (payload[:n1].decode(), payload[n1 + 1:n2].decode(), n2 + 1)
    except UnicodeDecodeError:
        return None


class JobTier:
    """Durable segment + WAL tier under one directory.

    ``injector`` is a resilience/faults.py FaultInjector carrying the
    ``disk=PROB[:kind]`` chaos plan; its decisions surface at every
    append seam (segment and WAL alike)."""

    def __init__(self, dirpath: str, segment_max_bytes: int = 512 << 20,
                 fsync: bool = False, injector=None, exporter=None):
        os.makedirs(dirpath, exist_ok=True)
        self.dir = dirpath
        self.seg_path = os.path.join(dirpath, "jobs.seg")
        self.wal_path = os.path.join(dirpath, "wal.log")
        self.wal_old_path = os.path.join(dirpath, "wal.old")
        self.segment_max_bytes = int(segment_max_bytes)
        self.fsync = bool(fsync)
        self.injector = injector
        self.exporter = exporter
        self._wal_lock = make_lock("engine.jobtier.wal")
        self._seg_lock = make_lock("engine.jobtier.segment")
        # key -> (body_off, body_len, status) in the CURRENT segment file
        self._index: dict[str, tuple[int, int, str]] = {}
        # doc status -> count over _K_DOC keys (kept incrementally so
        # /status never walks a million index entries)
        self._counts: dict[str, int] = {}
        self._seg_mm: mmap.mmap | None = None
        self._seg_mm_size = 0
        # observability counters (exposed on /metrics + /status)
        self.spills = 0
        self.spill_errors = 0
        self.compactions = 0
        self.wal_records = 0
        self.wal_errors = 0
        self.recovery: dict = {}
        self._last_err_log = 0.0

    # ------------------------------------------------------------- helpers
    def _degrade(self, what: str, e: Exception) -> None:
        """Log disk trouble at most once per 5 s breath — a full disk
        under a 1M-job fleet must not emit a log line per mutation."""
        now = time.monotonic()
        if now - self._last_err_log >= 5.0:
            self._last_err_log = now
            log.warning("job tier %s failed (degrading, will retry at "
                        "next checkpoint): %s", what, e)

    def _seg_buffer(self):
        """mmap over the current segment (remade on growth). Readers keep
        old views valid across compaction renames — POSIX keeps the
        mapping alive after os.replace."""
        size = os.path.getsize(self.seg_path) \
            if os.path.exists(self.seg_path) else 0
        if size == 0:
            return None
        if self._seg_mm is None or self._seg_mm_size != size:
            fd = os.open(self.seg_path, os.O_RDONLY)
            try:
                self._seg_mm = mmap.mmap(fd, size, access=mmap.ACCESS_READ)
                self._seg_mm_size = size
            finally:
                os.close(fd)
        return self._seg_mm

    def _observe_duration(self, kind: str, seconds: float):
        if self.exporter is not None:
            self.exporter.record_histogram(
                "foremastbrain:job_store_checkpoint_seconds",
                {"kind": kind}, max(float(seconds), 0.0),
                help="Job-store checkpoint (WAL rotate + dirty spill + "
                     "retire) and boot recovery durations in seconds, "
                     "by kind.")

    # ------------------------------------------------------------------ WAL
    def wal_append(self, kind: str, obj) -> bool:
        """Append one mutation record BEFORE the store acks it. Failures
        degrade (counted): the mutation stays dirty in RAM and reaches
        the segment at the next checkpoint instead."""
        return self.wal_append_many(kind, (obj,))

    def wal_append_many(self, kind: str, objs) -> bool:
        """Batch variant: claim sweeps lease hundreds of docs per call;
        one fd open + one locked write sequence covers them all."""
        payloads = [kind.encode() + b"\x00" + json.dumps(o).encode()
                    for o in objs]
        if not payloads:
            return True
        t0 = time.monotonic()
        with self._wal_lock:
            try:
                _, wrote = segfile.append_frames(
                    self.wal_path, payloads, fsync=self.fsync,
                    injector=self.injector)
            except OSError as e:
                self.wal_errors += 1
                self.wal_records += getattr(e, "frames_written", 0)
                self._degrade("WAL append", e)
                return False
            self.wal_records += wrote
        if self.exporter is not None:
            self.exporter.record_histogram(
                "foremastbrain:job_store_wal_append_seconds", {},
                time.monotonic() - t0,
                help="One job-store WAL append batch (write + optional "
                     "fsync) in seconds; rising tails signal disk "
                     "pressure before job_store_wal_errors does.")
        return True

    def wal_size(self) -> int:
        try:
            return os.path.getsize(self.wal_path)
        except OSError:
            return 0

    # -------------------------------------------------------------- segment
    def _spill_many_locked(self, entries) -> int:
        """Append ``(key, status, body_bytes)`` frames; index what
        landed. Returns the number written (a mid-batch disk failure
        keeps the completed prefix — segfile truncates back to the last
        frame boundary)."""
        payloads = []
        metas = []
        for key, status, body in entries:
            payload = (key.encode() + b"\x00" + status.encode() + b"\x00"
                       + body)
            payloads.append(payload)
            metas.append((key, status,
                          len(key.encode()) + len(status.encode()) + 2,
                          len(body)))
        if not payloads:
            return 0
        base = os.path.getsize(self.seg_path) \
            if os.path.exists(self.seg_path) else 0
        wrote = len(payloads)
        err = None
        try:
            _, wrote = segfile.append_frames(
                self.seg_path, payloads, fsync=self.fsync,
                injector=self.injector)
        except OSError as e:
            wrote = getattr(e, "frames_written", 0)
            err = e
        off = base
        for i in range(wrote):
            key, status, body_rel, body_len = metas[i]
            off += segfile.FRAME_OVERHEAD
            self._note_index_locked(key, status,
                                    (off + body_rel, body_len, status))
            off += len(payloads[i])
        self.spills += wrote
        if err is not None:
            self.spill_errors += 1
            self._degrade("segment spill", err)
        elif os.path.getsize(self.seg_path) > self.segment_max_bytes:
            self._compact_locked()
        return wrote

    def _note_index_locked(self, key: str, status: str, slot) -> None:
        """An empty body (slot length 0) is a TOMBSTONE: the key leaves
        the index, and the next compaction erases both the tombstone and
        whatever it shadowed."""
        tombstone = slot[1] == 0
        if key.startswith(_K_DOC):
            prev = self._index.get(key)
            if prev is not None:
                self._counts[prev[2]] = self._counts.get(prev[2], 1) - 1
            if not tombstone:
                self._counts[status] = self._counts.get(status, 0) + 1
        if tombstone:
            self._index.pop(key, None)
        else:
            self._index[key] = slot

    def spill_docs(self, recs) -> int:
        """Spill full Document JSON dicts; returns how many landed."""
        entries = [(_K_DOC + r["id"], r.get("status", ""),
                    json.dumps(r).encode()) for r in recs]
        with self._seg_lock:
            return self._spill_many_locked(entries)

    def tombstone_docs(self, job_ids) -> int:
        """Erase spilled docs (handed-off jobs whose record of truth
        moved to the archive for a peer): an empty-body frame drops the
        key now, compaction reclaims the bytes later."""
        entries = [(_K_DOC + jid, "", b"") for jid in job_ids]
        with self._seg_lock:
            return self._spill_many_locked(entries)

    def spill_prov(self, job_id: str, rec: dict) -> bool:
        """Spill one CLOSED provenance record (terminal verdicts close
        the hop chain + detection annotations; the record never mutates
        again, so it goes straight to the segment — no WAL hop)."""
        with self._seg_lock:
            return self._spill_many_locked(
                [(_K_PROV + job_id, "", json.dumps(rec).encode())]) == 1

    def spill_state(self, key: str, value, stamp: float) -> bool:
        body = json.dumps({"v": value, "ts": stamp}).encode()
        with self._seg_lock:
            return self._spill_many_locked(
                [(_K_STATE + key, "", body)]) == 1

    def _read_locked(self, key: str) -> bytes | None:
        slot = self._index.get(key)
        if slot is None:
            return None
        off, length, _ = slot
        buf = self._seg_buffer()
        if buf is None or off + length > len(buf):
            return None
        return bytes(buf[off:off + length])

    def get_doc(self, job_id: str) -> dict | None:
        with self._seg_lock:
            raw = self._read_locked(_K_DOC + job_id)
        if not raw:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def get_prov(self, job_id: str) -> dict | None:
        with self._seg_lock:
            raw = self._read_locked(_K_PROV + job_id)
        if not raw:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def get_state(self, key: str) -> tuple[object, float] | None:
        with self._seg_lock:
            raw = self._read_locked(_K_STATE + key)
        if not raw:
            return None
        try:
            rec = json.loads(raw)
            return rec["v"], float(rec.get("ts", 0.0))
        except (ValueError, KeyError, TypeError):
            return None

    def status_of(self, job_id: str) -> str | None:
        """O(1): the spilled doc's status from the index, no parse."""
        with self._seg_lock:
            slot = self._index.get(_K_DOC + job_id)
            return slot[2] if slot is not None else None

    def doc_count(self) -> int:
        with self._seg_lock:
            return sum(self._counts.values())

    def doc_status_counts(self) -> dict:
        with self._seg_lock:
            return {k: v for k, v in self._counts.items() if v > 0}

    def snapshot(self) -> dict:
        """Point-in-time tier health for /status and /metrics: on-disk
        footprint plus the WAL/spill traffic counters."""
        try:
            seg_bytes = os.path.getsize(self.seg_path)
        except OSError:
            seg_bytes = 0
        with self._seg_lock:
            entries = len(self._index)
        return {
            "segment_bytes": seg_bytes,
            "segment_entries": entries,
            "docs": self.doc_count(),
            "wal_bytes": self.wal_size(),
            "wal_records": self.wal_records,
            "wal_errors": self.wal_errors,
            "spills": self.spills,
            "spill_errors": self.spill_errors,
            "compactions": self.compactions,
        }

    def iter_docs(self, statuses=None):
        """Yield spilled Document JSON dicts (optionally filtered by
        status WITHOUT parsing non-matching bodies). The index cut and
        the mmap ref are taken together under the lock; parsing runs
        outside it — an old view stays valid across a concurrent
        compaction, it just misses records spilled after the cut."""
        want = set(statuses) if statuses is not None else None
        with self._seg_lock:
            buf = self._seg_buffer()
            items = [(off, length) for key, (off, length, status)
                     in self._index.items()
                     if key.startswith(_K_DOC)
                     and (want is None or status in want)]
        if buf is None:
            return
        n = len(buf)
        for off, length in items:
            if off + length > n:
                continue
            try:
                yield json.loads(buf[off:off + length])
            except ValueError:
                continue

    def _compact_locked(self) -> None:
        """Newest-wins rewrite: keep only each key's latest record.
        Atomic — build ``.tmp``, fsync, rename over, re-point index."""
        buf = self._seg_buffer()
        if buf is None:
            return
        tmp = self.seg_path + ".tmp"
        new_index: dict[str, tuple[int, int, str]] = {}
        off = 0
        with open(tmp, "wb") as f:
            for key, (o, length, status) in self._index.items():
                if o + length > len(buf):
                    continue
                body = buf[o:o + length]
                payload = (key.encode() + b"\x00" + status.encode()
                           + b"\x00" + body)
                f.write(segfile.frame(payload))
                body_rel = len(payload) - length
                new_index[key] = (off + segfile.FRAME_OVERHEAD + body_rel,
                                  length, status)
                off += segfile.FRAME_OVERHEAD + len(payload)
            f.flush()
            os.fsync(f.fileno())
        seam_point(self, "jobtier.compact.replace")
        os.replace(tmp, self.seg_path)
        self._index = new_index
        self._seg_mm = None  # old views stay valid; next read re-maps
        self._seg_mm_size = 0
        self.compactions += 1

    def compact(self) -> None:
        with self._seg_lock:
            self._compact_locked()

    def _build_index_locked(self) -> tuple[int, str]:
        """Rebuild the index from the segment file. Segment records are
        independent newest-wins states — ORDER carries no meaning — so
        the walk RESUMES past damage at the next CRC-valid frame, then
        compacts so valid frames never sit behind unparseable bytes."""
        self._index = {}
        self._counts = {}
        self._seg_mm = None
        self._seg_mm_size = 0
        buf = self._seg_buffer()
        if buf is None:
            return 0, SCAN_OK
        total, status, pos = 0, SCAN_OK, 0
        while True:
            frames, st, bad = segfile.scan(buf, pos)
            for off, length in frames:
                parsed = _split_payload(bytes(buf[off:off + length]))
                if parsed is None:
                    continue
                key, doc_status, body_rel = parsed
                self._note_index_locked(
                    key, doc_status,
                    (off + body_rel, length - body_rel, doc_status))
                total += 1
            if st == SCAN_OK:
                break
            status = st
            nxt = segfile.next_valid_frame(buf, bad + 1)
            if nxt == -1:
                break
            pos = nxt
        if status != SCAN_OK:
            try:
                self._compact_locked()
            except OSError as e:
                log.warning("segment rewrite after bad scan failed: %s", e)
        return total, status

    # ------------------------------------------------- recovery/checkpoint
    def recover(self, apply_fn) -> dict:
        """Boot-time replay. Rebuild the segment index, then replay
        ``wal.old`` + ``wal.log`` IN ORDER through ``apply_fn(kind,
        obj) -> 'applied'|'stale'|'dropped'`` (JobStore wires this to
        its newest-wins install — the same rule live mutation uses, so
        replay is idempotent and a twice-replayed WAL is all stale
        no-ops the second time). WAL order matters, so the replay walk
        STOPS at damage instead of salvaging past it."""
        t0 = time.monotonic()
        with self._seg_lock:
            seg_frames, seg_status = self._build_index_locked()
        replayed = stale = dropped = 0
        wal_status = SCAN_OK
        with self._wal_lock:
            for path in (self.wal_old_path, self.wal_path):
                buf = segfile.read_file(path)
                if not buf:
                    continue
                frames, st, _ = segfile.scan(buf)
                if st != SCAN_OK:
                    wal_status = st
                for off, length in frames:
                    payload = buf[off:off + length]
                    n1 = payload.find(b"\x00")
                    if n1 <= 0:
                        dropped += 1
                        continue
                    try:
                        obj = json.loads(payload[n1 + 1:])
                    except ValueError:
                        dropped += 1
                        continue
                    verdict = apply_fn(payload[:n1].decode(), obj)
                    if verdict == "applied":
                        replayed += 1
                    elif verdict == "stale":
                        stale += 1
                    else:
                        dropped += 1
        self.recovery = {
            "segment_frames": seg_frames,
            "segment_docs": self.doc_count(),
            "segment_scan": seg_status,
            "wal_records_replayed": replayed,
            "wal_records_stale": stale,
            "wal_records_dropped": dropped,
            "wal_scan": wal_status,
            "seconds": round(time.monotonic() - t0, 4),
        }
        self._observe_duration("recovery", time.monotonic() - t0)
        return dict(self.recovery)

    def rotate_wal(self) -> bool:
        """Rename ``wal.log`` -> ``wal.old`` (start a fresh generation).
        No-op when a previous rotation's generation still exists — its
        spill debt has not cleared, and records must never be lost to a
        double rotation."""
        with self._wal_lock:
            if os.path.exists(self.wal_old_path):
                return False
            if os.path.exists(self.wal_path):
                seam_point(self, "jobtier.checkpoint.rotate")
                os.replace(self.wal_path, self.wal_old_path)
            return True

    def retire_wal(self) -> None:
        """Drop the rotated generation — caller asserts zero spill debt
        (every record in it now has its effect in the segment)."""
        with self._wal_lock:
            seam_point(self, "jobtier.checkpoint.retire")
            try:
                os.unlink(self.wal_old_path)
            except FileNotFoundError:
                pass
