"""Verdict provenance: per-(job, cycle) attribution records.

PRs 3–4 gave the engine SEVEN distinct ways to produce a verdict (full
score, fingerprint-memo reuse, stale-serve, shed carry-over, quarantine
park, watchdog failover, blast-radius isolation) but nothing recorded
WHICH path fired for a given job — when the operator suppresses a
rollback or a canary flips Unhealthy, aggregate counters cannot answer
the per-job "why". This module is that answer: the analyzer stamps one
structured record per judged (job, cycle) into a bounded ring, the
service serves the latest record at ``GET /jobs/<id>/explain``, the
``foremast-tpu explain`` CLI renders it human-readably, terminal
verdicts carry a compact copy into the archive Document
(``processing_content``), and the flight recorder folds affected jobs'
records into its incident dumps.

Always-on and allocation-bounded: the ring and the per-job index are
LRU-capped, per-record family lists are capped, and with ``enabled=False``
every method is a no-op — the A/B leg pins that verdicts are
byte-identical either way (recording only OBSERVES the cycle; it never
feeds back into scoring).

Path tags are REGISTERED constants (the devtools trace-registry rule
rejects inline literals), so the tag set stays a stable inventory the
runbook can enumerate.
"""
from __future__ import annotations

import json
import logging
import time
from collections import OrderedDict, deque

from .jobs import TERMINAL_STATUSES
from ..utils import tracing
from ..utils.locks import make_lock

log = logging.getLogger("foremast_tpu.engine.provenance")

__all__ = [
    "ProvenanceRecorder", "PATHS",
    "PATH_SCORED", "PATH_STREAM_SCORED", "PATH_MEMO_HIT", "PATH_TRIAGED",
    "PATH_STALE_SERVED", "PATH_SHED_CARRYOVER", "PATH_QUARANTINED",
    "PATH_WATCHDOG_FAILOVER", "PATH_BLAST_RADIUS", "PATH_FETCH_RETRY",
    "PATH_NO_DATA",
]

# -- verdict-path registry ---------------------------------------------------
PATH_SCORED = "scored"                      # fresh device-scored verdict
PATH_STREAM_SCORED = "stream-scored"        # scored by an event-driven
#                                             partial cycle (push ingest
#                                             woke the scheduler; the
#                                             verdict did not wait for
#                                             the global tick)
PATH_MEMO_HIT = "memo-hit"                  # served from fingerprint memo
PATH_TRIAGED = "triaged"                    # tier-0 screen cleared the row(s)
PATH_STALE_SERVED = "stale-served"          # last fresh verdict re-served
PATH_SHED_CARRYOVER = "shed-carryover"      # cycle deadline shed the job
PATH_QUARANTINED = "quarantined"            # parked as a poison job
PATH_WATCHDOG_FAILOVER = "watchdog-failover"  # hung launch, infra requeue
PATH_BLAST_RADIUS = "blast-radius-isolated"  # per-job isolation failed it
PATH_FETCH_RETRY = "fetch-retry"            # transient fetch failure requeue
PATH_NO_DATA = "no-data"                    # nothing judgeable (unknown/fail)

PATHS = frozenset({
    PATH_SCORED, PATH_STREAM_SCORED, PATH_MEMO_HIT, PATH_TRIAGED,
    PATH_STALE_SERVED, PATH_SHED_CARRYOVER, PATH_QUARANTINED,
    PATH_WATCHDOG_FAILOVER, PATH_BLAST_RADIUS, PATH_FETCH_RETRY,
    PATH_NO_DATA,
})

# per-record bound on family score entries: a 40-metric job keeps its 16
# most informative rows plus a drop count, not an unbounded list
_MAX_FAMILY_ENTRIES = 16

# bound on the handoff-hop chain a record carries: a job ping-ponging
# across replicas keeps its newest hops, never an unbounded history
_MAX_HOPS = 8


class ProvenanceRecorder:
    """Bounded store of per-(job, cycle) verdict-attribution records.

    The engine's cycle thread writes; HTTP/CLI threads read. All methods
    are no-ops when ``enabled`` is False (the PROVENANCE=0 A/B leg)."""

    def __init__(self, enabled: bool = True, max_jobs: int = 4096,
                 ring_size: int = 1024):
        self.enabled = enabled
        self.max_jobs = max_jobs
        self._lock = make_lock("engine.provenance")
        self._latest: OrderedDict[str, dict] = OrderedDict()  # job -> record
        self._ring: deque = deque(maxlen=ring_size)  # recent records
        # job -> inherited handoff-hop chain (adopt() seeds it from the
        # Document blob a releasing peer attached; record() stamps it
        # onto every later record so `explain` on the adopter shows the
        # full cross-replica decision chain)
        self._hops: OrderedDict[str, list] = OrderedDict()
        # job -> sticky latest-DETECTION annotations (trace_id,
        # detection_latency_s, detection_stages — annotate() refreshes
        # them at each observed window advance). Re-confirming sweeps
        # re-record a job every cycle; without the carry-forward the
        # push's trace linkage would survive exactly one cadence before
        # the next memo-hit record overwrote it (found live-driving the
        # runtime). Terminal records close the entry like hops.
        self._detections: OrderedDict[str, dict] = OrderedDict()
        self._cycle: dict = {}        # shared per-cycle block (stamped late)
        self._cycle_records: int = 0  # records written this cycle
        self.records_total = 0
        # durable spill hook (engine/jobtier.py JobTier.spill_prov): a
        # TERMINAL record closes the job's chain and never mutates
        # again, so it goes to the segment tier the moment it is
        # written — `explain` then outlives the LRU, gc, and kill -9.
        # Called OUTSIDE the recorder lock (it does file I/O);
        # best-effort — a full disk must not fail the scoring cycle.
        self.spill = None
        self.spills_total = 0
        self.spill_failures_total = 0

    # ------------------------------------------------------------- writing
    def begin_cycle(self, cycle_id: str, worker: str = ""):
        """Open a cycle: records written until finish_cycle share one
        mutable cycle block (stage timings land there after the fold)."""
        if not self.enabled:
            return
        with self._lock:
            self._cycle = {"cycle_id": cycle_id, "worker": worker}
            self._cycle_records = 0

    def record(self, job_id: str, path: str, status: str = "",
               detail: str = "", families: list | None = None,
               fetch: dict | None = None, reason: str = ""):
        """Stamp one job's verdict attribution for the open cycle."""
        if not self.enabled:
            return
        rec = {
            "job_id": job_id,
            "ts": time.time(),
            "path": path,
            "status": status,
            "cycle": self._cycle,  # shared ref; finish_cycle fills it in
        }
        # trace linkage: the current thread's open trace (the engine
        # cycle span) — `explain` answers with the trace_id a
        # /debug/traces?trace_id= fetch (or `foremast-tpu trace`)
        # resolves. For pushed jobs the analyzer's later annotate()
        # overrides this with the push's own distributed trace id.
        tid = tracing.tracer.current_trace_id()
        if tid:
            rec["trace_id"] = tid
        if detail:
            rec["detail"] = detail
        if reason:
            rec["reason"] = reason
        if families:
            if len(families) > _MAX_FAMILY_ENTRIES:
                rec["families_dropped"] = len(families) - _MAX_FAMILY_ENTRIES
                families = families[:_MAX_FAMILY_ENTRIES]
            rec["families"] = families
        if fetch:
            rec["fetch"] = fetch
        with self._lock:
            det = self._detections.get(job_id)
            if det:
                # the latest DETECTION's linkage (trace_id, latency,
                # waterfall) rides every later record until a newer
                # advance refreshes it — a re-confirming sweep must not
                # sever explain's verdict -> trace link. annotate()
                # (running after record() in the observing cycle)
                # overwrites these with the fresh detection's values.
                rec.update(det)
            hops = self._hops.get(job_id)
            if hops:
                # the inherited chain survives every later record: the
                # adopter's terminal verdict archives WITH its history.
                # A TERMINAL record closes the chain — job ids are
                # deterministic (hpa/hmac over the request), so a
                # re-submitted incarnation of the same id must start
                # clean instead of inheriting a dead run's handoffs.
                rec["hops"] = list(hops)
                if status in TERMINAL_STATUSES:
                    self._hops.pop(job_id, None)
            if status in TERMINAL_STATUSES:
                self._detections.pop(job_id, None)
            self._latest[job_id] = rec
            self._latest.move_to_end(job_id)
            while len(self._latest) > self.max_jobs:
                self._latest.popitem(last=False)
            self._ring.append(rec)
            self._cycle_records += 1
            self.records_total += 1
        if self.spill is not None and status in TERMINAL_STATUSES:
            # same slimming the archive summary applies: keep the
            # attribution skeleton, drop the bulky shared cycle block
            # (which finish_cycle would mutate AFTER this spill anyway)
            slim = {k: v for k, v in rec.items() if k != "cycle"}
            slim["cycle_id"] = (self._cycle or {}).get("cycle_id", "")
            try:
                if self.spill(job_id, slim):
                    self.spills_total += 1
                else:
                    self.spill_failures_total += 1
            except Exception as e:  # noqa: BLE001 - observer, never fatal
                self.spill_failures_total += 1
                log.warning("provenance spill failed for %s: %s",
                            job_id, e)

    def finish_cycle(self, stage_seconds: dict | None = None,
                     device_launches: int | None = None,
                     jobs: int | None = None):
        """Close the cycle: stamp cycle-level context into the SHARED
        cycle block every record of this cycle references (one mutation,
        not one per record)."""
        if not self.enabled:
            return
        with self._lock:
            if stage_seconds is not None:
                self._cycle["stage_seconds"] = {
                    k: round(float(v), 6) for k, v in stage_seconds.items()}
            if device_launches is not None:
                self._cycle["device_launches"] = int(device_launches)
            if jobs is not None:
                self._cycle["jobs"] = int(jobs)

    _DETECTION_KEYS = ("trace_id", "detection_latency_s",
                       "detection_stages")

    def annotate(self, job_id: str, **kv):
        """Fold late-arriving fields (detection latency, measured after
        the record was written) into a job's LATEST record. The record
        dict is shared with the ring, so both views update; a no-op when
        the job has no record. Detection fields additionally stick to
        the job (LRU-bounded), so later re-confirming records keep the
        last detection's trace/waterfall linkage."""
        if not self.enabled or not kv:
            return
        det = {k: kv[k] for k in self._DETECTION_KEYS if k in kv}
        with self._lock:
            rec = self._latest.get(job_id)
            if rec is not None:
                rec.update(kv)
            if det:
                self._detections[job_id] = {
                    **self._detections.get(job_id, {}), **det}
                self._detections.move_to_end(job_id)
                while len(self._detections) > self.max_jobs:
                    self._detections.popitem(last=False)

    # --------------------------------------------- cross-replica handoffs
    def handoff_json(self, job_id: str, replica: str = "", worker: str = "",
                     reason: str = "", max_bytes: int = 4096) -> str:
        """Compact JSON a RELEASING replica attaches to the Document
        (processing_content) when it hands a job off — the job's latest
        attribution plus an explicit handoff hop naming this replica and
        its cycle, appended to any hops the job already inherited. The
        adopter feeds it back through adopt(), so `explain` there shows
        the full chain including every handoff. Empty string when
        recording is off (the field stays untouched)."""
        if not self.enabled:
            return ""
        rec = self.get(job_id)
        hop = {
            "replica": replica,
            "worker": worker,
            "reason": reason,
            "ts": round(time.time(), 3),
            "cycle_id": (rec.get("cycle") or {}).get("cycle_id", "")
            if rec else "",
            "path": rec.get("path", "") if rec else "",
        }
        with self._lock:
            inherited = list(self._hops.get(job_id) or ())
        prior = (rec.get("hops") if rec else None) or inherited
        hops = (list(prior) + [hop])[-_MAX_HOPS:]
        slim = {k: v for k, v in (rec or {"job_id": job_id}).items()
                if k != "cycle"}
        slim["cycle_id"] = hop["cycle_id"]
        slim["hops"] = hops
        slim["handoff"] = hop  # marker adopt() keys on
        blob = json.dumps(slim)
        if len(blob) > max_bytes:
            slim.pop("families", None)
            slim["families_dropped"] = "all"
            blob = json.dumps(slim)
        return blob

    def adopt(self, job_id: str, blob: str):
        """An ADOPTING replica imports the handoff blob that traveled on
        the Document: the hop chain is remembered and stamped onto every
        record this replica writes for the job. Non-handoff blobs (plain
        terminal summaries, legacy free text) are ignored."""
        if not self.enabled or not blob:
            return
        try:
            rec = json.loads(blob)
        except ValueError:
            return
        if not isinstance(rec, dict) or "handoff" not in rec:
            return
        hops = [h for h in (rec.get("hops") or []) if isinstance(h, dict)]
        if not hops:
            return
        with self._lock:
            self._hops[job_id] = hops[-_MAX_HOPS:]
            self._hops.move_to_end(job_id)
            while len(self._hops) > self.max_jobs:
                self._hops.popitem(last=False)

    # ------------------------------------------------------------- reading
    def get(self, job_id: str) -> dict | None:
        """Latest record for a job (deep enough copy for JSON serving)."""
        with self._lock:
            rec = self._latest.get(job_id)
            if rec is None:
                return None
            out = dict(rec)
            out["cycle"] = dict(rec.get("cycle") or {})
            return out

    def recent(self, limit: int = 50) -> list[dict]:
        with self._lock:
            recs = list(self._ring)[-limit:]
            return [{**r, "cycle": dict(r.get("cycle") or {})}
                    for r in recs]

    def for_jobs(self, job_ids) -> dict:
        """{job_id: record} for the ids that have one (flight dumps)."""
        out = {}
        for jid in job_ids:
            rec = self.get(jid)
            if rec is not None:
                out[jid] = rec
        return out

    def summary_json(self, job_id: str, max_bytes: int = 4096) -> str:
        """Compact JSON of a job's latest record for the archive
        Document's processing_content — bounded so one verbose record
        cannot bloat every archived verdict."""
        rec = self.get(job_id)
        if rec is None:
            return ""
        # archive documents are long-lived: keep the attribution skeleton,
        # drop the bulky per-cycle timing block
        slim = {k: v for k, v in rec.items() if k != "cycle"}
        slim["cycle_id"] = (rec.get("cycle") or {}).get("cycle_id", "")
        blob = json.dumps(slim)
        if len(blob) > max_bytes:
            slim.pop("families", None)
            slim["families_dropped"] = "all"
            blob = json.dumps(slim)
        return blob
