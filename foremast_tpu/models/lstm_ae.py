"""LSTM-autoencoder multivariate anomaly scorer (flax).

The reference brain escalates to "LSTM deep learning" for 3+ correlated
metrics (foremast-brain/faq.md:8-10 — Keras+MXNet LSTM autoencoder;
unsupervised per faq.md:3-5; menu position at docs/guides/design.md:53-88).
This is the TPU-native replacement: a flax seq2seq autoencoder trained on
healthy historical windows; anomaly score = reconstruction error normalized
against the healthy-error distribution.

TPU notes:
  * time recurrence runs under `flax.linen.RNN` (nn.scan -> lax.scan), batch
    and feature dims stay dense so the per-step matmuls hit the MXU.
  * all parameters/activations are float32 by default with a bfloat16 switch
    for large fleets (param dtype stays float32; activations cast).
  * masked windows: padded steps contribute zero loss and zero score; the
    encoder consumes gap-filled inputs (value 0 + mask channel) so shapes
    stay static.

Inputs are (B, T, F) windows: F metrics per service (e.g. latency_p99,
error_rate, cpu, tps) resampled by ops.windowing, standardized per feature.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

__all__ = ["LstmAutoencoder", "TrainState", "init_state", "train_step", "train",
           "train_fleet", "anomaly_scores", "anomaly_scores_fleet",
           "fit_score_normalizer", "param_shardings"]

_F = jnp.float32


class LstmAutoencoder(nn.Module):
    """Seq2seq reconstruction model.

    Encoder LSTM folds the window into a latent; decoder LSTM unrolls the
    latent back over T steps; a Dense head reconstructs the F features per
    step. The mask is appended as extra input channels so the model can
    distinguish gaps from true zeros.
    """

    hidden: int = 128  # MXU-friendly multiple of 128
    latent: int = 64
    features: int = 4
    dtype: Any = jnp.float32
    # lax.scan unroll factor. INFERENCE models use 8: windows are short
    # (W ~ 32) and the scan's per-step dispatch, not the tiny matmuls,
    # dominates fleet-scale scoring (measured with the warm stacked-fleet
    # launch on CPU; fewer, larger steps also fuse better on the MXU).
    # TRAINING keeps 1: the unrolled forward+backward graph compiles far
    # slower and runs ~2x slower through value_and_grad. The two module
    # instances share identical param trees (unroll changes no shapes), so
    # params trained at unroll=1 score under an unroll=8 apply unchanged.
    unroll: int = 1

    @nn.compact
    def __call__(self, x, mask):
        # x: (B, T, F); mask: (B, T, F) bool
        B, T, F = x.shape
        inp = jnp.concatenate([x, mask.astype(self.dtype)], axis=-1)
        enc = nn.RNN(nn.LSTMCell(self.hidden, param_dtype=jnp.float32,
                                 dtype=self.dtype), unroll=self.unroll)
        h = enc(inp)  # (B, T, H)
        z = nn.Dense(self.latent, dtype=self.dtype)(h[:, -1, :])  # (B, Z)
        # decoder: latent repeated over time, unrolled by a second LSTM
        dec_in = jnp.repeat(z[:, None, :], T, axis=1)
        dec = nn.RNN(nn.LSTMCell(self.hidden, param_dtype=jnp.float32,
                                 dtype=self.dtype), unroll=self.unroll)
        dh = dec(dec_in)
        recon = nn.Dense(F, dtype=self.dtype)(dh)
        return recon.astype(_F)


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int


def _loss_fn(params, model, x, mask, apply_fn):
    recon = apply_fn({"params": params}, x, mask)
    m = mask.astype(_F)
    se = (recon - x) ** 2 * m
    denom = jnp.maximum(jnp.sum(m), 1.0)
    return jnp.sum(se) / denom


def param_shardings(params, mesh, model_axis: str | None = None,
                    min_shard_width: int = 8):
    """Tensor-parallel NamedSharding pytree for the scorer's parameters.

    Megatron-style column split: every kernel whose output (last) dim is a
    multiple of the `model` axis size AND at least `min_shard_width` wide
    is sharded on that dim — the LSTM gate matmuls and the latent Dense
    head — while biases, indivisible leaves, and narrow heads replicate.
    The width floor keeps the reconstruction head (output dim = feature
    count, typically 3-4) replicated: splitting a 4-wide output saves no
    compute and would cost an all-gather per decode step.

    Handing these to jax.device_put / jit's in_shardings is enough: XLA
    GSPMD partitions the per-step matmuls and inserts the gate all-reduces
    over ICI, so a scorer whose hidden state outgrows one chip spans
    several without model changes (the `model` mesh axis reserved in
    parallel/mesh.py — the default axis name comes from there).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MODEL_AXIS

    model_axis = MODEL_AXIS if model_axis is None else model_axis
    axis_size = mesh.shape[model_axis]

    def rule(x):
        if (getattr(x, "ndim", 0) >= 2 and x.shape[-1] % axis_size == 0
                and x.shape[-1] >= min_shard_width):
            spec = [None] * (x.ndim - 1) + [model_axis]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def init_state(model: LstmAutoencoder, rng, T: int, lr: float = 1e-3):
    x0 = jnp.zeros((1, T, model.features), _F)
    m0 = jnp.ones((1, T, model.features), bool)
    params = model.init(rng, x0, m0)["params"]
    tx = optax.adam(lr)
    return TrainState(params=params, opt_state=tx.init(params), step=0), tx


# donate_argnums: the caller's previous-epoch params/opt_state buffers are
# dead the moment the step returns — donating them lets XLA update in
# place instead of allocating a fresh pytree per epoch (on TPU this also
# halves the training loop's peak HBM)
@partial(jax.jit, static_argnames=("apply_fn", "tx"), donate_argnums=(0, 1))
def train_step(params, opt_state, x, mask, apply_fn, tx):
    loss, grads = jax.value_and_grad(_loss_fn)(params, None, x, mask, apply_fn)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


# plateau early-stop shared by both training loops: the AE only needs to
# learn "normal" well enough for a stable error normalizer, and healthy
# fleet windows typically converge in well under the epoch budget — the
# budget is a CAP, not a target. Checked every `check_every` epochs via a
# scalar loss fetch (one host round-trip per check).
_ES_CHECK_EVERY = 5
_ES_MIN_EPOCHS = 10
_ES_REL_TOL = 0.02


class _Plateau:
    """Stateful plateau check, one shared rule for both training loops
    (single-job and fleet must never silently diverge in stopping
    behavior). `stop(epoch_done, loss)` -> True once the (scalar) loss
    improves < _ES_REL_TOL relatively between consecutive checks."""

    def __init__(self):
        self._prev = None

    def stop(self, done: int, loss_scalar: float) -> bool:
        if done < _ES_MIN_EPOCHS or done % _ES_CHECK_EVERY:
            return False
        prev, self._prev = self._prev, loss_scalar
        return (prev is not None
                and prev - loss_scalar < _ES_REL_TOL * max(prev, 1e-12))


def train(model, state, tx, x, mask, epochs: int = 50):
    """Full-batch training loop (fleet windows are small; one device batch),
    early-stopped on loss plateau."""
    params, opt_state = state.params, state.opt_state
    loss = None
    plateau = _Plateau()
    done = 0
    for e in range(epochs):
        params, opt_state, loss = train_step(
            params, opt_state, x, mask, model.apply, tx
        )
        done = e + 1
        if plateau.stop(done, float(loss)):
            break
    return TrainState(params=params, opt_state=opt_state, step=state.step + done), loss


@partial(jax.jit, static_argnames=("apply_fn", "tx"), donate_argnums=(0, 1))
def _train_step_fleet(params, opt_state, x, mask, apply_fn, tx):
    return jax.vmap(
        lambda p, o, xx, mm: train_step(p, o, xx, mm, apply_fn, tx)
    )(params, opt_state, x, mask)


def train_fleet(model, rng, x, mask, epochs: int = 50, lr: float = 1e-3):
    """Train J same-shape jobs' autoencoders in ONE vmapped loop.

    Every job deliberately starts from the SAME deterministic init (the
    single-job path uses a fixed PRNGKey too), so the stacked start state
    is a broadcast; each epoch is then one `train_step` vmapped over
    (params, opt_state, windows) — J jobs' training collapses from J
    sequential loops of E dispatches each into E dispatches total, and
    the per-step matmuls gain a J-wide batch dimension on the MXU.

    Args:
      x, mask: (J, K, W, F) historical training windows per job.
    Returns (params_stack, err_mu (J,), err_sd (J,)) — the stacked
    parameters slice per job for the cache, and the per-job healthy-error
    normalizers.
    """
    J, K, W, F = x.shape
    state, tx = init_state(model, rng, T=W, lr=lr)
    # broadcast_to makes views; donation needs real owned buffers, and the
    # first fleet step would otherwise donate the same aliased memory J ways
    bcast = lambda a: jnp.array(  # noqa: E731
        jnp.broadcast_to(a[None], (J,) + a.shape))
    params = jax.tree.map(bcast, state.params)
    opt_state = jax.tree.map(bcast, state.opt_state)
    plateau = _Plateau()
    for e in range(epochs):
        params, opt_state, loss = _train_step_fleet(
            params, opt_state, x, mask, model.apply, tx)
        # fleet-mean plateau criterion (the scalar fed to the shared rule)
        if plateau.stop(e + 1, float(jnp.mean(loss))):
            break
    mus, sds = jax.vmap(
        lambda p, xx, mm: fit_score_normalizer(p, xx, mm, model.apply)
    )(params, x, mask)
    return params, mus, sds


@partial(jax.jit, static_argnames=("apply_fn",))
def reconstruction_errors(params, x, mask, apply_fn):
    """Per-window masked MSE (B,)."""
    recon = apply_fn({"params": params}, x, mask)
    m = mask.astype(_F)
    se = (recon - x) ** 2 * m
    denom = jnp.maximum(jnp.sum(m, axis=(1, 2)), 1.0)
    return jnp.sum(se, axis=(1, 2)) / denom


def fit_score_normalizer(params, x_healthy, mask, apply_fn):
    """Mean/std of reconstruction error on healthy windows -> (mu, sigma)."""
    errs = reconstruction_errors(params, x_healthy, mask, apply_fn)
    mu = jnp.mean(errs)
    sigma = jnp.maximum(jnp.std(errs), 1e-6)
    return mu, sigma


@partial(jax.jit, static_argnames=("apply_fn",))
def anomaly_scores(params, x, mask, mu, sigma, apply_fn):
    """Z-score of reconstruction error vs the healthy distribution (B,).

    score > threshold (typically 3.0) => window judged anomalous; the engine
    maps that to completed_unhealth exactly like a pairwise rejection.
    """
    errs = reconstruction_errors(params, x, mask, apply_fn)
    return (errs - mu) / sigma


@partial(jax.jit, static_argnames=("apply_fn",))
def anomaly_scores_fleet(params_stack, x, mask, mu, sigma, apply_fn):
    """Fleet-wide scoring: J jobs' models in ONE launch.

    Each multi-metric job owns its own trained parameters, so fleet
    scoring vmaps over a STACKED parameter pytree alongside the data —
    (J, K, W, F) windows against (J, ...) params — collapsing J per-job
    device dispatches (~ms each, dominating a warm multi-metric cycle at
    fleet scale) into one batched program whose inner matmuls gain a
    J-wide batch dimension on the MXU. Returns (J, K) z-scores.
    """
    return jax.vmap(anomaly_scores, in_axes=(0, 0, 0, 0, 0, None))(
        params_stack, x, mask, mu, sigma, apply_fn)
