"""LSTM-autoencoder multivariate anomaly scorer (flax).

The reference brain escalates to "LSTM deep learning" for 3+ correlated
metrics (foremast-brain/faq.md:8-10 — Keras+MXNet LSTM autoencoder;
unsupervised per faq.md:3-5; menu position at docs/guides/design.md:53-88).
This is the TPU-native replacement: a flax seq2seq autoencoder trained on
healthy historical windows; anomaly score = reconstruction error normalized
against the healthy-error distribution.

TPU notes:
  * time recurrence runs under `flax.linen.RNN` (nn.scan -> lax.scan), batch
    and feature dims stay dense so the per-step matmuls hit the MXU.
  * all parameters/activations are float32 by default with a bfloat16 switch
    for large fleets (param dtype stays float32; activations cast).
  * masked windows: padded steps contribute zero loss and zero score; the
    encoder consumes gap-filled inputs (value 0 + mask channel) so shapes
    stay static.

Inputs are (B, T, F) windows: F metrics per service (e.g. latency_p99,
error_rate, cpu, tps) resampled by ops.windowing, standardized per feature.
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

__all__ = ["LstmAutoencoder", "TrainState", "init_state", "train_step", "train",
           "train_fleet", "anomaly_scores", "anomaly_scores_fleet",
           "fit_score_normalizer", "param_shardings"]

_F = jnp.float32


class LstmAutoencoder(nn.Module):
    """Seq2seq reconstruction model.

    Encoder LSTM folds the window into a latent; decoder LSTM unrolls the
    latent back over T steps; a Dense head reconstructs the F features per
    step. The mask is appended as extra input channels so the model can
    distinguish gaps from true zeros.
    """

    hidden: int = 128  # MXU-friendly multiple of 128
    latent: int = 64
    features: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, mask):
        # x: (B, T, F); mask: (B, T, F) bool
        B, T, F = x.shape
        inp = jnp.concatenate([x, mask.astype(self.dtype)], axis=-1)
        enc = nn.RNN(nn.LSTMCell(self.hidden, param_dtype=jnp.float32, dtype=self.dtype))
        h = enc(inp)  # (B, T, H)
        z = nn.Dense(self.latent, dtype=self.dtype)(h[:, -1, :])  # (B, Z)
        # decoder: latent repeated over time, unrolled by a second LSTM
        dec_in = jnp.repeat(z[:, None, :], T, axis=1)
        dec = nn.RNN(nn.LSTMCell(self.hidden, param_dtype=jnp.float32, dtype=self.dtype))
        dh = dec(dec_in)
        recon = nn.Dense(F, dtype=self.dtype)(dh)
        return recon.astype(_F)


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int


def _loss_fn(params, model, x, mask, apply_fn):
    recon = apply_fn({"params": params}, x, mask)
    m = mask.astype(_F)
    se = (recon - x) ** 2 * m
    denom = jnp.maximum(jnp.sum(m), 1.0)
    return jnp.sum(se) / denom


def param_shardings(params, mesh, model_axis: str | None = None,
                    min_shard_width: int = 8):
    """Tensor-parallel NamedSharding pytree for the scorer's parameters.

    Megatron-style column split: every kernel whose output (last) dim is a
    multiple of the `model` axis size AND at least `min_shard_width` wide
    is sharded on that dim — the LSTM gate matmuls and the latent Dense
    head — while biases, indivisible leaves, and narrow heads replicate.
    The width floor keeps the reconstruction head (output dim = feature
    count, typically 3-4) replicated: splitting a 4-wide output saves no
    compute and would cost an all-gather per decode step.

    Handing these to jax.device_put / jit's in_shardings is enough: XLA
    GSPMD partitions the per-step matmuls and inserts the gate all-reduces
    over ICI, so a scorer whose hidden state outgrows one chip spans
    several without model changes (the `model` mesh axis reserved in
    parallel/mesh.py — the default axis name comes from there).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MODEL_AXIS

    model_axis = MODEL_AXIS if model_axis is None else model_axis
    axis_size = mesh.shape[model_axis]

    def rule(x):
        if (getattr(x, "ndim", 0) >= 2 and x.shape[-1] % axis_size == 0
                and x.shape[-1] >= min_shard_width):
            spec = [None] * (x.ndim - 1) + [model_axis]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def init_state(model: LstmAutoencoder, rng, T: int, lr: float = 1e-3):
    x0 = jnp.zeros((1, T, model.features), _F)
    m0 = jnp.ones((1, T, model.features), bool)
    params = model.init(rng, x0, m0)["params"]
    tx = optax.adam(lr)
    return TrainState(params=params, opt_state=tx.init(params), step=0), tx


@partial(jax.jit, static_argnames=("apply_fn", "tx"))
def train_step(params, opt_state, x, mask, apply_fn, tx):
    loss, grads = jax.value_and_grad(_loss_fn)(params, None, x, mask, apply_fn)
    updates, opt_state = tx.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss


def train(model, state, tx, x, mask, epochs: int = 50):
    """Full-batch training loop (fleet windows are small; one device batch)."""
    params, opt_state = state.params, state.opt_state
    loss = None
    for _ in range(epochs):
        params, opt_state, loss = train_step(
            params, opt_state, x, mask, model.apply, tx
        )
    return TrainState(params=params, opt_state=opt_state, step=state.step + epochs), loss


@partial(jax.jit, static_argnames=("apply_fn", "tx"))
def _train_step_fleet(params, opt_state, x, mask, apply_fn, tx):
    return jax.vmap(
        lambda p, o, xx, mm: train_step(p, o, xx, mm, apply_fn, tx)
    )(params, opt_state, x, mask)


def train_fleet(model, rng, x, mask, epochs: int = 50, lr: float = 1e-3):
    """Train J same-shape jobs' autoencoders in ONE vmapped loop.

    Every job deliberately starts from the SAME deterministic init (the
    single-job path uses a fixed PRNGKey too), so the stacked start state
    is a broadcast; each epoch is then one `train_step` vmapped over
    (params, opt_state, windows) — J jobs' training collapses from J
    sequential loops of E dispatches each into E dispatches total, and
    the per-step matmuls gain a J-wide batch dimension on the MXU.

    Args:
      x, mask: (J, K, W, F) historical training windows per job.
    Returns (params_stack, err_mu (J,), err_sd (J,)) — the stacked
    parameters slice per job for the cache, and the per-job healthy-error
    normalizers.
    """
    J, K, W, F = x.shape
    state, tx = init_state(model, rng, T=W, lr=lr)
    bcast = lambda a: jnp.broadcast_to(a[None], (J,) + a.shape)  # noqa: E731
    params = jax.tree.map(bcast, state.params)
    opt_state = jax.tree.map(bcast, state.opt_state)
    for _ in range(epochs):
        params, opt_state, _ = _train_step_fleet(
            params, opt_state, x, mask, model.apply, tx)
    mus, sds = jax.vmap(
        lambda p, xx, mm: fit_score_normalizer(p, xx, mm, model.apply)
    )(params, x, mask)
    return params, mus, sds


@partial(jax.jit, static_argnames=("apply_fn",))
def reconstruction_errors(params, x, mask, apply_fn):
    """Per-window masked MSE (B,)."""
    recon = apply_fn({"params": params}, x, mask)
    m = mask.astype(_F)
    se = (recon - x) ** 2 * m
    denom = jnp.maximum(jnp.sum(m, axis=(1, 2)), 1.0)
    return jnp.sum(se, axis=(1, 2)) / denom


def fit_score_normalizer(params, x_healthy, mask, apply_fn):
    """Mean/std of reconstruction error on healthy windows -> (mu, sigma)."""
    errs = reconstruction_errors(params, x_healthy, mask, apply_fn)
    mu = jnp.mean(errs)
    sigma = jnp.maximum(jnp.std(errs), 1e-6)
    return mu, sigma


@partial(jax.jit, static_argnames=("apply_fn",))
def anomaly_scores(params, x, mask, mu, sigma, apply_fn):
    """Z-score of reconstruction error vs the healthy distribution (B,).

    score > threshold (typically 3.0) => window judged anomalous; the engine
    maps that to completed_unhealth exactly like a pairwise rejection.
    """
    errs = reconstruction_errors(params, x, mask, apply_fn)
    return (errs - mu) / sigma


@partial(jax.jit, static_argnames=("apply_fn",))
def anomaly_scores_fleet(params_stack, x, mask, mu, sigma, apply_fn):
    """Fleet-wide scoring: J jobs' models in ONE launch.

    Each multi-metric job owns its own trained parameters, so fleet
    scoring vmaps over a STACKED parameter pytree alongside the data —
    (J, K, W, F) windows against (J, ...) params — collapsing J per-job
    device dispatches (~ms each, dominating a warm multi-metric cycle at
    fleet scale) into one batched program whose inner matmuls gain a
    J-wide batch dimension on the MXU. Returns (J, K) z-scores.
    """
    return jax.vmap(anomaly_scores, in_axes=(0, 0, 0, 0, 0, None))(
        params_stack, x, mask, mu, sigma, apply_fn)
