"""Analyst client: operator -> analysis service.

The reference client (foremast-barrelman/pkg/client/analyst/analystclient.go)
POSTs /v1/healthcheck/create and GETs /id/:jobId, mapping service statuses
to monitor phases (:227-245):

  created/initial/new/inprogress/unknown -> Running
  completed_health/success               -> Healthy
  completed_unhealth/anomaly             -> Unhealthy
  abort                                  -> Abort
  completed_unknown                      -> Warning

Three implementations share the mapping:
  * HttpAnalyst — real HTTP with an injectable do_func (the reference's
    DoFunc test seam, analystclient.go:24).
  * GrpcAnalyst — the gRPC dispatch transport the north star names; same
    request/response dict shapes via service.grpc_api.DispatchClient.
  * InProcessAnalyst — calls the ForemastService handlers directly; the
    TPU-native collapse when operator + engine share a process.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from dataclasses import dataclass, field

from .types import PHASE_ABORT, PHASE_HEALTHY, PHASE_RUNNING, PHASE_UNHEALTHY, PHASE_WARNING

STATUS_TO_PHASE = {
    "created": PHASE_RUNNING,
    "initial": PHASE_RUNNING,
    "new": PHASE_RUNNING,
    "inprogress": PHASE_RUNNING,
    "unknown": PHASE_RUNNING,
    "completed_health": PHASE_HEALTHY,
    "success": PHASE_HEALTHY,
    "completed_unhealth": PHASE_UNHEALTHY,
    "anomaly": PHASE_UNHEALTHY,
    "abort": PHASE_ABORT,
    "completed_unknown": PHASE_WARNING,
}


@dataclass
class StatusResponse:
    phase: str
    reason: str = ""
    anomaly: dict = field(default_factory=dict)  # metric -> flat [ts,v,...]
    hpa_logs: list = field(default_factory=list)


class AnalystError(Exception):
    pass


def _map_status(status: str) -> str:
    return STATUS_TO_PHASE.get(status, PHASE_RUNNING)


class HttpAnalyst:
    def __init__(self, endpoint: str, do_func=None, timeout: float = 10.0):
        # accept both configured forms — the bare service base
        # ("http://svc:8099") and the reference metadata convention with the
        # path baked in ("http://svc:8099/v1/healthcheck/",
        # deployment-metadata-default.yaml) — by normalizing to the base;
        # the request methods append the canonical /v1/healthcheck/* paths
        self.endpoint = endpoint.rstrip("/").removesuffix("/v1/healthcheck")
        self.do_func = do_func  # (method, url, body_bytes) -> (status, bytes)
        self.timeout = timeout

    def _do(self, method: str, url: str, body: bytes | None = None):
        if self.do_func is not None:
            return self.do_func(method, url, body)
        req = urllib.request.Request(url, data=body, method=method)
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, r.read()
        except Exception as e:  # noqa: BLE001 - HTTP boundary
            raise AnalystError(f"{method} {url}: {e}") from e

    def start_analyzing(self, request: dict) -> str:
        status, payload = self._do(
            "POST",
            f"{self.endpoint}/v1/healthcheck/create",
            json.dumps(request).encode(),
        )
        if status != 200:
            raise AnalystError(f"create returned {status}: {payload[:200]!r}")
        return json.loads(payload)["jobId"]

    def get_status(self, job_id: str) -> StatusResponse:
        status, payload = self._do(
            "GET", f"{self.endpoint}/v1/healthcheck/id/{job_id}"
        )
        if status != 200:
            raise AnalystError(f"status returned {status}: {payload[:200]!r}")
        doc = json.loads(payload)
        return StatusResponse(
            phase=_map_status(doc.get("status", "")),
            reason=doc.get("reason", ""),
            anomaly=doc.get("anomaly", {}) or {},
            hpa_logs=doc.get("hpalogs", []) or [],
        )

    def probe_ready(self) -> tuple[int, dict]:
        """(http_status, payload) from /readyz. The 503 states
        (overloaded/stalled) carry their payload in the ERROR response,
        so this reads HTTPError bodies directly instead of going through
        _do (which flattens any non-200 into AnalystError and would lose
        exactly the most-degraded states). Raises AnalystError when the
        brain is unreachable or answers garbage. Shared transport for
        the operator's suppression probe AND the `foremast-tpu health`
        CLI — one copy of the readyz semantics."""
        url = f"{self.endpoint}/readyz"
        try:
            if self.do_func is not None:
                status, payload = self.do_func("GET", url, None)
            else:
                req = urllib.request.Request(url, method="GET")
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout) as r:
                        status, payload = r.status, r.read()
                except urllib.error.HTTPError as e:
                    status, payload = e.code, e.read()  # 503 has a body
            return status, json.loads(payload)
        except Exception as e:  # noqa: BLE001 - one probe-failure shape
            raise AnalystError(f"GET {url}: {e}") from e

    def get_health(self) -> str:
        """Brain degraded-mode state from /readyz (ok / degraded /
        overloaded / stalled). Raises AnalystError when the brain is
        unreachable — the CALLER owns the fail-open policy: an
        overloaded/stalled brain answers 503 on the very probe k8s uses
        for readiness, so "unreachable" often MEANS "most degraded"
        (endpoint pulled from the Service), and silently reporting it as
        "ok" here would dispatch held remediations at the worst moment
        (see OperatorLoop._probe_health for the bounded hold)."""
        _, payload = self.probe_ready()
        return str(payload.get("state", "ok"))


class GrpcAnalyst:
    """gRPC sibling of HttpAnalyst (north star: dispatch over gRPC).

    Lazy import so the operator works without grpc installed; the dispatch
    client speaks the same dict shapes as the HTTP facade, so the phase
    mapping above applies unchanged.
    """

    def __init__(self, target: str, timeout: float = 10.0):
        from ..service.grpc_api import DispatchClient

        self.client = DispatchClient(target, timeout=timeout)

    def start_analyzing(self, request: dict) -> str:
        from ..service.grpc_api import DispatchError

        try:
            return self.client.create(request)["jobId"]
        except DispatchError as e:
            raise AnalystError(f"create returned {e.status}: {e.message}") from e

    def get_status(self, job_id: str) -> StatusResponse:
        from ..service.grpc_api import DispatchError

        try:
            doc = self.client.status(job_id)
        except DispatchError as e:
            raise AnalystError(f"status returned {e.status}: {e.message}") from e
        return StatusResponse(
            phase=_map_status(doc.get("status", "")),
            reason=doc.get("reason", ""),
            anomaly=doc.get("anomaly", {}) or {},
            hpa_logs=doc.get("hpalogs", []) or [],
        )

    # no get_health: the gRPC dispatch surface has no readiness RPC; the
    # operator loop treats an absent probe as "ok" (fail-open)

    def close(self):
        self.client.close()


class InProcessAnalyst:
    """Zero-hop analyst over an in-process ForemastService.

    Service-layer ApiError surfaces as AnalystError, mirroring the HTTP
    path where a 400 response becomes AnalystError — callers must see the
    same failure type on both transports.
    """

    def __init__(self, service):
        self.service = service

    def start_analyzing(self, request: dict) -> str:
        from ..service.api import ApiError

        try:
            status, payload = self.service.create(request)
        except ApiError as e:
            raise AnalystError(f"create rejected: {e.message}") from e
        if status != 200:
            raise AnalystError(f"create returned {status}: {payload}")
        return payload["jobId"]

    def get_status(self, job_id: str) -> StatusResponse:
        from ..service.api import ApiError

        try:
            status, doc = self.service.status(job_id)
        except ApiError as e:
            raise AnalystError(f"status rejected: {e.message}") from e
        if status != 200:
            raise AnalystError(f"status returned {status}: {doc}")
        return StatusResponse(
            phase=_map_status(doc.get("status", "")),
            reason=doc.get("reason", ""),
            anomaly=doc.get("anomaly", {}) or {},
            hpa_logs=doc.get("hpalogs", []) or [],
        )

    def get_health(self) -> str:
        """Zero-hop readiness probe (service.readyz). Failures propagate
        like the HTTP analyst's — the operator loop owns the policy."""
        _, payload = self.service.readyz()
        return str(payload.get("state", "ok"))
