"""Kubernetes API seam: one small interface, a fake, and a REST client.

The reference operator talks to K8s through generated clientsets +
informers and ships fake clientsets as its test seam
(foremast-barrelman/pkg/client/clientset/versioned/fake/). The TPU-native
equivalent keeps that seam but collapses the surface to the eight calls the
controllers actually need. `FakeKube` is the in-memory double used by the
test-suite (and the local demo); `KubeClient` speaks the real REST API with
the in-cluster service-account token — no kubernetes client library
dependency.

Deployments/ReplicaSets/Pods/HPAs are plain dicts in the K8s JSON shape;
DeploymentMonitor/DeploymentMetadata use the operator dataclasses.
"""
from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable

from .types import DeploymentMetadata, DeploymentMonitor
from ..utils import knobs


class KubeError(Exception):
    """Kubernetes API failure; .status carries the HTTP code (0 = transport
    error), so callers can tell not-found (404) from a broken apiserver —
    treating a 500 as "missing" would make controllers recreate state."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class FakeKube:
    """In-memory K8s double, the controller test seam.

    Holds dict-shaped core resources and dataclass CRDs. Mutations notify
    subscribed watchers synchronously (the informer role).
    """

    def __init__(self):
        self.deployments: dict[tuple, dict] = {}  # (ns, name) -> deployment
        self.replicasets: dict[tuple, dict] = {}
        self.pods: dict[tuple, dict] = {}
        self.hpas: dict[tuple, dict] = {}
        self.monitors: dict[tuple, DeploymentMonitor] = {}
        self.metadata: dict[tuple, DeploymentMetadata] = {}
        self.namespaces: dict[str, dict] = {"default": {}}
        self.events: list[dict] = []
        self.patches: list[tuple] = []  # audit: (kind, ns, name, patch)
        self._watchers: list[Callable] = []

    # -- namespaces --
    def list_namespaces(self) -> list[str]:
        return list(self.namespaces)

    def namespace_annotations(self, ns: str) -> dict:
        return self.namespaces.get(ns, {}).get("annotations", {})

    # -- core resources --
    def get_deployment(self, ns: str, name: str) -> dict | None:
        return self.deployments.get((ns, name))

    def list_deployments(self, ns: str) -> list[dict]:
        return [d for (n, _), d in self.deployments.items() if n == ns]

    def patch_deployment(self, ns: str, name: str, patch: dict) -> dict:
        d = self.deployments.get((ns, name))
        if d is None:
            # status=404 keeps the fake's error shape identical to
            # KubeClient's, so `except KubeError as e: if e.status == 404`
            # behaves the same against either seam
            raise KubeError(f"deployment {ns}/{name} not found", status=404)
        _deep_merge(d, patch)
        self.patches.append(("deployment", ns, name, patch))
        self._notify("deployment", d)
        return d

    def list_replicasets(self, ns: str) -> list[dict]:
        return [r for (n, _), r in self.replicasets.items() if n == ns]

    def list_pods(self, ns: str, selector: dict | None = None) -> list[dict]:
        out = []
        for (n, _), p in self.pods.items():
            if n != ns:
                continue
            labels = p.get("metadata", {}).get("labels", {})
            if selector and any(labels.get(k) != v for k, v in selector.items()):
                continue
            out.append(p)
        return out

    def list_hpas(self, ns: str) -> list[dict]:
        return [h for (n, _), h in self.hpas.items() if n == ns]

    # -- CRDs --
    def get_monitor(self, ns: str, name: str) -> DeploymentMonitor | None:
        return self.monitors.get((ns, name))

    def list_monitors(self, ns: str | None = None) -> list[DeploymentMonitor]:
        return [
            m for (n, _), m in self.monitors.items() if ns is None or n == ns
        ]

    def upsert_monitor(self, monitor: DeploymentMonitor) -> DeploymentMonitor:
        self.monitors[(monitor.namespace, monitor.name)] = monitor
        self._notify("monitor", monitor)
        return monitor

    def patch_monitor(self, ns: str, name: str, patch: dict) -> None:
        """Merge-PATCH a subset of a monitor (KubeClient contract)."""
        m = self.monitors.get((ns, name))
        if m is None:
            raise KubeError(f"deploymentmonitor {ns}/{name} not found", status=404)
        obj = _monitor_to_k8s(m)
        _deep_merge(obj, patch)
        merged = _monitor_from_k8s(obj)
        self.monitors[(ns, name)] = merged
        self._notify("monitor", merged)

    def delete_monitor(self, ns: str, name: str):
        self.monitors.pop((ns, name), None)

    def get_metadata(self, ns: str, name: str) -> DeploymentMetadata | None:
        return self.metadata.get((ns, name))

    def upsert_metadata(self, md: DeploymentMetadata) -> DeploymentMetadata:
        self.metadata[(md.namespace, md.name)] = md
        return md

    def delete_metadata(self, ns: str, name: str):
        self.metadata.pop((ns, name), None)

    # -- events (EventRecorder role, DeploymentController.go:204-209) --
    def record_event(self, kind: str, ns: str, name: str, reason: str, message: str):
        self.events.append(
            {"kind": kind, "namespace": ns, "name": name, "reason": reason,
             "message": message}
        )

    # -- watch plumbing --
    def subscribe(self, fn: Callable):
        self._watchers.append(fn)

    def _notify(self, kind: str, obj):
        for fn in self._watchers:
            fn(kind, obj)


def _deep_merge(dst: dict, patch: dict):
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v


class KubeClient:
    """Strategic-merge-patch REST client using the in-cluster token.

    Covers the same eight calls as FakeKube against a real apiserver:
    core/v1 namespaces+pods, apps/v1 deployments+replicasets,
    autoscaling/v2 HPAs, deployment.foremast.ai/v1alpha1 CRDs.
    """

    CRD_GROUP = "deployment.foremast.ai/v1alpha1"

    def __init__(self, base_url: str | None = None, token: str | None = None,
                 ca_path: str | None = None, timeout: float = 10.0):
        sa = "/var/run/secrets/kubernetes.io/serviceaccount"
        host = knobs.read("KUBERNETES_SERVICE_HOST")
        port = knobs.read("KUBERNETES_SERVICE_PORT")
        self.base = base_url or f"https://{host}:{port}"
        if token is None and os.path.exists(f"{sa}/token"):
            with open(f"{sa}/token") as f:
                token = f.read().strip()
        self.token = token or ""
        ca = ca_path or (f"{sa}/ca.crt" if os.path.exists(f"{sa}/ca.crt") else None)
        self.ctx = ssl.create_default_context(cafile=ca) if ca else None
        self.timeout = timeout

    def _req(self, method: str, path: str, body: dict | None = None,
             content_type: str = "application/json"):
        req = urllib.request.Request(
            self.base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
        )
        req.add_header("Authorization", f"Bearer {self.token}")
        req.add_header("Accept", "application/json")
        if body is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout, context=self.ctx) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = e.read()[:300]
            raise KubeError(
                f"{method} {path}: HTTP {e.code}: {detail!r}", status=e.code
            ) from e
        except Exception as e:  # noqa: BLE001 - API boundary
            raise KubeError(f"{method} {path}: {e}") from e

    LIST_PAGE_LIMIT = 500

    def _list(self, path: str) -> list[dict]:
        """GET a collection in pages, following metadata.continue.

        The limit parameter is load-bearing: a real apiserver only returns
        continue tokens when the client asks for a page size, so without it
        a 100k-object fleet comes back as one giant response."""
        sep = "&" if "?" in path else "?"
        items: list[dict] = []
        token = ""
        while True:
            page = f"{path}{sep}limit={self.LIST_PAGE_LIMIT}"
            if token:
                page += "&continue=" + urllib.parse.quote(token, safe="")
            obj = self._req("GET", page)
            items += obj.get("items", [])
            token = (obj.get("metadata") or {}).get("continue") or ""
            if not token:
                return items

    # -- namespaces --
    def list_namespaces(self) -> list[str]:
        return [i["metadata"]["name"] for i in self._list("/api/v1/namespaces")]

    def namespace_annotations(self, ns: str) -> dict:
        obj = self._req("GET", f"/api/v1/namespaces/{ns}")
        return obj.get("metadata", {}).get("annotations", {}) or {}

    # -- core --
    def get_deployment(self, ns: str, name: str) -> dict | None:
        try:
            return self._req("GET", f"/apis/apps/v1/namespaces/{ns}/deployments/{name}")
        except KubeError as e:
            if e.status == 404:
                return None
            raise

    def list_deployments(self, ns: str) -> list[dict]:
        return self._list(f"/apis/apps/v1/namespaces/{ns}/deployments")

    def patch_deployment(self, ns: str, name: str, patch: dict) -> dict:
        return self._req(
            "PATCH",
            f"/apis/apps/v1/namespaces/{ns}/deployments/{name}",
            patch,
            content_type="application/strategic-merge-patch+json",
        )

    def list_replicasets(self, ns: str) -> list[dict]:
        return self._list(f"/apis/apps/v1/namespaces/{ns}/replicasets")

    def list_pods(self, ns: str, selector: dict | None = None) -> list[dict]:
        sel = ""
        if selector:
            sel = "?labelSelector=" + ",".join(f"{k}%3D{v}" for k, v in selector.items())
        return self._list(f"/api/v1/namespaces/{ns}/pods{sel}")

    def list_hpas(self, ns: str) -> list[dict]:
        return self._list(
            f"/apis/autoscaling/v2/namespaces/{ns}/horizontalpodautoscalers"
        )

    # -- CRDs --
    def _crd(self, ns: str, plural: str, name: str = "") -> str:
        path = f"/apis/{self.CRD_GROUP}/namespaces/{ns}/{plural}"
        return f"{path}/{name}" if name else path

    def _upsert_crd(self, collection: str, path: str, patch_body: dict,
                    post_body: dict) -> None:
        """merge-PATCH, falling back to POST on not-found: no GET round-trip,
        no resourceVersion bookkeeping, and no clobbering of fields this
        caller didn't set. A lost create race (PATCH 404, POST 409) retries
        the PATCH — the object exists now."""
        try:
            self._req(
                "PATCH", path, patch_body,
                content_type="application/merge-patch+json",
            )
        except KubeError as e:
            if e.status != 404:
                raise
            try:
                self._req("POST", collection, post_body)
            except KubeError as e2:
                if e2.status != 409:
                    raise
                self._req(
                    "PATCH", path, patch_body,
                    content_type="application/merge-patch+json",
                )

    def _delete_crd(self, path: str) -> None:
        """Idempotent delete: a 404 is success, anything else surfaces."""
        try:
            self._req("DELETE", path)
        except KubeError as e:
            if e.status != 404:
                raise

    def get_monitor(self, ns: str, name: str) -> DeploymentMonitor | None:
        try:
            obj = self._req("GET", self._crd(ns, "deploymentmonitors", name))
        except KubeError as e:
            if e.status == 404:
                return None
            raise
        return _monitor_from_k8s(obj)

    def list_monitors(self, ns: str | None = None) -> list[DeploymentMonitor]:
        if ns is None:
            items = self._list(f"/apis/{self.CRD_GROUP}/deploymentmonitors")
        else:
            items = self._list(self._crd(ns, "deploymentmonitors"))
        return [_monitor_from_k8s(i) for i in items]

    def upsert_monitor(self, monitor: DeploymentMonitor) -> DeploymentMonitor:
        path = self._crd(monitor.namespace, "deploymentmonitors", monitor.name)
        body = _monitor_to_k8s(monitor)
        self._upsert_crd(
            self._crd(monitor.namespace, "deploymentmonitors"),
            path,
            {"metadata": {"annotations": body["metadata"]["annotations"]},
             "spec": body["spec"]},
            body,
        )
        # status is a subresource (deploy/crds/deploymentmonitor.yaml): the
        # write above silently DROPS .status, so persist it with a separate
        # PATCH against /status or phases/verdicts never survive in-cluster
        try:
            self._req(
                "PATCH",
                path + "/status",
                {"status": body["status"]},
                content_type="application/merge-patch+json",
            )
        except KubeError as e:
            if e.status != 404:
                raise  # only tolerate a CRD installed without the subresource
        return monitor

    def patch_monitor(self, ns: str, name: str, patch: dict) -> None:
        """Merge-PATCH a subset of a monitor (e.g. {'spec': {'continuous':
        True}}) without touching any other field — the safe path for
        spec-only writers like the watch/unwatch CLI, which must not
        round-trip a possibly-stale status copy."""
        self._req(
            "PATCH",
            self._crd(ns, "deploymentmonitors", name),
            patch,
            content_type="application/merge-patch+json",
        )

    def delete_monitor(self, ns: str, name: str):
        self._delete_crd(self._crd(ns, "deploymentmonitors", name))

    def get_metadata(self, ns: str, name: str) -> DeploymentMetadata | None:
        try:
            obj = self._req("GET", self._crd(ns, "deploymentmetadatas", name))
        except KubeError as e:
            if e.status == 404:
                return None
            raise
        return _metadata_from_k8s(obj)

    def upsert_metadata(self, md: DeploymentMetadata) -> DeploymentMetadata:
        """Create-or-replace a DeploymentMetadata record.

        The reference operator both writes and deletes metadata
        (DeploymentController.go:381-407), and the shipped default-metadata
        flow (deploy/stack/50-deployment-metadata-default.yaml) expects the
        operator to be able to stamp per-app records. No status subresource
        on this CRD — one merge-PATCH (or POST on first write) suffices.
        """
        body = _metadata_to_k8s(md)
        self._upsert_crd(
            self._crd(md.namespace, "deploymentmetadatas"),
            self._crd(md.namespace, "deploymentmetadatas", md.name),
            {"spec": body["spec"]},
            body,
        )
        return md

    def delete_metadata(self, ns: str, name: str):
        self._delete_crd(self._crd(ns, "deploymentmetadatas", name))

    def record_event(self, kind: str, ns: str, name: str, reason: str, message: str):
        # K8s Events API; failures are non-fatal observability loss
        import time as _t

        now = _t.strftime("%Y-%m-%dT%H:%M:%SZ", _t.gmtime())
        try:
            self._req(
                "POST",
                f"/api/v1/namespaces/{ns}/events",
                {
                    "metadata": {"generateName": f"{name}-foremast-"},
                    "involvedObject": {"kind": kind, "namespace": ns, "name": name},
                    "reason": reason,
                    "message": message,
                    "type": "Normal",
                    "firstTimestamp": now,
                    "lastTimestamp": now,
                    "source": {"component": "foremast-tpu-operator"},
                },
            )
        except KubeError:
            pass


# --- CRD JSON codecs (camelCase wire shape of deploy/crds/*.yaml) ---

_CAMEL_TABLE = {
    "start_time": "startTime", "wait_until": "waitUntil",
    "rollback_revision": "rollbackRevision",
    "hpa_score_template": "hpaScoreTemplate",
    "hpa_score_templates": "hpaScoreTemplates",
    "data_source_type": "dataSourceType",
    "metric_name": "metricName", "metric_type": "metricType",
    "metric_alias": "metricAlias",
    "observed_generation": "observedGeneration", "job_id": "jobId",
    "remediation_taken": "remediationTaken",
    "hpa_score_enabled": "hpaScoreEnabled", "hpa_logs": "hpaLogs",
    "anomalous_metrics": "anomalousMetrics",
}


def _camel(d):
    if isinstance(d, dict):
        return {_CAMEL_TABLE.get(k, k): _camel(v) for k, v in d.items()}
    if isinstance(d, list):
        return [_camel(v) for v in d]
    return d


def _monitor_to_k8s(m: DeploymentMonitor) -> dict:
    from dataclasses import asdict

    return {
        "apiVersion": KubeClient.CRD_GROUP,
        "kind": "DeploymentMonitor",
        "metadata": {
            "name": m.name,
            "namespace": m.namespace,
            "annotations": m.annotations,
        },
        "spec": _camel(asdict(m.spec)),
        "status": _camel(asdict(m.status)),
    }


def _metadata_to_k8s(md: DeploymentMetadata) -> dict:
    from dataclasses import asdict

    d = asdict(md)
    d.pop("name", None)
    d.pop("namespace", None)
    return {
        "apiVersion": KubeClient.CRD_GROUP,
        "kind": "DeploymentMetadata",
        "metadata": {"name": md.name, "namespace": md.namespace},
        "spec": _camel(d),
    }


def _monitor_from_k8s(obj: dict) -> DeploymentMonitor:
    from .types import (
        Analyst,
        Anomaly,
        AnomalousMetric,
        AnomalousMetricValue,
        HpaLogEntry,
        Metrics,
        MonitorSpec,
        MonitorStatus,
        Monitoring,
        RemediationAction,
    )

    meta = obj.get("metadata", {})
    spec, status = obj.get("spec", {}) or {}, obj.get("status", {}) or {}
    mm = spec.get("metrics", {}) or {}
    an = status.get("anomaly", {}) or {}
    return DeploymentMonitor(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", ""),
        annotations=meta.get("annotations", {}) or {},
        spec=MonitorSpec(
            selector=(spec.get("selector") or {}).get("matchLabels", spec.get("selector") or {}),
            analyst=Analyst(**(spec.get("analyst") or {})),
            start_time=spec.get("startTime", ""),
            wait_until=spec.get("waitUntil", ""),
            metrics=Metrics(
                data_source_type=mm.get("dataSourceType", "prometheus"),
                endpoint=mm.get("endpoint", ""),
                monitoring=[
                    Monitoring(
                        metric_name=x.get("metricName", ""),
                        metric_type=x.get("metricType", "counter"),
                        metric_alias=x.get("metricAlias", ""),
                    )
                    for x in mm.get("monitoring", []) or []
                ],
            ),
            continuous=bool(spec.get("continuous", False)),
            remediation=RemediationAction(
                option=(spec.get("remediation") or {}).get("option", "None"),
                parameters=(spec.get("remediation") or {}).get("parameters", {}) or {},
            ),
            rollback_revision=int(spec.get("rollbackRevision", 0) or 0),
            hpa_score_template=spec.get("hpaScoreTemplate", "") or "",
        ),
        status=MonitorStatus(
            observed_generation=int(status.get("observedGeneration", 0) or 0),
            job_id=status.get("jobId", "") or "",
            phase=status.get("phase", "Healthy") or "Healthy",
            remediation_taken=bool(status.get("remediationTaken", False)),
            anomaly=Anomaly(
                anomalous_metrics=[
                    AnomalousMetric(
                        name=x.get("name", ""),
                        tags=x.get("tags", ""),
                        values=[
                            AnomalousMetricValue(int(v.get("time", 0)), float(v.get("value", 0)))
                            for v in x.get("values", []) or []
                        ],
                    )
                    for x in an.get("anomalousMetrics", []) or []
                ]
            ),
            timestamp=status.get("timestamp", "") or "",
            expired=bool(status.get("expired", False)),
            hpa_score_enabled=bool(status.get("hpaScoreEnabled", False)),
            hpa_logs=[
                HpaLogEntry(
                    timestamp=x.get("timestamp", ""),
                    hpascore=float(x.get("hpascore", 0) or 0),
                    reason=x.get("reason", "") or "",
                    details=x.get("details", []) or [],
                )
                for x in status.get("hpaLogs", []) or []
            ],
        ),
    )


def _metadata_from_k8s(obj: dict) -> DeploymentMetadata:
    from .types import Analyst, HpaScoreTemplate, Metrics, Monitoring

    meta = obj.get("metadata", {})
    spec = obj.get("spec", {}) or {}
    mm = spec.get("metrics", {}) or {}
    return DeploymentMetadata(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", ""),
        analyst=Analyst(**(spec.get("analyst") or {})),
        metrics=Metrics(
            data_source_type=mm.get("dataSourceType", "prometheus"),
            endpoint=mm.get("endpoint", ""),
            monitoring=[
                Monitoring(
                    metric_name=x.get("metricName", ""),
                    metric_type=x.get("metricType", "counter"),
                    metric_alias=x.get("metricAlias", ""),
                )
                for x in mm.get("monitoring", []) or []
            ],
        ),
        hpa_score_templates=[
            HpaScoreTemplate(name=t.get("name", ""), metrics=t.get("metrics", []) or [])
            for t in spec.get("hpaScoreTemplates", []) or []
        ],
    )
