"""Operator reconcile loop: poll-and-diff in place of Go informers.

The reference wires informer caches + a rate-limited workqueue with 2
workers and a 10 s status ticker (cmd/manager/main.go:65-111,
Barrelman.go:64-69). The TPU-native operator replaces that machinery with
one idempotent `tick()`: list the world, diff against the previous
snapshot, dispatch the controller handlers, then run the status sweep.
Restart-safe by construction — the first tick rebuilds the snapshot and
reconciles from the CRDs (the reference relies on the same property,
SURVEY.md §5 checkpoint/resume).
"""
from __future__ import annotations

import copy
import logging
import time

from .barrelman import Barrelman
from .controllers import DeploymentController, HpaController, MonitorController
from .types import PHASE_UNHEALTHY

log = logging.getLogger("foremast_tpu.operator")


class OperatorLoop:
    def __init__(self, kube, analyst, mode: str = "hpa_and_healthy_monitoring",
                 hpa_strategy: str = "hpa_exists", watch_namespaces=None,
                 health_probe=None):
        self.kube = kube
        self.barrelman = Barrelman(kube, analyst, mode=mode,
                                   hpa_strategy=hpa_strategy,
                                   watch_namespaces=watch_namespaces)
        self.deployments = DeploymentController(kube, self.barrelman)
        self.monitors = MonitorController(kube, self.barrelman)
        self.hpas = HpaController(kube, self.barrelman)
        self._depl_snapshot: dict[tuple, dict] = {}
        self._hpa_snapshot: dict[tuple, dict] = {}
        self._monitor_phases: dict[tuple, str] = {}
        self._primed = False
        self._stop_requested = False  # signal-handler seam (request_stop)
        # degraded-mode remediation gate: () -> brain health state
        # ("ok"/"degraded"/"overloaded"/"stalled"). Defaults to the
        # analyst's /readyz probe when it has one; absent probe = always
        # "ok" (fail-open — suppression must never outlive its evidence).
        if health_probe is None:
            health_probe = getattr(analyst, "get_health", None)
        self.health_probe = health_probe
        self.remediations_suppressed_total = 0
        self._brain_health = "ok"  # probed once per tick
        self._health_unreachable_since: float | None = None
        # flips currently being held: one event + one count per flip,
        # however many ticks the brain stays degraded (cleared when the
        # remediation finally dispatches or the phase recovers)
        self._suppressed_flips: set[tuple] = set()

    # how long a last-known NON-ok brain state keeps suppressing after the
    # probe goes unreachable. An overloaded/stalled brain fails /readyz —
    # the same probe k8s readiness uses — so its Service endpoint drops
    # and the operator's probe sees connection-refused at exactly the
    # moment suppression matters most; naive fail-open there would
    # dispatch the held rollback on the worst data. Bounded so a brain
    # that dies for good cannot suppress remediation forever.
    HEALTH_HOLD_S = 300.0

    def tick(self, now: float | None = None) -> dict:
        """One full reconcile pass. Returns the status sweep's touches."""
        now = time.time() if now is None else now
        self._brain_health = self._probe_health(now)
        self._diff_deployments()
        self._diff_hpas()
        touched = self.barrelman.check_running_status(now)
        self._sweep_monitors()
        self._primed = True
        return touched

    def _probe_health(self, now: float) -> str:
        if self.health_probe is None:
            return "ok"
        try:
            state = str(self.health_probe())
        except Exception:  # noqa: BLE001 - probe boundary
            # unreachable. Last seen healthy -> fail open (an unreachable
            # brain produced no NEW verdict flips, and failing closed
            # would let a dead endpoint suppress remediation forever).
            # Last seen NON-ok -> hold that state for a bounded window:
            # unreachability right after a degraded reading is usually
            # the readiness gate pulling the pod, not recovery.
            if self._brain_health != "ok":
                if self._health_unreachable_since is None:
                    self._health_unreachable_since = now
                if now - self._health_unreachable_since <= self.HEALTH_HOLD_S:
                    return self._brain_health
            return "ok"
        self._health_unreachable_since = None
        return state

    # -- deployments --
    def _diff_deployments(self):
        seen = {}
        for ns in self.kube.list_namespaces():
            if not self.deployments.is_monitored_namespace(ns):
                continue
            for d in self.kube.list_deployments(ns):
                key = (ns, d["metadata"]["name"])
                seen[key] = copy.deepcopy(d)
                old = self._depl_snapshot.get(key)
                try:
                    if old is None:
                        # on_add is idempotent, so the first tick after a
                        # restart just re-ensures baseline monitors exist
                        self.deployments.on_add(d)
                    elif old != seen[key]:
                        self.deployments.on_update(old, d)
                except Exception as e:  # noqa: BLE001 - one bad app must not
                    # wedge reconciliation for the rest (snapshot still
                    # advances, so the crash does not repeat every tick)
                    self.kube.record_event(
                        "Deployment", ns, key[1], "ReconcileError", str(e)
                    )
        for key in set(self._depl_snapshot) - set(seen):
            ns, name = key
            # a key can vanish because its namespace was un-annotated for
            # monitoring; only a truly deleted deployment gets on_delete
            # (which removes the app's user-managed DeploymentMetadata)
            try:
                if self.kube.get_deployment(ns, name) is None:
                    self.deployments.on_delete(self._depl_snapshot[key])
            except Exception as e:  # noqa: BLE001 - per-item isolation,
                # with RETRY: deletions are one-shot events not even a
                # restart can replay (the deployment is gone from lists),
                # so a transient failure here must keep the stale entry in
                # the snapshot and re-attempt cleanup next tick — never
                # silently leak the app's DeploymentMetadata
                seen[key] = self._depl_snapshot[key]
                self.kube.record_event(
                    "Deployment", ns, name, "ReconcileError", str(e)
                )
        self._depl_snapshot = seen

    # -- hpas --
    def _diff_hpas(self):
        seen = {}
        for ns in self.kube.list_namespaces():
            if not self.barrelman.watches_namespace(ns):
                continue
            for h in self.kube.list_hpas(ns):
                key = (ns, h["metadata"]["name"])
                seen[key] = copy.deepcopy(h)
                old = self._hpa_snapshot.get(key)
                try:
                    if old != seen[key]:
                        self.hpas.on_upsert(old, h)
                except Exception as e:  # noqa: BLE001 - one bad HPA must
                    # not wedge the tick — but the failed stamp RETRIES:
                    # the snapshot keeps the pre-failure view (old, or no
                    # key at all for a brand-new HPA) so the same diff
                    # fires again next tick; a transient apiserver blip
                    # must not silently disable hpa scoring until restart
                    if old is not None:
                        seen[key] = old
                    else:
                        del seen[key]
                    self.kube.record_event(
                        "HorizontalPodAutoscaler", ns, key[1],
                        "ReconcileError", str(e)
                    )
        for key in set(self._hpa_snapshot) - set(seen):
            try:
                self.hpas.on_delete(self._hpa_snapshot[key])
            except Exception as e:  # noqa: BLE001 - retry like the
                # deployment delete loop: a deleted HPA's key never
                # reappears, so dropping it here would leave
                # hpa_score_enabled set on the monitor forever
                seen[key] = self._hpa_snapshot[key]
                self.kube.record_event(
                    "HorizontalPodAutoscaler", key[0], key[1],
                    "ReconcileError", str(e)
                )
        self._hpa_snapshot = seen

    # -- monitors (remediation on phase flips) --
    def _sweep_monitors(self):
        for m in self.kube.list_monitors():
            if not self.barrelman.watches_namespace(m.namespace):
                continue
            key = (m.namespace, m.name)
            old_phase = self._monitor_phases.get(key)
            if m.status.phase == PHASE_UNHEALTHY and old_phase != PHASE_UNHEALTHY:
                if self._brain_health != "ok":
                    # degraded-mode suppression: while the brain reports
                    # DEGRADED/OVERLOADED/STALLED its verdicts may rest on
                    # stale or shed data — rolling a deployment back on
                    # them is the one failure mode worse than no verdict.
                    # The phase is NOT advanced, so the flip re-dispatches
                    # the first tick the brain is healthy again; the event
                    # and counter fire once per HELD FLIP, not per tick (a
                    # half-hour degradation must not emit 180 duplicates).
                    if key not in self._suppressed_flips:
                        self._suppressed_flips.add(key)
                        self.remediations_suppressed_total += 1
                        self.kube.record_event(
                            "DeploymentMonitor", m.namespace, m.name,
                            "RemediationSuppressed",
                            f"brain health is {self._brain_health}; "
                            "holding rollback/pause until it recovers",
                        )
                    continue
                self._suppressed_flips.discard(key)
                prev = None
                if old_phase is not None:
                    prev = copy.deepcopy(m)
                    prev.status.phase = old_phase
                try:
                    self.monitors.on_update(prev, m)
                except Exception as e:  # noqa: BLE001 - a failed
                    # remediation (apiserver hiccup mid-rollback) must not
                    # abort the sweep for the other monitors; the phase is
                    # deliberately NOT advanced, so the flip re-dispatches
                    # next tick — remediation retries until it applies
                    self.kube.record_event(
                        "DeploymentMonitor", m.namespace, m.name,
                        "RemediationError", str(e)
                    )
                    continue
            if m.status.phase != PHASE_UNHEALTHY:
                self._suppressed_flips.discard(key)  # flip resolved itself
            self._monitor_phases[key] = m.status.phase

    def request_stop(self):
        """Signal-safe: make run_forever return after the current tick
        (SIGTERM handler seam — pod termination should not cut a tick in
        half mid-remediation). Plain attribute write only — no Event/lock
        a mid-wait signal could deadlock on."""
        self._stop_requested = True

    # ceiling for the consecutive-failure backoff below; also caps the
    # exponent so 2**n can never overflow into a silly float
    MAX_TICK_BACKOFF = 300.0

    def _tick_delay(self, consecutive_failures: int, interval: float) -> float:
        """Delay until the next tick: the plain interval while healthy,
        doubling per CONSECUTIVE failure (capped) while the apiserver is
        down — a dead control plane must not be polled at full rate."""
        if consecutive_failures <= 0:
            return interval
        return min(self.MAX_TICK_BACKOFF,
                   interval * (2.0 ** min(consecutive_failures, 10)))

    def run_forever(self, interval: float = 10.0):
        consecutive_failures = 0
        while not self._stop_requested:
            t0 = time.time()
            try:
                self.tick()
                consecutive_failures = 0
            except Exception:  # noqa: BLE001 - operator must survive
                consecutive_failures += 1
                log.exception(
                    "operator tick failed (consecutive=%d, next in %.0fs)",
                    consecutive_failures,
                    self._tick_delay(consecutive_failures, interval),
                )
            delay = self._tick_delay(consecutive_failures, interval)
            while (not self._stop_requested
                   and time.time() - t0 < delay):
                time.sleep(min(0.2, delay))
