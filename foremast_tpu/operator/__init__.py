"""Operator layer: the TPU-native foremast-barrelman equivalent.

Watches Deployments/HPAs/DeploymentMonitors, drives analysis jobs through
the engine (in-process or HTTP), applies remediation, and maintains the HPA
score wiring — re-derived from foremast-barrelman (SURVEY.md §2.1) as a
tick-driven reconciler over a small injectable K8s API seam.
"""
from .analyst import GrpcAnalyst, HttpAnalyst, InProcessAnalyst, StatusResponse
from .barrelman import Barrelman
from .controllers import DeploymentController, HpaController, MonitorController
from .kube import FakeKube, KubeClient
from .types import (
    DeploymentMetadata,
    DeploymentMonitor,
    PHASE_HEALTHY,
    PHASE_RUNNING,
    PHASE_UNHEALTHY,
)

__all__ = [
    "Barrelman",
    "DeploymentController",
    "MonitorController",
    "HpaController",
    "FakeKube",
    "KubeClient",
    "GrpcAnalyst",
    "HttpAnalyst",
    "InProcessAnalyst",
    "StatusResponse",
    "DeploymentMetadata",
    "DeploymentMonitor",
    "PHASE_HEALTHY",
    "PHASE_RUNNING",
    "PHASE_UNHEALTHY",
]
