"""Barrelman: the shared operator engine.

Re-derives foremast-barrelman/pkg/controller/Barrelman.go as a tick-driven
reconciler (the Go version runs a 10 s ticker goroutine, Barrelman.go:64-69;
here the caller owns the loop — `tick()` is pure logic, trivially testable):

  * monitor_new_deployment (Barrelman.go:233-372): resolve old/new pod sets
    from ReplicaSet revisions, build the current/baseline/historical metric
    queries, start an analysis job (one retry, :289-296), upsert the
    DeploymentMonitor with phase Running + waitUntil.
  * check_running_status (Barrelman.go:448-571): poll every Running
    monitor's job, fold phase/anomaly/hpaLogs into status, expire past
    waitUntil, re-arm continuous/HPA monitors.
  * metadata resolution with TTL cache + app -> appType -> operator
    namespace fallbacks (Barrelman.go:382-417).

Modes (cmd/manager/main.go:69-76): MODE in {hpa_only,
hpa_and_healthy_monitoring, healthy_monitoring_only}; HPA_STRATEGY
`hpa_exists` stamps the score template when an HPA object exists.
"""
from __future__ import annotations

import time
from dataclasses import dataclass

from ..dataplane.promql import MetricQuerySpec, build_metric_windows, pod_count_url
from ..utils.timeutils import to_rfc3339
from .analyst import AnalystError, HttpAnalyst, InProcessAnalyst  # noqa: F401
from .types import (
    DEFAULT_HPA_TEMPLATE,
    PHASE_HEALTHY,
    PHASE_RUNNING,
    Anomaly,
    DeploymentMetadata,
    DeploymentMonitor,
    HpaLogEntry,
    MonitorSpec,
    MonitorStatus,
    STRATEGY_CANARY,
    STRATEGY_CONTINUOUS,
    STRATEGY_HPA,
    STRATEGY_ROLLING_UPDATE,
)

WATCH_TIME_MINUTES = 10  # DeploymentController.go:48
WAIT_UNTIL_MAX_MINUTES = 30  # DeploymentController.go:50
METADATA_CACHE_TTL = 60.0

MODE_HPA_ONLY = "hpa_only"
MODE_HPA_AND_HEALTHY = "hpa_and_healthy_monitoring"
MODE_HEALTHY_ONLY = "healthy_monitoring_only"


@dataclass
class _CachedMetadata:
    md: DeploymentMetadata
    at: float


class Barrelman:
    def __init__(self, kube, analyst, mode: str = MODE_HPA_AND_HEALTHY,
                 hpa_strategy: str = "hpa_exists", operator_namespace: str = "foremast",
                 watch_namespaces=None):
        self.kube = kube
        self.analyst = analyst
        self.mode = mode
        self.hpa_strategy = hpa_strategy
        self.operator_namespace = operator_namespace
        # non-empty set -> reconcile ONLY these namespaces (WATCH_NAMESPACES)
        self.watch_namespaces = set(watch_namespaces or ())
        self._md_cache: dict[tuple, _CachedMetadata] = {}

    def watches_namespace(self, ns: str) -> bool:
        return not self.watch_namespaces or ns in self.watch_namespaces

    # ------------------------------------------------------------ metadata
    def get_deployment_metadata(self, ns: str, app: str,
                                app_type: str = "") -> DeploymentMetadata | None:
        """App metadata with TTL cache and the reference's fallback chain:
        app name -> appType -> operator namespace (Barrelman.go:382-417)."""
        key = (ns, app, app_type)
        hit = self._md_cache.get(key)
        now = time.time()
        if hit and now - hit.at < METADATA_CACHE_TTL:
            return hit.md
        md = (
            self.kube.get_metadata(ns, app)
            or (self.kube.get_metadata(ns, app_type) if app_type else None)
            or self.kube.get_metadata(self.operator_namespace, app_type or "deployment-metadata-default")
            or self.kube.get_metadata(self.operator_namespace, "deployment-metadata-default")
        )
        if md is not None:
            self._md_cache[key] = _CachedMetadata(md, now)
        return md

    def monitors_health(self) -> bool:
        return self.mode in (MODE_HPA_AND_HEALTHY, MODE_HEALTHY_ONLY)

    def monitors_hpa(self) -> bool:
        return self.mode in (MODE_HPA_AND_HEALTHY, MODE_HPA_ONLY)

    # ------------------------------------------------------------ pod names
    def get_pod_names(self, ns: str, deployment: dict) -> tuple[list[str], list[str]]:
        """(old_pods, new_pods) from ReplicaSet revisions.

        The Go version diffs ReplicaSets with sleeps and retries around
        rollout churn (Barrelman.go:100-230); reconciliation re-runs every
        tick here, so one clean pass suffices: group the deployment's RSes
        by revision, newest revision's pods are "new", the rest "old".
        """
        name = deployment["metadata"]["name"]
        rss = [
            rs
            for rs in self.kube.list_replicasets(ns)
            if any(
                o.get("kind") == "Deployment" and o.get("name") == name
                for o in rs["metadata"].get("ownerReferences", [])
            )
        ]
        if not rss:
            return [], []

        def revision(rs):
            return int(rs["metadata"].get("annotations", {}).get(
                "deployment.kubernetes.io/revision", 0
            ))

        newest = max(revision(rs) for rs in rss)
        new_hashes = {
            rs["metadata"]["labels"].get("pod-template-hash", "")
            for rs in rss
            if revision(rs) == newest
        }
        old_hashes = {
            rs["metadata"]["labels"].get("pod-template-hash", "")
            for rs in rss
            if revision(rs) != newest and int(rs["spec"].get("replicas", 0)) >= 0
        }
        sel = (deployment["spec"].get("selector", {}) or {}).get("matchLabels", {})
        pods = self.kube.list_pods(ns, sel or None)
        old_pods, new_pods = [], []
        for p in pods:
            h = p["metadata"].get("labels", {}).get("pod-template-hash", "")
            if h in new_hashes:
                new_pods.append(p["metadata"]["name"])
            elif h in old_hashes:
                old_pods.append(p["metadata"]["name"])
        return old_pods, new_pods

    # ------------------------------------------------------------ requests
    def _specs_from_metadata(self, md: DeploymentMetadata) -> list[MetricQuerySpec]:
        return [
            MetricQuerySpec(
                name=m.metric_alias or m.metric_name,
                data_source_type=md.metrics.data_source_type or "prometheus",
                priority=i,
            )
            for i, m in enumerate(md.metrics.monitoring)
        ]

    def _specs_from_template(self, md: DeploymentMetadata, template: str) -> list[MetricQuerySpec]:
        t = md.template_named(template) or md.template_named(DEFAULT_HPA_TEMPLATE)
        aliases = t.metrics if t else ["cpu", "tps", "latency"]
        return [
            MetricQuerySpec(name=a, data_source_type=md.metrics.data_source_type,
                            priority=i)
            for i, a in enumerate(aliases)
        ]

    def build_request(self, ns: str, app: str, md: DeploymentMetadata,
                      strategy: str, current_pods=None, baseline_pods=None,
                      now: float | None = None) -> dict:
        now = time.time() if now is None else now
        start, end = now, now + WATCH_TIME_MINUTES * 60
        if strategy == STRATEGY_HPA:
            specs = self._specs_from_template(md, DEFAULT_HPA_TEMPLATE)
        else:
            specs = self._specs_from_metadata(md)
        windows = build_metric_windows(
            md.metrics.endpoint, specs, strategy, start, end, ns, app,
            current_pods=current_pods, baseline_pods=baseline_pods,
        )
        info = {"current": {}, "baseline": {}, "historical": {}}
        for w in windows:
            flags = {"priority": w.priority, "isIncrease": w.is_increase,
                     "isAbsolute": w.is_absolute}
            if w.current:
                info["current"][w.name] = {"url": w.current, **flags}
            if w.baseline:
                info["baseline"][w.name] = {"url": w.baseline, **flags}
            if w.historical:
                info["historical"][w.name] = {"url": w.historical, **flags}
        return {
            "appName": app,
            "namespace": ns,
            "strategy": strategy,
            "startTime": to_rfc3339(start),
            "endTime": to_rfc3339(end),
            "metricsInfo": info,
            "podCountURL": pod_count_url(md.metrics.endpoint, ns, app, start, end),
        }

    # ----------------------------------------------------------- monitoring
    def monitor_new_deployment(self, ns: str, app: str, deployment: dict,
                               strategy: str = STRATEGY_ROLLING_UPDATE,
                               continuous: bool = False,
                               rollback_revision: int = 0,
                               remediation_option: str = "",
                               now: float | None = None) -> DeploymentMonitor | None:
        """Create/refresh the monitor for a (re)deployed app and start a job."""
        now = time.time() if now is None else now
        app_type = deployment["metadata"].get("annotations", {}).get(
            "deployment.foremast.ai/type", ""
        )
        md = self.get_deployment_metadata(ns, app, app_type)
        if md is None:
            self.kube.record_event(
                "Deployment", ns, app, "NoMetadata",
                "no DeploymentMetadata found; skipping analysis",
            )
            return None
        old_pods, new_pods = ([], [])
        if strategy in (STRATEGY_ROLLING_UPDATE, STRATEGY_CANARY):
            old_pods, new_pods = self.get_pod_names(ns, deployment)
        req = self.build_request(
            ns, app, md, strategy,
            current_pods=new_pods or None, baseline_pods=old_pods or None,
            now=now,
        )
        job_id = ""
        try:
            job_id = self.analyst.start_analyzing(req)
        except AnalystError:
            try:  # one retry (Barrelman.go:289-296)
                job_id = self.analyst.start_analyzing(req)
            except AnalystError as e:
                self.kube.record_event(
                    "Deployment", ns, app, "AnalystUnavailable", str(e)
                )
        wait_minutes = min(WATCH_TIME_MINUTES * 2, WAIT_UNTIL_MAX_MINUTES)
        existing = self.kube.get_monitor(ns, app)
        monitor = existing or DeploymentMonitor(name=app, namespace=ns)
        monitor.annotations.setdefault("deployment.foremast.ai/name", app)
        monitor.spec = MonitorSpec(
            selector=(deployment["spec"].get("selector", {}) or {}).get("matchLabels", {}),
            analyst=monitor.spec.analyst,
            start_time=to_rfc3339(now),
            wait_until=to_rfc3339(now + wait_minutes * 60),
            metrics=md.metrics,
            continuous=continuous or monitor.spec.continuous,
            remediation=monitor.spec.remediation,
            rollback_revision=rollback_revision or monitor.spec.rollback_revision,
            hpa_score_template=monitor.spec.hpa_score_template,
        )
        if remediation_option:
            monitor.spec.remediation.option = remediation_option
        monitor.status = MonitorStatus(
            job_id=job_id,
            phase=PHASE_RUNNING if job_id else PHASE_HEALTHY,
            timestamp=to_rfc3339(now),
            expired=not job_id,
            hpa_score_enabled=monitor.status.hpa_score_enabled,
            hpa_logs=monitor.status.hpa_logs,
        )
        return self.kube.upsert_monitor(monitor)

    def monitor_continuously(self, monitor: DeploymentMonitor,
                             now: float | None = None):
        # MODE gate lives HERE, not at call sites, so every dispatch path
        # (MonitorController re-arm, HpaController upsert, the status
        # sweep) enforces the same invariant: an hpa_only operator never
        # starts health jobs, a healthy_monitoring_only one never starts
        # HPA scoring. (The reference declared hasHPA() but never called
        # it — Barrelman.go:74 is dead code there; we close the gap.)
        if not self.monitors_health():
            return None
        return self._monitor_perpetual(monitor, STRATEGY_CONTINUOUS, now)

    def monitor_hpa(self, monitor: DeploymentMonitor, now: float | None = None):
        if not self.monitors_hpa():
            return None
        return self._monitor_perpetual(monitor, STRATEGY_HPA, now)

    def _monitor_perpetual(self, monitor: DeploymentMonitor, strategy: str,
                           now: float | None = None):
        now = time.time() if now is None else now
        ns, app = monitor.namespace, monitor.name
        md = self.get_deployment_metadata(ns, app)
        if md is None:
            return None
        req = self.build_request(ns, app, md, strategy, now=now)
        try:
            job_id = self.analyst.start_analyzing(req)
        except AnalystError as e:
            self.kube.record_event("DeploymentMonitor", ns, app, "AnalystUnavailable", str(e))
            return None
        monitor.status.job_id = job_id
        monitor.status.phase = PHASE_RUNNING
        monitor.status.expired = False
        monitor.status.timestamp = to_rfc3339(now)
        return self.kube.upsert_monitor(monitor)

    # ----------------------------------------------------------- status tick
    def check_running_status(self, now: float | None = None) -> dict:
        """One reconcile pass over every namespace's monitors.

        Returns {"<ns>/<name>": phase} of monitors it touched.
        """
        now = time.time() if now is None else now
        touched = {}
        for ns in self.kube.list_namespaces():
            if not self.watches_namespace(ns):
                continue
            for monitor in self.kube.list_monitors(ns):
                key = f"{ns}/{monitor.name}"
                if monitor.status.phase == PHASE_RUNNING:
                    changed = self._poll_running(monitor, now)
                    if changed:
                        monitor.status.remediation_taken = False
                        self.kube.upsert_monitor(monitor)
                        touched[key] = monitor.status.phase
                elif monitor.spec.continuous or monitor.spec.hpa_score_template:
                    # re-arm perpetual monitors; unhealthy ones get a 60 s
                    # breather before re-trigger (Barrelman.go:552-565)
                    if monitor.status.phase == "Unhealthy":
                        try:
                            from ..utils.timeutils import from_rfc3339

                            last = from_rfc3339(monitor.status.timestamp)
                        except (ValueError, TypeError):
                            last = 0.0
                        if now - last <= 60:
                            continue
                    if self.monitors_health() and monitor.spec.continuous:
                        self.monitor_continuously(monitor, now)
                        touched[key] = monitor.status.phase
                    elif self.monitors_hpa() and monitor.spec.hpa_score_template:
                        self.monitor_hpa(monitor, now)
                        touched[key] = monitor.status.phase
        return touched

    def _poll_running(self, monitor: DeploymentMonitor, now: float) -> bool:
        changed = False
        if not monitor.status.expired:
            if not monitor.status.job_id:
                # no job was ever created: expire to Healthy
                monitor.status.expired = True
                monitor.status.phase = PHASE_HEALTHY
                monitor.status.timestamp = to_rfc3339(now)
                return True
            try:
                resp = self.analyst.get_status(monitor.status.job_id)
            except AnalystError:
                # analyst down or job gone: still fall through to the
                # expiry check below, else the monitor polls forever
                resp = None
            if resp is not None:
                old_phase = monitor.status.phase
                monitor.status.phase = resp.phase
                if resp.anomaly:
                    monitor.status.anomaly = Anomaly.from_flat(resp.anomaly)
                    changed = True
                if resp.hpa_logs:
                    new_logs = [
                        HpaLogEntry(
                            timestamp=str(l.get("timestamp", "")),
                            hpascore=float(l.get("hpascore", 0) or 0),
                            reason=l.get("reason", "") or "",
                            details=l.get("details", []) or [],
                        )
                        for l in resp.hpa_logs
                    ]
                    old_ts = sorted(l.timestamp for l in monitor.status.hpa_logs)
                    if sorted(l.timestamp for l in new_logs) != old_ts:
                        monitor.status.hpa_logs = new_logs
                        changed = True
                if monitor.status.phase != old_phase:
                    changed = True
                monitor.status.timestamp = to_rfc3339(now)
        if monitor.status.phase == PHASE_RUNNING and monitor.spec.wait_until:
            try:
                from ..utils.timeutils import from_rfc3339

                until = from_rfc3339(monitor.spec.wait_until)
            except (ValueError, TypeError):
                until = None
            if until is not None and until < now:
                monitor.status.phase = PHASE_HEALTHY
                monitor.status.expired = True
                monitor.status.timestamp = to_rfc3339(now)
                changed = True
        return changed
