"""The three operator controllers: Deployment, Monitor (remediation), HPA.

Event-handler re-derivations of foremast-barrelman/pkg/controller/
{DeploymentController,MonitorController,HpaController}.go. The Go versions
hang off informer caches; here each controller exposes plain on_* methods
the reconcile loop (or FakeKube watch) calls — same decisions, no informer
machinery.

Key behavior contracts:
  * namespace gating — blacklist {kube-public, kube-system, opa, monitoring}
    + namespace annotation foremast.ai/monitoring != "false"
    (DeploymentController.go:89-94, :412-429).
  * canary naming — deployments suffixed "-foremast-canary" are judged
    against the base deployment (DeploymentController.go:58).
  * redeploy detection — container image or env changed
    (DeploymentController.go:125-135, :156-194); rollback-generated updates
    skipped (revision == RollbackRevision or deprecated rollback annotation,
    :177-186).
  * remediation — phase flip to Unhealthy with !RemediationTaken dispatches
    rollback/pause/auto (MonitorController.go:122-143). Rollback is
    re-implemented as a ReplicaSet template patch (the modern equivalent of
    the removed extensions/v1beta1 DeploymentRollback the reference used,
    MonitorController.go:222-237); paused deployments are refused (:219-221).
  * HPA — stamps hpaScoreTemplate (default cpu_bound) when HPA_STRATEGY is
    hpa_exists, renders a scaling-explanation letter on desiredReplicas
    changes driven by the hpa_score metric: 4 most recent logs for scale-up,
    6 for scale-down (HpaController.go:94-141).
"""
from __future__ import annotations

import time

from ..utils.timeutils import to_rfc3339
from .barrelman import Barrelman
from .types import (
    DEFAULT_HPA_TEMPLATE,
    PHASE_HEALTHY,
    PHASE_UNHEALTHY,
    REMEDIATION_AUTO,
    REMEDIATION_AUTO_PAUSE,
    REMEDIATION_AUTO_ROLLBACK,
    DeploymentMonitor,
    MonitorSpec,
    MonitorStatus,
    STRATEGY_CANARY,
    STRATEGY_HPA,
    STRATEGY_ROLLING_UPDATE,
)

NAMESPACE_BLACKLIST = {"kube-public", "kube-system", "opa", "monitoring"}
MONITORING_ANNOTATION = "foremast.ai/monitoring"
CANARY_SUFFIX = "-foremast-canary"
ROLLBACK_ANNOTATION = "deprecated.deployment.rollback.to"
ROLLBACK_MESSAGE_ANNOTATION = "deployment.foremast.ai/rollbackMessage"
HPA_SCORE_METRIC = "namespace_app_pod_hpa_score"

ALERT_LETTER = """
At {timestamp} {application} at {namespace} was scaled {action} from {old} to {new} pods. This is because
{details}
If you have any question, please refer to the HPA docs.
"""


def _containers(deployment: dict) -> list[dict]:
    return (
        deployment.get("spec", {})
        .get("template", {})
        .get("spec", {})
        .get("containers", [])
    )


def _env_equal(a: list, b: list) -> bool:
    if len(a) != len(b):
        return False
    return all(
        x.get("name") == y.get("name") and x.get("value") == y.get("value")
        for x, y in zip(a, b)
    )


def _revision(deployment: dict) -> int:
    return int(
        deployment.get("metadata", {})
        .get("annotations", {})
        .get("deployment.kubernetes.io/revision", 0)
        or 0
    )


class DeploymentController:
    def __init__(self, kube, barrelman: Barrelman):
        self.kube = kube
        self.barrelman = barrelman

    # -- gating (DeploymentController.go:89-94, 412-429) --
    def is_monitored_namespace(self, ns: str) -> bool:
        if ns in NAMESPACE_BLACKLIST:
            return False
        if not self.barrelman.watches_namespace(ns):
            return False
        return self.kube.namespace_annotations(ns).get(MONITORING_ANNOTATION) != "false"

    def _app_name(self, deployment: dict) -> str:
        labels = deployment.get("metadata", {}).get("labels", {}) or {}
        return labels.get("app", deployment.get("metadata", {}).get("name", ""))

    # -- handlers --
    def on_add(self, deployment: dict):
        """New app-labeled deployment -> baseline Healthy monitor; canary
        deployments start a canary analysis against the base immediately."""
        ns = deployment["metadata"].get("namespace", "default")
        if not self.is_monitored_namespace(ns):
            return
        name = deployment["metadata"]["name"]
        app = self._app_name(deployment)
        if not app:
            return
        if name.endswith(CANARY_SUFFIX):
            base = name[: -len(CANARY_SUFFIX)]
            self.barrelman.monitor_new_deployment(
                ns, base, deployment, strategy=STRATEGY_CANARY
            )
            return
        if self.kube.get_monitor(ns, name) is None:
            self.kube.upsert_monitor(
                DeploymentMonitor(
                    name=name,
                    namespace=ns,
                    annotations={"deployment.foremast.ai/name": name},
                    spec=MonitorSpec(
                        selector=(deployment["spec"].get("selector", {}) or {}).get(
                            "matchLabels", {}
                        )
                    ),
                    status=MonitorStatus(
                        phase=PHASE_HEALTHY, timestamp=to_rfc3339(time.time())
                    ),
                )
            )

    def on_update(self, old: dict, new: dict):
        """Image/env diff -> start rolling-update analysis (with the
        rollback-loop guard)."""
        ns = new["metadata"].get("namespace", "default")
        if not self.is_monitored_namespace(ns):
            return
        name = new["metadata"]["name"]
        app = self._app_name(new)
        old_c, new_c = _containers(old), _containers(new)
        if len(old_c) != len(new_c):
            return
        changed = any(
            oc.get("image") != nc.get("image")
            or not _env_equal(oc.get("env", []), nc.get("env", []))
            for oc, nc in zip(old_c, new_c)
        )
        if not changed:
            return
        monitor = self.kube.get_monitor(ns, name)
        rollback_revision = _revision(old)
        if monitor is not None:
            new_rev = _revision(new)
            if new_rev > 0 and new_rev == monitor.spec.rollback_revision:
                return  # this update IS the rollback we asked for
        if old["metadata"].get("annotations", {}).get(ROLLBACK_ANNOTATION):
            return
        # MODE selects the default analysis strategy for a rollout
        # (DeploymentController.go:259-264): health-monitoring deploys get a
        # rollingUpdate analysis; an hpa_only operator dispatches an hpa
        # job instead. A canary-suffixed name overrides either.
        if name.endswith(CANARY_SUFFIX):
            strategy = STRATEGY_CANARY
        elif self.barrelman.monitors_health():
            strategy = STRATEGY_ROLLING_UPDATE
        else:
            strategy = STRATEGY_HPA
        self.barrelman.monitor_new_deployment(
            ns,
            name[: -len(CANARY_SUFFIX)] if strategy == STRATEGY_CANARY else app,
            new,
            strategy=strategy,
            rollback_revision=rollback_revision,
        )

    def on_delete(self, deployment: dict):
        ns = deployment["metadata"].get("namespace", "default")
        self.kube.delete_metadata(ns, self._app_name(deployment))


class MonitorController:
    def __init__(self, kube, barrelman: Barrelman):
        self.kube = kube
        self.barrelman = barrelman

    def on_update(self, old: DeploymentMonitor | None, new: DeploymentMonitor):
        # remediation on phase flip to Unhealthy (MonitorController.go:85-143)
        flipped = (
            new.status.phase == PHASE_UNHEALTHY
            and (old is None or old.status.phase != PHASE_UNHEALTHY)
        )
        if flipped and not new.status.remediation_taken:
            err = self.remediate(new)
            new.status.remediation_taken = True
            self.kube.upsert_monitor(new)
            if err:
                self.kube.record_event(
                    "DeploymentMonitor", new.namespace, new.name,
                    "RemediationFailed", err,
                )
        # re-arm perpetual monitors on spec change (:104-113, 146-155);
        # MODE gating happens inside monitor_continuously/monitor_hpa
        # (MonitorController.go:101-105 semantics, centralized)
        if old is not None:
            if new.spec.continuous and not old.spec.continuous:
                self.barrelman.monitor_continuously(new)
            if new.spec.hpa_score_template and (
                new.spec.hpa_score_template != old.spec.hpa_score_template
            ):
                self.barrelman.monitor_hpa(new)

    def remediate(self, monitor: DeploymentMonitor) -> str:
        option = monitor.spec.remediation.option
        if option == REMEDIATION_AUTO_ROLLBACK:
            return self.rollback(monitor)
        if option == REMEDIATION_AUTO_PAUSE:
            return self.pause(monitor)
        if option == REMEDIATION_AUTO:
            # the reference left this a stub (MonitorController.go:291-294);
            # the evident intent is policy-driven selection, so: roll back
            # when a known-good revision exists to return to, otherwise
            # pause the deployment (stops a bad rollout from progressing
            # while a human decides — the safe floor). A rollback that
            # ERRORS (target ReplicaSet pruned by revisionHistoryLimit,
            # deployment paused mid-flight, ...) falls back to pause too:
            # "Auto" promises SOME containment, never an error + a still-
            # progressing bad rollout. Both legs reuse the audited
            # single-action paths below.
            if monitor.spec.rollback_revision > 0:
                err = self.rollback(monitor)
                if not err:
                    return ""
                pause_err = self.pause(monitor)
                return err if pause_err else ""
            return self.pause(monitor)
        return ""

    def _deployment_name(self, monitor: DeploymentMonitor) -> str:
        return monitor.annotations.get("deployment.foremast.ai/name", monitor.name)

    def rollback(self, monitor: DeploymentMonitor) -> str:
        """Roll the deployment back to spec.rollback_revision by patching
        its pod template from the matching ReplicaSet — the modern
        replacement for the removed DeploymentRollback subresource."""
        if monitor.spec.rollback_revision == 0:
            return ""
        name = self._deployment_name(monitor)
        ns = monitor.namespace
        depl = self.kube.get_deployment(ns, name)
        if depl is None:
            return f"deployment {ns}/{name} not found"
        if _revision(depl) == monitor.spec.rollback_revision:
            return ""  # already there
        if depl.get("spec", {}).get("paused"):
            return (
                f"cannot rollback paused deployment {name}; resume it first "
                f"with 'kubectl rollout resume deployment/{name}'"
            )
        target_rs = None
        for rs in self.kube.list_replicasets(ns):
            owners = rs["metadata"].get("ownerReferences", [])
            if not any(o.get("name") == name and o.get("kind") == "Deployment" for o in owners):
                continue
            rev = int(
                rs["metadata"].get("annotations", {}).get(
                    "deployment.kubernetes.io/revision", 0
                ) or 0
            )
            if rev == monitor.spec.rollback_revision:
                target_rs = rs
                break
        if target_rs is None:
            return f"revision {monitor.spec.rollback_revision} not found for {name}"
        message = (
            "Foremast detected unhealthy, so rolled back automatically to "
            f"revision:{monitor.spec.rollback_revision}"
        )
        self.kube.patch_deployment(
            ns,
            name,
            {
                "metadata": {"annotations": {ROLLBACK_MESSAGE_ANNOTATION: message}},
                "spec": {"template": target_rs["spec"]["template"]},
            },
        )
        self.kube.record_event("Deployment", ns, name, "ForemastRollback", message)
        return ""

    def pause(self, monitor: DeploymentMonitor) -> str:
        name = self._deployment_name(monitor)
        ns = monitor.namespace
        if self.kube.get_deployment(ns, name) is None:
            return f"deployment {ns}/{name} not found"
        message = "Foremast detected unhealthy, so paused this deployment"
        self.kube.patch_deployment(
            ns,
            name,
            {
                "metadata": {"annotations": {ROLLBACK_MESSAGE_ANNOTATION: message}},
                "spec": {"paused": True},
            },
        )
        self.kube.record_event("Deployment", ns, name, "ForemastPaused", message)
        return ""


class HpaController:
    def __init__(self, kube, barrelman: Barrelman):
        self.kube = kube
        self.barrelman = barrelman
        self.alerts: list[str] = []  # rendered letters (log sink)

    def _monitor_for(self, hpa: dict) -> DeploymentMonitor | None:
        ns = hpa["metadata"].get("namespace", "default")
        target = hpa.get("spec", {}).get("scaleTargetRef", {}).get("name", "")
        return self.kube.get_monitor(ns, target) if target else None

    def on_upsert(self, old: dict | None, new: dict):
        """Stamp the score template + HpaScoreEnabled; alert on scaling.

        HPA_STRATEGY semantics (HpaController.go:210-218): `hpa_exists`
        and `anyway` both stamp the default template on the target's
        monitor; any OTHER strategy value actively CLEARS the template,
        disabling scoring for apps whose HPAs appear."""
        monitor = self._monitor_for(new)
        if self.barrelman.hpa_strategy in ("hpa_exists", "anyway"):
            if monitor is not None and not monitor.spec.hpa_score_template:
                monitor.spec.hpa_score_template = DEFAULT_HPA_TEMPLATE
                monitor.status.hpa_score_enabled = True
                self.kube.upsert_monitor(monitor)
                self.barrelman.monitor_hpa(monitor)
        elif monitor is not None and monitor.spec.hpa_score_template:
            monitor.spec.hpa_score_template = ""
            monitor.status.hpa_score_enabled = False  # both, like on_delete
            self.kube.upsert_monitor(monitor)
        if old is None:
            return
        old_desired = old.get("status", {}).get("desiredReplicas", 0)
        new_desired = new.get("status", {}).get("desiredReplicas", 0)
        if old_desired == new_desired:
            return
        metrics = new.get("spec", {}).get("metrics", [])
        if not any(
            m.get("type") == "Object"
            and m.get("object", {}).get("metric", {}).get("name") == HPA_SCORE_METRIC
            for m in metrics
        ):
            return
        # re-fetch, deliberately: the stamp branch above may have called
        # monitor_hpa(), which upserts a REBUILT monitor — the local object
        # would be stale for the hpa_logs the letter renders from
        monitor = self._monitor_for(new)
        if monitor is None:
            return
        scale_down = new_desired < old.get("status", {}).get("currentReplicas", old_desired)
        log_count = 6 if scale_down else 4  # HpaController.go:113-117
        logs = sorted(
            monitor.status.hpa_logs, key=lambda l: l.timestamp, reverse=True
        )[:log_count]
        details = "\n".join(
            f"{d.get('metricType', d.get('metricAlias', '?'))} at {l.timestamp} "
            f"value {d.get('current')} is out of normal range "
            f"({d.get('lower')}, {d.get('upper')})"
            for l in logs
            for d in l.details
        )
        letter = ALERT_LETTER.format(
            timestamp=to_rfc3339(time.time()),
            application=monitor.annotations.get(
                "deployment.foremast.ai/name", monitor.name
            ),
            namespace=monitor.namespace,
            action="down" if scale_down else "up",
            old=old.get("status", {}).get("currentReplicas", old_desired),
            new=new_desired,
            details=details,
        )
        self.alerts.append(letter)
        self.kube.record_event(
            "HorizontalPodAutoscaler",
            new["metadata"].get("namespace", "default"),
            new["metadata"]["name"],
            "ForemastScaling",
            letter.strip(),
        )

    def on_delete(self, hpa: dict):
        monitor = self._monitor_for(hpa)
        if monitor is not None:
            monitor.spec.hpa_score_template = ""
            monitor.status.hpa_score_enabled = False
            self.kube.upsert_monitor(monitor)
