"""`python -m foremast_tpu [serve|operator|watch|unwatch|status|demo]`."""
import sys

from .cli import main

sys.exit(main())
