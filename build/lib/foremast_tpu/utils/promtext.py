"""Prometheus/Wavefront text-exposition helpers shared by every renderer.

Label values reach these formats from user input (request paths, app
names); unescaped quotes/backslashes/newlines corrupt the whole scrape or
point batch, so every producer must go through escape_label_value().
"""
from __future__ import annotations

import re

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def escape_label_value(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def sanitize_metric_name(name: str) -> str:
    """Replace anything outside the Prometheus name charset with '_'."""
    return _NAME_BAD.sub("_", name)
