"""Deterministic job ids.

Mirrors the reference service's id scheme (behavior, not code):
  * normal jobs — an HMAC-SHA256 digest over the canonicalized request, so
    identical requests dedupe to the same job
    (foremast-service/pkg/common/stringutils.go:11-17).
  * HPA jobs — the stable composite "app:namespace:hpa" so each app has
    exactly one continuously-rearmed HPA job
    (foremast-service/pkg/search/elasticsearchstore.go:31-33).
"""
from __future__ import annotations

import hashlib
import hmac
import json

_KEY = b"foremast-tpu"


def hmac_job_id(payload: dict) -> str:
    canon = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return hmac.new(_KEY, canon.encode(), hashlib.sha256).hexdigest()


def hpa_job_id(app_name: str, namespace: str) -> str:
    return f"{app_name}:{namespace}:hpa"
