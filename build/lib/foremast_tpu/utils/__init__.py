"""Small shared helpers: deterministic ids, time parsing."""
from .ids import hmac_job_id, hpa_job_id  # noqa: F401
from .timeutils import from_rfc3339, to_rfc3339  # noqa: F401
