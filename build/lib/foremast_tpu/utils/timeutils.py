"""RFC3339 <-> unix seconds (wire format of the healthcheck API)."""
from __future__ import annotations

from datetime import datetime, timezone


def from_rfc3339(s: str) -> float:
    if s.endswith("Z"):
        s = s[:-1] + "+00:00"
    return datetime.fromisoformat(s).timestamp()


def to_rfc3339(t: float) -> str:
    return (
        datetime.fromtimestamp(t, tz=timezone.utc)
        .isoformat(timespec="seconds")
        .replace("+00:00", "Z")
    )
