"""Masked, tie-averaged ranking — the core primitive of the rank-test family.

TPU constraints drive the design (see /opt/skills/guides/pallas_guide.md and
SURVEY.md §7 "Hard parts"): no data-dependent shapes, so missing samples are
handled by masks, never by filtering. Masked slots sort to the end (+inf key)
and receive rank 0; valid slots receive scipy.rankdata-compatible average
ranks. Tie correction terms (sum of t^3 - t over tie groups) are computed with
segment sums over sorted tie-group ids, which XLA lowers to scatter-adds.

All functions operate on one 1-D series and are vmapped by callers; everything
is O(T log T) via a single sort.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["masked_rankdata", "rank_and_ties"]


@jax.jit
def rank_and_ties(values: jnp.ndarray, mask: jnp.ndarray):
    """Rank `values` where `mask` is True, averaging ties.

    Args:
      values: (T,) float array. Entries where mask is False are ignored.
      mask:   (T,) bool array.

    Returns:
      ranks:    (T,) float32 — 1-based average ranks among valid entries,
                0.0 for masked entries. Matches scipy.stats.rankdata on the
                valid subset.
      tie_term: scalar — sum over tie groups (valid entries only) of t^3 - t,
                the correction term used by Mann-Whitney / Kruskal / Wilcoxon.
      n_valid:  scalar float — number of valid entries.
    """
    T = values.shape[-1]
    dtype = jnp.float32
    vals = jnp.where(mask, values.astype(dtype), jnp.inf)
    # Stable sort: masked (+inf) entries land at the end.
    order = jnp.argsort(vals, stable=True)
    sorted_vals = vals[order]
    sorted_valid = mask[order]

    pos = jnp.arange(1, T + 1, dtype=dtype)
    new_group = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sorted_vals[1:] != sorted_vals[:-1]]
    )
    gid = jnp.cumsum(new_group) - 1  # 0-based tie-group ids, ascending

    first = jax.ops.segment_min(pos, gid, num_segments=T)
    last = jax.ops.segment_max(pos, gid, num_segments=T)
    avg = (first + last) * 0.5
    ranks_sorted = avg[gid]

    ranks = jnp.zeros(T, dtype=dtype).at[order].set(ranks_sorted)
    ranks = jnp.where(mask, ranks, 0.0)

    counts = jax.ops.segment_sum(sorted_valid.astype(dtype), gid, num_segments=T)
    tie_term = jnp.sum(counts**3 - counts)
    n_valid = jnp.sum(mask.astype(dtype))
    return ranks, tie_term, n_valid


def masked_rankdata(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """scipy.stats.rankdata over the masked subset; 0 at masked positions."""
    ranks, _, _ = rank_and_ties(values, mask)
    return ranks
