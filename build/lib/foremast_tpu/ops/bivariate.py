"""Bivariate-normal joint anomaly scorer (the two-metric judgment mode).

The reference brain's model menu assigns "Bivariate Normal Distribution" to
jobs monitoring exactly two correlated metrics (docs/guides/design.md:53-88
— one metric: univariate forecasters; two: bivariate normal; 3+: LSTM).
No reference source exists (the brain repo is absent); the spec is the menu
entry itself: fit a 2-D Gaussian to the joint historical distribution of the
metric pair and flag current points that fall outside the k-sigma ellipse.

TPU design: everything is closed-form — masked means, a 2x2 covariance with
a ridge floor, an analytic 2x2 inverse, and a Mahalanobis distance per time
step — batched over (B, T) with no iterative fitting at all. One jitted
program scores every two-metric job in the fleet batch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["bivariate_normal_anomalies"]

_F = jnp.float32


@jax.jit
def bivariate_normal_anomalies(x1, m1, x2, m2, region, threshold,
                               min_lower_bound1=None, min_lower_bound2=None,
                               bound_mode1=None, bound_mode2=None):
    """Joint k-sigma-ellipse anomaly flags for a metric pair.

    Args:
      x1, x2:    (B, T) the two metrics on a shared time grid.
      m1, m2:    (B, T) bool validity masks.
      region:    (B, T) bool — the current window being judged; the joint
                 Gaussian is fit on ``~region`` (history).
      threshold: (B,) Mahalanobis radius in sigmas (per-metric ML_THRESHOLD;
                 the pair uses the min — stricter — of its two policies).
      min_lower_bound1/2: (B,) optional floors for the exported marginal
                 lower bands (mirrors the univariate min_lower_bound{N}).
      bound_mode1/2: (B,) optional int32 ML_BOUND bitmasks per metric
                 (forecast.BOUND_*: bit0 upper, bit1 lower; 0 = both). The
                 ellipse itself is two-sided; a flagged point is kept only
                 when at least one metric's excursion direction is enabled
                 by that metric's bound mask — an upper-only error metric
                 must not alarm the pair on "too healthy" dips.

    Returns dict:
      flags (B, T) joint anomalies, d2 (B, T) squared Mahalanobis distance,
      count/first_index/checked (B,), and marginal upper/lower bands
      (B, T) per metric (mu_i +- threshold * sigma_i, constant over time)
      for the foremastbrain:*_{upper,lower} export.
    """
    B, T = x1.shape
    joint = m1 & m2
    hist = joint & ~region
    w = hist.astype(_F)
    n = jnp.sum(w, axis=-1)
    denom = jnp.maximum(n, 1.0)

    mu1 = jnp.sum(x1 * w, axis=-1) / denom
    mu2 = jnp.sum(x2 * w, axis=-1) / denom
    d1 = (x1 - mu1[:, None]) * w
    d2_ = (x2 - mu2[:, None]) * w
    # covariance with a ridge floor: keeps the ellipse defined for (nearly)
    # constant or perfectly-correlated history instead of exploding Sigma^-1
    var1 = jnp.sum(d1 * d1, axis=-1) / denom
    var2 = jnp.sum(d2_ * d2_, axis=-1) / denom
    cov = jnp.sum(d1 * d2_, axis=-1) / denom
    ridge = 1e-6 * jnp.maximum(jnp.maximum(var1, var2), 1.0)
    var1 = var1 + ridge
    var2 = var2 + ridge
    det = jnp.maximum(var1 * var2 - cov * cov, 1e-12)

    # analytic 2x2 inverse; d^2(t) = [a b] Sigma^-1 [a b]^T
    a = x1 - mu1[:, None]
    b = x2 - mu2[:, None]
    d2 = (var2[:, None] * a * a - 2.0 * cov[:, None] * a * b
          + var1[:, None] * b * b) / det[:, None]

    # fail-open like residual_sigma: <2 history points => nothing judgeable
    enough = (n >= 2.0)[:, None]
    flags = (d2 > (threshold[:, None] ** 2)) & joint & region & enough
    if bound_mode1 is not None or bound_mode2 is not None:
        def directional(dev, mode):
            if mode is None:
                return jnp.ones_like(dev, bool)
            md = jnp.where(mode == 0, 3, mode)[:, None]
            return ((dev > 0) & ((md & 1) > 0)) | ((dev < 0) & ((md & 2) > 0))
        flags = flags & (directional(a, bound_mode1) | directional(b, bound_mode2))
    counts = jnp.sum(flags, axis=-1)
    first = jnp.where(counts > 0, jnp.argmax(flags, axis=-1),
                      jnp.full((B,), -1))
    checked = jnp.sum((joint & region).astype(jnp.int32), axis=-1)

    s1 = jnp.sqrt(var1)[:, None]
    s2 = jnp.sqrt(var2)[:, None]
    thr = threshold[:, None]
    lo1 = mu1[:, None] - thr * s1
    lo2 = mu2[:, None] - thr * s2
    if min_lower_bound1 is not None:
        lo1 = jnp.maximum(lo1, min_lower_bound1[:, None])
    if min_lower_bound2 is not None:
        lo2 = jnp.maximum(lo2, min_lower_bound2[:, None])
    full = x1.shape
    return {
        "flags": flags,
        "d2": d2,
        "count": counts,
        "first_index": first,
        "checked": checked,
        "upper1": jnp.broadcast_to(mu1[:, None] + thr * s1, full),
        "lower1": jnp.broadcast_to(lo1, full),
        "upper2": jnp.broadcast_to(mu2[:, None] + thr * s2, full),
        "lower2": jnp.broadcast_to(lo2, full),
    }
