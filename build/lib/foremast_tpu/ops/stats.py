"""Distribution tail functions needed by the pairwise test kernels.

Everything here is elementwise, jit-safe, and batched for free. These are the
TPU-side replacements for the scipy distribution calls the reference brain's
pairwise comparators rely on (spec: SURVEY.md §2.4; foremast-brain/README.md
lists Mann-Whitney / Wilcoxon / Kruskal / Friedman as the pairwise family).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import erfc, gammaincc

_SQRT2 = 1.4142135623730951


def norm_sf(z: jnp.ndarray) -> jnp.ndarray:
    """Standard normal survival function P(Z > z)."""
    return 0.5 * erfc(z / _SQRT2)


def chi2_sf(x: jnp.ndarray, df: jnp.ndarray) -> jnp.ndarray:
    """Chi-squared survival function P(X > x) with df degrees of freedom.

    chi2.sf(x, k) == gammaincc(k/2, x/2) (regularized upper incomplete gamma).
    """
    x = jnp.maximum(x, 0.0)
    return gammaincc(df / 2.0, x / 2.0)


def kolmogorov_sf(x: jnp.ndarray, terms: int = 64) -> jnp.ndarray:
    """Survival function of the Kolmogorov distribution.

    sf(x) = 2 * sum_{k>=1} (-1)^{k-1} exp(-2 k^2 x^2), the asymptotic null
    distribution of the two-sample KS statistic (scaled). The truncated series
    only converges for x large enough that the `terms`-th term has decayed;
    below that cutoff sf(x) is 1 to far beyond float32 precision
    (sf(0.2) > 1 - 1e-6), so we return 1 exactly there instead of an
    arbitrarily wrong partial sum.
    """
    x = jnp.asarray(x)
    k = jnp.arange(1, terms + 1, dtype=x.dtype)
    signs = jnp.where(k % 2 == 1, 1.0, -1.0).astype(x.dtype)
    xc = jnp.maximum(x, 0.2)  # below cutoff the series result is discarded
    # shape (..., terms)
    expo = jnp.exp(-2.0 * (k**2) * (xc[..., None] ** 2))
    s = 2.0 * jnp.sum(signs * expo, axis=-1)
    s = jnp.where(x < 0.2, 1.0, s)
    return jnp.clip(s, 0.0, 1.0)
