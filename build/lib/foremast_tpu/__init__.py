"""foremast_tpu — TPU-native application-health / canary-analysis framework.

A ground-up re-design of the capabilities of classicvalues/foremast
(K8s app health manager: canary analysis, anomaly detection, remediation,
HPA scoring) with the entire anomaly engine built as jit-compiled JAX/XLA
kernels vmapped over a (service x metric x window) batch axis and sharded
across TPU chips via shard_map, instead of the reference's per-request CPU
Python worker (reference: foremast-brain, spec at SURVEY.md §2.4).

Layout:
  ops/       pure-JAX numerics: masked rank stats, pairwise tests, forecasters
  models/    flax models (LSTM autoencoder multivariate scorer)
  parallel/  mesh construction, shard_map fleet scoring, ICI reductions
  engine/    job state machine, micro-batching scheduler, analyzer
  dataplane/ Prometheus/Wavefront query builders + fetchers, metric exporter
  service/   HTTP job API (contract of foremast-service /v1/healthcheck/*)
  operator/  K8s control plane (contract of foremast-barrelman)
  utils/     ids, time helpers
"""

__version__ = "0.1.0"
