"""Dashboard assets: the L7 UI served by the foremast-tpu service.

The reference shipped a React build behind nginx with an /api proxy to
foremast-service (foremast-dashboard/nginx.conf, deploy/foremast/3_brain/
foremast-browser.yaml:22-33). Here the service serves one dependency-free
static page and already owns the /api/v1 query proxy, so the whole L7 layer
is a file.
"""
from __future__ import annotations

import os

_STATIC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "static")


def index_html() -> str:
    with open(os.path.join(_STATIC, "index.html"), encoding="utf-8") as f:
        return f.read()
