"""Operator resource types: DeploymentMetadata + DeploymentMonitor.

Re-derives the two CRDs of the reference operator
(foremast-barrelman/pkg/apis/deployment/v1alpha1/types.go) as plain
dataclasses with dict (JSON) codecs — the shapes the real K8s CRDs
(deploy/crds/*.yaml here) serialize to:

  * DeploymentMetadata (types.go:14-41): per-app config — analyst endpoint,
    metric source + monitored metric list, HPA score templates.
  * DeploymentMonitor (types.go:200-246 spec, :249-269 status): per-app job
    state — selector, watch window, continuous flag, remediation policy,
    rollback revision, hpaScoreTemplate; status carries jobId, phase,
    anomaly, hpa logs.
  * phases (types.go:300-314), remediation options (types.go:317-328),
    Anomaly (types.go:339-354).
"""
from __future__ import annotations

from dataclasses import asdict, dataclass, field

# --- monitor phases (types.go:300-314) ---
PHASE_HEALTHY = "Healthy"
PHASE_RUNNING = "Running"
PHASE_FAILED = "Failed"
PHASE_UNHEALTHY = "Unhealthy"
PHASE_WARNING = "Warning"
PHASE_EXPIRED = "Expired"
PHASE_ABORT = "Abort"

# --- remediation options (types.go:317-328) ---
REMEDIATION_NONE = "None"
REMEDIATION_AUTO_ROLLBACK = "AutoRollback"
REMEDIATION_AUTO_PAUSE = "AutoPause"
REMEDIATION_AUTO = "Auto"

# --- strategies (metricsquery.go:14-20) ---
STRATEGY_ROLLING_UPDATE = "rollingUpdate"
STRATEGY_CANARY = "canary"
STRATEGY_CONTINUOUS = "continuous"
STRATEGY_HPA = "hpa"


@dataclass
class Analyst:
    endpoint: str = ""
    version: str = "0.0.1"


@dataclass
class Monitoring:
    metric_name: str = ""
    metric_type: str = "counter"
    metric_alias: str = ""


@dataclass
class Metrics:
    data_source_type: str = "prometheus"
    endpoint: str = ""
    monitoring: list = field(default_factory=list)  # [Monitoring]


@dataclass
class HpaScoreTemplate:
    """Named alias list, e.g. cpu_bound -> [cpu, tps, latency]
    (types.go:63-67; default template name at Barrelman.go:37)."""

    name: str = ""
    metrics: list = field(default_factory=list)  # alias names, priority = index


DEFAULT_HPA_TEMPLATE = "cpu_bound"


@dataclass
class DeploymentMetadata:
    name: str = ""
    namespace: str = ""
    analyst: Analyst = field(default_factory=Analyst)
    metrics: Metrics = field(default_factory=Metrics)
    hpa_score_templates: list = field(default_factory=list)  # [HpaScoreTemplate]

    def template_named(self, name: str) -> HpaScoreTemplate | None:
        for t in self.hpa_score_templates:
            if t.name == name:
                return t
        return None


@dataclass
class RemediationAction:
    option: str = REMEDIATION_NONE
    parameters: dict = field(default_factory=dict)


@dataclass
class AnomalousMetricValue:
    time: int = 0
    value: float = 0.0


@dataclass
class AnomalousMetric:
    name: str = ""
    tags: str = ""
    values: list = field(default_factory=list)  # [AnomalousMetricValue]


@dataclass
class Anomaly:
    anomalous_metrics: list = field(default_factory=list)  # [AnomalousMetric]

    @classmethod
    def from_flat(cls, flat: dict) -> "Anomaly":
        """{metric: [ts, v, ts, v, ...]} -> structured pairs (the wire shape
        the engine emits; DeploymentController.go:431-458 did this in Go)."""
        ms = []
        for name, pairs in (flat or {}).items():
            vals = [
                AnomalousMetricValue(time=int(pairs[i]), value=float(pairs[i + 1]))
                for i in range(0, len(pairs) - 1, 2)
            ]
            ms.append(AnomalousMetric(name=name, values=vals))
        return cls(anomalous_metrics=ms)


@dataclass
class HpaLogEntry:
    timestamp: str = ""
    hpascore: float = 0.0
    reason: str = ""
    details: list = field(default_factory=list)  # [{metricType,current,upper,lower}]


@dataclass
class MonitorSpec:
    selector: dict = field(default_factory=dict)  # label query
    analyst: Analyst = field(default_factory=Analyst)
    start_time: str = ""
    wait_until: str = ""
    metrics: Metrics = field(default_factory=Metrics)
    continuous: bool = False
    remediation: RemediationAction = field(default_factory=RemediationAction)
    rollback_revision: int = 0
    hpa_score_template: str = ""


@dataclass
class MonitorStatus:
    observed_generation: int = 0
    job_id: str = ""
    phase: str = PHASE_HEALTHY
    remediation_taken: bool = False
    anomaly: Anomaly = field(default_factory=Anomaly)
    timestamp: str = ""
    expired: bool = False
    hpa_score_enabled: bool = False
    hpa_logs: list = field(default_factory=list)  # [HpaLogEntry]


@dataclass
class DeploymentMonitor:
    name: str = ""
    namespace: str = ""
    annotations: dict = field(default_factory=dict)
    spec: MonitorSpec = field(default_factory=MonitorSpec)
    status: MonitorStatus = field(default_factory=MonitorStatus)

    def to_json(self) -> dict:
        return asdict(self)
