"""Flax models: multivariate anomaly scorers."""
from .lstm_ae import (  # noqa: F401
    LstmAutoencoder,
    anomaly_scores,
    fit_score_normalizer,
    init_state,
    reconstruction_errors,
    train,
    train_step,
)
