"""HTTP job API (foremast-service contract)."""
from .api import ApiError, ForemastService, build_document, make_server, serve_background  # noqa: F401
