"""ASGI middleware: the async twin of wsgi.MetricsMiddleware.

The reference ships four starter variants so every framework generation
in its ecosystem can emit the same `http_server_requests` series
(SURVEY.md §2.5: Boot 2.x / 1.x / 1.5.x / plain Spring 4.x). The Python
ecosystem's second dialect is ASGI (FastAPI/Starlette/uvicorn apps); this
middleware mirrors the WSGI semantics exactly — same timer name, the same
{method, status, uri, exception, caller} tags, pre-registered error
statuses, scrape endpoint, and runtime toggle paths — so an async service
plugs into the same recording rules and analysis pipeline. The shared
behavior lives in base.MetricsMiddlewareBase.
"""
from __future__ import annotations

import time

from .base import HTTP_SERVER_REQUESTS, MetricsMiddlewareBase

__all__ = ["AsgiMetricsMiddleware"]


class AsgiMetricsMiddleware(MetricsMiddlewareBase):
    async def __call__(self, scope, receive, send):
        if scope.get("type") != "http":
            await self.app(scope, receive, send)
            return
        path = scope.get("path", "/")
        if path == self.scrape_path:
            await self._respond(send, 200, self.registry.render().encode(),
                                b"text/plain; version=0.0.4")
            return
        if path.startswith(self.toggle_prefix + "/"):
            status, msg = self._toggle_action(path)
            await self._respond(send, status, msg.encode(), b"text/plain")
            return

        t0 = time.perf_counter()
        holder = {"status": "200", "exc": "None"}

        async def capturing_send(message):
            if message.get("type") == "http.response.start":
                holder["status"] = str(message.get("status", 200))
            await send(message)

        try:
            await self.app(scope, receive, capturing_send)
        except Exception as e:
            holder["status"] = "500"
            holder["exc"] = type(e).__name__
            self._record(scope, holder, t0)
            raise
        self._record(scope, holder, t0)

    def _caller(self, scope) -> str:
        for k, v in scope.get("headers", []):
            if k.lower() == b"x-caller":
                return v.decode("latin-1")
        return "unknown"

    def _record(self, scope, holder, t0):
        tags = {
            "exception": holder["exc"],
            "method": scope.get("method", "GET"),
            "status": holder["status"],
            "uri": self._uri_tag(scope.get("path", "/")),
        }
        if self.caller_enabled:
            tags["caller"] = self._caller(scope)
        self.registry.timer(HTTP_SERVER_REQUESTS, tags, time.perf_counter() - t0)

    @staticmethod
    async def _respond(send, status: int, body: bytes, content_type: bytes):
        await send({"type": "http.response.start", "status": status,
                    "headers": [(b"content-type", content_type),
                                (b"content-length", str(len(body)).encode())]})
        await send({"type": "http.response.body", "body": body})
