"""WSGI middleware: http_server_requests timing + scrape + toggle endpoints.

The Python-side equivalent of the reference starters' servlet filter +
actuator endpoints (SURVEY.md §2.5):

  * every request lands in the `http_server_requests` timer tagged
    {method, status, uri, exception, caller} — caller from the X-CALLER
    header (K8sMetricsProperties.APP_ASSET_ALIAS_HEADER).
  * common tag `app` resolved from APP_NAME env (commonTagNameValuePairs
    default "app:ENV.APP_NAME|info.app.name").
  * error statuses 403,404,501,502 pre-registered at zero so the error
    series exist before the first error (initializeForStatuses default).
  * GET /actuator/prometheus — scrape endpoint.
  * POST|GET /k8s-metrics/enable/<metric> and /disable/<metric> — the
    runtime toggle actuator (K8sMetricsEndpoint.java:10-35).

Registration, uri-tag bounding, and toggle parsing live in
base.MetricsMiddlewareBase, shared with the ASGI twin.
"""
from __future__ import annotations

import time

from .base import DEFAULT_INIT_STATUSES, HTTP_SERVER_REQUESTS, MetricsMiddlewareBase

__all__ = ["MetricsMiddleware", "HTTP_SERVER_REQUESTS", "CALLER_HEADER",
           "DEFAULT_INIT_STATUSES"]

CALLER_HEADER = "HTTP_X_CALLER"


class MetricsMiddleware(MetricsMiddlewareBase):
    def __call__(self, environ, start_response):
        path = environ.get("PATH_INFO", "/")
        if path == self.scrape_path:
            body = self.registry.render().encode()
            start_response(
                "200 OK",
                [("Content-Type", "text/plain; version=0.0.4"),
                 ("Content-Length", str(len(body)))],
            )
            return [body]
        if path.startswith(self.toggle_prefix + "/"):
            status, msg = self._toggle_action(path)
            body = msg.encode()
            start_response(
                "200 OK" if status == 200 else "404 Not Found",
                [("Content-Length", str(len(body)))],
            )
            return [body]

        t0 = time.perf_counter()
        status_holder = {"status": "200", "exc": "None"}

        def capturing_start_response(status, headers, exc_info=None):
            status_holder["status"] = status.split(" ", 1)[0]
            return start_response(status, headers, exc_info)

        try:
            result = self.app(environ, capturing_start_response)
        except Exception as e:
            status_holder["status"] = "500"
            status_holder["exc"] = type(e).__name__
            self._record(environ, status_holder, t0)
            raise
        self._record(environ, status_holder, t0)
        return result

    def _record(self, environ, holder, t0):
        tags = {
            "exception": holder["exc"],
            "method": environ.get("REQUEST_METHOD", "GET"),
            "status": holder["status"],
            "uri": self._uri_tag(environ.get("PATH_INFO", "/")),
        }
        if self.caller_enabled:
            tags["caller"] = environ.get(CALLER_HEADER, "unknown")
        self.registry.timer(HTTP_SERVER_REQUESTS, tags, time.perf_counter() - t0)
