"""App instrumentation: the foremast-metrics equivalent for Python services.

The reference ships Java/Spring micrometer starters that make user apps
emit the Prometheus series the analysis pipeline consumes (SURVEY.md §2.5).
This package is the same contract for Python apps: a metrics registry with
common tags, the CommonMetricsFilter whitelist/blacklist/prefix/tag-rule
semantics with runtime enable/disable, and a WSGI middleware exporting
/actuator/prometheus.
"""
from .asgi import AsgiMetricsMiddleware
from .registry import CommonMetricsFilter, MetricsRegistry
from .wsgi import MetricsMiddleware

__all__ = ["MetricsRegistry", "CommonMetricsFilter", "MetricsMiddleware",
           "AsgiMetricsMiddleware"]
