"""Metrics registry + common-metrics filter.

Filter decision order re-derived from the reference's CommonMetricsFilter
(foremast-metrics/foremast-spring-boot-k8s-metrics-starter/src/main/java/ai/
foremast/metrics/k8s/starter/CommonMetricsFilter.java:38-150):

  1. filter disabled -> accept everything.
  2. explicit per-metric enable/disable map wins (NEUTRAL/DENY).
  3. whitelist -> NEUTRAL (kept); blacklist -> DENY.
  4. any configured prefix match -> ACCEPT.
  5. any tag rule `tag:value` matching the metric's tags -> ACCEPT.
  6. otherwise DENY (when the filter is enabled, default is closed).

Metric names normalize '_' -> '.' for list membership (filter() in the
reference, :133-135); runtime enable/disable move names between the lists
(:137-150, exposed by K8sMetricsEndpoint.java:10-35).
"""
from __future__ import annotations

import threading
import time

from ..utils.promtext import escape_label_value


class CommonMetricsFilter:
    def __init__(self, enabled: bool = False, whitelist: str = "",
                 blacklist: str = "", prefixes: str = "", tag_rules: str = ""):
        self.enabled = enabled
        self.whitelist = {self._norm(s) for s in self._split(whitelist)}
        self.blacklist = {self._norm(s) for s in self._split(blacklist)}
        self.prefixes = self._split(prefixes)
        self.tag_rules = {}
        for pair in self._split(tag_rules):
            name, _, value = pair.partition(":")
            if not value:
                raise ValueError(f"invalid tag rule {pair!r}")
            self.tag_rules[name.strip()] = value.strip()
        self.overrides: dict[str, bool] = {}  # explicit enable/disable

    @staticmethod
    def _split(s: str) -> list[str]:
        return [x.strip() for x in (s or "").split(",") if x.strip()]

    @staticmethod
    def _norm(name: str) -> str:
        return name.replace("_", ".")

    def accepts(self, name: str, tags: dict | None = None) -> bool:
        if not self.enabled:
            return True
        norm = self._norm(name)
        if norm in self.overrides:
            return self.overrides[norm]
        if norm in self.whitelist:
            return True
        if norm in self.blacklist:
            return False
        if any(name.startswith(p) or norm.startswith(p) for p in self.prefixes):
            return True
        for key, expected in self.tag_rules.items():
            if (tags or {}).get(key) == expected:
                return True
        return False

    def enable_metric(self, name: str):
        norm = self._norm(name)
        self.blacklist.discard(norm)
        self.whitelist.add(norm)
        self.overrides[norm] = True

    def disable_metric(self, name: str):
        norm = self._norm(name)
        self.whitelist.discard(norm)
        self.blacklist.add(norm)
        self.overrides[norm] = False


class MetricsRegistry:
    """Counters + timers with tags, rendered in Prometheus text format.

    Timers emit `<name>_seconds_count|_sum|_max` (micrometer's Prometheus
    mapping); counters emit `<name>_total`.
    """

    def __init__(self, common_tags: dict | None = None,
                 metrics_filter: CommonMetricsFilter | None = None):
        self.common_tags = dict(common_tags or {})
        self.filter = metrics_filter or CommonMetricsFilter()
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._timers: dict[tuple, list] = {}  # key -> [count, sum, max]

    def _key(self, name: str, tags: dict):
        merged = {**self.common_tags, **tags}
        return name, tuple(sorted(merged.items()))

    def counter(self, name: str, tags: dict | None = None, amount: float = 1.0):
        tags = tags or {}
        if not self.filter.accepts(name, {**self.common_tags, **tags}):
            return
        key = self._key(name, tags)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + amount

    def timer(self, name: str, tags: dict | None = None, seconds: float | None = None):
        """Record a timing; seconds=None just pre-registers the series at 0
        (the starter pre-registers error statuses so series exist from
        boot, K8sMetricsAutoConfiguration.java:179-190)."""
        tags = tags or {}
        if not self.filter.accepts(name, {**self.common_tags, **tags}):
            return
        key = self._key(name, tags)
        with self._lock:
            entry = self._timers.setdefault(key, [0, 0.0, 0.0])
            if seconds is not None:
                entry[0] += 1
                entry[1] += seconds
                entry[2] = max(entry[2], seconds)

    def time(self, name: str, tags: dict | None = None):
        """Context manager: `with registry.time("http_server_requests", t):`"""
        registry = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.timer(name, tags, time.perf_counter() - self.t0)
                return False

        return _Timer()

    # -- rendering --
    @staticmethod
    def _fmt_tags(tags: tuple) -> str:
        if not tags:
            return ""
        # tag values carry user input (request paths, app names): escape or
        # one stray quote corrupts the whole scrape
        inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in tags)
        return "{" + inner + "}"

    def render(self) -> str:
        lines = []
        with self._lock:
            counters = dict(self._counters)
            timers = {k: list(v) for k, v in self._timers.items()}
        for (name, tags), value in sorted(counters.items()):
            pname = name.replace(".", "_")
            lines.append(f"{pname}_total{self._fmt_tags(tags)} {value}")
        for (name, tags), (count, total, mx) in sorted(timers.items()):
            pname = name.replace(".", "_")
            t = self._fmt_tags(tags)
            lines.append(f"{pname}_seconds_count{t} {count}")
            lines.append(f"{pname}_seconds_sum{t} {total}")
            lines.append(f"{pname}_seconds_max{t} {mx}")
        return "\n".join(lines) + "\n"
