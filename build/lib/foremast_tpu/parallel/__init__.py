"""Mesh + fleet-scale SPMD scoring."""
from .mesh import fleet_mesh, fleet_sharding, pad_to_multiple, replicated  # noqa: F401
from .fleet import (  # noqa: F401
    COMBINE_ALL,
    COMBINE_ANY,
    fleet_summary,
    make_fleet_scorer,
    score_pairs,
)
