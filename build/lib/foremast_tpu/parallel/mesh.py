"""Device mesh construction + sharding helpers.

The reference scales by running N shared-nothing brain workers against an
Elasticsearch queue (docs/guides/design.md:37-43). The TPU-native design
replaces that with SPMD: one jitted program, batch ("fleet") axis sharded
across every chip, XLA inserting ICI collectives for fleet-level reductions.
Multi-pod scale-out extends the same mesh over DCN via jax.distributed
(initialize() on each host) — the program does not change.

Axes:
  fleet — the (service x metric x window) batch axis; pure data parallelism,
          zero communication except final reductions.
  model — reserved for tensor-sharding the LSTM scorer's hidden dim when a
          single scorer outgrows one chip (kept size 1 in the common case).
"""
from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["fleet_mesh", "fleet_sharding", "replicated", "pad_to_multiple", "P"]

FLEET_AXIS = "fleet"
MODEL_AXIS = "model"


def fleet_mesh(devices: Sequence[jax.Device] | None = None, model_parallel: int = 1) -> Mesh:
    """(fleet, model) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if n % model_parallel != 0:
        raise ValueError(f"{n} devices not divisible by model_parallel={model_parallel}")
    arr = np.array(devices).reshape(n // model_parallel, model_parallel)
    return Mesh(arr, (FLEET_AXIS, MODEL_AXIS))


def fleet_sharding(mesh: Mesh) -> NamedSharding:
    """Batch-dim sharding: leading axis split across the fleet axis."""
    return NamedSharding(mesh, P(FLEET_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(arrs, multiple: int, batch_axis: int = 0):
    """Right-pad every array's batch axis to a multiple (shardability).

    Returns (padded_arrays, original_B). Pads with zeros — callers carry
    masks, so padded rows score as fully-masked no-ops.
    """
    B = arrs[0].shape[batch_axis]
    rem = B % multiple
    if rem == 0:
        return list(arrs), B
    pad = multiple - rem
    out = []
    for a in arrs:
        widths = [(0, 0)] * a.ndim
        widths[batch_axis] = (0, pad)
        out.append(np.pad(np.asarray(a), widths))
    return out, B
