"""Standalone (non-K8s) trigger: poll metrics sources, drive analyses.

The TPU-native foremast-trigger (SURVEY.md §2.3): reads a requests file of
service/metric/query tuples, keeps a rollover analysis job per service
against the job API, records anomalies to daily TSV reports with deep-link
dashboard URLs, and produces daily summary reports.
"""
from .trigger import (
    JobInfo,
    TriggerService,
    parse_requests_file,
    parse_requests_lines,
)

__all__ = [
    "TriggerService",
    "JobInfo",
    "parse_requests_file",
    "parse_requests_lines",
]
