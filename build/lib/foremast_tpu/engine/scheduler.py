"""Background engine workers: the brain's poll loop, in-process.

The reference runs N shared-nothing brain replicas polling ES
(docs/guides/design.md:37-43). Here workers are threads over the in-process
JobStore — the lease/takeover semantics in JobStore.claim_open_jobs keep the
shared-nothing recovery behavior (a worker dying mid-job surrenders it after
MAX_STUCK_IN_SECONDS), while scoring itself is batched per cycle so more
workers are only needed to overlap fetch I/O, never for compute.
"""
from __future__ import annotations

import logging
import threading
import time

from .analyzer import Analyzer

log = logging.getLogger("foremast_tpu.engine")


class EngineWorker:
    def __init__(self, analyzer: Analyzer, name: str = "worker-0",
                 poll_interval: float = 10.0):
        self.analyzer = analyzer
        self.name = name
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.cycles = 0
        self.last_error: str = ""

    def start(self):
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.is_set():
            try:
                self.analyzer.run_cycle(worker=self.name)
                self.cycles += 1
            except Exception as e:  # noqa: BLE001 - worker must survive
                self.last_error = f"{type(e).__name__}: {e}"
                log.exception("engine cycle failed")
            self._stop.wait(self.poll_interval)

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout)
