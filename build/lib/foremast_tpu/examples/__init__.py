"""Examples: the demo app (error/load generators) and scenario scripts."""
