"""Verdict exporter: the foremastbrain:* Prometheus series.

The reference brain exports its model bounds, anomaly markers and HPA score
back into Prometheus (series consumed by the dashboard at
foremast-dashboard/src/config/metrics.js:21-29, by the custom-metrics
adapter at deploy/custom-metrics/custom-metrics-config-map.yaml:27-37, and
scraped from :8000/metrics per foremast-brain.yaml:88,110-122):

    foremastbrain:<metric>_upper / _lower / _anomaly    {app, namespace}
    foremastbrain:namespace_app_per_pod:hpa_score       {app, namespace}

This registry renders the Prometheus text exposition format; the service
mounts it at /metrics. A Wavefront mirror (custom.iks.foremast.* per
foremast-trigger/pkg/foremasttrigger/trigger.go:166-168) can subscribe to
the same registry via `samples()`.
"""
from __future__ import annotations

import threading
import time

from ..utils.promtext import escape_label_value as _esc
from ..utils.promtext import sanitize_metric_name as _sanitize_name


class VerdictExporter:
    def __init__(self, stale_seconds: float = 3600.0):
        self._lock = threading.Lock()
        self._gauges: dict[tuple, tuple[float, float]] = {}  # key -> (value, at)
        self.stale_seconds = stale_seconds

    def _set(self, name: str, labels: dict, value: float):
        key = (name, tuple(sorted(labels.items())))
        with self._lock:
            self._gauges[key] = (float(value), time.time())

    def record_bounds(self, app: str, namespace: str, metric: str,
                      upper: float, lower: float, anomaly: float):
        labels = {"app": app, "namespace": namespace}
        metric = _sanitize_name(metric)
        self._set(f"foremastbrain:{metric}_upper", labels, upper)
        self._set(f"foremastbrain:{metric}_lower", labels, lower)
        self._set(f"foremastbrain:{metric}_anomaly", labels, anomaly)

    def record_hpa_score(self, app: str, namespace: str, score: float):
        self._set(
            "foremastbrain:namespace_app_per_pod:hpa_score",
            {"app": app, "namespace": namespace},
            score,
        )

    def samples(self):
        """[(name, labels-dict, value)] for alternate sinks (Wavefront)."""
        now = time.time()
        with self._lock:
            # evict, don't just filter: label sets come from user-submitted
            # jobs, so unexpired-but-unevicted keys are an unbounded leak
            dead = [k for k, (_, at) in self._gauges.items()
                    if now - at > self.stale_seconds]
            for k in dead:
                del self._gauges[k]
            return [
                (name, dict(labels), value)
                for (name, labels), (value, at) in self._gauges.items()
            ]

    def render(self) -> str:
        """Prometheus text exposition (0.0.4)."""
        lines = []
        for name, labels, value in sorted(
            self.samples(), key=lambda s: (s[0], sorted(s[1].items()))
        ):
            lab = ",".join(f'{k}="{_esc(v)}"' for k, v in sorted(labels.items()))
            # ':' is legal in prometheus metric names (recording-rule style)
            lines.append(f"{name}{{{lab}}} {value}")
        return "\n".join(lines) + "\n"
