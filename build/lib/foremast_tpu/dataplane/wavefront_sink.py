"""Wavefront mirror of the verdict series.

The reference brain writes its bounds/anomaly verdicts to Wavefront as
custom.iks.foremast.<metric>_{upper,lower,anomaly} alongside the
foremastbrain:* Prometheus series (foremast-trigger/pkg/foremasttrigger/
trigger.go:166-168, :292 — trigger dashboards and anomaly counts read
them). This sink subscribes to the same VerdictExporter registry and
forwards renamed samples in Wavefront line protocol
(`name value ts source=... key="val"`), via a pluggable sender (TCP proxy
socket in production, a list in tests).
"""
from __future__ import annotations

import socket
import time

from ..utils.promtext import escape_label_value, sanitize_metric_name

PREFIX = "custom.iks.foremast."


def _rename(name: str) -> str | None:
    """foremastbrain:<metric>_suffix -> custom.iks.foremast.<metric>_suffix;
    the hpa score keeps its recording-rule-ish name under the prefix."""
    if not name.startswith("foremastbrain:"):
        return None
    rest = name[len("foremastbrain:"):]
    rest = rest.replace(":", ".").lower()
    return PREFIX + rest


def mirror_name(metric: str, suffix: str) -> str:
    """The Wavefront series this sink will emit for a RAW metric name.

    Consumers (trigger dashboards/reports) must build names through this so
    they track the exporter's sanitization ('.'/'-' -> '_') and the sink's
    rename — two hand-rolled copies of the mangling already diverged once.
    """
    return _rename(f"foremastbrain:{sanitize_metric_name(metric)}_{suffix}")


class WavefrontSink:
    def __init__(self, exporter, sender=None, host: str = "", port: int = 2878,
                 source: str = "foremast-tpu"):
        self.exporter = exporter
        self.sender = sender  # callable(list[str]) — overrides the socket
        self.host = host
        self.port = port
        self.source = source

    def lines(self, now: float | None = None) -> list[str]:
        now = time.time() if now is None else now
        out = []
        for name, labels, value in self.exporter.samples():
            wf = _rename(name)
            if wf is None:
                continue
            tags = " ".join(
                f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
            )
            out.append(f"{wf} {value} {int(now)} source={self.source} {tags}".strip())
        return out

    def flush(self, now: float | None = None) -> int:
        lines = self.lines(now)
        if not lines:
            return 0
        if self.sender is not None:
            self.sender(lines)
        elif self.host:
            payload = ("\n".join(lines) + "\n").encode()
            with socket.create_connection((self.host, self.port), timeout=5) as s:
                s.sendall(payload)
        return len(lines)
