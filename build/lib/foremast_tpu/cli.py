"""`foremast-tpu` command line: serve | operator | watch | unwatch | status | demo.

One entrypoint covers the reference's process zoo and kubectl plugins:

  serve     the runtime (job API + TPU engine + exporter + dashboard) —
            replaces foremast-service + foremast-brain (+ES).
  operator  the reconcile loop against a real cluster — replaces
            foremast-barrelman (cmd/manager/main.go env surface: MODE,
            HPA_STRATEGY, NAMESPACE).
  watch / unwatch <app>   toggle spec.continuous on the app's
            DeploymentMonitor — the bin/kubectl-watch & kubectl-unwatch
            plugins (bin/kubectl-watch:3 in the reference patched the CRD
            with kubectl; here we speak to the API server directly).
  status <app>            print the monitor's phase / job / anomaly.
  demo      self-contained local loop: chaos app + fake metric source +
            engine, no cluster (examples/demo_app.py).

Kube access: in-cluster service account when present, else KUBE_API/
KUBE_TOKEN env (operator/kube.py:KubeClient).
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _kube():
    from .operator.kube import KubeClient

    return KubeClient()


def cmd_serve(args) -> int:
    from .runtime import main

    main()
    return 0


def cmd_operator(args) -> int:
    from .operator.analyst import HttpAnalyst
    from .operator.loop import OperatorLoop

    endpoint = args.analyst or os.environ.get(
        "ANALYST_ENDPOINT", "http://localhost:8099/v1/healthcheck/"
    )
    watch = [n.strip() for n in os.environ.get("WATCH_NAMESPACES", "").split(",")
             if n.strip()]
    loop = OperatorLoop(
        _kube(),
        HttpAnalyst(endpoint),
        mode=os.environ.get("MODE", "hpa_and_healthy_monitoring"),
        hpa_strategy=os.environ.get("HPA_STRATEGY", "hpa_exists"),
        watch_namespaces=watch or None,
    )
    # NAMESPACE keeps the reference's meaning (Barrelman.go:402): where the
    # deployment-metadata-default fallback record lives
    ns = os.environ.get("OPERATOR_NAMESPACE") or os.environ.get("NAMESPACE", "")
    if ns:
        loop.barrelman.operator_namespace = ns
    tick = float(os.environ.get("TICK_SECONDS", "10"))
    print(f"[foremast-tpu] operator: analyst={endpoint} tick={tick}s", flush=True)
    loop.run_forever(interval=tick)
    return 0


def _toggle_continuous(args, value: bool) -> int:
    from .operator.kube import KubeError

    kube = _kube()
    if kube.get_monitor(args.namespace, args.app) is None:
        print(f"no DeploymentMonitor {args.namespace}/{args.app}", file=sys.stderr)
        return 1
    try:
        # spec-only merge patch: must NOT round-trip a stale status copy
        kube.patch_monitor(args.namespace, args.app,
                           {"spec": {"continuous": value}})
    except KubeError as e:
        print(f"patch failed: {e}", file=sys.stderr)
        return 1
    print(f"{args.namespace}/{args.app}: continuous={value}")
    return 0


def cmd_watch(args) -> int:
    return _toggle_continuous(args, True)


def cmd_unwatch(args) -> int:
    return _toggle_continuous(args, False)


def cmd_status(args) -> int:
    monitor = _kube().get_monitor(args.namespace, args.app)
    if monitor is None:
        print(f"no DeploymentMonitor {args.namespace}/{args.app}", file=sys.stderr)
        return 1
    s = monitor.status
    out = {
        "app": args.app,
        "namespace": args.namespace,
        "phase": s.phase,
        "jobId": s.job_id,
        "continuous": monitor.spec.continuous,
        "remediationTaken": s.remediation_taken,
        "expired": s.expired,
        "anomalousMetrics": [m.name for m in s.anomaly.anomalous_metrics],
    }
    print(json.dumps(out, indent=2))
    return 0


def cmd_demo(args) -> int:
    from .examples.demo_app import run_demo

    result = run_demo(unhealthy=not args.healthy)
    print(json.dumps(result, indent=2, default=str))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="foremast-tpu", description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command")
    sub.add_parser("serve", help="run the runtime (job API + engine)").set_defaults(
        func=cmd_serve
    )
    op = sub.add_parser("operator", help="run the K8s operator loop")
    op.add_argument("--analyst", default="", help="job API endpoint")
    op.set_defaults(func=cmd_operator)
    for name, fn, help_ in (
        ("watch", cmd_watch, "enable continuous monitoring for an app"),
        ("unwatch", cmd_unwatch, "disable continuous monitoring for an app"),
        ("status", cmd_status, "print an app's monitor status"),
    ):
        sp = sub.add_parser(name, help=help_)
        sp.add_argument("app")
        sp.add_argument("-n", "--namespace", default="default")
        sp.set_defaults(func=fn)
    d = sub.add_parser("demo", help="local end-to-end demo, no cluster")
    d.add_argument("--healthy", action="store_true",
                   help="run the healthy variant (no error generator)")
    d.set_defaults(func=cmd_demo)
    return p


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "command", None):
        args = parser.parse_args(["serve"])
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
