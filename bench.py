"""Benchmark: canary metric-pair scoring throughput on the fused TPU program.

North star (BASELINE.json / BASELINE.md): score 100k concurrent
(baseline, canary) metric-pair windows in <1 s p99 on a v5e-8 — i.e.
12,500 pairs/s/chip. This bench runs the single-chip fused scorer
(pairwise test family + forecast-band check, parallel/fleet.py) on
realistic windows (T=128 ≈ 2h of 60s-step points — wider than the
reference's 10-min canary window) and reports pairs scored per second
per chip. vs_baseline = value / 12500 (>1.0 beats the 8-chip-in-1s
target pro-rated to one chip).

Prints exactly one JSON line.
"""
from __future__ import annotations

import json
import time

import numpy as np

TARGET_PAIRS_PER_SEC_PER_CHIP = 100_000 / 8.0  # BASELINE.json north star, per chip


def main() -> None:
    import jax

    from foremast_tpu.parallel.fleet import score_pairs

    B, T = 8192, 128
    rng = np.random.default_rng(0)
    baseline = rng.normal(10.0, 2.0, (B, T)).astype(np.float32)
    current = rng.normal(10.0, 2.0, (B, T)).astype(np.float32)
    b_mask = rng.random((B, T)) > 0.05
    c_mask = rng.random((B, T)) > 0.05
    cfg = (
        np.full(B, 0.01, np.float32),
        np.full(B, 0b1111, np.int32),
        np.zeros(B, np.int32),
        np.full(B, 10, np.int32),
        np.full(B, 3.0, np.float32),
        np.zeros(B, np.int32),
        np.zeros(B, np.float32),
        np.tile(np.asarray([20, 20, 5], np.int32), (B, 1)),
    )
    args = [jax.device_put(a) for a in (baseline, b_mask, current, c_mask, *cfg)]

    def run():
        out = score_pairs(*args)
        jax.block_until_ready(out["unhealthy"])
        return out

    run()  # compile
    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    p50 = float(np.median(times))
    pairs_per_sec = B / p50
    print(json.dumps({
        "metric": "canary_pairs_scored_per_sec_per_chip",
        "value": round(pairs_per_sec, 1),
        "unit": "pairs/s/chip",
        "vs_baseline": round(pairs_per_sec / TARGET_PAIRS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
