"""Benchmark: canary metric-pair scoring, shaped like the north-star claim.

North star (BASELINE.json / BASELINE.md): score 100k concurrent
(baseline, canary) metric-pair windows in <1 s **p99** on a v5e-8.
The engine shards the fleet batch evenly over the 8-chip fleet axis
(parallel/fleet.py:make_fleet_scorer), so each chip scores exactly
B_total/8 = 12,500 pairs; the scoring itself is embarrassingly parallel
(the only cross-chip traffic is the O(k*n_chips) verdict reduction).
This bench therefore runs the per-chip shard — B=12,500 pairs, T=128
(~2h of 60s-step points, wider than the reference's 10-min canary
window) — on the one available chip and pro-rates explicitly: the wall
time of one chip's shard IS the fleet's time to 100k, up to the top-k
reduction, which is validated (compiled + executed, not timed — no
multi-chip hardware here) on the 8-device dryrun mesh.

Protocol (VERDICT r02 #2): p99 over >=100 timed runs (default 150,
override BENCH_RUNS); compile time reported separately; min/max/std
included so round-over-round drift in the headline is characterized
instead of mysterious.

Additionally the UNPRORATED claim is measured outright: the entire
100k-pair fleet batch on the ONE available chip, same run count and p99
protocol (p99_s_100k_single_chip). If that is < 1 s, the v5e-8 claim is
beaten on an eighth of the claimed hardware, no pro-rating needed.

MEASUREMENT INTEGRITY (discovered round 3, supersedes r01/r02 numbers):
under the axon development tunnel, `jax.block_until_ready` can return in
tens of microseconds for launches whose outputs are never transferred to
the host — the execution is effectively elided/deferred, and timing it
measures dispatch, not compute (r01-r02 recorded ~1e8 "pairs/s/chip"
this way; scan-isolated marginal cost per real iteration is ~400x
slower). Every timed run here therefore ends by fetching a 4-byte
on-device reduction of the outputs to the host, which forces — and
proves — completion. The fetch costs one tunnel round-trip, reported
separately as readback_rtt_floor_s (~70 ms on the dev tunnel; ~0 on a
locally-attached production TPU), so the e2e numbers are conservative.

Prints exactly one JSON line.
"""
from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

import numpy as np

TARGET_PAIRS_PER_SEC_PER_CHIP = 100_000 / 8.0  # north star pro-rated per chip
# BENCH_PAIRS_TOTAL exists for CPU smoke-tests of the bench itself; the
# recorded artifact always uses the real 100k claim shape, and the JSON
# self-describes the batch via "pairs_total" so an overridden run can
# never masquerade as a real one.
B_TOTAL = int(os.environ.get("BENCH_PAIRS_TOTAL", "100000"))
N_CHIPS = 8
B_CHIP = max(B_TOTAL // N_CHIPS, 1)  # 12,500: one chip's shard of 100k


def _run_json_child(cmd: list, timeout_s: float, env: dict | None = None,
                    cwd: str | None = None):
    """Run a child that prints one JSON line; returns (record, error).

    Shared by the cycle and device legs: a failing child must yield a
    DIAGNOSABLE error string (stderr tail included — subprocess errors
    alone say only 'non-zero exit status'), never a hang or a lost cause.
    """
    try:
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s,
            env=env, check=True, cwd=cwd,
        )
        return json.loads(out.stdout.strip().splitlines()[-1]), None
    except Exception as e:  # noqa: BLE001 - callers degrade, never crash
        stderr = getattr(e, "stderr", None) or ""
        if isinstance(stderr, bytes):
            stderr = stderr.decode(errors="replace")
        tail = stderr.strip().splitlines()[-3:]
        msg = f"{type(e).__name__}: {e}"
        if tail:
            msg += " | stderr: " + " / ".join(tail)
        return None, msg


def _cycle_bench() -> dict:
    """Host-path numbers: a 10k-job cycle through analyzer.run_cycle with
    the native parser on vs off (foremast_tpu/bench_cycle.py). One
    subprocess per variant (FOREMAST_NATIVE latches at first load),
    CPU-pinned so they never contend for the parent's TPU grant — the
    host path is what these measure; the device bound is the headline."""
    def run_child(native_flag: str, mix: bool):
        """One CPU-pinned bench_cycle child (FOREMAST_NATIVE latches at
        first load, so every variant needs its own process; the axon pool
        address is stripped so a wedged tunnel can't hang a CPU run)."""
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["FOREMAST_NATIVE"] = native_flag
        env["BENCH_CYCLE_MIX"] = "1" if mix else "0"
        env.setdefault("BENCH_CYCLE_JOBS", "10000")
        return _run_json_child(
            [sys.executable, "-m", "foremast_tpu.bench_cycle"],
            timeout_s=900, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )

    extra: dict = {}
    for flag, key in (("1", "native"), ("0", "python")):
        rec, err = run_child(flag, mix=False)
        if rec is not None:
            extra[f"cycle_jobs_per_sec_{key}"] = rec["value"]
            # the meaningful host-path number: cycle minus the CPU-pinned
            # score stage (device-bound in production; the headline above
            # measures it on the real chip). The raw cycle_jobs_per_sec_*
            # stays for continuity but is score-dominated on CPU. When the
            # child omits the decomposed field (clock-step anomaly), the
            # key is omitted here too — never silently substituted with
            # the score-dominated number it exists to correct.
            if "host_jobs_per_sec" in rec:
                extra[f"cycle_host_jobs_per_sec_{key}"] = rec["host_jobs_per_sec"]
            extra[f"cycle_preprocess_s_{key}"] = rec["preprocess_s_per_cycle"]
            extra[f"cycle_score_s_{key}"] = rec.get("score_s_per_cycle", 0.0)
        else:
            extra[f"cycle_error_{key}"] = err
    nat = extra.get("cycle_preprocess_s_native")
    py = extra.get("cycle_preprocess_s_python")
    if nat and py:
        extra["cycle_native_preprocess_speedup"] = round(py / nat, 2)
    nat_h = extra.get("cycle_host_jobs_per_sec_native")
    py_h = extra.get("cycle_host_jobs_per_sec_python")
    if nat_h and py_h:
        extra["cycle_native_host_speedup"] = round(nat_h / py_h, 2)
    # third leg: the MIXED model-family fleet (pair+band+bivariate+LSTM+HPA,
    # native parser) — per-family score decomposition and the bounded
    # LSTM train-on-miss cost (VERDICT r3 #3). The pure-pair legs above
    # stay as the round-over-round continuity numbers.
    # fourth leg: the 8-device virtual-mesh reduction share (VERDICT r3
    # #7) — time the sharded fleet program with and without its
    # psum/all_gather top-k tail; turns "validated, not timed" into a
    # measured fraction (bench_mesh.py documents the CPU-mesh caveats).
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    mrec, merr = _run_json_child(
        [sys.executable, "-m", "foremast_tpu.bench_mesh"],
        timeout_s=600, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if mrec is not None:
        for k_ in ("value", "with_reduction_s", "score_only_s",
                   "noise_floor_s", "overhead_below_noise",
                   "reduction_share_cpu_mesh", "share_vs_device_scoring_est"):
            extra[f"mesh_{k_}" if k_ != "value"
                  else "mesh_reduction_overhead_s"] = mrec.get(k_)
    else:
        extra["mesh_error"] = merr

    rec, err = run_child("1", mix=True)
    if rec is not None:
        extra["cycle_mixed_jobs_per_sec"] = rec["value"]
        if "host_jobs_per_sec" in rec:
            extra["cycle_mixed_host_jobs_per_sec"] = rec["host_jobs_per_sec"]
        extra["cycle_mixed_family_jobs"] = rec.get("family_jobs")
        extra["cycle_mixed_family_score_s"] = rec.get("family_score_s_per_cycle")
        extra["cycle_mixed_lstm_train_s"] = rec.get("lstm_train_s_per_cycle")
        extra["cycle_mixed_lstm_trains"] = rec.get("lstm_trains_per_cycle")
        # steady-state warm-up accounting (round 5): the timed cycles are
        # train-free; the one-time warm-up cost is recorded separately
        extra["cycle_mixed_warmup_cycles"] = rec.get("warmup_cycles")
        extra["cycle_mixed_lstm_train_warmup_s"] = rec.get("lstm_train_warmup_s")
        # pipeline-stage decomposition + compile counters (ISSUE 2): the
        # overlap story per cycle, and proof steady state never compiles
        extra["cycle_mixed_stage_s"] = rec.get("stage_s_per_cycle")
        extra["cycle_mixed_compiles_warmup"] = rec.get("compiles_warmup")
        extra["cycle_mixed_compiles_steady"] = rec.get("compiles_steady_state")
    else:
        extra["cycle_mixed_error"] = err
    return extra


def _rtt_floor(n: int = 5) -> float:
    """Host<->device round-trip floor: fetch a tiny precomputed reduction.
    This is the tunnel/transfer cost baked into every timed run below."""
    import jax

    tiny = jax.jit(lambda v: v.sum())
    z = jax.device_put(np.ones(8, np.float32))
    float(tiny(z))  # compile
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        float(tiny(z))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _measure(B: int, T: int, n_runs: int) -> dict:
    """Time score_pairs at batch B: p50/p99/min/max/std over n_runs, plus
    compile time for this batch shape.

    Each timed run ends with a host fetch of a jitted scalar reduction of
    the verdict outputs — completion is FORCED, not assumed (see module
    docstring: block_until_ready alone under-measures by ~400x on the dev
    tunnel because unconsumed executions are elided)."""
    import jax

    from foremast_tpu.parallel.fleet import score_pairs

    rng = np.random.default_rng(0)
    baseline = rng.normal(10.0, 2.0, (B, T)).astype(np.float32)
    current = rng.normal(10.0, 2.0, (B, T)).astype(np.float32)
    b_mask = rng.random((B, T)) > 0.05
    c_mask = rng.random((B, T)) > 0.05
    cfg = (
        np.full(B, 0.01, np.float32),
        np.full(B, 0b1111, np.int32),
        np.zeros(B, np.int32),
        np.full(B, 10, np.int32),
        np.full(B, 3.0, np.float32),
        np.zeros(B, np.int32),
        np.zeros(B, np.float32),
        np.tile(np.asarray([20, 20, 5], np.int32), (B, 1)),
    )
    args = [jax.device_put(a) for a in (baseline, b_mask, current, c_mask, *cfg)]

    import jax.numpy as jnp

    @jax.jit
    def _consume(out):
        # scalar digest of every output: nothing can be elided
        return jax.tree.reduce(
            lambda a, b: a + b.sum().astype(jnp.float32), out, jnp.float32(0)
        )

    def run():
        out = score_pairs(*args)
        return float(_consume(out))  # 4-byte host readback = proof of completion

    t0 = time.perf_counter()
    digest = run()  # compile + first execute
    compile_s = time.perf_counter() - t0

    times = []
    for _ in range(n_runs):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    ts = np.sort(np.asarray(times))
    return {
        "p50": float(np.median(ts)),
        "p99": float(np.percentile(ts, 99)),
        "min": float(ts[0]),
        "max": float(ts[-1]),
        "std": float(np.std(ts)),
        "compile_s": compile_s,
        "runs": n_runs,
        "digest": digest,
    }


def _digest_fields(key: str, value: float) -> dict:
    """Digest scalar, kept JSON-strict: a NaN/inf digest would make
    json.dumps emit a non-strict NaN/Infinity token and break the
    one-JSON-line contract for strict parsers — emit null + error instead
    (a non-finite digest is itself a finding: the kernel produced
    non-finite outputs)."""
    if math.isfinite(value):
        return {key: value}
    return {key: None, f"{key}_error": f"non-finite digest: {value!r}"}


def _long_window_fields() -> dict:
    """Long-window leg: the 7-day historical shapes (VERDICT r3 #4).

    The reference's historical model runs on ~10,080-point windows
    (metricsquery.go:93-99) — where `lax.scan` serialization and the
    60-candidate Holt-Winters grid actually bite, none of which the
    T=128 headline exercises. Three measurements, forced completion:

      * p50/p99 for a B-job moving-average BAND batch at T=10,080
        (predict + sigma + anomalies — the production band path);
      * sequential vs associative-scan SES at the same shape — the
        LONG_WINDOW_STEPS switch's justification, measured;
      * the Holt-Winters grid fit (60 candidates via lax.map) at a
        daily period on a smaller batch (its cost scales with G*B*T).
    """
    import jax
    import jax.numpy as jnp

    from foremast_tpu.ops import forecast as fc
    from foremast_tpu.ops import seqscan as sq

    T = int(os.environ.get("BENCH_LONG_WINDOW", "10080"))
    B = int(os.environ.get("BENCH_LONG_BATCH", "256"))
    B_HW = max(B // 8, 1)
    n_runs = int(os.environ.get("BENCH_LONG_RUNS", "30"))

    rng = np.random.default_rng(1)
    x = np.cumsum(rng.normal(0, 0.2, (B, T)), axis=-1).astype(np.float32) + 50.0
    m = rng.random((B, T)) > 0.05
    region = np.zeros((B, T), bool)
    region[:, -30:] = True  # judged current window: the last 30 min
    alphas = np.full(B, 0.3, np.float32)
    thr = np.full(B, 3.0, np.float32)
    bound = np.zeros(B, np.int32)
    mlb = np.zeros(B, np.float32)
    xd, md, rd = jax.device_put(x), jax.device_put(m), jax.device_put(region)

    @jax.jit
    def band_fn(xv, xm, reg):
        hist = xm & ~reg
        preds = fc.moving_average_predictions(xv, hist, 30)
        sigma = fc.residual_sigma(xv, preds, hist, ~reg)
        out = fc.band_anomalies(xv, xm, reg, preds, sigma, thr, bound, mlb)
        return jax.tree.reduce(
            lambda a, b: a + b.sum().astype(jnp.float32), out, jnp.float32(0))

    def timed(fn, runs):
        fn()  # compile + warm
        ts = []
        for _ in range(runs):
            t0 = time.perf_counter()
            fn()
            ts.append(time.perf_counter() - t0)
        ts = np.sort(np.asarray(ts))
        return {"p50": float(np.median(ts)),
                "p99": float(np.percentile(ts, 99))}

    out: dict = {"long_window": T, "long_batch": B}
    band = timed(lambda: float(band_fn(xd, md, rd)), n_runs)
    out["long_band_p99_s"] = round(band["p99"], 6)
    out["long_band_p50_s"] = round(band["p50"], 6)

    hist_mask = md & ~rd
    seq = jax.jit(lambda: fc.ses_predictions(xd, hist_mask, alphas).sum())
    assoc = jax.jit(
        lambda: sq.ses_predictions_assoc(xd, hist_mask, alphas).sum())
    seq_t = timed(lambda: float(seq()), max(n_runs // 3, 5))
    assoc_t = timed(lambda: float(assoc()), max(n_runs // 3, 5))
    out["long_ses_sequential_p50_s"] = round(seq_t["p50"], 6)
    out["long_ses_assoc_p50_s"] = round(assoc_t["p50"], 6)
    out["long_ses_assoc_speedup"] = round(
        seq_t["p50"] / max(assoc_t["p50"], 1e-9), 2)

    period = min(1440, T // 2)
    fitm = np.asarray(hist_mask).copy()
    fitm[:, : 2 * period] = False
    xh, mh, fh = (jax.device_put(a[:B_HW]) for a in
                  (x, np.asarray(hist_mask), fitm))
    hw = jax.jit(
        lambda: fc.fit_holt_winters(xh, mh, fh, period)[1].sum())
    hw_t = timed(lambda: float(hw()), max(n_runs // 6, 3))
    out["long_hw_fit_p50_s"] = round(hw_t["p50"], 6)
    out["long_hw_batch"] = B_HW
    return out


def _device_fields() -> dict:
    """The on-device measurements (runs inside the --device-only child)."""
    import jax

    T = 128
    n_runs = int(os.environ.get("BENCH_RUNS", "150"))
    rtt = _rtt_floor()
    shard = _measure(B_CHIP, T, n_runs)
    # the stronger statement: the ENTIRE 100k fleet batch on ONE chip —
    # no pro-rating, no fleet needed. Same run count (same p99 protocol);
    # guarded so an 8x-batch OOM can never destroy the headline in hand.
    try:
        whole = _measure(B_TOTAL, T, n_runs)
        whole_fields = {
            "p99_s_100k_single_chip": round(whole["p99"], 6),
            "p50_s_100k_single_chip": round(whole["p50"], 6),
            "single_chip_runs": whole["runs"],
            "compile_s_100k": round(whole["compile_s"], 3),
            **_digest_fields("digest_100k", whole["digest"]),
        }
    except Exception as e:  # noqa: BLE001 - headline must still print
        whole_fields = {"single_chip_error": f"{type(e).__name__}: {e}"}

    p50, p99 = shard["p50"], shard["p99"]
    pairs_per_sec = B_CHIP / p50
    # device-compute estimate: the same run with the measured readback
    # round-trip (absent on locally-attached production hardware) removed
    exec_est = max(p50 - rtt, 1e-9)
    return {
        "value": round(pairs_per_sec, 1),
        "vs_baseline": round(pairs_per_sec / TARGET_PAIRS_PER_SEC_PER_CHIP, 3),
        # the claim, measured in its own shape: time for one chip's 12,500-pair
        # shard of the 100k fleet batch == fleet time to 100k on v5e-8
        # (pro-rated; the O(k*8) top-k reduction is excluded — see docstring).
        # Forced-completion protocol: includes one readback round-trip.
        "p99_s_at_100k": round(p99, 6),
        "p50_s_at_100k": round(p50, 6),
        "min_s": round(shard["min"], 6),
        "max_s": round(shard["max"], 6),
        "std_s": round(shard["std"], 6),
        "runs": shard["runs"],
        "batch_per_chip": B_CHIP,
        "pairs_total": B_TOTAL,
        "compile_s": round(shard["compile_s"], 3),
        "readback_rtt_floor_s": round(rtt, 6),
        "pairs_per_sec_rtt_adjusted": round(B_CHIP / exec_est, 1),
        # the completion-proof scalar (also catches silent numerical drift
        # in score_pairs round-over-round: same seed, same digest)
        **_digest_fields("digest", shard["digest"]),
        # the whole 100k batch on ONE chip (unprorated: beats the 8-chip
        # claim outright if < 1 s)
        **whole_fields,
        "backend": jax.default_backend(),
    }


def _opportunistic_fallback() -> dict:
    """Device numbers banked mid-round by scripts/opportunistic_bench.py.

    Rounds 3 and 4 both recorded value 0.0 because the axon tunnel was
    wedged at the END of the round while it had been healthy earlier.
    When the preflight fails, any opportunistically-captured artifact in
    the repo root is folded in WITH PROVENANCE (capture_mode/captured_at
    ride along, device_skipped stays) — the headline then reports the
    real measurement from this round instead of being skipped, and the
    labeling keeps it honest: these numbers are from `captured_at`, not
    from this run."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.environ.get("BENCH_FALLBACK_ARTIFACT",
                                       "BENCH_LOCAL_r05.json"))
    try:
        with open(path) as f:
            rec = json.loads(f.read().strip().splitlines()[-1])
    except (OSError, ValueError, IndexError):
        return {}
    if not isinstance(rec, dict) or not rec.get("value"):
        return {}
    # freshness gate: a leftover artifact from a PRIOR round (older
    # kernels, older protocol) must never masquerade as this round's
    # measurement — the docstring's promise is enforced, not assumed.
    # Rounds run well under 14 h; a missing/unparseable stamp fails shut.
    max_age_h = _env_float("BENCH_FALLBACK_MAX_AGE_H", 14.0)
    try:
        import calendar

        captured = time.strptime(rec.get("captured_at", ""),
                                 "%Y-%m-%dT%H:%M:%SZ")
        # timegm, not mktime: the stamp is UTC ("Z"); mktime would read
        # it as local time and skew the age by the host's UTC offset
        age_h = (time.time() - calendar.timegm(captured)) / 3600.0
    except (ValueError, OverflowError):
        return {}
    if not (0 <= age_h <= max_age_h):
        return {}
    rec.pop("metric", None)
    rec.pop("unit", None)
    rec.setdefault("capture_mode", "opportunistic_mid_round")
    rec["device_numbers_from"] = os.path.basename(path)
    return rec


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _preflight(deadline_s: float, window_s: float) -> tuple[bool, str | None]:
    """Tunnel health probe: at most TWO timeout-kills, fail fast otherwise.

    Round 3 lost its device artifact to a wedged axon tunnel: the 1200 s
    device child hung in jax.devices() and the whole leg died to one
    TimeoutExpired. A cheap probe child answers "is the tunnel alive?"
    before the expensive leg commits. Two wedge facts shape the retry
    policy (both observed on this machine): (a) timeout-KILLING a process
    that holds/awaits the TPU grant is itself what wedges jax.devices()
    machine-wide, so each killed probe can re-wedge a recovering tunnel —
    the probe count must be bounded, not backoff-looped; (b) the wedge
    clears on its own given quiet time. So: one probe; a fast non-timeout
    failure (broken env, import error) returns immediately; a timeout
    sleeps out most of the remaining window WITHOUT spawning new
    grant-holders, then probes once more. Returns (healthy, last_error)."""
    probe = [
        sys.executable, "-c",
        "import json, jax; d = jax.devices(); "
        "print(json.dumps({'n': len(d), 'backend': jax.default_backend()}))",
    ]
    t_end = time.time() + window_s
    rec, err = _run_json_child(probe, timeout_s=deadline_s)
    if rec is not None:
        return True, None
    if not (err or "").startswith("TimeoutExpired"):
        return False, err  # deterministic failure: retrying is pure stall
    # Wedge signature. Give the tunnel quiet time to self-recover, keeping
    # enough of the window for one final, longer-deadline probe.
    remaining = t_end - time.time()
    if remaining <= 30.0:
        return False, err
    final_deadline = min(max(deadline_s, remaining * 0.4), 300.0)
    time.sleep(max(remaining - final_deadline, 15.0))
    rec, err2 = _run_json_child(probe, timeout_s=final_deadline)
    if rec is not None:
        return True, None
    return False, f"{err} | after quiet-wait: {err2}"


def main() -> None:
    if "--device-only" in sys.argv:
        print(json.dumps(_device_fields()))
        return
    if "--long-only" in sys.argv:
        print(json.dumps(_long_window_fields()))
        return

    # parse the deadlines FIRST: a malformed env var must not throw away
    # a 15-minute cycle bench later, outside the degrade path
    timeout_s = _env_float("BENCH_DEVICE_TIMEOUT", 1200.0)
    # 240 s, not 90: a healthy-but-slow grant was measured at ~2 min this
    # round, and killing a probe that is merely waiting re-wedges the
    # pool for ~25 min (docs/benchmarks.md post-mortem) — the first kill
    # must not fire inside the healthy-grant latency band
    preflight_timeout_s = _env_float("BENCH_PREFLIGHT_TIMEOUT", 240.0)
    preflight_window_s = _env_float("BENCH_PREFLIGHT_WINDOW", 900.0)
    # The device leg runs FIRST: the headline is the round's most
    # important artifact, so nothing may die before it — and its measured
    # score time then calibrates the mesh leg's share estimate.
    # It runs in a CHILD with a hard deadline: a wedged TPU
    # tunnel (a killed grant-holder can hang jax.devices() indefinitely)
    # must degrade to a JSON line carrying the host-path numbers + an
    # error field — never a silent hang that records nothing. The
    # pre-flight probe (cheap, retried) gates the expensive leg; CPU runs
    # skip it (nothing to probe — the "device" is the host).
    cpu_run = os.environ.get("JAX_PLATFORMS", "").strip() == "cpu"
    child_env = dict(os.environ)
    if cpu_run:
        # JAX_PLATFORMS=cpu alone does NOT stop the axon plugin from
        # dialing its tunnel at init — a wedged tunnel hangs the child in
        # jax.devices() even though the run never wanted the TPU. Strip
        # the pool address so CPU smoke runs are hermetic.
        child_env.pop("PALLAS_AXON_POOL_IPS", None)
        # CPU smoke runs: the real device protocol (150 forced-completion
        # runs plus a B=100k XLA:CPU compile) cannot finish inside the
        # child deadline — a default-size `make bench` burned the whole
        # 1200 s device timeout and recorded only a TimeoutExpired.
        # Shrink to a completing protocol unless the caller pinned sizes;
        # the JSON stays self-describing (backend=cpu, runs, pairs_total).
        child_env.setdefault("BENCH_RUNS", "20")
        child_env.setdefault("BENCH_PAIRS_TOTAL", "25000")
        # same for the long-window leg, which a completing device leg now
        # reaches: the full 10,080-step scan protocol is the exact slow-
        # compile workload the long-leg deadline exists to contain
        child_env.setdefault("BENCH_LONG_WINDOW", "2048")
        child_env.setdefault("BENCH_LONG_BATCH", "64")
        child_env.setdefault("BENCH_LONG_RUNS", "10")
        healthy, probe_err = True, None
    else:
        healthy, probe_err = _preflight(preflight_timeout_s, preflight_window_s)
    if healthy:
        device, err = _run_json_child(
            [sys.executable, os.path.abspath(__file__), "--device-only"],
            timeout_s=timeout_s, env=child_env,
        )
        if device is None and not (err or "").startswith("TimeoutExpired"):
            # one retry for CLEAN failures only (the probe said healthy, so
            # e.g. a transient OOM is worth a second attempt). A timeout
            # means the leg's own kill likely wedged the tunnel — an
            # immediate retry would hang in jax.devices() and burn another
            # full deadline for a worse error message.
            device, err = _run_json_child(
                [sys.executable, os.path.abspath(__file__), "--device-only"],
                timeout_s=timeout_s, env=child_env,
            )
        if device is None:
            # honesty convention (docs/benchmarks.md): an unavailable
            # device leg SKIPS the headline fields rather than recording
            # value 0.0 — r04/r05's environmental zeros read as 8900x
            # regressions in round-over-round diffs. `device_skipped`
            # carries the reason; a banked opportunistic artifact may
            # still fold real numbers in (with provenance) underneath it.
            device = {"device_skipped": err}
            device.update(_opportunistic_fallback())
        elif os.environ.get("BENCH_SKIP_LONG", "0").strip().lower() in (
                "1", "true", "yes", "on"):
            device["long_window_skipped"] = True
        else:
            # the 7-day-window leg gets its OWN child + deadline: 10k-step
            # scan compiles are slow through the axon remote-compile
            # tunnel, and a long-leg death must not cost the headline
            # artifact already in hand
            long_rec, long_err = _run_json_child(
                [sys.executable, os.path.abspath(__file__), "--long-only"],
                timeout_s=_env_float("BENCH_LONG_TIMEOUT", 600.0),
                env=child_env,
            )
            if long_rec is not None:
                device.update(long_rec)
            else:
                device["long_window_error"] = long_err
    else:
        device = {
            "device_skipped": f"preflight: tunnel unhealthy after "
                              f"{preflight_window_s:.0f}s window | {probe_err}",
        }
        device.update(_opportunistic_fallback())
    # calibrate the mesh leg's reduction-share estimate with THIS run's
    # measured device score time (p50 minus the readback round-trip)
    # instead of bench_mesh.py's hardcoded prior
    p50 = device.get("p50_s_at_100k")
    rtt = device.get("readback_rtt_floor_s", 0.0)
    if p50 and not cpu_run and "device_skipped" not in device:
        # self-calibration ONLY from this run's own device leg: numbers
        # folded in by the opportunistic fallback carry provenance the
        # mesh record would not inherit (bench_mesh falls back to its
        # documented prior instead)
        # setdefault: an operator-exported BENCH_DEVICE_SCORE_S is a
        # documented override and must win over self-calibration
        os.environ.setdefault(
            "BENCH_DEVICE_SCORE_S", str(max(p50 - rtt, 1e-6)))
    cycle_extra = _cycle_bench()
    print(json.dumps({
        "metric": "canary_pairs_scored_per_sec_per_chip",
        "unit": "pairs/s/chip",
        **device,
        **cycle_extra,
    }))


if __name__ == "__main__":
    main()
