"""Windowing: ragged -> masked grid invariants."""
import numpy as np

from foremast_tpu.ops.windowing import (
    Window,
    align_step,
    bucket_length,
    pack_windows,
    resample_to_grid,
)


def test_align_step():
    assert align_step(125, 60) == 120
    assert align_step(120, 60) == 120


def test_resample_basic():
    start, end = 0, 600  # 10-min canary window, T=10
    ts = [0, 60, 120, 300, 540]
    vals = [1.0, 2.0, 3.0, 4.0, 5.0]
    w = resample_to_grid(ts, vals, start, end)
    assert w.values.shape == (10,)
    assert w.mask.sum() == 5
    np.testing.assert_array_equal(w.values[[0, 1, 2, 5, 9]], [1, 2, 3, 4, 5])
    assert not w.mask[3] and not w.mask[4]


def test_resample_drops_nan_and_out_of_range():
    w = resample_to_grid([0, 60, 7200, 120], [1.0, np.nan, 9.0, 2.0], 0, 300)
    assert w.mask.sum() == 2  # nan and out-of-range dropped
    assert w.values[0] == 1.0 and w.values[2] == 2.0


def test_resample_rounds_to_nearest_slot():
    # scrape lag: samples a few seconds past the boundary still snap to it
    w = resample_to_grid([61.0, 124.0], [7.0, 8.0], 0, 300)
    assert w.mask[1] and w.values[1] == 7.0
    assert w.mask[2] and w.values[2] == 8.0


def test_pack_windows_buckets():
    ws = [
        Window(np.ones(10, np.float32), np.ones(10, bool), 0),
        Window(np.ones(30, np.float32), np.ones(30, bool), 0),
    ]
    vals, mask = pack_windows(ws)
    assert vals.shape == (2, 32)  # bucket of 30 is 32
    assert mask[0].sum() == 10 and mask[1].sum() == 30
    assert not mask[0, 10:].any()


def test_bucket_length_covers_7day_window():
    assert bucket_length(10_080) == 16384
    assert bucket_length(16) == 16


def test_resample_in_range_by_timestamp_not_slot():
    # review finding: ts=-29 must be dropped (before start); ts=575 must land
    # in the last slot instead of being dropped
    w = resample_to_grid([-29.0, 575.0], [5.0, 6.0], 0, 600)
    assert not w.mask[0]
    assert w.mask[9] and w.values[9] == 6.0


def test_pack_windows_refuses_truncation():
    import pytest

    ws = [Window(np.ones(100, np.float32), np.ones(100, bool), 0)]
    with pytest.raises(ValueError):
        pack_windows(ws, pad_to=64)


def test_resample_masks_values_beyond_f32_range():
    """A 1e39 sample is f64-finite but f32-inf: it must be MASKED, not
    stored as inf with mask=True (the mask contract is what lets every
    downstream kernel skip finiteness checks). Exercised on both the
    python path and (when built) the native >=512-point path."""
    import numpy as np

    from foremast_tpu.ops.windowing import resample_to_grid

    for n in (10, 600):  # python path; native path when available
        ts = [60.0 * i for i in range(n)]
        vals = [10.0] * n
        vals[n // 2] = 1e39
        w = resample_to_grid(ts, vals, 0, 60 * n)
        assert np.all(np.isfinite(w.values[w.mask]))
        assert w.mask.sum() == n - 1  # the monster sample is masked out
