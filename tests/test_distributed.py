"""Multi-process (DCN) smoke test: two OS processes join one JAX world
through parallel.distributed.initialize and run a psum whose operands
live in different processes.

This is the boundary the 8-device virtual mesh cannot reach: that mesh
is one process, so its collectives never cross a process gap. Here the
coordinator handshake, the global device view (2 processes x 1 CPU
device), make_array_from_process_local_data, and a cross-process psum
all run for real — the same code path a TPU pod uses over DCN
(SURVEY.md §2.8), shrunk to two local CPU processes.

Skips gracefully when the installed jax cannot serve cross-process CPU
collectives (the capability, not our wiring, is what varies by build).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial
from jax.sharding import NamedSharding, PartitionSpec as P

from foremast_tpu.parallel import distributed as D
from foremast_tpu.parallel.fleet import shard_map  # version-compat shim
from foremast_tpu.parallel.mesh import FLEET_AXIS

did_init = D.initialize()  # env contract: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID
assert did_init, "initialize() must join the 2-process world"
assert jax.process_count() == 2, jax.process_count()

info = D.host_info()
assert info.num_processes == 2
assert info.global_devices == 2, info.global_devices

mesh = D.global_fleet_mesh()
global_batch = 4
sl = D.process_batch_slice(global_batch, info)
full = np.arange(1.0, global_batch + 1.0, dtype=np.float32)  # 1+2+3+4 = 10
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(FLEET_AXIS)), full[sl], (global_batch,)
)

@partial(shard_map, mesh=mesh, in_specs=P(FLEET_AXIS), out_specs=P())
def total(x):
    return jax.lax.psum(jnp.sum(x), FLEET_AXIS)

out = jax.jit(total)(arr)
print("PSUM_TOTAL", float(out), flush=True)
assert float(out) == 10.0, float(out)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_workers(worker_src: str, timeout: float, what: str) -> str:
    """Launch two single-device CPU processes joined via a local
    coordinator; return combined output (skips when the jax build lacks
    cross-process CPU collectives, fails on any other error)."""
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = "2"
        env["PROCESS_ID"] = str(rank)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", worker_src],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=os.path.dirname(os.path.dirname(__file__)),
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"{what} workers timed out")
    combined = "\n\n".join(outs)
    if any(p.returncode != 0 for p in procs):
        lowered = combined.lower()
        # every phrasing jax/XLA builds use for the missing capability —
        # this container's jaxlib raises INVALID_ARGUMENT "Multiprocess
        # computations aren't implemented on the CPU backend", which is
        # environmental (the capability, not our wiring) and must SKIP
        # with the reason, not fail tier-1
        unsupported = (
            "unimplemented" in lowered
            or "not supported" in lowered
            or "aren't implemented" in lowered
            or "are not implemented" in lowered
            or "multiprocess computations" in lowered
        )
        if unsupported:
            pytest.skip(f"cross-process CPU collectives unavailable in "
                        f"this jax build: {combined[-500:]}")
        pytest.fail(f"{what} failed:\n{combined[-4000:]}")
    return combined


@pytest.mark.slow
def test_two_process_psum_over_coordinator():
    combined = _run_two_workers(_WORKER, 180, "DCN smoke")
    # both ranks computed the same global reduction over DCN
    assert combined.count("PSUM_TOTAL 10.0") == 2, combined[-2000:]

_SCORER_WORKER = r"""
import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from foremast_tpu.parallel import distributed as D
from foremast_tpu.parallel import fleet as fl
from foremast_tpu.parallel.mesh import FLEET_AXIS

assert D.initialize(), "initialize() must join the 2-process world"
info = D.host_info()
mesh = D.global_fleet_mesh()

B, T = 4, 32
rng = np.random.default_rng(0)
base = rng.normal(10.0, 1.0, (B, T)).astype(np.float32)
cur = base.copy()
cur[1] += 100.0  # row 1 is catastrophically shifted
cur[3] += 100.0  # row 3 too
mask = np.ones((B, T), bool)

def g(a):
    # identical full array on every process; each contributes its slice
    sl = D.process_batch_slice(a.shape[0], info)
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(FLEET_AXIS)), a[sl], a.shape
    )

cfg = {
    "pvalue_threshold": np.full(B, 0.01, np.float32),
    "test_mask": np.full(B, 0b1111, np.int32),
    "combine": np.zeros(B, np.int32),
    "ma_window": np.full(B, 10, np.int32),
    "band_threshold": np.full(B, 3.0, np.float32),
    "bound_mode": np.zeros(B, np.int32),
    "min_lower_bound": np.zeros(B, np.float32),
}
run = fl.make_fleet_scorer(mesh, k=2)
args = [g(a) for a in (base, mask, cur, mask)]
gcfg = {k: g(v) for k, v in cfg.items()}
out, total, top_v, top_idx = run(*args, gcfg)
from jax.experimental import multihost_utils as mh
flags = np.asarray(mh.process_allgather(out["unhealthy"], tiled=True))
print("FLEET_FLAGS", "".join("U" if f else "h" for f in flags),
      "TOTAL", total, "TOPIDX", sorted(int(i) for i in np.asarray(top_idx)[:2]),
      flush=True)
assert total == 2, total
assert list(flags) == [False, True, False, True], flags
"""


@pytest.mark.slow
def test_two_process_fleet_scorer_over_coordinator():
    """The ACTUAL sharded fleet program (make_fleet_scorer: vmapped verdicts
    + psum unhealthy-count + all-gathered top-k) across two OS processes —
    the full multi-pod scoring path, shrunk to 2 CPU procs over DCN."""
    combined = _run_two_workers(_SCORER_WORKER, 240, "fleet-scorer DCN")
    # both ranks agree: rows 1 and 3 unhealthy, fleet total 2, top-k global
    assert combined.count("FLEET_FLAGS hUhU TOTAL 2 TOPIDX [1, 3]") == 2, \
        combined[-2000:]
