"""Multi-process (DCN) smoke test: two OS processes join one JAX world
through parallel.distributed.initialize and run a psum whose operands
live in different processes.

This is the boundary the 8-device virtual mesh cannot reach: that mesh
is one process, so its collectives never cross a process gap. Here the
coordinator handshake, the global device view (2 processes x 1 CPU
device), make_array_from_process_local_data, and a cross-process psum
all run for real — the same code path a TPU pod uses over DCN
(SURVEY.md §2.8), shrunk to two local CPU processes.

Skips gracefully when the installed jax cannot serve cross-process CPU
collectives (the capability, not our wiring, is what varies by build).
"""
from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import jax
import jax.numpy as jnp
import numpy as np
from functools import partial
from jax.sharding import NamedSharding, PartitionSpec as P

from foremast_tpu.parallel import distributed as D
from foremast_tpu.parallel.mesh import FLEET_AXIS

did_init = D.initialize()  # env contract: COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID
assert did_init, "initialize() must join the 2-process world"
assert jax.process_count() == 2, jax.process_count()

info = D.host_info()
assert info.num_processes == 2
assert info.global_devices == 2, info.global_devices

mesh = D.global_fleet_mesh()
global_batch = 4
sl = D.process_batch_slice(global_batch, info)
full = np.arange(1.0, global_batch + 1.0, dtype=np.float32)  # 1+2+3+4 = 10
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P(FLEET_AXIS)), full[sl], (global_batch,)
)

@partial(jax.shard_map, mesh=mesh, in_specs=P(FLEET_AXIS), out_specs=P())
def total(x):
    return jax.lax.psum(jnp.sum(x), FLEET_AXIS)

out = jax.jit(total)(arr)
print("PSUM_TOTAL", float(out), flush=True)
assert float(out) == 10.0, float(out)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_psum_over_coordinator():
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        # one CPU device per process: the world is 2 devices across 2 procs
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["NUM_PROCESSES"] = "2"
        env["PROCESS_ID"] = str(rank)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=os.path.dirname(os.path.dirname(__file__)),
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("DCN smoke workers timed out (coordinator handshake hung)")
    combined = "\n\n".join(outs)
    if any(p.returncode != 0 for p in procs):
        lowered = combined.lower()
        if "unimplemented" in lowered or "not supported" in lowered:
            pytest.skip(f"cross-process CPU collectives unavailable: "
                        f"{combined[-500:]}")
        pytest.fail(f"DCN smoke failed:\n{combined[-4000:]}")
    # both ranks computed the same global reduction over DCN
    assert combined.count("PSUM_TOTAL 10.0") == 2, combined[-2000:]
