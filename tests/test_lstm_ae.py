"""LSTM autoencoder: trains on healthy windows, flags anomalous ones."""
import jax
import numpy as np
import pytest

from foremast_tpu.models import lstm_ae


@pytest.fixture(scope="module")
def trained():
    rng = np.random.default_rng(0)
    B, T, F = 32, 24, 4
    # healthy multivariate pattern: correlated sinusoids + noise
    t = np.arange(T)
    base = np.stack(
        [np.sin(t * 0.3), np.cos(t * 0.3), 0.5 * np.sin(t * 0.3), np.ones(T)], -1
    )
    x = (base[None] + rng.normal(0, 0.05, (B, T, F))).astype(np.float32)
    mask = np.ones((B, T, F), bool)
    model = lstm_ae.LstmAutoencoder(hidden=32, latent=16, features=F)
    state, tx = lstm_ae.init_state(model, jax.random.PRNGKey(0), T, lr=5e-3)
    state, loss = lstm_ae.train(model, state, tx, x, mask, epochs=200)
    return model, state, x, mask, float(loss)


def test_training_reduces_loss(trained):
    model, state, x, mask, final_loss = trained
    assert final_loss < 0.05, final_loss


def test_anomaly_scores_separate_bad_windows(trained):
    model, state, x, mask, _ = trained
    rng = np.random.default_rng(1)
    mu, sigma = lstm_ae.fit_score_normalizer(state.params, x, mask, model.apply)
    # anomalous: one metric decorrelates violently (error spike pattern)
    bad = x.copy()
    bad[:, :, 1] += rng.normal(3.0, 1.0, bad.shape[:2])
    s_h = np.asarray(
        lstm_ae.anomaly_scores(state.params, x, mask, mu, sigma, model.apply)
    )
    s_b = np.asarray(
        lstm_ae.anomaly_scores(state.params, bad, mask, mu, sigma, model.apply)
    )
    assert np.median(s_h) < 3.0
    assert np.min(s_b) > 3.0  # every corrupted window flagged


def test_masked_steps_do_not_contribute(trained):
    model, state, x, mask, _ = trained
    mu, sigma = lstm_ae.fit_score_normalizer(state.params, x, mask, model.apply)
    # corrupt ONLY masked-out steps: score must stay healthy
    x2 = x.copy()
    m2 = mask.copy()
    m2[:, 5:8, :] = False
    x2[:, 5:8, :] = 99.0
    s = np.asarray(
        lstm_ae.anomaly_scores(state.params, x2, m2, mu, sigma, model.apply)
    )
    # reconstruction error is masked there; scores stay moderate (the model
    # still *sees* the garbage through inputs, so allow slack but not 99-level)
    assert np.median(s) < 10.0


def test_param_shardings_tensor_parallel_train_and_score():
    """tp x dp: gate matmuls column-sharded over the model axis, batch over
    fleet; one train step + scoring run under GSPMD on the 8-device mesh."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from foremast_tpu.models import lstm_ae
    from foremast_tpu.parallel.mesh import FLEET_AXIS, MODEL_AXIS, fleet_mesh

    mesh = fleet_mesh(jax.devices(), model_parallel=2)
    model = lstm_ae.LstmAutoencoder(hidden=16, latent=8, features=3)
    state, tx = lstm_ae.init_state(model, jax.random.PRNGKey(0), T=16)
    shardings = lstm_ae.param_shardings(state.params, mesh)

    leaves = jax.tree_util.tree_leaves_with_path(state.params)
    shard_leaves = {jax.tree_util.keystr(k): s for k, s in
                    jax.tree_util.tree_leaves_with_path(shardings)}
    n_sharded = 0
    for path, leaf in leaves:
        spec = shard_leaves[jax.tree_util.keystr(path)].spec
        if leaf.ndim >= 2 and leaf.shape[-1] % 2 == 0 and leaf.shape[-1] >= 8:
            assert spec[-1] == MODEL_AXIS, (path, leaf.shape, spec)
            n_sharded += 1
        else:
            # narrow heads (e.g. the F-wide reconstruction kernel) replicate
            assert all(s is None for s in spec), (path, leaf.shape, spec)
    assert n_sharded >= 3  # encoder/decoder gates + a Dense head

    params = jax.device_put(state.params, shardings)
    opt_state = tx.init(params)
    rng = np.random.default_rng(0)
    B = 8
    x = jax.device_put(
        np.asarray(rng.normal(size=(B, 16, 3)), np.float32),
        NamedSharding(mesh, P(FLEET_AXIS)),
    )
    m = jax.device_put(np.ones((B, 16, 3), bool), NamedSharding(mesh, P(FLEET_AXIS)))
    params, opt_state, loss = lstm_ae.train_step(
        params, opt_state, x, m, model.apply, tx
    )
    assert np.isfinite(float(loss))
    errs = lstm_ae.reconstruction_errors(params, x, m, model.apply)
    assert np.asarray(errs).shape == (B,)
    # tensor-sharded execution must be numerically equivalent to the
    # replicated one for the SAME parameters (GSPMD partitioning check)
    params_repl = jax.device_put(
        jax.tree_util.tree_map(np.asarray, params), NamedSharding(mesh, P())
    )
    errs_repl = lstm_ae.reconstruction_errors(params_repl, x, m, model.apply)
    np.testing.assert_allclose(np.asarray(errs), np.asarray(errs_repl),
                               rtol=1e-5, atol=1e-6)


def test_fleet_scoring_matches_per_job():
    """anomaly_scores_fleet (one launch, stacked params) must reproduce
    per-job anomaly_scores exactly — it is the same computation with a
    vmapped job axis."""
    import numpy as np

    from foremast_tpu.models import lstm_ae

    J, K, W, F = 5, 3, 12, 3
    model = lstm_ae.LstmAutoencoder(hidden=8, latent=4, features=F)
    rng = np.random.default_rng(0)
    params_list, mus, sds = [], [], []
    X = rng.normal(0, 1, (J, K, W, F)).astype(np.float32)
    M = rng.random((J, K, W, F)) > 0.1
    for j in range(J):
        state, _ = lstm_ae.init_state(model, jax.random.PRNGKey(j), T=W)
        params_list.append(state.params)
        mus.append(0.5 + 0.1 * j)
        sds.append(1.0 + 0.05 * j)
    import jax.numpy as jnp

    pstack = jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)
    zs_fleet = np.asarray(lstm_ae.anomaly_scores_fleet(
        pstack, X, M, np.asarray(mus, np.float32),
        np.asarray(sds, np.float32), model.apply))
    for j in range(J):
        zs_one = np.asarray(lstm_ae.anomaly_scores(
            params_list[j], X[j], M[j], mus[j], sds[j], model.apply))
        np.testing.assert_allclose(zs_fleet[j], zs_one, rtol=2e-5, atol=1e-5)


def test_train_fleet_matches_per_job_training():
    """Batched training (one vmapped loop for J same-shape jobs) must
    reproduce the per-job path: same deterministic init, same adam
    updates, so per-job slices equal sequentially-trained params."""
    import numpy as np

    J, K, W, F = 4, 8, 12, 3
    model = lstm_ae.LstmAutoencoder(hidden=8, latent=4, features=F)
    rng = np.random.default_rng(1)
    X = rng.normal(0, 1, (J, K, W, F)).astype(np.float32)
    M = rng.random((J, K, W, F)) > 0.1
    ps, mus, sds = lstm_ae.train_fleet(model, jax.random.PRNGKey(0), X, M,
                                       epochs=4)
    for j in range(J):
        st, tx = lstm_ae.init_state(model, jax.random.PRNGKey(0), T=W)
        st, _ = lstm_ae.train(model, st, tx, X[j], M[j], epochs=4)
        mu, sd = lstm_ae.fit_score_normalizer(st.params, X[j], M[j],
                                              model.apply)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
            jax.tree.map(lambda a: a[j], ps), st.params)
        np.testing.assert_allclose(float(mus[j]), float(mu), rtol=1e-3)
        np.testing.assert_allclose(float(sds[j]), float(sd), rtol=1e-3)
