"""Fleet scoring on the 8-device virtual CPU mesh."""
import jax
import numpy as np
import pytest

from foremast_tpu.parallel import fleet_mesh, make_fleet_scorer, pad_to_multiple
from foremast_tpu.parallel import fleet as fl


def _fleet_batch(B=64, T=32, bad_every=8, seed=0):
    """Healthy pairs except every bad_every-th (shifted current)."""
    rng = np.random.default_rng(seed)
    base = rng.normal(10, 1, (B, T)).astype(np.float32)
    cur = rng.normal(10, 1, (B, T)).astype(np.float32)
    bad = np.arange(B) % bad_every == 0
    cur[bad] += 8.0
    bm = np.ones((B, T), bool)
    cm = np.ones((B, T), bool)
    return base, bm, cur, cm, bad


def _cfg(B):
    return {
        # decisive threshold: with dozens of healthy pairs, a 1-5% per-pair
        # false-positive rate would (correctly) flag some by chance
        "pvalue_threshold": np.full(B, 1e-4, np.float32),
        "test_mask": np.full(B, fl.TEST_MANN_WHITNEY | fl.TEST_KRUSKAL, np.int32),
        "combine": np.full(B, fl.COMBINE_ANY, np.int32),
        "ma_window": np.full(B, 30, np.int32),
        "band_threshold": np.full(B, 2.0, np.float32),
        "bound_mode": np.full(B, 3, np.int32),
        "min_lower_bound": np.full(B, -np.inf, np.float32),
        "min_points": np.tile(np.asarray([20, 20, 5], np.int32), (B, 1)),
    }


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = fleet_mesh()
    assert mesh.shape["fleet"] == 8


def test_score_pairs_flags_bad_pairs():
    B = 32
    base, bm, cur, cm, bad = _fleet_batch(B)
    cfg = _cfg(B)
    out = fl.score_pairs(
        base, bm, cur, cm,
        cfg["pvalue_threshold"], cfg["test_mask"], cfg["combine"],
        cfg["ma_window"], cfg["band_threshold"], cfg["bound_mode"],
        cfg["min_lower_bound"], cfg["min_points"],
    )
    got = np.asarray(out["unhealthy"])
    np.testing.assert_array_equal(got, bad)


def test_fleet_scorer_end_to_end_sharded():
    mesh = fleet_mesh()
    B = 64
    base, bm, cur, cm, bad = _fleet_batch(B)
    run = make_fleet_scorer(mesh, k=8)
    out, total, top_v, top_idx = run(base, bm, cur, cm, _cfg(B))
    assert total == int(bad.sum())
    # every reported top index is a genuinely bad pair
    tv = np.asarray(top_v)
    ti = np.asarray(top_idx)
    real = tv > -np.inf
    assert real.sum() == min(8, bad.sum())
    assert all(bad[i] for i in ti[real])


def test_fleet_scorer_rejects_undivisible_batch():
    mesh = fleet_mesh()
    base, bm, cur, cm, _ = _fleet_batch(60)
    run = make_fleet_scorer(mesh)
    with pytest.raises(ValueError):
        run(base, bm, cur, cm, _cfg(60))


def test_pad_to_multiple_roundtrip():
    base, bm, cur, cm, _ = _fleet_batch(60)
    (pb, pbm), B0 = pad_to_multiple([base, bm], 8)
    assert pb.shape[0] == 64 and B0 == 60
    assert not pbm[60:].any()  # padding is fully masked


def test_fleet_summary_standalone():
    mesh = fleet_mesh()
    B = 64
    unhealthy = np.zeros(B, bool)
    unhealthy[[3, 17, 42]] = True
    sev = np.zeros(B, np.float32)
    sev[[3, 17, 42]] = [5.0, 9.0, 7.0]
    total, tv, ti = fl.fleet_summary(unhealthy, sev, mesh, k=4)
    assert int(total) == 3
    got = [int(i) for i, v in zip(np.asarray(ti), np.asarray(tv)) if v > -np.inf]
    assert got == [17, 42, 3]  # severity-descending


def test_friedman_bit_in_fused_verdict():
    """ML_PAIRWISE_ALGORITHM=friedman drives the verdict through the paired
    Friedman member of the family (design.md:89-92)."""
    import numpy as np

    from foremast_tpu.engine.config import EngineConfig
    from foremast_tpu.parallel import fleet as fl

    assert EngineConfig(pairwise_algorithm="friedman_all").enabled_tests() \
        == fl.TEST_FRIEDMAN
    assert EngineConfig(pairwise_algorithm="all").enabled_tests() & fl.TEST_FRIEDMAN

    rng = np.random.default_rng(0)
    B, T = 4, 64
    baseline = rng.normal(10.0, 1.0, (B, T)).astype(np.float32)
    # rows 0,1: current consistently above baseline; rows 2,3: same dist
    current = baseline + np.array([3.0, 3.0, 0.0, 0.0])[:, None] \
        + rng.normal(0, 0.2, (B, T)).astype(np.float32)
    masks = np.ones((B, T), bool)
    out = fl.score_pairs(
        baseline, masks, current.astype(np.float32), masks,
        np.full(B, 0.01, np.float32),
        np.full(B, fl.TEST_FRIEDMAN, np.int32),
        np.zeros(B, np.int32),
        np.full(B, 10, np.int32),
        np.full(B, 30.0, np.float32),  # very wide band: pairwise decides
        np.zeros(B, np.int32),
        np.zeros(B, np.float32),
        np.tile(np.asarray([20, 20, 5], np.int32), (B, 1)),
    )
    pw = np.asarray(out["pairwise_unhealthy"])
    assert pw.tolist() == [True, True, False, False]
    # too few paired blocks -> friedman gated out, healthy by default
    few = np.zeros((1, T), bool)
    few[:, :3] = True
    out2 = fl.score_pairs(
        baseline[:1], few, current[:1].astype(np.float32), few,
        np.full(1, 0.01, np.float32), np.full(1, fl.TEST_FRIEDMAN, np.int32),
        np.zeros(1, np.int32), np.full(1, 10, np.int32),
        np.full(1, 30.0, np.float32), np.zeros(1, np.int32),
        np.zeros(1, np.float32), np.tile(np.asarray([20, 20, 5], np.int32), (1, 1)),
    )
    assert not bool(np.asarray(out2["pairwise_unhealthy"])[0])


def test_min_friedman_points_config_wired():
    """MIN_FRIEDMAN_DATA_POINTS reaches the kernel: the analyzer passes a
    4-wide min_points vector, and raising the gate above the available block
    count disables the Friedman member (advisor round 1: the fifth test
    silently fell back to the MIN_FRIEDMAN constant)."""
    import numpy as np

    from foremast_tpu.engine.config import from_env
    from foremast_tpu.parallel import fleet as fl

    cfg = from_env({"MIN_FRIEDMAN_DATA_POINTS": "12"})
    assert cfg.min_friedman_points == 12

    # 8 clean paired blocks, strongly shifted: friedman fires at gate<=8,
    # is gated out at gate>8. Baseline must be non-constant (sigma>0) so the
    # huge band_threshold actually disables the band detector.
    B, T = 1, 8
    rng = np.random.default_rng(0)
    base = rng.normal(10.0, 1.0, (B, T)).astype(np.float32)
    cur = base + 5.0
    ones = np.ones((B, T), bool)

    def verdict(gate):
        out = fl.score_pairs(
            base, ones, cur, ones,
            np.full(B, 0.05, np.float32),
            np.full(B, fl.TEST_FRIEDMAN, np.int32),
            np.zeros(B, np.int32),
            np.full(B, 4, np.int32),
            np.full(B, 1e9, np.float32),  # band never fires
            np.zeros(B, np.int32),
            np.zeros(B, np.float32),
            np.tile(np.asarray([20, 20, 5, gate], np.int32), (B, 1)),
        )
        return bool(np.asarray(out["unhealthy"])[0])

    assert verdict(8) is True   # 8/8 wins: exact p = 2*(1/2)^8 ~ 0.0078 < 0.05
    assert verdict(9) is False  # gated: not enough blocks -> cannot judge


def test_verdict_program_lowers_without_scatters():
    """Scatters serialize on TPU; the round-3 sorted-space redesign removed
    every one from the fleet-scoring program (docs/benchmarks.md 'Kernel
    optimization'). Pin it: a reintroduced segment op or .at[].set in any
    sub-kernel shows up as a scatter in the lowered HLO."""
    import jax

    B, T = 8, 32
    rng = np.random.default_rng(0)
    args = (
        rng.normal(10, 2, (B, T)).astype(np.float32),
        rng.random((B, T)) > 0.05,
        rng.normal(10, 2, (B, T)).astype(np.float32),
        rng.random((B, T)) > 0.05,
        np.full(B, 0.01, np.float32), np.full(B, 0b1111, np.int32),
        np.zeros(B, np.int32), np.full(B, 10, np.int32),
        np.full(B, 3.0, np.float32), np.zeros(B, np.int32),
        np.zeros(B, np.float32),
        np.tile(np.asarray([20, 20, 5], np.int32), (B, 1)),
    )
    hlo = jax.jit(jax.vmap(fl._pair_verdict)).lower(*args).as_text()
    assert "scatter" not in hlo, "a scatter crept back into the verdict program"


def test_moving_average_band_lowers_with_one_batched_gather_at_most():
    """The MA band's per-element dynamic lookups (the old csum[lo], ma[t0],
    x[idx] — 3-4 gathers of computed indices) were rewritten as rolls and
    associative hold-last scans. The one remaining gather is the vmapped
    dynamic roll itself: a batched contiguous row-shift (ma_window is
    per-pair), a fundamentally cheaper access pattern. Pin the ceiling so
    a reintroduced per-element index shows up as a count regression."""
    import jax

    from foremast_tpu.ops import forecast as fc

    B, T = 8, 32
    rng = np.random.default_rng(0)
    x = rng.normal(10, 2, (B, T)).astype(np.float32)
    m = rng.random((B, T)) > 0.3
    w = np.full(B, 10, np.int32)
    f = jax.jit(jax.vmap(fc._moving_average_1d))
    hlo = f.lower(x, m, w).as_text()
    assert "scatter" not in hlo
    # quote-insensitive: the StableHLO printer may emit the op in quoted
    # generic or pretty form; counting the bare name survives both, so the
    # pin cannot vacuously pass on printer-format drift
    # upper bound only: the regression this pin guards is gather growth
    # (per-element indexing reintroduced); an XLA improvement lowering the
    # batched roll without any gather should pass, not fail
    n_gather = hlo.count("stablehlo.gather")
    # jax < 0.5 lowers the batched roll through two extra gathers (4
    # total); the per-element regression this pin guards produces O(T)
    # of them, so the looser legacy bound still catches it
    legacy_jax = tuple(int(p) for p in jax.__version__.split(".")[:2]) < (0, 5)
    bound = 4 if legacy_jax else 2
    assert n_gather <= bound, n_gather  # the batched roll, possibly quoted+typed
