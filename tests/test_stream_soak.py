"""Streaming-ingest soaks (`make soak-stream`, ISSUEs 12 + 14):

  * push + poll interleaved against a LIVE runtime under chaos latency
    and a hard blackout — pushed jobs keep stream-scoring while polled
    jobs ride the degraded-mode machinery, DEGRADED -> OK over the wire;
  * the two-replica distributed-trace acceptance (ISSUE 14): a push
    sent to the NON-owner replica produces ONE trace whose spans name
    both replicas — the forward hop a child on the origin's trace, the
    scoring replica's receive/verdict spans parented under it — with
    `explain` on the scoring replica carrying the same trace_id.

Flight-dump artifacts are written by the runtime's own recorder on
failure; these soaks additionally dump each replica's /debug/traces ring
and detection-stage histogram lines to /tmp/foremast-traces-*.json (the
CI soak job uploads both families).

Marked slow+chaos so tier-1 (-m 'not slow') stays fast.
"""
import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from foremast_tpu.dataplane.delta import parse_range_params
from foremast_tpu.dataplane.fetch import FetchError, RawFixtureDataSource
from foremast_tpu.engine import Document, EngineConfig, MetricQueries
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.ingest import encode_remote_write, snappy_compress
from foremast_tpu.runtime import Runtime
from foremast_tpu.utils.timeutils import to_rfc3339

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

SEED = 20260812
STEP = 60

# chaos latency throughout (spikes early, a low-rate hung socket) — the
# BLACKOUT itself is the test-driven brownout of the poll jobs' store
# shard below, so its phases are deterministic rather than call-counted
CHAOS_SPEC = (
    f"seed={SEED};"
    "fetch.spike=0..10:0.01;"
    "fetch.hang=0.02:0.03"
)


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_for(predicate, budget_s, interval=0.1):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        try:
            if predicate():
                return True
        except Exception:  # noqa: BLE001 - booting replicas refuse/404
            pass
        time.sleep(interval)
    return False


def _dump_trace_artifacts(name: str, bases):
    """On soak failure: persist each replica's /debug/traces ring and
    its detection-latency/stage histogram lines next to the flight
    dumps, so the CI soak job uploads the trace evidence an operator
    would want for the incident (satellite: ISSUE 14)."""
    out = {}
    for base in bases:
        entry = {}
        try:
            code, traces = _get(f"{base}/debug/traces?limit=100")
            entry["traces"] = json.loads(traces) if code == 200 else None
            code, metrics = _get(f"{base}/metrics")
            entry["stage_histograms"] = [
                ln for ln in metrics.decode().splitlines()
                if "detection_stage_seconds" in ln
                or "detection_latency_seconds" in ln
            ] if code == 200 else []
        except Exception as e:  # noqa: BLE001 - dead replica: note it
            entry["error"] = repr(e)
        out[base] = entry
    try:
        with open(f"/tmp/foremast-traces-{name}.json", "w") as f:
            json.dump(out, f, indent=1)
    except OSError:
        pass


def test_stream_soak_push_scores_through_blackout(tmp_path):
    rng = np.random.default_rng(SEED)
    now0 = int(time.time()) // STEP * STEP
    t0 = now0 - 60 * STEP
    series = {}
    for jid in ("pushed", "poll0", "poll1"):
        series[f"{jid}/cur"] = [
            (t0 + k * STEP, round(float(rng.normal(5.0, 0.2)), 4))
            for k in range(60)]
        series[f"{jid}/hist"] = [
            (t0 - 500 * STEP + k * STEP,
             round(float(rng.normal(5.0, 0.2)), 4))
            for k in range(560)]

    # the brownout: the store shard serving the POLL jobs' series goes
    # dark mid-soak (test-driven, deterministic); the pushed job's
    # series live on a separate healthy shard — and its CURRENT window
    # needs no shard at all once pushes feed the delta cache
    outage = {"on": False}

    def resolver(url: str) -> bytes:
        parts = url.split("?", 1)[0].rsplit("/", 2)
        name = parts[-2] + "/" + parts[-1]
        if outage["on"] and "//prom-poll" in url:
            raise FetchError("store shard down (soak brownout)")
        qs, qe, _ = parse_range_params(url)
        samples = [(t, v) for t, v in series.get(name, [])
                   if qs <= t <= qe]
        return json.dumps({
            "status": "success",
            "data": {"resultType": "matrix", "result": [
                {"metric": {"__name__": "m"},
                 "values": [[t, str(v)] for t, v in samples]}]},
        }).encode()

    archive = FileArchive(str(tmp_path / "archive.jsonl"))
    rt = Runtime(
        config=EngineConfig(
            fetch_concurrency=2,
            max_stuck_seconds=1e9,
            retry_max_attempts=2,
            retry_base_delay=0.001,
            retry_max_delay=0.01,
            breaker_failure_threshold=3,
            breaker_recovery_seconds=0.1,
            fetch_cycle_deadline_seconds=2.0,
        ),
        data_source=RawFixtureDataSource(resolver=resolver),
        cache=False,  # the TTL cache would hide the brownout from jobs
        archive=archive,
        chaos_spec=CHAOS_SPEC,
        ingest_debounce_ms=20.0,
    )

    def url(host, name, s, e):
        return (f"http://{host}:9090/{name}"
                f"?query=x&start={s:.0f}&end={e:.0f}&step={STEP}")

    for jid in ("pushed", "poll0", "poll1"):
        host = "prom-push" if jid == "pushed" else "prom-poll"
        rt.store.create(Document(
            id=jid, app_name=f"app-{jid}", namespace="soak",
            strategy="canary",
            start_time=to_rfc3339(t0), end_time=to_rfc3339(now0 + 86400),
            metrics={"error5xx": MetricQueries(
                current=url(host, f"{jid}/cur", t0, now0 + 86400),
                historical=url(host, f"{jid}/hist",
                               t0 - 500 * STEP, t0))},
        ))

    rt.start(host="127.0.0.1", port=0, cycle_seconds=0.3)
    pusher_stop = threading.Event()
    push_errors: list = []
    bases: list = []
    try:
        port = rt._server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        bases.append(base)

        def readyz_state():
            _, payload = _get(f"{base}/readyz")
            return json.loads(payload)["state"]

        def prov_path(jid):
            _, payload = _get(f"{base}/jobs/{jid}/explain")
            return (json.loads(payload).get("provenance") or {}).get(
                "path", "")

        # a pusher thread streams one fresh on-grid sample per tick for
        # the pushed job, remote-write over the real HTTP endpoint
        def pusher():
            k = 0
            while not pusher_stop.is_set():
                k += 1
                ts = float(now0 + k * STEP)
                val = round(float(5.0 + 0.01 * k), 4)
                series["pushed/cur"].append((ts, val))
                raw = snappy_compress(encode_remote_write([(
                    {"foremast_job": "pushed",
                     "foremast_metric": "error5xx"}, [(ts, val)])]))
                req = urllib.request.Request(
                    f"{base}/ingest/remote-write", data=raw,
                    headers={"Content-Type": "application/x-protobuf",
                             "Content-Encoding": "snappy"},
                    method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=5) as r:
                        if r.status != 200:
                            push_errors.append(r.status)
                except Exception as e:  # noqa: BLE001 - soak records
                    push_errors.append(repr(e))
                pusher_stop.wait(0.25)

        # let the poll loop warm every window first, then start pushing
        assert _wait_for(lambda: prov_path("poll0") != "", 30.0)
        t_push = threading.Thread(target=pusher, daemon=True)
        t_push.start()

        # phase 1: the poll shard goes dark — the POLLED path degrades
        # (stale serving / fetch retries), visible over the wire
        outage["on"] = True
        assert _wait_for(lambda: readyz_state() == "degraded", 45.0), \
            readyz_state()
        # ... while the PUSHED job keeps producing fresh stream-scored
        # verdicts with its windows served from the push-fed cache
        assert _wait_for(
            lambda: prov_path("pushed") == "stream-scored", 30.0), \
            prov_path("pushed")
        _, payload = _get(f"{base}/status")
        status_doc = json.loads(payload)
        assert status_doc["ingest"]["samples"]["remote_write"] >= 1
        assert status_doc["scheduler"]["partial_cycles"] >= 1
        assert status_doc["delta_fetch"]["ingest_hits"] >= 1

        # phase 2: the shard comes back; health recovers OK
        outage["on"] = False
        assert _wait_for(lambda: readyz_state() == "ok", 60.0), \
            readyz_state()
        # polled jobs are back to fresh verdicts and nothing was lost
        _, payload = _get(f"{base}/status")
        jobs = json.loads(payload)["jobs"]
        assert sum(jobs.values()) == 3
        # the soak's pushes were all accepted (429/5xx would show here)
        assert not push_errors, push_errors[:5]

        # ingest metrics render under the scrape grammar content type
        code, metrics = _get(f"{base}/metrics")
        assert code == 200
        body = metrics.decode()
        assert "foremastbrain:ingest_samples_total" in body
        assert "foremastbrain:partial_cycles_total" in body
    except BaseException:
        _dump_trace_artifacts("stream-blackout", bases)
        raise
    finally:
        pusher_stop.set()
        rt.stop()
    # graceful stop released the leases for peer adoption
    assert rt.store.lease_releases_total >= 0

# ===================================================================
# Two-replica push-to-verdict trace (ISSUE 14 acceptance): REAL runtime
# subprocesses over one shared archive, so each replica has its own
# tracer ring, its own /debug/traces, and its own resource identity.
# ===================================================================
def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _TraceBackend:
    """Threaded HTTP Prometheus stand-in shared by both replicas;
    serves /<job>/<cur|hist>?start=&end= from mutable series."""

    def __init__(self):
        self.series: dict[str, list] = {}
        self.lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802 - stdlib API
                pass

            def do_GET(self):  # noqa: N802 - stdlib API
                parts = self.path.split("?", 1)[0].strip("/").split("/")
                name = "/".join(parts[-2:])
                rng = parse_range_params(self.path)
                with outer.lock:
                    samples = [
                        (t, v) for t, v in outer.series.get(name, [])
                        if rng is None or rng[0] <= t <= rng[1]]
                body = json.dumps({
                    "status": "success",
                    "data": {"resultType": "matrix", "result": [
                        {"metric": {"__name__": "m"},
                         "values": [[t, str(v)] for t, v in samples]}]},
                }).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()

    def close(self):
        self.server.shutdown()


_TRACE_CHILD = textwrap.dedent("""
    import signal, sys
    from foremast_tpu.engine import EngineConfig
    from foremast_tpu.engine.archive import FileArchive
    from foremast_tpu.runtime import Runtime

    replica, port, archive_path = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3])
    rt = Runtime(
        config=EngineConfig(
            fetch_concurrency=2, max_stuck_seconds=1e9,
            retry_max_attempts=2, retry_base_delay=0.01,
            retry_max_delay=0.05, fetch_cycle_deadline_seconds=4.0),
        archive=FileArchive(archive_path),
        replica_id=replica,
        heartbeat_seconds=0.5,
        member_ttl_seconds=3.0,
        adopt_interval_seconds=1.0,
        ingest_advertise_addr=f"http://127.0.0.1:{port}",
        ingest_debounce_ms=20.0,
    )
    signal.signal(signal.SIGTERM, lambda *_: rt.request_stop())
    rt.run_forever(host="127.0.0.1", port=port, cycle_seconds=0.4)
""")


def _spawn_replica(tmp_path, replica, port, archive_path):
    script = tmp_path / "trace_replica.py"
    if not script.exists():
        script.write_text(_TRACE_CHILD)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FLIGHT_DUMP_DIR=str(tmp_path / "dumps"),
               PYTHONPATH=os.pathsep.join(
                   p for p in (repo_root, os.environ.get("PYTHONPATH"))
                   if p))
    return subprocess.Popen(
        [sys.executable, str(script), replica, str(port), archive_path],
        env=env, stdout=open(tmp_path / f"{replica}.log", "ab"),
        stderr=subprocess.STDOUT)


def _post_json(url, body, timeout=5.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_two_replica_push_to_verdict_single_trace(tmp_path):
    """A push sent to the NON-owner replica produces ONE trace: the
    origin's receive span with the forward hop as its child, the
    scoring replica's receive span remote-parented under that hop
    (naming the origin replica), a verdict span closing the trace at
    fold with a waterfall carrying forward_hop — and `explain` on the
    scoring replica reports the same trace_id the push response
    returned."""
    be = _TraceBackend()
    now0 = int(time.time()) // STEP * STEP
    t0 = now0 - 60 * STEP
    n_jobs = 10
    for i in range(n_jobs):
        be.series[f"j{i}/cur"] = [
            (t0 + k * STEP, round(5.0 + 0.01 * k, 4)) for k in range(60)]
        be.series[f"j{i}/hist"] = [
            (t0 - 500 * STEP + k * STEP, round(5.0 + 0.01 * (k % 60), 4))
            for k in range(560)]

    def url(name, s, e):
        return (f"http://127.0.0.1:{be.port}/{name}"
                f"?query=x&start={s:.0f}&end={e:.0f}&step={STEP}")

    def create_body(i):
        return {
            "appName": f"app-{i}", "namespace": "soak",
            "strategy": "canary",
            "startTime": to_rfc3339(t0),
            "endTime": to_rfc3339(now0 + 86400),
            "metricsInfo": {
                "current": {"error5xx": {
                    "url": url(f"j{i}/cur", t0, now0 + 86400)}},
                "historical": {"error5xx": {
                    "url": url(f"j{i}/hist", t0 - 500 * STEP, t0)}},
            },
        }

    archive_path = str(tmp_path / "archive.jsonl")
    pa, pb = _free_port(), _free_port()
    base_a, base_b = f"http://127.0.0.1:{pa}", f"http://127.0.0.1:{pb}"
    proc_a = _spawn_replica(tmp_path, "rep-a", pa, archive_path)
    proc_b = _spawn_replica(tmp_path, "rep-b", pb, archive_path)
    k_push = [0]

    def explain(base, jid):
        code, payload = _get(f"{base}/jobs/{jid}/explain")
        if code != 200:
            return {}
        return json.loads(payload).get("provenance") or {}

    def push_to_a(jid, i):
        """One fresh on-grid sample for job `jid`, addressed, pushed to
        replica A (backend updated first — it stays source of truth)."""
        k_push[0] += 1
        ts = float(now0 + k_push[0] * STEP)
        v = round(5.0 + 0.01 * k_push[0], 4)
        with be.lock:
            be.series[f"j{i}/cur"].append((ts, v))
        raw = snappy_compress(encode_remote_write([(
            {"foremast_job": jid, "foremast_metric": "error5xx"},
            [(ts, v)])]))
        req = urllib.request.Request(
            f"{base_a}/ingest/remote-write", data=raw,
            headers={"Content-Type": "application/x-protobuf",
                     "Content-Encoding": "snappy"}, method="POST")
        with urllib.request.urlopen(req, timeout=5) as r:
            return json.loads(r.read())

    try:
        # both replicas up + mutual membership (2 fresh rows on /fleet)
        def fleet_fresh():
            _, payload = _get(f"{base_a}/fleet")
            doc = json.loads(payload)
            return doc.get("aggregate", {}).get("replicas_fresh") == 2

        assert _wait_for(fleet_fresh, 60.0), "membership never converged"

        job_ids = {}
        for i in range(n_jobs):
            _, resp = _post_json(f"{base_a}/v1/healthcheck/create",
                                 create_body(i))
            job_ids[i] = resp["jobId"]

        # wait until some job is owned AND scored by B (live provenance
        # record whose cycle worker is rep-b)
        def b_owned_job():
            for i, jid in job_ids.items():
                rec = explain(base_b, jid)
                worker = (rec.get("cycle") or {}).get("worker", "")
                if rec.get("path") and worker == "rep-b":
                    return (i, jid)
            return None

        candidate = _wait_for(lambda: b_owned_job(), 90.0)
        assert candidate, "no job landed on replica B"
        i, jid = b_owned_job()

        # push to the NON-owner (A). A may have pruned its handed-off
        # copy — re-creating the job (deterministic id) restores the
        # routing metadata without changing ownership, then the push
        # forwards one hop to B.
        trace_id = None
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline and trace_id is None:
            _post_json(f"{base_a}/v1/healthcheck/create", create_body(i))
            payload = push_to_a(jid, i)
            if payload.get("forwarded_samples", 0) >= 1:
                trace_id = payload["trace_id"]
                break
            time.sleep(0.5)
        assert trace_id, "push to the non-owner never forwarded"

        # ONE trace on the SCORING replica: receive span remote-parented
        # under the origin's forward hop, naming rep-a
        def b_trace():
            _, payload = _get(
                f"{base_b}/debug/traces?trace_id={trace_id}&limit=100")
            trees = json.loads(payload).get("traces", [])
            recv = [t for t in trees if t["name"] == "ingest.receive"]
            return recv or None

        assert _wait_for(lambda: bool(b_trace()), 30.0), \
            "forwarded push never traced on B"
        b_recv = b_trace()[-1]
        assert b_recv["trace_id"] == trace_id
        assert b_recv["attrs"]["origin_replica"] == "rep-a"
        assert b_recv["attrs"]["replica"] == "rep-b"
        assert (b_recv.get("resource") or {}).get("replica") == "rep-b"
        assert b_recv.get("parent_span_id"), "receive span not parented"

        # ... whose parent is the FORWARD hop on the origin's trace
        _, payload = _get(
            f"{base_a}/debug/traces?trace_id={trace_id}&limit=100")
        a_trees = json.loads(payload)["traces"]
        a_recv = [t for t in a_trees if t["name"] == "ingest.receive"][-1]
        assert (a_recv.get("resource") or {}).get("replica") == "rep-a"
        fwd = [c for c in a_recv.get("children", ())
               if c["name"] == "ingest.forward"]
        assert fwd, "origin trace has no forward hop"
        assert b_recv["parent_span_id"] == fwd[0]["span_id"]

        # the verdict closes the SAME trace on B, waterfall included
        def b_verdict():
            _, payload = _get(
                f"{base_b}/debug/traces?trace_id={trace_id}&limit=100")
            trees = json.loads(payload).get("traces", [])
            return [t for t in trees
                    if t["name"] == "engine.verdict"] or None

        assert _wait_for(lambda: bool(b_verdict()), 45.0), \
            "verdict span never closed the trace on B"
        verdict = b_verdict()[-1]
        assert verdict["attrs"]["job_id"] == jid
        wf = verdict["attrs"]["waterfall"]
        assert "forward_hop" in wf and "score" in wf, wf

        # explain on the scoring replica carries the same trace_id
        def b_explained():
            rec = explain(base_b, jid)
            return rec.get("trace_id") == trace_id

        assert _wait_for(b_explained, 30.0), explain(base_b, jid)
        rec = explain(base_b, jid)
        assert "forward_hop" in rec.get("detection_stages", {})

        # stage histograms are live on the scoring replica's /metrics
        _, metrics = _get(f"{base_b}/metrics")
        assert b"foremastbrain:detection_stage_seconds_bucket" in metrics
    except BaseException:
        _dump_trace_artifacts("two-replica", [base_a, base_b])
        raise
    finally:
        for proc in (proc_a, proc_b):
            try:
                os.kill(proc.pid, signal.SIGTERM)
            except ProcessLookupError:
                pass
        for proc in (proc_a, proc_b):
            try:
                proc.wait(20)
            except subprocess.TimeoutExpired:
                proc.kill()
        be.close()
