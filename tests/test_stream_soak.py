"""Streaming-ingest soak (`make soak-stream`, ISSUE 12): push + poll
interleaved against a LIVE runtime under chaos latency and a hard
blackout. The claim under test: a job whose samples arrive as pushes
keeps scoring through the blackout — its windows come from the push-fed
delta cache, zero backend round-trips — while poll-only jobs ride the
degraded-mode machinery (stale serving) and the health state machine
walks DEGRADED -> OK end to end over the wire. Flight-dump artifacts are
written by the runtime's own recorder on failure (CI uploads them).

Marked slow+chaos so tier-1 (-m 'not slow') stays fast.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from foremast_tpu.dataplane.delta import parse_range_params
from foremast_tpu.dataplane.fetch import FetchError, RawFixtureDataSource
from foremast_tpu.engine import Document, EngineConfig, MetricQueries
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.ingest import encode_remote_write, snappy_compress
from foremast_tpu.runtime import Runtime
from foremast_tpu.utils.timeutils import to_rfc3339

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

SEED = 20260812
STEP = 60

# chaos latency throughout (spikes early, a low-rate hung socket) — the
# BLACKOUT itself is the test-driven brownout of the poll jobs' store
# shard below, so its phases are deterministic rather than call-counted
CHAOS_SPEC = (
    f"seed={SEED};"
    "fetch.spike=0..10:0.01;"
    "fetch.hang=0.02:0.03"
)


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_for(predicate, budget_s, interval=0.1):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def test_stream_soak_push_scores_through_blackout(tmp_path):
    rng = np.random.default_rng(SEED)
    now0 = int(time.time()) // STEP * STEP
    t0 = now0 - 60 * STEP
    series = {}
    for jid in ("pushed", "poll0", "poll1"):
        series[f"{jid}/cur"] = [
            (t0 + k * STEP, round(float(rng.normal(5.0, 0.2)), 4))
            for k in range(60)]
        series[f"{jid}/hist"] = [
            (t0 - 500 * STEP + k * STEP,
             round(float(rng.normal(5.0, 0.2)), 4))
            for k in range(560)]

    # the brownout: the store shard serving the POLL jobs' series goes
    # dark mid-soak (test-driven, deterministic); the pushed job's
    # series live on a separate healthy shard — and its CURRENT window
    # needs no shard at all once pushes feed the delta cache
    outage = {"on": False}

    def resolver(url: str) -> bytes:
        parts = url.split("?", 1)[0].rsplit("/", 2)
        name = parts[-2] + "/" + parts[-1]
        if outage["on"] and "//prom-poll" in url:
            raise FetchError("store shard down (soak brownout)")
        qs, qe, _ = parse_range_params(url)
        samples = [(t, v) for t, v in series.get(name, [])
                   if qs <= t <= qe]
        return json.dumps({
            "status": "success",
            "data": {"resultType": "matrix", "result": [
                {"metric": {"__name__": "m"},
                 "values": [[t, str(v)] for t, v in samples]}]},
        }).encode()

    archive = FileArchive(str(tmp_path / "archive.jsonl"))
    rt = Runtime(
        config=EngineConfig(
            fetch_concurrency=2,
            max_stuck_seconds=1e9,
            retry_max_attempts=2,
            retry_base_delay=0.001,
            retry_max_delay=0.01,
            breaker_failure_threshold=3,
            breaker_recovery_seconds=0.1,
            fetch_cycle_deadline_seconds=2.0,
        ),
        data_source=RawFixtureDataSource(resolver=resolver),
        cache=False,  # the TTL cache would hide the brownout from jobs
        archive=archive,
        chaos_spec=CHAOS_SPEC,
        ingest_debounce_ms=20.0,
    )

    def url(host, name, s, e):
        return (f"http://{host}:9090/{name}"
                f"?query=x&start={s:.0f}&end={e:.0f}&step={STEP}")

    for jid in ("pushed", "poll0", "poll1"):
        host = "prom-push" if jid == "pushed" else "prom-poll"
        rt.store.create(Document(
            id=jid, app_name=f"app-{jid}", namespace="soak",
            strategy="canary",
            start_time=to_rfc3339(t0), end_time=to_rfc3339(now0 + 86400),
            metrics={"error5xx": MetricQueries(
                current=url(host, f"{jid}/cur", t0, now0 + 86400),
                historical=url(host, f"{jid}/hist",
                               t0 - 500 * STEP, t0))},
        ))

    rt.start(host="127.0.0.1", port=0, cycle_seconds=0.3)
    pusher_stop = threading.Event()
    push_errors: list = []
    try:
        port = rt._server.server_address[1]
        base = f"http://127.0.0.1:{port}"

        def readyz_state():
            _, payload = _get(f"{base}/readyz")
            return json.loads(payload)["state"]

        def prov_path(jid):
            _, payload = _get(f"{base}/jobs/{jid}/explain")
            return (json.loads(payload).get("provenance") or {}).get(
                "path", "")

        # a pusher thread streams one fresh on-grid sample per tick for
        # the pushed job, remote-write over the real HTTP endpoint
        def pusher():
            k = 0
            while not pusher_stop.is_set():
                k += 1
                ts = float(now0 + k * STEP)
                val = round(float(5.0 + 0.01 * k), 4)
                series["pushed/cur"].append((ts, val))
                raw = snappy_compress(encode_remote_write([(
                    {"foremast_job": "pushed",
                     "foremast_metric": "error5xx"}, [(ts, val)])]))
                req = urllib.request.Request(
                    f"{base}/ingest/remote-write", data=raw,
                    headers={"Content-Type": "application/x-protobuf",
                             "Content-Encoding": "snappy"},
                    method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=5) as r:
                        if r.status != 200:
                            push_errors.append(r.status)
                except Exception as e:  # noqa: BLE001 - soak records
                    push_errors.append(repr(e))
                pusher_stop.wait(0.25)

        # let the poll loop warm every window first, then start pushing
        assert _wait_for(lambda: prov_path("poll0") != "", 30.0)
        t_push = threading.Thread(target=pusher, daemon=True)
        t_push.start()

        # phase 1: the poll shard goes dark — the POLLED path degrades
        # (stale serving / fetch retries), visible over the wire
        outage["on"] = True
        assert _wait_for(lambda: readyz_state() == "degraded", 45.0), \
            readyz_state()
        # ... while the PUSHED job keeps producing fresh stream-scored
        # verdicts with its windows served from the push-fed cache
        assert _wait_for(
            lambda: prov_path("pushed") == "stream-scored", 30.0), \
            prov_path("pushed")
        _, payload = _get(f"{base}/status")
        status_doc = json.loads(payload)
        assert status_doc["ingest"]["samples"]["remote_write"] >= 1
        assert status_doc["scheduler"]["partial_cycles"] >= 1
        assert status_doc["delta_fetch"]["ingest_hits"] >= 1

        # phase 2: the shard comes back; health recovers OK
        outage["on"] = False
        assert _wait_for(lambda: readyz_state() == "ok", 60.0), \
            readyz_state()
        # polled jobs are back to fresh verdicts and nothing was lost
        _, payload = _get(f"{base}/status")
        jobs = json.loads(payload)["jobs"]
        assert sum(jobs.values()) == 3
        # the soak's pushes were all accepted (429/5xx would show here)
        assert not push_errors, push_errors[:5]

        # ingest metrics render under the scrape grammar content type
        code, metrics = _get(f"{base}/metrics")
        assert code == 200
        body = metrics.decode()
        assert "foremastbrain:ingest_samples_total" in body
        assert "foremastbrain:partial_cycles_total" in body
    finally:
        pusher_stop.set()
        rt.stop()
    # graceful stop released the leases for peer adoption
    assert rt.store.lease_releases_total >= 0