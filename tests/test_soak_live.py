"""Live-runtime chaos soak (`make soak`, ISSUE 4): a short (<120 s) soak
against a REAL runtime process — HTTP server, worker loop, exporter — with
the new chaos fault shapes (latency spikes, hung sockets, an outage burst)
injected under the resilience layer. Asserts the health state machine
degrades and recovers END TO END over the wire (/readyz), stale verdicts
are served during the blackout, and graceful shutdown drains cleanly with
the lease handoff mirrored for peer adoption.

Marked slow+chaos so tier-1 (-m 'not slow') stays fast.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource
from foremast_tpu.engine import Document, EngineConfig, MetricQueries
from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.runtime import Runtime
from foremast_tpu.utils.timeutils import to_rfc3339

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

SEED = 20260805
STEP = 60

# warm cycles first (calls 0..29), then a hard blackout long enough to
# span several cycles, plus latency spikes early and a low-rate hung
# socket throughout — the two new fault shapes, live
CHAOS_SPEC = (
    f"seed={SEED};"
    "fetch.spike=0..10:0.01;"
    "fetch.hang=0.05:0.03;"
    "fetch.outage=30..110"
)


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_for(predicate, budget_s, interval=0.1):
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _series(rng, level, n):
    ts = np.arange(n) * STEP
    vals = np.clip(rng.normal(level, level * 0.1 + 0.01, n), 0, None)
    return ts.tolist(), vals.tolist()


def test_live_runtime_soak_degrades_and_recovers(tmp_path):
    rng = np.random.default_rng(SEED)
    threads_before = threading.active_count()
    fixtures = {}
    archive = FileArchive(str(tmp_path / "archive.jsonl"))
    rt = Runtime(
        config=EngineConfig(
            fetch_concurrency=2,
            max_stuck_seconds=1e9,
            retry_max_attempts=2,
            retry_base_delay=0.001,
            retry_max_delay=0.01,
            # the breaker must keep probing fast enough for the soak's
            # outage window to be consumed and recovery observed live
            breaker_failure_threshold=3,
            breaker_recovery_seconds=0.1,
            fetch_cycle_deadline_seconds=2.0,
        ),
        data_source=FixtureDataSource(fixtures),
        cache=False,  # the TTL cache would hide the blackout from jobs
        archive=archive,
        chaos_spec=CHAOS_SPEC,
    )
    for i in range(3):
        jid = f"watch{i}"
        cur = f"http://prom:9090/{jid}/cur"
        hist = f"http://prom:9090/{jid}/hist"
        fixtures[cur] = _series(rng, 0.5, 30)
        fixtures[hist] = _series(rng, 0.5, 600)
        rt.store.create(Document(
            id=jid, app_name=f"app-{jid}", namespace="soak",
            strategy="continuous",
            start_time=to_rfc3339(0.0), end_time="",
            metrics={"error5xx": MetricQueries(current=cur,
                                               historical=hist)},
        ))

    rt.start(host="127.0.0.1", port=0, cycle_seconds=0.2)
    try:
        port = rt._server.server_address[1]
        base = f"http://127.0.0.1:{port}"

        def readyz_state():
            code, payload = _get(f"{base}/readyz")
            return json.loads(payload)["state"]

        # liveness vs readiness are distinct endpoints
        code, payload = _get(f"{base}/healthz")
        assert code == 200

        # phase 1: the blackout (outage calls 30..110) drives the brain
        # DEGRADED — warm jobs serve stale verdicts instead of flapping
        assert _wait_for(lambda: readyz_state() == "degraded", 30.0), \
            readyz_state()
        code, payload = _get(f"{base}/metrics")
        text = payload.decode()
        assert "foremastbrain:stale_verdicts_served_total" in text
        assert "foremastbrain:health_state" in text
        assert rt.analyzer.stale_verdicts_served_total > 0
        # no UNKNOWN flips: every monitor is still cycling
        for i in range(3):
            assert rt.store.get(f"watch{i}").status not in (
                J.COMPLETED_UNKNOWN, J.PREPROCESS_FAILED)

        # the CLI health gate reads the same state over the wire
        from foremast_tpu.cli import main as cli_main

        assert cli_main(["health", "--endpoint", base]) == 0

        # phase 2: the outage window drains (breaker half-open probes keep
        # consuming calls) and one clean cycle recovers the brain to OK
        assert _wait_for(lambda: readyz_state() == "ok", 60.0), \
            readyz_state()
    finally:
        rt.stop(drain_seconds=10.0)

    # graceful shutdown: leases released + mirrored for immediate adoption
    rec = archive.get("watch0")
    assert rec is not None and rec["released_at"] > 0
    # and no wedged threads (the worker, flusher, and server all joined)
    assert _wait_for(
        lambda: threading.active_count() <= threads_before + 2, 10.0), \
        threading.enumerate()
