"""Service API over a real socket: create -> engine cycle -> status."""
import json
import urllib.request

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource, VerdictExporter
from foremast_tpu.engine import Analyzer, EngineConfig, JobStore
from foremast_tpu.service import ForemastService, build_document, serve_background
from foremast_tpu.utils.timeutils import to_rfc3339


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=5) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def stack():
    fixtures = {}
    store = JobStore()
    exporter = VerdictExporter()
    service = ForemastService(store, exporter)
    server = serve_background(service, port=0)
    port = server.server_address[1]
    analyzer = Analyzer(EngineConfig(pairwise_threshold=1e-4),
                        FixtureDataSource(fixtures), store, exporter)
    yield f"http://127.0.0.1:{port}", fixtures, analyzer, store
    server.shutdown()


def _create_body(app="demo-app", strategy="canary", urls=("cu", "bu", "hu")):
    cur, base, hist = urls
    return {
        "appName": app,
        "namespace": "demo",
        "strategy": strategy,
        "startTime": to_rfc3339(0.0),
        "endTime": to_rfc3339(600.0),
        "metricsInfo": {
            "current": {"error5xx": {"url": cur, "priority": 0}},
            "baseline": {"error5xx": {"url": base}},
            "historical": {"error5xx": {"url": hist}},
        },
    }


def test_create_then_score_then_status(stack):
    base_url, fixtures, analyzer, store = stack
    rng = np.random.default_rng(0)
    ts = (np.arange(30) * 60).tolist()
    fixtures["cu"] = (ts, rng.normal(6.0, 0.4, 30).clip(0).tolist())
    fixtures["bu"] = (ts, rng.normal(0.5, 0.05, 30).clip(0).tolist())
    fixtures["hu"] = ((np.arange(600) * 60).tolist(),
                      rng.normal(0.5, 0.05, 600).clip(0).tolist())
    code, resp = _req("POST", f"{base_url}/v1/healthcheck/create", _create_body())
    assert code == 200 and resp["status"] == "new"
    job_id = resp["jobId"]

    # duplicate create returns the same open job
    code, resp2 = _req("POST", f"{base_url}/v1/healthcheck/create", _create_body())
    assert resp2["jobId"] == job_id

    code, st = _req("GET", f"{base_url}/v1/healthcheck/id/{job_id}")
    assert st["status"] == "new"

    analyzer.run_cycle(now=10_000.0)
    code, st = _req("GET", f"{base_url}/v1/healthcheck/id/{job_id}")
    assert st["status"] == "anomaly"
    assert "error5xx" in st["reason"]

    # verdict series exposed on /metrics
    with urllib.request.urlopen(f"{base_url}/metrics", timeout=5) as r:
        text = r.read().decode()
    assert "foremastbrain:error5xx_upper" in text


def test_validation_errors(stack):
    base_url, *_ = stack
    code, resp = _req("POST", f"{base_url}/v1/healthcheck/create",
                      {"appName": "bad app!", "strategy": "canary"})
    assert code == 400 and "appName" in resp["error"]
    code, resp = _req("POST", f"{base_url}/v1/healthcheck/create",
                      {"appName": "ok", "strategy": "nope"})
    assert code == 400 and "strategy" in resp["error"]
    code, resp = _req("GET", f"{base_url}/v1/healthcheck/id/missing-job")
    assert code == 404


def test_hpa_job_id_and_placeholders():
    body = {
        "appName": "shop",
        "namespace": "prod",
        "strategy": "hpa",
        "metricsInfo": {
            "current": {
                "tps": {
                    "dataSourceType": "prometheus",
                    "parameters": {
                        "endpoint": "http://prom:9090/api/v1/",
                        "query": "namespace_app_pod_tps{app='shop'}",
                        "start": 1000,
                        "end": 2000,
                        "step": 60,
                    },
                }
            },
            "historical": {"tps": {"url": "http://prom/api?start=1&end=2&step=60"}},
        },
    }
    body["podCountURL"] = "http://prom/api?query=ready&start=1000&end=2000&step=60"
    doc = build_document(body)
    assert doc.id == "shop:prod:hpa"
    assert "start=START_TIME&end=END_TIME" in doc.metrics["tps"].current
    assert "start=START_TIME_H" in doc.metrics["tps"].historical
    assert doc.start_time == "START_TIME"
    # the pod-count query re-materializes per cycle like the metric URLs
    # (a create-time window would freeze per-pod scoring at day-one
    # replica counts) and spans the capacity-proxy history (_H)
    assert "start=START_TIME_H" in doc.pod_count_url
    assert "end=END_TIME" in doc.pod_count_url


def test_wavefront_url_construction():
    body = {
        "appName": "w",
        "strategy": "rollover",
        "metricsInfo": {
            "current": {
                "m": {
                    "dataSourceType": "wavefront",
                    "parameters": {
                        "endpoint": "https://wf.example/chart/api",
                        "query": "ts(my.metric)",
                        "start": 100,
                        "end": 200,
                        "step": 60,
                    },
                }
            }
        },
    }
    doc = build_document(body)
    url = doc.metrics["m"].current
    assert url.startswith("https://wf.example/chart/api?q=ts%28my.metric%29")
    assert "&g=m&" in url


def test_alert_endpoint_returns_hpalogs(stack):
    base_url, fixtures, analyzer, store = stack
    from foremast_tpu.engine.jobs import HpaLog

    store.add_hpalog(HpaLog(job_id="web:prod:hpa", hpascore=80.0,
                            reason="scale up", details=[]))
    code, resp = _req("GET", f"{base_url}/alert/web/prod/hpa")
    assert code == 200
    assert resp["hpalogs"][0]["hpascore"] == 80.0


# ------------------------------------------------------------- query proxy
def test_query_proxy_forwards_with_cors_over_wire():
    """GET /api/v1/<rest>?<qs> forwards to the configured metric store and
    returns the body with CORS headers — the dashboard's data path
    (reference QueryProxy, foremast-service/cmd/manager/main.go:277-297)."""
    import http.server
    import json as _json
    import threading
    import urllib.request

    from foremast_tpu.engine.jobs import JobStore
    from foremast_tpu.service.api import ForemastService, serve_background

    seen = []

    class Upstream(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            seen.append(self.path)
            body = _json.dumps({"status": "success",
                                "data": {"result": []}}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    up = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Upstream)
    threading.Thread(target=up.serve_forever, daemon=True).start()
    try:
        svc = ForemastService(
            JobStore(),
            query_endpoint=f"http://127.0.0.1:{up.server_address[1]}/api/v1/")
        server = serve_background(svc, port=0)
        port = server.server_address[1]
        try:
            r = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/query_range"
                "?query=up&start=1&end=2&step=60", timeout=5)
            assert r.status == 200
            assert _json.loads(r.read())["status"] == "success"
            # CORS for the dashboard's browser fetches
            assert r.headers.get("Access-Control-Allow-Origin") == "*"
            assert seen == ["/api/v1/query_range?query=up&start=1&end=2&step=60"]
        finally:
            server.shutdown()
            server.server_close()
    finally:
        up.shutdown()
        up.server_close()


def test_query_proxy_unconfigured_and_unreachable():
    from foremast_tpu.engine.jobs import JobStore
    from foremast_tpu.service.api import ForemastService

    svc = ForemastService(JobStore())  # no endpoint
    status, payload = svc.query_proxy("query?x=1")
    assert status == 502 and "no query endpoint" in payload["error"]
    svc2 = ForemastService(JobStore(), query_endpoint="http://127.0.0.1:1/")
    status, payload = svc2.query_proxy("query?x=1")
    assert status == 502 and "query proxy failed" in payload["error"]


def test_metrics_includes_engine_self_gauges():
    """/metrics self-reports engine health alongside the verdict series:
    job counts by status, snapshot flush cost, archive errors, and the
    HTTP admission gate's shed counter (reference brain self-reported on
    its :8000 /metrics likewise)."""
    import urllib.request

    from foremast_tpu.engine.archive import FileArchive
    from foremast_tpu.engine.jobs import Document, JobStore
    from foremast_tpu.service.api import ForemastService, serve_background

    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        store = JobStore(archive=FileArchive(tmp + "/a.jsonl"))
        store.create(Document(id="a", app_name="x", strategy="canary",
                              start_time="", end_time=""))
        store.create(Document(id="b", app_name="x", strategy="canary",
                              start_time="", end_time=""))
        store.claim_open_jobs("w", limit=1)
        svc = ForemastService(store)
        server = serve_background(svc, port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.server_address[1]}/metrics",
                timeout=5).read().decode()
            assert 'foremast_jobs{status="initial"} 1' in body
            assert 'foremast_jobs{status="preprocess_inprogress"} 1' in body
            assert "foremast_snapshot_flush_seconds" in body
            assert "foremast_archive_errors 0" in body
            assert "foremast_http_shed_total 0" in body
        finally:
            server.shutdown()
            server.server_close()


def test_malformed_payload_zoo_never_500s(stack):
    """Every malformed create body gets a clean 4xx with a string error —
    never a 500 (an unhandled exception in build_document) and never a
    silent 200 on garbage. The zoo covers the JSON type confusions real
    clients produce."""
    base_url, *_ = stack
    zoo = [
        None,  # null body
        [],  # array, not object
        "string",  # scalar
        {},  # empty object
        {"appName": "x" * 10_000, "strategy": "canary"},  # absurd name
        {"appName": "ok", "strategy": "canary", "metricsInfo": "nope"},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": []}},  # wrong container type
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": "not-a-dict"}}},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"bad metric name!": {"url": "u"}}}},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": {"url": "u",
                                           "priority": "high"}}}},
        {"appName": "ok", "strategy": "hpa",
         "metricsInfo": {"current": {"m": {"parameters": "nope"}}}},
        {"appName": "ok", "strategy": "canary", "startTime": 12345,
         "metricsInfo": {"current": {"m": {"url": "u"}}}},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": {"url": 123}}}},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": {"parameters": {"query": 123}}}}},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": {"parameters": {
             "query": "q", "endpoint": 9}}}}},
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": {"parameters": {
             "query": "q", "start": [1, 2]}}}}},
        # string booleans on direction-flipping flags: bool("false") is
        # True — silent inversion of every verdict direction, must 400
        {"appName": "ok", "strategy": "canary",
         "metricsInfo": {"current": {"m": {"url": "u",
                                           "isIncrease": "maybe"}}}},
        {"appName": "ok", "strategy": "canary", "podCountURL": 77,
         "metricsInfo": {"current": {"m": {"url": "u"}}}},
    ]
    for body in zoo:
        code, resp = _req("POST", f"{base_url}/v1/healthcheck/create", body)
        assert 400 <= code < 500, (body, code, resp)
        assert isinstance(resp, dict) and isinstance(resp.get("error"), str), (
            body, resp)
    # a LITERAL JSON null body (json.dumps(None) -> b"null"): _req's
    # body=None sends an EMPTY body instead, so post raw bytes here
    r = urllib.request.Request(
        f"{base_url}/v1/healthcheck/create", b"null", method="POST",
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(r, timeout=5) as resp:
            code, payload = resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        code, payload = e.code, json.loads(e.read())
    assert 400 <= code < 500 and isinstance(payload.get("error"), str)
    # unambiguous string/int booleans are ACCEPTED (Go clients marshal
    # "true"/"false"; JSON clients send 0/1)
    ok = {"appName": "okflags", "strategy": "canary",
          "metricsInfo": {"current": {"m": {"url": "u", "isIncrease": "false",
                                            "isAbsolute": 1}}}}
    code, resp = _req("POST", f"{base_url}/v1/healthcheck/create", ok)
    assert code == 200, resp
