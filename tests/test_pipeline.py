"""Pipelined scoring cycle (engine/pipeline.py, ISSUE 2).

Covers the three tentpole contracts — byte-identical verdicts vs. the
barriered path, streamed rung-granular dispatch, `_isolate` blast radius
through the launch/collect split — plus the compile-count regression
gates (zero steady-state recompiles; persistent-cache restarts) and the
batch-rung edge cases.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource, VerdictExporter
from foremast_tpu.engine import (
    Analyzer,
    Document,
    EngineConfig,
    JobStore,
    MetricQueries,
)
from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.pipeline import CompileCounter, CyclePipeline, prewarm
from foremast_tpu.ops.windowing import Window
from foremast_tpu.utils.timeutils import to_rfc3339

STEP = 60


def _series(rng, level, n, spread=None, step=STEP):
    spread = level * 0.1 + 0.01 if spread is None else spread
    ts = np.arange(n) * step
    return ts.tolist(), np.clip(rng.normal(level, spread, n), 0, None).tolist()


def _mixed_fleet(n_pair=12, n_band=6, n_bi=4, n_lstm=2, n_hpa=3, seed=11):
    """A deterministic mixed-family fixture fleet: (store, fixtures).

    Some pair canaries are bad so the fold exercises the unhealthy path;
    band/bi/lstm/hpa jobs are healthy continuous-ish jobs with history.
    """
    rng = np.random.default_rng(seed)
    fixtures = {}
    store = JobStore()

    def mk(job_id, metrics, strategy="canary"):
        doc = Document(
            id=job_id, app_name=f"app-{job_id}", namespace="px",
            strategy=strategy, start_time=to_rfc3339(0.0),
            end_time=to_rfc3339(5_000_000.0), metrics=metrics,
        )
        store.create(doc)

    for i in range(n_pair):
        bad = i % 5 == 3
        cur, base = f"u/p{i}/c", f"u/p{i}/b"
        fixtures[cur] = _series(rng, 5.0 if bad else 0.5, 30)
        fixtures[base] = _series(rng, 0.5, 30)
        mk(f"pair{i}", {"error5xx": MetricQueries(current=cur, baseline=base)})
    for i in range(n_band):
        cur, hist = f"u/bd{i}/c", f"u/bd{i}/h"
        fixtures[cur] = _series(rng, 10.0, 25)
        fixtures[hist] = _series(rng, 10.0, 300)
        mk(f"band{i}", {"latency": MetricQueries(current=cur, historical=hist)})
    for i in range(n_bi):
        ms = {}
        for m in ("latency", "cpu"):
            cur, hist = f"u/bi{i}/{m}/c", f"u/bi{i}/{m}/h"
            fixtures[cur] = _series(rng, 10.0, 25)
            fixtures[hist] = _series(rng, 10.0, 300)
            ms[m] = MetricQueries(current=cur, historical=hist)
        mk(f"bi{i}", ms)
    for i in range(n_lstm):
        ms = {}
        for m in ("latency", "cpu", "tps"):
            cur, hist = f"u/ml{i}/{m}/c", f"u/ml{i}/{m}/h"
            fixtures[cur] = _series(rng, 10.0, 25)
            fixtures[hist] = _series(rng, 10.0, 300)
            ms[m] = MetricQueries(current=cur, historical=hist)
        mk(f"lstm{i}", ms)
    for i in range(n_hpa):
        tps_c, tps_h = f"u/h{i}/tps/c", f"u/h{i}/tps/h"
        lat_c, lat_h = f"u/h{i}/lat/c", f"u/h{i}/lat/h"
        fixtures[tps_c] = _series(rng, 100.0, 25)
        fixtures[tps_h] = _series(rng, 100.0, 300)
        fixtures[lat_c] = _series(rng, 5.0, 25)
        fixtures[lat_h] = _series(rng, 5.0, 300)
        tps = MetricQueries(current=tps_c, historical=tps_h)
        lat = MetricQueries(current=lat_c, historical=lat_h)
        lat.priority, lat.is_increase = 1, True
        mk(f"hpa{i}", {"tps": tps, "latency": lat}, strategy="hpa")
    return store, fixtures


def _snapshot(store: JobStore) -> str:
    """Canonical byte view of every job's verdict-bearing state."""
    docs = {}
    for doc in store._jobs.values():
        docs[doc.id] = {
            "status": doc.status,
            "reason": doc.reason,
            "anomaly": doc.anomaly,
        }
    logs = [
        {"job": h.job_id, "score": h.hpascore, "reason": h.reason,
         "details": h.details}
        for h in store._hpalogs
    ]
    return json.dumps({"docs": docs, "hpalogs": logs}, sort_keys=True)


def _run_fleet(score_pipeline: bool, cycles: int = 2, fleet_kw=None,
               **cfg_kw):
    store, fixtures = _mixed_fleet(**(fleet_kw or {}))
    cfg = EngineConfig(pairwise_threshold=1e-4, lstm_epochs=2,
                       score_pipeline=score_pipeline, **cfg_kw)
    eng = Analyzer(cfg, FixtureDataSource(fixtures), store, VerdictExporter())
    outs = [eng.run_cycle(now=1000.0 + 10 * c) for c in range(cycles)]
    return outs, _snapshot(store), eng


# ------------------------------------------------------------ determinism
def test_pipeline_verdicts_byte_identical_to_barriered():
    """The acceptance gate: pipeline on vs. off over an identical mixed
    fixture fleet produces byte-identical verdict state (statuses,
    reasons, anomaly payloads, hpalogs) and identical outcome dicts —
    fold order is claim order regardless of device completion order."""
    outs_p, snap_p, _ = _run_fleet(True)
    outs_s, snap_s, _ = _run_fleet(False)
    assert outs_p == outs_s
    assert snap_p == snap_s


def test_pipeline_chunk_boundaries_match_barriered_rungs():
    """A tiny score_batch forces mid-stream launches; results must still
    match the barriered path exactly (the accumulator fires at the same
    chunk boundaries _score_chunks would cut)."""
    outs_p, snap_p, eng = _run_fleet(True, cycles=1, score_batch=4)
    outs_s, snap_s, _ = _run_fleet(False, cycles=1, score_batch=4)
    assert outs_p == outs_s
    assert snap_p == snap_s


def test_pipeline_early_fire_rung_keeps_verdicts_identical():
    """PIPELINE_FIRE_ROWS below the chunk cap launches mid-stream at
    DIFFERENT boundaries than the barriered chunker — scorers are
    row-wise, so verdicts must still be byte-identical."""
    fleet = dict(n_pair=40, n_band=20, n_bi=6, n_lstm=0, n_hpa=18)
    outs_p, snap_p, _ = _run_fleet(True, cycles=1, fleet_kw=fleet,
                                   pipeline_fire_rows=16)
    outs_s, snap_s, _ = _run_fleet(False, cycles=1, fleet_kw=fleet)
    assert outs_p == outs_s
    assert snap_p == snap_s


# ------------------------------------------------------------- streaming
def test_streaming_accumulator_fires_full_rungs_early():
    """Buckets launch the moment they fill the chunk cap; partials flush
    at finish. 40 one-bucket pair items with cap 16 -> 2 early launches
    + 1 flush, every result present."""
    from foremast_tpu.engine.analyzer import _PairItem

    rng = np.random.default_rng(0)
    cfg = EngineConfig(score_batch=16)
    eng = Analyzer(cfg, FixtureDataSource({}), JobStore())

    def item(i):
        vals = rng.normal(5.0, 0.5, 30).astype(np.float32)
        w = Window(vals, np.ones(30, bool), 0)
        w2 = Window(vals.copy(), np.ones(30, bool), 0)
        return _PairItem(f"j{i}", "m", w, w2, cfg.policy_for("m"))

    pipe = CyclePipeline(eng)
    for i in range(40):
        pipe.feed([item(i)], [], [], [], [])
        # two full rungs fire during the stream, not at the end
        assert pipe.launches == (i + 1) // 16
    pair_res, *_rest = pipe.finish()
    assert pipe.launches == 3
    assert len(pair_res) == 40
    sync = eng._score_pairs([item(i) for i in range(40)])
    assert pair_res.keys() == sync.keys()


def test_pipeline_collect_failure_retries_per_job():
    """A collect-time failure (deferred device error) falls back to the
    per-job synchronous path: results complete, nothing reported bad."""
    store, fixtures = _mixed_fleet(n_pair=6, n_band=0, n_bi=0, n_lstm=0,
                                   n_hpa=0)
    cfg = EngineConfig(pairwise_threshold=1e-4)
    eng = Analyzer(cfg, FixtureDataSource(fixtures), store)
    orig = eng._collect_pairs
    calls = {"n": 0}

    def flaky(state):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("deferred device error")
        return orig(state)

    eng._collect_pairs = flaky
    out = eng.run_cycle(now=1000.0)
    assert calls["n"] > 1  # the retry actually re-collected
    assert set(out) == {f"pair{i}" for i in range(6)}
    # blast radius: no job ended ABORT/INITIAL-on-error
    assert all(s in (J.INITIAL, J.COMPLETED_UNHEALTH) for s in out.values())


def test_pipeline_poisoned_family_reports_only_bad_jobs():
    """A launch that fails even per job reports {job_id: error} for the
    offenders only; other families' jobs still fold normally."""
    store, fixtures = _mixed_fleet(n_pair=4, n_band=2, n_bi=0, n_lstm=0,
                                   n_hpa=0)
    eng = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)

    def boom(*a, **kw):
        raise RuntimeError("poisoned launch")

    eng._launch_pairs = boom  # sync fallback hits it too -> per-job errors
    out = eng.run_cycle(now=1000.0)
    # canary pair jobs die terminally on scoring failure...
    assert all(out[f"pair{i}"] == J.ABORT for i in range(4))
    assert all("poisoned launch" in store.get(f"pair{i}").reason
               for i in range(4))
    # ...band jobs are untouched by the pair family's blast
    assert all(out[f"band{i}"] == J.INITIAL for i in range(2))


# ------------------------------------------------------- batch-rung edges
def test_bucket_rows_exact_rung_boundary_and_tiny_cap():
    eng = Analyzer(EngineConfig(score_batch=8192), FixtureDataSource({}),
                   JobStore())
    assert eng._bucket_rows(64) == 64      # exactly on a rung: no pad
    assert eng._bucket_rows(65) == 256     # next rung up
    # score_batch below the smallest rung clamps to 16, not below
    tiny = Analyzer(EngineConfig(score_batch=8), FixtureDataSource({}),
                    JobStore())
    assert tiny._bucket_rows(1) == 16
    assert tiny._bucket_rows(100) == 16    # cap wins over the ladder


def test_score_chunks_rung_boundary_no_padding():
    """n exactly on a rung boundary launches unpadded."""
    eng = Analyzer(EngineConfig(score_batch=8192), FixtureDataSource({}),
                   JobStore())
    calls = []

    def fn(vals):
        calls.append(vals.shape[0])
        return {"s": vals.sum(axis=1)}

    vals = np.ones((64, 4), np.float32)
    out = eng._score_chunks(fn, [vals])
    assert calls == [64]
    assert out["s"].shape == (64,)


def test_score_chunks_big_fleet_tail_pads_to_own_rung():
    """The tail of a big fleet re-buckets DOWN the ladder (6 -> 16), it
    must not pad to the full chunk."""
    eng = Analyzer(EngineConfig(score_batch=64), FixtureDataSource({}),
                   JobStore())
    calls = []

    def fn(vals):
        calls.append(vals.shape[0])
        return {"s": vals.sum(axis=1)}

    vals = np.arange(70, dtype=np.float32)[:, None] * np.ones(4, np.float32)
    out = eng._score_chunks(fn, [vals])
    assert calls == [64, 16]
    np.testing.assert_allclose(out["s"], vals.sum(axis=1))


# --------------------------------------------------- hpa step regression
def test_hpa_bucket_preserves_series_step(monkeypatch):
    """A 30 s-step HPA job must keep its step through the pack path —
    the old build() dropped it back to the 60 s DEFAULT_STEP."""
    from foremast_tpu.engine import analyzer as A

    captured = []
    orig = A.pack_windows

    def spy(windows, pad_to=None):
        captured.append(list(windows))
        return orig(windows, pad_to=pad_to)

    monkeypatch.setattr(A, "pack_windows", spy)
    rng = np.random.default_rng(0)

    def win(n, start, step):
        return Window(rng.normal(100.0, 3.0, n).astype(np.float32),
                      np.ones(n, bool), start, step)

    eng = Analyzer(EngineConfig(), FixtureDataSource({}), JobStore())
    items = [
        A._HpaItem("j30", "tps", win(90, 0, 30), win(30, 90 * 30, 30),
                   True, 0),
        A._HpaItem("j30", "latency", win(90, 0, 30), win(30, 90 * 30, 30),
                   True, 1),
    ]
    out = eng._score_hpa(items)
    assert "j30" in out and out["j30"]["raw_score"] >= 0.0
    steps = {w.step for group in captured for w in group}
    assert steps == {30}


class _WindowSource:
    """Byte-level-style source: serves prebuilt grid Windows directly
    (the fetch_window fast path), so non-default steps survive fetch."""

    def __init__(self, windows):
        self.windows = windows

    def fetch_window(self, url):
        return self.windows[url]

    def fetch(self, url):  # pragma: no cover - fetch_window always hits
        raise AssertionError("fetch_window path expected")


def test_hpa_e2e_30s_step_job_scores():
    """Full cycle over a 30 s-grid HPA job (fetch_window source): scores,
    emits an hpalog, requeues — no snap back to the 60 s default."""
    rng = np.random.default_rng(4)

    def win(level, n, start):
        return Window(rng.normal(level, level * 0.03, n).astype(np.float32),
                      np.ones(n, bool), start, 30)

    windows = {
        "u/t/c": win(100.0, 30, 9000), "u/t/h": win(100.0, 300, 0),
        "u/l/c": win(5.0, 30, 9000), "u/l/h": win(5.0, 300, 0),
    }
    store = JobStore()
    tps = MetricQueries(current="u/t/c", historical="u/t/h")
    lat = MetricQueries(current="u/l/c", historical="u/l/h")
    lat.priority, lat.is_increase = 1, True
    store.create(Document(
        id="h30", app_name="a", namespace="n", strategy="hpa",
        start_time=to_rfc3339(0.0), end_time=to_rfc3339(5_000_000.0),
        metrics={"tps": tps, "latency": lat},
    ))
    eng = Analyzer(EngineConfig(), _WindowSource(windows), store)
    out = eng.run_cycle(now=10_000.0)
    assert out["h30"] == J.INITIAL  # scored + requeued (continuous)
    assert store._hpalogs and store._hpalogs[-1].job_id == "h30"


# --------------------------------------------------- stage observability
def test_cycle_stage_gauges_and_status_surface():
    exporter = VerdictExporter()
    store, fixtures = _mixed_fleet(n_pair=4, n_band=2, n_bi=0, n_lstm=0,
                                   n_hpa=1)
    eng = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store,
                   exporter)
    eng.run_cycle(now=1000.0)
    text = exporter.render()
    for stage in ("preprocess", "dispatch", "collect", "fold"):
        assert f'foremastbrain:cycle_stage_seconds{{stage="{stage}"}}' in text
    assert 'foremastbrain:cycle_family_score_seconds{family="pair"}' in text
    # /status mirrors the same decomposition
    from foremast_tpu.service.api import ForemastService

    svc = ForemastService(store, exporter=exporter, analyzer=eng)
    status, payload = svc.status_summary()
    assert status == 200
    cyc = payload["cycle"]
    assert cyc["pipelined"] is True
    assert set(cyc["stage_seconds"]) == {"preprocess", "dispatch",
                                         "collect", "fold"}
    assert cyc["family_score_seconds"]["pair"] > 0


# -------------------------------------------------- compile-count gates
@pytest.mark.perf
def test_steady_state_cycles_trigger_zero_recompiles():
    """The regression gate for the rung/bucket design + pipeline: after
    warmup, mixed cycles launch ONLY already-compiled programs."""
    store, fixtures = _mixed_fleet()
    cfg = EngineConfig(pairwise_threshold=1e-4, lstm_epochs=2)
    eng = Analyzer(cfg, FixtureDataSource(fixtures), store)
    warm = 0
    eng.run_cycle(now=1000.0)
    while eng._lstm_trained_this_cycle > 0 and warm < 6:
        eng.run_cycle(now=1000.0)
        warm += 1
    eng.run_cycle(now=1000.0)  # one settle cycle past the last training
    with CompileCounter() as cc:
        eng.run_cycle(now=1000.0)
        eng.run_cycle(now=1000.0)
    assert cc.compiles == 0, (
        f"steady-state mixed cycles compiled {cc.compiles} fresh XLA "
        "program(s); a shape is leaking past the rung/bucket ladder"
    )


@pytest.mark.perf
def test_prewarm_grid_covers_matching_cycle_shapes():
    """After prewarm of a (rung 16, T 64/512) grid, a cycle whose fleet
    lands on those shapes compiles nothing new — this also pins
    fleet.pair_arg_spec to the analyzer's real packing."""
    cfg = EngineConfig(pairwise_threshold=1e-4)
    prewarm(cfg, rungs=(16,), t_buckets=(64, 512))
    rng = np.random.default_rng(3)
    fixtures = {}
    store = JobStore()
    for i in range(5):  # rung 16 after padding; pair T bucket = 64
        cur, base = f"u/p{i}/c", f"u/p{i}/b"
        fixtures[cur] = _series(rng, 0.5, 60)
        fixtures[base] = _series(rng, 0.5, 60)
        store.create(Document(
            id=f"p{i}", app_name="a", namespace="n", strategy="canary",
            start_time=to_rfc3339(0.0), end_time=to_rfc3339(5_000_000.0),
            metrics={"error5xx": MetricQueries(current=cur, baseline=base)},
        ))
    for i in range(3):  # band concat 300+25 -> T bucket 1024, rung 16
        cur, hist = f"u/b{i}/c", f"u/b{i}/h"
        fixtures[cur] = _series(rng, 10.0, 25)
        fixtures[hist] = _series(rng, 10.0, 300)
        store.create(Document(
            id=f"b{i}", app_name="a", namespace="n", strategy="canary",
            start_time=to_rfc3339(0.0), end_time=to_rfc3339(5_000_000.0),
            metrics={"latency": MetricQueries(current=cur, historical=hist)},
        ))
    eng = Analyzer(cfg, FixtureDataSource(fixtures), store)
    with CompileCounter() as cc:
        out = eng.run_cycle(now=1000.0)
    assert len(out) == 8
    assert cc.compiles == 0, (
        f"cycle after prewarm compiled {cc.compiles} program(s): the "
        "prewarm grid (or fleet.pair_arg_spec) drifted from the "
        "production packing"
    )


@pytest.mark.perf
@pytest.mark.slow
def test_compile_cache_restart_skips_compile_storm(tmp_path):
    """With COMPILE_CACHE_PATH set, a restarted process replays compiled
    programs from disk: run the same tiny cycle in two fresh interpreters
    and require the second to compile (almost) nothing fresh."""
    cache = str(tmp_path / "xla-cache")
    script = r"""
import json, os, sys
import numpy as np
from foremast_tpu.engine import Analyzer, Document, EngineConfig, JobStore, MetricQueries
from foremast_tpu.engine.pipeline import CompileCounter, enable_compile_cache
from foremast_tpu.dataplane import FixtureDataSource
from foremast_tpu.utils.timeutils import to_rfc3339

assert enable_compile_cache(sys.argv[1])
rng = np.random.default_rng(0)
fixtures, store = {}, JobStore()
for i in range(4):
    cur, base = f"u/{i}/c", f"u/{i}/b"
    ts = (np.arange(30) * 60).tolist()
    fixtures[cur] = (ts, rng.normal(0.5, 0.05, 30).tolist())
    fixtures[base] = (ts, rng.normal(0.5, 0.05, 30).tolist())
    store.create(Document(id=f"j{i}", app_name="a", namespace="n",
                 strategy="canary", start_time=to_rfc3339(0.0),
                 end_time=to_rfc3339(5_000_000.0),
                 metrics={"error5xx": MetricQueries(current=cur, baseline=base)}))
eng = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
with CompileCounter() as cc:
    eng.run_cycle(now=1000.0)
print(json.dumps({"cache_misses": cc.cache_misses, "cache_hits": cc.cache_hits}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run_once():
        out = subprocess.run(
            [sys.executable, "-c", script, cache], env=env,
            capture_output=True, text=True, timeout=420, check=True,
        )
        return json.loads(out.stdout.strip().splitlines()[-1])

    first = run_once()
    second = run_once()
    # cold start: every program is fresh work (persistent-cache misses);
    # restart: programs replay from disk — misses (the compile storm)
    # collapse while hits take their place
    assert first["cache_misses"] > 0 and first["cache_hits"] == 0, first
    assert second["cache_hits"] > 0, second
    assert second["cache_misses"] < first["cache_misses"], (first, second)


# ------------------------------------------------------------ prewarm CLI
def test_prewarm_cli_prints_grid_summary(capsys):
    from foremast_tpu import cli

    rc = cli.main(["prewarm", "--rungs", "16", "--buckets", "32",
                   "--families", "pair,hpa"])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["families"] == ["pair", "hpa"]
    assert rec["rungs"] == [16]
    assert rec["programs"] == 2
    assert rec["seconds"] >= 0
