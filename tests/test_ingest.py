"""Push-based streaming dataplane (ISSUE 12): wire codecs, the ingest
receiver's routing/backpressure/forwarding contracts, event-driven
partial cycles, and the A/B gates the subsystem ships under.

The three load-bearing contracts:

  * pushed windows are BYTE-IDENTICAL to polled windows (the splice
    property lives in tests/test_delta.py; here the end-to-end identity
    leg pins verdicts — unhealthy ones included — across the two paths);
  * backpressure is clean: wrong media types answer 415 with a reason,
    undecodable bodies 400, buffer overfill 429 — and none of it ever
    blocks or corrupts the scoring path (the poll loop stays the source
    of truth for anything rejected);
  * a pushed job scores IMMEDIATELY (partial cycle, `stream-scored`
    provenance path) while unpushed jobs keep the reconciliation sweep.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from foremast_tpu.dataplane.delta import DeltaWindowSource, parse_range_params
from foremast_tpu.dataplane.fetch import (
    CachingDataSource,
    RawFixtureDataSource,
    parse_prometheus_body,
    grid_from_series,
)
from foremast_tpu.engine import (
    Analyzer,
    Document,
    EngineConfig,
    JobStore,
    MetricQueries,
    StreamScheduler,
)
from foremast_tpu.engine import jobs as J
from foremast_tpu.ingest import (
    IngestDecodeError,
    IngestReceiver,
    decode_otlp_json,
    decode_remote_write,
    encode_remote_write,
    selector_matches,
    snappy_compress,
    snappy_decompress,
)
from foremast_tpu.ingest import wire as ingest_wire
from foremast_tpu.service.api import ForemastService, serve_background
from foremast_tpu.utils.timeutils import to_rfc3339

STEP = 60
T0 = 1_700_000_000 // STEP * STEP


# ------------------------------------------------------------- wire codecs
def test_snappy_roundtrip_and_copies():
    data = b"foremast" * 500 + b"tail"
    assert snappy_decompress(snappy_compress(data)) == data
    assert snappy_decompress(snappy_compress(b"")) == b""
    # a hand-built body with a copy tag (the all-literal compressor never
    # emits one): literal "abcd" + copy2(offset=4, len=8) = "abcdabcd"
    body = bytes([12]) + bytes([3 << 2]) + b"abcd" \
        + bytes([(7 << 2) | 2]) + (4).to_bytes(2, "little")
    assert snappy_decompress(body) == b"abcdabcdabcd"


def test_snappy_rejects_garbage():
    with pytest.raises(IngestDecodeError):
        snappy_decompress(b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff")
    # length mismatch
    with pytest.raises(IngestDecodeError):
        snappy_decompress(bytes([9]) + bytes([3 << 2]) + b"abcd")
    # copy offset beyond the produced output
    with pytest.raises(IngestDecodeError):
        snappy_decompress(bytes([8]) + bytes([(7 << 2) | 2])
                          + (9).to_bytes(2, "little"))
    # a header claiming gigabytes must be refused before allocation
    with pytest.raises(IngestDecodeError):
        snappy_decompress(b"\xff\xff\xff\xff\x7f" + b"\x00")


def test_remote_write_roundtrip():
    series = [
        ({"__name__": "m", "app": "a", "namespace": "n"},
         [(float(T0), 1.5), (float(T0 + 60), -2.25)]),
        ({"__name__": "other"}, [(float(T0) + 0.25, 0.0)]),
    ]
    out = decode_remote_write(encode_remote_write(series))
    assert out == series
    # unknown fields (metadata, field 3) skip cleanly
    from foremast_tpu.ingest.wire import _pb_len

    extra = encode_remote_write(series) + _pb_len(3, b"\x0a\x01x")
    assert decode_remote_write(extra) == series
    with pytest.raises(IngestDecodeError):
        decode_remote_write(b"\x0a\xff\xff\xff\xff\xff")


def test_otlp_json_decode():
    body = {
        "resourceMetrics": [{
            "resource": {"attributes": [
                {"key": "app", "value": {"stringValue": "a"}}]},
            "scopeMetrics": [{"metrics": [
                {"name": "g", "gauge": {"dataPoints": [
                    {"timeUnixNano": str(T0 * 10**9), "asDouble": 3.5,
                     "attributes": [{"key": "namespace",
                                     "value": {"stringValue": "n"}}]}]}},
                {"name": "s", "sum": {"dataPoints": [
                    {"timeUnixNano": str((T0 + 60) * 10**9),
                     "asInt": "7"}]}},
                {"name": "h", "histogram": {"dataPoints": [
                    {"timeUnixNano": "1", "sum": 9.0}]}},
            ]}],
        }],
    }
    out = decode_otlp_json(json.dumps(body).encode())
    assert out == [
        ({"__name__": "g", "app": "a", "namespace": "n"},
         [(float(T0), 3.5)]),
        ({"__name__": "s", "app": "a"}, [(float(T0 + 60), 7.0)]),
    ]
    # exact second division even at ns magnitudes past 2**53
    assert out[0][1][0][0] == float(T0)
    with pytest.raises(IngestDecodeError):
        decode_otlp_json(b"[1, 2]")
    with pytest.raises(IngestDecodeError):
        decode_otlp_json(b"{nope")


def test_selector_matching():
    labels = {"__name__": "namespace_app_pod_error5xx",
              "namespace": "prod", "app": "checkout"}
    assert selector_matches("namespace_app_pod_error5xx", labels)
    assert selector_matches(
        'namespace_app_pod_error5xx{namespace="prod",app="checkout"}',
        labels)
    assert not selector_matches(
        'namespace_app_pod_error5xx{namespace="other"}', labels)
    assert not selector_matches("something_else", labels)
    # non-equality matchers and functions are not provable -> no match
    assert not selector_matches(
        'namespace_app_pod_error5xx{app=~"check.*"}', labels)
    assert not selector_matches(
        "rate(namespace_app_pod_error5xx[5m])", labels)


# ----------------------------------------------------------- test harness
def _body(samples) -> bytes:
    return json.dumps({
        "status": "success",
        "data": {"resultType": "matrix", "result": [
            {"metric": {"__name__": "m"},
             "values": [[t, str(v)] for t, v in samples]}
        ]},
    }).encode()


class _Backend:
    """Range-honoring synthetic Prometheus over mutable series."""

    def __init__(self):
        self.series: dict[str, list] = {}

    def resolver(self, url: str) -> bytes:
        name = url.split("?", 1)[0].rsplit("/", 1)[-1]
        qs, qe, _ = parse_range_params(url)
        return _body([(t, v) for t, v in self.series.get(name, [])
                      if qs <= t <= qe])


def _url(name, s, e):
    return f"http://prom/{name}?query=x&start={s:.0f}&end={e:.0f}&step=60"


def _mk_world(n_jobs=1, warm=True, clock_now=None, strategy="canary"):
    """(backend, delta, store, analyzer, receiver, clock) with n_jobs
    single-metric jobs whose current windows hold 40 warm samples."""
    be = _Backend()
    clock = {"now": float(T0 + 40 * STEP if clock_now is None
                          else clock_now)}
    delta = DeltaWindowSource(RawFixtureDataSource(resolver=be.resolver),
                              clock=lambda: clock["now"])
    store = JobStore()
    for i in range(n_jobs):
        be.series[f"cur{i}"] = [(T0 + k * STEP, 10.0 + 0.1 * k)
                                for k in range(40)]
        be.series[f"base{i}"] = list(be.series[f"cur{i}"])
        store.create(Document(
            id=f"j{i}", app_name=f"app-{i}", namespace="ns",
            strategy=strategy,
            start_time=to_rfc3339(T0), end_time=to_rfc3339(T0 + 86400),
            metrics={"latency": MetricQueries(
                current=_url(f"cur{i}", T0, T0 + 86400),
                baseline=_url(f"base{i}", T0, T0 + 40 * STEP))},
        ))
    an = Analyzer(EngineConfig(), delta, store)
    if warm:
        an.run_cycle(now=clock["now"])
    rec = IngestReceiver(store, delta_source=delta, exporter=an.exporter)
    return be, delta, store, an, rec, clock


def _push(rec, series, now, transport="remote_write",
          ctype="application/x-protobuf", enc="snappy", forwarded=False):
    raw = encode_remote_write(series)
    if enc == "snappy":
        raw = snappy_compress(raw)
    return rec.handle(transport, raw, content_type=ctype,
                      content_encoding=enc, forwarded=forwarded, now=now)


# ------------------------------------------------- receiver: media contracts
def test_wrong_content_type_is_415_with_reason():
    _, _, _, an, rec, clock = _mk_world(warm=False)
    status, payload = rec.handle("remote_write", b"{}",
                                 content_type="application/json")
    assert status == 415
    assert payload["reason"] == "unsupported_media"
    status, payload = rec.handle("otlp", b"x",
                                 content_type="application/x-protobuf")
    assert status == 415
    status, payload = rec.handle(
        "remote_write", b"x", content_type="application/x-protobuf",
        content_encoding="gzip")
    assert status == 415
    assert rec.rejected_total["unsupported_media"] == 3
    # counters ride the exporter with TYPE/HELP metadata
    rendered = an.exporter.render()
    assert ('foremastbrain:ingest_rejected_total'
            '{reason="unsupported_media"} 3') in rendered
    assert "# TYPE foremastbrain:ingest_rejected_total counter" in rendered


def test_undecodable_body_is_400_never_a_stack_trace():
    _, _, _, _, rec, clock = _mk_world(warm=False)
    status, payload = rec.handle(
        "remote_write", b"\x0a\xff\xff\xff\xff\xff",
        content_type="application/x-protobuf", content_encoding="identity")
    assert status == 400
    assert payload["reason"] == "decode_error"
    status, payload = rec.handle("otlp", b"{broken",
                                 content_type="application/json")
    assert status == 400
    assert rec.rejected_total["decode_error"] == 2


def test_snappy_codec_unavailable_degrades_to_415(monkeypatch):
    _, _, _, _, rec, clock = _mk_world(warm=False)
    raw = snappy_compress(encode_remote_write(
        [({"foremast_job": "j0"}, [(float(T0), 1.0)])]))
    monkeypatch.setattr(ingest_wire, "_SNAPPY_ENABLED", False)
    status, payload = rec.handle(
        "remote_write", raw, content_type="application/x-protobuf",
        content_encoding="snappy", now=clock["now"])
    assert status == 415
    assert "snappy" in payload["error"]
    assert rec.rejected_total["unsupported_media"] == 1
    monkeypatch.setattr(ingest_wire, "_SNAPPY_ENABLED", True)
    # identity-encoded bodies keep working either way
    status, _ = _push(rec, [({"foremast_job": "j0"},
                             [(float(T0 + 40 * STEP), 1.0)])],
                      now=clock["now"], enc="identity")
    assert status == 200


# ------------------------------------------------- receiver: routing rules
def test_unknown_job_rejected_and_counted():
    _, _, _, _, rec, clock = _mk_world()
    status, payload = _push(
        rec, [({"foremast_job": "nope"}, [(float(T0), 1.0)]),
              ({"app": "ghost", "namespace": "ns"}, [(float(T0), 1.0)]),
              ({"no_labels_at_all": "1"}, [(float(T0), 1.0)])],
        now=clock["now"])
    assert status == 200
    assert payload["accepted_samples"] == 0
    assert payload["rejected"] == {"unknown_job": 3}


def test_app_namespace_routing_wakes_job():
    _, _, _, an, rec, clock = _mk_world()
    woken = []
    rec.notify_fn = lambda ids: woken.extend(ids)
    tnew = float(T0 + 40 * STEP)
    # app/namespace labels route; the query here is not a plain selector
    # (query=x vs __name__=m) so this is wakeup-only — no splice
    status, payload = _push(
        rec, [({"__name__": "m", "app": "app-0", "namespace": "ns"},
               [(tnew, 5.0)])], now=tnew + 0.5)
    assert status == 200
    assert payload["jobs_advanced"] == 1
    assert woken == ["j0"]
    assert rec.wakeups_total == 1
    assert rec.spliced_points_total == 0


def test_terminal_jobs_are_unknown_to_ingest():
    _, _, store, _, rec, clock = _mk_world(warm=False)
    store.transition("j0", J.PREPROCESS_INPROGRESS, worker="w")
    store.transition("j0", J.PREPROCESS_FAILED, worker="w")
    status, payload = _push(
        rec, [({"foremast_job": "j0"}, [(float(T0), 1.0)])],
        now=clock["now"])
    assert payload["rejected"] == {"unknown_job": 1}


# --------------------------------------------- receiver: splice + serving
def test_addressed_push_splices_and_serves_byte_identical():
    be, delta, _, an, rec, clock = _mk_world()
    tnew = T0 + 40 * STEP
    be.series["cur0"].append((tnew, 99.0))  # backend has it too
    clock["now"] = tnew + 0.5
    status, payload = _push(
        rec, [({"foremast_job": "j0", "foremast_metric": "latency"},
               [(float(tnew), 99.0)])], now=clock["now"])
    assert status == 200
    assert payload["accepted_samples"] == 1
    assert delta.snapshot()["ingest_spliced_points"] == 1
    # the next fetch of the current window is served from the pushed
    # cache (no backend hit) and is byte-identical to a full refetch
    n_req = len(delta.inner.requests)
    served = delta.fetch_window(_url("cur0", T0, T0 + 86400))
    assert len(delta.inner.requests) == n_req
    assert delta.snapshot()["ingest_hits"] == 1
    full = grid_from_series(*parse_prometheus_body(
        be.resolver(_url("cur0", T0, tnew))))
    assert served.start == full.start
    np.testing.assert_array_equal(served.values, full.values)
    np.testing.assert_array_equal(served.mask, full.mask)


def test_stale_and_offgrid_pushes_never_corrupt_the_cache():
    be, delta, _, an, rec, clock = _mk_world()
    url = _url("cur0", T0, T0 + 86400)
    before = delta.fetch_window(url)
    # duplicate of an existing sample, a REWRITE of one, and an off-grid
    # sample: all dropped, none mutate the cached grid
    for samples in ([(float(T0 + 39 * STEP), 10.0)],
                    [(float(T0 + 39 * STEP), -5.0)],
                    [(float(T0 + 40 * STEP) + 7.0, 1.0)]):
        status, _ = _push(
            rec, [({"foremast_job": "j0", "foremast_metric": "latency"},
                   samples)], now=clock["now"])
        assert status == 200  # per-series outcomes, not request failures
    after = delta.fetch_window(url)
    np.testing.assert_array_equal(before.values, after.values)
    np.testing.assert_array_equal(before.mask, after.mask)
    rejects = delta.snapshot()["ingest_rejects"]
    assert rejects.get("off_grid", 0) >= 1


def test_buffer_overfill_is_429_and_scoring_survives():
    _, delta, store, an, rec, clock = _mk_world()
    rec._buffer.per_job = 8  # tiny staging buffer
    # samples that cannot splice (future far beyond the grid tail is
    # fine; pick a job with NO cache entry so they stage) — use a fresh
    # unwarmed job
    store.create(Document(
        id="cold", app_name="cold", namespace="ns", strategy="canary",
        start_time=to_rfc3339(T0), end_time=to_rfc3339(T0 + 86400),
        metrics={"latency": MetricQueries(
            current=_url("coldcur", T0, T0 + 86400))},
    ))
    samples = [(float(T0 + k * STEP), 1.0) for k in range(6)]
    status, payload = _push(
        rec, [({"foremast_job": "cold", "foremast_metric": "latency"},
               samples)], now=clock["now"])
    assert status == 200  # staged, awaiting a priming poll
    status, payload = _push(
        rec, [({"foremast_job": "cold", "foremast_metric": "latency"},
               [(float(T0 + k * STEP), 1.0) for k in range(6, 12)])],
        now=clock["now"])
    assert status == 429
    assert payload["rejected"] == {"buffer_full": 6}
    assert rec.snapshot()["buffer_fill_ratio"] > 0.5
    # the scoring thread is untouched by any of this: a full cycle still
    # runs and judges the warm job
    out = an.run_cycle(now=clock["now"])
    assert out["j0"] == J.INITIAL


def test_ingest_buffer_gauge_renders_with_metadata():
    _, _, _, an, rec, clock = _mk_world(warm=False)
    rec.refresh_metrics()
    rendered = an.exporter.render()
    assert "# TYPE foremastbrain:ingest_buffer_fill_ratio gauge" in rendered
    assert "foremastbrain:ingest_buffer_fill_ratio 0" in rendered


# ------------------------------------------------------ sharding/forwarding
class _FakeShard:
    def __init__(self, owns, addr=None):
        self._owns = owns
        self._addr = addr

    def owns(self, job_id):
        return self._owns

    def owner_addr(self, job_id):
        return self._addr


def test_unowned_push_rejected_without_address():
    _, _, _, _, rec, clock = _mk_world()
    rec.shard = _FakeShard(owns=False, addr=None)
    status, payload = _push(
        rec, [({"foremast_job": "j0"}, [(float(T0), 1.0)])],
        now=clock["now"])
    assert payload["rejected"] == {"not_owner": 1}


def test_forwarded_push_never_forwards_again():
    _, _, _, _, rec, clock = _mk_world()
    rec.shard = _FakeShard(owns=False, addr="http://peer:1")
    status, payload = _push(
        rec, [({"foremast_job": "j0"}, [(float(T0), 1.0)])],
        now=clock["now"], forwarded=True)
    assert payload["rejected"] == {"not_owner": 1}
    assert rec.forwarded_total == 0


def test_push_forwards_to_owner_over_http():
    # owner replica: a real HTTP service whose receiver accepts the push
    be, delta, store, an, rec_owner, clock = _mk_world()
    rec_owner.shard = _FakeShard(owns=True)
    svc = ForemastService(store, exporter=an.exporter, analyzer=an,
                          ingest=rec_owner)
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        # non-owner replica: same store metadata, forwards everything
        _, _, store2, an2, rec, _ = _mk_world()
        rec.shard = _FakeShard(owns=False,
                               addr=f"http://127.0.0.1:{port}")
        tnew = float(T0 + 40 * STEP)
        status, payload = _push(
            rec, [({"foremast_job": "j0", "foremast_metric": "latency"},
                   [(tnew, 42.0)])], now=tnew + 0.2)
        assert status == 200
        assert payload["rejected"] == {}
        assert rec.forwarded_total == 1
        # the owner decoded, routed, and spliced the forwarded sample
        assert rec_owner.samples_total.get("remote_write") == 1
        assert delta.snapshot()["ingest_spliced_points"] == 1
    finally:
        server.shutdown()


# -------------------------------------------------- event-driven scheduler
def test_stream_scheduler_partial_and_sweep():
    sweeps = []
    partials = []

    class _An:
        def run_cycle(self, worker="w", job_ids=None, partial=False):
            partials.append((frozenset(job_ids), partial))

    sched = StreamScheduler(_An(), full_cycle_fn=lambda: sweeps.append(1),
                            cycle_seconds=0.6, worker="w",
                            debounce_seconds=0.02)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while not sweeps and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sweeps, "first sweep never ran"
        sched.notify({"a", "b"})
        while not partials and time.monotonic() < deadline:
            time.sleep(0.01)
        assert partials and partials[0] == (frozenset({"a", "b"}), True)
        # sweeps keep their cadence around partial cycles
        while len(sweeps) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(sweeps) >= 2
        snap = sched.snapshot()
        assert snap["partial_cycles"] == 1
        assert snap["partial_jobs"] == 2
    finally:
        stop.set()
        t.join(timeout=5.0)


def test_partial_cycle_scores_only_pushed_jobs_stream_path():
    be, delta, store, an, rec, clock = _mk_world(n_jobs=3)
    woken: set = set()
    rec.notify_fn = woken.update
    tnew = T0 + 40 * STEP
    for name in ("cur0", "base0"):
        be.series[name].append((tnew, 10.0))
    clock["now"] = tnew + 0.5
    _push(rec, [({"foremast_job": "j0", "foremast_metric": "latency"},
                 [(float(tnew), 10.0)])], now=clock["now"])
    assert woken == {"j0"}
    out = an.run_cycle(now=clock["now"], job_ids=woken, partial=True)
    assert set(out) == {"j0"}  # j1/j2 untouched by the partial cycle
    rec0 = an.provenance.get("j0")
    assert rec0["path"] == "stream-scored"
    assert rec0["cycle"]["cycle_id"].startswith("worker-0-p")
    assert "fetch_ingest" in rec0["fetch"]
    # detection latency of the advance is push latency, not the tick
    assert 0.0 < rec0["detection_latency_s"] < 5.0
    # the other jobs still belong to the sweep
    out2 = an.run_cycle(now=clock["now"] + 1.0)
    assert {"j1", "j2"} <= set(out2)


# ---------------------------------------------------------- HTTP endpoints
def test_http_ingest_endpoints_end_to_end():
    be, delta, store, an, rec, clock = _mk_world()
    woken: set = set()
    rec.notify_fn = woken.update
    svc = ForemastService(store, exporter=an.exporter, analyzer=an,
                          delta_source=delta, ingest=rec)
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        tnew = float(T0 + 40 * STEP)
        be.series["cur0"].append((tnew, 12.0))
        raw = snappy_compress(encode_remote_write(
            [({"foremast_job": "j0", "foremast_metric": "latency"},
              [(tnew, 12.0)])]))
        req = urllib.request.Request(
            f"{base}/ingest/remote-write", data=raw,
            headers={"Content-Type": "application/x-protobuf",
                     "Content-Encoding": "snappy"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
            payload = json.loads(r.read())
        assert payload["accepted_samples"] == 1
        assert woken == {"j0"}
        # OTLP leg: next sample, JSON encoding
        t2 = tnew + STEP
        be.series["cur0"].append((t2, 13.0))
        otlp = {"resourceMetrics": [{"scopeMetrics": [{"metrics": [
            {"name": "latency", "gauge": {"dataPoints": [
                {"timeUnixNano": str(int(t2) * 10**9), "asDouble": 13.0,
                 "attributes": [
                     {"key": "foremast_job",
                      "value": {"stringValue": "j0"}},
                     {"key": "foremast_metric",
                      "value": {"stringValue": "latency"}}]}]}}]}]}]}
        req = urllib.request.Request(
            f"{base}/ingest/otlp", data=json.dumps(otlp).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        # wrong media type is a clean 415 with a reason body
        req = urllib.request.Request(
            f"{base}/ingest/remote-write", data=b"{}",
            headers={"Content-Type": "text/plain"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 415
        assert json.loads(ei.value.read())["reason"] == "unsupported_media"
        # surfaces: /status ingest section + /metrics counters
        with urllib.request.urlopen(f"{base}/status", timeout=10) as r:
            status_doc = json.loads(r.read())
        assert status_doc["ingest"]["samples"]["remote_write"] == 1
        assert status_doc["ingest"]["samples"]["otlp"] == 1
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            metrics = r.read().decode()
        assert ('foremastbrain:ingest_samples_total'
                '{transport="remote_write"} 1') in metrics
        assert ('foremastbrain:ingest_samples_total'
                '{transport="otlp"} 1') in metrics
        assert "foremastbrain:ingest_spliced_points_total 2" in metrics
        assert "foremastbrain:ingest_served_windows_total" in metrics
    finally:
        server.shutdown()


def test_ingest_disabled_runtime_answers_503():
    store = JobStore()
    svc = ForemastService(store)  # no receiver wired
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ingest/remote-write", data=b"",
            headers={"Content-Type": "application/x-protobuf"},
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 503
    finally:
        server.shutdown()


def test_runtime_end_to_end_push_to_stream_scored_verdict():
    """Full runtime: HTTP push -> receiver -> scheduler partial cycle ->
    stream-scored provenance on /jobs/<id>/explain, with the sweep still
    covering the fleet."""
    from foremast_tpu.runtime import Runtime

    be = _Backend()
    now0 = int(time.time()) // STEP * STEP
    t0 = now0 - 40 * STEP
    be.series["cur0"] = [(t0 + k * STEP, 5.0 + 0.01 * k)
                         for k in range(40)]
    be.series["base0"] = list(be.series["cur0"])
    rt = Runtime(
        config=EngineConfig(fetch_concurrency=2),
        data_source=RawFixtureDataSource(resolver=be.resolver),
        ingest_debounce_ms=10.0,
    )
    rt.store.create(Document(
        id="j0", app_name="app-0", namespace="ns", strategy="canary",
        start_time=to_rfc3339(t0), end_time=to_rfc3339(now0 + 86400),
        metrics={"latency": MetricQueries(
            current=_url("cur0", t0, now0 + 86400),
            baseline=_url("base0", t0, now0))},
    ))
    rt.start(host="127.0.0.1", port=0, cycle_seconds=30.0)
    try:
        port = rt._server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        # wait for the first sweep to warm the window cache
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with urllib.request.urlopen(f"{base}/jobs/j0/explain",
                                        timeout=5) as r:
                if (json.loads(r.read()).get("provenance")
                        or {}).get("path"):
                    break
            time.sleep(0.05)
        tnew = float(now0)
        be.series["cur0"].append((tnew, 5.5))
        raw = snappy_compress(encode_remote_write(
            [({"foremast_job": "j0", "foremast_metric": "latency"},
              [(tnew, 5.5)])]))
        req = urllib.request.Request(
            f"{base}/ingest/remote-write", data=raw,
            headers={"Content-Type": "application/x-protobuf",
                     "Content-Encoding": "snappy"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200
        prov = {}
        while time.monotonic() < deadline:
            with urllib.request.urlopen(f"{base}/jobs/j0/explain",
                                        timeout=5) as r:
                prov = json.loads(r.read()).get("provenance") or {}
            if prov.get("path") == "stream-scored":
                break
            time.sleep(0.05)
        assert prov.get("path") == "stream-scored", prov
        with urllib.request.urlopen(f"{base}/status", timeout=5) as r:
            status_doc = json.loads(r.read())
        assert status_doc["scheduler"]["partial_cycles"] >= 1
        assert status_doc["ingest"]["samples"]["remote_write"] == 1
    finally:
        rt.stop()


# -------------------------------------------------------------- perf gates
@pytest.mark.perf
def test_stream_identity_gate():
    """The non-negotiable A/B: pushed-path verdicts byte-identical to
    polled-path verdicts — with convicting anomalies in the fleet, and
    the pushed leg demonstrably serving windows from the push-fed cache."""
    from foremast_tpu.bench_cycle import run_stream_identity

    out = run_stream_identity(n_jobs=24, sweeps=14)
    assert out["verdicts_identical"], out
    assert out["unhealthy_pushed"] > 0, "anomalies never convicted"
    assert out["ingest_served_windows"] > 0, "pushed cache never served"


@pytest.mark.perf
def test_stream_latency_gate():
    """The SLO the plane measures: streamed detection-latency p99 <= 10 s
    on the steady bench (vs the ~60 s polled baseline), verdicts equal."""
    from foremast_tpu.bench_cycle import run_stream

    polled = run_stream(n_jobs=40, cycles=18, stream=False)
    streamed = run_stream(n_jobs=40, cycles=18, stream=True)
    assert streamed["verdict_digest"] == polled["verdict_digest"]
    assert streamed["detection_latency_p99_s"] <= 10.0, streamed
    assert polled["detection_latency_p99_s"] >= 30.0, polled
    assert streamed["ingest_served_windows"] > 0


# ------------------------------------------------- review-fix regressions
def test_oversized_burst_escalates_to_immediate_sweep():
    """A notify burst past the partial budget must trigger the FULL
    sweep right away (the batched path), not spin on the unconsumed
    pending set until the cadence tick."""
    sweeps = []

    class _An:
        def run_cycle(self, worker="w", job_ids=None, partial=False):
            raise AssertionError("oversized burst must not partial-cycle")

    sched = StreamScheduler(_An(), full_cycle_fn=lambda: sweeps.append(1),
                            cycle_seconds=30.0, worker="w",
                            debounce_seconds=0.0, max_partial_jobs=2)
    stop = threading.Event()
    t = threading.Thread(target=sched.run, args=(stop,), daemon=True)
    t.start()
    try:
        deadline = time.monotonic() + 5.0
        while not sweeps and time.monotonic() < deadline:
            time.sleep(0.01)
        sched.notify({"a", "b", "c"})
        # far inside the 30 s cadence, the burst forces sweep #2
        while len(sweeps) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(sweeps) >= 2
        assert sched.snapshot()["pending_jobs"] == 0
        assert sched.partial_cycles_total == 0
    finally:
        stop.set()
        t.join(timeout=5.0)


def test_unknown_series_does_not_rebuild_index_per_push():
    _, _, store, _, rec, clock = _mk_world()
    calls = {"n": 0}
    orig = store.by_status

    def counting(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    store.by_status = counting
    for _ in range(5):
        _push(rec, [({"app": "ghost", "namespace": "ns"},
                     [(float(T0), 1.0)])], now=clock["now"])
    # one rebuild for the fresh index; the 4 repeat misses answer from it
    assert calls["n"] == 1


def test_ttl_invalidate_poisons_in_flight_fetch():
    """A fetch in flight when invalidate() lands must not re-publish its
    (pre-push) result into the cache."""
    import foremast_tpu.dataplane.fetch as F

    release = threading.Event()
    entered = threading.Event()
    fetches = []

    class _Slow:
        def fetch(self, url):
            fetches.append(url)
            entered.set()
            release.wait(5.0)
            return ([1.0], [2.0])

    cache = F.CachingDataSource(_Slow(), ttl_seconds=60.0)
    out = {}
    t = threading.Thread(target=lambda: out.setdefault(
        "res", cache.fetch("u1")), daemon=True)
    t.start()
    assert entered.wait(5.0)
    cache.invalidate("u1")  # push landed mid-flight
    release.set()
    t.join(5.0)
    assert out["res"] == ([1.0], [2.0])  # the waiter still got an answer
    cache.fetch("u1")  # must MISS (not served from a poisoned publish)
    assert len(fetches) == 2


def test_watermarks_are_lru_bounded():
    _, _, store, _, rec, clock = _mk_world()
    rec._buffer.max_jobs = 4
    for i in range(12):
        store.create(Document(
            id=f"wm{i}", app_name=f"wm-{i}", namespace="ns",
            strategy="canary", start_time=to_rfc3339(T0),
            end_time=to_rfc3339(T0 + 86400),
            metrics={"latency": MetricQueries(
                current=_url(f"wmcur{i}", T0, T0 + 86400))},
        ))
        _push(rec, [({"foremast_job": f"wm{i}"},
                     [(float(T0 + 40 * STEP), 1.0)])], now=clock["now"])
    assert len(rec._watermarks) <= 4


def test_otlp_bad_data_point_skipped_not_fatal():
    body = {"resourceMetrics": [{"scopeMetrics": [{"metrics": [
        {"name": "g", "gauge": {"dataPoints": [
            {"timeUnixNano": "not-a-number", "asDouble": 1.0},
            {"timeUnixNano": str(T0 * 10**9), "asDouble": 2.0}]}}]}]}]}
    out = decode_otlp_json(json.dumps(body).encode())
    assert out == [({"__name__": "g"}, [(float(T0), 2.0)])]


def test_series_fanout_counts_samples_once():
    _, _, store, _, rec, clock = _mk_world()
    # second open job under the same (app, namespace)
    store.create(Document(
        id="j0b", app_name="app-0", namespace="ns", strategy="canary",
        start_time=to_rfc3339(T0), end_time=to_rfc3339(T0 + 86400),
        metrics={"latency": MetricQueries(
            current=_url("cur0b", T0, T0 + 86400))},
    ))
    status, payload = _push(
        rec, [({"__name__": "m", "app": "app-0", "namespace": "ns"},
               [(float(T0 + 40 * STEP), 5.0)])],
        now=float(T0 + 40 * STEP) + 0.5)
    assert status == 200
    assert payload["jobs_advanced"] == 2  # both jobs woke
    assert payload["accepted_samples"] == 1  # but the sample counts once
    assert rec.samples_total["remote_write"] == 1


def test_nan_only_push_batch_splices_as_staleness_marker():
    """Prometheus staleness markers arrive as NaN-VALUED samples on
    finite timestamps: they must splice (carried via the entry's nan_ts
    span bookkeeping like every other path), never reject as off_grid or
    latch resync."""
    be, delta, _, an, rec, clock = _mk_world()
    tnew = float(T0 + 40 * STEP)
    be.series["cur0"].append((tnew, float("nan")))
    clock["now"] = tnew + 0.5
    res = delta.ingest_append(_url("cur0", T0, T0 + 86400),
                              [tnew], [float("nan")])
    assert res["spliced"] == 1 and res["reason"] is None, res
    assert delta.snapshot()["ingest_rejects"] == {}
    # still byte-identical to a full refetch of the same backend
    served = delta.fetch_window(_url("cur0", T0, T0 + 86400))
    full = grid_from_series(*parse_prometheus_body(
        be.resolver(_url("cur0", T0, tnew))))
    assert served.start == full.start
    np.testing.assert_array_equal(served.mask, full.mask)


def test_http_429_carries_retry_after():
    _, delta, store, an, rec, clock = _mk_world()
    rec._buffer.per_job = 2
    store.create(Document(
        id="cold2", app_name="cold2", namespace="ns", strategy="canary",
        start_time=to_rfc3339(T0), end_time=to_rfc3339(T0 + 86400),
        metrics={"latency": MetricQueries(
            current=_url("cold2cur", T0, T0 + 86400))},
    ))
    svc = ForemastService(store, exporter=an.exporter, analyzer=an,
                          ingest=rec)
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        raw = snappy_compress(encode_remote_write(
            [({"foremast_job": "cold2", "foremast_metric": "latency"},
              [(float(T0 + k * STEP), 1.0) for k in range(2)])]))
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ingest/remote-write", data=raw,
            headers={"Content-Type": "application/x-protobuf",
                     "Content-Encoding": "snappy"}, method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status == 200  # staged (no entry yet)
        raw = snappy_compress(encode_remote_write(
            [({"foremast_job": "cold2", "foremast_metric": "latency"},
              [(float(T0 + k * STEP), 1.0) for k in range(2, 5)])]))
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/ingest/remote-write", data=raw,
            headers={"Content-Type": "application/x-protobuf",
                     "Content-Encoding": "snappy"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") == "1"
    finally:
        server.shutdown()


def test_failed_invalidated_flight_does_not_poison_next_fetch():
    import foremast_tpu.dataplane.fetch as F

    release = threading.Event()
    entered = threading.Event()
    state = {"fail": True, "calls": 0}

    class _Flaky:
        def fetch(self, url):
            state["calls"] += 1
            entered.set()
            release.wait(5.0)
            if state["fail"]:
                raise F.FetchError("blip")
            return ([1.0], [2.0])

    cache = F.CachingDataSource(_Flaky(), ttl_seconds=60.0)

    def leader():
        try:
            cache.fetch("u1")
        except F.FetchError:
            pass

    t = threading.Thread(target=leader, daemon=True)
    t.start()
    assert entered.wait(5.0)
    cache.invalidate("u1")  # poison lands on the (about to fail) flight
    release.set()
    t.join(5.0)
    state["fail"] = False
    cache.fetch("u1")  # succeeds and MUST be cached
    cache.fetch("u1")  # served from cache
    assert state["calls"] == 2


# --------------------------------------------- push-path chaos (ISSUE 13)
def _cur_entry(delta):
    """The cached entry for the job's CURRENT window."""
    with delta._lock:
        for key, entry in delta._cache.items():
            if "cur0" in key:
                return entry
    return None


def _push_chaos_world():
    """World + the job's current-window URL + a fresh-sample generator
    whose samples ALWAYS land in the backend (chaos is delivery-level:
    the source of truth has the data whether or not a push arrives)."""
    be, delta, store, an, rec, clock = _mk_world()
    u = _url("cur0", T0, T0 + 86400)
    state = {"k": 40}

    def gen_batch(n=3):
        samples = []
        for _ in range(n):
            ts = float(T0 + state["k"] * STEP)
            v = round(10.0 + 0.01 * state["k"], 4)
            be.series["cur0"].append((ts, v))
            samples.append((ts, v))
            state["k"] += 1
        clock["now"] = ts + STEP
        return ({"foremast_job": "j0", "foremast_metric": "latency"},
                samples)

    return be, delta, rec, clock, u, gen_batch


def _deliver(rec, batch, now):
    return _push(rec, [batch], now)


def test_push_chaos_byte_identical_or_resync():
    """The receiver property the new chaos shapes pin: under duplicated,
    reordered and late pushes, the cached window is either byte-identical
    to clean in-order delivery, or the entry is resync-latched — and one
    poll always restores byte-identity. Deterministic per seed."""
    from foremast_tpu.resilience.faults import (
        FaultInjector,
        FaultPlan,
        FaultyPushStream,
    )

    plans = {
        "duplicate": FaultPlan(duplicate_rate=0.5),
        "reorder": FaultPlan(reorder_rate=0.7),
        "late": FaultPlan(late_rate=0.4, late_hold=2),
        "mixed": FaultPlan(duplicate_rate=0.3, reorder_rate=0.3,
                           late_rate=0.3, late_hold=1),
    }
    for name, plan in plans.items():
        for seed in (1, 2, 3):
            # chaotic world A vs clean world B over identical streams
            be_a, delta_a, rec_a, clock_a, u, gen = _push_chaos_world()
            be_b, delta_b, rec_b, clock_b, u_b, _ = _push_chaos_world()
            stream = FaultyPushStream(
                FaultInjector(plan, seed=seed, target="push"))
            for _ in range(12):
                batch = gen()
                # mirror the samples into B's backend + clean delivery
                labels, samples = batch
                be_b.series["cur0"] = list(be_a.series["cur0"])
                clock_b["now"] = clock_a["now"]
                for out in stream.mutate(batch):
                    _deliver(rec_a, out, now=clock_a["now"])
                _deliver(rec_b, batch, now=clock_b["now"])
            for out in stream.flush():
                _deliver(rec_a, out, now=clock_a["now"])
            ea, eb = _cur_entry(delta_a), _cur_entry(delta_b)
            assert eb is not None and not eb.push_blocked
            ctx = f"{name} seed={seed}"
            if not ea.push_blocked:
                # no latch -> the chaotic stream must not have diverged
                assert ea.win.start == eb.win.start, ctx
                np.testing.assert_array_equal(ea.win.mask, eb.win.mask,
                                              err_msg=ctx)
                np.testing.assert_array_equal(ea.win.values, eb.win.values,
                                              err_msg=ctx)
            # and a poll ALWAYS restores byte-identity (the latch's heal
            # path; a no-op refresh for the already-identical case)
            wa = delta_a.fetch_window(u)
            wb = delta_b.fetch_window(u)
            assert wa.start == wb.start, ctx
            np.testing.assert_array_equal(wa.mask, wb.mask, err_msg=ctx)
            np.testing.assert_array_equal(wa.values, wb.values,
                                          err_msg=ctx)
            assert not _cur_entry(delta_a).push_blocked, ctx


def test_late_push_latches_resync_not_silent_hole():
    """Batch k arriving after k+1 was spliced must NOT leave a hole
    inside the pushed horizon: the splice latches resync instead."""
    be, delta, rec, clock, u, gen = _push_chaos_world()
    b1, b2 = gen(), gen()
    _deliver(rec, b2, now=clock["now"])  # k+1 first
    entry = _cur_entry(delta)
    assert entry is not None and entry.pushed_until > 0
    status, payload = _deliver(rec, b1, now=clock["now"])  # k late
    assert status == 200
    assert payload["rejected"].get("late") == len(b1[1])
    entry = _cur_entry(delta)
    assert entry.push_blocked and entry.pushed_until == 0.0
    # duplicate redelivery of ALREADY-CACHED samples is NOT late: after
    # the poll heals, resending b2 is a clean stale drop
    delta.fetch_window(u)
    status, payload = _deliver(rec, b2, now=clock["now"])
    assert status == 200
    assert "late" not in payload["rejected"]
    assert not _cur_entry(delta).push_blocked


def test_receiver_wals_accepted_push_before_ack(tmp_path):
    """/ingest 2xx means durable: the staged batch is WAL'd before the
    splice (and before handle() returns)."""
    from foremast_tpu.dataplane.winstore import WindowStore

    be, delta, store, an, rec, clock = _mk_world()
    ws = WindowStore(str(tmp_path))
    delta.store = ws
    rec.window_store = ws
    batch = ({"foremast_job": "j0", "foremast_metric": "latency"},
             [(float(T0 + 40 * STEP), 5.0), (float(T0 + 41 * STEP), 6.0)])
    status, payload = _push(rec, [batch], now=float(T0 + 42 * STEP))
    assert status == 200 and payload["accepted_samples"] == 2
    assert ws.wal_appends == 1 and ws.wal_samples == 2
    assert rec.snapshot()["durable"] is True
    # the WAL record replays to the same splice
    records, scan = WindowStore._wal_records(
        open(ws.wal_path, "rb").read())
    assert scan == "ok" and len(records) == 1
    url, ts, vals = records[0]
    assert list(ts) == [float(T0 + 40 * STEP), float(T0 + 41 * STEP)]
    res = delta.ingest_append(url, ts, vals)
    assert res["reason"] == "stale"  # already spliced: replay idempotent


# ------------------------------------------------ wire fuzz (ISSUE 13)
def _fuzz_receiver():
    be, delta, store, an, rec, clock = _mk_world()
    return rec, clock


def _assert_clean_push_still_works(rec, now, k):
    """The staging buffer must not be poisoned by whatever garbage the
    last request carried."""
    batch = ({"foremast_job": "j0", "foremast_metric": "latency"},
             [(float(T0 + k * STEP), 1.0)])
    status, payload = _push(rec, [batch], now=now)
    assert status == 200, payload
    assert payload["accepted_samples"] == 1


def test_fuzz_malformed_snappy_blocks():
    """Hand-built hostile snappy bodies + seeded mutations of a valid
    one: always a typed 4xx (or a 200 that rejected per series), never
    an exception out of the receiver, never a poisoned buffer."""
    rng = np.random.default_rng(20260804)
    rec, clock = _fuzz_receiver()
    valid = snappy_compress(encode_remote_write(
        [({"foremast_job": "j0", "foremast_metric": "latency"},
          [(float(T0 + 100 * STEP), 1.0)])]))
    hostile = [
        b"",
        b"\xff" * 64,
        b"\xff\xff\xff\xff\x7f\x00",          # 4 GiB length claim
        bytes([200]) + bytes([3 << 2]) + b"ab",  # length mismatch
        bytes([8]) + bytes([(7 << 2) | 2]) + (60000).to_bytes(2, "little"),
    ]
    for i in range(150):
        body = bytearray(valid)
        for _ in range(rng.integers(1, 6)):
            body[rng.integers(0, len(body))] = rng.integers(0, 256)
        hostile.append(bytes(body[:rng.integers(0, len(body) + 1)]))
    for i, body in enumerate(hostile):
        status, payload = rec.handle(
            "remote_write", body,
            content_type="application/x-protobuf",
            content_encoding="snappy")
        assert status in (200, 400, 415, 429), (i, status, payload)
        assert isinstance(payload, dict), i
        if status != 200:
            assert payload.get("reason") in ("decode_error",
                                             "unsupported_media"), i
    _assert_clean_push_still_works(rec, float(T0 + 200 * STEP), 120)


def test_fuzz_truncated_protobuf():
    """A valid WriteRequest truncated at EVERY offset: typed 400 or a
    cleanly-parsed prefix, never a crash."""
    rec, clock = _fuzz_receiver()
    valid = encode_remote_write(
        [({"foremast_job": "j0", "foremast_metric": "latency",
           "extra": "label-value"},
          [(float(T0 + 100 * STEP), 1.5), (float(T0 + 101 * STEP), 2.5)])])
    for cut in range(len(valid)):
        status, payload = rec.handle(
            "remote_write", valid[:cut],
            content_type="application/x-protobuf",
            content_encoding="identity")
        assert status in (200, 400, 429), (cut, status, payload)
        assert isinstance(payload, dict), cut
    _assert_clean_push_still_works(rec, float(T0 + 200 * STEP), 121)


def test_fuzz_bad_otlp_json():
    """Type-confused / truncated / hostile OTLP JSON: typed 400 (or a
    200 whose bad points were skipped), never a crash."""
    rng = np.random.default_rng(4)
    rec, clock = _fuzz_receiver()
    hostile = [
        b"",
        b"not json",
        b"[]",
        b"5",
        b'{"resourceMetrics": 5}',
        b'{"resourceMetrics": [5, {"scopeMetrics": "x"}]}',
        b'{"resourceMetrics": [{"scopeMetrics": [{"metrics": '
        b'[{"name": 3, "gauge": {"dataPoints": "zzz"}}]}]}]}',
        b'{"resourceMetrics": [{"scopeMetrics": [{"metrics": '
        b'[{"name": "m", "gauge": {"dataPoints": [{"timeUnixNano": '
        b'{"a": 1}, "asDouble": 1}]}}]}]}]}',
        b'{"resourceMetrics": [{"scopeMetrics": [{"metrics": '
        b'[{"name": "m", "sum": {"dataPoints": [{"timeUnixNano": "1",'
        b' "asInt": "not-an-int"}]}}]}]}]}',
        json.dumps({"resourceMetrics": [{"resource": {"attributes": [
            {"key": 7, "value": None}]}, "scopeMetrics": [{"metrics": [
                {"name": "m", "gauge": {"dataPoints": [
                    {"timeUnixNano": "9" * 40, "asDouble": 1e308}]}}
            ]}]}]}).encode(),
    ]
    valid = json.dumps({"resourceMetrics": [{"scopeMetrics": [{
        "metrics": [{"name": "m", "gauge": {"dataPoints": [
            {"timeUnixNano": str((T0 + 100 * STEP) * 10**9),
             "asDouble": 1.0}]}}]}]}]}).encode()
    for i in range(100):
        body = bytearray(valid)
        for _ in range(rng.integers(1, 5)):
            body[rng.integers(0, len(body))] = rng.integers(0, 256)
        hostile.append(bytes(body[:rng.integers(0, len(body) + 1)]))
    for i, body in enumerate(hostile):
        status, payload = rec.handle(
            "otlp", body, content_type="application/json")
        assert status in (200, 400, 415, 429), (i, status, payload)
        assert isinstance(payload, dict), i
    _assert_clean_push_still_works(rec, float(T0 + 200 * STEP), 122)


def test_receiver_wals_only_batches_that_spliced(tmp_path):
    """Durability scope is exact: a push that did NOT advance durable
    state (no_entry -> RAM staging buffer, stale duplicate) is never
    WAL'd — the poll path is its source of truth — so recovery can
    never ack-then-lose it, and the WAL holds only replayable splices."""
    from foremast_tpu.dataplane.winstore import WindowStore

    be, delta, store, an, rec, clock = _mk_world(warm=False)
    ws = WindowStore(str(tmp_path))
    delta.store = ws
    rec.window_store = ws
    batch = ({"foremast_job": "j0", "foremast_metric": "latency"},
             [(float(T0 + 40 * STEP), 5.0)])
    # nothing primed yet: accepted (buffered), NOT WAL'd
    status, payload = _push(rec, [batch], now=float(T0 + 41 * STEP))
    assert status == 200 and payload["accepted_samples"] == 1
    assert ws.wal_appends == 0
    # prime + splice: WAL'd exactly once
    an.run_cycle(now=float(T0 + 41 * STEP))
    batch2 = ({"foremast_job": "j0", "foremast_metric": "latency"},
              [(float(T0 + 41 * STEP), 6.0)])
    status, _ = _push(rec, [batch2], now=float(T0 + 42 * STEP))
    assert status == 200 and ws.wal_appends == 1
    # exact duplicate redelivery: accepted, dropped stale, NOT WAL'd
    status, _ = _push(rec, [batch2], now=float(T0 + 42 * STEP))
    assert status == 200 and ws.wal_appends == 1


# --------------------------------------- traceparent propagation (ISSUE 14)
def test_fuzz_hostile_traceparent_headers():
    """Malformed/hostile `traceparent` headers (bad version, short ids,
    non-hex, all-zero, oversized, binary junk): ALWAYS a typed outcome —
    the push is processed under a fresh root trace with a
    `bad_traceparent` rejection counted — never a 5xx out of the
    receiver, never a poisoned staging buffer (the body-fuzz contract
    from PR 13, applied to the header)."""
    rng = np.random.default_rng(20260814)
    rec, clock = _fuzz_receiver()
    tid, sid = "a" * 32, "b" * 16
    hostile = [
        "00",
        f"ff-{tid}-{sid}-01",
        f"00-{'0' * 32}-{sid}-01",
        f"00-{tid}-{'0' * 16}-01",
        f"00-{tid[:-1]}-{sid}-01",
        f"00-{tid}-{sid[:-1]}-01",
        f"00-{tid.upper()}-{sid}-01",
        f"00-{tid}-{sid}-zz",
        f"00-{tid}-{sid}-01-junk",
        "00-" + "g" * 32 + "-" + sid + "-01",
        "x" * 8192,
        "00-\x00\x01\x02-\x03-\x04",
        "traceparent: 00-aa-bb-01",
        "00 " + tid + " " + sid + " 01",
    ]
    valid_header = f"00-{tid}-{sid}-01"
    for _ in range(100):
        body = bytearray(valid_header.encode())
        for _ in range(rng.integers(1, 4)):
            body[rng.integers(0, len(body))] = rng.integers(0, 256)
        hostile.append(bytes(body[:rng.integers(1, len(body) + 1)])
                       .decode("latin-1"))
    k = 100
    for i, header in enumerate(hostile):
        if ingest_wire.snappy_available():
            pass  # keep the push bodies valid: the HEADER is under test
        k += 1
        batch = ({"foremast_job": "j0", "foremast_metric": "latency"},
                 [(float(T0 + k * STEP), 1.0)])
        status, payload = rec.handle(
            "remote_write", snappy_compress(encode_remote_write([batch])),
            content_type="application/x-protobuf",
            content_encoding="snappy", now=float(T0 + (k + 1) * STEP),
            traceparent=header)
        assert status == 200, (i, status, payload)
        # the push itself was accepted under a FRESH root trace
        assert payload["accepted_samples"] == 1, (i, payload)
        assert payload["rejected"].get("bad_traceparent") == 1, (i, payload)
        assert len(payload["trace_id"]) == 32
        assert payload["trace_id"] != tid
    assert rec.rejected_total["bad_traceparent"] == len(hostile)
    _assert_clean_push_still_works(rec, float(T0 + 400 * STEP), 301)


def test_valid_traceparent_adopted_and_answered():
    """A valid header continues the SENDER's trace: the receive span
    parents under it, the response names the trace, and /debug/traces
    can fetch it by id."""
    from foremast_tpu.utils import tracing

    rec, clock = _fuzz_receiver()
    tid = "c" * 32
    batch = ({"foremast_job": "j0", "foremast_metric": "latency"},
             [(float(T0 + 40 * STEP), 2.0)])
    status, payload = rec.handle(
        "remote_write", snappy_compress(encode_remote_write([batch])),
        content_type="application/x-protobuf", content_encoding="snappy",
        now=float(T0 + 41 * STEP), traceparent=f"00-{tid}-{'d' * 16}-01")
    assert status == 200
    assert payload["trace_id"] == tid
    assert "bad_traceparent" not in payload["rejected"]
    trees = tracing.tracer.snapshot(trace_id=tid)
    recv = [t for t in trees if t["name"] == "ingest.receive"]
    assert recv and recv[-1]["parent_span_id"] == "d" * 16
    # splice span nested under the receive span, same trace
    children = {c["name"] for c in recv[-1].get("children", ())}
    assert "ingest.splice" in children


def test_forward_reinjects_context_and_origin_stamp():
    """One-hop forward: the forwarded request carries a `traceparent`
    naming the origin's FORWARD span (the hop is a child on the origin's
    trace; the target parents under it), the origin's first-contact
    timestamp, and the origin replica's name — so detection latency is
    measured from first contact and the target's spans name both
    replicas."""
    import http.server

    from foremast_tpu.ingest import (
        FORWARDED_HEADER,
        ORIGIN_REPLICA_HEADER,
        ORIGIN_TS_HEADER,
    )
    from foremast_tpu.utils import tracing

    seen = {}

    class _Capture(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            seen["headers"] = dict(self.headers)
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Capture)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        _, _, _, _, rec, clock = _mk_world()
        rec.replica = "origin-A"
        rec.shard = _FakeShard(
            owns=False,
            addr=f"http://127.0.0.1:{server.server_address[1]}")
        tnew = float(T0 + 40 * STEP)
        sender = "00-" + "e" * 32 + "-" + "f" * 16 + "-01"
        status, payload = _push(
            rec, [({"foremast_job": "j0"}, [(tnew, 1.0)])],
            now=tnew + 0.25)
        # re-send with an upstream trace to pin adoption across the hop
        status, payload = rec.handle(
            "remote_write",
            snappy_compress(encode_remote_write(
                [({"foremast_job": "j0"}, [(tnew + STEP, 1.0)])])),
            content_type="application/x-protobuf",
            content_encoding="snappy", now=tnew + STEP + 0.25,
            traceparent=sender)
        assert status == 200
        assert payload["forwarded_samples"] == 1
        headers = {k.lower(): v for k, v in seen["headers"].items()}
        assert headers[FORWARDED_HEADER.lower()] == "1"
        assert float(headers[ORIGIN_TS_HEADER.lower()]) == \
            pytest.approx(tnew + STEP + 0.25)
        assert headers[ORIGIN_REPLICA_HEADER.lower()] == "origin-A"
        fwd_tp = tracing.parse_traceparent(headers["traceparent"])
        assert fwd_tp is not None and fwd_tp.trace_id == "e" * 32
        # the injected parent is the origin's ingest.forward span
        trees = tracing.tracer.snapshot(trace_id="e" * 32)
        recv = [t for t in trees if t["name"] == "ingest.receive"][-1]
        fwd = [c for c in recv.get("children", ())
               if c["name"] == "ingest.forward"]
        assert fwd and fwd[0]["span_id"] == fwd_tp.span_id
    finally:
        server.shutdown()


def test_forwarded_push_measures_from_origin_receipt():
    """Satellite fix: the forward target's waterfall starts at the
    ORIGIN's first contact — the hop shows as a forward_hop stage, and
    the origin timestamp is kept through the book (not reset to the
    target's receipt)."""
    from foremast_tpu.engine import slo as slo_mod

    _, _, _, an, rec, clock = _mk_world()
    rec.waterfall = an.waterfall
    tnew = float(T0 + 40 * STEP)
    origin_ts = tnew + 0.2
    target_now = tnew + 1.7
    status, payload = rec.handle(
        "remote_write",
        snappy_compress(encode_remote_write(
            [({"foremast_job": "j0", "foremast_metric": "latency"},
              [(tnew, 3.0)])])),
        content_type="application/x-protobuf", content_encoding="snappy",
        now=target_now, forwarded=True, origin_ts=f"{origin_ts:.6f}",
        origin_replica="origin-A")
    assert status == 200 and payload["accepted_samples"] == 1
    rec_book = an.waterfall._inflight["j0"]
    assert rec_book["origin"] == pytest.approx(origin_ts)
    stages = rec_book["stages"]
    assert stages[slo_mod.STAGE_FORWARD_HOP] == \
        pytest.approx(target_now - origin_ts)
    # ingest_receive covers sample-ts -> ORIGIN receipt (+ proc time),
    # not the reset-to-target wait
    assert stages[slo_mod.STAGE_INGEST_RECEIVE] >= origin_ts - tnew - 1e-6
    assert stages[slo_mod.STAGE_INGEST_RECEIVE] < 1.0


def test_multi_series_batch_stamps_request_stages_once():
    """A batch fanning k advancing series into one job records the
    PER-REQUEST stages (receive lag, forward hop) once — not k times
    (forward_hop is a request quantity; handle time re-counted per
    series would grow O(k^2)). Per-series splice work still
    accumulates."""
    from foremast_tpu.engine import slo as slo_mod

    _, _, _, an, rec, clock = _mk_world()
    rec.waterfall = an.waterfall
    tnew = float(T0 + 40 * STEP)
    origin_ts = tnew + 0.2
    target_now = tnew + 1.7
    series = [
        ({"foremast_job": "j0", "foremast_metric": "latency"},
         [(tnew, 3.0)]),
        ({"foremast_job": "j0", "foremast_metric": "latency"},
         [(tnew + STEP, 3.1)]),  # advances the watermark again
        ({"foremast_job": "j0", "foremast_metric": "latency"},
         [(tnew + 2 * STEP, 3.2)]),
    ]
    status, payload = rec.handle(
        "remote_write", snappy_compress(encode_remote_write(series)),
        content_type="application/x-protobuf", content_encoding="snappy",
        now=target_now, forwarded=True, origin_ts=f"{origin_ts:.6f}",
        origin_replica="origin-A")
    assert status == 200 and payload["accepted_samples"] == 3
    stages = an.waterfall._inflight["j0"]["stages"]
    # exactly ONE hop's latency, not three
    assert stages[slo_mod.STAGE_FORWARD_HOP] == \
        pytest.approx(target_now - origin_ts)
    # receive = one (lag + proc) stamp, bounded well under 2x
    assert stages[slo_mod.STAGE_INGEST_RECEIVE] < \
        2 * (origin_ts - tnew)


def test_hostile_origin_ts_never_poisons_the_histograms():
    """An origin stamp older than the sanity window (garbage header /
    badly skewed peer clock) is IGNORED: first contact falls back to the
    local receipt and no ~1e9 s forward_hop sample ever lands in the
    stage histograms."""
    from foremast_tpu.engine import slo as slo_mod

    _, _, _, an, rec, clock = _mk_world()
    rec.waterfall = an.waterfall
    tnew = float(T0 + 40 * STEP)
    target_now = tnew + 1.0
    status, payload = rec.handle(
        "remote_write",
        snappy_compress(encode_remote_write(
            [({"foremast_job": "j0", "foremast_metric": "latency"},
              [(tnew, 3.0)])])),
        content_type="application/x-protobuf", content_encoding="snappy",
        now=target_now, forwarded=True, origin_ts="1",
        origin_replica="evil")
    assert status == 200 and payload["accepted_samples"] == 1
    book = an.waterfall._inflight["j0"]
    assert book["origin"] == pytest.approx(target_now)
    assert slo_mod.STAGE_FORWARD_HOP not in book["stages"]
    # receive = (local now - sample ts) + proc — NOT now - 1970
    assert book["stages"][slo_mod.STAGE_INGEST_RECEIVE] == pytest.approx(
        target_now - tnew, abs=0.2)


def test_below_span_duplicate_is_not_late():
    """A retried sample whose timestamp sits BELOW the cached window's
    retained span is indistinguishable from a clipped-out duplicate —
    it must drop free (stale), never latch resync."""
    be, delta, rec, clock, u, gen = _push_chaos_world()
    _deliver(rec, gen(), now=clock["now"])
    entry = _cur_entry(delta)
    assert entry is not None and entry.pushed_until > 0
    below = float(entry.win.start - STEP)
    status, payload = _deliver(
        rec, ({"foremast_job": "j0", "foremast_metric": "latency"},
              [(below, 1.0)]), now=clock["now"])
    assert status == 200
    assert "late" not in payload["rejected"]
    entry = _cur_entry(delta)
    assert not entry.push_blocked and entry.pushed_until > 0
