"""Multi-replica kill -9 chaos soak: zero lost, zero double-scored jobs.

The sharded-brain acceptance gate (ISSUE 8 / ROADMAP item 1): three
in-process replicas — each a full JobStore + Analyzer + ShardManager —
share ONE archive path. Jobs submitted at one replica distribute across
the ring (release_unowned handoff -> owner adoption); one replica is then
killed -9 MID-CYCLE (it has just claimed and mirrored in-progress leases;
no drain, no release, no withdraw, its in-RAM state simply vanishes — the
exact state a SIGKILLed pod leaves behind). The survivors detect the
death at membership-TTL latency, rebalance, adopt the dead replica's
fleet through the dead-holder gate, and drive every job to a verdict:

  * zero lost jobs — every submitted job reaches a terminal archive record;
  * zero double-scored jobs — the replicas' terminal-verdict sets are
    pairwise disjoint (ownership + the claim_job CAS);
  * verdicts byte-identical to a single-replica run of the same fleet.

Deterministic: seeded fixtures, synthetic scoring clock (wall time only
drives membership/lease machinery), sequential cycle interleaving.
Bounded well under 120 s; marked slow+chaos so tier-1 (-m 'not slow')
never blocks on it — CI runs it in the separate soak job (`make
soak-sharded`).
"""
from __future__ import annotations

import time

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource
from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.analyzer import Analyzer
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.engine.config import EngineConfig
from foremast_tpu.engine.jobs import Document, JobStore, MetricQueries
from foremast_tpu.engine.sharding import ShardManager
from foremast_tpu.utils.timeutils import to_rfc3339

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

N_JOBS = 36
ANOMALOUS = {f"job-{i}" for i in range(0, N_JOBS, 6)}  # every 6th is bad
T_MID = 10_000.0   # scoring clock mid-watch (before every endTime)
T_END = 20_000.0   # every job's endTime
MEMBER_TTL = 1.0   # seconds — the kill -9 recovery latency under test


def _fixtures() -> dict:
    """Seeded per-job windows: healthy jobs' current tracks baseline;
    anomalous jobs' current is catastrophically shifted."""
    rng = np.random.default_rng(42)
    ts = (np.arange(30) * 60.0).tolist()
    fixtures = {}
    for i in range(N_JOBS):
        jid = f"job-{i}"
        base = rng.normal(0.5, 0.05, 30).tolist()
        if jid in ANOMALOUS:
            cur = rng.normal(5.0, 0.5, 30).tolist()
        else:
            cur = rng.normal(0.5, 0.05, 30).tolist()
        fixtures[f"http://prom/{jid}/cur"] = (ts, cur)
        fixtures[f"http://prom/{jid}/base"] = (ts, base)
    return fixtures


def _doc(i: int) -> Document:
    jid = f"job-{i}"
    return Document(
        id=jid, app_name=f"app{i}", namespace="soak", strategy="canary",
        start_time=to_rfc3339(0.0), end_time=to_rfc3339(T_END),
        metrics={"error5xx": MetricQueries(
            current=f"http://prom/{jid}/cur",
            baseline=f"http://prom/{jid}/base")},
    )


class Replica:
    """One in-process brain replica over the shared archive path, wired
    exactly as the runtime wires it: status digest on the membership
    heartbeat (the /fleet federation medium), cycle ids on handoff/
    adoption flight events, provenance handoff blobs both directions."""

    def __init__(self, rid: str, archive_path: str, fixtures: dict):
        self.rid = rid
        self.archive = FileArchive(archive_path)
        self.store = JobStore(archive=self.archive)
        self.analyzer = Analyzer(
            EngineConfig(pairwise_threshold=1e-4),
            FixtureDataSource(fixtures), self.store)
        self.shard = ShardManager(
            self.store, rid, shard_count=16, vnodes=32,
            heartbeat_seconds=0.0,  # heartbeat every tick
            member_ttl_seconds=MEMBER_TTL, worker=rid,
            flight=self.analyzer.flight,
            digest_fn=self.analyzer.status_digest,
            cycle_id_fn=lambda: self.analyzer.current_cycle_id,
            handoff_content_fn=lambda jid:
            self.analyzer.provenance.handoff_json(
                jid, replica=rid, worker=rid, reason="rebalance"))
        self.analyzer.shard = self.shard
        self.scored: set[str] = set()  # terminal verdicts THIS replica wrote

    def cycle(self, score_now: float) -> dict:
        """One worker-loop lap: membership tick, adoption scan, engine
        cycle (the cycle's trailing store.flush() mirrors to the archive)."""
        self.shard.tick()
        adopted_ids: list[str] = []

        def _on_adopt(d):
            adopted_ids.append(d.id)
            self.analyzer.provenance.adopt(d.id, d.processing_content)

        n = self.store.adopt_stale_from_archive(
            worker=self.rid, owns_fn=self.shard.owns,
            dead_holder_fn=self.shard.dead_holder,
            on_adopt=_on_adopt)
        # jobs= mirrors the runtime's wiring (runtime.py _worker_loop):
        # the adoption flight event carries the adopted ids so the
        # incident is correlatable with the releasing side's handoff
        self.shard.mark_adopt_complete(n, jobs=adopted_ids)
        out = self.analyzer.run_cycle(worker=self.rid, now=score_now)
        for jid, status in out.items():
            if status in J.TERMINAL_STATUSES:
                self.scored.add(jid)
        return out


def _terminal_records(path: str) -> dict[str, dict]:
    ar = FileArchive(path)
    return {
        rec["id"]: rec
        for rec in ar.search(status=list(J.TERMINAL_STATUSES), limit=500)
    }


def _verdict(rec: dict, with_reason: bool) -> tuple:
    """The comparable verdict: status + anomaly series (+ reason for
    unhealthy verdicts, whose reason text is scoring output; healthy
    completions carry no reason of their own, so a handed-off job may
    keep its release note there)."""
    anomaly = {k: list(v) for k, v in sorted(
        (rec.get("anomaly") or {}).items())}
    out = (rec["status"], anomaly)
    if with_reason and rec["status"] == J.COMPLETED_UNHEALTH:
        out = out + (rec.get("reason", ""),)
    return out


def _run_single_replica_baseline(archive_path: str, fixtures: dict) -> dict:
    """The same fleet through ONE replica: the verdict ground truth."""
    solo = Replica("solo", archive_path, fixtures)
    for i in range(N_JOBS):
        solo.store.create(_doc(i))
    for _ in range(4):
        solo.cycle(T_MID)
    for _ in range(3):
        solo.cycle(T_END + 1.0)
    recs = _terminal_records(archive_path)
    assert len(recs) == N_JOBS, "baseline must complete the whole fleet"
    return recs


def test_kill9_one_of_three_replicas_zero_lost_zero_double_scored(tmp_path):
    fixtures = _fixtures()
    # the baseline runs FIRST: it is the verdict ground truth AND it
    # compiles every (rung, T) scoring program this process will use — the
    # scorers are module-level jits, so the multi-replica phase then
    # cycles in milliseconds and the wall-clock heartbeat TTL below stays
    # honest (a first-cycle compile storm mid-soak would stall heartbeats
    # and flap membership, which is realistic for pods but not what this
    # test isolates; production covers it with PREWARM_ON_START)
    baseline = _run_single_replica_baseline(
        str(tmp_path / "baseline.jsonl"), fixtures)
    shared = str(tmp_path / "shared.jsonl")
    A = Replica("A", shared, fixtures)
    B = Replica("B", shared, fixtures)
    C = Replica("C", shared, fixtures)

    # -- membership forms: two laps so everyone sees everyone
    for r in (A, B, C):
        r.shard.tick()
    for r in (A, B, C):
        t = r.shard.tick()
        assert t["replicas"] == ["A", "B", "C"], t
    # the 16 shards partition across the three (gained shards still show
    # `adopting` until each replica's first adoption scan lands)
    assert sum(r.shard.health_summary()["owned"]
               + r.shard.health_summary()["adopting"]
               for r in (A, B, C)) == 16

    # -- the fleet-federation view: GET /fleet on a replica shows all
    # three peers with FRESH digests (digests ride the heartbeats the
    # membership laps above just wrote)
    import json as _json
    import urllib.request as _rq

    from foremast_tpu.service.api import ForemastService, serve_background

    svc = ForemastService(A.store, exporter=A.analyzer.exporter,
                          analyzer=A.analyzer, shard=A.shard)
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        with _rq.urlopen(f"http://127.0.0.1:{port}/fleet", timeout=10) as r:
            fleet = _json.loads(r.read().decode())
    finally:
        server.shutdown()
    rows = {row["replica"]: row for row in fleet["replicas"]}
    assert set(rows) == {"A", "B", "C"}
    assert all(not row["stale"] for row in rows.values())
    assert all((row.get("digest") or {}).get("health") == "ok"
               for row in rows.values())

    # -- the whole fleet is submitted at ONE replica; the ring distributes
    for i in range(N_JOBS):
        A.store.create(_doc(i))
    for _ in range(3):
        for r in (A, B, C):
            r.cycle(T_MID)
    # distributed: every replica scored/holds only its own shards, and the
    # anomalous jobs already completed (fail-fast)
    done = _terminal_records(shared)
    assert set(done) == ANOMALOUS
    for r in (A, B, C):
        held = {d.id for d in r.store.by_status(*J.OPEN_STATUSES)}
        assert held, f"{r.rid} ended up with no shard slice"
        assert all(r.shard.owns(jid) for jid in held)

    # -- kill -9 B MID-CYCLE: it just claimed its open jobs and mirrored
    # the in-progress leases; then its in-RAM world vanishes. No drain,
    # no release, no membership withdraw.
    B.shard.tick()
    in_flight = B.store.claim_open_jobs("B", owns_fn=B.shard.owns)
    assert in_flight, "the victim must die with claimed work in flight"
    B.store.flush()
    b_scored_before_kill = set(B.scored)
    b_open_ids = {d.id for d in in_flight}
    killed_at = time.time()
    del B  # kill -9

    # -- survivors: TTL expiry -> rebalance -> dead-holder adoption
    time.sleep(MEMBER_TTL + 0.3)
    for _ in range(4):
        for r in (A, C):
            r.cycle(T_MID)
        survivors_hold = {
            d.id for r in (A, C) for d in r.store.by_status(*J.OPEN_STATUSES)}
        if b_open_ids <= survivors_hold:
            break
    assert b_open_ids <= survivors_hold, (
        "the dead replica's in-flight jobs must be adopted")
    recovery_s = time.time() - killed_at
    # the recovery ran on the membership TTL, nowhere near the 90 s
    # MAX_STUCK_IN_SECONDS window the dead-holder gate bypasses
    assert recovery_s < 30.0, recovery_s
    assert A.shard.tick()["replicas"] == ["A", "C"]
    # the killed replica's fleet row flipped STALE within MEMBER_TTL of
    # its last heartbeat (it never withdrew, so not `left` — age did it)
    b_row = {row["replica"]: row
             for row in A.shard.fleet_snapshot()["replicas"]}["B"]
    assert b_row["stale"] and not b_row["left"]
    assert b_row["age_s"] > MEMBER_TTL

    # -- drive to completion past every endTime
    for _ in range(5):
        for r in (A, C):
            r.cycle(T_END + 1.0)
        if len(_terminal_records(shared)) == N_JOBS:
            break

    # ---- zero lost jobs
    recs = _terminal_records(shared)
    assert len(recs) == N_JOBS, (
        f"lost jobs: {sorted(set(f'job-{i}' for i in range(N_JOBS)) - set(recs))}")
    assert FileArchive(shared).search(status=list(J.OPEN_STATUSES),
                                      limit=500) == []

    # ---- zero double-scored jobs: the three replicas' terminal-verdict
    # sets are pairwise disjoint (ownership + CAS adoption)
    sets = {"A": A.scored, "B": b_scored_before_kill, "C": C.scored}
    for x in sets:
        for y in sets:
            if x < y:
                dup = sets[x] & sets[y]
                assert not dup, f"double-scored by {x} and {y}: {sorted(dup)}"
    assert sets["A"] | sets["B"] | sets["C"] == set(recs)

    # ---- verdicts byte-identical to the single-replica run
    for jid in sorted(recs):
        assert _verdict(recs[jid], with_reason=True) == \
            _verdict(baseline[jid], with_reason=True), jid
    # and the anomaly split is the seeded one
    unhealthy = {jid for jid, rec in recs.items()
                 if rec["status"] == J.COMPLETED_UNHEALTH}
    assert unhealthy == ANOMALOUS

    # ---- the incident is observable: membership + adoption events landed
    events = [e["type"] for r in (A, C)
              for e in r.analyzer.flight.snapshot(limit=200)]
    assert "replica-leave" in events
    assert "shard-rebalance" in events
    # adoption events name the adopting replica's live cycle id (the
    # releasing side's id rides each job's provenance handoff hops)
    adoptions = [e for r in (A, C)
                 for e in r.analyzer.flight.snapshot(limit=200)
                 if e["type"] == "shard-adoption"]
    assert adoptions
    # scope the cycle-id check to the POST-KILL adoptions (the dead
    # holder's jobs): the initial-distribution adoptions on lap 1 land
    # before the adopting replica's first engine cycle, so their events
    # honestly carry cycle_id "" (sharding._cycle_id documents that), and
    # whether those early events are still inside this bounded snapshot
    # depends on how many events the soak generated — not on the
    # correlation contract under test here
    post_kill = [e for e in adoptions
                 if set(e["detail"].get("jobs") or []) & b_open_ids]
    assert post_kill, adoptions
    assert all(e["detail"]["cycle_id"] for e in post_kill), post_kill
    # ---- detection latency was measured across the soak (all-canary
    # fleet here; the per-class criterion is tests/test_fleet_plane.py)
    for r in (A, C):
        dig = r.analyzer.slo.digest()
        assert dig.get("canary", {}).get("n", 0) > 0, r.rid
