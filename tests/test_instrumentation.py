"""Instrumentation tests — CommonMetricsFilter semantics mirror the
reference's CommonMetricsFilterTest (SURVEY.md §4)."""

from foremast_tpu.examples.demo_app import Generator, build_demo, demo_app
from foremast_tpu.instrumentation import (
    CommonMetricsFilter,
    MetricsMiddleware,
    MetricsRegistry,
)


# ------------------------------------------------------------------ filter
def test_filter_disabled_accepts_everything():
    f = CommonMetricsFilter(enabled=False, blacklist="jvm.threads")
    assert f.accepts("jvm.threads")
    assert f.accepts("anything.else")


def test_filter_whitelist_blacklist_prefix_tagrules():
    f = CommonMetricsFilter(
        enabled=True,
        whitelist="http_server_requests",
        blacklist="jvm.gc.pause",
        prefixes="tomcat",
        tag_rules="caller:loadgen",
    )
    assert f.accepts("http.server.requests")  # whitelist, _ -> . normalized
    assert not f.accepts("jvm.gc.pause")  # blacklist
    assert f.accepts("tomcat.threads.busy")  # prefix
    assert f.accepts("random.metric", {"caller": "loadgen"})  # tag rule
    assert not f.accepts("random.metric", {"caller": "other"})
    assert not f.accepts("random.metric")  # default closed


def test_filter_runtime_enable_disable():
    f = CommonMetricsFilter(enabled=True, blacklist="a.b")
    assert not f.accepts("a.b")
    f.enable_metric("a_b")  # normalization applies
    assert f.accepts("a.b")
    f.disable_metric("a.b")
    assert not f.accepts("a.b")


def test_filter_invalid_tag_rule_raises():
    import pytest

    with pytest.raises(ValueError):
        CommonMetricsFilter(enabled=True, tag_rules="noseparator")


# ---------------------------------------------------------------- registry
def test_registry_counters_and_timers_render():
    r = MetricsRegistry(common_tags={"app": "demo"})
    r.counter("requests.total.count", {"status": "200"}, 3)
    r.timer("http_server_requests", {"status": "200"}, 0.25)
    r.timer("http_server_requests", {"status": "200"}, 0.75)
    out = r.render()
    assert 'requests_total_count_total{app="demo",status="200"} 3.0' in out
    assert 'http_server_requests_seconds_count{app="demo",status="200"} 2' in out
    assert 'http_server_requests_seconds_sum{app="demo",status="200"} 1.0' in out
    assert 'http_server_requests_seconds_max{app="demo",status="200"} 0.75' in out


def test_registry_respects_filter():
    f = CommonMetricsFilter(enabled=True, whitelist="kept")
    r = MetricsRegistry(metrics_filter=f)
    r.counter("kept")
    r.counter("dropped")
    out = r.render()
    assert "kept_total" in out and "dropped" not in out


# -------------------------------------------------------------- middleware
def _call(app, path, method="GET", headers=None):
    environ = {"PATH_INFO": path, "REQUEST_METHOD": method, **(headers or {})}
    captured = {}

    def sr(status, hdrs, exc_info=None):
        captured["status"] = status
        captured["headers"] = hdrs

    body = b"".join(app(environ, sr))
    return captured.get("status", ""), body


def test_middleware_times_requests_with_tags():
    app = MetricsMiddleware(demo_app, app_name="demo")
    _call(app, "/", headers={"HTTP_X_CALLER": "svc-b"})
    _call(app, "/error5xx")
    status, body = _call(app, "/actuator/prometheus")
    text = body.decode()
    assert status.startswith("200")
    assert 'status="200"' in text and 'caller="svc-b"' in text
    assert 'status="502"' in text and 'uri="/error5xx"' in text
    assert 'app="demo"' in text


def test_middleware_preregisters_error_statuses():
    app = MetricsMiddleware(demo_app, app_name="demo")
    _, body = _call(app, "/actuator/prometheus")
    text = body.decode()
    for code in ("403", "404", "501", "502"):
        assert f'status="{code}"' in text  # series exist at zero from boot
    assert 'uri="/**"' in text


def test_middleware_toggle_endpoints():
    f = CommonMetricsFilter(enabled=True, whitelist="http_server_requests")
    reg = MetricsRegistry(metrics_filter=f)
    app = MetricsMiddleware(demo_app, registry=reg, init_statuses=())
    status, body = _call(app, "/k8s-metrics/disable/http_server_requests")
    assert status.startswith("200") and b"disabled" in body
    _call(app, "/")
    _, body = _call(app, "/actuator/prometheus")
    assert b"http_server_requests_seconds_count" not in body
    _call(app, "/k8s-metrics/enable/http_server_requests")
    _call(app, "/")
    _, body = _call(app, "/actuator/prometheus")
    assert b"http_server_requests_seconds_count" in body


def test_middleware_exception_records_500():
    def boom(environ, start_response):
        raise RuntimeError("kaput")

    app = MetricsMiddleware(boom, app_name="demo", init_statuses=())
    import pytest

    with pytest.raises(RuntimeError):
        _call(app, "/explode")
    text = app.registry.render()
    assert 'status="500"' in text and 'exception="RuntimeError"' in text


# ---------------------------------------------------------------- demo app
def test_demo_generators_produce_error_series():
    app, registry, _ = build_demo("demo-v2")
    gen = Generator(app, "/error5xx", per_second=100, caller="errorgen")
    gen.hit(25)
    text = registry.render()
    assert 'status="502"' in text
    line = next(
        l for l in text.splitlines()
        if "seconds_count" in l and 'status="502"' in l and 'caller="errorgen"' in l
    )
    assert float(line.rsplit(" ", 1)[1]) == 25


# ---------------------------------------------------------------- ASGI twin
def _run(coro):
    import asyncio

    return asyncio.run(coro)


def _asgi_call(mw, path, method="GET", headers=(), raise_exc=False):
    """Drive one request; returns (status, body)."""
    out = {"status": None, "body": b""}

    async def app(scope, receive, send):
        if raise_exc:
            raise RuntimeError("boom")
        await send({"type": "http.response.start",
                    "status": 502 if path == "/error5xx" else 200,
                    "headers": []})
        await send({"type": "http.response.body", "body": b"ok"})

    async def send(message):
        if message["type"] == "http.response.start":
            out["status"] = message["status"]
        else:
            out["body"] += message.get("body", b"")

    async def receive():
        return {"type": "http.request"}

    m = mw(app)
    scope = {"type": "http", "path": path, "method": method,
             "headers": [(k.encode(), v.encode()) for k, v in headers]}

    async def drive():
        await m(scope, receive, send)

    if raise_exc:
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            _run(drive())
    else:
        _run(drive())
    return m, out["status"], out["body"]


def test_asgi_records_same_series_as_wsgi():
    from foremast_tpu.instrumentation import AsgiMetricsMiddleware

    registry = MetricsRegistry(common_tags={"app": "demo"})
    mw = lambda app: AsgiMetricsMiddleware(app, registry=registry)  # noqa: E731
    _asgi_call(mw, "/error5xx", headers=[("x-caller", "loadgen")])
    text = registry.render()
    assert 'status="502"' in text
    assert 'caller="loadgen"' in text
    assert 'app="demo"' in text
    assert "http_server_requests_seconds_count" in text
    # pre-registered error statuses exist at zero (starter parity)
    assert 'status="404"' in text


def test_asgi_scrape_and_toggle_endpoints():
    from foremast_tpu.instrumentation import AsgiMetricsMiddleware

    registry = MetricsRegistry()
    m, status, body = _asgi_call(
        lambda app: AsgiMetricsMiddleware(app, registry=registry), "/")
    # scrape endpoint returns the rendered registry
    _, status2, body2 = _asgi_call(lambda app: m, "/actuator/prometheus")
    assert status2 == 200 and b"http_server_requests" in body2
    _, status3, body3 = _asgi_call(lambda app: m, "/k8s-metrics/disable/http_server_requests")
    assert status3 == 200 and b"disabled" in body3
    _, status4, _ = _asgi_call(lambda app: m, "/k8s-metrics/bogus")
    assert status4 == 404


def test_asgi_exception_tagged_500():
    from foremast_tpu.instrumentation import AsgiMetricsMiddleware

    registry = MetricsRegistry()
    _asgi_call(lambda app: AsgiMetricsMiddleware(app, registry=registry),
               "/x", raise_exc=True)
    text = registry.render()
    assert 'status="500"' in text
    assert 'exception="RuntimeError"' in text


def test_asgi_passes_through_non_http_scopes():
    from foremast_tpu.instrumentation import AsgiMetricsMiddleware

    called = {}

    async def app(scope, receive, send):
        called["scope"] = scope["type"]

    m = AsgiMetricsMiddleware(app, registry=MetricsRegistry())
    _run(m({"type": "lifespan"}, None, None))
    assert called["scope"] == "lifespan"
