"""Engine: state machine, lease takeover, and the end-to-end scoring slice.

The e2e test is SURVEY.md §7's "minimum end-to-end slice": a synthetic
ErrorGenerator scenario (reference demo app self-inflicts 5xx) through a
fixture data source -> job -> batched TPU-kernel scoring -> verdict.
"""
import os
import time

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource, VerdictExporter
from foremast_tpu.engine import Analyzer, Document, EngineConfig, JobStore, MetricQueries
from foremast_tpu.engine import jobs as J
from foremast_tpu.utils.timeutils import to_rfc3339


# ---------------------------------------------------------------- state machine
def test_status_machine_happy_path():
    store = JobStore()
    doc, created = store.create(Document(id="j1", app_name="a", strategy="canary",
                                         start_time="", end_time=""))
    assert created and doc.status == J.INITIAL
    store.transition("j1", J.PREPROCESS_INPROGRESS)
    store.transition("j1", J.PREPROCESS_COMPLETED)
    store.transition("j1", J.POSTPROCESS_INPROGRESS)
    store.transition("j1", J.COMPLETED_UNHEALTH, reason="bad")
    assert store.get("j1").status == J.COMPLETED_UNHEALTH
    assert J.to_external(J.COMPLETED_UNHEALTH) == "anomaly"
    assert J.to_external(J.INITIAL) == "new"
    assert J.to_external(J.PREPROCESS_FAILED) == "abort"


def test_invalid_transition_rejected():
    store = JobStore()
    store.create(Document(id="j1", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    with pytest.raises(J.InvalidTransition):
        store.transition("j1", J.COMPLETED_HEALTH)


def test_create_dedupes_open_jobs():
    store = JobStore()
    d1, c1 = store.create(Document(id="x", app_name="a", strategy="canary",
                                   start_time="", end_time=""))
    d2, c2 = store.create(Document(id="x", app_name="a", strategy="canary",
                                   start_time="", end_time=""))
    assert c1 and not c2 and d1 is d2
    # terminal jobs may be recreated
    store.transition("x", J.ABORT)
    _, c3 = store.create(Document(id="x", app_name="a", strategy="canary",
                                  start_time="", end_time=""))
    assert c3


def test_stuck_job_takeover():
    store = JobStore()
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    got = store.claim_open_jobs("w1", max_stuck_seconds=90)
    assert [d.id for d in got] == ["j"]
    # w2 cannot steal a fresh lease
    assert store.claim_open_jobs("w2", max_stuck_seconds=90) == []
    # ...but can steal an expired one
    store.get("j").lease_at -= 120
    got2 = store.claim_open_jobs("w2", max_stuck_seconds=90)
    assert [d.id for d in got2] == ["j"]
    assert store.get("j").lease_holder == "w2"


def test_snapshot_resume(tmp_path):
    p = str(tmp_path / "snap.json")
    store = JobStore(snapshot_path=p)
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time="",
                          metrics={"error5xx": MetricQueries(current="u1")}))
    store.flush()  # write-behind store: boundaries flush explicitly
    store2 = JobStore(snapshot_path=p)
    doc = store2.get("j")
    assert doc is not None and doc.metrics["error5xx"].current == "u1"


def test_snapshot_background_flusher_writes_without_explicit_flush(tmp_path):
    """Mutations persist via the background flusher alone (write-behind
    durability: snapshot at most ~1 s stale with no flush() call)."""
    p = str(tmp_path / "snap.json")
    store = JobStore(snapshot_path=p)
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if os.path.exists(p) and JobStore(snapshot_path=str(p)).get("j"):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("background flusher never wrote the snapshot")
    store.close()


def test_store_close_flushes_and_is_idempotent(tmp_path):
    p = str(tmp_path / "snap.json")
    store = JobStore(snapshot_path=p)
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    store.close()
    store.close()  # second close is a no-op, not an error
    assert JobStore(snapshot_path=p).get("j") is not None


# ---------------------------------------------------------------- e2e slice
STEP = 60


def _series(rng, level, n, spread=None):
    spread = level * 0.1 + 0.01 if spread is None else spread
    ts = np.arange(n) * STEP
    return ts.tolist(), np.clip(rng.normal(level, spread, n), 0, None).tolist()


def _mk_job(store, fixtures, job_id, *, bad=False, end_time=0.0, rng=None):
    """Canary job: healthy baseline ~0.5 err/s; canary 5 err/s if bad."""
    rng = rng or np.random.default_rng(0)
    cur_url = f"http://prom/{job_id}/cur"
    base_url = f"http://prom/{job_id}/base"
    hist_url = f"http://prom/{job_id}/hist"
    fixtures[cur_url] = _series(rng, 5.0 if bad else 0.5, 30)
    fixtures[base_url] = _series(rng, 0.5, 30)
    fixtures[hist_url] = _series(rng, 0.5, 600)
    doc = Document(
        id=job_id, app_name=f"app-{job_id}", namespace="demo", strategy="canary",
        start_time=to_rfc3339(0.0), end_time=to_rfc3339(end_time),
        metrics={"error5xx": MetricQueries(current=cur_url, baseline=base_url,
                                           historical=hist_url)},
    )
    store.create(doc)
    return doc


def test_e2e_slice_bad_canary_flagged_good_passes():
    rng = np.random.default_rng(7)
    fixtures = {}
    store = JobStore()
    exporter = VerdictExporter()
    _mk_job(store, fixtures, "bad", bad=True, rng=rng)
    _mk_job(store, fixtures, "good", bad=False, rng=rng)
    analyzer = Analyzer(EngineConfig(pairwise_threshold=1e-4), FixtureDataSource(fixtures),
                        store, exporter)
    outcomes = analyzer.run_cycle(now=10_000.0)  # past endTime
    assert outcomes["bad"] == J.COMPLETED_UNHEALTH
    assert outcomes["good"] == J.COMPLETED_HEALTH
    bad = store.get("bad")
    assert "error5xx" in bad.reason
    assert bad.anomaly  # flat [ts, v, ...] payload present
    pairs = next(iter(bad.anomaly.values()))
    assert len(pairs) >= 2 and len(pairs) % 2 == 0
    # exporter published foremastbrain series
    text = exporter.render()
    assert "foremastbrain:error5xx_upper" in text
    assert 'app="app-bad"' in text


def test_e2e_healthy_before_endtime_requeues():
    rng = np.random.default_rng(3)
    fixtures = {}
    store = JobStore()
    _mk_job(store, fixtures, "j", bad=False, end_time=5_000_000.0, rng=rng)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    outcomes = analyzer.run_cycle(now=100.0)  # before endTime
    assert outcomes["j"] == J.INITIAL  # fail-fast: keep watching
    # bad data arriving on a later cycle flips it
    fixtures[f"http://prom/j/cur"] = _series(rng, 8.0, 30)
    outcomes = analyzer.run_cycle(now=200.0)
    assert outcomes["j"] == J.COMPLETED_UNHEALTH


def test_e2e_fetch_failure_marks_preprocess_failed():
    store = JobStore()
    doc = Document(id="j", app_name="a", namespace="d", strategy="canary",
                   start_time=to_rfc3339(0), end_time=to_rfc3339(0),
                   metrics={"error5xx": MetricQueries(current="http://nope")})
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource({}), store)
    out = analyzer.run_cycle()
    # failed in preprocess, never judged — and the outcome is REPORTED
    # (degraded-mode bookkeeping prunes warm state off these outcomes)
    assert out == {"j": J.PREPROCESS_FAILED}
    assert store.get("j").status == J.PREPROCESS_FAILED
    assert J.to_external(store.get("j").status) == "abort"


def test_e2e_no_data_is_unknown():
    store = JobStore()
    fixtures = {"u": ([], [])}
    doc = Document(id="j", app_name="a", namespace="d", strategy="canary",
                   start_time=to_rfc3339(0), end_time=to_rfc3339(0),
                   metrics={"error5xx": MetricQueries(current="u")})
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=100.0)
    assert out["j"] == J.COMPLETED_UNKNOWN


def test_hpa_job_emits_logs_and_requeues():
    rng = np.random.default_rng(5)
    fixtures = {}
    store = JobStore()
    exporter = VerdictExporter()
    tps_url, sla_url = "http://prom/tps", "http://prom/sla"
    hist_ts, hist_v = _series(rng, 100.0, 90, spread=3.0)
    cur_ts = [t + hist_ts[-1] + STEP for t in np.arange(30) * STEP]
    fixtures[tps_url] = (hist_ts + list(cur_ts),
                         hist_v + np.random.default_rng(1).normal(240, 5, 30).tolist())
    fixtures[sla_url] = _series(rng, 5.0, 120, spread=0.3)
    doc = Document(
        id="app:demo:hpa", app_name="app", namespace="demo", strategy="hpa",
        start_time="START_TIME", end_time="END_TIME",
        metrics={
            "tps": MetricQueries(historical=tps_url, current=tps_url, priority=0),
            "latency": MetricQueries(historical=sla_url, current=sla_url, priority=1),
        },
    )
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store, exporter)
    out = analyzer.run_cycle(now=0.0)
    assert out["app:demo:hpa"] == J.INITIAL  # hpa jobs never terminate
    logs = store.hpalogs_for("app:demo:hpa")
    assert logs and logs[0].details[0]["metricType"] == "tps"
    assert "foremastbrain:namespace_app_per_pod:hpa_score" in exporter.render()
    # first cycle is breath-gated to 50
    assert logs[0].hpascore == 50.0


# -------------------------------------------------- review-finding regressions
def test_continuous_job_never_completes_while_healthy():
    rng = np.random.default_rng(2)
    fixtures = {}
    store = JobStore()
    ts = (np.arange(60) * STEP).tolist()
    fixtures["cu"] = (ts, rng.normal(0.5, 0.05, 60).clip(0).tolist())
    fixtures["hu"] = ((np.arange(600) * STEP).tolist(),
                      rng.normal(0.5, 0.05, 600).clip(0).tolist())
    doc = Document(id="c", app_name="a", namespace="d", strategy="continuous",
                   start_time="START_TIME", end_time="END_TIME",
                   metrics={"error5xx": MetricQueries(current="cu", historical="hu")})
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    for cycle in range(3):
        out = analyzer.run_cycle(now=1000.0 + cycle)
        assert out["c"] == J.INITIAL  # healthy continuous jobs loop forever


def test_continuous_job_survives_transient_fetch_error():
    store = JobStore()
    fixtures = {}
    doc = Document(id="c", app_name="a", namespace="d", strategy="continuous",
                   start_time="START_TIME", end_time="END_TIME",
                   metrics={"m": MetricQueries(current="missing")})
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    analyzer.run_cycle(now=100.0)
    assert store.get("c").status == J.INITIAL  # requeued, not dead
    # one-shot canary jobs DO fail terminally on fetch errors
    doc2 = Document(id="k", app_name="a", namespace="d", strategy="canary",
                    start_time=to_rfc3339(0), end_time=to_rfc3339(0),
                    metrics={"m": MetricQueries(current="missing")})
    store.create(doc2)
    analyzer.run_cycle(now=100.0)
    assert store.get("k").status == J.PREPROCESS_FAILED


def test_empty_current_is_unknown_not_healthy():
    rng = np.random.default_rng(4)
    store = JobStore()
    ts = (np.arange(30) * STEP).tolist()
    fixtures = {
        "cu": ([], []),  # deployment produced NO metrics
        "bu": (ts, rng.normal(0.5, 0.05, 30).tolist()),
        "hu": ((np.arange(600) * STEP).tolist(),
               rng.normal(0.5, 0.05, 600).tolist()),
    }
    doc = Document(id="j", app_name="a", namespace="d", strategy="canary",
                   start_time=to_rfc3339(0), end_time=to_rfc3339(0),
                   metrics={"error5xx": MetricQueries(current="cu", baseline="bu",
                                                      historical="hu")})
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=100.0)
    assert out["j"] == J.COMPLETED_UNKNOWN  # silence is not health


def test_band_anomaly_timestamps_on_current_grid():
    rng = np.random.default_rng(6)
    store = JobStore()
    hist_n = 600
    hist_ts = (np.arange(hist_n) * STEP).tolist()
    cur_start = 900_000.0  # current window far from historical grid's end
    cur_ts = (cur_start + np.arange(30) * STEP).tolist()
    fixtures = {
        "cu": (cur_ts, rng.normal(8.0, 0.3, 30).tolist()),
        "hu": (hist_ts, rng.normal(0.5, 0.05, hist_n).tolist()),
    }
    doc = Document(id="j", app_name="a", namespace="d", strategy="canary",
                   start_time=to_rfc3339(0), end_time=to_rfc3339(0),
                   metrics={"error5xx": MetricQueries(current="cu", historical="hu")})
    store.create(doc)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=1_000_000.0)
    assert out["j"] == J.COMPLETED_UNHEALTH
    pairs = next(iter(store.get("j").anomaly.values()))
    stamps = pairs[0::2]
    assert all(cur_start <= t < cur_start + 30 * STEP for t in stamps), stamps


def test_exporter_sanitizes_metric_names():
    from foremast_tpu.dataplane import VerdictExporter

    ex = VerdictExporter()
    ex.record_bounds("a", "ns", 'x{y} 1\nfake_series 99', 1.0, 0.0, 0.0)
    text = ex.render()
    assert "fake_series 99" not in text.replace("x_y__1_fake_series_99", "")
    for line in text.strip().splitlines():
        assert line.startswith("foremastbrain:"), line


# -------------------------------------------------- multivariate (LSTM) mode
def _multi_job(fixtures, *, bad, n_h=256, n_c=16):
    t_h = np.arange(n_h)
    t_c = n_h + np.arange(n_c)
    rng = np.random.default_rng(11)
    for i, name in enumerate(("latency", "cpu", "tps")):
        wave_h = np.sin(2 * np.pi * t_h / 32 + i) + rng.normal(0, 0.05, n_h)
        wave_c = np.sin(2 * np.pi * t_c / 32 + i) + rng.normal(0, 0.05, n_c)
        if bad and name == "tps":
            wave_c = wave_c + 6.0  # decorrelated level shift
        fixtures[f"h{i}"] = ((t_h * STEP).tolist(), wave_h.tolist())
        fixtures[f"c{i}"] = ((t_c * STEP).tolist(), wave_c.tolist())
    return Document(
        id="multi", app_name="app", namespace="d", strategy="canary",
        start_time=to_rfc3339(0), end_time=to_rfc3339(0),
        metrics={
            name: MetricQueries(current=f"c{i}", historical=f"h{i}")
            for i, name in enumerate(("latency", "cpu", "tps"))
        },
    )


def _lstm_cfg():
    return EngineConfig(algorithm="lstm_autoencoder", lstm_window=16,
                        lstm_epochs=60, lstm_hidden=8, lstm_latent=4,
                        policies={})


def test_engine_lstm_mode_flags_multivariate_anomaly():
    fixtures = {}
    store = JobStore()
    store.create(_multi_job(fixtures, bad=True))
    analyzer = Analyzer(_lstm_cfg(), FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=1_000_000.0)
    assert out["multi"] == J.COMPLETED_UNHEALTH
    assert "LSTM-AE" in store.get("multi").reason


def test_engine_lstm_mode_passes_healthy_and_caches_model():
    fixtures = {}
    store = JobStore()
    store.create(_multi_job(fixtures, bad=False))
    analyzer = Analyzer(_lstm_cfg(), FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=1_000_000.0)
    assert out["multi"] == J.COMPLETED_HEALTH
    assert len(analyzer._lstm_cache) == 1
    # second job for the same app reuses the cached model (no retrain)
    store.create(_multi_job(fixtures, bad=False))
    analyzer.run_cycle(now=1_000_001.0)
    assert len(analyzer._lstm_cache) == 1


# ------------------------------------------------- fetch_window fallback path
# These live here (NOT in test_native.py, which skips wholesale without a
# toolchain) because they are exactly the coverage for the no-native case.
def _prom_raw(series):
    import json as _json

    return _json.dumps({
        "status": "success",
        "data": {"resultType": "matrix",
                 "result": [{"metric": {}, "values": [[t, str(v)] for t, v in s]}
                            for s in series]},
    }).encode()


def test_fetch_window_matches_fetch_plus_grid():
    """RawFixtureDataSource.fetch_window == grid_from_series(fetch(url)) —
    the two engine paths stay equivalent whether or not native is built."""
    from foremast_tpu.dataplane.fetch import RawFixtureDataSource, grid_from_series

    t0 = 1_700_000_000 // 60 * 60
    raw = _prom_raw([[(t0 + 60 * i, float(i) * 1.5) for i in range(100)]])
    src = RawFixtureDataSource({"http://q": raw})
    win = src.fetch_window("http://q")
    ts, vals = src.fetch("http://q")
    want = grid_from_series(ts, vals)
    assert win.start == want.start and win.step == want.step
    np.testing.assert_array_equal(win.values, want.values)
    np.testing.assert_array_equal(win.mask, want.mask)
    assert src.requests == ["http://q", "http://q"]


def test_fetch_window_empty_body_parity_any_step():
    """Empty responses produce the same 1-slot empty Window (including
    step) on both the native and pure-Python paths."""
    from foremast_tpu.dataplane.fetch import window_from_prometheus_body

    raw = _prom_raw([])
    for step in (60, 300):
        w = window_from_prometheus_body(raw, step=step)
        assert len(w.values) == 1 and not w.mask.any()
        assert w.start == 0 and w.step == step


def test_caching_source_caches_windows_separately():
    from foremast_tpu.dataplane.fetch import (
        CachingDataSource,
        FixtureDataSource,
        RawFixtureDataSource,
    )

    t0 = 1_700_000_000 // 60 * 60
    raw = _prom_raw([[(t0 + 60 * i, 2.0) for i in range(10)]])
    inner = RawFixtureDataSource({"http://q": raw})
    src = CachingDataSource(inner, ttl_seconds=60.0)
    w1 = src.fetch_window("http://q")
    w2 = src.fetch_window("http://q")
    assert w2 is w1 and src.hits == 1  # second hit served from cache
    src.fetch("http://q")  # parsed-series entry is a SEPARATE key
    assert src.misses == 2
    # non-byte inner -> fetch_window signals "use fetch()"
    plain = CachingDataSource(FixtureDataSource({"u": ([1], [1.0])}))
    assert plain.fetch_window("u") is None


def test_document_to_json_covers_every_dataclass_field():
    """to_json is hand-rolled for flush speed; this pins it against the
    dataclass so adding a field without serializing it fails here."""
    import dataclasses

    doc = Document(id="j", app_name="a", strategy="canary",
                   start_time="s", end_time="e",
                   metrics={"m": MetricQueries(current="u", priority=2)},
                   anomaly={"m": [1, 2.0]})
    d = doc.to_json()
    assert set(d) == {f.name for f in dataclasses.fields(Document)}
    assert set(d["metrics"]["m"]) == {
        f.name for f in dataclasses.fields(MetricQueries)
    }
    # the payload is detached: mutating it cannot corrupt the doc
    d["anomaly"]["m"].append(99)
    d["metrics"]["m"]["current"] = "x"
    assert doc.anomaly["m"] == [1, 2.0]
    assert doc.metrics["m"].current == "u"
    # and it round-trips
    assert Document.from_json(doc.to_json()) == doc


def test_advance_validates_each_hop_and_rejects_terminal():
    store = JobStore()
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    store.claim_open_jobs("w")
    store.advance("j", J.PREPROCESS_COMPLETED, J.POSTPROCESS_INPROGRESS,
                  worker="w")
    assert store.get("j").status == J.POSTPROCESS_INPROGRESS
    with pytest.raises(J.InvalidTransition):
        store.advance("j", J.COMPLETED_HEALTH)  # terminal -> transition()
    with pytest.raises(J.InvalidTransition):
        store.advance("j", J.PREPROCESS_COMPLETED)  # invalid hop


def test_wavefront_fetch_window_matches_fetch_plus_grid(monkeypatch):
    import json as _json

    from foremast_tpu.dataplane import fetch as F

    t0 = 1_700_000_000 // 60 * 60
    raw = _json.dumps({"timeseries": [
        {"data": [[t0 + 60 * i, float(i)] for i in range(50)]}
    ]}).encode()
    src = F.WavefrontDataSource()
    monkeypatch.setattr(src, "_raw", lambda url: raw)
    win = src.fetch_window("http://wf")
    ts, vals = src.fetch("http://wf")
    want = F.grid_from_series(ts, vals)
    assert win.start == want.start
    np.testing.assert_array_equal(win.values, want.values)
    np.testing.assert_array_equal(win.mask, want.mask)


def test_advance_failed_chain_leaves_doc_untouched():
    """advance() validates the whole chain before mutating: a bad chain
    must not leave the doc half-advanced (snapshot/live divergence)."""
    store = JobStore()
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    store.claim_open_jobs("w")
    before = store.get("j").modified_at
    with pytest.raises(J.InvalidTransition):
        store.advance("j", J.PREPROCESS_COMPLETED, J.COMPLETED_HEALTH)
    doc = store.get("j")
    assert doc.status == J.PREPROCESS_INPROGRESS  # unchanged
    assert doc.modified_at == before


def test_crash_resume_e2e_snapshot_plus_lease_takeover(tmp_path):
    """Checkpoint/resume, whole story: worker-1 claims a job and dies
    mid-flight (nothing scored, lease held); a replacement process
    restores the fleet from the SNAPSHOT, takes over the expired lease,
    and completes the verdict — the reference's MAX_STUCK_IN_SECONDS
    recovery (design.md:37-43) riding our snapshot instead of ES."""
    rng = np.random.default_rng(11)
    fixtures = {}
    snap = str(tmp_path / "snap.json")
    store1 = JobStore(snapshot_path=snap)
    _mk_job(store1, fixtures, "takeover", bad=True, rng=rng)
    # worker-1 claims (job -> preprocess_inprogress, lease held) then dies
    claimed = store1.claim_open_jobs("worker-1")
    assert [d.id for d in claimed] == ["takeover"]
    store1.flush()  # cycle-boundary flush happened before the crash

    # replacement process: fresh store from the snapshot
    store2 = JobStore(snapshot_path=snap)
    doc = store2.get("takeover")
    assert doc.status == J.PREPROCESS_INPROGRESS
    assert doc.lease_holder == "worker-1"
    analyzer = Analyzer(EngineConfig(pairwise_threshold=1e-4),
                        FixtureDataSource(fixtures), store2)
    # fresh lease: not stealable yet -> cycle is a no-op for this job
    out = analyzer.run_cycle(worker="worker-2", now=10_000.0)
    assert "takeover" not in out
    # age the lease past MAX_STUCK_IN_SECONDS -> takeover + full verdict
    store2.get("takeover").lease_at -= 120
    out = analyzer.run_cycle(worker="worker-2", now=10_000.0)
    assert out["takeover"] == J.COMPLETED_UNHEALTH
    assert store2.get("takeover").lease_holder == "worker-2"
    store2.close()
    # and the verdict itself survives another restart
    assert JobStore(snapshot_path=snap).get("takeover").status == \
        J.COMPLETED_UNHEALTH


def test_score_chunks_fixed_buckets_and_edge_padding():
    """_score_chunks: chunked results equal a single whole-batch call, and
    batch sizes map to FIXED buckets so fleet-size changes cannot force
    recompiles (B<=bucket pads up; B>chunk splits)."""
    from foremast_tpu.dataplane import FixtureDataSource

    eng = Analyzer(EngineConfig(score_batch=32), FixtureDataSource({}), JobStore())
    calls = []

    def fn(vals, mask):
        calls.append(vals.shape[0])
        return {"s": vals.sum(axis=1), "m": mask.any(axis=1)}

    rng = np.random.default_rng(0)
    vals = rng.normal(0, 1, (70, 8)).astype(np.float32)
    mask = rng.random((70, 8)) > 0.5
    out = eng._score_chunks(fn, [vals, mask])
    # full chunks launch at 32; the 6-row tail re-buckets DOWN the ladder
    assert calls == [32, 32, 16]
    np.testing.assert_allclose(out["s"], vals.sum(axis=1), rtol=1e-6)
    np.testing.assert_array_equal(out["m"], mask.any(axis=1))
    # small batches pad UP to a fixed bucket, not down to raw B
    calls.clear()
    eng._score_chunks(fn, [vals[:5], mask[:5]])
    assert calls == [16]


def test_e2e_fleet_crosses_chunk_rungs():
    """Chunk boundaries must not perturb results: a 70-job fleet scored
    with score_batch=32 (three launches: 32+32+16-padded) produces
    byte-identical outcomes to a single whole-fleet launch, and every
    truly-bad job is flagged either way."""
    def run(score_batch):
        rng = np.random.default_rng(5)
        fixtures = {}
        store = JobStore()
        for i in range(70):
            _mk_job(store, fixtures, f"j{i:02d}", bad=(i % 7 == 3), rng=rng)
        a = Analyzer(
            EngineConfig(pairwise_threshold=1e-4, score_batch=score_batch),
            FixtureDataSource(fixtures), store)
        return a.run_cycle(now=10_000.0)

    chunked = run(32)
    single = run(8192)  # 70 <= first rung: one launch
    assert chunked == single  # row<->job mapping survives chunking exactly
    bad_ids = {f"j{i:02d}" for i in range(70) if i % 7 == 3}
    flagged = {j for j, s in chunked.items() if s == J.COMPLETED_UNHEALTH}
    assert bad_ids <= flagged  # no false negatives (FPs are fixture noise)


def test_flusher_cadence_adapts_to_snapshot_cost(tmp_path):
    """The background flusher's interval stretches with the measured
    serialize+write cost (5x, capped 30 s) so huge stores don't pin a
    core re-serializing at 1 Hz, while small stores keep ~1 s cadence."""
    store = JobStore(snapshot_path=str(tmp_path / "s.json"))
    assert store._flush_cost == 0.0  # 1 Hz until measured
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    store.flush()
    assert 0.0 < store._flush_cost < 1.0  # tiny store: stays at 1 Hz floor
    # the PRODUCTION formula (floor 1 s, 5x cost, 30 s cap)
    for cost, want in ((0.01, 1.0), (1.5, 7.5), (60.0, 30.0)):
        store._flush_cost = cost
        assert store._flush_interval() == want
    store.close()


# ------------------------------------------- Holt-Winters period auto-detection
def _seasonal_band_job(period_steps=60, n_h=220, n_c=30, amp=2.0):
    """Healthy hourly-seasonal service: the current window CONTINUES the
    historical pattern."""
    rng = np.random.default_rng(9)
    t_all = np.arange(n_h + n_c)
    wave = 5.0 + amp * np.sin(2 * np.pi * t_all / period_steps) \
        + rng.normal(0, 0.05, n_h + n_c)
    fixtures = {
        "hu": ((t_all[:n_h] * STEP).tolist(), wave[:n_h].tolist()),
        "cu": ((t_all[n_h:] * STEP).tolist(), wave[n_h:].tolist()),
    }
    doc = Document(id="hwj", app_name="a", namespace="d", strategy="canary",
                   start_time=to_rfc3339(0), end_time=to_rfc3339(0),
                   metrics={"latency": MetricQueries(current="cu",
                                                     historical="hu")})
    return fixtures, doc


def test_hw_wrong_static_period_condemns_healthy_seasonal_service():
    """The round-3 verdict's missing capability, shown end-to-end: with the
    static daily default (clamped to the window), the HW band free-runs a
    wrong-phase season across the judged region and condemns a HEALTHY
    hourly-seasonal service; auto-detection picks the true cycle and the
    same service scores healthy. (SURVEY §7 hard part;
    reference spec docs/dynamic_autoscaling.md:28-44.)"""
    from foremast_tpu.engine.config import MetricPolicy

    for auto, expected in ((False, J.COMPLETED_UNHEALTH),
                           (True, J.COMPLETED_HEALTH)):
        fixtures, doc = _seasonal_band_job()
        store = JobStore()
        store.create(doc)
        cfg = EngineConfig(
            algorithm="holt_winters", hw_period_auto=auto,
            policies={"latency": MetricPolicy(threshold=3.0, bound=3,
                                              min_lower_bound=0.0)},
        )
        analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
        out = analyzer.run_cycle(now=1_000_000.0)
        assert out["hwj"] == expected, (auto, out)


def test_lstm_train_budget_amortizes_across_cycles():
    """A cold multi-metric fleet warms up under LSTM_MAX_TRAIN_PER_CYCLE
    instead of training every model in one cycle; capped-out jobs stay
    in progress (requeued) and train later."""
    fixtures = {}
    docs = []
    for j in range(3):
        rng = np.random.default_rng(20 + j)
        n_h, n_c = 128, 16
        for i, name in enumerate(("latency", "cpu", "tps")):
            w_h = rng.normal(10, 1, n_h)
            w_c = rng.normal(10, 1, n_c)
            fixtures[f"h{j}{i}"] = ((np.arange(n_h) * STEP).tolist(),
                                    w_h.tolist())
            fixtures[f"c{j}{i}"] = (((n_h + np.arange(n_c)) * STEP).tolist(),
                                    w_c.tolist())
        docs.append(Document(
            id=f"m{j}", app_name=f"app{j}", namespace="d", strategy="canary",
            start_time=to_rfc3339(0), end_time=to_rfc3339(1e9),
            metrics={name: MetricQueries(current=f"c{j}{i}",
                                         historical=f"h{j}{i}")
                     for i, name in enumerate(("latency", "cpu", "tps"))},
        ))
    store = JobStore()
    for d in docs:
        store.create(d)
    cfg = EngineConfig(algorithm="lstm_autoencoder", lstm_window=16,
                       lstm_epochs=3, lstm_hidden=8, lstm_latent=4,
                       lstm_max_train_per_cycle=1, policies={},
                       lstm_threshold=1e9)  # budget is under test, not detection
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    for cycle, expected_models in ((1, 1), (2, 2), (3, 3)):
        out = analyzer.run_cycle(now=100.0)
        assert len(analyzer._lstm_cache) == expected_models, (cycle, out)
        # nothing terminal: capped-out jobs requeue, trained ones are
        # healthy within the window and requeue too
        assert all(s == J.INITIAL for s in out.values()), out


def test_loss_window_is_measured_per_flush(tmp_path):
    """VERDICT r3 #8: the RAM-only exposure of accepted jobs is a
    measured gauge, not an assumption. Each flush records how long its
    oldest mutation lived unflushed; the open gauge tracks live dirt."""
    store = JobStore(snapshot_path=str(tmp_path / "s.json"))
    assert store.loss_window_open_seconds == 0.0
    # hold the background flusher off so the open-window gauge is
    # observable deterministically (production: it flushes ~1 Hz)
    store._closed = True
    store.create(Document(id="j", app_name="a", strategy="canary",
                          start_time="", end_time=""))
    time.sleep(0.05)
    open_w = store.loss_window_open_seconds
    assert open_w >= 0.05
    store._closed = False
    store.flush()
    assert store.loss_window_last_seconds >= 0.05
    assert store.loss_window_max_seconds >= store.loss_window_last_seconds
    assert store.loss_window_open_seconds == 0.0  # everything durable
    # a second, faster flush keeps max at the worst case
    store.transition("j", J.PREPROCESS_INPROGRESS)
    store.flush()
    assert store.loss_window_max_seconds >= 0.05
    store.close()


def test_lstm_fleet_scoring_path_engages(monkeypatch):
    """>=4 same-shape multi jobs score through ONE vmapped launch
    (anomaly_scores_fleet) instead of per-job dispatches, with verdicts
    unchanged."""
    from foremast_tpu.models import lstm_ae as L

    calls = {"fleet": 0, "single": 0}
    real_fleet, real_single = L.anomaly_scores_fleet, L.anomaly_scores

    def spy_fleet(*a, **k):
        calls["fleet"] += 1
        return real_fleet(*a, **k)

    def spy_single(*a, **k):
        calls["single"] += 1
        return real_single(*a, **k)

    monkeypatch.setattr(L, "anomaly_scores_fleet", spy_fleet)
    monkeypatch.setattr(L, "anomaly_scores", spy_single)

    fixtures = {}
    docs = []
    n_h, n_c = 128, 16
    for j in range(5):
        rng = np.random.default_rng(40 + j)
        for i, name in enumerate(("latency", "cpu", "tps")):
            fixtures[f"h{j}{i}"] = ((np.arange(n_h) * STEP).tolist(),
                                    rng.normal(10, 1, n_h).tolist())
            fixtures[f"c{j}{i}"] = (((n_h + np.arange(n_c)) * STEP).tolist(),
                                    rng.normal(10, 1, n_c).tolist())
        docs.append(Document(
            id=f"m{j}", app_name=f"app{j}", namespace="d", strategy="canary",
            start_time=to_rfc3339(0), end_time=to_rfc3339(1e9),
            metrics={name: MetricQueries(current=f"c{j}{i}",
                                         historical=f"h{j}{i}")
                     for i, name in enumerate(("latency", "cpu", "tps"))},
        ))
    store = JobStore()
    for d in docs:
        store.create(d)
    cfg = EngineConfig(algorithm="lstm_autoencoder", lstm_window=16,
                       lstm_epochs=3, lstm_hidden=8, lstm_latent=4,
                       policies={}, lstm_threshold=1e9)
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=100.0)
    assert all(s == J.INITIAL for s in out.values()), out
    assert calls["fleet"] >= 1, calls
    # anomaly_scores_fleet's jitted body resolves anomaly_scores from the
    # module namespace at trace time, so the spy fires once during the
    # trace — what must NOT happen is one dispatch per job (5 calls)
    assert calls["single"] <= 1, calls


def test_lstm_same_app_jobs_share_one_training_slot():
    """N jobs of one app share a cache key: a cold cycle must train ONE
    model for them (one budget slot), and all N score from it — not N
    redundant trainings draining the warm-up budget."""
    fixtures = {}
    docs = []
    n_h, n_c = 128, 16
    rng = np.random.default_rng(50)
    for i, name in enumerate(("latency", "cpu", "tps")):
        fixtures[f"h{i}"] = ((np.arange(n_h) * STEP).tolist(),
                             rng.normal(10, 1, n_h).tolist())
        fixtures[f"c{i}"] = (((n_h + np.arange(n_c)) * STEP).tolist(),
                             rng.normal(10, 1, n_c).tolist())
    for j in range(3):  # three jobs, same app, same metrics
        docs.append(Document(
            id=f"dup{j}", app_name="one-app", namespace="d",
            strategy="canary",
            start_time=to_rfc3339(0), end_time=to_rfc3339(1e9),
            metrics={name: MetricQueries(current=f"c{i}",
                                         historical=f"h{i}")
                     for i, name in enumerate(("latency", "cpu", "tps"))},
        ))
    store = JobStore()
    for d in docs:
        store.create(d)
    cfg = EngineConfig(algorithm="lstm_autoencoder", lstm_window=16,
                       lstm_epochs=3, lstm_hidden=8, lstm_latent=4,
                       policies={}, lstm_threshold=1e9,
                       lstm_max_train_per_cycle=1)  # ONE slot suffices
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=100.0)
    assert len(analyzer._lstm_cache) == 1
    assert analyzer._lstm_trained_this_cycle == 1
    # all three jobs were judged (healthy requeue), none starved
    assert all(s == J.INITIAL for s in out.values()), out


# ------------------------- VERDICT r04 #2: HPA SLA modes + per-pod scoring
def _mk_hpa_job(store, fixtures, job_id, *, tps_current=240.0,
                sla_current=5.0, pods=None, rng=None, sla_absolute=True):
    """HPA job: history ~100 tps / ~5 latency; current window overridable;
    optional pod-count series (hist_pods -> now_pods)."""
    rng = rng or np.random.default_rng(5)
    # production-shaped windows: the current URL covers ONLY the trailing
    # scoring window, the historical URL the 90-step history before it —
    # the per-pod recent/older split keys off current.start, so a
    # current window spanning the whole series would wash it out
    hist_ts, hist_v = _series(rng, 100.0, 90, spread=3.0)
    cur_ts = [hist_ts[-1] + STEP + t for t in np.arange(30) * STEP]
    cur_url = f"http://prom/{job_id}/tps_cur"
    hist_url = f"http://prom/{job_id}/tps_hist"
    fixtures[hist_url] = (hist_ts, hist_v)
    fixtures[cur_url] = (cur_ts, rng.normal(tps_current, 5, 30).tolist())
    s_ts, s_v = _series(rng, 5.0, 90, spread=0.3)
    sla_cur_url = f"http://prom/{job_id}/sla_cur"
    sla_hist_url = f"http://prom/{job_id}/sla_hist"
    fixtures[sla_hist_url] = (s_ts, s_v)
    fixtures[sla_cur_url] = (cur_ts,
                             rng.normal(sla_current, 0.3, 30).tolist())
    pod_url = ""
    if pods is not None:
        hist_pods, now_pods = pods
        pod_url = f"http://prom/{job_id}/pods"
        fixtures[pod_url] = (hist_ts + cur_ts,
                            [hist_pods] * 90 + [now_pods] * 30)
    doc = Document(
        id=job_id, app_name=job_id, namespace="demo", strategy="hpa",
        start_time="START_TIME", end_time="END_TIME",
        metrics={
            "tps": MetricQueries(historical=hist_url, current=cur_url,
                                 priority=0),
            "latency": MetricQueries(historical=sla_hist_url,
                                     current=sla_cur_url,
                                     priority=1, is_absolute=sla_absolute),
        },
        pod_count_url=pod_url,
    )
    store.create(doc)
    return float(cur_ts[-1]) + STEP  # a "now" placing the last 30min window


def _raw_score(store, job_id):
    import re

    logs = store.hpalogs_for(job_id)
    m = re.search(r"raw ([0-9.]+)", logs[0].reason)
    return float(m.group(1))


def test_hpa_per_pod_score_absorbs_taken_scaleups():
    """podCountURL consumed (VERDICT r04 missing #3): traffic 2.4x with
    replicas already scaled 4->9.6 reads per-pod-neutral (~50); the same
    traffic with no pod data reads as a surge (>65)."""
    fixtures, store = {}, JobStore()
    now = _mk_hpa_job(store, fixtures, "nopods:demo:hpa")
    _mk_hpa_job(store, fixtures, "pods:demo:hpa", pods=(4.0, 9.6))
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    analyzer.run_cycle(now=now)
    assert _raw_score(store, "nopods:demo:hpa") > 65
    assert 35 <= _raw_score(store, "pods:demo:hpa") <= 65
    # the reason records the replica count + per-pod demand it used; the
    # details list stays strictly band-shaped {current, upper, lower} so
    # letter templating and wire consumers never render a replicas-vs-
    # demand tuple as a metric band (models.go:194-209)
    podded = store.hpalogs_for("pods:demo:hpa")[0]
    assert "[per-pod: 9.6 pods" in podded.reason
    assert {d["metricType"] for d in podded.details} == {"tps", "latency"}
    # and the no-pod job logs no per-pod context (nothing fabricated)
    assert "per-pod" not in store.hpalogs_for("nopods:demo:hpa")[0].reason


def test_hpa_sla_mode_static_env_plumbed():
    """ML_SLA_MODE=static + ML_SLA_LIMIT below the healthy latency level
    forces the SLA-violation scale-up path; the same data under the
    default dynamic mode stays trend-driven (limit ~ mean+3sigma)."""
    from foremast_tpu.engine.config import from_env

    fixtures, store = {}, JobStore()
    now = _mk_hpa_job(store, fixtures, "app:demo:hpa", tps_current=100.0)
    cfg = from_env({"ML_SLA_MODE": "static", "ML_SLA_LIMIT": "3.0"})
    assert cfg.sla_mode == "static" and cfg.sla_limit == 3.0
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    analyzer.run_cycle(now=now)
    assert "SLA violation" in store.hpalogs_for("app:demo:hpa")[0].reason

    store2 = JobStore()
    fixtures2 = {}
    now2 = _mk_hpa_job(store2, fixtures2, "app:demo:hpa", tps_current=100.0)
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures2), store2)
    analyzer.run_cycle(now=now2)
    assert "SLA violation" not in store2.hpalogs_for("app:demo:hpa")[0].reason


def test_hpa_static_mode_without_limit_degrades_to_dynamic():
    """A static/min mode with no limit configured anywhere must not
    invent one: the job scores under the dynamic criteria instead."""
    fixtures, store = {}, JobStore()
    now = _mk_hpa_job(store, fixtures, "app:demo:hpa", tps_current=100.0)
    analyzer = Analyzer(EngineConfig(sla_mode="static"),
                        FixtureDataSource(fixtures), store)
    analyzer.run_cycle(now=now)
    logs = store.hpalogs_for("app:demo:hpa")
    assert logs and "SLA violation" not in logs[0].reason
    # dynamic limit ~ mean+3sigma of healthy history (~5 +- 0.3) -> single
    # digits, not a 1e9 sentinel leaking into the log details
    sla_detail = [d for d in logs[0].details if d["metricType"] == "latency"]
    assert sla_detail and sla_detail[0]["upper"] < 100


def test_per_metric_sla_limit_env_override():
    from foremast_tpu.engine.config import from_env

    cfg = from_env({
        "metric_type_threshold_count": "1",
        "metric_type0": "latency",
        "sla_limit0": "250",
        "ML_SLA_MODE": "min",
    })
    assert cfg.policy_for("namespace_app_pod_latency").sla_limit == 250.0
    assert cfg.policy_for("error5xx").sla_limit == 0.0


def test_relative_sla_limit_requires_explicit_opt_in():
    """ML_SLA_LIMIT=250 quoted in ms must stay absolute under the wire
    isAbsolute flag's bare default (false); ML_SLA_LIMIT_RELATIVE=1 opts
    the fleet into the multiple-of-mean reading (limit 3x mean ~5 -> ~15,
    healthy ~5 passes; absolute 3.0 would violate — asserted above)."""
    from foremast_tpu.engine.config import from_env

    fixtures, store = {}, JobStore()
    now = _mk_hpa_job(store, fixtures, "app:demo:hpa", tps_current=100.0)
    cfg = from_env({"ML_SLA_MODE": "static", "ML_SLA_LIMIT": "250"})
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    analyzer.run_cycle(now=now)
    logs = store.hpalogs_for("app:demo:hpa")
    sla_detail = [d for d in logs[0].details if d["metricType"] == "latency"]
    assert abs(sla_detail[0]["upper"] - 250.0) < 1e-3  # absolute, not 250*mean

    fixtures2, store2 = {}, JobStore()
    now2 = _mk_hpa_job(store2, fixtures2, "app:demo:hpa", tps_current=100.0,
                       sla_absolute=False)  # un-flagged on the wire
    cfg = from_env({"ML_SLA_MODE": "static", "ML_SLA_LIMIT": "3.0",
                    "ML_SLA_LIMIT_RELATIVE": "1"})
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures2), store2)
    analyzer.run_cycle(now=now2)
    logs = store2.hpalogs_for("app:demo:hpa")
    assert "SLA violation" not in logs[0].reason  # 3x mean ~15 > current ~5
    sla_detail = [d for d in logs[0].details if d["metricType"] == "latency"]
    assert 10 < sla_detail[0]["upper"] < 20


def test_garbage_pod_count_body_never_fails_the_job():
    """podCountURL is an OPTIONAL signal: a proxy flattening errors to a
    200 with an unparseable body must degrade to the aggregate score,
    not crash preprocess for the job (or the cycle)."""
    fixtures, store = {}, JobStore()
    now = _mk_hpa_job(store, fixtures, "app:demo:hpa", pods=(4.0, 9.6))
    fixtures["http://prom/app:demo:hpa/pods"] = (["<html>"], ["oops"])
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=now)
    assert out["app:demo:hpa"] == J.INITIAL  # scored + requeued
    logs = store.hpalogs_for("app:demo:hpa")
    assert logs and "per-pod" not in logs[0].reason  # aggregate fallback


def test_hpa_fleet_with_heterogeneous_history_lengths():
    """HPA rows bucket by their own pack length: a lone long-history job
    must not inflate every short job's launch (and both must score)."""
    fixtures, store = {}, JobStore()
    now = _mk_hpa_job(store, fixtures, "short:demo:hpa")
    # a second job with a 7x longer history rides its own bucket
    rng = np.random.default_rng(9)
    hist_ts, hist_v = _series(rng, 100.0, 700, spread=3.0)
    cur_ts = [hist_ts[-1] + STEP + t for t in np.arange(30) * STEP]
    fixtures["http://prom/long/tps_hist"] = (hist_ts, hist_v)
    fixtures["http://prom/long/tps_cur"] = (cur_ts,
                                            rng.normal(240, 5, 30).tolist())
    s_ts, s_v = _series(rng, 5.0, 700, spread=0.3)
    fixtures["http://prom/long/sla_hist"] = (s_ts, s_v)
    fixtures["http://prom/long/sla_cur"] = (cur_ts,
                                            rng.normal(5, 0.3, 30).tolist())
    store.create(Document(
        id="long:demo:hpa", app_name="long", namespace="demo",
        strategy="hpa", start_time="START_TIME", end_time="END_TIME",
        metrics={
            "tps": MetricQueries(historical="http://prom/long/tps_hist",
                                 current="http://prom/long/tps_cur",
                                 priority=0),
            "latency": MetricQueries(historical="http://prom/long/sla_hist",
                                     current="http://prom/long/sla_cur",
                                     priority=1),
        },
    ))
    analyzer = Analyzer(EngineConfig(), FixtureDataSource(fixtures), store)
    outcomes = analyzer.run_cycle(now=now)
    assert outcomes == {"short:demo:hpa": J.INITIAL,
                        "long:demo:hpa": J.INITIAL}
    for job in ("short:demo:hpa", "long:demo:hpa"):
        logs = store.hpalogs_for(job)
        assert logs and 0.0 <= logs[0].hpascore <= 100.0


# ------------------------------------------- LSTM model-cache persistence
def test_lstm_cache_roundtrip_warm_starts_fresh_analyzer(tmp_path):
    """Train on one analyzer, save; a FRESH analyzer must, after load,
    judge the same app WITHOUT training (asserted via the param version,
    which every training bumps) — the restart warm-start the reference
    brain cannot do (its model cache was RAM-only)."""
    fixtures = {}
    store = JobStore()
    store.create(_multi_job(fixtures, bad=False))
    a1 = Analyzer(_lstm_cfg(), FixtureDataSource(fixtures), store)
    assert a1.run_cycle(now=1_000_000.0)["multi"] == J.COMPLETED_HEALTH
    path = str(tmp_path / "lstm_cache.msgpack")
    assert a1.save_lstm_cache(path) == 1

    # warm-start: load -> judged WITHOUT any training (training bumps
    # _lstm_param_version; it must not move past the loaded entries).
    # One warm analyzer per scenario: _multi_job writes fixed fixture
    # keys, so a healthy and a bad job cannot share one fixture dict.
    for bad, expected in ((False, J.COMPLETED_HEALTH),
                          (True, J.COMPLETED_UNHEALTH)):
        fixtures3 = {}
        store3 = JobStore()
        store3.create(_multi_job(fixtures3, bad=bad))
        warm = Analyzer(_lstm_cfg(), FixtureDataSource(fixtures3), store3)
        assert warm.load_lstm_cache(path) == 1
        v_loaded = warm._lstm_param_version
        out = warm.run_cycle(now=1_000_000.0)
        assert out["multi"] == expected
        assert warm._lstm_param_version == v_loaded  # no retrain happened


def test_lstm_cache_load_rejects_corrupt_and_mismatched(tmp_path):
    import dataclasses

    fixtures = {}
    store = JobStore()
    store.create(_multi_job(fixtures, bad=False))
    a1 = Analyzer(_lstm_cfg(), FixtureDataSource(fixtures), store)
    a1.run_cycle(now=1_000_000.0)
    path = str(tmp_path / "cache.msgpack")
    a1.save_lstm_cache(path)

    # corrupt bytes: load 0, no raise
    bad = tmp_path / "corrupt.msgpack"
    bad.write_bytes(b"\x93\x01\x02 not msgpack really \xff\xfe")
    fresh = Analyzer(_lstm_cfg(), FixtureDataSource({}), JobStore())
    assert fresh.load_lstm_cache(str(bad)) == 0
    assert fresh.load_lstm_cache(str(tmp_path / "absent")) == 0

    # architecture mismatch: a different hidden size must refuse the blob
    other = Analyzer(
        dataclasses.replace(_lstm_cfg(), lstm_hidden=16),
        FixtureDataSource({}), JobStore())
    assert other.load_lstm_cache(path) == 0
    # while the matching geometry accepts it
    match = Analyzer(_lstm_cfg(), FixtureDataSource({}), JobStore())
    assert match.load_lstm_cache(path) == 1
