"""The host-path cycle benchmark is itself product surface: the driver
runs it inside bench.py every round. Pin its contract — a steady
synthetic fleet (healthy jobs requeue forever), raw fixture bytes
flowing through the production Prometheus parse path, and sane stats."""
import json

import numpy as np
import pytest

from foremast_tpu import bench_cycle
from foremast_tpu.dataplane.fetch import (
    FetchError,
    RawFixtureDataSource,
    parse_prometheus_body,
)


def test_raw_fixture_source_parses_through_production_path():
    body = bench_cycle._prom_body(1_700_000_000 // 60 * 60, [1.5, 2.25, 3.0])
    src = RawFixtureDataSource(pages={"http://p/q": body})
    ts, vals = src.fetch("http://p/q")
    np.testing.assert_allclose(np.asarray(vals, float), [1.5, 2.25, 3.0])
    assert np.all(np.diff(np.asarray(ts, float)) == 60)
    assert src.requests == ["http://p/q"]
    with pytest.raises(FetchError):
        src.fetch("http://p/unknown")


def test_raw_fixture_source_error_status_raises():
    raw = json.dumps({"status": "error", "error": "boom"}).encode()
    src = RawFixtureDataSource(pages={"u": raw})
    with pytest.raises(FetchError):
        src.fetch("u")


def test_parse_prometheus_body_plain_python_parity():
    body = bench_cycle._prom_body(1_700_000_040, [9.875, 10.5])
    ts, vals = parse_prometheus_body(body)
    assert list(np.asarray(vals, float)) == [9.875, 10.5]


def test_concurrent_fetch_overlaps_store_latency():
    """The fetch pool's reason to exist: with a slow metric store the cycle
    must track store latency, not fleet size. Simulate 2 ms per fetch and
    compare a serial engine against the pooled one on identical fleets."""
    import dataclasses
    import time

    from foremast_tpu.dataplane.fetch import FixtureDataSource
    from foremast_tpu.engine import jobs as J
    from foremast_tpu.engine.analyzer import Analyzer
    from foremast_tpu.engine.config import EngineConfig
    from foremast_tpu.utils.timeutils import to_rfc3339

    t_end = 1_700_000_040 // 60 * 60
    series = ([float(t_end - (32 - i) * 60) for i in range(32)],
              [10.0] * 32)

    def slow_resolver(url):
        time.sleep(0.002)
        return series

    def build_engine(workers: int):
        store = J.JobStore()
        for i in range(48):
            store.create(J.Document(
                id=f"j{i}", app_name="a", namespace="n", strategy="canary",
                start_time=to_rfc3339(t_end - 3600),
                end_time=to_rfc3339(t_end + 3600),
                metrics={"err": J.MetricQueries(
                    current=f"c{i}", baseline=f"b{i}")},
            ))
        cfg = dataclasses.replace(EngineConfig(), fetch_concurrency=workers)
        return Analyzer(cfg, FixtureDataSource(resolver=slow_resolver), store)

    # warmup compiles the shared score program so timing isolates fetch
    build_engine(1).run_cycle(now=t_end)

    # 96 fetches x 2ms = ~0.2s serial floor; 16-wide overlap cuts it ~16x.
    # Assert a conservative 2x so slow CI boxes still pass — and measure
    # up to 3 times before failing: this asserts a concurrency BENEFIT,
    # which transient background load on a shared box can mask in any
    # single sample (observed flaking during a full-suite run that
    # overlapped a CPU-heavy bench; passes in isolation).
    attempts = []
    for _ in range(3):
        timings = {}
        for workers in (1, 16):
            eng = build_engine(workers)
            t0 = time.perf_counter()
            eng.run_cycle(now=t_end)
            timings[workers] = time.perf_counter() - t0
        attempts.append(timings)
        if timings[16] < timings[1] / 2:
            break
    else:
        raise AssertionError(f"no overlap benefit in 3 samples: {attempts}")


def test_cycle_bench_small_fleet_is_steady():
    rec = bench_cycle.run(n_jobs=24, cycles=2, window_steps=64)
    assert rec["value"] > 0
    # the host-only decomposition excludes the (device-bound) score stage,
    # so it can never be slower than the raw cycle number. The key is
    # deliberately absent when the monotonic clock fails to advance
    # (bench_cycle omits it rather than divide by zero) — fail with that
    # explanation instead of an opaque KeyError.
    host_jps = rec.get("host_jobs_per_sec")
    assert host_jps is not None, (
        "host_jobs_per_sec missing from bench record: host wall-clock did "
        f"not advance during the run (clock anomaly). record={rec}"
    )
    assert host_jps >= rec["value"]
    # identical baseline/current series must stay healthy and requeue:
    # a shrinking fleet would skew every jobs/s number the driver records
    assert rec["unhealthy_or_terminal"] == 0
    assert rec["fetches_per_cycle"] == 48  # baseline+current per job
    assert rec["jobs"] == 24 and rec["cycles"] == 2


def test_cycle_bench_mixed_fleet_reports_family_decomposition():
    rec = bench_cycle.run(n_jobs=40, cycles=1, window_steps=64, mix=True)
    assert rec["value"] > 0
    fams = rec["family_jobs"]
    assert set(fams) == {"pair", "band", "bivariate", "lstm", "hpa"}
    assert sum(fams.values()) == 40
    costs = rec["family_score_s_per_cycle"]
    assert set(costs) == set(fams)
    # every family actually ran work (pair/band/bi/hpa measurable; lstm
    # may be fully cache-warm in the timed cycle, so only require the
    # train accounting fields to exist)
    assert costs["pair"] > 0 and costs["band"] > 0
    assert "lstm_train_s_per_cycle" in rec and "lstm_trains_per_cycle" in rec


def test_restart_bench_leg_measures_the_storm():
    """Miniature of the BENCH_CYCLE_RESTART leg: the warm restart must
    re-download strictly less than the cold boot (the refetch storm the
    window store exists to kill), with zero full refetches and a
    bounded capped-tier RAM footprint."""
    out = bench_cycle.run_restart(n_jobs=24, window_steps=32)
    assert out["cold"]["full_fetches"] == out["cold"]["fetches"]
    assert out["warm_restart"]["full_fetches"] == 0
    assert out["warm_restart"]["delta_hits"] == out["warm_restart"]["fetches"]
    assert out["refetch_bytes_avoided"] > 0
    assert out["warm_restart"]["bytes_fetched"] \
        < out["cold"]["bytes_fetched"]
    assert out["resident_bytes_tier_on"] < out["resident_bytes_tier_off"]
    json.dumps(out)  # the leg must stay JSON-serializable
