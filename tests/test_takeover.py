"""Cross-replica failover through the shared archive.

The reference's brain replicas are shared-nothing EXCEPT for ES: any
replica re-claims jobs stuck past MAX_STUCK_IN_SECONDS from the shared
store (docs/guides/design.md:37-43; elasticsearchstore.go:155 ByStatus
"used by backend python model"). Here the pluggable archive plays ES's
role: open jobs + lease stamps mirror to it on the flush cadence, and
`JobStore.adopt_stale_from_archive` lets a replacement runtime pull a
crashed peer's in-flight work. The flagship test below is the verdict's
acceptance shape: kill -9 one runtime mid-job, a peer completes it
within the stuck window — two real OS processes, one shared archive.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np

from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.engine.jobs import Document, JobStore, MetricQueries
from foremast_tpu.utils.timeutils import to_rfc3339


def _doc(job_id="j1", status_time=0.0):
    return Document(
        id=job_id, app_name="a", namespace="d", strategy="canary",
        start_time=to_rfc3339(0), end_time=to_rfc3339(status_time),
        metrics={"error5xx": MetricQueries(current="cu", baseline="bu")},
    )


# ------------------------------------------------------- archive semantics
def test_file_archive_search_sees_only_latest_state(tmp_path):
    """Status filters must see each job's LATEST record (ES overwrite
    semantics) — filtering before dedupe would resurrect a completed
    job's earlier open-status record and re-adopt finished work."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    ar.index_job({"id": "x", "status": J.INITIAL, "modified_at": 1.0})
    ar.index_job({"id": "x", "status": J.COMPLETED_HEALTH, "modified_at": 2.0})
    assert ar.search(status=list(J.OPEN_STATUSES)) == []
    got = ar.search(status=J.COMPLETED_HEALTH)
    assert len(got) == 1 and got[0]["modified_at"] == 2.0


def test_file_archive_state_roundtrip(tmp_path):
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    assert ar.get_state("breath") is None
    ar.index_state("breath", {"job": 1}, 10.0)
    ar.index_state("breath", {"job": 2}, 20.0)
    assert ar.get_state("breath") == ({"job": 2}, 20.0)


# --------------------------------------------------------- mirror + adopt
def test_open_jobs_mirror_to_archive_on_flush(tmp_path):
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    store = JobStore(archive=ar)
    store.create(_doc())
    store.claim_open_jobs("w1", max_stuck_seconds=90)
    store.flush()
    recs = ar.search(status=list(J.OPEN_STATUSES))
    assert len(recs) == 1
    assert recs[0]["lease_holder"] == "w1"
    assert recs[0]["status"] == J.PREPROCESS_INPROGRESS


def test_adopt_stale_job_then_complete(tmp_path):
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    a = JobStore(archive=ar)
    a.create(_doc())
    a.claim_open_jobs("w1", max_stuck_seconds=90)
    a.flush()

    b = JobStore(archive=ar)
    # fresh lease: the owner is alive, nothing to adopt
    assert b.adopt_stale_from_archive(max_stuck_seconds=90) == 0
    # lease gone stale (peer crashed): adopted and re-claimable
    assert b.adopt_stale_from_archive(max_stuck_seconds=90,
                                      now=time.time() + 1000) == 1
    assert b.adopted_total == 1
    got = b.claim_open_jobs("w2", max_stuck_seconds=1e-9)
    assert [d.id for d in got] == ["j1"]
    b.transition("j1", J.PREPROCESS_COMPLETED, worker="w2")
    b.transition("j1", J.POSTPROCESS_INPROGRESS, worker="w2")
    b.transition("j1", J.COMPLETED_HEALTH, worker="w2")
    # the archive's latest record is terminal now: nobody re-adopts it
    c = JobStore(archive=ar)
    assert c.adopt_stale_from_archive(max_stuck_seconds=90,
                                      now=time.time() + 2000) == 0


def test_adopt_never_clobbers_newer_local_state(tmp_path):
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    a = JobStore(archive=ar)
    a.create(_doc())
    a.claim_open_jobs("w1", max_stuck_seconds=90)
    a.flush()
    # the same store completed the job AFTER the open mirror; a later
    # adopt scan must not resurrect the open record over the terminal one
    a.transition("j1", J.PREPROCESS_COMPLETED, worker="w1")
    a.transition("j1", J.POSTPROCESS_INPROGRESS, worker="w1")
    a.transition("j1", J.COMPLETED_UNHEALTH, worker="w1", reason="bad")
    assert a.adopt_stale_from_archive(max_stuck_seconds=90,
                                      now=time.time() + 1000) == 0
    assert a.get("j1").status == J.COMPLETED_UNHEALTH


def test_breath_state_rides_the_archive(tmp_path):
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    a = JobStore(archive=ar)
    a.put_state("breath", {"app:ns:hpa": {"armed": True}})
    a.flush()
    b = JobStore(archive=ar)  # replacement runtime, no snapshot
    assert b.get_state("breath") == {"app:ns:hpa": {"armed": True}}
    # a local write wins over the archived copy afterwards
    b.put_state("breath", {"app:ns:hpa": {"armed": False}})
    assert b.get_state("breath") == {"app:ns:hpa": {"armed": False}}


# ---------------------------------------------- two-process kill -9 e2e
_CHILD_A = r"""
import sys, time
import numpy as np
from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.engine.jobs import Document, JobStore, MetricQueries
from foremast_tpu.utils.timeutils import to_rfc3339

store = JobStore(archive=FileArchive(sys.argv[1]))
store.create(Document(
    id="flagship", app_name="app", namespace="demo", strategy="canary",
    start_time=to_rfc3339(0.0), end_time=to_rfc3339(0.0),
    metrics={"error5xx": MetricQueries(current="http://prom/cur",
                                       baseline="http://prom/base")},
))
claimed = store.claim_open_jobs("runtime-A", max_stuck_seconds=90)
assert [d.id for d in claimed] == ["flagship"]
store.flush()  # open job + lease stamp reach the shared archive
print("READY", flush=True)
time.sleep(300)  # wedged mid-job until kill -9
"""

_CHILD_B = r"""
import sys, time
import numpy as np
from foremast_tpu.dataplane import FixtureDataSource
from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.analyzer import Analyzer
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.engine.config import EngineConfig
from foremast_tpu.engine.jobs import JobStore

MAX_STUCK = 2.0
rng = np.random.default_rng(0)
ts = (np.arange(30) * 60.0).tolist()
fixtures = {
    "http://prom/cur": (ts, rng.normal(5.0, 0.5, 30).tolist()),   # bad canary
    "http://prom/base": (ts, rng.normal(0.5, 0.05, 30).tolist()),
}
store = JobStore(archive=FileArchive(sys.argv[1]))
eng = Analyzer(EngineConfig(max_stuck_seconds=MAX_STUCK,
                            pairwise_threshold=1e-4),
               FixtureDataSource(fixtures), store)
t0 = time.time()
# 90 s: the bound is the harness's patience, not the takeover semantics
# (MAX_STUCK is 2 s) — a fresh process cold-compiles its JAX programs,
# which under concurrent machine load alone can eat the old 30 s budget
while time.time() - t0 < 90.0:
    store.adopt_stale_from_archive(worker="runtime-B",
                                   max_stuck_seconds=MAX_STUCK)
    eng.run_cycle(worker="runtime-B", now=10_000.0)
    doc = store.get("flagship")
    if doc is not None and doc.status in J.TERMINAL_STATUSES:
        print("TERMINAL", doc.status, round(time.time() - t0, 2), flush=True)
        sys.exit(0)
    time.sleep(0.2)
print("TIMEOUT", flush=True)
sys.exit(1)
"""


def test_kill9_runtime_peer_completes_job_within_stuck_window(tmp_path):
    """Verdict r3 #6 acceptance: runtime A claims a job and dies (kill -9,
    no shutdown flush beyond the mirror it already did); replacement
    runtime B adopts the job from the shared archive once the lease goes
    stale and drives it to a verdict within the stuck window."""
    archive_path = str(tmp_path / "shared.jsonl")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    a = subprocess.Popen([sys.executable, "-c", _CHILD_A, archive_path],
                         stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = a.stdout.readline()
        assert line.strip() == "READY", line
        os.kill(a.pid, signal.SIGKILL)  # mid-job, no clean shutdown
        a.wait(timeout=10)

        t0 = time.time()
        out = subprocess.run(
            [sys.executable, "-c", _CHILD_B, archive_path],
            capture_output=True, text=True, timeout=180, env=env,
        )
        assert out.returncode == 0, (out.stdout, out.stderr[-800:])
        fields = out.stdout.split()
        assert fields[0] == "TERMINAL" and fields[1] == J.COMPLETED_UNHEALTH, out.stdout
        # "within MAX_STUCK_IN_SECONDS": B's takeover latency is bounded
        # by the stuck window (2 s) + one adopt/cycle lap, not by a human.
        # The wall bound must cover interpreter startup + cold JAX
        # compile under concurrent machine load (the child's own 90 s
        # loop budget plus imports), which is harness cost, not takeover
        # latency — the semantic latency is pinned by the child reporting
        # TERMINAL at all with MAX_STUCK=2 s.
        assert time.time() - t0 < 150.0
    finally:
        if a.poll() is None:
            a.kill()
    # the shared archive's final word on the job is the terminal verdict
    ar = FileArchive(archive_path)
    assert ar.search(status=list(J.OPEN_STATUSES)) == []
    final = ar.get("flagship")
    assert final is not None and final["status"] == J.COMPLETED_UNHEALTH


# ------------------------------------------------- compaction + multi-writer
def test_file_archive_compaction_preserves_terminal_records(tmp_path):
    """Open-job mirror churn must never rotate a terminal verdict away:
    gc() trusts the archive to hold it. Compaction keeps the latest
    record per id, so size tracks job count, not write rate."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"), max_bytes=4096)
    now = time.time()
    ar.index_job({"id": "done", "status": J.COMPLETED_UNHEALTH,
                  "modified_at": now, "reason": "bad"})
    # churn: one open job re-mirrored far past the rotation threshold
    for i in range(200):
        ar.index_job({"id": "busy", "status": J.INITIAL,
                      "modified_at": now + 2.0 + i, "pad": "x" * 64})
    assert ar.compactions >= 1
    final = ar.get("done")
    assert final is not None and final["status"] == J.COMPLETED_UNHEALTH
    busy = ar.get("busy")
    assert busy is not None and busy["modified_at"] == now + 201.0
    # compacted steady state: 2 jobs, so both generations stay small
    total = sum(os.path.getsize(str(tmp_path / "ar.jsonl") + s)
                for s in ("", ".1") if os.path.exists(str(tmp_path / "ar.jsonl") + s))
    assert total < 16 * 1024, total


def test_file_archive_state_survives_compaction(tmp_path):
    ar = FileArchive(str(tmp_path / "ar.jsonl"), max_bytes=2048)
    ar.index_state("breath", {"v": 1}, 10.0)
    for i in range(100):
        ar.index_job({"id": "busy", "status": J.INITIAL,
                      "modified_at": float(i), "pad": "y" * 64})
    assert ar.compactions >= 1
    assert ar.get_state("breath") == ({"v": 1}, 10.0)


def test_stale_open_record_cannot_shadow_newer_terminal(tmp_path):
    """Multi-writer ordering hazard: a wedged peer appends its stale open
    record AFTER another replica's terminal one. Dedupe is by the
    record's own modified_at, not append order, so the terminal record
    wins and the job is never re-adopted."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    ar.index_job({"id": "j", "status": J.COMPLETED_HEALTH,
                  "modified_at": 100.0})
    ar.index_job({"id": "j", "status": J.PREPROCESS_INPROGRESS,
                  "modified_at": 50.0, "lease_at": 50.0})  # late stale append
    assert ar.search(status=list(J.OPEN_STATUSES)) == []
    assert ar.get("j")["status"] == J.COMPLETED_HEALTH
    b = JobStore(archive=ar)
    assert b.adopt_stale_from_archive(max_stuck_seconds=1,
                                      now=time.time() + 1000) == 0


_CHILD_WRITER = r"""
import sys, time
from foremast_tpu.engine.archive import FileArchive

path, tag = sys.argv[1], sys.argv[2]
ar = FileArchive(path, max_bytes=8192)  # small: forces compactions mid-run
now = time.time()
for i in range(120):
    # open mirror then terminal — the terminal must be each id's last word
    ar.index_job({"id": f"{tag}-{i}", "status": "preprocess_inprogress",
                  "modified_at": now + i, "pad": "x" * 80})
    assert ar.index_job({"id": f"{tag}-{i}", "status": "completed_health",
                         "modified_at": now + i + 0.5, "pad": "x" * 80})
print("DONE", ar.compactions, flush=True)
"""


def test_two_process_archive_writers_lose_nothing(tmp_path):
    """Concurrent mirror churn from two OS processes on one shared path,
    with compactions firing throughout: every job's terminal record must
    survive (flock-serialized mutations, single-write appends, compaction
    merging both generations). A torn interleave or a rotation clobber
    would silently drop records — the exact multi-writer hazards the
    failover deployment introduces."""
    path = str(tmp_path / "shared.jsonl")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen([sys.executable, "-c", _CHILD_WRITER, path, tag],
                         stdout=subprocess.PIPE, text=True, env=env)
        for tag in ("a", "b")
    ]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    compactions = sum(int(o.split()[1]) for o in outs)
    assert compactions >= 1, f"no compaction fired: {outs}"
    ar = FileArchive(path, max_bytes=8192)
    for tag in ("a", "b"):
        for i in range(120):
            rec = ar.get(f"{tag}-{i}")
            assert rec is not None, (tag, i, compactions)
            assert rec["status"] == "completed_health", (tag, i, rec)
    # and no job is still visible as open
    assert ar.search(status="preprocess_inprogress", limit=500) == []


def test_concurrent_adoption_is_optimistic_and_converges(tmp_path):
    """SEQUENTIAL adopters may both take a job whose claim went stale
    again (the reference's ES takeover has the same property) — that must
    be safe: both can claim and complete it, verdict writes are
    last-write-wins, and the archive converges to one terminal record.
    (A SIMULTANEOUS race — both scans reading the same version — is
    resolved to a single winner by the claim_job CAS instead:
    tests/test_sharding.py::test_single_adopter_cas_two_stores_one_archive.)
    Here C's adoption is legitimate: B's claim record itself aged past
    the stuck window on C's clock."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    a = JobStore(archive=ar)
    a.create(_doc())
    a.claim_open_jobs("w-dead", max_stuck_seconds=90)
    a.flush()

    later = time.time() + 1000
    b, c = JobStore(archive=ar), JobStore(archive=ar)
    assert b.adopt_stale_from_archive(worker="B", max_stuck_seconds=90,
                                      now=later) == 1
    assert c.adopt_stale_from_archive(worker="C", max_stuck_seconds=90,
                                      now=later) == 1  # optimistic: both
    for store, w in ((b, "wB"), (c, "wC")):
        assert [d.id for d in store.claim_open_jobs(
            w, max_stuck_seconds=1e-9)] == ["j1"]
        store.transition("j1", J.PREPROCESS_COMPLETED, worker=w)
        store.transition("j1", J.POSTPROCESS_INPROGRESS, worker=w)
        store.transition("j1", J.COMPLETED_HEALTH, worker=w)
    # the archive holds exactly one terminal record for the job
    assert ar.get("j1")["status"] == J.COMPLETED_HEALTH
    assert ar.search(status=list(J.OPEN_STATUSES)) == []


# ------------------------------------------------ lease lifecycle counters
def test_lease_lifecycle_counters_exported_end_to_end(tmp_path):
    """foremastbrain:lease_{claims,steals,releases,adoptions}_total cover
    the full lease lifecycle across two stores over one shared archive,
    and every leg lands on /metrics — the churn cross-replica failover
    runs on was previously invisible."""
    from foremast_tpu.service.api import ForemastService

    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    a = JobStore(archive=ar)
    a.create(_doc("j1"))
    a.create(_doc("j2"))
    assert len(a.claim_open_jobs("w1", max_stuck_seconds=90)) == 2
    assert a.lease_claims_total == 2 and a.lease_steals_total == 0
    # a stuck lease is STOLEN, not freshly claimed
    time.sleep(0.01)
    assert len(a.claim_open_jobs("w1b", max_stuck_seconds=1e-9)) == 2
    assert a.lease_claims_total == 2 and a.lease_steals_total == 2
    a.flush()
    # graceful shutdown releases both
    assert a.release_leases(worker="w1b") == 2
    assert a.lease_releases_total == 2
    a.flush()

    b = JobStore(archive=ar)
    assert b.adopt_stale_from_archive(worker="w2", max_stuck_seconds=90) == 2
    assert b.adopted_total == 2
    assert len(b.claim_open_jobs("w2", max_stuck_seconds=90)) == 2
    _, text = ForemastService(b).metrics()
    assert "foremastbrain:lease_claims_total 2" in text
    assert "foremastbrain:lease_adoptions_total 2" in text
    _, text_a = ForemastService(a).metrics()
    assert "foremastbrain:lease_steals_total 2" in text_a
    assert "foremastbrain:lease_releases_total 2" in text_a


# ------------------------------------------- ADVICE r04: mirror resilience
def test_mirror_skips_permanently_rejected_doc(tmp_path):
    """A single doc the archive rejects (ES 400 mapping conflict shape)
    must not head-of-line-block every doc behind it from mirroring —
    that would silently disable cross-replica failover fleet-wide."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    real_index = ar.index_job
    ar.index_job = lambda rec: (False if rec.get("id") == "poison"
                                else real_index(rec))
    store = JobStore(archive=ar)
    store.create(_doc("poison"))
    store.create(_doc("j2"))
    store.create(_doc("j3"))
    store.claim_open_jobs("w1", max_stuck_seconds=90)
    store.flush()
    mirrored = {r["id"] for r in ar.search(status=list(J.OPEN_STATUSES))}
    assert {"j2", "j3"} <= mirrored and "poison" not in mirrored
    assert store.mirror_failures_total >= 1


def test_mirror_outage_short_circuits_on_consecutive_failures(tmp_path):
    """A genuinely dead archive must still short-circuit the flush (the
    per-doc skip is for isolated rejections, not for hammering a dead
    backend N times per flush)."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    calls = []
    ar.index_job = lambda rec: (calls.append(rec.get("id")), False)[1]
    store = JobStore(archive=ar)
    for i in range(JobStore._MIRROR_FAIL_CAP * 3):
        store.create(_doc(f"j{i}"))
    store.claim_open_jobs("w1", max_stuck_seconds=90)
    calls.clear()
    store._mirror_to_archive()
    assert len(calls) == JobStore._MIRROR_FAIL_CAP


def test_adopt_skew_margin_spares_borderline_lease(tmp_path):
    """Staleness within max_stuck + skew margin belongs to a live peer
    whose clock may simply drift — adoption starts past the margin."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    a = JobStore(archive=ar)
    a.create(_doc())
    a.claim_open_jobs("w1", max_stuck_seconds=90)
    a.flush()
    b = JobStore(archive=ar)
    # 95 s stale: past max_stuck(90) but inside the 15 s skew margin
    assert b.adopt_stale_from_archive(max_stuck_seconds=90,
                                      now=time.time() + 95) == 0
    # 110 s stale: past margin too -> adopted
    assert b.adopt_stale_from_archive(max_stuck_seconds=90,
                                      now=time.time() + 110) == 1


def test_degraded_flock_suppresses_compaction(tmp_path, monkeypatch):
    """When the sidecar .lock cannot be flocked while fcntl IS available,
    appends proceed (O_APPEND is interleave-atomic) but compaction must
    NOT run — an unlocked truncation can destroy a peer's concurrent
    append on a shared (RWX PVC) archive. Counted for observability."""
    from foremast_tpu.engine import archive as A

    ar = FileArchive(str(tmp_path / "ar.jsonl"), max_bytes=200)

    def broken_flock(fd, op):
        raise OSError(13, "flock denied")

    monkeypatch.setattr(A.fcntl, "flock", broken_flock)
    for i in range(20):  # enough bytes to cross max_bytes repeatedly
        assert ar.index_job({"id": f"j{i}", "status": J.INITIAL,
                             "modified_at": float(i)})
    assert ar.compactions == 0
    assert ar.compactions_skipped_unlocked > 0
    assert ar.lock_degradations > 0
    # every record still present (no truncation happened)
    assert len(ar.search(limit=100)) == 20


def test_adjacent_poison_run_cannot_starve_docs_behind_it(tmp_path):
    """Review hardening: >= _MIRROR_FAIL_CAP adjacent permanently-rejected
    docs trip the outage short-circuit on one flush, but their failure
    backoff must let the docs behind them mirror on the next flush."""
    ar = FileArchive(str(tmp_path / "ar.jsonl"))
    real_index = ar.index_job
    ar.index_job = lambda rec: (False if rec.get("id", "").startswith("poison")
                                else real_index(rec))
    store = JobStore(archive=ar)
    for i in range(JobStore._MIRROR_FAIL_CAP + 2):
        store.create(_doc(f"poison{i}"))
    store.create(_doc("good1"))
    store.create(_doc("good2"))
    store.claim_open_jobs("w1", max_stuck_seconds=90)
    store._mirror_to_archive()  # trips the cap inside the poison run
    store._mirror_to_archive()  # poisons backed off -> goods mirror
    mirrored = {r["id"] for r in ar.search(status=list(J.OPEN_STATUSES),
                                           limit=100)}
    assert {"good1", "good2"} <= mirrored
    assert store.mirror_backed_off_docs() >= JobStore._MIRROR_FAIL_CAP
