"""Fleet observability plane (ISSUE 10): detection-latency SLOs,
cross-replica federation, handoff-surviving provenance.

Three connected layers over the sharded brain:
  * engine/slo.py — ingest->verdict latency per job class, SLO targets,
    error-budget burn (the baseline the streaming dataplane must beat);
  * GET /fleet + `foremast-tpu top` — every replica's status digest,
    published on the membership heartbeat blobs, aggregated from ANY
    replica, with explicit staleness semantics;
  * provenance handoff hops — a job's "why" (and the releasing
    replica's cycle id) travels with the Document through lease
    release/adoption, so `explain` on the adopter shows the full chain.

Plus the satellites: Prometheus exposition content type + scrape
grammar, the on-disk flight-dump index, and bench honesty for latency.
"""
from __future__ import annotations

import json
import re
import urllib.error
import urllib.request

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource, VerdictExporter
from foremast_tpu.engine import (
    Analyzer,
    Document,
    EngineConfig,
    JobStore,
    MetricQueries,
)
from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.archive import FileArchive
from foremast_tpu.engine.flightrec import (
    EVENT_LEASE_HANDOFF,
    EVENT_SHARD_ADOPTION,
    FlightRecorder,
)
from foremast_tpu.engine.sharding import ShardManager
from foremast_tpu.engine.slo import DetectionSLO, classify
from foremast_tpu.service.api import ForemastService, serve_background
from foremast_tpu.utils.timeutils import to_rfc3339

STEP = 60
SEED = 20260804


def _series(rng, level, n):
    ts = np.arange(n) * STEP
    vals = np.clip(rng.normal(level, level * 0.1 + 0.01, n), 0, None)
    return ts.tolist(), vals.tolist()


def _mk_job(store, fixtures, job_id, *, bad=False, strategy="canary",
            end_time=10_000_000.0, rng=None):
    rng = rng or np.random.default_rng(SEED)
    cur = f"http://prom:9090/{job_id}/cur"
    base = f"http://prom:9090/{job_id}/base"
    fixtures[cur] = _series(rng, 5.0 if bad else 0.5, 30)
    fixtures[base] = _series(rng, 0.5, 30)
    continuous = strategy in ("continuous", "hpa")
    store.create(Document(
        id=job_id, app_name=f"app-{job_id}", namespace="fleet",
        strategy=strategy,
        start_time="START_TIME" if continuous else to_rfc3339(0.0),
        end_time="END_TIME" if continuous else to_rfc3339(end_time),
        metrics={"error5xx": MetricQueries(current=cur, baseline=base)},
    ))


def _mk_hpa_job(store, fixtures, job_id):
    rng = np.random.default_rng(5)
    tps_url = f"http://prom/{job_id}/tps"
    sla_url = f"http://prom/{job_id}/sla"
    hist_ts, hist_v = _series(rng, 100.0, 90)
    cur_ts = [t + hist_ts[-1] + STEP for t in np.arange(30) * STEP]
    fixtures[tps_url] = (
        hist_ts + list(cur_ts),
        hist_v + np.random.default_rng(1).normal(240, 5, 30).tolist())
    fixtures[sla_url] = _series(rng, 5.0, 120)
    store.create(Document(
        id=job_id, app_name="app", namespace="fleet", strategy="hpa",
        start_time="START_TIME", end_time="END_TIME",
        metrics={
            "tps": MetricQueries(historical=tps_url, current=tps_url),
            "latency": MetricQueries(historical=sla_url, current=sla_url,
                                     priority=1),
        },
    ))


def _analyzer(fixtures, store, **cfg):
    cfg.setdefault("max_stuck_seconds", 1e9)
    return Analyzer(EngineConfig(**cfg), FixtureDataSource(fixtures), store,
                    VerdictExporter())


# ------------------------------------------------------- detection SLO unit

def test_slo_quantiles_attainment_burn():
    slo = DetectionSLO(targets={"canary": 0.5}, objective=0.99)
    for v in (0.01, 0.02, 0.3, 0.6, 2.0):
        slo.observe("canary", v)
    # bucket-resolution estimates: upper edge of the rank's bucket
    assert slo.quantile(0.5, "canary") == 0.5
    assert slo.quantile(0.99, "canary") == 2.5
    assert slo.attainment("canary") == pytest.approx(0.6)
    # 40% violations against a 1% budget = 40x burn
    assert slo.burn("canary") == pytest.approx(40.0)
    snap = slo.snapshot()["classes"]["canary"]
    assert snap["count"] == 5 and snap["violations"] == 2
    assert snap["target_s"] == 0.5
    # pooled quantile spans classes; summaries list only observed ones
    slo.observe("hpa", 0.001)
    assert slo.quantile(0.0, None) == 0.001
    assert set(slo.burn_summary()) == {"canary", "hpa"}
    assert set(slo.digest()) == {"canary", "hpa"}
    slo.reset()
    assert slo.quantile(0.5, "canary") == 0.0
    assert slo.burn_summary() == {}


def test_slo_no_target_never_violates():
    slo = DetectionSLO(targets={}, objective=0.99)
    slo.observe("continuous", 1e6)
    assert slo.attainment("continuous") == 1.0
    assert slo.burn("continuous") == 0.0


def test_slo_exporter_series():
    ex = VerdictExporter()
    slo = DetectionSLO(exporter=ex, targets={"canary": 0.1})
    slo.observe("canary", 0.5)
    rendered = ex.render()
    assert "foremastbrain:detection_latency_seconds_bucket" in rendered
    assert 'foremastbrain:slo_attainment{class="canary"} 0.0' in rendered
    assert 'foremastbrain:slo_violations_total{class="canary"} 1' in rendered
    assert "foremastbrain:slo_error_budget_burn" in rendered


def test_classify_strategies():
    assert classify("hpa") == "hpa"
    assert classify("continuous") == "continuous"
    for s in ("canary", "rollingUpdate", "rollover"):
        assert classify(s) == "canary"


# ------------------------------------------- engine latency instrumentation

def test_detection_latency_recorded_for_every_job_class():
    fixtures, store = {}, JobStore()
    an = _analyzer(fixtures, store)
    _mk_job(store, fixtures, "c1", bad=True, end_time=5000.0)
    _mk_job(store, fixtures, "m1", strategy="continuous")
    _mk_hpa_job(store, fixtures, "app:fleet:hpa")
    out = an.run_cycle(worker="w", now=0.0)
    assert out["c1"] == J.COMPLETED_UNHEALTH
    assert out["m1"] == J.INITIAL
    assert out["app:fleet:hpa"] == J.INITIAL
    # non-empty histogram per class — the acceptance criterion
    dig = an.slo.digest()
    assert set(dig) == {"canary", "continuous", "hpa"}
    assert all(d["n"] >= 1 for d in dig.values())
    # the latency annotation rides the provenance record AND the archived
    # terminal summary
    rec = an.provenance.get("c1")
    assert rec["detection_latency_s"] > 0.0
    attached = json.loads(store.get("c1").processing_content)
    assert attached["detection_latency_s"] == rec["detection_latency_s"]
    # surfaces: /status slo section + health-detail burn + /metrics
    svc = ForemastService(store, exporter=an.exporter, analyzer=an)
    _, status = svc.status_summary()
    assert status["slo"]["classes"]["canary"]["count"] >= 1
    assert status["slo"]["classes"]["canary"]["target_s"] == \
        an.config.slo_canary_seconds
    _, detail = an.health.state()
    assert set(detail["slo_burn"]) == {"canary", "continuous", "hpa"}
    _, metrics = svc.metrics()
    assert "foremastbrain:detection_latency_seconds_bucket" in metrics


def test_verdicts_identical_with_plane_observing_vs_provenance_off():
    """The plane only OBSERVES: statuses/reasons/anomalies byte-identical
    with PROVENANCE=0 (SLO recording is always-on and must not feed
    back either)."""
    outs = {}
    for flag in (True, False):
        fixtures, store = {}, JobStore()
        an = _analyzer(fixtures, store, provenance=flag)
        rng = np.random.default_rng(99)
        for i in range(6):
            _mk_job(store, fixtures, f"j{i}", bad=(i % 3 == 0),
                    end_time=5000.0, rng=rng)
        an.run_cycle(worker="w", now=1000.0)
        an.run_cycle(worker="w", now=6000.0)
        outs[flag] = {
            d.id: (d.status, d.reason, sorted(d.anomaly.items()))
            for d in store.by_status(*J.OPEN_STATUSES, *J.TERMINAL_STATUSES)}
    assert outs[True] == outs[False]


# ------------------------------------------------------ federation / /fleet

def _manager(path, rid, digest=None, **kw):
    store = JobStore(archive=FileArchive(path))
    kw.setdefault("shard_count", 8)
    kw.setdefault("vnodes", 16)
    kw.setdefault("heartbeat_seconds", 0.0)  # heartbeat every tick
    kw.setdefault("member_ttl_seconds", 5.0)
    return ShardManager(store, rid, digest_fn=digest, **kw)


def test_fleet_snapshot_digests_staleness_and_ttl(tmp_path):
    path = str(tmp_path / "a.jsonl")
    clock = {"now": 1000.0}
    A = _manager(path, "A", digest=lambda: {"health": "ok", "who": "A"},
                 clock=lambda: clock["now"])
    B = _manager(path, "B", digest=lambda: {"health": "degraded",
                                            "who": "B"},
                 clock=lambda: clock["now"])
    for _ in range(2):
        A.tick()
        B.tick()
    snap = A.fleet_snapshot()
    rows = {r["replica"]: r for r in snap["replicas"]}
    assert set(rows) == {"A", "B"}
    assert rows["A"]["self"] and rows["A"]["digest"]["who"] == "A"
    assert not rows["B"]["stale"]
    assert rows["B"]["digest"] == {"health": "degraded", "who": "B"}
    assert rows["B"]["age_s"] <= snap["member_ttl_seconds"]

    # graceful leave flips the row stale immediately
    B.withdraw()
    A._last_read = None  # force a fresh membership read
    A.tick()
    rows = {r["replica"]: r for r in A.fleet_snapshot()["replicas"]}
    assert rows["B"]["left"] and rows["B"]["stale"]

    # kill -9 (no withdraw): stale within MEMBER_TTL_S of the last beat
    C = _manager(path, "C", digest=lambda: {"health": "ok"},
                 clock=lambda: clock["now"])
    C.tick()
    A._last_read = None
    A.tick()
    rows = {r["replica"]: r for r in A.fleet_snapshot()["replicas"]}
    assert not rows["C"]["stale"]
    del C  # kill -9: heartbeats simply stop
    clock["now"] += A.member_ttl_seconds + 1.0
    A._last_read = None
    A.tick()
    rows = {r["replica"]: r for r in A.fleet_snapshot()["replicas"]}
    assert rows["C"]["stale"] and not rows["C"]["left"]
    assert rows["C"]["age_s"] > A.member_ttl_seconds


def test_fleet_endpoint_aggregates_and_serves_over_http(tmp_path):
    path = str(tmp_path / "a.jsonl")
    fixtures, store = {}, JobStore()
    an = _analyzer(fixtures, store)
    _mk_job(store, fixtures, "c1", bad=True, end_time=5000.0)
    an.run_cycle(worker="A", now=1000.0)
    A = _manager(path, "A", digest=an.status_digest)
    B = _manager(path, "B",
                 digest=lambda: {"health": "overloaded",
                                 "jobs": {"initial": 3},
                                 "slo": {"canary": {"p50_s": 1.0,
                                                    "p99_s": 9.0,
                                                    "burn": 7.5}},
                                 "shards": {"owned": 4}})
    an.shard = A
    for _ in range(2):
        A.tick()
        B.tick()
    svc = ForemastService(store, exporter=an.exporter, analyzer=an, shard=A)
    code, payload = svc.fleet()
    assert code == 200
    agg = payload["aggregate"]
    assert agg["replicas"] == 2 and agg["replicas_fresh"] == 2
    # worst-wins across fresh digests
    assert agg["worst_health"] == "overloaded"
    assert agg["jobs"]["initial"] >= 3  # summed across replicas
    assert agg["slo_worst"]["canary"]["burn"] == 7.5
    assert agg["shards_owned"] >= 4

    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet", timeout=10) as r:
            wire = json.loads(r.read().decode())
        assert {row["replica"] for row in wire["replicas"]} == {"A", "B"}
        assert wire["aggregate"]["worst_health"] == "overloaded"
    finally:
        server.shutdown()


def test_fleet_endpoint_single_replica_serves_local_digest():
    fixtures, store = {}, JobStore()
    an = _analyzer(fixtures, store)
    _mk_job(store, fixtures, "m1", strategy="continuous")
    an.run_cycle(worker="w", now=1000.0)
    svc = ForemastService(store, exporter=an.exporter, analyzer=an)
    code, payload = svc.fleet()
    assert code == 200
    (row,) = payload["replicas"]
    assert row["self"] and not row["stale"]
    assert row["digest"]["health"] == "ok"
    assert row["digest"]["cycle_id"] == "w-c1"
    assert payload["aggregate"]["replicas_fresh"] == 1


def test_top_cli_renders_fleet(capsys):
    from foremast_tpu import cli

    fixtures, store = {}, JobStore()
    an = _analyzer(fixtures, store)
    _mk_job(store, fixtures, "m1", strategy="continuous")
    an.run_cycle(worker="w", now=1000.0)
    svc = ForemastService(store, exporter=an.exporter, analyzer=an)
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        rc = cli.main(["top", "--endpoint", f"http://127.0.0.1:{port}"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "worst health ok" in out
        assert "local *" in out  # the self row
        assert "REPLICA" in out and "DETECT p50/p99" in out
        rc = cli.main(["top", "--json",
                       "--endpoint", f"http://127.0.0.1:{port}"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["aggregate"]["worst_health"] == "ok"
    finally:
        server.shutdown()


# ------------------------------------- handoff-surviving provenance (hops)

def _replica(path, fixtures, rid):
    store = JobStore(archive=FileArchive(path))
    an = _analyzer(fixtures, store)
    return store, an


def test_cross_replica_explain_pins_full_chain(tmp_path):
    """Two JobStores over one FileArchive: scored on A, handed off,
    re-scored on B — `explain` on B names the handoff hop AND A's
    cycle_id alongside B's own (the acceptance-criteria chain)."""
    path = str(tmp_path / "shared.jsonl")
    fixtures = {}
    sA, aA = _replica(path, fixtures, "A")
    sB, aB = _replica(path, fixtures, "B")
    _mk_job(sA, fixtures, "watch", strategy="continuous")
    _mk_job(sA, fixtures, "roll", end_time=5000.0)
    aA.run_cycle(worker="A", now=1000.0)
    assert aA.provenance.get("watch")["cycle"]["cycle_id"] == "A-c1"

    # graceful handoff (the runtime.stop path): the provenance summary +
    # handoff hop travel on the released Documents
    released = sA.release_leases(
        worker="A",
        content_fn=lambda jid: aA.provenance.handoff_json(
            jid, replica="repA", worker="A", reason="shutdown"))
    assert released == 2
    sA.flush()

    adopted_blobs = {}
    n = sB.adopt_stale_from_archive(
        worker="B",
        on_adopt=lambda d: (adopted_blobs.__setitem__(
            d.id, d.processing_content),
            aB.provenance.adopt(d.id, d.processing_content)))
    assert n == 2
    assert "handoff" in adopted_blobs["watch"]
    aB.run_cycle(worker="B", now=1060.0)

    svc = ForemastService(sB, exporter=aB.exporter, analyzer=aB)
    code, payload = svc.explain("watch")
    assert code == 200
    rec = payload["provenance"]
    assert rec["cycle"]["cycle_id"] == "B-c1"  # B's own judgment
    (hop,) = rec["hops"]
    assert hop["replica"] == "repA"
    assert hop["cycle_id"] == "A-c1"  # the originating replica's cycle
    assert hop["reason"] == "shutdown"

    # the chain survives into B's ARCHIVED terminal record too
    aB.run_cycle(worker="B", now=6000.0)  # past roll's endTime
    arec = FileArchive(path).get("roll")
    assert arec["status"] in J.TERMINAL_STATUSES
    attached = json.loads(arec["processing_content"])
    assert attached["hops"][0]["cycle_id"] == "A-c1"

    # CLI rendering names the hop
    from foremast_tpu.cli import _render_explain
    out = _render_explain(payload)
    assert "handoff: from repA cycle A-c1 (shutdown" in out


def test_rebalance_handoff_carries_chain_and_cycle_ids(tmp_path):
    """The shard-rebalance handoff path: ShardManager releases non-owned
    jobs WITH the provenance blob, and both sides' flight events carry
    correlatable cycle ids."""
    path = str(tmp_path / "shared.jsonl")
    fixtures = {}
    sA, aA = _replica(path, fixtures, "A")
    flightA = aA.flight
    A = ShardManager(
        sA, "A", shard_count=8, vnodes=16, heartbeat_seconds=0.0,
        member_ttl_seconds=5.0, worker="A", flight=flightA,
        digest_fn=aA.status_digest,
        cycle_id_fn=lambda: aA.current_cycle_id,
        handoff_content_fn=lambda jid: aA.provenance.handoff_json(
            jid, replica="A", worker="A", reason="rebalance"))
    aA.shard = A
    A.tick()
    # a fleet big enough that a joining peer takes some of it
    rng = np.random.default_rng(3)
    for i in range(12):
        _mk_job(sA, fixtures, f"w{i}", strategy="continuous", rng=rng)
    aA.run_cycle(worker="A", now=1000.0)

    sB, aB = _replica(path, fixtures, "B")
    B = ShardManager(
        sB, "B", shard_count=8, vnodes=16, heartbeat_seconds=0.0,
        member_ttl_seconds=5.0, worker="B", flight=aB.flight,
        cycle_id_fn=lambda: aB.current_cycle_id)
    B.tick()
    A.tick()  # sees B: rebalance + handoff of B's shards
    sA.flush()
    handed = [d.id for d in sA.by_status(*J.OPEN_STATUSES)
              if d.released_at > 0]
    assert handed, "the join must hand some shards off"
    # released docs carry the handoff blob with A's cycle id
    blob = json.loads(sA.get(handed[0]).processing_content)
    assert blob["handoff"]["reason"] == "rebalance"
    assert blob["hops"][-1]["cycle_id"] == "A-c1"

    adopted_ids = []
    n = sB.adopt_stale_from_archive(
        worker="B", owns_fn=B.owns, dead_holder_fn=B.dead_holder,
        on_adopt=lambda d: (adopted_ids.append(d.id),
                            aB.provenance.adopt(d.id,
                                                d.processing_content)))
    assert n >= 1
    aB.run_cycle(worker="B", now=1060.0)
    B.mark_adopt_complete(n, jobs=adopted_ids)

    # releasing side: lease-handoff / rebalance event with A's cycle id
    evA = [e for e in flightA.snapshot(limit=100)
           if e["type"] in ("shard-rebalance", EVENT_LEASE_HANDOFF)]
    assert any(e["detail"].get("cycle_id") == "A-c1" for e in evA)
    # adopting side: shard-adoption event with B's cycle id + job ids
    evB = [e for e in aB.flight.snapshot(limit=100)
           if e["type"] == EVENT_SHARD_ADOPTION]
    assert evB and evB[-1]["detail"]["cycle_id"] == "B-c1"
    assert set(evB[-1]["detail"]["jobs"]) == set(adopted_ids)
    # and the adopter's explain names A's cycle
    rec = aB.provenance.get(adopted_ids[0])
    assert rec["hops"][-1]["cycle_id"] == "A-c1"


def test_terminal_record_closes_the_hop_chain():
    """Job ids are deterministic: a re-submitted incarnation of the same
    id must NOT inherit a dead run's handoff history. The terminal record
    keeps the chain (it archives with it); the next record starts clean."""
    from foremast_tpu.engine.provenance import ProvenanceRecorder

    rec = ProvenanceRecorder()
    blob = rec.handoff_json("x", replica="repA", worker="A", reason="test")
    rec.adopt("x", blob)
    rec.record("x", "scored", status=J.COMPLETED_HEALTH)
    assert rec.get("x")["hops"]  # the closing record carries the chain
    rec.record("x", "scored", status=J.INITIAL)  # re-submitted incarnation
    assert "hops" not in rec.get("x")


# ------------------------------------------ /metrics exposition (satellite)

_HELP_RE = re.compile(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* .*$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) (counter|gauge|histogram|summary|untyped)$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\")*\})?"  # labels
    r" (-?[0-9.eE+-]+|NaN|[+-]Inf)$")       # value


def test_metrics_content_type_and_scrape_grammar():
    fixtures, store = {}, JobStore()
    an = _analyzer(fixtures, store)
    _mk_job(store, fixtures, "c1", bad=True, end_time=5000.0)
    _mk_hpa_job(store, fixtures, "app:fleet:hpa")
    an.run_cycle(worker="w", now=0.0)
    svc = ForemastService(store, exporter=an.exporter, analyzer=an)
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            ctype = r.headers.get("Content-Type")
            body = r.read().decode()
    finally:
        server.shutdown()
    assert ctype == "text/plain; version=0.0.4; charset=utf-8"
    # every line parses under the exposition grammar
    typed: dict[str, str] = {}
    seen_samples: set[str] = set()
    for line in body.splitlines():
        if not line:
            continue
        m = _TYPE_RE.match(line)
        if m:
            # TYPE precedes the family's samples and appears once
            assert m.group(1) not in typed, f"duplicate TYPE: {line}"
            assert m.group(1) not in seen_samples, f"TYPE after samples: {line}"
            typed[m.group(1)] = m.group(2)
            continue
        if line.startswith("# HELP"):
            assert _HELP_RE.match(line), f"bad HELP line: {line}"
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        seen_samples.add(m.group(1))
    # histograms expose the full triplet
    hists = [n for n, t in typed.items() if t == "histogram"]
    assert "foremastbrain:detection_latency_seconds" in hists
    for h in hists:
        assert f"{h}_sum" in seen_samples and f"{h}_count" in seen_samples
        assert any(s == f"{h}_bucket" for s in seen_samples)


# ---------------------------------------- flight dump index (satellite)

def test_flight_dump_index_and_fetch(tmp_path):
    fr = FlightRecorder(dump_dir=str(tmp_path), min_dump_interval_s=0.0)
    fr.record_event(EVENT_LEASE_HANDOFF, released=1)
    assert fr.dump(reason="health:stalled") is not None
    assert fr.dump(reason="shutdown") is not None
    dumps = fr.list_dumps()
    assert len(dumps) == 2
    assert {d["trigger"] for d in dumps} == {"health-stalled", "shutdown"}
    assert all(d["age_s"] >= 0.0 and d["size_bytes"] > 0 for d in dumps)
    payload = fr.read_dump(dumps[0]["name"])
    assert payload is not None and "events" in payload
    # name validation: traversal and garbage never reach the filesystem
    assert fr.read_dump("../etc/passwd") is None
    assert fr.read_dump("foremast-flight-x/../../y.json") is None
    assert fr.read_dump("nope.json") is None

    class _An:  # minimal analyzer stub carrying the recorder
        flight = fr

    svc = ForemastService(JobStore(), analyzer=_An())
    server = serve_background(svc, host="127.0.0.1", port=0)
    try:
        port = server.server_address[1]
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(f"{base}/debug/flight/dumps",
                                    timeout=10) as r:
            idx = json.loads(r.read().decode())
        assert len(idx["dumps"]) == 2 and idx["dump_dir"] == str(tmp_path)
        name = idx["dumps"][0]["name"]
        with urllib.request.urlopen(f"{base}/debug/flight/dumps/{name}",
                                    timeout=10) as r:
            one = json.loads(r.read().decode())
        assert one["reason"] in ("health:stalled", "shutdown")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/debug/flight/dumps/nope.json",
                                   timeout=10)
        assert ei.value.code == 404
    finally:
        server.shutdown()


# -------------------------------------------- bench honesty (satellite)

@pytest.mark.slow
def test_bench_steady_records_detection_latency():
    from foremast_tpu.bench_cycle import run_steady

    out = run_steady(n_jobs=40, cycles=4)
    assert out["detection_latency_p50_s"] > 0.0
    assert out["detection_latency_p99_s"] >= out["detection_latency_p50_s"]
