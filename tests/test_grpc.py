"""gRPC dispatch frontend: one contract suite over both transports.

The north star names gRPC as the job-dispatch transport; the build keeps
the HTTP facade for reference parity (main.go:326-346). Both fronts wrap
the same ForemastService handlers, and these tests prove the contract is
transport-independent: every scenario runs over real HTTP and real gRPC
and must produce identical logical payloads — including error statuses.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from foremast_tpu.engine import jobs as J
from foremast_tpu.engine.jobs import HpaLog, JobStore
from foremast_tpu.service.api import ForemastService, serve_background
from foremast_tpu.service.grpc_api import (
    DispatchClient,
    DispatchError,
    serve_grpc_background,
)
from foremast_tpu.utils.ids import hpa_job_id

CREATE_REQ = {
    "appName": "demo",
    "namespace": "default",
    "strategy": "canary",
    "startTime": "2026-07-29T00:00:00Z",
    "endTime": "2026-07-29T00:10:00Z",
    "metricsInfo": {
        "current": {
            "error5xx": {
                "url": "http://prom/api/v1/query_range?query=cur",
                "priority": 1,
            }
        },
        "baseline": {
            "error5xx": {"url": "http://prom/api/v1/query_range?query=base"}
        },
        "historical": {
            "error5xx": {"url": "http://prom/api/v1/query_range?query=hist"}
        },
    },
}


class HttpDispatch:
    """urllib adapter exposing the same method surface as DispatchClient,
    raising DispatchError with the HTTP status so error-path assertions are
    shared verbatim across transports."""

    def __init__(self, endpoint: str):
        self.endpoint = endpoint.rstrip("/")

    def _req(self, method, path, body=None):
        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=5) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            payload = json.loads(e.read() or b"{}")
            raise DispatchError(e.code, payload.get("error", "")) from e

    def create(self, req: dict) -> dict:
        return self._req("POST", "/v1/healthcheck/create", req)

    def status(self, job_id: str) -> dict:
        return self._req("GET", f"/v1/healthcheck/id/{job_id}")

    def search(self, app=None, namespace=None, status=None, strategy=None,
               limit=0) -> list[dict]:
        q = []
        for k, v in (("appName", app), ("namespace", namespace),
                     ("status", status), ("strategy", strategy)):
            if v:
                q.append(f"{k}={v}")
        if limit:
            q.append(f"limit={limit}")
        qs = ("?" + "&".join(q)) if q else ""
        return self._req("GET", f"/v1/healthcheck/search{qs}")["jobs"]

    def alert(self, app, namespace, strategy) -> dict:
        return self._req("GET", f"/alert/{app}/{namespace}/{strategy}")

    def close(self):
        pass


@pytest.fixture(scope="module")
def stack():
    """One service, two live transports."""
    store = JobStore()
    service = ForemastService(store)
    http_server = serve_background(service, port=0)
    http_port = http_server.server_address[1]
    grpc_server, grpc_port = serve_grpc_background(service, port=0)
    clients = {
        "http": HttpDispatch(f"http://127.0.0.1:{http_port}"),
        "grpc": DispatchClient(f"127.0.0.1:{grpc_port}"),
    }
    yield store, service, clients
    clients["grpc"].close()
    grpc_server.stop(grace=0.5)
    http_server.shutdown()


@pytest.fixture(params=["http", "grpc"])
def dispatch(request, stack):
    _, _, clients = stack
    return clients[request.param]


# ------------------------------------------------------------- create
def test_create_same_job_id_on_both_transports(stack):
    _, _, clients = stack
    got = {name: c.create(CREATE_REQ) for name, c in clients.items()}
    assert got["http"]["jobId"] == got["grpc"]["jobId"]
    assert got["http"]["status"] == got["grpc"]["status"] == "new"


def test_create_dedupes(dispatch):
    a = dispatch.create(CREATE_REQ)
    b = dispatch.create(CREATE_REQ)
    assert a["jobId"] == b["jobId"]


def test_create_structured_parameters_match_url_form(stack):
    """The reference's {dataSourceType, parameters} shape builds the same
    query URLs over both transports (constructURL, main.go:34-48)."""
    _, _, clients = stack
    req = {
        "appName": "paramapp",
        "strategy": "canary",
        "metricsInfo": {
            "current": {
                "latency": {
                    "dataSourceType": "prometheus",
                    "parameters": {
                        "endpoint": "http://prom:9090/api/v1/",
                        "query": "namespace_pod_latency",
                        "start": 1000,
                        "end": 1600,
                        "step": 60,
                    },
                }
            }
        },
    }
    ids = {name: c.create(req)["jobId"] for name, c in clients.items()}
    assert ids["http"] == ids["grpc"]


def test_create_invalid_app_rejected(dispatch):
    with pytest.raises(DispatchError) as exc:
        dispatch.create({"appName": "bad app!", "strategy": "canary",
                         "metricsInfo": {"current": {"m": {"url": "http://x"}}}})
    assert exc.value.status == 400


def test_create_invalid_strategy_rejected(dispatch):
    with pytest.raises(DispatchError) as exc:
        dispatch.create({"appName": "demo", "strategy": "nope",
                         "metricsInfo": {"current": {"m": {"url": "http://x"}}}})
    assert exc.value.status == 400


# ------------------------------------------------------------- status
def test_status_unknown_job_404(dispatch):
    with pytest.raises(DispatchError) as exc:
        dispatch.status("no-such-job")
    assert exc.value.status == 404


def test_status_terminal_job_identical_payloads(stack):
    store, _, clients = stack
    job_id = clients["http"].create(CREATE_REQ)["jobId"]
    store.transition(job_id, J.PREPROCESS_INPROGRESS)
    store.transition(job_id, J.PREPROCESS_COMPLETED)
    store.transition(job_id, J.POSTPROCESS_INPROGRESS)
    store.transition(
        job_id,
        J.COMPLETED_UNHEALTH,
        reason="anomaly detected on error5xx",
        anomaly={"error5xx": [1000.0, 42.0, 1060.0, 43.0]},
    )
    got = {name: c.status(job_id) for name, c in clients.items()}
    assert got["http"] == got["grpc"]
    assert got["grpc"]["status"] == "anomaly"
    assert got["grpc"]["anomaly"]["error5xx"] == [1000.0, 42.0, 1060.0, 43.0]


def test_status_hpa_job_carries_hpalogs(stack):
    store, _, clients = stack
    job_id = hpa_job_id("hpaapp", "default")
    clients["grpc"].create({
        "appName": "hpaapp",
        "namespace": "default",
        "strategy": "hpa",
        "metricsInfo": {},
    })
    store.add_hpalog(HpaLog(
        job_id=job_id,
        hpascore=78.0,
        reason="tps above predicted band",
        details=[{"metricType": "tps", "current": 900.0, "upper": 800.0,
                  "lower": 400.0}],
        timestamp=time.time(),
    ))
    got = {name: c.status(job_id) for name, c in clients.items()}
    assert got["http"] == got["grpc"]
    log = got["grpc"]["hpalogs"][0]
    assert log["hpascore"] == 78.0
    assert log["details"][0]["metricType"] == "tps"


# ------------------------------------------------------------- search/alert
def test_search_identical_across_transports(stack):
    _, _, clients = stack
    clients["grpc"].create(CREATE_REQ)
    got = {
        name: c.search(app="demo", status="anomaly", limit=10)
        for name, c in clients.items()
    }
    assert got["http"] == got["grpc"]
    assert all(j["appName"] == "demo" for j in got["grpc"])


def test_search_unknown_status_rejected(dispatch):
    with pytest.raises(DispatchError) as exc:
        dispatch.search(status="bogus")
    assert exc.value.status == 400


def test_alert_identical_across_transports(stack):
    _, _, clients = stack
    got = {name: c.alert("hpaapp", "default", "hpa") for name, c in clients.items()}
    assert got["http"] == got["grpc"]
    assert got["grpc"]["hpalogs"], "hpa logs recorded earlier must surface"


# ------------------------------------------------- operator e2e over gRPC
@pytest.mark.parametrize("via_cli", [False, True], ids=["direct", "cli"])
def test_operator_grpc_engine_e2e(via_cli):
    """Flagship path with the gRPC hop in the middle: operator (GrpcAnalyst)
    -> gRPC dispatch -> shared service -> engine scores on the accelerator
    path -> verdict flows back over gRPC -> rollback.

    via_cli runs the SAME scenario through the shipped configuration path
    (cli.build_operator_loop + `--analyst grpc://...`), so operator-over-
    gRPC is proven reachable from the `foremast-tpu operator` entrypoint,
    not only from a hand-constructed analyst (round-2 verdict #2)."""
    from test_operator import _deployment, _metadata, _pod, _replicaset

    from foremast_tpu.dataplane.exporter import VerdictExporter
    from foremast_tpu.dataplane.fetch import FixtureDataSource
    from foremast_tpu.engine.analyzer import Analyzer
    from foremast_tpu.engine.config import EngineConfig
    from foremast_tpu.operator import FakeKube
    from foremast_tpu.operator.analyst import GrpcAnalyst
    from foremast_tpu.operator.loop import OperatorLoop
    from foremast_tpu.operator.types import (
        PHASE_HEALTHY,
        PHASE_RUNNING,
        PHASE_UNHEALTHY,
        RemediationAction,
    )

    rng = np.random.default_rng(7)
    now = time.time()
    kube = FakeKube()
    kube.upsert_metadata(_metadata(endpoint="http://prom/api/v1/"))
    store = JobStore()
    exporter = VerdictExporter()

    def resolver(url):
        # decoded match — see test_operator's flagship resolver note
        url = urllib.parse.unquote(url)
        n_hist = 1440
        if "pod=~" in url and "p-new" in url:
            return ([now - 600 + 60 * i for i in range(10)],
                    list(rng.poisson(300, 10).astype(float)))
        if "pod=~" in url:
            return ([now - 1200 + 60 * i for i in range(10)],
                    list(rng.poisson(30, 10).astype(float)))
        return ([now - 86400 + 60 * i for i in range(n_hist)],
                list(rng.poisson(30, n_hist).astype(float)))

    engine = Analyzer(EngineConfig(), FixtureDataSource(resolver=resolver),
                      store, exporter=exporter)
    service = ForemastService(store, exporter=exporter)
    server, port = serve_grpc_background(service, port=0)
    if via_cli:
        from foremast_tpu import cli

        args = cli.build_parser().parse_args(
            ["operator", "--analyst", f"grpc://127.0.0.1:{port}"]
        )
        loop, desc = cli.build_operator_loop(args, kube=kube)
        assert "GrpcAnalyst" in desc
        analyst = loop.barrelman.analyst
        assert isinstance(analyst, GrpcAnalyst)
    else:
        analyst = GrpcAnalyst(f"127.0.0.1:{port}")
        loop = OperatorLoop(kube, analyst)
    try:
        kube.deployments[("default", "demo")] = _deployment(
            "demo", image="app:v1", revision=1
        )
        kube.replicasets[("default", "rs1")] = _replicaset("rs1", "demo", 1, "h1")
        kube.pods[("default", "p-old")] = _pod("p-old", "demo", "h1")
        loop.tick(now)
        assert kube.get_monitor("default", "demo").status.phase == PHASE_HEALTHY

        kube.deployments[("default", "demo")] = _deployment(
            "demo", image="app:v2", revision=2
        )
        kube.replicasets[("default", "rs2")] = _replicaset("rs2", "demo", 2, "h2")
        kube.pods[("default", "p-new")] = _pod("p-new", "demo", "h2")
        m = kube.get_monitor("default", "demo")
        m.spec.remediation = RemediationAction(option="AutoRollback")
        kube.upsert_monitor(m)

        loop.tick(now)
        assert kube.get_monitor("default", "demo").status.phase == PHASE_RUNNING

        engine.run_cycle(now=now)
        loop.tick(now)
        m = kube.get_monitor("default", "demo")
        assert m.status.phase == PHASE_UNHEALTHY
        assert m.status.anomaly.anomalous_metrics
        assert m.status.remediation_taken
        d = kube.get_deployment("default", "demo")
        assert d["spec"]["template"]["spec"]["containers"][0]["image"] == "app:r1"
    finally:
        analyst.close()
        server.stop(grace=0.5)


def test_explicit_step_zero_survives_both_transports(stack):
    """step=0 must not be rewritten to the 60 s default over gRPC (proto3
    zero-vs-unset: step is presence-tracked in the schema) — otherwise the
    materialized URLs and HMAC job ids diverge across transports."""
    _, _, clients = stack
    req = {
        "appName": "stepzero",
        "strategy": "canary",
        "metricsInfo": {
            "current": {
                "m": {
                    "parameters": {"query": "q", "start": 1, "end": 2, "step": 0}
                }
            }
        },
    }
    ids = {name: c.create(req)["jobId"] for name, c in clients.items()}
    assert ids["http"] == ids["grpc"]


def test_client_side_validation_raises_dispatch_error(stack):
    """Garbage that can't cross the proto wire fails client-side with the
    SAME error type/status the server path produces (review finding: it
    leaked the server-internal ApiError, which GrpcAnalyst doesn't catch)."""
    _, _, clients = stack
    bad = {
        "appName": "demo",
        "strategy": "canary",
        "metricsInfo": {"current": {"m": {"url": "http://x", "priority": "high"}}},
    }
    for c in clients.values():
        with pytest.raises(DispatchError) as exc:
            c.create(bad)
        assert exc.value.status == 400
