"""Real-apiserver smoke (VERDICT r04 weak #5 / next #6).

`KubeClient`'s wire coverage lives in tests/test_kube_wire.py against
tests/fake_apiserver.py; what that cannot prove is acceptance by a REAL
apiserver: CRD schema admission, merge-patch semantics, the /status
subresource, RBAC'd token auth, and controller-manager-created
ReplicaSets/Pods feeding pod-name resolution. This module proves exactly
that, against a `kind` cluster, end to end:

  1. apply deploy/crds/ (schema acceptance),
  2. run the real OperatorLoop (KubeClient transport, in-process analyst
     + engine with canned metrics) over a real Deployment,
  3. roll a "bad" revision, let the engine flag it, and assert the
     remediation ReplicaSet-template PATCH landed on the live Deployment.

GATING: skips — visibly, never silently passes — unless `kind` AND
`kubectl` are on PATH. A cluster named `foremast-smoke` is reused when
present (fast local iteration), else created and torn down; cluster
creation needs image pulls, so a sandboxed/airgapped box skips at that
point with the creation error as the reason.
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
import urllib.parse

import numpy as np
import pytest

HAVE_TOOLS = shutil.which("kind") and shutil.which("kubectl")
pytestmark = pytest.mark.skipif(
    not HAVE_TOOLS, reason="kind/kubectl not installed: real-apiserver "
    "smoke runs only where a cluster can exist")

CLUSTER = "foremast-smoke"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout=180, **kw):
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, **kw)


def _kubectl(*args, timeout=60):
    r = _run(["kubectl", "--context", f"kind-{CLUSTER}", *args],
             timeout=timeout)
    if r.returncode != 0:
        raise RuntimeError(f"kubectl {' '.join(args)}: {r.stderr.strip()}")
    return r.stdout


@pytest.fixture(scope="module")
def kind_client():
    """A KubeClient bound to a kind cluster (created here if absent)."""
    from foremast_tpu.operator.kube import KubeClient

    clusters = _run(["kind", "get", "clusters"]).stdout.split()
    created = False
    if CLUSTER not in clusters:
        r = _run(["kind", "create", "cluster", "--name", CLUSTER,
                  "--wait", "120s"], timeout=600)
        if r.returncode != 0:
            pytest.skip(f"kind cluster creation failed (no image access?): "
                        f"{r.stderr.strip().splitlines()[-1:]}")
        created = True
    try:
        # token auth: the client is in-cluster-token-shaped, so mint a
        # short-lived SA token instead of repacking kind's client certs
        _run(["kubectl", "--context", f"kind-{CLUSTER}", "create",
              "serviceaccount", "foremast-smoke", "-n", "default"])
        _run(["kubectl", "--context", f"kind-{CLUSTER}", "create",
              "clusterrolebinding", "foremast-smoke-admin",
              "--clusterrole=cluster-admin",
              "--serviceaccount=default:foremast-smoke"])
        token = _kubectl("create", "token", "foremast-smoke",
                         "-n", "default", "--duration", "1h").strip()
        cfg = json.loads(_kubectl("config", "view", "--raw", "-o", "json"))
        cluster = next(c["cluster"] for c in cfg["clusters"]
                       if c["name"] == f"kind-{CLUSTER}")
        server = cluster["server"]
        ca_path = None
        if "certificate-authority-data" in cluster:
            import base64
            import tempfile

            f = tempfile.NamedTemporaryFile("wb", suffix=".crt",
                                            delete=False)
            f.write(base64.b64decode(cluster["certificate-authority-data"]))
            f.close()
            ca_path = f.name
        yield KubeClient(base_url=server, token=token, ca_path=ca_path)
    finally:
        if created:
            _run(["kind", "delete", "cluster", "--name", CLUSTER],
                 timeout=300)


def _wait(pred, what, timeout=90, interval=2.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def test_crds_accepted_and_flagship_rollback_on_real_apiserver(kind_client):
    ns, app = "default", "smoke-demo"
    try:
        _flow(kind_client, ns, app)
    finally:
        # ALWAYS start a reused cluster clean: a stale AutoRollback monitor
        # surviving a failed run would let the next run's assertions pass
        # against yesterday's state
        for res in ("deployment", "deploymentmonitor", "deploymentmetadata"):
            _run(["kubectl", "--context", f"kind-{CLUSTER}", "delete",
                  res, app, "-n", ns, "--ignore-not-found"])


def _flow(kind_client, ns, app):
    from foremast_tpu.dataplane import FixtureDataSource, VerdictExporter
    from foremast_tpu.engine import Analyzer, EngineConfig, JobStore
    from foremast_tpu.operator.analyst import InProcessAnalyst
    from foremast_tpu.operator.loop import OperatorLoop
    from foremast_tpu.operator.types import (
        Analyst, DeploymentMetadata, Metrics, Monitoring, RemediationAction,
    )
    from foremast_tpu.service.api import ForemastService

    kube = kind_client

    # 1. CRD schema acceptance by the real admission chain
    for crd in ("deploymentmetadata.yaml", "deploymentmonitor.yaml"):
        _kubectl("apply", "-f", os.path.join(REPO, "deploy", "crds", crd))
    _wait(lambda: "deploymentmonitors" in _kubectl(
        "api-resources", "--api-group=deployment.foremast.ai",
        "-o", "name"), "CRD registration")

    # per-app config through the real CRD path (exercises the codec both
    # ways: upsert -> apiserver admission -> list/get)
    kube.upsert_metadata(DeploymentMetadata(
        name=app, namespace=ns,
        analyst=Analyst(endpoint="in-process"),
        metrics=Metrics(data_source_type="prometheus",
                        endpoint="http://prom/api/v1/",
                        # without a monitored-metric list no analysis job
                        # is ever created and the flow dies silently
                        # healthy (caught driving the Auto-remediation
                        # path end-to-end)
                        monitoring=[Monitoring(metric_name="error5xx",
                                               metric_alias="error5xx")]),
    ))
    assert kube.get_metadata(ns, app) is not None

    # 2. a real Deployment; the controller-manager mints RS + pods (the
    # kind node preloads the pause image, so no external pull needed)
    manifest = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": app, "namespace": ns, "labels": {"app": app}},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": {"app": app}},
            "template": {
                "metadata": {"labels": {"app": app}},
                "spec": {"containers": [{
                    "name": "main",
                    "image": "registry.k8s.io/pause:3.9",
                    "env": [{"name": "REV", "value": "v1"}],
                }]},
            },
        },
    }
    p = _run(["kubectl", "--context", f"kind-{CLUSTER}", "apply",
              "-f", "-"], input=json.dumps(manifest))
    assert p.returncode == 0, p.stderr
    v1_pods = {po["metadata"]["name"] for po in _wait(
        lambda: kube.list_pods(ns, {"app": app}), "v1 pod object")}

    # engine with canned metrics, keyed on POD IDENTITY captured before
    # the rollout: the baseline query is pod-scoped to the v1 pods
    # (barrelman old_pods) and must stay healthy even while the v1 pod is
    # still alive during the maxSurge overlap — "any live pod" labeling
    # would storm the baseline too and erase the contrast the verdict
    # needs
    rng = np.random.default_rng(5)
    now = time.time()

    def resolver(url):
        url = urllib.parse.unquote(url)
        if "pod=~" in url:
            level = 30 if any(pn in url for pn in v1_pods) else 300
            return ([now - 600 + 60 * i for i in range(10)],
                    list(rng.poisson(level, 10).astype(float)))
        return ([now - 86400 + 60 * i for i in range(1440)],
                list(rng.poisson(30, 1440).astype(float)))

    store = JobStore()
    exporter = VerdictExporter()
    engine = Analyzer(EngineConfig(), FixtureDataSource(resolver=resolver),
                      store, exporter=exporter)
    service = ForemastService(store, exporter=exporter)
    loop = OperatorLoop(kube, InProcessAnalyst(service))

    loop.tick(now)  # v1 world -> baseline Healthy monitor
    m = _wait(lambda: kube.get_monitor(ns, app), "baseline monitor")
    m.spec.remediation = RemediationAction(option="AutoRollback")
    kube.upsert_monitor(m)

    # 3. roll v2 (env diff) and wait for the second RS revision + pod
    manifest["spec"]["template"]["spec"]["containers"][0]["env"] = [
        {"name": "REV", "value": "v2"}]
    p = _run(["kubectl", "--context", f"kind-{CLUSTER}", "apply",
              "-f", "-"], input=json.dumps(manifest))
    assert p.returncode == 0, p.stderr
    _wait(lambda: len({rs["metadata"]["name"]
                       for rs in kube.list_replicasets(ns)
                       if rs["metadata"].get("ownerReferences", [{}])[0]
                       .get("name") == app}) >= 2, "second ReplicaSet")

    loop.tick(time.time())  # sees the env diff -> starts canary analysis
    engine.run_cycle()  # scores: new pods error storm -> unhealthy
    loop.tick(time.time())  # applies verdict -> remediation rollback

    m = kube.get_monitor(ns, app)
    assert m is not None and m.status.remediation_taken, (
        f"phase={m.status.phase} remediation_taken="
        f"{m.status.remediation_taken}")
    # the rollback PATCH is synchronous: the live Deployment's template
    # must already read back at v1
    dep = kube.get_deployment(ns, app)
    env = dep["spec"]["template"]["spec"]["containers"][0].get("env", [])
    assert {"name": "REV", "value": "v1"} in env, env
