"""Bivariate-normal joint scorer: ops-level behavior + engine dispatch.

The two-metric judgment mode from the reference's model menu
(docs/guides/design.md:53-88): joint Gaussian fit on history, k-sigma
Mahalanobis ellipse on the current window.
"""
import numpy as np

from foremast_tpu.engine import Analyzer, Document, EngineConfig, JobStore, MetricQueries
from foremast_tpu.engine import jobs as J
from foremast_tpu.dataplane import FixtureDataSource
from foremast_tpu.ops.bivariate import bivariate_normal_anomalies
from foremast_tpu.utils.timeutils import to_rfc3339

STEP = 60


def _corr_pair(rng, n, rho=0.98, mu=(10.0, 5.0), scale=(1.0, 0.5)):
    z1 = rng.normal(size=n)
    z2 = rho * z1 + np.sqrt(1 - rho**2) * rng.normal(size=n)
    return mu[0] + scale[0] * z1, mu[1] + scale[1] * z2


def test_joint_anomaly_invisible_to_marginals():
    """Points that break the correlation structure are flagged even though
    each metric stays within its own k-sigma marginal band."""
    rng = np.random.default_rng(0)
    n_h, n_c = 400, 40
    x1h, x2h = _corr_pair(rng, n_h)
    # current: same marginals, correlation inverted -> jointly anomalous
    z1 = rng.normal(size=n_c)
    z2 = -0.98 * z1 + np.sqrt(1 - 0.98**2) * rng.normal(size=n_c)
    x1c = 10.0 + 2.0 * z1
    x2c = 5.0 + 1.0 * z2  # anti-correlated, amplitudes ~2 marginal sigma
    x1 = np.concatenate([x1h, x1c])[None].astype(np.float32)
    x2 = np.concatenate([x2h, x2c])[None].astype(np.float32)
    m = np.ones_like(x1, bool)
    region = np.zeros_like(m)
    region[:, n_h:] = True
    out = bivariate_normal_anomalies(
        x1, m, x2, m, region, np.asarray([3.0], np.float32)
    )
    assert int(out["count"][0]) >= 5
    # marginal check: most current x1 points are inside mean +- 3 sigma
    inside = np.abs(x1c - x1h.mean()) < 3 * x1h.std()
    assert inside.mean() > 0.5


def test_healthy_current_not_flagged():
    rng = np.random.default_rng(1)
    x1h, x2h = _corr_pair(rng, 400)
    x1c, x2c = _corr_pair(rng, 40)
    x1 = np.concatenate([x1h, x1c])[None].astype(np.float32)
    x2 = np.concatenate([x2h, x2c])[None].astype(np.float32)
    m = np.ones_like(x1, bool)
    region = np.zeros_like(m)
    region[:, 400:] = True
    out = bivariate_normal_anomalies(
        x1, m, x2, m, region, np.asarray([4.0], np.float32)
    )
    assert int(out["count"][0]) <= 1


def test_fail_open_without_history():
    x = np.ones((1, 10), np.float32)
    m = np.ones((1, 10), bool)
    region = np.ones((1, 10), bool)
    region[0, 0] = False  # a single history point: not judgeable
    out = bivariate_normal_anomalies(
        x * 100, m, x, m, region, np.asarray([2.0], np.float32)
    )
    assert int(out["count"][0]) == 0


def test_min_lower_bound_floors_marginal_band():
    rng = np.random.default_rng(2)
    x1h, x2h = _corr_pair(rng, 200)
    x1 = x1h[None].astype(np.float32)
    x2 = x2h[None].astype(np.float32)
    m = np.ones_like(x1, bool)
    region = np.zeros_like(m)
    region[:, 150:] = True
    out = bivariate_normal_anomalies(
        x1, m, x2, m, region, np.asarray([50.0], np.float32),
        np.asarray([9.0], np.float32), np.asarray([4.0], np.float32),
    )
    assert float(np.min(np.asarray(out["lower1"]))) >= 9.0
    assert float(np.min(np.asarray(out["lower2"]))) >= 4.0


# ------------------------------------------------------------- engine dispatch
def _two_metric_job(fixtures, rng, *, bad):
    n_h, n_c = 400, 40
    x1h, x2h = _corr_pair(rng, n_h)
    if bad:
        z1 = rng.normal(size=n_c)
        x1c = 10.0 + 2.0 * z1
        x2c = 5.0 + 1.0 * z1 * -1.0  # correlation flipped
    else:
        x1c, x2c = _corr_pair(rng, n_c)
    h_ts = (np.arange(n_h) * STEP).tolist()
    c_ts = ((n_h + np.arange(n_c)) * STEP).tolist()
    fixtures["h1"] = (h_ts, x1h.tolist())
    fixtures["h2"] = (h_ts, x2h.tolist())
    fixtures["c1"] = (c_ts, x1c.tolist())
    fixtures["c2"] = (c_ts, x2c.tolist())
    return Document(
        id="bi", app_name="app", namespace="d", strategy="canary",
        start_time=to_rfc3339(0), end_time=to_rfc3339(0),
        metrics={
            "latency": MetricQueries(current="c1", historical="h1"),
            "cpu": MetricQueries(current="c2", historical="h2"),
        },
    )


def test_engine_bivariate_mode_flags_broken_correlation():
    rng = np.random.default_rng(3)
    fixtures = {}
    store = JobStore()
    store.create(_two_metric_job(fixtures, rng, bad=True))
    cfg = EngineConfig(algorithm="bivariate_normal", threshold=4.0, policies={})
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=100_000.0)
    assert out["bi"] == J.COMPLETED_UNHEALTH
    assert "bivariate" in store.get("bi").reason


def test_engine_bivariate_mode_passes_healthy():
    rng = np.random.default_rng(4)
    fixtures = {}
    store = JobStore()
    store.create(_two_metric_job(fixtures, rng, bad=False))
    cfg = EngineConfig(algorithm="bivariate_normal", threshold=4.0, policies={})
    analyzer = Analyzer(cfg, FixtureDataSource(fixtures), store)
    out = analyzer.run_cycle(now=100_000.0)
    assert out["bi"] == J.COMPLETED_HEALTH


def test_bound_bitmask_upper_only_ignores_improvement_dips():
    """An upper-only metric pair (e.g. error rates, bound=1) must not alarm
    when both metrics drop far BELOW their history (an improvement)."""
    rng = np.random.default_rng(5)
    x1h, x2h = _corr_pair(rng, 300)
    n_c = 30
    x1c = np.full(n_c, x1h.mean() - 8 * x1h.std())
    x2c = np.full(n_c, x2h.mean() - 8 * x2h.std())
    x1 = np.concatenate([x1h, x1c])[None].astype(np.float32)
    x2 = np.concatenate([x2h, x2c])[None].astype(np.float32)
    m = np.ones_like(x1, bool)
    region = np.zeros_like(m)
    region[:, 300:] = True
    thr = np.asarray([3.0], np.float32)
    upper_only = np.asarray([1], np.int32)
    both = np.asarray([3], np.int32)
    out = bivariate_normal_anomalies(
        x1, m, x2, m, region, thr, None, None, upper_only, upper_only
    )
    assert int(out["count"][0]) == 0  # dips ignored
    out2 = bivariate_normal_anomalies(
        x1, m, x2, m, region, thr, None, None, both, both
    )
    assert int(out2["count"][0]) == n_c  # two-sided policy still fires
