"""Resilience primitives: breaker transitions, retry determinism, budgets,
deadlines, the resilient boundary wrappers, the chaos spec parser, and the
single-flight cache (ISSUE 1 tentpole + satellites)."""
import threading
import time

import pytest

from foremast_tpu.dataplane.exporter import VerdictExporter
from foremast_tpu.dataplane.fetch import CachingDataSource, FetchError
from foremast_tpu.resilience import (
    BreakerBoard,
    BreakerOpenError,
    CircuitBreaker,
    Deadline,
    FaultInjector,
    FaultyArchive,
    FaultyDataSource,
    FaultyKube,
    ResilientArchive,
    ResilientDataSource,
    ResilientKube,
    RetryBudget,
    RetryPolicy,
    host_key,
    parse_chaos_spec,
)
from foremast_tpu.resilience.breaker import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
)
from foremast_tpu.resilience.faults import (
    ERROR,
    OK,
    InjectedFetchError,
    InjectedKubeError,
    injectors_from_spec,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ------------------------------------------------------------- breaker
def test_breaker_full_lifecycle():
    clock = FakeClock()
    br = CircuitBreaker("prom", failure_threshold=3, recovery_seconds=10.0,
                        clock=clock)
    transitions = []
    br.subscribe(lambda name, old, new: transitions.append((old, new)))
    assert br.state == STATE_CLOSED
    br.record_failure()
    br.record_failure()
    assert br.state == STATE_CLOSED  # below threshold
    br.record_failure()
    assert br.state == STATE_OPEN
    assert not br.allow()
    # recovery elapses -> half-open, ONE probe slot
    clock.t = 11.0
    assert br.state == STATE_HALF_OPEN
    assert br.allow()
    assert not br.allow()  # second probe rejected while one is in flight
    br.record_success()
    assert br.state == STATE_CLOSED
    assert transitions == [
        (STATE_CLOSED, STATE_OPEN),
        (STATE_OPEN, STATE_HALF_OPEN),
        (STATE_HALF_OPEN, STATE_CLOSED),
    ]
    assert br.trips == 1


def test_breaker_half_open_failure_reopens():
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, recovery_seconds=5.0, clock=clock)
    br.record_failure()
    assert br.state == STATE_OPEN
    clock.t = 6.0
    assert br.allow()  # half-open probe
    br.record_failure()
    assert br.state == STATE_OPEN  # probe failed: fresh recovery clock
    assert not br.allow()
    clock.t = 10.0  # 4s after the reopen: still open
    assert br.state == STATE_OPEN
    clock.t = 11.5
    assert br.state == STATE_HALF_OPEN
    assert br.trips == 2


def test_breaker_success_resets_consecutive_count():
    br = CircuitBreaker(failure_threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()  # streak broken
    br.record_failure()
    br.record_failure()
    assert br.state == STATE_CLOSED  # consecutive, not windowed


def test_breaker_board_keys_and_hooks():
    board = BreakerBoard(failure_threshold=1, recovery_seconds=60.0)
    seen = []
    board.subscribe(lambda name, old, new: seen.append((name, new)))
    board.for_key("a").record_failure()
    board.for_key("b").record_failure()
    assert board.states() == {"a": STATE_OPEN, "b": STATE_OPEN}
    assert set(seen) == {("a", STATE_OPEN), ("b", STATE_OPEN)}
    assert board.counters()["a"]["trips"] == 1


def test_breaker_thread_safety_under_contention():
    br = CircuitBreaker(failure_threshold=50, recovery_seconds=0.01)
    errors = []

    def worker():
        try:
            for _ in range(500):
                if br.allow():
                    br.record_failure()
                br.state  # noqa: B018 - exercise the lazy transition path
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert br.state in (STATE_CLOSED, STATE_OPEN, STATE_HALF_OPEN)


# --------------------------------------------------------------- retry
def test_retry_jitter_deterministic_under_fixed_seed():
    a = RetryPolicy(seed=1234, base_delay=0.1, max_delay=10.0)
    b = RetryPolicy(seed=1234, base_delay=0.1, max_delay=10.0)
    assert [a.backoff(i) for i in range(8)] == [b.backoff(i) for i in range(8)]
    c = RetryPolicy(seed=99, base_delay=0.1, max_delay=10.0)
    assert [a.backoff(i) for i in range(8)] != [c.backoff(i) for i in range(8)]


def test_retry_backoff_exponential_envelope():
    pol = RetryPolicy(seed=7, base_delay=0.5, max_delay=4.0)
    for attempt in range(10):
        cap = min(4.0, 0.5 * 2 ** attempt)
        for _ in range(20):
            assert 0.0 <= pol.backoff(attempt) <= cap


def test_retry_call_retries_then_raises():
    sleeps = []
    pol = RetryPolicy(max_attempts=3, base_delay=0.01, seed=0,
                      sleep=sleeps.append)
    calls = []

    def flaky():
        calls.append(1)
        raise FetchError("down")

    with pytest.raises(FetchError):
        pol.call(flaky)
    assert len(calls) == 3
    assert len(sleeps) <= 2  # zero-delay jitter draws skip the sleep call
    assert pol.retries_total == 2


def test_retry_succeeds_midway():
    pol = RetryPolicy(max_attempts=5, base_delay=0.0, seed=0,
                      sleep=lambda s: None)
    state = {"n": 0}

    def eventually():
        state["n"] += 1
        if state["n"] < 3:
            raise FetchError("flap")
        return "ok"

    assert pol.call(eventually) == "ok"
    assert state["n"] == 3


def test_retry_no_retry_exceptions_propagate_immediately():
    pol = RetryPolicy(max_attempts=5, base_delay=0.0, sleep=lambda s: None)
    calls = []

    def boom():
        calls.append(1)
        raise BreakerOpenError("open")

    with pytest.raises(BreakerOpenError):
        pol.call(boom, no_retry=(BreakerOpenError,))
    assert len(calls) == 1


def test_retry_budget_bounds_total_attempts_against_dead_backend():
    """Acceptance: retry counts against a dead backend respect the budget —
    bounded TOTAL attempts per window (first attempts + budget), never
    first-attempts x max_attempts."""
    clock = FakeClock()
    budget = RetryBudget(max_retries=5, window_seconds=60.0, clock=clock)
    pol = RetryPolicy(max_attempts=4, base_delay=0.0, seed=0, budget=budget,
                      sleep=lambda s: None)
    attempts = []

    def dead():
        attempts.append(1)
        raise FetchError("dead")

    n_calls = 20
    for _ in range(n_calls):
        with pytest.raises(FetchError):
            pol.call(dead)
    # total attempts = one first attempt per call + at most the budget
    assert len(attempts) == n_calls + 5
    assert budget.denials > 0
    # a new window refills the budget
    clock.t = 61.0
    with pytest.raises(FetchError):
        pol.call(dead)
    assert len(attempts) == n_calls + 5 + 4  # full retry train again


def test_retry_budget_sliding_window_evicts():
    clock = FakeClock()
    b = RetryBudget(max_retries=2, window_seconds=10.0, clock=clock)
    assert b.try_spend() and b.try_spend() and not b.try_spend()
    clock.t = 10.5  # first two spent at t=0 age out
    assert b.try_spend()


# ------------------------------------------------------------ deadline
def test_deadline_clips_backoff_sleep():
    clock = FakeClock()
    dl = Deadline(5.0, clock=clock)
    assert dl.remaining() == 5.0
    assert dl.clip(10.0) == 5.0  # clipped to what's left
    assert dl.clip(2.0) == 2.0
    clock.t = 5.1
    assert dl.expired()
    assert dl.clip(2.0) == 0.0


def test_deadline_stops_retry_train():
    clock = FakeClock()
    dl = Deadline(0.35, clock=clock)
    sleeps = []

    def fake_sleep(s):
        sleeps.append(s)
        clock.t += max(s, 0.2)  # each attempt costs at least 0.2s

    pol = RetryPolicy(max_attempts=10, base_delay=0.3, max_delay=0.3,
                      seed=3, sleep=fake_sleep)
    attempts = []

    def dead():
        attempts.append(1)
        clock.t += 0.1
        raise FetchError("dead")

    with pytest.raises(FetchError):
        pol.call(dead, deadline=dl)
    # far fewer than max_attempts: the deadline cut the train short
    assert len(attempts) < 5
    # every sleep fit inside the remaining budget at its moment
    assert all(s <= 0.35 for s in sleeps)


# -------------------------------------------------- resilient data source
class DeadSource:
    def __init__(self, exc=None):
        self.calls = 0
        self.exc = exc or FetchError("connection refused")

    def fetch(self, url):
        self.calls += 1
        raise self.exc


class SlowDeadSource(DeadSource):
    def fetch(self, url):
        self.calls += 1
        time.sleep(0.25)
        raise self.exc


def _fast_policy(**kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("base_delay", 0.0)
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


def test_breaker_open_error_is_fetch_error():
    assert issubclass(BreakerOpenError, FetchError)


def test_host_key_extraction():
    assert host_key("http://prom:9090/api/v1/query?x=1") == "prom:9090"
    assert host_key("") == "unknown"
    assert host_key("not a url") == "not a url"


def test_resilient_source_opens_breaker_and_fast_fails():
    """Acceptance: with the breaker open, fetch returns in <10ms with no
    network attempt."""
    inner = SlowDeadSource()
    rs = ResilientDataSource(
        inner, retry=_fast_policy(),
        breakers=BreakerBoard(failure_threshold=2, recovery_seconds=300.0),
    )
    url = "http://prom:9090/api/v1/query"
    with pytest.raises(FetchError):
        rs.fetch(url)  # 2 attempts -> 2 consecutive failures -> trips
    calls_before = inner.calls
    t0 = time.perf_counter()
    with pytest.raises(BreakerOpenError):
        rs.fetch(url)
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.010, f"open breaker took {elapsed*1000:.1f}ms"
    assert inner.calls == calls_before  # no network attempt


def test_resilient_source_breaker_keys_are_per_host():
    inner = DeadSource()
    rs = ResilientDataSource(
        inner, retry=_fast_policy(),
        breakers=BreakerBoard(failure_threshold=2, recovery_seconds=300.0),
    )
    with pytest.raises(FetchError):
        rs.fetch("http://dead:9090/q")
    assert rs.breakers.states()["dead:9090"] == STATE_OPEN

    class Live:
        def fetch(self, url):
            return ([1.0], [2.0])

    rs.inner = Live()
    assert rs.fetch("http://live:9090/q") == ([1.0], [2.0])  # unaffected


def test_resilient_source_recovers_through_half_open():
    clock = FakeClock()
    inner = DeadSource()
    rs = ResilientDataSource(
        inner, retry=_fast_policy(),
        breakers=BreakerBoard(failure_threshold=2, recovery_seconds=5.0,
                              clock=clock),
    )
    url = "http://prom:9090/q"
    with pytest.raises(FetchError):
        rs.fetch(url)
    assert rs.breakers.states()["prom:9090"] == STATE_OPEN

    class Healed:
        def fetch(self, url):
            return ([1.0], [1.0])

    rs.inner = Healed()
    clock.t = 6.0  # recovery elapsed: next call is the half-open probe
    assert rs.fetch(url) == ([1.0], [1.0])
    assert rs.breakers.states()["prom:9090"] == STATE_CLOSED


def test_resilient_source_wraps_parse_errors_as_fetch_error():
    class Garbage:
        def fetch(self, url):
            raise ValueError("Expecting value: line 1 column 1 (char 0)")

    rs = ResilientDataSource(Garbage(), retry=_fast_policy())
    with pytest.raises(FetchError, match="fetch failed after retries"):
        rs.fetch("http://prom:9090/q")


def test_resilient_source_exports_metrics():
    exp = VerdictExporter()
    rs = ResilientDataSource(
        DeadSource(), retry=_fast_policy(max_attempts=3),
        breakers=BreakerBoard(failure_threshold=2, recovery_seconds=300.0),
        exporter=exp,
    )
    with pytest.raises(FetchError):
        rs.fetch("http://prom:9090/q")
    text = exp.render()
    assert "# TYPE foremastbrain:fetch_retries_total counter" in text
    assert "# TYPE foremastbrain:breaker_state gauge" in text
    assert 'foremastbrain:breaker_state{host="prom:9090"} 2.0' in text
    assert ('foremastbrain:breaker_transitions_total'
            '{host="prom:9090",to="open"} 1.0') in text


def test_resilient_source_none_fetch_window_is_breaker_neutral():
    """A None from fetch_window means "no byte-level path", not backend
    health: it must neither reset the consecutive-failure count (a reset
    before every real fetch would make the breaker untrippable for
    series-level sources) nor leak a half-open probe slot."""

    class SeriesOnly:  # has fetch_window, but its inner has no byte path
        def __init__(self):
            self.exc = FetchError("down")

        def fetch_window(self, url):
            return None

        def fetch(self, url):
            raise self.exc

    rs = ResilientDataSource(
        SeriesOnly(), retry=_fast_policy(max_attempts=1),
        breakers=BreakerBoard(failure_threshold=3, recovery_seconds=300.0),
    )
    url = "http://prom:9090/q"
    for _ in range(3):
        assert rs.fetch_window(url) is None  # neutral: no state change
        with pytest.raises(FetchError):
            rs.fetch(url)
    # 3 consecutive real failures trip the breaker despite the interleaved
    # neutral fetch_window calls
    assert rs.breakers.states()["prom:9090"] == STATE_OPEN


def test_breaker_release_returns_half_open_probe_slot():
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, recovery_seconds=5.0,
                        clock=clock)
    br.record_failure()
    clock.t = 6.0
    assert br.allow()  # probe slot taken
    br.release()  # neutral outcome: slot returned, state unchanged
    assert br.state == STATE_HALF_OPEN
    assert br.allow()  # slot available again


def test_resilient_source_cycle_deadline_plumbing():
    rs = ResilientDataSource(DeadSource(), retry=_fast_policy())
    dl = Deadline(0.0, clock=lambda: 1.0)  # already expired
    rs.set_cycle_deadline(dl)
    assert rs._deadline() is dl
    rs.set_cycle_deadline(None)
    assert rs._deadline() is None
    # and through the cache wrapper (the runtime composition)
    cached = CachingDataSource(rs)
    cached.set_cycle_deadline(dl)
    assert rs._deadline() is dl


# ---------------------------------------------------- resilient archive
class CountingArchive:
    """EsArchive-shaped double: swallows failures, counts .errors."""

    def __init__(self):
        self.errors = 0
        self.fail = False
        self.calls = 0

    def index_job(self, doc):
        self.calls += 1
        if self.fail:
            self.errors += 1
            return False
        return True

    def index_hpalog(self, log):
        return self.index_job(log)

    def index_state(self, key, value, updated_at):
        return self.index_job(None)

    def get(self, job_id):
        self.calls += 1
        if self.fail:
            self.errors += 1
            return None
        return {"id": job_id}

    def get_state(self, key):
        return None

    def search(self, *a, **kw):
        self.calls += 1
        return []


def test_resilient_archive_breaker_short_circuits():
    inner = CountingArchive()
    ra = ResilientArchive(
        inner, breakers=BreakerBoard(failure_threshold=3,
                                     recovery_seconds=300.0))
    inner.fail = True
    for _ in range(3):
        assert ra.index_job({"id": "x"}) is False
    assert ra.breakers.states()["archive"] == STATE_OPEN
    calls_before = inner.calls
    # open: sentinel returns with NO inner calls
    assert ra.index_job({"id": "x"}) is False
    assert ra.get("x") is None
    assert ra.search() == []
    assert inner.calls == calls_before


def test_resilient_archive_detects_swallowed_errors_and_recovers():
    clock = FakeClock()
    inner = CountingArchive()
    ra = ResilientArchive(
        inner, breakers=BreakerBoard(failure_threshold=2,
                                     recovery_seconds=5.0, clock=clock))
    inner.fail = True
    ra.get("a")
    ra.get("b")  # errors-counter delta marks both as failures
    assert ra.breakers.states()["archive"] == STATE_OPEN
    inner.fail = False
    clock.t = 6.0
    assert ra.get("c") == {"id": "c"}  # half-open probe succeeds
    assert ra.breakers.states()["archive"] == STATE_CLOSED


def test_resilient_archive_passes_attrs_through():
    inner = CountingArchive()
    ra = ResilientArchive(inner)
    assert ra.errors == 0  # observability attr delegated


# ------------------------------------------------------- resilient kube
class FlakyKube:
    def __init__(self, failures: int = 0, status: int = 0):
        from foremast_tpu.operator.kube import KubeError

        self._exc = KubeError("boom", status=status)
        self.failures = failures
        self.calls = 0

    def list_namespaces(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise self._exc
        return ["default"]


def test_resilient_kube_retries_transport_errors():
    k = ResilientKube(FlakyKube(failures=2, status=0),
                      retry=_fast_policy(max_attempts=3))
    assert k.list_namespaces() == ["default"]
    assert k.inner.calls == 3


def test_resilient_kube_does_not_retry_4xx():
    k = ResilientKube(FlakyKube(failures=99, status=404),
                      retry=_fast_policy(max_attempts=5))
    from foremast_tpu.operator.kube import KubeError

    with pytest.raises(KubeError):
        k.list_namespaces()
    assert k.inner.calls == 1  # API answer, not backend health
    assert k.breakers.states().get("kube") != STATE_OPEN


def test_resilient_kube_breaker_opens_on_5xx():
    from foremast_tpu.operator.kube import KubeError

    k = ResilientKube(
        FlakyKube(failures=99, status=503),
        retry=_fast_policy(max_attempts=2),
        breakers=BreakerBoard(failure_threshold=2, recovery_seconds=300.0),
    )
    with pytest.raises(KubeError):
        k.list_namespaces()
    assert k.breakers.states()["kube"] == STATE_OPEN
    calls = k.inner.calls
    with pytest.raises(KubeError):
        k.list_namespaces()  # fast-fail, no inner call
    assert k.inner.calls == calls


# ----------------------------------------------------------- chaos spec
def test_parse_chaos_spec_full_grammar():
    seed, plans = parse_chaos_spec(
        "seed=42; fetch.error=0.3; fetch.latency=0.2:0.05;"
        "fetch.garbage=0.1; archive.outage=5..10; kube.flap=3:2;"
        "kube.timeout=0.5:1.5"
    )
    assert seed == 42
    f = plans["fetch"]
    assert f.error_rate == 0.3
    assert (f.latency_rate, f.latency_seconds) == (0.2, 0.05)
    assert f.garbage_rate == 0.1
    assert plans["archive"].outages == [(5, 10)]
    k = plans["kube"]
    assert (k.flap_up, k.flap_down) == (3, 2)
    assert (k.timeout_rate, k.timeout_seconds) == (0.5, 1.5)


@pytest.mark.parametrize("bad", [
    "fetch.error",  # no '='
    "disk.error=0.5",  # unknown target
    "fetch.explode=1",  # unknown fault
    "archive.garbage=0.5",  # garbage is fetch-only
    "fetch.outage=5",  # malformed window
    "fetch.latency=0.5",  # missing seconds
])
def test_parse_chaos_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_chaos_spec(bad)


def test_fault_injector_deterministic_per_seed_and_target():
    _, plans = parse_chaos_spec("fetch.error=0.4")
    runs = []
    for _ in range(2):
        inj = FaultInjector(plans["fetch"], seed=7, target="fetch")
        runs.append([inj.decide() for _ in range(100)])
    assert runs[0] == runs[1]
    other = FaultInjector(plans["fetch"], seed=8, target="fetch")
    assert [other.decide() for _ in range(100)] != runs[0]


def test_fault_injector_outage_and_flap_windows_exact():
    _, plans = parse_chaos_spec("fetch.outage=2..4;")
    inj = FaultInjector(plans["fetch"], seed=0, target="fetch")
    assert [inj.decide() for _ in range(6)] == [OK, OK, ERROR, ERROR, OK, OK]
    _, plans = parse_chaos_spec("kube.flap=2:1")
    inj = FaultInjector(plans["kube"], seed=0, target="kube")
    assert [inj.decide() for _ in range(6)] == [OK, OK, ERROR, OK, OK, ERROR]


def test_faulty_data_source_injects_errors_and_garbage():
    _, plans = parse_chaos_spec("fetch.error=1.0")
    inj = FaultInjector(plans["fetch"], seed=0, target="fetch")

    class Fine:
        def fetch(self, url):
            return ([1.0], [1.0])

    fs = FaultyDataSource(Fine(), inj)
    with pytest.raises(InjectedFetchError):
        fs.fetch("http://x/q")
    # garbage goes through the REAL parse path: a truncated body either
    # raises (python json fallback) or parses to an EMPTY series (the
    # tolerant native scanner) — both degrade the job, never the cycle
    _, plans = parse_chaos_spec("fetch.garbage=1.0")
    inj = FaultInjector(plans["fetch"], seed=0, target="fetch")
    fs = FaultyDataSource(Fine(), inj)
    for _ in range(3):  # all three garbage bodies
        try:
            ts, vals = fs.fetch("http://x/q")
        except Exception:  # noqa: BLE001 - parse-dependent
            continue
        assert len(ts) == 0 and len(vals) == 0
    assert inj.injected_garbage == 3


def test_faulty_archive_returns_sentinels():
    _, plans = parse_chaos_spec("archive.error=1.0")
    inj = FaultInjector(plans["archive"], seed=0, target="archive")
    fa = FaultyArchive(CountingArchive(), inj)
    assert fa.index_job({}) is False
    assert fa.get("x") is None
    assert fa.search() == []
    assert fa.errors == 3


def test_faulty_kube_raises_kube_errors():
    _, plans = parse_chaos_spec("kube.error=1.0")
    inj = FaultInjector(plans["kube"], seed=0, target="kube")
    fk = FaultyKube(FlakyKube(), inj)
    with pytest.raises(InjectedKubeError):
        fk.list_namespaces()


def test_injectors_from_spec_only_active_targets():
    injs = injectors_from_spec("seed=1;fetch.error=0.5")
    assert set(injs) == {"fetch"}


# --------------------------------------------- exporter counters / TYPE
def test_exporter_counter_rendering_well_formed():
    exp = VerdictExporter()
    exp.record_counter("foremastbrain:fetch_retries_total",
                       {"host": "prom:9090"}, 2, help="retries by host")
    exp.record_counter("foremastbrain:fetch_retries_total",
                       {"host": "prom:9090"}, 1)
    exp.record_gauge("foremastbrain:breaker_state", {"host": "prom:9090"},
                     2.0, help="circuit state")
    text = exp.render()
    lines = text.strip().splitlines()
    assert "# HELP foremastbrain:fetch_retries_total retries by host" in lines
    assert "# TYPE foremastbrain:fetch_retries_total counter" in lines
    assert "# TYPE foremastbrain:breaker_state gauge" in lines
    assert 'foremastbrain:fetch_retries_total{host="prom:9090"} 3.0' in lines
    # metadata lines precede their metric's samples (exposition contract)
    type_i = lines.index("# TYPE foremastbrain:fetch_retries_total counter")
    sample_i = lines.index(
        'foremastbrain:fetch_retries_total{host="prom:9090"} 3.0')
    assert type_i < sample_i


def test_exporter_counters_survive_stale_eviction():
    exp = VerdictExporter(stale_seconds=0.0)  # everything gauge-stale
    exp.record_bounds("a", "ns", "m", 1, 0, 0)
    exp.record_counter("foremastbrain:x_total", {}, 1)
    assert exp.samples() == []  # gauges evicted (existing contract)
    assert exp.counter_samples() == [("foremastbrain:x_total", {}, 1.0)]
    assert "foremastbrain:x_total" in exp.render()


def test_exporter_counter_key_set_is_bounded():
    """Counter labels derive from job-submitted query-URL hosts: a create
    flood with unique endpoints must not grow /metrics without bound."""
    exp = VerdictExporter()
    cap = VerdictExporter.MAX_COUNTER_KEYS
    for i in range(cap + 10):
        exp.record_counter("foremastbrain:x_total", {"host": f"h{i}"}, 1)
    assert len(exp.counter_samples()) == cap
    # existing keys still increment in place at the ceiling
    exp.record_counter("foremastbrain:x_total", {"host": f"h{cap + 9}"}, 1)
    vals = {labels["host"]: v for _, labels, v in exp.counter_samples()}
    assert vals[f"h{cap + 9}"] == 2.0


def test_exporter_plain_gauges_render_without_metadata():
    exp = VerdictExporter()
    exp.record_bounds("a", "ns", "m", 1, 0, 0)
    for line in exp.render().strip().splitlines():
        assert not line.startswith("#")


# ------------------------------------------------- single-flight cache
def test_caching_source_single_flight_on_concurrent_miss():
    calls = []
    release = threading.Event()

    class Slow:
        def fetch(self, url):
            calls.append(url)
            release.wait(2.0)
            return ([1.0], [2.0])

    cache = CachingDataSource(Slow(), ttl_seconds=100.0)
    results = [None] * 6
    threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(
            i, cache.fetch("http://x/q")))
        for i in range(6)
    ]
    for t in threads:
        t.start()
    time.sleep(0.05)  # let every thread reach the miss
    release.set()
    for t in threads:
        t.join(5.0)
    assert len(calls) == 1  # only the leader hit the backend
    assert all(r == ([1.0], [2.0]) for r in results)
    assert cache.single_flight_waits == 5
    assert cache.hits == 0 and cache.misses == 1


def test_caching_source_single_flight_leader_failure_shared():
    class Failing:
        def __init__(self):
            self.calls = 0

        def fetch(self, url):
            self.calls += 1
            time.sleep(0.05)
            raise FetchError("down")

    inner = Failing()
    cache = CachingDataSource(inner, ttl_seconds=100.0)
    errors = []

    def go():
        try:
            cache.fetch("u")
        except FetchError as e:
            errors.append(e)

    threads = [threading.Thread(target=go) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5.0)
    assert len(errors) == 4  # every waiter saw the leader's failure
    assert inner.calls == 1
    # the failure is NOT cached: the next call fetches again
    with pytest.raises(FetchError):
        cache.fetch("u")
    assert inner.calls == 2


def test_caching_source_distinct_keys_fly_independently():
    calls = []

    class Rec:
        def fetch(self, url):
            calls.append(url)
            return ([1.0], [1.0])

    cache = CachingDataSource(Rec(), ttl_seconds=100.0)
    cache.fetch("a")
    cache.fetch("b")
    cache.fetch("a")  # hit
    assert calls == ["a", "b"]
    assert cache.hits == 1 and cache.misses == 2


def test_resilient_source_refresh_metrics_resurrects_stale_state_gauge():
    """An idle OPEN breaker fires no transitions; the scrape-time refresh
    must re-stamp its state gauge so it cannot stale-evict away while the
    circuit is still open."""
    exp = VerdictExporter(stale_seconds=0.05)
    rs = ResilientDataSource(
        DeadSource(), retry=_fast_policy(),
        breakers=BreakerBoard(failure_threshold=1, recovery_seconds=300.0),
        exporter=exp)
    with pytest.raises(FetchError):
        rs.fetch("http://h:1/q")
    time.sleep(0.1)  # past the stale horizon, breaker untouched
    assert "breaker_state" not in exp.render()
    rs.refresh_metrics()
    assert 'foremastbrain:breaker_state{host="h:1"} 2.0' in exp.render()


def test_breaker_board_eviction_prefers_closed_breakers():
    board = BreakerBoard(failure_threshold=1, recovery_seconds=300.0,
                         max_keys=2)
    board.for_key("open-one").record_failure()
    board.for_key("closed-one")
    board.for_key("new-key")  # at capacity: must evict the CLOSED entry
    states = board.states()
    assert states["open-one"] == STATE_OPEN  # protection survives
    assert "closed-one" not in states


def test_faulty_archive_errors_counter_stays_live():
    """Chaos must not blind the errors-delta failure detection: the
    wrapper's .errors is injected + the inner archive's LIVE count."""
    from foremast_tpu.resilience.faults import FaultPlan

    class SwallowingEs:
        def __init__(self):
            self.errors = 0

        def get(self, job_id):
            self.errors += 1  # real swallowed transport error
            return None

    fa = FaultyArchive(SwallowingEs(),
                       FaultInjector(FaultPlan(), seed=0, target="archive"))
    fa.get("x")
    assert fa.errors == 1
    ra = ResilientArchive(
        fa, breakers=BreakerBoard(failure_threshold=2,
                                  recovery_seconds=300.0))
    ra.get("a")
    ra.get("b")
    assert ra.breakers.states()["archive"] == STATE_OPEN


# ------------------------------------------------------- operator loop
def test_operator_tick_backoff_schedule():
    from foremast_tpu.operator.loop import OperatorLoop

    loop = OperatorLoop.__new__(OperatorLoop)  # delay math only
    assert loop._tick_delay(0, 10.0) == 10.0
    assert loop._tick_delay(1, 10.0) == 20.0
    assert loop._tick_delay(2, 10.0) == 40.0
    assert loop._tick_delay(5, 10.0) == 300.0  # capped
    assert loop._tick_delay(50, 10.0) == 300.0  # exponent clamped too


def test_operator_run_forever_logs_and_backs_off(caplog):
    import logging

    from foremast_tpu.operator.loop import OperatorLoop

    loop = OperatorLoop.__new__(OperatorLoop)
    loop._stop_requested = False
    ticks = {"n": 0}

    def bad_tick(now=None):
        ticks["n"] += 1
        if ticks["n"] >= 3:
            loop.request_stop()
        raise RuntimeError("apiserver down")

    loop.tick = bad_tick
    with caplog.at_level(logging.ERROR, logger="foremast_tpu.operator"):
        t0 = time.time()
        loop.run_forever(interval=0.01)
        elapsed = time.time() - t0
    assert ticks["n"] == 3
    msgs = [r.message for r in caplog.records]
    assert any("operator tick failed" in m for m in msgs)
    assert any("consecutive=2" in m for m in msgs)
    # backoff happened: 0.01 + 0.02+0.04 floors (minus the final stop)
    assert elapsed >= 0.02


# ------------------------------------------------------ service /status
def test_service_status_endpoint_reports_breakers():
    from foremast_tpu.engine.jobs import JobStore
    from foremast_tpu.service.api import ForemastService

    rs = ResilientDataSource(
        DeadSource(), retry=_fast_policy(),
        breakers=BreakerBoard(failure_threshold=1, recovery_seconds=300.0))
    svc = ForemastService(JobStore(), resilience=rs)
    code, body = svc.status_summary()
    assert code == 200 and body["status"] == "ok"
    with pytest.raises(FetchError):
        rs.fetch("http://dead:1/q")
    code, body = svc.status_summary()
    assert body["status"] == "degraded"
    assert body["resilience"]["breakers"]["dead:1"] == STATE_OPEN
    assert body["resilience"]["retries_total"] >= 1
