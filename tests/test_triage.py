"""Tier-0 triage screen (ISSUE 7): ops/triage.py + engine/triage.py.

The two load-bearing contracts:

  * the fused screen's statistics match a plain-numpy reference
    (randomized property test over NaN runs, gaps, short windows,
    constant/quantized series);
  * triage never flips a verdict the full path would give — the
    escalation-threshold sweep runs the SAME fixture stream through
    TRIAGE=0 and a grid of (TRIAGE_Z, TRIAGE_MARGIN) arms and pins the
    verdict state byte-identical every time; only the launch count may
    differ. `make perf` additionally gates the launch cut (≤ 20% of the
    screen-free path on a no-anomaly steady fleet).
"""
from __future__ import annotations

import json

import numpy as np
import pytest

from foremast_tpu.dataplane import FixtureDataSource, VerdictExporter
from foremast_tpu.engine import (
    Analyzer,
    Document,
    EngineConfig,
    JobStore,
    MetricQueries,
)
from foremast_tpu.engine import provenance as prov
from foremast_tpu.engine.triage import TriageGate, screen_cap
from foremast_tpu.ops import triage as triage_ops
from foremast_tpu.service.api import ForemastService
from foremast_tpu.utils.timeutils import to_rfc3339

STEP = 60
SEED = 20260807


# ---------------------------------------------------------------------------
# plain-numpy reference of the screen statistics (independent loop
# implementation — NOT the kernel's cumsum algebra)
# ---------------------------------------------------------------------------

def _ref_ma_preds(x, mask, window):
    """Causal rolling mean over the valid points of the last `window` time
    slots; undefined slots freeze at the rolling mean evaluated just after
    the most recent observation (slots before the first observation see
    the first valid value). Mirrors the documented semantics of
    `ops.forecast._moving_average_1d`, by loop."""
    T = x.shape[0]
    x = x.astype(np.float32)
    ma = np.full(T, np.nan, np.float32)
    for t in range(T):
        lo = max(t - window, 0)
        sel = mask[lo:t]
        if sel.any():
            ma[t] = np.float32(x[lo:t][sel].mean())
    first = np.float32(x[mask][0]) if mask.any() else x[0]
    preds = np.empty(T, np.float32)
    hold = np.nan
    prev = -1  # last valid index <= t-1
    for t in range(T):
        if t == 0 or mask[t - 1]:
            hold = ma[t]
        if not np.isnan(ma[t]):
            preds[t] = ma[t]
        else:
            preds[t] = hold if prev >= 0 else first
        if mask[t]:
            prev = t
    return preds


def _ref_screen(x, mask, region, thr, bound, mlb, margin, window):
    """Reference screen statistics for one row (float64 reductions)."""
    x = x.astype(np.float32)
    hist = mask & ~region
    checked = mask & region
    n_h = int(hist.sum())
    # predictions come from the HISTORY mask only — the judged region is
    # extrapolated from the frozen rolling mean, exactly like the band
    # scorer's hist_mask = xm & ~region
    preds = _ref_ma_preds(x, hist, window)
    r = np.where(hist, x - preds, 0.0).astype(np.float64)
    sigma = float(np.sqrt((r ** 2).sum() / max(n_h, 1)))
    if n_h < 2:
        sigma = float("inf")
    mode = bound if bound != 0 else 3

    def band(width_sigmas, eps=0.0):
        # errstate: rows with an empty history make preds NaN / sigma inf
        # (evaluated here, skipped by the caller's min-points floor)
        with np.errstate(invalid="ignore"):
            w = width_sigmas * sigma
            upper = preds + w + eps
            lower = np.maximum(preds - w, mlb) - eps
            viol = ((x > upper) & bool(mode & 1)) | (
                (x < lower) & bool(mode & 2))
        return int((viol & checked).sum()), upper, lower

    count, upper, lower = band(thr)
    dev = np.abs(x - preds)
    resid_z = float(np.where(checked, dev, 0.0).max()
                    / max(sigma, 1e-30)) if np.isfinite(sigma) else 0.0
    hv = np.sort(x[hist].astype(np.float64))
    if n_h:
        med = 0.5 * (hv[(n_h - 1) // 2] + hv[n_h // 2])
        ad = np.sort(np.abs(x[hist].astype(np.float64) - med))
        mad = 0.5 * (ad[(n_h - 1) // 2] + ad[n_h // 2])
        scale = max(1.4826 * mad, sigma if np.isfinite(sigma) else 0.0)
        robust_z = float(np.where(checked, np.abs(x - med), 0.0).max()
                         / max(scale, 1e-30))
    else:
        robust_z = 0.0
    n_r = max(int(region.sum()), 1)
    return {
        "count": count,
        "checked": int(checked.sum()),
        "n_hist": n_h,
        "sigma": sigma,
        "resid_z": resid_z,
        "robust_z": robust_z,
        "upper_mean": float(np.where(region, upper, 0.0).sum() / n_r),
        "lower_mean": float(np.where(region, lower, 0.0).sum() / n_r),
        "band": band,  # closure for eps-bracketing count checks
        "thr": thr,
    }


def _rand_row(rng, T):
    """One randomized packed row: varied level/noise, gaps, NaN runs at
    masked slots, occasional quantized (integer) or constant series, and
    occasionally a too-short history."""
    kind = rng.integers(0, 5)
    level = float(rng.uniform(0.5, 100.0))
    noise = float(rng.uniform(0.01, 0.3)) * level
    x = rng.normal(level, noise, T).astype(np.float32)
    if kind == 1:      # quantized: MAD can be 0 while sigma isn't
        x = np.round(x).astype(np.float32)
    elif kind == 2:    # constant series
        x = np.full(T, np.float32(level))
    mask = rng.random(T) > 0.12
    if kind == 3:      # NaN run at masked-out slots (parse gaps)
        run = slice(T // 4, T // 4 + max(T // 8, 1))
        x[run] = np.nan
        mask[run] = False
    L = T if kind != 4 else int(rng.integers(3, max(T // 8, 4)))
    mask[L:] = False   # right padding (short window when kind == 4)
    x[~mask] = np.where(rng.random((~mask).sum()) < 0.3, np.nan,
                        0.0).astype(np.float32)
    n_h = int(L * rng.uniform(0.5, 0.9))
    region = np.zeros(T, bool)
    region[n_h:L] = True
    thr = float(rng.choice([2.0, 3.0, 5.0, 10.0]))
    bound = int(rng.choice([0, 1, 2, 3]))
    mlb = float(rng.choice([0.0, 0.0, level * 0.5]))
    return x, mask, region, thr, bound, mlb


def test_screen_stats_property_vs_numpy_reference():
    rng = np.random.default_rng(SEED)
    window = 30
    margin = 0.25
    for round_i in range(8):
        T = int(rng.choice([32, 64, 128]))
        B = 16
        rows = [_rand_row(rng, T) for _ in range(B)]
        xv = np.stack([r[0] for r in rows])
        xm = np.stack([r[1] for r in rows])
        reg = np.stack([r[2] for r in rows])
        thr = np.asarray([r[3] for r in rows], np.float32)
        bnd = np.asarray([r[4] for r in rows], np.int32)
        mlb = np.asarray([r[5] for r in rows], np.float32)
        mg = np.full(B, margin, np.float32)
        out = {k: np.asarray(v) for k, v in triage_ops.screen_rows(
            xv, xm, reg, thr, bnd, mlb, mg, window).items()}
        for i in range(B):
            ref = _ref_screen(xv[i], xm[i], reg[i], float(thr[i]),
                              int(bnd[i]), float(mlb[i]), margin, window)
            ctx = f"round {round_i} row {i}"
            assert int(out["checked"][i]) == ref["checked"], ctx
            assert int(out["n_hist"][i]) == ref["n_hist"], ctx
            # no NaN may ever escape the kernel: a NaN statistic would
            # make the host-side CLEAR comparison silently False (an
            # escalate, so verdict-safe, but the stats must stay honest)
            for k in ("count", "shrunk_count", "robust_z", "resid_z"):
                assert not np.isnan(float(out[k][i])), f"{ctx}: {k} NaN"
            if ref["n_hist"] == 0:
                continue  # unscreenable either way (min-points floor)
            sg = float(out["sigma"][i])
            if np.isfinite(ref["sigma"]):
                np.testing.assert_allclose(sg, ref["sigma"], rtol=2e-3,
                                           atol=1e-5, err_msg=ctx)
            else:
                assert not np.isfinite(sg), ctx
            # counts: float32-vs-float64 drift may flip only points within
            # eps of the band boundary — bracket instead of exact-match
            eps = 1e-3 * max(abs(ref["upper_mean"]), abs(ref["lower_mean"]),
                             1e-3)
            lo, _, _ = ref["band"](ref["thr"], eps)
            hi, _, _ = ref["band"](ref["thr"], -eps)
            assert lo <= int(out["count"][i]) <= hi, ctx
            s_lo, _, _ = ref["band"](ref["thr"] - margin, eps)
            s_hi, _, _ = ref["band"](ref["thr"] - margin, -eps)
            assert s_lo <= int(out["shrunk_count"][i]) <= s_hi, ctx
            # the shrunk band is strictly narrower: dominance, always
            assert int(out["shrunk_count"][i]) >= int(out["count"][i]), ctx
            # degenerate floor: on a (near-)constant series sigma is pure
            # float-rounding noise, so resid_z and the counts are
            # noise/noise ratios — escalation-direction-safe (robust_z is
            # exactly 0 there) but not comparable to a float64 reference
            scale = max(abs(ref["upper_mean"]), abs(ref["lower_mean"]), 1.0)
            if np.isfinite(ref["sigma"]) and ref["sigma"] > 1e-5 * scale:
                np.testing.assert_allclose(
                    float(out["resid_z"][i]), ref["resid_z"], rtol=2e-3,
                    atol=1e-4, err_msg=ctx)
                # the bounds are preds ± thr*sigma: sigma's float32 drift
                # amplifies by thr and the subtraction cancels, so the
                # honest tolerance scales with the BAND WIDTH, not the
                # bound's own magnitude
                btol = 5e-3 * (ref["thr"] * ref["sigma"]
                               + abs(ref["upper_mean"])) + 1e-4
                assert abs(float(out["upper_mean"][i])
                           - ref["upper_mean"]) <= btol, ctx
                assert abs(float(out["lower_mean"][i])
                           - ref["lower_mean"]) <= btol, ctx
            if ref["robust_z"] < 1e6:  # scale ~0 blowups: sign-only check
                np.testing.assert_allclose(
                    float(out["robust_z"][i]), ref["robust_z"], rtol=2e-3,
                    atol=1e-4, err_msg=ctx)


def test_screen_constant_series_clears_and_spike_escalates():
    """A constant series is the boring-row archetype: zero violations,
    robust_z 0 (MAD 0 must not divide-by-zero into always-escalate).
    The same series with one current-region spike must escalate."""
    T, window = 128, 30
    x = np.full(T, np.float32(42.0))
    mask = np.ones(T, bool)
    region = np.zeros(T, bool)
    region[96:] = True
    args = (np.stack([x, x.copy()]), np.stack([mask, mask]),
            np.stack([region, region]),
            np.full(2, 2.0, np.float32), np.ones(2, np.int32),
            np.zeros(2, np.float32), np.full(2, 0.25, np.float32))
    spiked = args[0].copy()
    spiked[1, 100] = 1000.0
    args = (spiked, *args[1:])
    out = {k: np.asarray(v) for k, v in
           triage_ops.screen_rows(*args, window).items()}
    assert int(out["shrunk_count"][0]) == 0
    assert float(out["robust_z"][0]) == 0.0
    assert int(out["shrunk_count"][1]) >= 1
    assert float(out["robust_z"][1]) > 8.0


def test_triage_z_zero_escalates_constant_series():
    """TRIAGE_Z=0 must screen nothing — the documented off-semantics —
    including rows whose robust_z is exactly 0.0 (constant series), which
    a strict > guard would still clear."""
    g = TriageGate.__new__(TriageGate)
    g.z, g.margin, g.min_points = 0.0, 0.25, 1

    class _An:
        @staticmethod
        def _gate(checked):
            return 2.0

    g.an = _An()
    o = {"n_hist": 100, "shrunk_count": 0, "checked": 32, "robust_z": 0.0}
    assert g._row_clear("band", o) is False
    g.z = 8.0
    assert g._row_clear("band", o) is True


def test_screen_cap_memory_scaling():
    assert screen_cap(16384, 128) == 16384
    assert screen_cap(16384, 1024) == 16384
    assert screen_cap(16384, 4096) == 4096   # budget / T
    assert screen_cap(16384, 16384) == 1024  # floor
    assert screen_cap(4, 128) == 16          # fire_rows floor


def test_arg_spec_matches_kernel_signature():
    out = triage_ops.screen_rows(*triage_ops.triage_arg_spec(16, 64), 30)
    assert np.asarray(out["count"]).shape == (16,)


# ---------------------------------------------------------------------------
# e2e fixtures: a continuous monitor fleet of band jobs
# ---------------------------------------------------------------------------

def _series(rng, level, n, spread=None):
    spread = level * 0.1 + 0.01 if spread is None else spread
    ts = np.arange(n) * STEP
    return ts.tolist(), np.clip(rng.normal(level, spread, n), 0,
                                None).tolist()


def _fleet(n_watch=6, seed=SEED):
    """(store, fixtures, advance): continuous single-metric band monitors
    plus the escalation shapes — a VERDICT-anomalous job (crosses the band
    gate), a borderline sub-verdict job (fails the screen, stays healthy),
    a canary-class band job, and a short-history job. `advance(cycle)`
    appends one fresh sample per series so every fingerprint moves every
    cycle (the memo-miss regime triage exists for)."""
    rng = np.random.default_rng(seed)
    fixtures: dict = {}
    store = JobStore()
    levels: dict = {}

    def mk(job_id, strategy="continuous", level=10.0, n_cur=32,
           n_hist=200, metric="latency"):
        cur, hist = f"u/{job_id}/c", f"u/{job_id}/h"
        fixtures[cur] = _series(rng, level, n_cur)
        fixtures[hist] = _series(rng, level, n_hist)
        levels[job_id] = level
        store.create(Document(
            id=job_id, app_name=f"app-{job_id}", namespace="triage",
            strategy=strategy, start_time=to_rfc3339(0.0),
            end_time="" if strategy == "continuous" else
            to_rfc3339(5_000_000.0),
            metrics={metric: MetricQueries(current=cur, historical=hist)},
        ))

    for i in range(n_watch):
        mk(f"watch-{i}", level=float(5 + 3 * i))
    mk("anomalous", level=10.0)
    cur = fixtures["u/anomalous/c"]
    # every current point far outside the band: crosses the verdict gate
    fixtures["u/anomalous/c"] = (cur[0], [v + 200.0 for v in cur[1]])
    mk("borderline", level=10.0)
    cur = fixtures["u/borderline/c"]
    # sustained sub-verdict anomaly: a few big spikes — enough to fail
    # the screen forever, too few to cross max(2, 0.1 * checked)
    vals = list(cur[1])
    vals[5] += 200.0
    fixtures["u/borderline/c"] = (cur[0], vals)
    mk("canary-band", strategy="canary", level=10.0)
    mk("thin", level=10.0, n_hist=12)  # below TRIAGE_MIN_POINTS

    def advance(cycle):
        for url, (ts, vals) in list(fixtures.items()):
            job_id = url.split("/")[1]
            if not url.endswith("/c"):
                continue
            nrng = np.random.default_rng(hash((url, cycle)) % 2 ** 32)
            lvl = levels[job_id]
            nxt = float(np.clip(nrng.normal(lvl, lvl * 0.1 + 0.01), 0,
                                None))
            if job_id == "anomalous":
                nxt += 200.0
            fixtures[url] = (ts + [ts[-1] + STEP], vals + [nxt])

    return store, fixtures, advance


def _snapshot(store: JobStore) -> str:
    docs = {}
    for doc in store._jobs.values():
        docs[doc.id] = {"status": doc.status, "reason": doc.reason,
                        "anomaly": doc.anomaly}
    return json.dumps(docs, sort_keys=True)


def _run_arm(cycles=3, seed=SEED, **cfg):
    cfg.setdefault("max_stuck_seconds", 1e9)
    cfg.setdefault("multimetric_auto", False)
    store, fixtures, advance = _fleet(seed=seed)
    an = Analyzer(EngineConfig(**cfg), FixtureDataSource(fixtures), store,
                  VerdictExporter())
    snaps = []
    for c in range(cycles):
        an.run_cycle(worker="w", now=1000.0 + 10 * c)
        snaps.append(_snapshot(store))
        advance(c)
    return an, store, snaps


# ------------------------------------------------- verdict-safety sweep

def test_threshold_sweep_verdicts_byte_identical_to_triage_off():
    """The acceptance pin: for EVERY swept (TRIAGE_Z, TRIAGE_MARGIN) the
    per-cycle verdict state equals the TRIAGE=0 arm byte-for-byte on the
    same advancing fixture stream — anomalous, borderline, canary, thin
    and boring jobs alike. Only the launch count may differ."""
    _, _, off_snaps = _run_arm(triage=False)
    swept = [(0.0, 0.25), (2.0, 0.25), (8.0, 0.0), (8.0, 0.25),
             (8.0, 1.0), (1e9, 0.25), (8.0, 100.0)]
    for z, margin in swept:
        an, _, snaps = _run_arm(triage=True, triage_z=z,
                                triage_margin=margin)
        assert snaps == off_snaps, f"TRIAGE_Z={z} TRIAGE_MARGIN={margin}"
        # the arms must actually exercise both classifications: at the
        # default thresholds the boring rows clear; at the paranoid ends
        # (z=0, or margin >= threshold) everything escalates
        cleared = sum(an.triage_cleared_total.values())
        screened = sum(an.triage_screened_total.values())
        assert screened > 0
        if (z, margin) == (8.0, 0.25):
            assert cleared > 0
        if z == 0.0 or margin >= 100.0:
            assert cleared == 0


def test_triage_off_restores_screen_free_path_exactly():
    an, _, _ = _run_arm(triage=False)
    assert an.triage_screened_total == {}
    assert an.last_cycle_stages.get("triage") is None


def test_escalation_classes_always_take_full_path():
    """Canary-class jobs, thin histories, and the verdict-anomalous job
    must never be cleared; the boring watchers clear."""
    an, store, _ = _run_arm(triage=True)
    gate_hits = an.provenance.get("canary-band")
    assert gate_hits["path"] != prov.PATH_TRIAGED
    assert an.provenance.get("thin")["path"] != prov.PATH_TRIAGED
    assert an.provenance.get("anomalous")["path"] == prov.PATH_SCORED
    assert store.get("anomalous").status in ("anomaly",) or \
        store.get("anomalous").anomaly
    assert an.provenance.get("watch-0")["path"] == prov.PATH_TRIAGED
    # the borderline job fails the screen every cycle yet stays healthy:
    # the suspect-that-never-convicts re-escalates forever, by design
    assert an.provenance.get("borderline")["path"] == prov.PATH_SCORED


def test_non_ma_algorithm_disables_band_screening():
    """The one-sided dominance argument only covers moving_average*; any
    other forecaster must deactivate the band screen entirely."""
    an, _, snaps = _run_arm(triage=True, algorithm="exponential_smoothing")
    off_an, _, off_snaps = _run_arm(triage=False,
                                    algorithm="exponential_smoothing")
    assert snaps == off_snaps
    assert an.triage_screened_total == {}


# --------------------------------------------------- provenance + surfaces

def test_explain_names_triaged_path_over_the_wire():
    an, store, _ = _run_arm(triage=True)
    svc = ForemastService(store, exporter=an.exporter, analyzer=an)
    status, payload = svc.explain("watch-0")
    assert status == 200
    rec = payload["provenance"]
    assert rec["path"] == prov.PATH_TRIAGED
    assert "screened clear" in rec["detail"]
    fam = next(f for f in rec["families"] if f.get("triaged"))
    # the screen's statistics vs its thresholds: the "why" is auditable
    assert fam["robust_z"] <= fam["z_threshold"] == 8.0
    assert fam["margin"] == 0.25
    assert fam["checked"] > 0
    assert fam["unhealthy"] is False


def test_status_and_metrics_surface_triage_counters():
    an, store, _ = _run_arm(triage=True)
    svc = ForemastService(store, exporter=an.exporter, analyzer=an)
    status, payload = svc.status_summary()
    assert status == 200
    tri = payload["triage"]
    assert tri["screened"]["band"] > 0
    assert tri["cleared"]["band"] > 0
    assert 0.0 <= tri["escalation_ratio"] < 1.0
    assert tri["screen_launches"] >= 1
    cyc = payload["cycle"]["triage"]
    assert cyc["screened"] == cyc["cleared"] + cyc["escalated"]
    assert cyc["seconds"] >= 0.0
    text = an.exporter.render()
    assert 'foremastbrain:triage_screened_total{family="band"}' in text
    assert 'foremastbrain:triage_cleared_total{family="band"}' in text
    assert "foremastbrain:triage_escalation_ratio" in text
    assert "foremastbrain:triage_seconds" in text


def test_screen_failure_escalates_whole_bucket(monkeypatch):
    """A wedged/poisoned screen must cost only launches, never a cycle:
    every unit escalates to the full path and verdicts match TRIAGE=0."""
    def boom(*a, **k):
        raise RuntimeError("screen wedged")

    monkeypatch.setattr(TriageGate, "_screen", boom)
    an, _, snaps = _run_arm(triage=True)
    _, _, off_snaps = _run_arm(triage=False)
    assert snaps == off_snaps
    assert sum(an.triage_cleared_total.values()) == 0
    assert sum(an.triage_escalated_total.values()) > 0


def test_bench_triage_ab_identity_small():
    """The bench A/B's identity claim in miniature (the 1500-job figure
    is `BENCH_CYCLE_TRIAGE=1 python -m foremast_tpu.bench_cycle`)."""
    from foremast_tpu.bench_cycle import run_triage

    on = run_triage(n_jobs=24, cycles=2, anomaly_rate=0.1, triage=True,
                    metrics_per_job=3)
    off = run_triage(n_jobs=24, cycles=2, anomaly_rate=0.1, triage=False,
                     metrics_per_job=3)
    assert on["verdict_digest"] == off["verdict_digest"]
    assert on["cleared_per_cycle"] > 0


# ------------------------------------------------------------- perf gate

@pytest.mark.perf
def test_triage_launch_cut_gate():
    """`make perf` gate: on a no-anomaly steady fleet whose every row
    changes every cycle, TRIAGE=1 launches ≤ 20% of the TRIAGE=0
    programs, at byte-identical verdicts. pipeline_fire_rows is shrunk so
    the screen-free arm streams multiple rung launches per cycle — the
    shape a real fleet has at PIPELINE_FIRE_ROWS=1024 with 10k+ rows."""
    def arm(triage):
        rng = np.random.default_rng(7)
        fixtures: dict = {}
        store = JobStore()
        for i in range(96):
            cur, hist = f"u/w{i}/c", f"u/w{i}/h"
            fixtures[cur] = _series(rng, 10.0, 32)
            fixtures[hist] = _series(rng, 10.0, 200)
            store.create(Document(
                id=f"w{i}", app_name=f"app-{i}", namespace="perf",
                strategy="continuous", start_time=to_rfc3339(0.0),
                end_time="",
                metrics={"latency": MetricQueries(current=cur,
                                                  historical=hist)},
            ))
        an = Analyzer(
            EngineConfig(max_stuck_seconds=1e9, multimetric_auto=False,
                         triage=triage, pipeline_fire_rows=16),
            FixtureDataSource(fixtures), store, VerdictExporter())
        an.run_cycle(worker="w", now=1000.0)  # warm: compiles + memo fill
        for url, (ts, vals) in list(fixtures.items()):
            if url.endswith("/c"):
                nrng = np.random.default_rng(hash(url) % 2 ** 32)
                fixtures[url] = (ts + [ts[-1] + STEP],
                                 vals + [float(nrng.normal(10.0, 1.0))])
        before = an.device_launches
        an.run_cycle(worker="w", now=1010.0)
        return an.device_launches - before, _snapshot(store)

    on_launches, on_snap = arm(True)
    off_launches, off_snap = arm(False)
    assert on_snap == off_snap
    assert off_launches >= 5  # the gate must compare real streamed launches
    assert on_launches <= 0.2 * off_launches, (
        f"triage launch cut gate: {on_launches} vs {off_launches}")
