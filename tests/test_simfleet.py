"""Fleet-scale load simulator (ISSUE 15): foremast_tpu/simfleet.

Contracts under test:

  * determinism — a trace is a pure function of its (spec, seed);
  * range-query honesty — the backend's query_range bodies honor their
    start/end params and the sim clock exactly (a sliced query equals
    the slice of the full body), which is what lets delta fetch
    exercise for real;
  * push == poll — remote-write payloads for a sample range are
    byte-consistent with the polled bodies (the 4-decimal convention),
    so streamed and polled verdicts stay identical;
  * artifact honesty — every driver JSON records seed / trace shape /
    fleet size (docs/benchmarks.md);
  * ground truth — injected anomalies convict (recall 1.0) and clean
    steady fleets convict nothing;
  * the perf-marked A/B gate (CI perf-smoke leg).
"""
from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from foremast_tpu.simfleet import SimBackend, SimTrace, preset
from foremast_tpu.simfleet.driver import run_fleet
from foremast_tpu.simfleet.trace import lead_steps


def _trace(shape="steady", jobs=64, seed=0, horizon=256, **over):
    spec = preset(shape, jobs, seed, window_steps=32, hist_windows=2,
                  **over)
    t0 = 1_700_000_000 // spec.step_s * spec.step_s
    return SimTrace(spec, t0, horizon + lead_steps(spec))


# ------------------------------------------------------------ determinism
def test_trace_deterministic_per_seed():
    a = _trace(seed=7)
    b = _trace(seed=7)
    c = _trace(seed=8)
    sa = a.series(5, 0, 10, 120)
    assert np.array_equal(sa, b.series(5, 0, 10, 120))
    assert not np.array_equal(sa, c.series(5, 0, 10, 120))
    # distinct jobs and slots read distinct series
    assert not np.array_equal(sa, a.series(6, 0, 10, 120))
    assert not np.array_equal(sa, a.series(5, 1, 10, 120))


def test_trace_labels_and_truth():
    tr = _trace(jobs=100, anomaly_rate=0.1, seed=5)
    labels = tr.labels()
    assert len(labels["anomalous_jobs"]) == 10
    assert tr.truth_jobs() == frozenset(labels["anomalous_jobs"])
    # reproducible from the spec alone
    assert _trace(jobs=100, anomaly_rate=0.1, seed=5).labels() == labels


def test_spec_as_dict_is_json_able():
    spec = preset("incident", 10, 3)
    blob = json.dumps(spec.as_dict())
    assert json.loads(blob)["shape"] == "incident"
    assert json.loads(blob)["incidents"] == 2


# ------------------------------------------------------ range-query honesty
def _parse_samples(body: bytes) -> list:
    doc = json.loads(body)
    return doc["data"]["result"][0]["values"]


def test_backend_range_queries_honor_params_and_clock():
    tr = _trace(jobs=8)
    bk = SimBackend(tr)
    t0, step = bk.t0, bk.step
    bk.set_now(t0 + 200 * step)
    full = _parse_samples(bk.body(3, 0, t0, t0 + 200 * step))
    # a narrower range returns exactly the matching slice
    sub = _parse_samples(bk.body(3, 0, t0 + 50 * step, t0 + 90 * step))
    assert sub == [s for s in full if t0 + 50 * step <= s[0] <= t0 + 90 * step]
    # the sim clock withholds the future: end past `now` clamps
    bk.set_now(t0 + 60 * step)
    clamped = _parse_samples(bk.body(3, 0, t0, t0 + 200 * step))
    assert clamped == [s for s in full if s[0] <= t0 + 60 * step]
    # off-grid starts round UP to the next slot (range semantics)
    off = _parse_samples(bk.body(3, 0, t0 + 50 * step + 1, t0 + 60 * step))
    assert off[0][0] == t0 + 51 * step


def test_push_series_byte_consistent_with_polled_bodies():
    tr = _trace(jobs=6)
    bk = SimBackend(tr)
    t0, step = bk.t0, bk.step
    hi = t0 + (bk.hist_steps + bk.W + 4) * step
    bk.set_now(hi)
    lo = hi - 3 * step
    pushes = {}
    for labels, samples in bk.push_series(lo, hi):
        pushes[(labels["foremast_job"], labels["foremast_metric"])] = samples
    assert pushes, "no pushes for an advancing window"
    for job in range(6):
        cls = bk.class_of(job)
        name, slot, _ = bk._metric_layout(cls)[0]
        got = pushes[(bk.job_id(job), name)]
        body = _parse_samples(bk.body(job, slot, lo + 1, hi))
        # the push carries EXACTLY the values the backend serves —
        # same 4-decimal serialization, so splice == refetch
        assert [(float(ts), float(v)) for ts, v in body] == got


def test_native_render_parity_with_python_join():
    """The native body renderer and the Python f-string fallback must
    produce identical bytes (the parse twin contract)."""
    from foremast_tpu import native

    tr = _trace(jobs=4)
    bk = SimBackend(tr)
    bk.set_now(bk.t0 + 200 * bk.step)
    body = bk.body(1, 0, bk.t0, bk.t0 + 150 * bk.step)
    series = tr.series(1, 0, 0, 150)
    expect = ",".join(
        f'[{bk.t0 + i * bk.step},"{v:.4f}"]'
        for i, v in enumerate(series.tolist())).encode()
    assert expect in body
    if native.available():
        assert native.render_matrix(bk.t0, bk.step, series) == expect


def test_backend_http_serving_matches_resolver():
    tr = _trace(jobs=4)
    bk = SimBackend(tr)
    bk.set_now(bk.t0 + 150 * bk.step)
    srv, base = bk.serve()
    try:
        bk.url_base = base
        url = bk.url(2, 0, "cur", 10, 90)
        with urllib.request.urlopen(url, timeout=10) as r:
            over_http = r.read()
        assert over_http == bk.body(2, 0, bk.t0 + 10 * bk.step,
                                    bk.t0 + 90 * bk.step)
    finally:
        srv.shutdown()
        srv.server_close()


def test_class_mix_fractions():
    tr = _trace(jobs=1000)
    bk = SimBackend(tr)
    from collections import Counter

    mix = Counter(bk.class_of(j) for j in range(1000))
    assert 650 <= mix["continuous"] <= 750
    assert 100 <= mix["canary"] <= 200
    assert 50 <= mix["hpa"] <= 150
    assert 20 <= mix["bivariate"] <= 80


def test_class_mix_remainder_goes_to_first_class():
    """Fractions summing under 1.0: the FleetSpec contract sends the
    remainder to the FIRST class, not silently to the last."""
    tr = _trace(jobs=200, mix=(("continuous", 0.5), ("canary", 0.25)))
    bk = SimBackend(tr)
    from collections import Counter

    mix = Counter(bk.class_of(j) for j in range(200))
    # no surprise hpa/bivariate jobs — the 0.25 remainder widens the
    # continuous band (0.5 declared + 0.25 remainder ~ 0.75)
    assert set(mix) == {"continuous", "canary"}
    assert 140 <= mix["continuous"] <= 160


# ------------------------------------------------------------- the driver
def test_driver_artifact_honesty_and_ground_truth():
    out = run_fleet(jobs=80, seed=11, shape="steady", cycles=2,
                    cadence_s=60.0, anomaly_rate=0.1)
    # reproducibility header: seed + full trace shape + fleet size
    assert out["seed"] == 11
    assert out["trace"]["shape"] == "steady"
    assert out["trace"]["jobs"] == 80
    assert out["fleet"] == 80
    json.dumps(out)  # the whole artifact is JSON-able
    assert out["jobs_per_sec"] > 0
    assert out["resident_rss_bytes"] > 0
    assert out["window_cache_bytes"] > 0
    # ground truth on the quiet steady trace: every labeled non-hpa job
    # convicts, nothing unlabeled does
    assert out["truth"]["labeled"] > 0
    assert out["truth"]["recall"] == 1.0
    assert out["truth"]["false_positives"] == 0


def test_driver_replicas_partition_whole_fleet():
    out = run_fleet(jobs=60, seed=2, shape="steady", cycles=2,
                    cadence_s=60.0, replicas=3)
    assert out["replicas"] == 3
    # every job is scored exactly once per cycle across the 3 replicas
    assert out["jobs_scored"] == 60 * 2


def test_driver_churn_arrivals():
    import dataclasses

    spec = dataclasses.replace(
        preset("steady", 50, 0, window_steps=32, hist_windows=2),
        churn_per_cycle=0.1)
    out = run_fleet(cycles=3, cadence_s=60.0, spec=spec)
    assert out["churn_arrivals"] == 15  # 10% of 50, 3 cycles
    assert out["fleet"] == 65


def test_driver_stream_leg_matches_polled_verdicts():
    """Push ingest (remote-write through the real receiver) must land
    byte-identical verdicts vs the poll-only leg on the same trace."""
    spec = preset("steady", 40, 4, window_steps=32, hist_windows=2,
                  anomaly_rate=0.1)
    polled = run_fleet(cycles=3, cadence_s=60.0, spec=spec, stream=False)
    streamed = run_fleet(cycles=3, cadence_s=60.0, spec=spec, stream=True)
    assert streamed["ingest_spliced_points"] > 0
    assert streamed["verdict_digest"] == polled["verdict_digest"]
    # throughput honesty: a job judged by a partial (push) cycle and
    # re-confirmed by the same tick's full sweep counts ONCE — the
    # streamed leg's jobs/s denominator work must match the polled leg's
    assert streamed["jobs_scored"] == polled["jobs_scored"]


def test_driver_jobstore_leg_digests_identical(tmp_path):
    """Tier-1 shape check for the crash-durable job-store leg (the 1M
    acceptance run is `SIM_JOBS=1000000 SIM_JOBSTORE=1`, artifact
    BENCH_JOBSTORE_r01.json): tiny fleet, all three passes — tier on,
    restart-recovery, tier off — must land one verdict digest."""
    from foremast_tpu.simfleet import run_jobstore

    out = run_jobstore(jobs=360, seed=7, shape="steady", cycles=2,
                       cadence_s=60.0, tier_dir=str(tmp_path / "tier"),
                       open_jobs=40, checkpoint_every=100)
    assert out["verdicts_identical"]
    d = out["digests"]
    assert d["tier_on"] == d["recovered"] == d["tier_off"]
    # reproducibility header + honest split
    assert out["seed"] == 7 and out["fleet"] == 360
    assert out["open_jobs"] == 40 and out["terminal_jobs"] == 320
    json.dumps(out)
    # the tier really carried the fleet: every doc spilled, the cold
    # majority evicted from RAM, and recovery restored the open set
    assert out["tier"]["docs"] == 360
    assert out["ram_docs_after_evict"] < 360
    assert out["recovery"]["wall_seconds"] > 0
    assert out["steady_jobs_per_sec"] > 0


# ---------------------------------------------------------- perf A/B gate
@pytest.mark.slow
@pytest.mark.perf
def test_simfleet_ab_gate():
    """The simulator half of the CI perf-smoke gate: a ~2k-job mini
    fleet, mega on/off byte-identical, >= 2 families collapsed to
    exactly one launch per cycle, artifact honesty on the A/B record."""
    from foremast_tpu.simfleet import run_fleet_ab

    # rounds=1: this gate asserts only the deterministic invariants
    # (identity, collapse), so one pair keeps the CI leg bounded
    ab = run_fleet_ab(jobs=2000, seed=0, shape="diurnal", cycles=3,
                      cadence_s=60.0, rounds=1)
    assert ab["verdicts_identical"]
    assert len(ab["families_single_launch"]) >= 2, ab
    assert ab["seed"] == 0 and ab["fleet"] == 2000
    assert ab["trace"]["shape"] == "diurnal"
    assert ab["padding_waste_ratio"] is not None
